"""Batched consensus kernels: single-strand log-likelihood calling and
duplex top/bottom-strand reconciliation.

TPU-first design: per-read per-cycle log-likelihood contributions are
reduced into per-family tensors with ONE one-hot matmul on the MXU
(``onehot_families.T @ contributions``), fusing the log-likelihood
accumulation, per-cycle depth counting, and family sizing into a single
(F+1, R) x (R, 5L+1) GEMM — no scatter, no ragged loops, no
data-dependent shapes. Alternatives, all measured in-pipeline on v5e
(journal: tools/tune_ssc.py): ``segment`` (jax.ops.segment_sum
scatter-add), ``blockseg`` (family-sorted block-local one-hot GEMMs —
16x fewer FLOPs, exact, 1.4x slower on TPU but 4.2x FASTER on XLA-CPU,
hence the CPU-backend default), ``runsum`` (cumsum + boundary gather —
rejected: prefix cancellation multiplies consensus error 4.8x), and
``pallas`` (kernels/pallas_ssc.py, r2: 1.59x slower).

Numerics mirror oracle/consensus.py exactly (float32 on device):
  loglik[b] = sum_i [ base_i==b ? log1p(-e_i) : log(e_i/3) ]
  err       = 1 - p_max = (sum_exp - 1)/sum_exp  after max-shift
  qual      = floor(-10*log10(err) + 1e-9) clipped to [2, max_qual]
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.constants import (
    BASE_N,
    MIN_ERROR_PROB,
    N_REAL_BASES,
    NO_CALL_QUAL,
)

I32_MAX = jnp.iinfo(jnp.int32).max

# blockseg tile height default: rows per local one-hot GEMM. A
# PipelineSpec.blockseg_t / ssc_kernel(blockseg_t=...) static argument
# (r4: was a trace-time module constant — the CPU-default method's main
# tuning knob should not require editing source); tools/tune_ssc.py
# sweeps it on the real chip — see the journal there for measured
# values.
BLOCKSEG_T = 128


def _phred_from_err(err: jnp.ndarray, max_qual: int) -> jnp.ndarray:
    err = jnp.maximum(err, MIN_ERROR_PROB)
    q = jnp.floor(-10.0 * jnp.log10(err) + 1e-9)
    return jnp.clip(q, 2, max_qual).astype(jnp.int32)


def _evidence_columns(
    bases, quals, ok, max_input_qual, min_input_qual, want_err,
    want_depth=True, want_fit_counts=False,
):
    """(rows, C) evidence block: loglik contributions (4L)[, depth
    indicators (L)], read-count (1)[, real-masked base counts (4L) for
    the err reduction][, UNfiltered base counts (4L) for the error-model
    fit]. Column slicing happens BEFORE the reduction GEMM on purpose:
    XLA cannot narrow a dot's output columns through post-hoc slices, so
    every column here costs real MXU work.

    The fit counts deliberately skip the min_input_qual mask: the
    error-model fit tallies every real base against the consensus
    (oracle/error_model.py's `ok`), while the consensus argmax itself
    excludes sub-threshold reads — two different masks by contract."""
    r, l = bases.shape
    contrib, real = _contributions(bases, quals, ok, max_input_qual, min_input_qual)
    cols = [contrib.reshape(r, 4 * l)]
    if want_depth:
        cols.append(real)
    cols.append(ok.astype(jnp.float32)[:, None])
    if want_err:
        oh = (
            (bases[:, :, None] == jnp.arange(N_REAL_BASES, dtype=bases.dtype))
            & (real > 0)[:, :, None]
        ).astype(jnp.float32)
        cols.append(oh.reshape(r, 4 * l))
    if want_fit_counts:
        ohf = (
            (bases[:, :, None] == jnp.arange(N_REAL_BASES, dtype=bases.dtype))
            & ok[:, None, None]
        ).astype(jnp.float32)
        cols.append(ohf.reshape(r, 4 * l))
    return jnp.concatenate(cols, axis=1)


def _contributions(bases, quals, valid, max_input_qual, min_input_qual=0):
    """Per-read per-cycle evidence rows, zeroed for N/PAD/invalid and
    for bases below min_input_qual (masked like N, per fgbio's
    min-input-base-quality).

    Returns (contrib (R, L, 4) f32, real (R, L) f32).
    """
    real = (bases < N_REAL_BASES) & valid[:, None]
    if min_input_qual > 0:
        real = real & (quals >= min_input_qual)
    # NOTE: a 256-entry qual->loglik LUT gather was tried here and is
    # ~15x SLOWER than the elementwise transcendentals — TPU gathers
    # with per-element dynamic indices serialize; the VPU chews
    # pow/log1p/log at full rate. Keep the elementwise form.
    q = jnp.minimum(quals.astype(jnp.float32), float(max_input_qual))
    e = jnp.power(10.0, -q / 10.0)
    e = jnp.maximum(e, MIN_ERROR_PROB)
    log_match = jnp.log1p(-e)
    log_mis = jnp.log(e / 3.0)
    onehot = (bases[:, :, None] == jnp.arange(N_REAL_BASES, dtype=bases.dtype)).astype(
        jnp.float32
    )
    contrib = log_mis[:, :, None] + onehot * (log_match - log_mis)[:, :, None]
    contrib = contrib * real[:, :, None].astype(jnp.float32)
    return contrib, real.astype(jnp.float32)


@partial(
    jax.jit,
    static_argnames=(
        "f_max", "min_reads", "max_qual", "max_input_qual",
        "min_input_qual", "method", "want_err", "columns", "blockseg_t",
    ),
)
def ssc_kernel(
    bases: jnp.ndarray,  # (R, L) u8
    quals: jnp.ndarray,  # (R, L) u8
    family_id: jnp.ndarray,  # (R,) i32, NO_FAMILY for unassigned
    valid: jnp.ndarray,  # (R,) bool
    *,
    f_max: int,
    min_reads: int = 1,
    max_qual: int = 90,
    max_input_qual: int = 50,
    min_input_qual: int = 0,
    method: str = "matmul",
    want_err: bool = False,
    columns: str = "full",
    blockseg_t: int = BLOCKSEG_T,
):
    """Single-strand consensus for all families at once.

    Returns (cons_base (F, L) i32, cons_qual (F, L) i32,
             depth (F, L) i32, fam_size (F,) i32, fam_valid (F,) bool
             [, err (F, L) i32 with want_err=True]).
    Row f corresponds to dense family id f; rows >= actual family count
    have fam_size 0 and fam_valid False. err counts contributing reads
    disagreeing with the called base (the per-base ce tag); it widens
    the reduction by 4L count columns, so it is opt-in.

    columns="fit" is the error-model pass-1 variant: it drops the L
    depth columns from the reduction (20% fewer GEMM FLOPs) and returns
    only (cons_base, fam_size, fam_valid). The depth>0 masking is
    recovered exactly from the loglik sign (strictly negative iff any
    read contributed — see the inline proof), so fit-mode cons_base is
    bit-identical to the full pass's UP TO the fam_valid mask: the full
    pass additionally blanks sub-min_reads families to BASE_N; fit mode
    returns the unmasked argmax and the caller must apply the returned
    fam_valid itself (the pipeline does). Exception: method="runsum" keeps
    its depth columns even in fit mode — its prefix-difference sums can
    cancel a tiny loglik to exact 0.0, so the sign test is unsound
    there (advisor r4); the depth>0 mask is used instead.
    """
    r, l = bases.shape
    if columns not in ("full", "fit", "fit_counts"):
        raise ValueError(f"unknown ssc columns mode {columns!r}")
    fit_mode = columns in ("fit", "fit_counts")
    fit_counts = columns == "fit_counts"
    # runsum family sums are differences of two large prefix sums; a
    # tiny contribution (lone Phred-90 read, loglik ~ -1e-9) can cancel
    # to exact 0.0 against ~1e6-magnitude prefixes, so the sign test
    # that replaces the depth>0 mask below is unsound for it. Keep the
    # depth columns (integer prefix sums are exact below 2^24, so their
    # differences never cancel) and mask on depth instead.
    want_depth = (not fit_mode) or method == "runsum"
    if fit_mode and want_err:
        raise ValueError("columns='fit' is incompatible with want_err")
    ok = valid & (family_id >= 0)
    fid = jnp.where(ok, family_id, f_max)  # overflow row, sliced off below

    if method in ("matmul", "pallas", "pallas_interpret", "segment"):
        # (R, 4L | L | 1 [| 4L]): loglik contributions, depth
        # indicators, read count, optional base counts (want_err)
        big = _evidence_columns(
            bases, quals, ok, max_input_qual, min_input_qual, want_err,
            want_depth, fit_counts,
        )
        if method == "matmul":
            onehot_f = (
                fid[:, None] == jnp.arange(f_max + 1, dtype=jnp.int32)
            ).astype(jnp.float32)
            out = jnp.dot(onehot_f.T, big, preferred_element_type=jnp.float32)[
                :f_max
            ]
        elif method == "segment":
            out = jax.ops.segment_sum(big, fid, num_segments=f_max + 1)[:f_max]
        else:
            from duplexumiconsensusreads_tpu.kernels.pallas_ssc import segment_gemm

            out = segment_gemm(
                big, fid, f_max=f_max, interpret=(method == "pallas_interpret")
            )
    elif method in ("blockseg", "runsum"):
        # After a stable sort by id every family is one contiguous run,
        # and any T consecutive sorted rows hold at most T DISTINCT id
        # values — true for any id layout, including the sparse strided
        # duplex ids (molecule*2 + strand) where single-strand molecules
        # leave gaps. The u8 inputs are permuted (cheap) so the f32
        # evidence rows are built directly in id order.
        perm = jnp.argsort(fid, stable=True)
        sfid = jnp.take(fid, perm)
        sok = jnp.take(ok, perm)
        big = _evidence_columns(
            jnp.take(bases, perm, axis=0),
            jnp.take(quals, perm, axis=0),
            sok,
            max_input_qual,
            min_input_qual,
            want_err,
            want_depth,
            fit_counts,
        )
        c = big.shape[1]
        if method == "runsum":
            # VERDICT-r2 shape: one cumsum over the sorted evidence +
            # a boundary gather per family. O(R*C) elementwise, zero
            # GEMM — but each family sum is a difference of two large
            # prefixes; the f32 cancellation measurably corrupts quals
            # (4.8x consensus error on the bench sim — rejected, kept
            # only as the measured refutation; tools/tune_ssc.py).
            z = jnp.concatenate(
                [jnp.zeros((1, c), jnp.float32), jnp.cumsum(big, axis=0)], axis=0
            )
            starts = jnp.searchsorted(
                sfid, jnp.arange(f_max + 1, dtype=jnp.int32), side="left"
            )
            out = jnp.take(z, starts[1:], axis=0) - jnp.take(z, starts[:-1], axis=0)
        else:
            # blockseg: per-block local one-hot GEMMs. Within block k of
            # T sorted rows, `local` is the row's RANK among the block's
            # distinct ids (cumsum of change flags), which always fits
            # in [0, T) no matter how sparse the id values are — the
            # earlier offset form (fid - fid[first]) silently corrupted
            # rows whenever a block spanned > T id values, which the
            # strided duplex ids (gaps at single-strand molecules) hit
            # on singleton-heavy data (advisor r4, high). A (T, T)
            # one-hot reduces the block exactly; block partials (at most
            # 2 blocks share a family boundary) are scatter-added into
            # the family rows via a per-rank destination table. 2*R*T*C
            # FLOPs vs the dense method's 2*R*(F+1)*C — an F/T reduction
            # with no prefix cancellation.
            t = min(blockseg_t, r)
            nb = -(-r // t)
            pad = nb * t - r
            if pad:
                big = jnp.concatenate([big, jnp.zeros((pad, c), jnp.float32)])
                sfid = jnp.concatenate(
                    [sfid, jnp.full((pad,), f_max, jnp.int32)]
                )
            sfid2 = sfid.reshape(nb, t)
            chg = jnp.concatenate(
                [
                    jnp.zeros((nb, 1), jnp.int32),
                    (sfid2[:, 1:] != sfid2[:, :-1]).astype(jnp.int32),
                ],
                axis=1,
            )
            local = jnp.cumsum(chg, axis=1)  # (nb, t) ranks in [0, t)
            onehot = (
                local[:, :, None] == jnp.arange(t, dtype=jnp.int32)
            ).astype(jnp.float32)
            partials = jnp.einsum(
                "btj,btc->bjc",
                onehot,
                big.reshape(nb, t, c),
                preferred_element_type=jnp.float32,
            )
            # the id occupying each rank slot; unused slots keep f_max
            # and are dropped with the padding/invalid rows below.
            # Duplicate (block, rank) indices all write the same id, so
            # the scatter is deterministic.
            dest = (
                jnp.full((nb, t), f_max, jnp.int32)
                .at[jnp.arange(nb, dtype=jnp.int32)[:, None], local]
                .set(sfid2)
            )
            out = (
                jnp.zeros((f_max + 1, c), jnp.float32)
                .at[dest.reshape(-1)]
                .add(partials.reshape(-1, c), mode="drop")[:f_max]
            )
    else:
        raise ValueError(f"unknown ssc method {method!r}")

    loglik = out[:, : 4 * l].reshape(f_max, l, 4)
    if fit_mode:
        # fit mode: argmax + family size only. Zero-evidence masking
        # WITHOUT depth columns: every contributing read's loglik terms
        # are strictly negative (log(e/3) < log(1/3) and log1p(-e) < 0
        # for e >= MIN_ERROR_PROB), non-contributors add exact ±0.0, and
        # f32 sums of negatives never round to zero — so max(loglik) < 0
        # iff the (family, cycle) has >= 1 contributing read, exactly
        # the depth > 0 test of the full pass. This matters when
        # min_input_qual > 0: a cycle whose reads are all sub-threshold
        # must yield BASE_N so the fit excludes those reads, matching
        # the oracle (review r4 finding). The sign argument needs exact
        # per-family sums; runsum keeps its depth columns (see above)
        # and masks on those instead.
        if want_depth:  # runsum: exact integer depth, sound mask
            size_col = 5 * l
            fam_size = out[:, size_col].astype(jnp.int32)
            has_evidence = out[:, 4 * l : 5 * l] > 0
        else:
            size_col = 4 * l
            fam_size = out[:, size_col].astype(jnp.int32)
            has_evidence = jnp.max(loglik, axis=-1) < 0
        cons_base = jnp.where(
            has_evidence, jnp.argmax(loglik, axis=-1), BASE_N
        ).astype(jnp.int32)
        fam_valid = fam_size >= min_reads
        if fit_counts:
            # per-(family, cycle, base) counts of ALL real contributing
            # bases (min_input_qual deliberately not applied — see
            # _evidence_columns); f32 sums of 0/1 are exact below 2^24.
            # Returned FLAT (F, 4L), column l*4+b, and kept f32: a
            # reshape to (F, L, 4) puts 4 on the minor axis, which TPU
            # T(8,128) tiling pads to 128 lanes — a 32x memory blowup
            # (measured: 22.3 GB for the 280-bucket bench class, OOM)
            counts = out[:, size_col + 1 : size_col + 1 + 4 * l]
            return cons_base, fam_size, fam_valid, counts
        return cons_base, fam_size, fam_valid
    depth = out[:, 4 * l : 5 * l].astype(jnp.int32)
    fam_size = out[:, 5 * l].astype(jnp.int32)
    counts = (
        out[:, 5 * l + 1 : 9 * l + 1].reshape(f_max, l, 4).astype(jnp.int32)
        if want_err
        else None
    )

    # err = 1 - p_max, computed by summing ONLY the non-argmax
    # exponentials: with the max term included the f32 sum rounds to 1.0
    # whenever err < 1e-7 and the subtraction cancels to 0 (capping every
    # deep family at max_qual). Excluding it keeps the residual exact.
    maxll = jnp.max(loglik, axis=-1, keepdims=True)
    base = jnp.argmax(loglik, axis=-1).astype(jnp.int32)
    not_max = jnp.arange(4, dtype=jnp.int32) != base[..., None]
    s = jnp.sum(jnp.exp(loglik - maxll) * not_max.astype(jnp.float32), axis=-1)
    err = s / (1.0 + s)
    qual = _phred_from_err(err, max_qual)

    called = depth > 0
    cons_base = jnp.where(called, base, BASE_N)
    cons_qual = jnp.where(called, qual, NO_CALL_QUAL)
    fam_valid = fam_size >= min_reads
    cons_base = jnp.where(fam_valid[:, None], cons_base, BASE_N)
    cons_qual = jnp.where(fam_valid[:, None], cons_qual, NO_CALL_QUAL)
    depth = jnp.where(fam_valid[:, None], depth, 0)  # oracle parity: uncalled rows are 0
    if not want_err:
        return cons_base, cons_qual, depth, fam_size, fam_valid
    # contributing reads disagreeing with the called base (ce tag):
    # depth minus the count supporting the argmax; zero where no call
    match = jnp.take_along_axis(counts, base[..., None], axis=-1)[..., 0]
    err_n = jnp.where(called & fam_valid[:, None], depth - match, 0)
    return cons_base, cons_qual, depth, fam_size, fam_valid, err_n


@partial(
    jax.jit,
    static_argnames=("m_max", "min_duplex_reads", "max_qual", "want_err"),
)
def duplex_merge_strided(
    cons_base: jnp.ndarray,  # (2M, L) i32, row 2m = AB strand of unit m, 2m+1 = BA
    cons_qual: jnp.ndarray,  # (2M, L) i32
    depth: jnp.ndarray,  # (2M, L) i32
    fam_size: jnp.ndarray,  # (2M,) i32
    fam_valid: jnp.ndarray,  # (2M,) bool
    ss_err: jnp.ndarray | None = None,  # (2M, L) i32, required iff want_err
    *,
    m_max: int,
    min_duplex_reads: int = 1,
    max_qual: int = 90,
    want_err: bool = False,
):
    """Duplex merge when the ssc reduction was keyed by the STRIDED id
    ``molecule*2 + strand_ba`` instead of the dense family rank: the two
    strands of unit m are rows 2m and 2m+1, so the merge is pure
    reshape-slicing — zero gathers, zero segment reductions. Measured
    r4 on v5e: the gather-based duplex_kernel was 18.6% of the fused
    step (six (M, L) row-gathers + four R-length segment ops); this
    variant removes all of it. Semantics are identical: a unit missing
    a strand has an all-zero evidence row (fam_size 0), which fails the
    size>0 presence check exactly like the old table-presence test.
    """
    if want_err and ss_err is None:
        raise ValueError("duplex_merge_strided: ss_err required when want_err=True")
    l = cons_base.shape[1]
    b2 = cons_base.reshape(m_max, 2, l)
    q2 = cons_qual.reshape(m_max, 2, l)
    d2 = depth.reshape(m_max, 2, l)
    s2 = fam_size.reshape(m_max, 2)
    v2 = fam_valid.reshape(m_max, 2)
    b_ab, b_ba = b2[:, 0], b2[:, 1]
    q_ab, q_ba = q2[:, 0], q2[:, 1]

    both_real = (b_ab < N_REAL_BASES) & (b_ba < N_REAL_BASES)
    agree = both_real & (b_ab == b_ba)
    disagree = both_real & (b_ab != b_ba) & (q_ab != q_ba)

    dx_base = jnp.where(
        agree,
        b_ab,
        jnp.where(disagree, jnp.where(q_ab > q_ba, b_ab, b_ba), BASE_N),
    )
    dx_qual = jnp.where(
        agree,
        jnp.minimum(q_ab + q_ba, max_qual),
        jnp.where(
            disagree,
            jnp.maximum(jnp.abs(q_ab - q_ba), NO_CALL_QUAL),
            NO_CALL_QUAL,
        ),
    )
    dx_depth = d2[:, 0] + d2[:, 1]

    dx_valid = (
        (s2[:, 0] > 0)  # strand present (== the old table-presence test)
        & (s2[:, 1] > 0)
        & (s2[:, 0] >= min_duplex_reads)
        & (s2[:, 1] >= min_duplex_reads)
        & v2[:, 0]
        & v2[:, 1]
    )
    dx_base = jnp.where(dx_valid[:, None], dx_base, BASE_N)
    dx_qual = jnp.where(dx_valid[:, None], dx_qual, NO_CALL_QUAL)
    dx_depth = jnp.where(dx_valid[:, None], dx_depth, 0)
    if not want_err:
        return dx_base, dx_qual, dx_depth, dx_valid
    e2 = ss_err.reshape(m_max, 2, l)
    dx_err = jnp.where(dx_valid[:, None], e2[:, 0] + e2[:, 1], 0)
    return dx_base, dx_qual, dx_depth, dx_valid, dx_err


@partial(
    jax.jit,
    static_argnames=("m_max", "min_duplex_reads", "max_qual", "want_err"),
)
def duplex_kernel(
    cons_base: jnp.ndarray,  # (F, L) i32 single-strand consensus bases
    cons_qual: jnp.ndarray,  # (F, L) i32
    depth: jnp.ndarray,  # (F, L) i32
    fam_valid: jnp.ndarray,  # (F,) bool
    family_id: jnp.ndarray,  # (R,) i32
    molecule_id: jnp.ndarray,  # (R,) i32
    strand_ab: jnp.ndarray,  # (R,) bool
    valid: jnp.ndarray,  # (R,) bool
    ss_err: jnp.ndarray | None = None,  # (F, L) i32, required iff want_err
    *,
    m_max: int,
    min_duplex_reads: int = 1,
    max_qual: int = 90,
    want_err: bool = False,
):
    """Duplex merge of AB/BA single-strand consensi per molecule.

    Returns (dx_base (M, L) i32, dx_qual (M, L) i32, dx_depth (M, L) i32,
             dx_valid (M,) bool[, dx_err (M, L) i32 with want_err=True —
             the sum of the strands' own-consensus disagreement counts,
             mirroring the oracle's duplex_merge]).
    """
    if want_err and ss_err is None:
        raise ValueError("duplex_kernel: ss_err is required when want_err=True")
    ok = valid & (molecule_id >= 0) & (family_id >= 0)
    mid = jnp.where(ok, molecule_id, m_max)

    def strand_tables(is_ab):
        sel = ok & (strand_ab == is_ab)
        fam = jnp.where(sel, family_id, I32_MAX)
        fam_of_mol = jax.ops.segment_min(
            fam, jnp.where(sel, mid, m_max), num_segments=m_max + 1
        )[:m_max]
        size = jax.ops.segment_sum(
            sel.astype(jnp.int32), mid, num_segments=m_max + 1
        )[:m_max]
        return fam_of_mol, size

    fam_ab, size_ab = strand_tables(True)
    fam_ba, size_ba = strand_tables(False)

    have = (fam_ab < I32_MAX) & (fam_ba < I32_MAX)
    fam_ab_c = jnp.where(have, fam_ab, 0)
    fam_ba_c = jnp.where(have, fam_ba, 0)

    b_ab = jnp.take(cons_base, fam_ab_c, axis=0)
    q_ab = jnp.take(cons_qual, fam_ab_c, axis=0)
    d_ab = jnp.take(depth, fam_ab_c, axis=0)
    b_ba = jnp.take(cons_base, fam_ba_c, axis=0)
    q_ba = jnp.take(cons_qual, fam_ba_c, axis=0)
    d_ba = jnp.take(depth, fam_ba_c, axis=0)

    both_real = (b_ab < N_REAL_BASES) & (b_ba < N_REAL_BASES)
    agree = both_real & (b_ab == b_ba)
    disagree = both_real & (b_ab != b_ba) & (q_ab != q_ba)

    dx_base = jnp.where(
        agree,
        b_ab,
        jnp.where(disagree, jnp.where(q_ab > q_ba, b_ab, b_ba), BASE_N),
    )
    dx_qual = jnp.where(
        agree,
        jnp.minimum(q_ab + q_ba, max_qual),
        jnp.where(disagree, jnp.maximum(jnp.abs(q_ab - q_ba), NO_CALL_QUAL), NO_CALL_QUAL),
    )
    dx_depth = d_ab + d_ba

    dx_valid = (
        have
        & (fam_ab_c != fam_ba_c)  # unpaired grouping: AB==BA would
        # self-merge a family and double its quality; emit no call instead
        & (size_ab >= min_duplex_reads)
        & (size_ba >= min_duplex_reads)
        & jnp.take(fam_valid, fam_ab_c)
        & jnp.take(fam_valid, fam_ba_c)
    )
    dx_base = jnp.where(dx_valid[:, None], dx_base, BASE_N)
    dx_qual = jnp.where(dx_valid[:, None], dx_qual, NO_CALL_QUAL)
    dx_depth = jnp.where(dx_valid[:, None], dx_depth, 0)
    if not want_err:
        return dx_base, dx_qual, dx_depth, dx_valid
    dx_err = jnp.take(ss_err, fam_ab_c, axis=0) + jnp.take(
        ss_err, fam_ba_c, axis=0
    )
    dx_err = jnp.where(dx_valid[:, None], dx_err, 0)
    return dx_base, dx_qual, dx_depth, dx_valid, dx_err
