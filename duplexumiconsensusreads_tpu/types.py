"""Core data model: padded, static-shape tensors for reads and consensus.

Everything downstream of IO operates on ``ReadBatch`` — an
HBM-resident struct-of-arrays with fully static shapes, the design
mandated by the north-star (BASELINE.json: "batched JAX kernels over an
HBM-resident padded read/quality tensor"). Fields are NumPy arrays on
the host path and jnp arrays on the device path; every dataclass here
is registered as a JAX pytree so it can flow through jit/shard_map.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import numpy as np

from duplexumiconsensusreads_tpu.constants import NO_FAMILY


def _register(cls):
    """Register a dataclass as a JAX pytree (all fields are leaves)."""
    fields = [f.name for f in dataclasses.fields(cls)]
    jax.tree_util.register_pytree_node(
        cls,
        lambda x: ([getattr(x, n) for n in fields], None),
        lambda _, leaves: cls(**dict(zip(fields, leaves))),
    )
    return cls


@_register
@dataclasses.dataclass
class ReadBatch:
    """A padded batch of N aligned reads, each up to L cycles.

    bases:     u8 (N, L)  0..3 real, 4=N, 5=PAD (beyond read length)
    quals:     u8 (N, L)  Phred; 0 on PAD cycles
    umi:       u8 (N, U)  2-bit codes; for duplex input this is the
                          *canonicalised* concatenated UMI pair (see io/)
    pos_key:   i64 (N,)   packed canonical genomic key (ref, unclipped
                          start[, mate start]); identical for all reads
                          of one source molecule
    strand_ab: bool (N,)  True = top (AB) strand read, False = bottom (BA)
    frag_end:  bool (N,)  fragment-end bit: True iff the read observes
                          the template's SECOND fragment end. For a
                          paired record this is READ2==top-strand (so
                          top-R1 and bottom-R2 share end 1 — the
                          fgbio-style cross-mate duplex partners);
                          single-end records are always end 1. Used by
                          mate-aware grouping (GroupingParams.mate_aware)
                          to keep opposite fragment ends in separate
                          cycle-space families.
    valid:     bool (N,)  False marks padding slots in the batch
    """

    bases: Any
    quals: Any
    umi: Any
    pos_key: Any
    strand_ab: Any
    frag_end: Any
    valid: Any

    @property
    def n_reads(self) -> int:
        return self.bases.shape[0]

    @property
    def read_len(self) -> int:
        return self.bases.shape[1]

    @property
    def umi_len(self) -> int:
        return self.umi.shape[1]

    @staticmethod
    def empty(n: int, l: int, u: int) -> "ReadBatch":
        from duplexumiconsensusreads_tpu.constants import BASE_PAD

        return ReadBatch(
            bases=np.full((n, l), BASE_PAD, np.uint8),
            quals=np.zeros((n, l), np.uint8),
            umi=np.zeros((n, u), np.uint8),
            pos_key=np.zeros((n,), np.int64),
            strand_ab=np.zeros((n,), bool),
            frag_end=np.zeros((n,), bool),
            valid=np.zeros((n,), bool),
        )

    def take(self, idx) -> "ReadBatch":
        return ReadBatch(
            bases=self.bases[idx],
            quals=self.quals[idx],
            umi=self.umi[idx],
            pos_key=self.pos_key[idx],
            strand_ab=self.strand_ab[idx],
            frag_end=self.frag_end[idx],
            valid=self.valid[idx],
        )


@_register
@dataclasses.dataclass
class FamilyAssignment:
    """Output of UmiGrouper: per-read family/molecule labels.

    family_id:   i32 (N,)  dense id of the (molecule, strand) single-strand
                           family; NO_FAMILY for invalid/unassigned reads.
                           Mate-aware grouping splits families further by
                           fragment end: (molecule, frag_end, strand)
    molecule_id: i32 (N,)  dense id of the consensus OUTPUT unit: the
                           source molecule (duplex: the AB and BA
                           families of one molecule share it), or, under
                           mate-aware grouping, the (molecule, frag_end)
                           pair — each emits its own duplex consensus
    pair_id:     i32 (N,)  dense id of the source molecule proper —
                           equals molecule_id except under mate-aware
                           grouping, where the two fragment-end units of
                           one molecule share it (it links the emitted
                           R1/R2 consensus mates)
    n_families:  i32 ()    number of distinct family ids in this batch
    n_molecules: i32 ()    number of distinct molecule (unit) ids
    """

    family_id: Any
    molecule_id: Any
    pair_id: Any
    n_families: Any
    n_molecules: Any

    @staticmethod
    def none(n: int) -> "FamilyAssignment":
        return FamilyAssignment(
            family_id=np.full((n,), NO_FAMILY, np.int32),
            molecule_id=np.full((n,), NO_FAMILY, np.int32),
            pair_id=np.full((n,), NO_FAMILY, np.int32),
            n_families=np.int32(0),
            n_molecules=np.int32(0),
        )


@_register
@dataclasses.dataclass
class ConsensusBatch:
    """Output of ConsensusCaller: F padded consensus reads.

    bases: u8 (F, L)   consensus base codes (4=N)
    quals: u8 (F, L)   consensus Phred qualities
    depth: i32 (F, L)  per-cycle read depth that contributed
    valid: bool (F,)   False marks padding families
    err:   i32 (F, L)  per-cycle count of contributing reads that
                       disagree with the consensus base (duplex: sum of
                       the two strands' own-consensus disagreements)
    """

    bases: Any
    quals: Any
    depth: Any
    valid: Any
    err: Any = None


@dataclasses.dataclass(frozen=True)
class GroupingParams:
    """UmiGrouper configuration (static / hashable — safe as jit static arg).

    strategy:     "exact" (identical UMI), "adjacency" (directional
                  clustering, UMI-tools algorithm, Hamming <= max_hamming),
                  or "cluster" (UMI-tools cluster method: symmetric
                  connected components within Hamming <= max_hamming,
                  labeled by their highest-count member — identical to
                  adjacency with the count condition removed, which is
                  exactly how both implementations realize it:
                  count_ratio 0 makes the directed edge condition
                  count >= -1 vacuously true and the edge set symmetric)
    max_hamming:  adjacency/cluster edge threshold (reference: 1)
    count_ratio:  directional edge condition count(a) >= ratio*count(b)-1
                  (reference behaviour: 2; forced 0 under "cluster")
    paired:       duplex mode — reads carry a canonicalised UMI pair and
                  strand_ab distinguishes top/bottom families
    mate_aware:   paired-end mode — the fragment-end bit joins the
                  family identity, so a template's R1 and R2 mates
                  (opposite fragment ends, disjoint cycle spaces) form
                  separate families, and each (molecule, fragment end)
                  becomes its own duplex output unit — pairing the
                  top-strand R1 family with the bottom-strand R2 family
                  (the fgbio CallDuplexConsensusReads pairing). With no
                  second-end reads present the grouping is identical to
                  mate_aware=False by construction.
    """

    strategy: str = "exact"
    max_hamming: int = 1
    count_ratio: int = 2
    paired: bool = False
    mate_aware: bool = False

    @property
    def effective_count_ratio(self) -> int:
        """The directional edge ratio the implementations consume:
        "cluster" is adjacency with the count condition removed."""
        return 0 if self.strategy == "cluster" else self.count_ratio


@dataclasses.dataclass(frozen=True)
class ConsensusParams:
    """ConsensusCaller configuration (static / hashable).

    mode:            "single_strand" or "duplex"
    min_reads:       minimum reads per single-strand family; smaller
                     families emit no consensus
    min_duplex_reads: minimum reads on EACH strand for a duplex call
    max_qual:        cap on emitted consensus quality
    max_input_qual:  cap applied to input qualities before the math
    min_input_qual:  bases below this quality contribute NO evidence
                     (masked like N, excluded from depth) — the
                     fgbio-style min-input-base-quality filter
    error_model:     None, or "cycle" to apply a fitted per-cycle
                     quality cap before consensus (benchmark config 5)
    """

    mode: str = "single_strand"
    min_reads: int = 1
    min_duplex_reads: int = 1
    max_qual: int = 90
    max_input_qual: int = 50
    min_input_qual: int = 0
    error_model: str | None = None
