"""duplexumiconsensusreads_tpu — TPU-native duplex UMI consensus framework.

A from-scratch JAX/XLA re-design of the capabilities of
``paurrodri/DuplexUMIConsensusReads`` (reference mount was empty; the
contract is BASELINE.json's north-star + five benchmark configs — see
SURVEY.md). The preserved operator boundary is ``UmiGrouper`` /
``ConsensusCaller`` with swappable ``cpu`` (NumPy oracle) and ``tpu``
(JAX) backends.

Layers (bottom-up):
  utils/      Phred math, packing helpers.
  simulate/   truth-aware synthetic read generator (ground-truth molecules).
  oracle/     pure-NumPy reference implementation of every algorithm.
  kernels/    pure-JAX batched kernels (adjacency, clustering, consensus,
              duplex merge, per-cycle error model) — jit/vmap, static shapes.
  bucketing/  host-side (genomic-tile, family-size) bucketing → static shapes.
  ops/        UmiGrouper, ConsensusCaller, fused pipeline.
  parallel/   jax.sharding Mesh + shard_map data-parallel sharding of buckets.
  io/         BGZF/BAM codec (no pysam) + npz interchange.
  cli/        command-line entry point mapping 1:1 to the benchmark configs.
"""

__version__ = "0.1.0"

from duplexumiconsensusreads_tpu.types import (  # noqa: F401
    ReadBatch,
    FamilyAssignment,
    ConsensusBatch,
    ConsensusParams,
    GroupingParams,
)
