"""Streaming executor: consensus-call BAMs far larger than host RAM.

The whole-file path (runtime/executor.py) parses everything up front;
this module processes a coordinate-sorted BAM as a pipeline of chunks:

  BGZF blocks → rolling decompress → record chunks (holding back the
  trailing pos_key group so no family straddles a boundary) → buckets →
  ASYNC device dispatch (several chunks in flight — on a tunneled chip
  each dispatch costs ~100ms fixed latency, so overlap is what turns
  per-chunk latency into pipeline throughput) → scatter-back → per-chunk
  output shards → final single consensus BAM.

Checkpoint/resume: a JSON manifest records finished chunk shards keyed
by a parameter fingerprint; re-running with --resume skips completed
chunks (the batch-domain analogue of training checkpoint/resume).

Input contract (documented limitation, mirrors the reference domain's
sort requirements — fgbio-style tools demand template-coordinate
order): records must be ordered so that equal pos_keys are contiguous
and pos_keys are non-decreasing. `duplexumi simulate --sorted` and any
coordinate-sorted single-end BAM satisfy this.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import struct
import time
from collections import deque

import numpy as np

from duplexumiconsensusreads_tpu.io import bgzf
from duplexumiconsensusreads_tpu.io.bam import BamHeader, BamRecords, parse_bam
from duplexumiconsensusreads_tpu.io.convert import (
    UNMAPPED_POS_KEY,
    consensus_to_records,
    records_to_readbatch,
)

# chunk-boundary key MUST be the grouping key: one shared implementation
from duplexumiconsensusreads_tpu.io.convert import records_pos_keys as _rec_pos_keys
from duplexumiconsensusreads_tpu.runtime.executor import (
    RunReport,
    partition_buckets,
    scatter_bucket_outputs,
    sort_consensus_outputs,
)
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


# --------------------------------------------------------------- input

def _complete_prefix(buf: bytes) -> int:
    """Byte length of the complete-BGZF-block prefix of ``buf``.

    Header-only scan (a few struct reads per ≤64 KiB block) — the
    expensive inflate happens elsewhere, per-block in Python or batched
    in the native library."""
    off = 0
    while off + 18 <= len(buf):
        size = bgzf.read_block_size(buf, off)
        if off + size > len(buf):
            break
        off += size
    return off


def _inflate_native(lib, buf: bytes, n_threads: int) -> bytes:
    """Parallel-inflate a byte string of complete BGZF blocks."""
    src = np.frombuffer(buf, np.uint8)
    usize = lib.dut_bgzf_usize(src, len(src))
    if usize < 0:
        raise ValueError("malformed BGZF block batch")
    out = np.empty(max(usize, 1), np.uint8)
    if lib.dut_bgzf_decompress(src, len(src), out, usize, n_threads) != usize:
        raise ValueError("BGZF decompression failed")
    return out[:usize].tobytes()


def _iter_bgzf_stream(f, read_size=4 << 20, native_lib=None, n_threads=0):
    """Yield decompressed byte chunks from a BGZF (or raw BAM) file obj.

    With ``native_lib`` (the ctypes-bound C++ loader), each batch of
    complete blocks is inflated in one multithreaded native call —
    the streaming analogue of the whole-file native path, so host
    ingest no longer serialises on Python zlib at 200M-read scale.
    """
    head = f.read(18)
    if head[:2] == b"\x1f\x8b":
        buf = head
        while True:
            data = f.read(read_size)
            if data:
                buf += data
            off = _complete_prefix(buf)
            if off:
                if native_lib is not None:
                    yield _inflate_native(native_lib, buf[:off], n_threads)
                else:
                    yield b"".join(
                        bgzf.decompress_block(buf, o, s)
                        for o, s in bgzf.iter_block_offsets(buf[:off])
                    )
            buf = buf[off:]
            if not data:
                if buf:
                    raise ValueError("trailing truncated BGZF block")
                return
    else:
        yield head
        while True:
            data = f.read(read_size)
            if not data:
                return
            yield data


class BamStreamReader:
    """Incremental BAM record reader over a rolling decompressed buffer."""

    def __init__(
        self, path: str, read_size: int = 8 << 20, use_native: bool = True
    ):
        native_lib = None
        n_threads = 0
        if use_native:
            from duplexumiconsensusreads_tpu.native import get_lib

            native_lib = get_lib()
            n_threads = min(os.cpu_count() or 1, 16)
        self._native_lib = native_lib
        self._f = open(path, "rb")
        self._gen = _iter_bgzf_stream(
            self._f, read_size, native_lib=native_lib, n_threads=n_threads
        )
        self._buf = bytearray()
        self._eof = False
        self.header = self._read_header()

    def close(self):
        self._f.close()

    def _fill(self, need: int) -> bool:
        while len(self._buf) < need and not self._eof:
            try:
                self._buf += next(self._gen)
            except StopIteration:
                self._eof = True
        return len(self._buf) >= need

    def _need(self, n: int, what: str) -> None:
        if not self._fill(n):
            raise ValueError(f"truncated BAM: incomplete {what}")

    def _read_header(self) -> BamHeader:
        self._need(12, "magic")
        if bytes(self._buf[:4]) != b"BAM\x01":
            raise ValueError("not a BAM file")
        (l_text,) = struct.unpack_from("<i", self._buf, 4)
        if l_text < 0:
            raise ValueError("malformed BAM: negative l_text")
        self._need(8 + l_text + 4, "header text")
        text = bytes(self._buf[8 : 8 + l_text]).split(b"\x00", 1)[0].decode()
        off = 8 + l_text
        (n_ref,) = struct.unpack_from("<i", self._buf, off)
        if n_ref < 0:
            raise ValueError("malformed BAM: negative n_ref")
        off += 4
        names, lengths = [], []
        for _ in range(n_ref):
            self._need(off + 4, "reference entry")
            (l_name,) = struct.unpack_from("<i", self._buf, off)
            if l_name < 1:
                raise ValueError("malformed BAM: bad reference name length")
            off += 4
            self._need(off + l_name + 4, "reference entry")
            names.append(bytes(self._buf[off : off + l_name - 1]).decode())
            off += l_name
            (l_ref,) = struct.unpack_from("<i", self._buf, off)
            off += 4
            lengths.append(l_ref)
        del self._buf[:off]
        return BamHeader(text=text, ref_names=names, ref_lengths=lengths)

    def read_raw_records(self, n: int) -> bytes | None:
        """Raw bytes of up to n whole records; None at EOF."""
        if self._native_lib is not None:
            return self._read_raw_records_native(n)
        count = 0
        off = 0
        while count < n:
            if not self._fill(off + 4):
                break
            (bsz,) = struct.unpack_from("<i", self._buf, off)
            # 32 fixed bytes + >=1 read-name byte is the smallest record
            if bsz < 33:
                raise ValueError(f"malformed BAM: record block_size {bsz}")
            self._need(off + 4 + bsz, "record")
            off += 4 + bsz
            count += 1
        if count == 0:
            if self._buf and self._eof:
                raise ValueError(
                    "truncated BAM: trailing partial record at EOF"
                )
            return None
        out = bytes(self._buf[:off])
        del self._buf[:off]
        return out

    def _read_raw_records_native(self, n: int) -> bytes | None:
        """read_raw_records via the C record-chain walker: no
        per-record Python loop (the walk was the streaming reader's
        top host cost at scale)."""
        import ctypes

        lib = self._native_lib
        count = 0
        off = 0
        while count < n:
            # the frombuffer view must not outlive the iteration: a live
            # export would block the bytearray resize below
            buf_arr = np.frombuffer(self._buf, np.uint8)
            end = ctypes.c_long()
            c = lib.dut_bam_chain(
                buf_arr, len(buf_arr), off, n - count, ctypes.byref(end)
            )
            del buf_arr
            if c < 0:
                bad = int(end.value)  # chain reports the offending record
                bsz = struct.unpack_from("<i", self._buf, bad)[0] if len(
                    self._buf
                ) >= bad + 4 else -1
                raise ValueError(f"malformed BAM: record block_size {bsz}")
            count += c
            off = int(end.value)
            if count >= n:
                break
            if not self._fill(len(self._buf) + 1):
                break  # EOF: return what we have; partial tail errors next call
        if count == 0:
            if self._buf and self._eof:
                raise ValueError(
                    "truncated BAM: trailing partial record at EOF"
                )
            return None
        out = bytes(self._buf[:off])
        del self._buf[:off]
        return out


def _records_from_raw(header: BamHeader, raw: bytes) -> BamRecords:
    """Parse a raw record stream by prepending a minimal header."""
    _, recs = parse_bam(_header_shell(header) + raw)
    return recs


def _resolve_chunk_boundary(keys: np.ndarray, prev_last):
    """THE chunk-boundary rule, shared by the Python and native chunk
    iterators (their boundaries must stay byte-identical — checkpoint
    manifests key chunks by index). On the combined buffer's pos_keys,
    returns (cut, new_prev_last):

      cut == 0         entire buffer is one position group: keep growing
      cut == len(keys) unmapped sentinel tail: flush everything, no
                       hold-back (sentinel keys are never groupable)
      otherwise        yield records [:cut], hold back the final group

    Raises on sort-contract violations (the one shared wording).
    """
    if len(keys) > 1 and (np.diff(keys) < 0).any():
        i = int(np.nonzero(np.diff(keys) < 0)[0][0])
        raise ValueError(
            "input violates the streaming sort contract: pos_key "
            f"decreases at record ~{i} ({keys[i]} -> "
            f"{keys[i+1]}). Streaming needs non-decreasing "
            "fragment keys (template-coordinate order for paired "
            "data); use whole-file mode (--chunk-reads 0) for "
            "unsorted input."
        )
    if prev_last is not None and len(keys) and keys[0] <= prev_last:
        raise ValueError(
            "input violates the streaming sort contract across a "
            "chunk boundary (pos_key repeats after being flushed)"
        )
    # Unmapped EOF tail: sentinel-key records are never groupable (the
    # FLAG filter invalidates them downstream), so family integrity
    # doesn't apply — flush immediately. Carrying them would be
    # unbounded: the whole tail shares ONE pos_key. Later all-sentinel
    # chunks must pass the repeat check, but any MAPPED key after the
    # tail is a sort violation and must trip it.
    if keys[-1] == UNMAPPED_POS_KEY:
        return len(keys), UNMAPPED_POS_KEY - 1
    last = keys[-1]
    keep = np.nonzero(keys != last)[0]
    if len(keep) == 0:
        return 0, prev_last
    cut = int(keep[-1]) + 1
    return cut, keys[cut - 1]


def iter_record_chunks(path: str, chunk_reads: int):
    """Yield (header, BamRecords) chunks; the trailing pos_key group of
    each chunk is held back and prepended to the next so no molecule's
    reads are split across chunks.

    The sort contract (non-decreasing pos_key — see module docstring)
    is VALIDATED on every chunk: a violation raises instead of silently
    splitting a family across chunks. Note plain coordinate order is
    NOT sufficient for paired-end data (a mate's pos_key is the
    fragment's min coordinate, which sorts earlier than the mate) —
    that input needs template-coordinate sorting, exactly as the
    reference domain's duplex tools require.
    """
    reader = BamStreamReader(path)
    header = reader.header
    carry: BamRecords | None = None
    prev_last = None
    try:
        while True:
            raw = reader.read_raw_records(chunk_reads)
            if raw is None:
                if carry is not None and len(carry):
                    yield header, carry
                return
            recs = _records_from_raw(header, raw)
            if carry is not None and len(carry):
                recs = _concat_records(carry, recs)
            batch_pos = _rec_pos_keys(recs)
            cut, prev_last = _resolve_chunk_boundary(batch_pos, prev_last)
            if cut == 0:
                carry = recs  # entire chunk is one group; keep growing
                continue
            if cut == len(recs):  # sentinel tail: flush, no hold-back
                carry = None
                yield header, recs
                continue
            carry = _slice_records(recs, cut, len(recs))
            yield header, _slice_records(recs, 0, cut)
    finally:
        reader.close()




def _header_shell(header: BamHeader) -> bytes:
    shell = bytearray()
    shell += b"BAM\x01"
    text = header.text.encode()
    shell += struct.pack("<i", len(text)) + text
    shell += struct.pack("<i", len(header.ref_names))
    for name, length in zip(header.ref_names, header.ref_lengths):
        nb = name.encode() + b"\x00"
        shell += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)
    return bytes(shell)


def iter_batch_chunks(path: str, chunk_reads: int, duplex: bool):
    """Yield (header, ReadBatch, info) chunks with the family-integrity
    hold-back of iter_record_chunks, but parsed NATIVELY: record fields
    go straight from raw BAM bytes into NumPy arrays (io/native_reader),
    bypassing the per-record Python loop — the difference between the
    host starving the device and keeping up at 200M-read scale.

    Chunk boundaries are byte-identical to iter_record_chunks' (same
    hold-back and sentinel-flush rules on the same pos_keys), so
    checkpoint manifests remain valid whichever path produced them.
    Falls back to the pure-Python iterator when the native library is
    unavailable or DUT_NO_NATIVE is set.
    """
    lib = None
    if not os.environ.get("DUT_NO_NATIVE"):
        from duplexumiconsensusreads_tpu.native import get_lib

        lib = get_lib()
    if lib is None:
        for header, recs in iter_record_chunks(path, chunk_reads):
            batch, info = records_to_readbatch(recs, duplex=duplex)
            yield header, batch, info
        return

    from duplexumiconsensusreads_tpu.io.native_reader import (
        batch_from_offsets,
        region_pos_keys,
        scan_region,
    )

    nt = min(os.cpu_count() or 1, 16)
    reader = BamStreamReader(path)
    header = reader.header
    shell = _header_shell(header)
    carry = b""
    prev_last = None
    try:
        while True:
            raw = reader.read_raw_records(chunk_reads)
            if raw is None:
                if carry:
                    data = np.frombuffer(shell + carry, np.uint8)
                    he, lm, rm, off = scan_region(lib, data, path)
                    yield header, *batch_from_offsets(
                        lib, data, off, lm, rm, duplex=duplex, n_threads=nt
                    )
                return
            buf = carry + raw
            data = np.frombuffer(shell + buf, np.uint8)
            he, lm, rm, rec_off = scan_region(lib, data, path)
            keys = region_pos_keys(data, rec_off)
            cut, prev_last = _resolve_chunk_boundary(keys, prev_last)
            if cut == 0:
                carry = buf  # entire buffer is one group; keep growing
                continue
            if cut == len(keys):  # sentinel tail: flush, no hold-back
                carry = b""
                yield header, *batch_from_offsets(
                    lib, data, rec_off, lm, rm, duplex=duplex, n_threads=nt
                )
                continue
            carry = buf[int(rec_off[cut]) - len(shell):]
            yield header, *batch_from_offsets(
                lib, data, rec_off[:cut], lm, rm, duplex=duplex, n_threads=nt
            )
    finally:
        reader.close()


def _slice_records(recs: BamRecords, a: int, b: int) -> BamRecords:
    return BamRecords(
        names=recs.names[a:b],
        flags=recs.flags[a:b],
        ref_id=recs.ref_id[a:b],
        pos=recs.pos[a:b],
        mapq=recs.mapq[a:b],
        next_ref_id=recs.next_ref_id[a:b],
        next_pos=recs.next_pos[a:b],
        tlen=recs.tlen[a:b],
        lengths=recs.lengths[a:b],
        seq=recs.seq[a:b],
        qual=recs.qual[a:b],
        cigars=recs.cigars[a:b],
        umi=recs.umi[a:b],
        aux_raw=recs.aux_raw[a:b],
    )


def _concat_records(a: BamRecords, b: BamRecords) -> BamRecords:
    lmax = max(a.seq.shape[1], b.seq.shape[1])

    def padseq(x, fill):
        out = np.full((x.shape[0], lmax), fill, np.uint8)
        out[:, : x.shape[1]] = x
        return out

    from duplexumiconsensusreads_tpu.constants import BASE_PAD

    return BamRecords(
        names=a.names + b.names,
        flags=np.concatenate([a.flags, b.flags]),
        ref_id=np.concatenate([a.ref_id, b.ref_id]),
        pos=np.concatenate([a.pos, b.pos]),
        mapq=np.concatenate([a.mapq, b.mapq]),
        next_ref_id=np.concatenate([a.next_ref_id, b.next_ref_id]),
        next_pos=np.concatenate([a.next_pos, b.next_pos]),
        tlen=np.concatenate([a.tlen, b.tlen]),
        lengths=np.concatenate([a.lengths, b.lengths]),
        seq=np.concatenate([padseq(a.seq, BASE_PAD), padseq(b.seq, BASE_PAD)]),
        qual=np.concatenate([padseq(a.qual, 0), padseq(b.qual, 0)]),
        cigars=a.cigars + b.cigars,
        umi=a.umi + b.umi,
        aux_raw=a.aux_raw + b.aux_raw,
    )


# ------------------------------------------------------------ checkpoint

@dataclasses.dataclass
class Checkpoint:
    path: str
    fingerprint: str
    done: dict  # chunk index (str) -> shard path

    @staticmethod
    def load_or_create(path: str, fingerprint: str) -> "Checkpoint":
        """Load the manifest, pruning entries that no longer apply.

        Whatever this returns is immediately persisted if it differs
        from the on-disk state: a diverging manifest (mismatched
        fingerprint, dead shard paths) must not survive on disk, where
        a crash-before-first-mark would let a later --resume splice
        stale shard bytes from a different run into the output."""
        done: dict = {}
        on_disk = None
        if os.path.exists(path):
            with open(path) as f:
                on_disk = json.load(f)
            if on_disk.get("fingerprint") == fingerprint:
                done = {
                    k: v for k, v in on_disk.get("done", {}).items() if os.path.exists(v)
                }
        ckpt = Checkpoint(path, fingerprint, done)
        if on_disk is not None and on_disk != {"fingerprint": fingerprint, "done": done}:
            ckpt.save()
        return ckpt

    def save(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"fingerprint": self.fingerprint, "done": self.done}, f)
        os.replace(tmp, self.path)

    def mark(self, chunk: int, shard_path: str) -> None:
        self.done[str(chunk)] = shard_path
        self.save()


def _fingerprint(in_path: str, grouping, consensus, capacity, chunk_reads) -> str:
    st = os.stat(in_path)
    key = json.dumps(
        [
            os.path.abspath(in_path),
            st.st_size,
            int(st.st_mtime),
            dataclasses.asdict(grouping),
            dataclasses.asdict(consensus),
            capacity,
            chunk_reads,
        ],
        sort_keys=True,
    )
    return hashlib.sha256(key.encode()).hexdigest()[:16]


# -------------------------------------------------------------- executor

def stream_call_consensus(
    in_path: str,
    out_path: str,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    capacity: int = 2048,
    chunk_reads: int = 500_000,
    n_devices: int | None = None,
    max_inflight: int = 4,
    checkpoint_path: str | None = None,
    resume: bool = False,
    report_path: str | None = None,
    profile_dir: str | None = None,
    cycle_shards: int = 1,
    progress=None,
) -> RunReport:
    """Chunked, async-pipelined consensus calling (TPU backend).

    Writes per-chunk shards next to out_path, then finalises a single
    consensus BAM. With checkpoint_path + resume=True, finished chunks
    are skipped on rerun and shards are kept on disk for future
    resumes; without a checkpoint the shard directory is removed after
    a successful finalise.
    """
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.io.bam import serialize_bam
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import sharded_pipeline

    rep = RunReport(backend="tpu-stream")
    duplex = consensus.mode == "duplex"
    t_start = time.time()
    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    ckpt = None
    if checkpoint_path:
        fp = _fingerprint(in_path, grouping, consensus, capacity, chunk_reads)
        ckpt = Checkpoint.load_or_create(checkpoint_path, fp)
        if not resume:
            # persist a fresh manifest NOW, unconditionally: a stale
            # on-disk manifest (same OR different fingerprint) must not
            # survive a crash-before-first-mark — this run is about to
            # overwrite the shard files it points at, so a later
            # --resume against the old manifest would serve shards
            # whose content no longer matches its params
            ckpt.done = {}
            ckpt.save()

    n_dev = n_devices or len(jax.devices())
    mesh = make_mesh(n_dev, cycle_shards=cycle_shards)
    n_data = max(n_dev // max(cycle_shards, 1), 1)
    rep.n_devices = n_dev
    header_out: BamHeader | None = None

    shard_dir = out_path + ".shards"
    os.makedirs(shard_dir, exist_ok=True)
    shards: dict[int, str] = {}
    inflight: deque = deque()
    spec_cache: dict = {}

    def dispatch(buckets, spec):
        stacked = stack_buckets(buckets, multiple_of=n_data)
        return sharded_pipeline(stacked, spec, mesh)

    def drain_one():
        nonlocal rep
        k, entries, batch = inflight.popleft()
        parts = []
        for out, cbuckets, cspec in entries:
            try:
                out = {key: np.asarray(v) for key, v in out.items()}
            except Exception as e:  # failure detection: retry the class once
                rep.n_retries += 1
                import sys

                print(
                    f"[duplexumi] chunk {k} device execution failed ({e!r}); "
                    "re-dispatching once",
                    file=sys.stderr,
                )
                out = dispatch(cbuckets, cspec)
                out = {key: np.asarray(v) for key, v in out.items()}
            rep.n_families += int(out["n_families"].sum())
            rep.n_molecules += int(out["n_molecules"].sum())
            parts.append(scatter_bucket_outputs(out, cbuckets, batch, duplex))
        shard = _finish_chunk(
            k, parts, duplex, shard_dir, serialize_bam, header_out
        )
        shards[k] = shard
        if ckpt:
            ckpt.mark(k, shard)
        if progress:
            progress(k, rep)

    n_skipped = 0
    try:
        for k, (header, batch, info) in enumerate(
            iter_batch_chunks(in_path, chunk_reads, duplex)
        ):
            header_out = header_out or header
            rep.n_chunks += 1
            if ckpt and str(k) in ckpt.done:
                shards[k] = ckpt.done[str(k)]
                n_skipped += 1
                continue
            # per-read counters cover FRESH work only, so a resumed
            # run's report is internally consistent (n_records matches
            # n_valid_reads + drops); skipped chunks show up in
            # n_chunks_skipped and the final n_consensus instead
            rep.n_records += info["n_records"]
            rep.n_valid_reads += info["n_valid"]
            rep.n_dropped += (
                info["n_dropped_no_umi"]
                + info["n_dropped_umi_len"]
                + info.get("n_dropped_flag", 0)
            )
            buckets = build_buckets(batch, capacity=capacity, grouping=grouping)
            rep.n_buckets += len(buckets)
            if not buckets:
                shards[k] = _write_shard(shard_dir, k, b"")
                if ckpt:
                    ckpt.mark(k, shards[k])
                continue
            entries = []
            for cbuckets, cspec in partition_buckets(buckets, grouping, consensus):
                spec_cache[cspec] = True
                entries.append((dispatch(cbuckets, cspec), cbuckets, cspec))
            inflight.append((k, entries, batch))
            while len(inflight) >= max_inflight:
                drain_one()
        while inflight:
            drain_one()
    finally:
        if profile_dir:
            jax.profiler.stop_trace()

    # ---- finalise: header + shard record streams -> one BAM. Shards
    # are compressed and appended one at a time (BGZF members
    # concatenate), so peak memory stays one chunk regardless of the
    # total output size; records are counted during the same pass. ----
    if header_out is None:
        # record-less input: the real header is still authoritative
        _r = BamStreamReader(in_path)
        header_out = _r.header
        _r.close()
    shell = serialize_bam(header_out, _empty_records())
    with open(out_path, "wb") as f:
        f.write(bgzf.compress_fast(shell, eof=False))
        for k in sorted(shards):
            with open(shards[k], "rb") as s:
                data = s.read()
            if data:
                f.write(bgzf.compress_fast(data, eof=False))
            rep.n_consensus += _count_records(data)
        f.write(bgzf.BGZF_EOF)
    if not checkpoint_path:
        # no resume requested: the shards can never be reused
        for k in shards:
            try:
                os.remove(shards[k])
            except OSError:
                pass
        try:
            os.rmdir(shard_dir)
        except OSError:
            pass
    rep.n_chunks_skipped = n_skipped
    rep.n_pipeline_compiles = len(spec_cache)
    rep.seconds["total"] = round(time.time() - t_start, 3)
    if report_path:
        with open(report_path, "w") as f:
            f.write(rep.to_json() + "\n")
    return rep


def _empty_records() -> BamRecords:
    return BamRecords(
        names=[],
        flags=np.zeros(0, np.uint16),
        ref_id=np.zeros(0, np.int32),
        pos=np.zeros(0, np.int32),
        mapq=np.zeros(0, np.uint8),
        next_ref_id=np.zeros(0, np.int32),
        next_pos=np.zeros(0, np.int32),
        tlen=np.zeros(0, np.int32),
        lengths=np.zeros(0, np.int32),
        seq=np.zeros((0, 0), np.uint8),
        qual=np.zeros((0, 0), np.uint8),
        cigars=[],
        umi=[],
        aux_raw=[],
    )


def _write_shard(shard_dir: str, k: int, payload: bytes) -> str:
    path = os.path.join(shard_dir, f"chunk{k:06d}.recs")
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)
    return path


def _count_records(data: bytes) -> int:
    n = 0
    off = 0
    while off < len(data):
        (bsz,) = struct.unpack_from("<i", data, off)
        off += 4 + bsz
        n += 1
    return n


def _finish_chunk(
    k, parts, duplex, shard_dir, serialize_bam, header
) -> str:
    """Merge one chunk's per-class scattered outputs and write its shard."""
    cb, cq, cd, fp, fu = (np.concatenate(x) for x in zip(*parts))
    cb, cq, cd, fp, fu = sort_consensus_outputs(cb, cq, cd, fp, fu)
    recs = consensus_to_records(
        cb,
        cq,
        cd,
        np.ones(len(cb), bool),
        fp,
        fu,
        duplex=duplex,
        name_prefix=f"cons{k}",
    )
    # record stream only (header stripped) so shards concatenate
    full = serialize_bam(header, recs)
    shell = serialize_bam(header, _empty_records())
    return _write_shard(shard_dir, k, full[len(shell):])
