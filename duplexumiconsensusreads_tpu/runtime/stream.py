"""Streaming executor: consensus-call BAMs far larger than host RAM.

The whole-file path (runtime/executor.py) parses everything up front;
this module processes a coordinate-sorted BAM as a pipeline of chunks:

  BGZF blocks → rolling decompress → record chunks (holding back the
  trailing pos_key group so no family straddles a boundary) → buckets →
  ASYNC device dispatch (wire-packed per the per-chunk packing ladder;
  several chunks in flight under the bounded --prefetch-depth window —
  on a tunneled chip each dispatch costs ~100ms fixed latency, so
  overlap is what turns per-chunk latency into pipeline throughput) →
  PIPELINED drain (a bounded worker pool runs packed fetch → unpack →
  scatter → serialize → BGZF deflate → durable shard write off the
  main loop) → ordered-completion frontier (checkpoint marks and
  incremental finalise appends commit strictly in chunk order,
  whatever order drain workers finish in) → final atomic fsync+rename
  of the single consensus BAM.

Checkpoint/resume: a JSON manifest records finished chunk shards keyed
by a parameter fingerprint; re-running with --resume skips completed
chunks (the batch-domain analogue of training checkpoint/resume).

Input contract (documented limitation, mirrors the reference domain's
sort requirements — fgbio-style tools demand template-coordinate
order): records must be ordered so that equal pos_keys are contiguous
and pos_keys are non-decreasing. `duplexumi simulate --sorted` and any
coordinate-sorted single-end BAM satisfy this.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import queue as _queue
import struct
import sys
import threading
import time
import zlib
from collections import deque

import numpy as np

from duplexumiconsensusreads_tpu.io import bgzf
from duplexumiconsensusreads_tpu.io.durable import (
    fsync_file,
    replace_durable,
    rewrite_from,
    unique_tmp,
    write_durable,
)
from duplexumiconsensusreads_tpu.io.bam import BamHeader, BamRecords, parse_bam
from duplexumiconsensusreads_tpu.io.convert import (
    UNMAPPED_POS_KEY,
    consensus_to_records,
    downsample_families,
    records_to_readbatch,
)

# chunk-boundary key MUST be the grouping key: one shared implementation
from duplexumiconsensusreads_tpu.io.convert import records_pos_keys as _rec_pos_keys
from duplexumiconsensusreads_tpu.ops.pipeline import (
    SUBBYTE_QBITS,
    analytic_flops,
    pack_stacked,
    qual_alphabet,
)

# largest qual alphabet any sub-byte dictionary width can hold; past
# this the run-level union can never fit again and the per-chunk
# alphabet scan becomes pure waste (the chunk loop stops scanning)
_ALPHA_CAP = (1 << max(SUBBYTE_QBITS)) - 1
from duplexumiconsensusreads_tpu.runtime.executor import (
    DRAIN_PHASES,
    IDS16_FETCH_KEYS,
    PACKED_FETCH_KEYS,
    D2hCompactionOverflow,
    RunReport,
    d2h_k_pad,
    d2h_logical_nbytes,
    d2h_pack_ok,
    d2h_rung_for_class,
    fetch_outputs,
    pack_fetch_outputs,
    pack_ids_u16,
    partition_buckets,
    scatter_bucket_outputs,
    sort_consensus_outputs,
    start_fetch,
    unpack_fetch_outputs,
)
from duplexumiconsensusreads_tpu.runtime.faults import (
    fault_point,
    install_from_env,
)
from duplexumiconsensusreads_tpu.telemetry import trace as telemetry
from duplexumiconsensusreads_tpu.telemetry.trace import Heartbeat, TraceRecorder
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


# ------------------------------------------------------- host I/O retry

# Transient HOST I/O failures (NFS blips, EIO, ENOSPC races on shared
# pod storage) get the same bounded-exponential-backoff treatment the
# device path's materialize() gives dispatch failures. Each attempt
# passes the step's named fault site first, so chaos schedules
# (runtime/faults.py) exercise exactly this ladder.
_HOST_IO_RETRIES = 3


def _io_retry(site: str, fn, what: str, *args):
    # ``*args`` are forwarded to ``fn`` so hot-loop callers can pass a
    # module-level function instead of allocating a fresh closure per
    # call (the BGZF header scan hits this once per 18-byte read).
    last: OSError | None = None
    for attempt in range(_HOST_IO_RETRIES + 1):
        try:
            fault_point(site)
            return fn(*args)
        except OSError as e:
            last = e
            if attempt == _HOST_IO_RETRIES:
                break
            delay = min(0.05 * (2 ** attempt), 2.0)
            # every retry attempt is a structured trace event (site +
            # attempt + backoff): a capture must explain a slow run's
            # retry churn without stderr archaeology
            telemetry.emit_event(
                "retry", site=site, attempt=attempt + 1,
                max_attempts=_HOST_IO_RETRIES, backoff_s=round(delay, 3),
                error=repr(e)[:200],
            )
            print(
                f"[duplexumi] transient {what} failure ({e!r}); retry "
                f"{attempt + 1}/{_HOST_IO_RETRIES} in {delay:.2f}s",
                file=sys.stderr,
            )
            time.sleep(delay)
    raise last


def _seek_read(f, pos: int, n: int) -> bytes:
    # re-seek per attempt: a real transient error can fire after the fd
    # offset already advanced past partially-read bytes, and a naive
    # re-read would silently skip them (desynced BGZF framing at best,
    # silently wrong records at worst)
    f.seek(pos)
    return f.read(n)


def _read_ingest(f, n: int) -> bytes:
    return _io_retry("ingest.read", _seek_read, "ingest read", f, f.tell(), n)


def _noop():
    # the ingest.queue fault probe: the handoff itself is a pure
    # in-memory enqueue, so the chaos site wraps a no-op — transients
    # ride the standard _io_retry ladder, kills escape it
    return None


class _IngestAbort(BaseException):
    """Internal unwind signal for the ingest producer thread: the run
    is aborting (the main loop already owns the error), so the producer
    must exit its blocked handoff put WITHOUT emitting another sentinel.
    BaseException so no retry/isolation ladder can absorb it — the same
    reasoning as faults.InjectedKill."""


# --------------------------------------------------------------- input

def _complete_prefix(buf: bytes) -> int:
    """Byte length of the complete-BGZF-block prefix of ``buf``.

    Header-only scan (a few struct reads per ≤64 KiB block) — the
    expensive inflate happens elsewhere, per-block in Python or batched
    in the native library."""
    off = 0
    while off + 18 <= len(buf):
        size = bgzf.read_block_size(buf, off)
        if off + size > len(buf):
            break
        off += size
    return off


def _inflate_native(lib, buf: bytes, n_threads: int) -> bytes:
    """Parallel-inflate a byte string of complete BGZF blocks."""
    src = np.frombuffer(buf, np.uint8)
    usize = lib.dut_bgzf_usize(src, len(src))
    if usize < 0:
        raise ValueError("malformed BGZF block batch")
    out = np.empty(max(usize, 1), np.uint8)
    if lib.dut_bgzf_decompress(src, len(src), out, usize, n_threads) != usize:
        raise ValueError("BGZF decompression failed")
    return out[:usize].tobytes()


def _inflate_python(block: bytes) -> bytes:
    """Per-block pure-Python inflate of a batch of complete blocks."""
    return b"".join(
        bgzf.decompress_block(block, o, s)
        for o, s in bgzf.iter_block_offsets(block)
    )


def _iter_bgzf_stream(f, read_size=4 << 20, native_lib=None, n_threads=0):
    """Yield decompressed byte chunks from a BGZF (or raw BAM) file obj.

    With ``native_lib`` (the ctypes-bound C++ loader), each batch of
    complete blocks is inflated in one multithreaded native call —
    the streaming analogue of the whole-file native path, so host
    ingest no longer serialises on Python zlib at 200M-read scale.
    """
    head = _read_ingest(f, 18)
    if head[:2] == b"\x1f\x8b":
        buf = head
        while True:
            data = _read_ingest(f, read_size)
            if data:
                buf += data
            off = _complete_prefix(buf)
            if off:
                block = buf[:off]
                if native_lib is not None:
                    yield _io_retry(
                        "bgzf.inflate", _inflate_native, "BGZF inflate",
                        native_lib, block, n_threads,
                    )
                else:
                    yield _io_retry(
                        "bgzf.inflate", _inflate_python, "BGZF inflate",
                        block,
                    )
            buf = buf[off:]
            if not data:
                if buf:
                    raise ValueError("trailing truncated BGZF block")
                return
    else:
        yield head
        while True:
            data = _read_ingest(f, read_size)
            if not data:
                return
            yield data


class BamStreamReader:
    """Incremental BAM record reader over a rolling decompressed buffer."""

    def __init__(
        self,
        path: str,
        read_size: int = 8 << 20,
        use_native: bool = True,
        start: tuple[int, int] | None = None,
        open_fn=None,
    ):
        """start=(coffset, uoffset): begin the record stream at that
        BGZF virtual offset (from a BamLinearIndex entry) instead of the
        first record; the header is still parsed from the file start.

        open_fn(path) -> file-like overrides the plain open — the
        follow-mode tailer (live/tail.py) injects its TailSource here
        so the reader consumes a growing input through the exact same
        read/seek/tell surface. Forward-only sources refuse ``start``.
        """
        native_lib = None
        n_threads = 0
        if use_native:
            from duplexumiconsensusreads_tpu.native import get_lib

            native_lib = get_lib()
            n_threads = min(os.cpu_count() or 1, 16)
        self._native_lib = native_lib
        self._f = open_fn(path) if open_fn is not None else open(path, "rb")
        self._buf = bytearray()
        self._eof = False
        self._consumed = 0  # decompressed bytes consumed (header incl.)
        if start is None:
            self._gen = _iter_bgzf_stream(
                self._f, read_size, native_lib=native_lib, n_threads=n_threads
            )
            self.header = self._read_header()
        else:
            tmp = BamStreamReader(path, read_size, use_native)
            self.header = tmp.header
            tmp.close()
            coff, uoff = start
            self._f.seek(coff)
            self._gen = _iter_bgzf_stream(
                self._f, read_size, native_lib=native_lib, n_threads=n_threads
            )
            if uoff:
                if not self._fill(uoff):
                    raise ValueError("index start offset past EOF")
                del self._buf[:uoff]

    def close(self):
        self._f.close()

    def _fill(self, need: int) -> bool:
        while len(self._buf) < need and not self._eof:
            try:
                self._buf += next(self._gen)
            except StopIteration:
                self._eof = True
        return len(self._buf) >= need

    def _need(self, n: int, what: str) -> None:
        if not self._fill(n):
            raise ValueError(f"truncated BAM: incomplete {what}")

    def _read_header(self) -> BamHeader:
        self._need(12, "magic")
        if bytes(self._buf[:4]) != b"BAM\x01":
            raise ValueError("not a BAM file")
        (l_text,) = struct.unpack_from("<i", self._buf, 4)
        if l_text < 0:
            raise ValueError("malformed BAM: negative l_text")
        self._need(8 + l_text + 4, "header text")
        text = bytes(self._buf[8 : 8 + l_text]).split(b"\x00", 1)[0].decode()
        off = 8 + l_text
        (n_ref,) = struct.unpack_from("<i", self._buf, off)
        if n_ref < 0:
            raise ValueError("malformed BAM: negative n_ref")
        off += 4
        names, lengths = [], []
        for _ in range(n_ref):
            self._need(off + 4, "reference entry")
            (l_name,) = struct.unpack_from("<i", self._buf, off)
            if l_name < 1:
                raise ValueError("malformed BAM: bad reference name length")
            off += 4
            self._need(off + l_name + 4, "reference entry")
            names.append(bytes(self._buf[off : off + l_name - 1]).decode())
            off += l_name
            (l_ref,) = struct.unpack_from("<i", self._buf, off)
            off += 4
            lengths.append(l_ref)
        del self._buf[:off]
        self._consumed += off
        return BamHeader(text=text, ref_names=names, ref_lengths=lengths)

    def read_raw_records(self, n: int) -> bytes | None:
        """Raw bytes of up to n whole records; None at EOF."""
        if self._native_lib is not None:
            return self._read_raw_records_native(n)
        count = 0
        off = 0
        while count < n:
            if not self._fill(off + 4):
                break
            (bsz,) = struct.unpack_from("<i", self._buf, off)
            # 32 fixed bytes + >=1 read-name byte is the smallest record
            if bsz < 33:
                raise ValueError(f"malformed BAM: record block_size {bsz}")
            self._need(off + 4 + bsz, "record")
            off += 4 + bsz
            count += 1
        if count == 0:
            if self._buf and self._eof:
                raise ValueError(
                    "truncated BAM: trailing partial record at EOF"
                )
            return None
        out = bytes(self._buf[:off])
        del self._buf[:off]
        self._consumed += off
        return out

    def _read_raw_records_native(self, n: int) -> bytes | None:
        """read_raw_records via the C record-chain walker: no
        per-record Python loop (the walk was the streaming reader's
        top host cost at scale)."""
        import ctypes

        lib = self._native_lib
        count = 0
        off = 0
        while count < n:
            # the frombuffer view must not outlive the iteration: a live
            # export would block the bytearray resize below
            buf_arr = np.frombuffer(self._buf, np.uint8)
            end = ctypes.c_long()
            c = lib.dut_bam_chain(
                buf_arr, len(buf_arr), off, n - count, ctypes.byref(end)
            )
            del buf_arr
            if c < 0:
                bad = int(end.value)  # chain reports the offending record
                bsz = struct.unpack_from("<i", self._buf, bad)[0] if len(
                    self._buf
                ) >= bad + 4 else -1
                raise ValueError(f"malformed BAM: record block_size {bsz}")
            count += c
            off = int(end.value)
            if count >= n:
                break
            if not self._fill(len(self._buf) + 1):
                break  # EOF: return what we have; partial tail errors next call
        if count == 0:
            if self._buf and self._eof:
                raise ValueError(
                    "truncated BAM: trailing partial record at EOF"
                )
            return None
        # one copy, not two: bytes(bytearray-slice) would slice-copy
        # then copy again; memoryview slices are zero-copy views
        mv = memoryview(self._buf)
        out = bytes(mv[:off])
        mv.release()
        del self._buf[:off]
        self._consumed += off
        return out


def _records_from_raw(header: BamHeader, raw: bytes) -> BamRecords:
    """Parse a raw record stream by prepending a minimal header."""
    _, recs = parse_bam(_header_shell(header) + raw)
    return recs


def _validate_sort_contract(keys: np.ndarray, prev_last) -> None:
    """Raise on a streaming sort-contract violation (shared wording).

    Factored out of _resolve_chunk_boundary so range-mode early-exit
    paths (key_hi cut, EOF carry flush) can validate chunks that never
    reach the boundary rule — an unsorted final in-range chunk must
    fail loudly, not be silently mis-truncated by searchsorted
    (ADVICE r2)."""
    if len(keys) > 1 and (np.diff(keys) < 0).any():
        i = int(np.nonzero(np.diff(keys) < 0)[0][0])
        raise ValueError(
            "input violates the streaming sort contract: pos_key "
            f"decreases at record ~{i} ({keys[i]} -> "
            f"{keys[i+1]}). Streaming needs non-decreasing "
            "fragment keys (template-coordinate order for paired "
            "data); use whole-file mode (--chunk-reads 0) for "
            "unsorted input."
        )
    if prev_last is not None and len(keys) and keys[0] <= prev_last:
        raise ValueError(
            "input violates the streaming sort contract across a "
            "chunk boundary (pos_key repeats after being flushed)"
        )


def _resolve_chunk_boundary(keys: np.ndarray, prev_last):
    """THE chunk-boundary rule, shared by the Python and native chunk
    iterators (their boundaries must stay byte-identical — checkpoint
    manifests key chunks by index). On the combined buffer's pos_keys,
    returns (cut, new_prev_last):

      cut == 0         entire buffer is one position group: keep growing
      cut == len(keys) unmapped sentinel tail: flush everything, no
                       hold-back (sentinel keys are never groupable)
      otherwise        yield records [:cut], hold back the final group

    Raises on sort-contract violations (the one shared wording).
    """
    _validate_sort_contract(keys, prev_last)
    # Unmapped EOF tail: sentinel-key records are never groupable (the
    # FLAG filter invalidates them downstream), so family integrity
    # doesn't apply — flush immediately. Carrying them would be
    # unbounded: the whole tail shares ONE pos_key. Later all-sentinel
    # chunks must pass the repeat check, but any MAPPED key after the
    # tail is a sort violation and must trip it.
    if keys[-1] == UNMAPPED_POS_KEY:
        return len(keys), UNMAPPED_POS_KEY - 1
    last = keys[-1]
    keep = np.nonzero(keys != last)[0]
    if len(keep) == 0:
        return 0, prev_last
    cut = int(keep[-1]) + 1
    return cut, keys[cut - 1]


def iter_record_chunks(path: str, chunk_reads: int, open_fn=None):
    """Yield (header, BamRecords) chunks; the trailing pos_key group of
    each chunk is held back and prepended to the next so no molecule's
    reads are split across chunks.

    The sort contract (non-decreasing pos_key — see module docstring)
    is VALIDATED on every chunk: a violation raises instead of silently
    splitting a family across chunks. Note plain coordinate order is
    NOT sufficient for paired-end data (a mate's pos_key is the
    fragment's min coordinate, which sorts earlier than the mate) —
    that input needs template-coordinate sorting, exactly as the
    reference domain's duplex tools require.
    """
    reader = BamStreamReader(path, open_fn=open_fn)
    header = reader.header
    carry: BamRecords | None = None
    prev_last = None
    try:
        while True:
            raw = reader.read_raw_records(chunk_reads)
            if raw is None:
                if carry is not None and len(carry):
                    yield header, carry
                return
            recs = _records_from_raw(header, raw)
            if carry is not None and len(carry):
                recs = _concat_records(carry, recs)
            batch_pos = _rec_pos_keys(recs)
            cut, prev_last = _resolve_chunk_boundary(batch_pos, prev_last)
            if cut == 0:
                carry = recs  # entire chunk is one group; keep growing
                continue
            if cut == len(recs):  # sentinel tail: flush, no hold-back
                carry = None
                yield header, recs
                continue
            carry = _slice_records(recs, cut, len(recs))
            yield header, _slice_records(recs, 0, cut)
    finally:
        reader.close()




def _header_shell(header: BamHeader) -> bytes:
    shell = bytearray()
    shell += b"BAM\x01"
    text = header.text.encode()
    shell += struct.pack("<i", len(text)) + text
    shell += struct.pack("<i", len(header.ref_names))
    for name, length in zip(header.ref_names, header.ref_lengths):
        nb = name.encode() + b"\x00"
        shell += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)
    return bytes(shell)


def iter_batch_chunks(
    path: str,
    chunk_reads: int,
    duplex: bool,
    start: tuple[int, int] | None = None,
    key_lo=None,
    key_hi=None,
    warn_mixed: bool = True,
    first_read: int | None = None,
    open_fn=None,
):
    """Yield (header, ReadBatch, info) chunks with the family-integrity
    hold-back of iter_record_chunks, but parsed NATIVELY: record fields
    go straight from raw BAM bytes into NumPy arrays (io/native_reader),
    bypassing the per-record Python loop — the difference between the
    host starving the device and keeping up at 200M-read scale.

    Chunk boundaries are byte-identical to iter_record_chunks' (same
    hold-back and sentinel-flush rules on the same pos_keys), so
    checkpoint manifests remain valid whichever path produced them.
    Falls back to the pure-Python iterator when the native library is
    unavailable or DUT_NO_NATIVE is set.

    Multi-host range mode (io/index.py): ``start`` opens the stream at
    a BGZF virtual offset; only records with key_lo <= pos_key < key_hi
    are yielded (None = open end). Leading records below key_lo are
    skipped; iteration stops at the first record >= key_hi.

    ``first_read`` (range mode, native path): record count of the FIRST
    raw read, after which reads revert to ``chunk_reads`` — the shard
    planner's chunk-grid realignment. Chunk boundaries are a pure
    function of the sequence of raw-read end positions plus the
    pos_keys, so a ranged stream whose first read ends exactly where
    the whole-file stream's corresponding read would reproduces the
    whole-file chunk boundaries from there on — the property the
    scatter-gather byte-identity contract (serve/shard/) is built on.
    The Python fallback ignores both ``start`` and ``first_read``: it
    re-chunks the full stream and filters per chunk, so its boundaries
    are whole-file-aligned by construction.
    """
    lib = None
    if not os.environ.get("DUT_NO_NATIVE"):
        from duplexumiconsensusreads_tpu.native import get_lib

        lib = get_lib()
    if lib is None:
        # portable fallback: full scan with host-range filtering (the
        # `start` seek is an optimisation the Python path skips)
        for header, recs in iter_record_chunks(path, chunk_reads, open_fn=open_fn):
            keys = _rec_pos_keys(recs)
            a, b = 0, len(recs)
            if key_lo is not None:
                a = int(np.searchsorted(keys, key_lo, side="left"))
            if key_hi is not None:
                b = int(np.searchsorted(keys, key_hi, side="left"))
            if a >= b:
                if key_hi is not None and len(keys) and keys[0] >= key_hi:
                    return
                continue
            sub = recs if (a, b) == (0, len(recs)) else _slice_records(recs, a, b)
            batch, info = records_to_readbatch(
                sub, duplex=duplex, warn_mixed=warn_mixed
            )
            yield header, batch, info
            if key_hi is not None and b < len(recs):
                return
        return

    from duplexumiconsensusreads_tpu.io.native_reader import (
        batch_from_offsets,
        region_pos_keys,
        scan_region,
    )

    nt = min(os.cpu_count() or 1, 16)
    reader = BamStreamReader(path, start=start, open_fn=open_fn)
    header = reader.header
    shell = _header_shell(header)
    carry = b""
    prev_last = None
    lo_done = key_lo is None

    def emit(data, offs, lm, rm):
        return (
            header,
            *batch_from_offsets(
                lib, data, offs, lm, rm, duplex=duplex, n_threads=nt,
                warn_mixed=warn_mixed,
            ),
        )

    # chunk-grid realignment: only the first read differs (see the
    # docstring); a None/0 first_read keeps the uniform grid
    n_next_read = (
        first_read if first_read is not None and first_read > 0
        else chunk_reads
    )
    try:
        while True:
            raw = reader.read_raw_records(n_next_read)
            n_next_read = chunk_reads
            if raw is None:
                if carry:
                    data = np.frombuffer(shell + carry, np.uint8)
                    he, lm, rm, off = scan_region(lib, data, path)
                    if key_hi is not None and len(off):
                        keys = region_pos_keys(data, off)
                        _validate_sort_contract(keys, prev_last)
                        off = off[: int(np.searchsorted(keys, key_hi, side="left"))]
                    if len(off):
                        yield emit(data, off, lm, rm)
                return
            # single join: shell + carry + raw concatenated once; carry
            # slices index into this blob directly (offsets absolute)
            blob = b"".join((shell, carry, raw))
            data = np.frombuffer(blob, np.uint8)
            he, lm, rm, rec_off = scan_region(lib, data, path)
            keys = region_pos_keys(data, rec_off)
            if not lo_done and len(keys):
                # searchsorted assumes sorted keys; an unsorted chunk
                # must raise here, not be silently mis-cut (the a ==
                # len(keys) discard below would even swallow it whole)
                _validate_sort_contract(keys, prev_last)
                a = int(np.searchsorted(keys, key_lo, side="left"))
                if a == len(keys):
                    carry = b""  # everything below the range: discard
                    continue
                rec_off, keys = rec_off[a:], keys[a:]
                lo_done = True
            if key_hi is not None and len(keys) and keys[-1] >= key_hi:
                # the boundary rule never sees this final chunk, so the
                # sort contract must be validated here before the
                # searchsorted cut (unsorted keys would mis-truncate)
                _validate_sort_contract(keys, prev_last)
                b = int(np.searchsorted(keys, key_hi, side="left"))
                if b:
                    yield emit(data, rec_off[:b], lm, rm)
                return
            cut, prev_last = _resolve_chunk_boundary(keys, prev_last)
            if cut == 0:
                # entire (in-range) buffer is one group; keep growing.
                # rec_off[0] rebases past any below-range records the
                # lo filter dropped this iteration.
                carry = blob[int(rec_off[0]):]
                continue
            if cut == len(keys):  # sentinel tail: flush, no hold-back
                carry = b""
                yield emit(data, rec_off, lm, rm)
                continue
            carry = blob[int(rec_off[cut]):]
            yield emit(data, rec_off[:cut], lm, rm)
    finally:
        reader.close()


def _slice_records(recs: BamRecords, a: int, b: int) -> BamRecords:
    from duplexumiconsensusreads_tpu.io.bam import _slice_recs

    return _slice_recs(recs, a, b)


def _concat_records(a: BamRecords, b: BamRecords) -> BamRecords:
    lmax = max(a.seq.shape[1], b.seq.shape[1])

    def padseq(x, fill):
        out = np.full((x.shape[0], lmax), fill, np.uint8)
        out[:, : x.shape[1]] = x
        return out

    from duplexumiconsensusreads_tpu.constants import BASE_PAD

    return BamRecords(
        names=a.names + b.names,
        flags=np.concatenate([a.flags, b.flags]),
        ref_id=np.concatenate([a.ref_id, b.ref_id]),
        pos=np.concatenate([a.pos, b.pos]),
        mapq=np.concatenate([a.mapq, b.mapq]),
        next_ref_id=np.concatenate([a.next_ref_id, b.next_ref_id]),
        next_pos=np.concatenate([a.next_pos, b.next_pos]),
        tlen=np.concatenate([a.tlen, b.tlen]),
        lengths=np.concatenate([a.lengths, b.lengths]),
        seq=np.concatenate([padseq(a.seq, BASE_PAD), padseq(b.seq, BASE_PAD)]),
        qual=np.concatenate([padseq(a.qual, 0), padseq(b.qual, 0)]),
        cigars=a.cigars + b.cigars,
        umi=a.umi + b.umi,
        aux_raw=a.aux_raw + b.aux_raw,
    )


# ------------------------------------------------------------ checkpoint

def _verify_shard(entry, expect_codec: str | None = None) -> bool:
    """Trust a manifest entry only when the shard's bytes still match
    the size + CRC32 recorded at write time. Existence alone would let
    a torn shard (crash mid-write before the durable rename, or later
    corruption) be spliced silently into the final BAM on resume —
    verification failure just means the chunk is recomputed."""
    if not isinstance(entry, dict):  # pre-CRC manifest format: recompute
        return False
    if not isinstance(entry.get("n_records"), int) or not isinstance(
        entry.get("n_pairs"), int
    ):
        # pre-pipelined-drain manifest: record counts were derived from
        # the raw shard bytes at finalise, which BGZF-compressed shards
        # no longer expose — recompute rather than guess
        return False
    if expect_codec is not None and entry.get("codec") != expect_codec:
        # the shard was deflated by a DIFFERENT codec than this run
        # will use (e.g. the native library failed at runtime mid-run
        # and compress_fast fell back to pure Python, under a
        # fingerprint whose capability probe said native): reusing it
        # would splice mixed-codec bytes — different, both-valid
        # deflate streams — breaking resume-converges-to-identical-
        # bytes. Recompute. Entries without a codec field (pre-codec
        # manifests) recompute for the same reason.
        return False
    path = entry.get("path")
    try:
        if not path or os.path.getsize(path) != entry.get("size"):
            return False
        # bounded-memory streaming CRC: a shard can be a whole chunk's
        # records, and resume verifies every one of them
        crc = 0
        with open(path, "rb") as f:
            while True:
                block = f.read(1 << 20)
                if not block:
                    break
                crc = zlib.crc32(block, crc)
    except OSError:
        return False
    return crc == entry.get("crc32")


@dataclasses.dataclass
class Checkpoint:
    path: str
    fingerprint: str
    # chunk index (str) -> {"path", "size", "crc32", "n_records",
    # "n_pairs", "codec"} — counts ride in the manifest because shards
    # are stored BGZF-compressed and resumed chunks must still
    # contribute to the report totals without a decompress pass; codec
    # is the deflate flavor ACTUALLY used for the shard's bytes, so a
    # runtime native->python fallback can never be spliced under a
    # healthy-native resume
    done: dict

    @staticmethod
    def load_or_create(
        path: str, fingerprint: str, verify: bool = True,
        expect_codec: str | None = None,
    ) -> "Checkpoint":
        """Load the manifest, pruning entries that no longer apply.

        Whatever this returns is immediately persisted if it differs
        from the on-disk state: a diverging manifest (mismatched
        fingerprint, dead or checksum-failing shards, torn/garbage
        JSON) must not survive on disk, where a crash-before-first-mark
        would let a later --resume splice stale shard bytes from a
        different run into the output.

        ``verify=False`` skips the per-shard size+CRC re-read — for
        callers about to discard ``done`` anyway (resume=False), where
        re-reading every prior shard (~ the whole output BAM) would be
        thrown-away I/O."""
        done: dict = {}
        on_disk = None
        torn = False
        if os.path.exists(path):
            try:
                with open(path) as f:
                    on_disk = json.load(f)
                if not isinstance(on_disk, dict) or not isinstance(
                    on_disk.get("done", {}), dict
                ):
                    raise ValueError("manifest is not a JSON object")
            except (OSError, ValueError) as e:
                # torn or garbage manifest (crash mid-write where the
                # rename wasn't durable, external corruption): never
                # fatal — recomputing the chunks is always safe
                print(
                    f"[duplexumi] discarding unreadable checkpoint "
                    f"manifest {path} ({e})",
                    file=sys.stderr,
                )
                on_disk, torn = None, True
            else:
                if on_disk.get("fingerprint") == fingerprint:
                    done = {
                        k: v
                        for k, v in on_disk.get("done", {}).items()
                        if not verify or _verify_shard(v, expect_codec)
                    }
        ckpt = Checkpoint(path, fingerprint, done)
        if torn or (
            on_disk is not None
            and on_disk != {"fingerprint": fingerprint, "done": done}
        ):
            ckpt.save()
        return ckpt

    def save(self) -> None:
        payload = json.dumps(
            {"fingerprint": self.fingerprint, "done": self.done}
        ).encode()
        # unique staging name: under the serve/ fleet a reclaimed job's
        # new daemon and a not-yet-fenced zombie can both persist this
        # manifest — private tmps keep the atomic rename torn-file-free
        _io_retry(
            "ckpt.save",
            lambda: write_durable(self.path, payload, tmp=unique_tmp(self.path)),
            "checkpoint save",
        )

    def mark(
        self, chunk: int, shard_path: str, size: int, crc: int,
        n_records: int, n_pairs: int, codec: str,
    ) -> None:
        self.done[str(chunk)] = {
            "path": shard_path, "size": size, "crc32": crc,
            "n_records": n_records, "n_pairs": n_pairs, "codec": codec,
        }
        self.save()


def _fingerprint(
    in_path: str, grouping, consensus, capacity, chunk_reads, input_range=None,
    mate_aware: str = "auto", max_reads: int = 0, per_base_tags: bool = False,
    read_group: str = "A", chunk_base: int = 0, first_read: int | None = None,
    stat_sig: str | None = None,
) -> str:
    """The mate_aware SETTING (auto/on/off) joins the key rather than
    the resolved boolean: resolution is a deterministic function of the
    fingerprinted input file, and fingerprinting the setting lets the
    manifest be initialised before any input byte is read (the
    stale-manifest-clearing guarantee).

    This signature IS the checkpoint-fingerprint surface that
    `runtime/knobs.py` declares per knob: dutlint's knob-taint rule
    reads KNOB_TABLE and checks every parameter/literal here against
    each knob's declared surfaces — a scheduling knob (max_inflight,
    drain_workers, ...) added to this key would make resumability
    depend on scheduling and is a lint finding; a semantic knob
    REMOVED from it is one too.

    ``stat_sig`` replaces the input's (size, mtime) pair: a follow run
    tails a GROWING file, whose size and mtime change every poll, so
    the live watermark (live/watermark.py) pins a per-run token instead
    — kill/resume mid-tail keeps one fingerprint while two different
    follow runs still get distinct ones. Not a knob: it is run identity
    (like the input path), never user-steerable scheduling."""
    st = os.stat(in_path)
    key = json.dumps(
        [
            os.path.abspath(in_path),
            *([st.st_size, int(st.st_mtime)] if stat_sig is None
              else ["live", stat_sig]),
            dataclasses.asdict(grouping),
            dataclasses.asdict(consensus),
            capacity,
            chunk_reads,
            mate_aware,
            max_reads,
            per_base_tags,
            read_group,
            [list(x) if isinstance(x, tuple) else x for x in (input_range or [])],
            # range-mode chunk boundaries differ between the native and
            # Python iterators (the fallback ignores the seek and
            # filters instead), so a manifest written by one flavor must
            # never be resumed by the other; no-range boundaries are
            # byte-identical (parity-tested), so the flavor only taints
            # ranged fingerprints
            _iterator_flavor() if input_range else "any",
            # shard on-disk format: BGZF-compressed record stream with
            # counts in the manifest. Tagging the fingerprint means a
            # manifest written by the raw-shard format can never be
            # spliced by this code (and vice versa)
            "shard:bgzf1",
            # deflate codec flavor, UNCONDITIONALLY: native and
            # pure-Python BGZF deflate produce different (both valid)
            # bytes for the same records, and resumed shards are
            # appended verbatim — splicing across codecs would break
            # the resume-converges-to-identical-bytes guarantee.
            # deflate_flavor PROBES the native compress entry point
            # (not get_lib(): a library that loads but cannot compress
            # must fingerprint as python); the residual risk — native
            # failing at runtime AFTER a successful probe — is covered
            # by the per-shard "codec" manifest field, which resume
            # verification checks against this same flavor
            "deflate:" + bgzf.deflate_flavor(),
        ]
        # shard-mode chunk-grid parameters change every chunk boundary,
        # so a manifest from one plan must never be resumed by another;
        # appended only when set, keeping pre-shard fingerprints stable
        + ([chunk_base, first_read] if (chunk_base or first_read) else []),
        sort_keys=True,
    )
    return hashlib.sha256(key.encode()).hexdigest()[:16]


def _iterator_flavor() -> str:
    if os.environ.get("DUT_NO_NATIVE"):
        return "python"
    from duplexumiconsensusreads_tpu.native import get_lib

    return "native" if get_lib() is not None else "python"


# -------------------------------------------------------------- executor

def stream_call_consensus(
    in_path: str,
    out_path: str,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    capacity: int = 2048,
    chunk_reads: int = 500_000,
    n_devices: int | None = None,
    max_inflight: int = 4,
    drain_workers: int = 2,  # drain worker threads (fetch/scatter/
    # serialize/shard-write off the main loop); 1 = single-worker
    # pipelined drain. Output bytes are identical at any setting.
    checkpoint_path: str | None = None,
    resume: bool = False,
    report_path: str | None = None,
    profile_dir: str | None = None,
    cycle_shards: int = 1,
    progress=None,
    commit_guard=None,  # called with the chunk index BEFORE each chunk's
    # durable commit (checkpoint mark + finalise append) on the main
    # thread. The serving layer passes its lease fence check here: a
    # daemon whose lease was reclaimed must abort before splicing
    # another byte, not after. Exceptions propagate unhandled.
    max_retries: int = 3,
    input_range=None,  # (start_voffset, key_lo, key_hi) — multi-host partition
    name_tag: str = "",  # disambiguates consensus names across hosts
    mate_aware: str = "auto",
    max_reads: int = 0,  # cap per exact sub-family (0 = off); see
    # io.convert.downsample_families
    per_base_tags: bool = False,  # emit cd:B,I per-base depth arrays
    # (fetches the (F, L) depth matrix off-device — costs transfer)
    read_group: str = "A",  # consensus @RG id (fgbio-style single
    # output read group); joins the checkpoint fingerprint — it changes
    # record bytes
    write_index: bool = False,  # write the standard .bai after finalise
    packed: str = "auto",  # H2D wire packing rung: "auto" picks the
    # best lossless rung per chunk class (sub-byte qual-dictionary
    # where the alphabet fits, else the base|qual byte), "byte" caps at
    # the byte rung, "off" disables — the bench A/B measures the rungs
    # on the same input. Output bytes are identical at any setting.
    d2h_packed: str = "auto",  # packed consensus-only return path:
    # "auto" compacts + packs the fetch (executor.pack_fetch_outputs)
    # whenever the u16 lanes fit and per-base tags are off; "off"
    # fetches the full padded FETCH_KEYS arrays. Byte-identical output
    # either way (the drain-side unpack reconstructs exact arrays).
    prefetch_depth: int = 2,  # bounded H2D prefetch window: at most
    # this many chunks may be dispatched (host pack + device_put +
    # device compute started) ahead of the drain's materialisation —
    # host packing + H2D of chunk k+1 overlaps device compute of chunk
    # k without unbounded device-buffer pileup. Output bytes are
    # identical at any depth.
    ingest_overlap: str = "auto",  # bounded background producer:
    # "auto"/"on" run BGZF read + decode + host prep (bucketing) on a
    # dedicated ingest thread that works up to prefetch_depth prepped
    # chunks AHEAD of the main loop, handing chunks off through a
    # depth-bounded queue whose bound couples ingest back-pressure to
    # the same window as the H2D prefetch semaphore; "off" keeps the
    # fully synchronous main-loop ingest (today's exact path). A
    # scheduling decision like the mesh: output bytes are identical
    # either way, and the knob stays OUT of the checkpoint fingerprint
    # so overlap-on runs can resume overlap-off prefixes and vice versa.
    bucket_ladder="off",  # mixed-capacity bucket ladder (tuning/):
    # "off" = the single --capacity (legacy), "auto" = profile the
    # first chunk's group-size histogram and pick a 1-3 rung ladder by
    # the tuner's padded-cycles cost model (a ledgered tuner_verdict
    # event), or an explicit ascending pow2 rung tuple / "r1,r2" string
    # whose top rung REPLACES capacity as the bucket capacity. Output
    # bytes are identical at every setting (the final per-chunk
    # (pos_key, UMI) sort makes bytes a pure function of the read set),
    # which is also why the ladder deliberately stays OUT of the
    # checkpoint fingerprint: shards are ladder-invariant, so a
    # verdict-driven serve slice can resume a prefix an auto slice
    # committed.
    trace_path: str | None = None,  # per-chunk span capture (JSONL;
    # telemetry/trace.py). None = tracing off, and every hook in the
    # hot path is a single None check — the zero-cost contract
    heartbeat_s: float = 0.0,  # >0: periodic liveness line to stderr
    # (chunks done/inflight, stall fraction, retries, drain util)
    trace_max_events: int = 1_000_000,  # bounded-capture cap
    provenance_cl: str | None = None,  # @PG CL override for the output
    # header. None = this process's argv (the one-shot convention); the
    # serving layer passes a canonical config-derived line so a job's
    # bytes are a pure function of (input, config), not of which daemon
    # process happened to finish it
    chunk_base: int = 0,  # global index of this run's first chunk: a
    # shard sub-job (serve/shard/) numbers its chunks — and therefore
    # its consensus record names — on the parent's whole-file grid, so
    # merged shard outputs are byte-identical to the unsharded run
    first_read: int | None = None,  # record count of the first raw read
    # (shard chunk-grid realignment; see iter_batch_chunks)
    devices=None,  # local-device INDEX subset to build the mesh from
    # (dut-serve --devices pinning: a fleet of daemons on one host can
    # each own a disjoint device set). None = all local devices;
    # n_devices then counts within the subset. Output bytes are
    # identical for any subset/count — device count is a wire/compute
    # topology knob, never a result knob (the mesh byte-identity
    # contract, A/B-tested like --drain-workers).
    follow: bool = False,  # follow-mode ingest (live/): tail a GROWING
    # input — regular file or FIFO — admitting only complete-BGZF-block
    # byte runs, and finalise when the input is finished (see
    # finalize_on). Scheduling-class like the mesh: a follow run over
    # the finished file is byte-identical to the batch run, so the knob
    # stays OUT of the checkpoint fingerprint and @PG provenance.
    finalize_on: str = "eof",  # follow termination rule: "eof" (the
    # 28-byte BGZF EOF block — the BAM spec's own terminator),
    # "idle:<seconds>" (no growth for N seconds), or "marker"
    # (<input>.done exists). See live.tail.parse_finalize_on.
    live_poll_s: float = 0.25,  # follow poll cadence: how long the
    # tailer sleeps when the read has caught up with the writer
    snapshot_chunks: int = 0,  # >0: publish an indexed partial
    # snapshot (a valid BAM prefix + BAI at out+".snapshot.bam") every
    # N committed chunks. Output-bytes-neutral: the snapshot is a side
    # artifact, the final output never depends on it.
) -> RunReport:
    """Chunked, async-pipelined consensus calling (TPU backend).

    Public entry point: a telemetry wrapper around :func:`_stream_call`
    (the executor body — see its docstring for the pipeline/recovery
    semantics). The trace recorder and heartbeat are owned HERE so they
    are torn down on every exit path — normal return, device failure,
    injected kill, Ctrl-C — and a crashed run still leaves a valid
    (summary-less) capture on disk for post-mortem. The recorder is
    also installed as the process-global telemetry hook so the fault
    switchboard (runtime/faults.py) and durable-write layer
    (io/durable.py) can emit events without threading a handle through
    every call."""
    tr: TraceRecorder | None = None
    hb_box: list = []  # the body parks its Heartbeat here for teardown
    hooked = False
    if trace_path:
        tr = TraceRecorder(trace_path, max_events=trace_max_events)
        # the global hook is single-run (same assumption the faults
        # switchboard makes): a concurrent traced run in this process
        # keeps its direct spans but must not steal another run's
        # fault/retry/durable events — or tear down its hook
        if telemetry.get_active() is None:
            telemetry.install(tr)
            hooked = True
        else:
            print(
                "[duplexumi] another trace recorder is active in this "
                "process; fault/retry/durable events will be recorded "
                "by that run, not this capture",
                file=sys.stderr,
            )
    try:
        return _stream_call(
            in_path, out_path, grouping, consensus,
            capacity=capacity, chunk_reads=chunk_reads,
            n_devices=n_devices, max_inflight=max_inflight,
            drain_workers=drain_workers, checkpoint_path=checkpoint_path,
            resume=resume, report_path=report_path,
            profile_dir=profile_dir, cycle_shards=cycle_shards,
            progress=progress, commit_guard=commit_guard,
            max_retries=max_retries,
            input_range=input_range, name_tag=name_tag,
            mate_aware=mate_aware, max_reads=max_reads,
            per_base_tags=per_base_tags, read_group=read_group,
            write_index=write_index, packed=packed,
            d2h_packed=d2h_packed, prefetch_depth=prefetch_depth,
            ingest_overlap=ingest_overlap,
            bucket_ladder=bucket_ladder,
            tr=tr, heartbeat_s=heartbeat_s, hb_box=hb_box,
            provenance_cl=provenance_cl,
            chunk_base=chunk_base, first_read=first_read,
            devices=devices,
            follow=follow, finalize_on=finalize_on,
            live_poll_s=live_poll_s, snapshot_chunks=snapshot_chunks,
        )
    finally:
        for hb in hb_box:
            hb.stop()
        if tr is not None:
            if hooked:
                telemetry.uninstall()
            tr.close()


def _stream_call(
    in_path: str,
    out_path: str,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    capacity: int = 2048,
    chunk_reads: int = 500_000,
    n_devices: int | None = None,
    max_inflight: int = 4,
    drain_workers: int = 2,
    checkpoint_path: str | None = None,
    resume: bool = False,
    report_path: str | None = None,
    profile_dir: str | None = None,
    cycle_shards: int = 1,
    progress=None,
    commit_guard=None,
    max_retries: int = 3,
    input_range=None,
    name_tag: str = "",
    mate_aware: str = "auto",
    max_reads: int = 0,
    per_base_tags: bool = False,
    read_group: str = "A",
    write_index: bool = False,
    packed: str = "auto",
    d2h_packed: str = "auto",
    prefetch_depth: int = 2,
    ingest_overlap: str = "auto",
    bucket_ladder="off",
    tr: TraceRecorder | None = None,
    heartbeat_s: float = 0.0,
    hb_box: list | None = None,
    provenance_cl: str | None = None,
    chunk_base: int = 0,
    first_read: int | None = None,
    devices=None,
    follow: bool = False,
    finalize_on: str = "eof",
    live_poll_s: float = 0.25,
    snapshot_chunks: int = 0,
) -> RunReport:
    """Chunked, async-pipelined consensus calling (TPU backend).

    Writes per-chunk shards next to out_path and finalises a single
    consensus BAM INCREMENTALLY: a bounded pool of ``drain_workers``
    threads runs the consumer side of the pipeline (device fetch →
    scatter-back → record serialization → BGZF deflate → durable shard
    write) off the main loop, while an ordered-completion frontier on
    the main thread commits checkpoint marks and appends finished
    shards into ``out_path + ".tmp"`` strictly in chunk order — so
    ingest/bucket/dispatch never stalls behind the drain, resume/CRC
    semantics are exactly the serial drain's, and the end-of-run
    finalise collapses to the last chunk plus the atomic fsync+rename.
    Chunked runs checkpoint BY DEFAULT to
    ``out_path + ".ckpt"`` (crash -> rerun with resume=True skips
    finished chunks); pass an explicit checkpoint_path to also keep
    shards after a successful finalise. Device failures retry with
    exponential backoff, then fall back to bucket-by-bucket re-dispatch
    so one poisoned bucket cannot take down a whole chunk class.

    mate_aware="auto" resolves against the FIRST chunk (mates share a
    canonical fragment pos_key, so any chunk holding paired templates
    holds both their mates); the resolved mode is stable for the whole
    run and joins the checkpoint fingerprint. If a later chunk turns
    out mixed-mate under a resolved-off mode, the standard loud
    warning fires and the counter fills — exactly the non-mate-aware
    contract.
    """
    import itertools
    import warnings as _warnings

    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.io.bam import serialize_bam
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import sharded_pipeline
    from duplexumiconsensusreads_tpu.runtime.executor import (
        count_consensus_pairs,
        resolve_mate_aware,
    )

    if drain_workers < 1:
        raise ValueError(f"drain_workers must be >= 1 (got {drain_workers})")
    if prefetch_depth < 1:
        raise ValueError(f"prefetch_depth must be >= 1 (got {prefetch_depth})")
    if packed not in ("auto", "byte", "off"):
        raise ValueError(f"packed must be auto/byte/off, got {packed!r}")
    if d2h_packed not in ("auto", "off"):
        raise ValueError(f"d2h_packed must be auto/off, got {d2h_packed!r}")
    if ingest_overlap not in ("auto", "on", "off"):
        raise ValueError(
            f"ingest_overlap must be auto/on/off, got {ingest_overlap!r}"
        )
    # auto == on: the producer pipeline is pure scheduling (byte-
    # identical output, proven by the A/B matrix), so there is nothing
    # input-dependent for "auto" to resolve — it exists so callers can
    # express "the default" without pinning today's default
    overlap_on = ingest_overlap != "off"
    if snapshot_chunks < 0:
        raise ValueError(
            f"snapshot_chunks must be >= 0 (got {snapshot_chunks})"
        )
    live_src = None  # the follow-mode TailSource (live/tail.py)
    live_mark: dict | None = None  # its durable admission watermark
    if follow:
        from duplexumiconsensusreads_tpu.live import (
            parse_finalize_on as _parse_finalize_on,
        )

        _parse_finalize_on(finalize_on)  # validate the domain up front
        if live_poll_s <= 0:
            raise ValueError(f"live_poll_s must be > 0 (got {live_poll_s})")
        if input_range is not None:
            raise ValueError(
                "follow mode cannot combine with an input range: a "
                "growing input has no random access"
            )
        if chunk_base or first_read:
            raise ValueError(
                "follow mode cannot run as a shard sub-job: the chunk "
                "grid of a growing input is not plannable up front"
            )
    from duplexumiconsensusreads_tpu import tuning

    # bucket-ladder resolution: an explicit ladder is known now (its
    # top rung replaces --capacity as the effective bucket capacity);
    # "auto" resolves ONCE against the first non-empty chunk's profile
    # below, so the compile classes stay stable for the whole run
    ladder_mode = tuning.normalize_bucket_ladder(bucket_ladder)
    run_ladder: tuple | None = None
    ladder_auto = ladder_mode == "auto"
    if isinstance(ladder_mode, tuple):
        run_ladder = ladder_mode if len(ladder_mode) > 1 else None
        capacity = ladder_mode[-1]
    rep = RunReport(backend="tpu-stream")
    if isinstance(ladder_mode, tuple):
        rep.bucket_ladder = [int(r) for r in ladder_mode]
    rep.n_drain_workers = drain_workers
    rep.ingest_overlap = overlap_on
    duplex = consensus.mode == "duplex"
    # monotonic everywhere in phase accounting: an NTP step mid-run
    # would corrupt wall-clock deltas (negative or inflated phases)
    t_start = time.monotonic()
    # chaos harness: a DUT_FAULTS schedule gets fresh hit counters per
    # run (a no-op when unset and no plan was installed programmatically)
    install_from_env()

    # auto-checkpoint: chunked runs are long; a crash mid-file must
    # always be resumable without the user having had the foresight to
    # pass --checkpoint (VERDICT r1 item 10). Initialised BEFORE any
    # input is read (the mate-aware setting, not its resolution, joins
    # the fingerprint) so a stale manifest can never survive an early
    # crash.
    auto_ckpt = checkpoint_path is None
    if auto_ckpt:
        checkpoint_path = out_path + ".ckpt"
    if follow:
        # pin the follow-run identity BEFORE fingerprinting: a growing
        # input's (size, mtime) change every poll, so the fingerprint
        # substitutes the watermark's stat_sig — kill/resume mid-tail
        # keeps one fingerprint and converges exactly once
        from duplexumiconsensusreads_tpu.live import watermark as _watermark

        live_mark = _watermark.load_or_create(out_path, in_path, resume=resume)
        # a resumed follower continues the published-snapshot series
        rep.snapshot_seq = int(live_mark.get("snapshot_seq", 0))
    ckpt = None
    if checkpoint_path:
        fp = _fingerprint(
            in_path, grouping, consensus, capacity, chunk_reads, input_range,
            mate_aware=mate_aware, max_reads=max_reads,
            per_base_tags=per_base_tags, read_group=read_group,
            chunk_base=chunk_base, first_read=first_read,
            stat_sig=live_mark["stat_sig"] if live_mark else None,
        )
        # resume=False discards `done` just below — skip the per-shard
        # CRC re-read (it would read ~ the whole prior output for
        # nothing). expect_codec prunes shards whose recorded deflate
        # codec differs from this run's — a runtime native->python
        # fallback shard must be recomputed, never spliced.
        ckpt = Checkpoint.load_or_create(
            checkpoint_path, fp, verify=resume,
            expect_codec=bgzf.deflate_flavor(),
        )
        if not resume:
            # persist a fresh manifest NOW, unconditionally: a stale
            # on-disk manifest (same OR different fingerprint) must not
            # survive a crash-before-first-mark — this run is about to
            # overwrite the shard files it points at, so a later
            # --resume against the old manifest would serve shards
            # whose content no longer matches its params
            ckpt.done = {}
            ckpt.save()

    # ---- mate-aware resolution on the first chunk (mates share a
    # canonical fragment pos_key, so any chunk holding paired templates
    # holds both their mates; the resolved mode is stable for the run) ----
    rng_start, rng_lo, rng_hi = input_range or (None, None, None)
    live_open = None
    if follow:
        from duplexumiconsensusreads_tpu.live import TailSource

        # ONE forward-only source for the whole run: the stream reader
        # opens it through open_fn and closes it with the reader
        live_src = TailSource(
            in_path, poll_s=live_poll_s, finalize_on=finalize_on
        )

        def live_open(_path):
            return live_src

    chunk_iter = iter_batch_chunks(
        in_path, chunk_reads, duplex,
        start=rng_start, key_lo=rng_lo, key_hi=rng_hi,
        warn_mixed=False,  # warning responsibility moves to the chunk loop
        first_read=first_read,
        open_fn=live_open,
    )
    first = next(chunk_iter, None)
    grouping = resolve_mate_aware(
        grouping, first[2] if first is not None else {}, mate_aware
    )
    rep.mate_aware = grouping.mate_aware
    chunk_iter = itertools.chain([] if first is None else [first], chunk_iter)

    if profile_dir:
        jax.profiler.start_trace(profile_dir)

    # local devices: the executors are host-local programs (each host
    # streams its own input partition), so under an initialized
    # multi-controller runtime the mesh must never span other hosts.
    # ``devices`` narrows the pool to an index subset (daemon pinning);
    # n_devices then counts within it.
    pool = jax.local_devices()
    if devices:
        bad = [i for i in devices if not (0 <= int(i) < len(pool))]
        if bad:
            raise ValueError(
                f"devices={list(devices)} out of range: this host has "
                f"{len(pool)} local devices"
            )
        pool = [pool[int(i)] for i in devices]
    n_dev = n_devices or len(pool)
    mesh = make_mesh(n_dev, cycle_shards=cycle_shards, devices=pool)
    n_data = max(n_dev // max(cycle_shards, 1), 1)
    rep.n_devices = n_dev
    header_out: BamHeader | None = None

    from concurrent.futures import ThreadPoolExecutor

    shard_dir = out_path + ".shards"
    os.makedirs(shard_dir, exist_ok=True)
    shards: dict[int, str] = {}
    inflight: deque = deque()  # (chunk idx, drain future), chunk order
    spec_cache: dict = {}
    from duplexumiconsensusreads_tpu.runtime.executor import XFER_WORKERS

    # XFER_WORKERS transfer workers pipeline the tunnel's per-put RPC
    # gaps (measured r3: 1 worker 17.7k reads/s, 2 -> 19.6k, 4 -> ~21k
    # on the 2M-read e2e); device_put releases the GIL on the wire wait.
    # The dut-* prefixes below must stay STRING LITERALS: they are the
    # THREAD_ROLES markers (runtime/knobs.py) that dutlint's
    # thread-confinement rule and test_knobs' closed-world pin key on.
    xfer = ThreadPoolExecutor(
        max_workers=XFER_WORKERS, thread_name_prefix="dut-xfer"
    )
    # drain workers run fetch → scatter → serialize → deflate → shard
    # write per chunk, off the main loop; back-pressure stays the
    # existing max_inflight window (the main loop blocks on the OLDEST
    # outstanding chunk), so peak memory is still O(inflight chunks)
    drain = ThreadPoolExecutor(
        max_workers=drain_workers, thread_name_prefix="dut-drain"
    )
    phase_lock = threading.Lock()
    # set when the run is going down (error or Ctrl-C): surviving drain
    # workers must stop their retry/isolation ladders instead of
    # grinding through minutes of backoff the shutdown then waits on
    aborting = threading.Event()

    # per-phase BUSY-time breakdown (VERDICT r2 item 2). Since the
    # pipelined drain, phases overlap each other and the main loop, so
    # these are per-stage busy seconds accrued on whichever thread runs
    # the stage — they no longer sum to the wall. The honest wall-side
    # views are "main_loop_stall" (time the main loop spent blocked on
    # the drain back-pressure window) and "drain_utilization"
    # (drain busy seconds / (drain_workers * wall)), emitted alongside.
    # Every += below is paired with a trace span carrying the SAME
    # (t0, dt), so a capture's per-stage sums reproduce these totals
    # exactly (the trace_report sum-check).
    phase = {
        "ingest": 0.0, "bucketing": 0.0, "dispatch": 0.0,
        "mesh_h2d": 0.0,
        "device_wait_fetch": 0.0, "scatter": 0.0, "deflate": 0.0,
        "shard_write": 0.0, "ckpt": 0.0, "finalise": 0.0,
        "main_loop_stall": 0.0, "prefetch_stall": 0.0,
        "ingest_stall": 0.0, "ingest_backpressure": 0.0,
        "live_poll": 0.0, "live_wait": 0.0,
    }
    # byte-ledger running totals (telemetry/ledger.py), maintained only
    # while tracing: every `led[...] +=` below pairs with a tr.xfer()
    # record carrying the SAME increment, so the capture's per-record
    # sums reproduce these totals exactly — the wirestat byte sum-check
    # (integer equality, the byte analogue of the span sum-check).
    # Guarded by phase_lock wherever workers touch it.
    led = {
        "h2d_logical": 0, "h2d_wire": 0, "d2h_logical": 0, "d2h_wire": 0,
        "shard_logical": 0, "shard_wire": 0, "output_overhead_bytes": 0,
    }
    # device-ledger side table (telemetry/devledger.py), maintained
    # only while tracing: dispatch() accrues one entry per
    # (chunk, dispatch class) — dispatch busy seconds, analytic FLOPs,
    # wire bytes, padded bucket count; retries and bucket-isolation
    # re-dispatches fold into the SAME entry, exactly like the byte
    # ledger counts a re-transfer each time it crosses the wire. The
    # drain worker pops the entry once the class's device results are
    # materialised and emits ONE ``dev`` record carrying the chunk's
    # device_wait_fetch window, so a capture's dev-record sums
    # reproduce phase["device_wait_fetch"] and phase["dispatch"]
    # exactly — the devstat time sum-check, the device twin of the
    # wirestat byte sum-check. Guarded by phase_lock like ``led``.
    dev_pending: dict = {}
    # per-class compile ledger: classes whose FIRST pipeline call
    # (trace + XLA compile + first dispatch, synchronous under jit) has
    # been timed into a jit_compile event. Guarded by phase_lock.
    dev_compiled: set = set()

    # packed consensus-only return path (runtime/executor packed-D2H
    # rung): one run-level decision — the per-chunk epilogue bound
    # (d2h_k_pad) is per class, but the gate (u16 lanes, per-base
    # tags) is a pure function of run params, so a downgrade is
    # ledgered ONCE here, not per chunk
    d2h_on = (
        packed != "off" and d2h_packed != "off"
        and d2h_pack_ok(capacity, per_base_tags)
    )
    # the ids-lane u16 rung wants to fire whenever the return path is
    # not explicitly off — it covers exactly the classes the FULL
    # compaction rung cannot (per-base-tag runs, u16-overflowing
    # capacities re-checked per class); "off" keeps the honest
    # fully-unpacked A/B baseline on both knobs
    ids16_want = packed != "off" and d2h_packed != "off"
    if (
        packed != "off" and d2h_packed != "off"
        and not d2h_pack_ok(capacity, per_base_tags)
    ):
        telemetry.emit_event(
            "packed_fallback", scope="d2h",
            reason=(
                "per-base-tags-fetch-full-matrices" if per_base_tags
                else "ids-overflow-u16"
            ),
            capacity=capacity,
        )
    from duplexumiconsensusreads_tpu.parallel.sharded import stacked_nbytes

    # bounded H2D prefetch window: the main loop takes one permit per
    # dispatched chunk BEFORE submitting its transfers; the drain
    # worker returns it once the chunk's device results are
    # materialised (finally-backstopped, so a failing chunk can never
    # leak its permit and wedge the loop). Every permit's release site
    # runs unconditionally, so a plain blocking acquire cannot
    # deadlock.
    prefetch_sem = threading.Semaphore(prefetch_depth)
    # run-level qual-alphabet union for the sub-byte rung (see the
    # chunk loop; None = overflowed past every dictionary width,
    # scanning stopped for the rest of the run)
    alpha_seen: set | None = set()

    # the mesh's per-device H2D path needs the device list in data-axis
    # order and the raw array-key set (parallel/sharded.py owns both)
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        _ARRAY_KEYS,
        presharded_pipeline,
    )

    mesh_devs = list(mesh.devices.flat)
    # per-device telemetry lanes exist ONLY on the 1-D multi-device
    # mesh: on the ('data', 'cycle') mesh a data-axis shard spans
    # cycle_shards physical devices, so a dev-N lane would name no
    # real chip — both the h2d and d2h ledger splits key on this
    dev_lanes_on = n_data > 1 and cycle_shards <= 1

    def _mesh_put(stacked, buckets, bucket_rows, chunk):
        """Per-device H2D of one dispatch on a multi-device 1-D mesh:
        slice the stacked arrays into the mesh's contiguous per-device
        bucket blocks and device_put each block on its own device
        (timed per device — the "mesh_h2d" spans on dev-N lanes).
        Value-identical to shard_stacked's one NamedSharding
        device_put; what it adds is per-device attribution — wire
        bytes, fill rows and mesh-pad buckets per device — so
        wirestat/trace_report can say WHICH device's share of the
        tunnel a slow chunk paid. ``bucket_rows`` is the caller's
        one-pass per-bucket valid-read counts (recomputing the masks
        here would rescan every bucket on the hot xfer path). Returns
        (per-key device buffers, per-device ledger stats); the caller
        assembles the global arrays inside its own timed window."""
        n_stacked = int(stacked["pos"].shape[0])
        per = n_stacked // n_data
        cap = buckets[0].capacity
        bufs: dict[str, list] = {k: [] for k in _ARRAY_KEYS}
        stats = []
        for di, dev in enumerate(mesh_devs):
            td = time.monotonic()
            wire_d = 0
            for key in _ARRAY_KEYS:
                sl = stacked[key][di * per : (di + 1) * per]
                bufs[key].append(jax.device_put(sl, dev))
                wire_d += sl.nbytes
            dtd = time.monotonic() - td
            with phase_lock:
                phase["mesh_h2d"] += dtd
            if tr is not None:
                tr.span("mesh_h2d", td, dtd, chunk=chunk, lane=f"dev-{di}")
            sub_rows = bucket_rows[di * per : (di + 1) * per]
            stats.append({
                "t0": td, "dt": dtd, "wire": wire_d,
                "rows_real": sum(sub_rows),
                "rows_pad": per * cap,
                "mesh_pad": per - len(sub_rows),
            })
        return bufs, stats

    def dispatch(buckets, spec, chunk=None):
        t0 = time.monotonic()
        # runs on a transfer worker; a fault here surfaces through the
        # submit future into materialize's retry/isolation ladder
        fault_point("dispatch.device_put")
        stacked = stack_buckets(buckets, multiple_of=n_data)
        logical = 0
        if tr is not None:
            # byte ledger: the PRE-packing payload of the arrays that
            # actually cross the wire — against the wire bytes below it
            # measures what packing bought this chunk (host-only
            # bookkeeping like read_index is excluded on both sides)
            logical = stacked_nbytes(stacked)
        # chaos site: the host-side wire-packing step (the pack step
        # runs — and can fail — whichever rung is active)
        fault_point("dispatch.pack")
        if spec.packed_io:
            # sub-byte (qual-dictionary bit-planes) or byte (base|qual)
            # rung, decided per class at partition time: the
            # host->device transfer is the dominant streaming phase on
            # a tunneled chip (see the per-phase breakdown)
            pack_stacked(stacked, spec)
        h2d = stacked_nbytes(stacked)
        # padding observability: real read rows vs padded row-slots of
        # this class's dispatch (mesh-pad empties included — they ride
        # the wire and the GEMM alike); retried dispatches re-count,
        # exactly like the byte ledger counts wire traffic
        n_stacked = int(stacked["pos"].shape[0])
        rows_pad = n_stacked * buckets[0].capacity
        # ONE pass over the valid masks: the per-device stats and the
        # dispatch totals both read these counts
        bucket_rows = [int(bk.valid.sum()) for bk in buckets]
        rows_real = sum(bucket_rows)
        mesh_pad = n_stacked - len(buckets)
        # device ledger: executed analytic FLOPs of this dispatch —
        # per-bucket cost (ops/pipeline.py's SSC_METHOD_COSTS registry)
        # x padded bucket count (mesh-pad buckets ride the GEMM like
        # they ride the wire, so they are in the FLOPs). Accrued on the
        # report unconditionally (the serving layer's per-job MFU needs
        # it without a capture) and into the dev side table while
        # tracing; a retried dispatch re-counts, exactly like the byte
        # ledger counts a re-transfer.
        l_cyc = int(buckets[0].bases.shape[1])
        flops_d = analytic_flops(
            spec, buckets[0].capacity, l_cyc,
            int(buckets[0].umi.shape[1]),
        ) * n_stacked
        # per-class compile ledger: claim first-call status under the
        # lock BEFORE the pipeline call (concurrent xfer workers may
        # race the same fresh class; exactly one times it)
        first_call = False
        if tr is not None:
            with phase_lock:
                first_call = spec not in dev_compiled
                if first_call:
                    dev_compiled.add(spec)
        # multi-device 1-D mesh: the per-device put path (value-
        # identical, per-device-attributed). The 2-D (data, cycle)
        # mesh shards bases/quals along cycles too, so its transfers
        # stay on shard_stacked's one NamedSharding put — and its
        # ledger records stay unlaned (a data-axis "shard" there spans
        # several physical devices, so dev-N lanes would lie).
        dev_stats = None
        if dev_lanes_on:
            t_pre = time.monotonic() - t0  # stack + pack, "dispatch"
            bufs, dev_stats = _mesh_put(
                stacked, buckets, bucket_rows, chunk
            )
            t0b = time.monotonic()
            from jax.sharding import NamedSharding, PartitionSpec as P

            sh = NamedSharding(mesh, P("data"))
            args = {
                key: jax.make_array_from_single_device_arrays(
                    stacked[key].shape, sh, bufs[key]
                )
                for key in _ARRAY_KEYS
            }
            t_pipe = time.monotonic()
            out = presharded_pipeline(args, spec, mesh)
        else:
            t_pre, t0b = None, t0
            t_pipe = time.monotonic()
            out = sharded_pipeline(stacked, spec, mesh)
        if tr is not None and first_call:
            # under jit the first call of a fresh class traces + XLA-
            # compiles synchronously before its (async) dispatch
            # returns, so the first-call seconds ARE the class's
            # compile cost to within one dispatch enqueue — the
            # per-class jit-cache ledger devstat totals
            tr.event(
                "jit_compile", chunk=chunk,
                compile_s=round(time.monotonic() - t_pipe, 6),
                cap=int(buckets[0].capacity), cycles=l_cyc,
                method=spec.ssc_method,
            )
        # the run-level d2h decision re-checked against the CLASS
        # capacity (one pure helper — executor.d2h_rung_for_class — so
        # the gate matrix is unit-tested without a device): jumbo
        # buckets carry a next-pow2 capacity up to 64x the run's
        # (bucketing/buckets.py), and the packed layouts' u16 lanes are
        # only lossless below 2**16 rows
        rung, fallback = d2h_rung_for_class(
            d2h_on, ids16_want, buckets[0].capacity, per_base_tags
        )
        if fallback is not None:
            # same ledgered-downgrade discipline as every other rung
            telemetry.emit_event(
                "packed_fallback", scope="d2h",
                reason=fallback, capacity=buckets[0].capacity,
            )
        if rung == "packed":
            # packed consensus-only return path: compact + pack the
            # output rows ON DEVICE before any copy starts (still at
            # dispatch time, so the async overlap is intact), then
            # start the d2h copies of the compact set. The compaction
            # runs PER MESH SHARD (n_data) — a cross-shard compaction
            # compiles to collectives that deadlock concurrent
            # dispatches (see the executor's packed-D2H comment)
            out = start_fetch(
                pack_fetch_outputs(
                    out, spec, d2h_k_pad(buckets, spec, n_data),
                    n_data, mesh=mesh,
                ),
                keys=PACKED_FETCH_KEYS,
            )
        elif rung == "ids16":
            # ids-lane u16 rung: the full compaction is gated off for
            # this class (per-base tags), but the scatter still
            # consumes only ONE id array and biased dense ids fit u16 —
            # fetch that one, u16, instead of both i32 arrays
            out = start_fetch(
                pack_ids_u16(out, duplex),
                keys=IDS16_FETCH_KEYS,
                extra=("cons_depth", "cons_err") if per_base_tags else (),
            )
        else:
            # start the device->host copies of the consumed keys right
            # at dispatch: by drain time the results are already on the
            # host, so the tunnel's per-fetch latency overlaps compute
            out = start_fetch(
                out,
                extra=("cons_depth", "cons_err") if per_base_tags else (),
            )
        dt_post = time.monotonic() - t0b
        # dispatch busy time excludes the per-device put loop: the
        # "mesh_h2d" stage owns it. Each stage's spans carry exactly
        # the dt its phase accumulator receives (the sum-check
        # contract); the stats/emission slivers between the windows
        # are deliberately unattributed rather than misattributed.
        disp_dt = dt_post if t_pre is None else t_pre + dt_post
        with phase_lock:  # dict += from concurrent workers would race
            phase["dispatch"] += disp_dt
            rep.bytes_h2d += h2d
            rep.device_flops += flops_d
            rep.n_rows_real += rows_real
            rep.n_rows_padded += rows_pad
            rep.n_mesh_pad_buckets += mesh_pad
            if tr is not None:
                led["h2d_logical"] += logical
                led["h2d_wire"] += h2d
                # dev side table: fold this dispatch into its
                # (chunk, class) entry — the drain worker pops it into
                # ONE dev record once the class's results materialise
                ent = dev_pending.setdefault((chunk, spec), {
                    "cap": int(buckets[0].capacity), "cycles": l_cyc,
                    "method": spec.ssc_method, "buckets": 0,
                    "flops": 0.0, "h2d_wire": 0, "disp_s": 0.0,
                })
                ent["buckets"] += n_stacked
                ent["flops"] += flops_d
                ent["h2d_wire"] += h2d
                ent["disp_s"] += disp_dt
        if tr is not None:
            if t_pre is None:
                tr.span(
                    "dispatch", t0, disp_dt, chunk=chunk,
                    n_buckets=len(buckets),
                )
            else:
                # mesh path: the stack/pack prologue and the pipeline
                # epilogue are separate dispatch spans bracketing the
                # per-device mesh_h2d spans emitted between them
                tr.span(
                    "dispatch", t0, t_pre, chunk=chunk,
                    n_buckets=len(buckets),
                )
                tr.span("dispatch", t0b, dt_post, chunk=chunk)
            # retried dispatches emit again on purpose: the ledger
            # counts wire traffic, and a retry really crossed the wire.
            # bpc = wire bits per base/qual cycle of this class's rung
            # (16 unpacked, 8 byte, 7/5 sub-byte) — the per-chunk
            # packing decision, recorded in the ledger
            bpc = (
                2 + spec.packed_qbits if spec.packed_qbits
                else 8 if spec.packed_io else 16
            )
            # rows_real/rows_pad + the class capacity: the per-rung
            # fill-factor audit trail (wirestat's fill column and the
            # tuner acceptance both read these). mesh_pad: the mesh-
            # alignment pad buckets this dispatch shipped — summed per
            # device on the mesh path, where each record carries ITS
            # device's slice on the dev-N lane, logical split exactly
            # (every stacked array's bucket axis divides by n_data).
            if dev_stats is not None:
                log_d = logical // n_data
                for di, st in enumerate(dev_stats):
                    tr.xfer(
                        "h2d",
                        logical - log_d * (n_data - 1)
                        if di == n_data - 1 else log_d,
                        st["wire"], st["t0"], st["dt"], chunk=chunk,
                        lane=f"dev-{di}", bpc=bpc,
                        rows_real=st["rows_real"],
                        rows_pad=st["rows_pad"],
                        cap=buckets[0].capacity,
                        mesh_pad=st["mesh_pad"],
                    )
            else:
                tr.xfer(
                    "h2d", logical, h2d, t0, disp_dt, chunk=chunk,
                    bpc=bpc, rows_real=rows_real, rows_pad=rows_pad,
                    cap=buckets[0].capacity, mesh_pad=mesh_pad,
                )
        return out

    def unpack(raw, cbuckets, cspec):
        """Host-side unpack of one fetched dict: reconstruct the exact
        unpacked FETCH_KEYS arrays from a packed-D2H fetch (identity
        when the rung is off). Returns (full dict, wire bytes moved,
        logical bytes the unpacked fetch would have moved). Chaos site
        fetch.unpack rides the bounded host-I/O ladder — the unpack is
        pure compute, so a retry is trivially idempotent."""
        wire = sum(v.nbytes for v in raw.values() if hasattr(v, "nbytes"))
        full = _io_retry(
            "fetch.unpack",
            lambda: unpack_fetch_outputs(
                raw, cbuckets, cspec, n_shards=n_data
            ),
            "packed d2h unpack",
        )
        return full, wire, d2h_logical_nbytes(raw, cbuckets, cspec)

    def materialize(out, cbuckets, cspec, k):
        """Device results -> host arrays, with failure recovery:
        bounded exponential-backoff class retries, then bucket-by-bucket
        re-dispatch to isolate a poisoned bucket. Returns
        (outputs, wire_bytes, logical_bytes) — the d2h ledger pair of
        the fetch that finally succeeded."""
        err: Exception | None = None
        if out is not None and hasattr(out, "result"):
            try:
                out = out.result()  # transfer-thread future
            except Exception as e:
                out, err = None, e
        if out is None:
            err = err or RuntimeError("device dispatch failed at submit")
        else:
            try:
                return unpack(fetch_outputs(out), cbuckets, cspec)
            except D2hCompactionOverflow:
                raise  # deterministic invariant violation: no retry
            except Exception as e:
                err = e
        for attempt in range(max_retries):
            if aborting.is_set():
                raise err
            with phase_lock:  # drain workers retry concurrently
                rep.n_retries += 1
            delay = min(0.5 * (2 ** attempt), 8.0)
            if tr is not None:
                tr.event(
                    "retry", chunk=k, site="device.execute",
                    attempt=attempt + 1, max_attempts=max_retries,
                    backoff_s=round(delay, 3), error=repr(err)[:200],
                )
            print(
                f"[duplexumi] chunk {k} device execution failed ({err!r}); "
                f"retry {attempt + 1}/{max_retries} in {delay:.1f}s",
                file=sys.stderr,
            )
            time.sleep(delay)
            try:
                return unpack(
                    fetch_outputs(dispatch(cbuckets, cspec, chunk=k)),
                    cbuckets, cspec,
                )
            except D2hCompactionOverflow:
                raise
            except Exception as e:
                err = e
        # class keeps failing: isolate per bucket so one bad bucket
        # cannot take down the chunk
        if tr is not None:
            tr.event("bucket_isolation", chunk=k, n_buckets=len(cbuckets))
        print(
            f"[duplexumi] chunk {k}: class retries exhausted; "
            f"re-dispatching {len(cbuckets)} buckets individually",
            file=sys.stderr,
        )
        rows: dict[str, list] = {}
        wire_total = logical_total = 0
        for bi, bk in enumerate(cbuckets):
            last = None
            for attempt in range(max_retries):
                if aborting.is_set():
                    raise RuntimeError(
                        f"chunk {k} bucket {bi}: run aborting"
                    ) from (last or err)
                try:
                    raw = fetch_outputs(dispatch([bk], cspec, chunk=k))
                    single, w1, l1 = unpack(raw, [bk], cspec)
                    single = {key: np.asarray(v)[0] for key, v in single.items()}
                    break
                except D2hCompactionOverflow:
                    raise
                except Exception as e:
                    last = e
                    with phase_lock:
                        rep.n_retries += 1
                    if tr is not None:
                        tr.event(
                            "retry", chunk=k, site="device.execute",
                            attempt=attempt + 1, max_attempts=max_retries,
                            backoff_s=round(min(0.5 * (2 ** attempt), 8.0), 3),
                            bucket=bi, error=repr(e)[:200],
                        )
                    time.sleep(min(0.5 * (2 ** attempt), 8.0))
            else:
                raise RuntimeError(
                    f"chunk {k} bucket {bi} failed {max_retries} "
                    f"re-dispatches; giving up"
                ) from last
            wire_total += w1
            logical_total += l1
            for key, v in single.items():
                rows.setdefault(key, []).append(v)
        return (
            {key: np.stack(v) for key, v in rows.items()},
            wire_total, logical_total,
        )

    def drain_chunk(k, entries, batch):
        """Consumer side of the pipeline for ONE chunk, on a drain
        worker: materialize device outputs, scatter back to batch
        coordinates, serialize + deflate + durably write the shard.
        Returns the commit payload; committing (checkpoint mark,
        incremental finalise append) stays on the MAIN thread so marks
        and appends land in chunk order whatever order workers finish
        in. A fault/kill raised here surfaces through the future into
        the main loop unchanged. Releases the chunk's H2D prefetch
        permit once every entry's device results are materialised
        (finally-backstopped: a failing chunk must not wedge the main
        loop's prefetch window)."""
        released = [False]

        def release_prefetch():
            if not released[0]:
                released[0] = True
                prefetch_sem.release()

        def on_stage(stage, t0, dt):
            # _finish_chunk's accounting callback: one pair of phase +=
            # and span per sub-stage (deflate vs serialize/write), so
            # the drain worker's shard work decomposes in the capture
            with phase_lock:
                phase[stage] += dt
            if tr is not None:
                tr.span(stage, t0, dt, chunk=k)

        try:
            return _drain_chunk_body(
                k, entries, batch, on_stage, release_prefetch
            )
        finally:
            release_prefetch()

    def _drain_chunk_body(k, entries, batch, on_stage, release_prefetch):
        parts = []
        pair_base = 0
        for i, (out, cbuckets, cspec) in enumerate(entries):
            t0 = time.monotonic()
            out, d2h_wire, d2h_logical = materialize(out, cbuckets, cspec, k)
            if i == len(entries) - 1:
                # every class's device work for this chunk is done:
                # open the prefetch window before the (host-heavy)
                # scatter/serialize tail
                release_prefetch()
            dt = time.monotonic() - t0
            with phase_lock:
                phase["device_wait_fetch"] += dt
                rep.device_seconds += dt
                rep.bytes_d2h += d2h_wire
                rep.n_families += int(out["n_families"].sum())
                rep.n_molecules += int(out["n_molecules"].sum())
                if tr is not None:
                    led["d2h_wire"] += d2h_wire
                    led["d2h_logical"] += d2h_logical
                    # device ledger: this class's dispatch-side
                    # accumulator, complete now that materialize (and
                    # every retry it ran) has returned
                    dent = dev_pending.pop((k, cspec), None)
            if tr is not None:
                tr.span("device_wait_fetch", t0, dt, chunk=k)
                if dent is not None:
                    # one dev record per (chunk, dispatch class): the
                    # SAME (t0, dt) window as the span above, so a
                    # capture's dev durs sum to the device_wait_fetch
                    # phase and its disp_s to the dispatch phase — the
                    # devstat sum-check contract
                    tr.dev(
                        t0, dt, chunk=k,
                        cap=dent["cap"], cycles=dent["cycles"],
                        buckets=dent["buckets"], method=dent["method"],
                        flops=round(dent["flops"], 3),
                        h2d_wire=dent["h2d_wire"], d2h_wire=d2h_wire,
                        disp_s=round(dent["disp_s"], 6),
                    )
                # the packed return path: wire is what the compact
                # consensus-only fetch moved, logical what the full
                # padded FETCH_KEYS arrays would have — the d2h
                # logical-vs-wire gap the ROADMAP's wire item asked the
                # ledger to close (equal when the rung is off). On a
                # multi-device mesh the fetch splits into one record
                # per device lane: every fetched array's leading axis
                # is bucket- (or per-shard-row-) aligned, so the byte
                # split is exact; the (t0, dt) window is shared — the
                # async copies all land inside this one wait.
                if (
                    dev_lanes_on
                    and d2h_wire % n_data == 0
                    and d2h_logical % n_data == 0
                ):
                    for di in range(n_data):
                        tr.xfer(
                            "d2h", d2h_logical // n_data,
                            d2h_wire // n_data, t0, dt, chunk=k,
                            lane=f"dev-{di}",
                        )
                else:
                    tr.xfer("d2h", d2h_logical, d2h_wire, t0, dt, chunk=k)
            t0 = time.monotonic()
            # chaos site drain.scatter rides the same bounded-retry
            # ladder as the host I/O steps (scatter is pure compute, so
            # a retry is trivially idempotent)
            parts.append(
                _io_retry(
                    "drain.scatter",
                    lambda: scatter_bucket_outputs(
                        out, cbuckets, batch, duplex, pair_base=pair_base,
                        want_depth=per_base_tags,
                    ),
                    f"chunk {k} scatter",
                )
            )
            dt = time.monotonic() - t0
            with phase_lock:
                phase["scatter"] += dt
            if tr is not None:
                tr.span("scatter", t0, dt, chunk=k)
            pair_base += len(cbuckets)
        on_xfer = None
        if tr is not None:

            def on_xfer(logical, wire, t0, dt):
                # shard ledger record: raw record-stream bytes vs the
                # deflated bytes that hit disk (and, verbatim, the
                # finalise append) — the (t0, dt) pair is the deflate
                # span's, so the record sits on the drain lane beside it
                with phase_lock:
                    led["shard_logical"] += logical
                    led["shard_wire"] += wire
                tr.xfer("shard", logical, wire, t0, dt, chunk=k)

        res = _finish_chunk(
            k, parts, duplex, shard_dir, serialize_bam, header_out, name_tag,
            paired_out=grouping.mate_aware, read_group=read_group,
            on_stage=on_stage, on_xfer=on_xfer,
        )
        return res + (False,)  # marked=False: commit still owes the mark

    # ---- ordered-completion frontier: chunk k is committed (checkpoint
    # mark + incremental finalise append) only when every chunk < k is
    # already durable — PR 1's resume/CRC contract is phrased over a
    # prefix of chunks, and out-of-order marks would let --resume splice
    # around a hole. fin holds the incremental out+".tmp" assembly; the
    # durable publish (fsync+rename) still happens exactly once, at the
    # end. done_q buffers payloads of chunks that finished early
    # (bounded: <= max_inflight entries, each a compressed shard).
    done_q: dict[int, tuple] = {}
    fin: dict = {"f": None}
    frontier = chunk_base  # chunk indices live on the (possibly
    # shard-offset) global grid; the frontier starts at this run's first
    tmp_path = out_path + ".tmp"

    def _fin_open():
        # first commit: create the tmp and write the derived header.
        # Opened lazily because read_group/header_out resolve on the
        # first chunk.
        from duplexumiconsensusreads_tpu.io.bam import derive_output_header

        # chunks sort by (pos, UMI) and chunk boundaries are
        # genomic-order (coordinate-sorted input contract), so the
        # concatenation is coordinate-sorted end to end — say so,
        # chain @PG, add the @RG
        hdr = derive_output_header(
            header_out, sort_order="coordinate", rg_id=read_group,
            cl=provenance_cl,
        )
        shell_c = bgzf.compress_fast(
            serialize_bam(hdr, _empty_records()), eof=False
        )
        f = open(tmp_path, "wb")
        try:
            _io_retry(
                "finalise.write",
                lambda: rewrite_from(f, 0, shell_c),
                "finalise header",
            )
        except BaseException:
            # fin["f"] is only set on success, so the outer cleanup
            # would never see (and close) this handle
            try:
                f.close()
            except OSError:
                pass
            raise
        fin["f"] = f
        if tr is not None:
            # everything in the output that is NOT a ledgered shard:
            # the compressed header shell now, the EOF block at
            # publish — so output_bytes == overhead + shard wire is an
            # EXACT identity, not a tolerance
            with phase_lock:
                led["output_overhead_bytes"] += len(shell_c)

    snap_path = out_path + ".snapshot.bam"

    def _publish_snapshot(k):
        """Indexed partial snapshot at a checkpoint mark: the committed
        tmp assembly so far — a VALID BAM prefix of the final output
        (header shell + committed shards + EOF block) — published
        atomically at ``out + ".snapshot.bam"`` with its own index.
        Main-thread only, straight after chunk k's durable commit, so
        every snapshot is exactly a committed-chunk prefix; a side
        artifact by contract — the final output bytes never depend on
        whether (or how often) snapshots were taken."""
        from duplexumiconsensusreads_tpu.io.durable import unique_tmp

        f = fin["f"]
        end = f.tell()
        t0 = time.monotonic()

        def _snap():
            f.flush()
            stage = unique_tmp(snap_path)
            done = False
            try:
                with open(tmp_path, "rb") as src, open(stage, "wb") as dst:
                    left = end
                    while left > 0:
                        block = src.read(min(4 << 20, left))
                        if not block:
                            raise ValueError(
                                f"{tmp_path}: truncated under the "
                                f"snapshot copy"
                            )
                        dst.write(block)
                        left -= len(block)
                    dst.write(bgzf.BGZF_EOF)
                    fsync_file(dst)
                replace_durable(stage, snap_path)
                done = True
            finally:
                if not done:
                    try:
                        os.remove(stage)
                    except OSError:
                        pass
            # the unsharded finalise's index choice, over the prefix
            if max(header_out.ref_lengths, default=0) > (1 << 29):
                from duplexumiconsensusreads_tpu.io.csi import build_csi

                build_csi(snap_path)
            else:
                from duplexumiconsensusreads_tpu.io.bai import build_bai

                build_bai(snap_path)

        _io_retry("live.snapshot", _snap, "snapshot publish")
        rep.snapshot_seq += 1
        if live_mark is not None:
            # persist the series position so a resumed follower
            # continues it (main thread: the tailer role holds no
            # durable grant)
            from duplexumiconsensusreads_tpu.live import watermark as _wm

            live_mark["snapshot_seq"] = rep.snapshot_seq
            if live_src is not None:
                live_mark["admitted_bytes"] = live_src.admitted_bytes()
            _io_retry(
                "live.snapshot", _wm.save, "watermark save",
                out_path, live_mark,
            )
        dt = time.monotonic() - t0
        phase["finalise"] += dt
        if tr is not None:
            tr.span("finalise", t0, dt, chunk=k)
            tr.event(
                "snapshot_published", chunk=k,
                snapshot_seq=rep.snapshot_seq,
                chunks_done=k + 1 - chunk_base,
                reads=rep.n_consensus,
            )

    def _commit(k, payload):
        """Main-thread commit of a drained chunk: durable mark first,
        then the idempotent append into the tmp assembly. The mark is
        its own phase ("ckpt") since PR 3: on shared pod storage the
        per-chunk manifest fsync is a real cost that used to hide
        inside "finalise"."""
        if commit_guard is not None:
            # fleet fence: the serving layer verifies its lease is
            # still the job's current one BEFORE this chunk becomes
            # durable — resumed (marked=True) chunks included, since
            # their finalise append splices bytes all the same
            commit_guard(k)
        shard, size, crc, n_rec, n_pairs, codec, data, marked = payload
        shards[k] = shard
        if ckpt and not marked:
            t0 = time.monotonic()
            ckpt.mark(k, shard, size, crc, n_rec, n_pairs, codec)
            dt = time.monotonic() - t0
            phase["ckpt"] += dt
            if tr is not None:
                tr.span("ckpt", t0, dt, chunk=k)
        t0 = time.monotonic()
        if fin["f"] is None:
            _fin_open()
        if data is None:
            # resume-skipped chunk: the shard bytes live only on disk
            def _read():
                with open(shard, "rb") as s:
                    return s.read()

            data = _io_retry("finalise.write", _read, f"shard {k} read")
        if data:
            f = fin["f"]
            off = f.tell()
            # rewrite_from makes the bounded retry idempotent: a torn
            # append is truncated away and rewritten from `off`
            _io_retry(
                "finalise.write",
                lambda: rewrite_from(f, off, data),
                "finalise append",
            )
        rep.n_consensus += n_rec
        rep.n_consensus_pairs += n_pairs
        dt = time.monotonic() - t0
        phase["finalise"] += dt
        if tr is not None:
            tr.span("finalise", t0, dt, chunk=k)
        if snapshot_chunks and (k + 1 - chunk_base) % snapshot_chunks == 0:
            _publish_snapshot(k)
        if progress:
            progress(k, rep)

    def _advance_frontier():
        nonlocal frontier
        while frontier in done_q:
            _commit(frontier, done_q.pop(frontier))
            frontier += 1

    def _wait_oldest():
        """Back-pressure: block on the OLDEST outstanding chunk (the
        only one the frontier can need next). Worker exceptions —
        including InjectedKill, a BaseException — re-raise here."""
        k, fut = inflight.popleft()
        t0 = time.monotonic()
        res = fut.result()
        dt = time.monotonic() - t0
        phase["main_loop_stall"] += dt
        if tr is not None:
            # the back-pressure record: main blocked this long waiting
            # for chunk k's drain — the span IS the stall event
            tr.span("main_loop_stall", t0, dt, chunk=k)
        done_q[k] = res
        _advance_frontier()

    def _prep_chunk(k, batch):
        """Per-chunk host prep: family downsample → (one-shot) ladder
        resolution → build_buckets → qual-alphabet union. ONE shared
        implementation for the forced-sync path (runs inline on the
        main thread, today's exact order) and the overlap producer
        (runs on the dut-ingest thread, ahead of the main loop). Either
        way there is exactly ONE caller at a time processing chunks in
        chunk order, so the run_ladder / alpha_seen mutations stay
        sequential and the decisions — and therefore the output
        bytes — are identical across modes."""
        nonlocal run_ladder, ladder_auto, alpha_seen
        n_down = 0
        if max_reads > 0:
            n_down = downsample_families(batch, max_reads)
        fb: dict = {}
        t0 = time.monotonic()
        if ladder_auto:
            # profile pass (host-only, once per run): the first
            # non-empty chunk's position-group size sequence feeds
            # the tuner's padded-cycles cost model; the verdict is
            # pinned for the whole run so compile classes stay
            # stable, and it is LEDGERED so any capture can audit
            # the shape decision
            sizes = tuning.group_sizes(batch)
            if len(sizes):
                verdict = tuning.choose_ladder(
                    sizes, capacity, pack_mult=n_data
                )
                run_ladder = (
                    verdict.ladder if len(verdict.ladder) > 1 else None
                )
                ladder_auto = False
                rep.bucket_ladder = [int(r) for r in verdict.ladder]
                if tr is not None:
                    tr.event(
                        "tuner_verdict", chunk=k,
                        ladder=list(verdict.ladder),
                        fill_factor=verdict.fill_factor,
                        fill_factor_off=verdict.fill_factor_off,
                        predicted_speedup=verdict.predicted_speedup,
                        n_groups=verdict.n_groups,
                        source=verdict.source,
                    )
        buckets = build_buckets(
            batch, capacity=capacity, grouping=grouping, counters=fb,
            ladder=run_ladder,
        )
        # the run's real-cycle qual alphabet feeds the sub-byte
        # rung decision: one scan per chunk, accumulated into a
        # MONOTONE-GROWING run-level union so a rare qual bin
        # absent from some chunks cannot flip the lut back and
        # forth and recompile the pipeline per chunk — the lut only
        # ever grows (bounded by the dictionary capacity, after
        # which the class falls back to the byte rung). A superset
        # lut stays exact for every chunk: searchsorted is an exact
        # index for any member. ("byte" caps the ladder.)
        alpha = None
        if packed == "auto" and buckets and alpha_seen is not None:
            alpha_seen.update(qual_alphabet(buckets))
            if len(alpha_seen) > _ALPHA_CAP:
                # every dictionary width has overflowed for good
                # (the union only grows): stop paying the per-chunk
                # scan — the byte rung owns the rest of the run
                alpha_seen = None
            else:
                alpha = tuple(sorted(alpha_seen))
        dt = time.monotonic() - t0
        with phase_lock:
            phase["bucketing"] += dt
        if tr is not None:
            tr.span("bucketing", t0, dt, chunk=k, n_buckets=len(buckets))
        return buckets, alpha, fb, n_down

    def _drain_live(chunk=None):
        # follow mode: pull the tailer's idle-poll time and the
        # reader's blocked-on-tailer time into the phase ledger at
        # chunk boundaries. Pull-based on purpose — the dut-live-tail
        # role's shared set is empty, so the tailing thread never
        # touches this module's state; whichever thread runs ingest
        # (main when sync, dut-ingest when overlapped) does the accrual
        # under the declared lock
        if live_src is None:
            return
        poll_s, wait_s = live_src.take_phase_seconds()
        now = time.monotonic()
        for stage, dt in (("live_poll", poll_s), ("live_wait", wait_s)):
            if dt <= 0:
                continue
            with phase_lock:
                phase[stage] += dt
            if tr is not None:
                tr.span(stage, now - dt, dt, chunk=chunk)

    def timed_chunks(it):
        i = chunk_base
        while True:
            t0 = time.monotonic()
            item = next(it, None)
            dt = time.monotonic() - t0
            phase["ingest"] += dt
            if tr is not None:
                # the final (None-returning) read keeps its span too —
                # chunkless, so the per-stage sums still match phase
                tr.span("ingest", t0, dt, chunk=i if item is not None else None)
            _drain_live(chunk=i if item is not None else None)
            if item is None:
                return
            i += 1
            yield item

    # live liveness line: a long run must be observable without waiting
    # for the report (and without a trace file to post-process). Started
    # here so the stats closure reads fully-initialised loop state; the
    # caller (stream_call_consensus) owns teardown via hb_box.
    if heartbeat_s and heartbeat_s > 0:

        def _hb_stats():
            elapsed = max(time.monotonic() - t_start, 1e-9)
            with phase_lock:
                stall = phase["main_loop_stall"]
                drain_busy = sum(phase[k] for k in DRAIN_PHASES)
                retries = rep.n_retries
                snap_seq = rep.snapshot_seq
            return {
                "elapsed_s": round(elapsed, 1),
                "chunks_done": frontier - chunk_base,
                "chunks_inflight": len(inflight),
                "stall_frac": round(stall / elapsed, 3),
                "retries": retries,
                "drain_util": round(
                    min(drain_busy / (drain_workers * elapsed), 1.0), 3
                ),
                # follow-mode subscribers (call --wait, serve clients)
                # read snapshot progress off this stream
                "snapshot_seq": snap_seq,
            }

        hb = Heartbeat(heartbeat_s, _hb_stats, recorder=tr).start()
        if hb_box is not None:
            hb_box.append(hb)

    # ---- bounded background producer (--ingest-overlap) ----
    # Overlap mode moves ingest (BGZF read + inflate + chunk parse) AND
    # host prep (_prep_chunk) onto one dedicated "dut-ingest" thread
    # that works ahead of the main loop, so BGZF/decode/bucketing of
    # chunk k+1..k+D overlap device compute of chunk k. The handoff
    # queue is bounded at prefetch_depth: together with the prefetch
    # semaphore (taken by the main loop at dispatch) total in-flight
    # chunks stay bounded by the SAME window — the producer can run at
    # most depth prepped chunks ahead, then blocks (the
    # "ingest_backpressure" span). The producer emits strictly in chunk
    # order, so the consumer sees exactly the sequence the sync path
    # would — which is why output bytes are provably identical across
    # modes. Producer errors (typed OSErrors past the retry ladder,
    # InjectedKill, anything) forward through the queue's error
    # sentinel and re-raise on the main loop, preserving the sync
    # path's exception surface; GIL note: the native inflate, zlib,
    # numpy packing and file reads all release the GIL, so the overlap
    # is real even on CPU-simulated devices.
    ingest_thread: threading.Thread | None = None
    if overlap_on:
        ingest_q: _queue.Queue = _queue.Queue(maxsize=prefetch_depth)
        # resume-skip snapshot: ckpt.done only ever grows with marks
        # for chunks the frontier already committed (all < the chunk
        # the producer is looking at), so this pre-loop snapshot equals
        # the sync path's live per-chunk membership check
        done_set = frozenset(int(s) for s in ckpt.done) if ckpt else frozenset()

        def _q_put(item, chunk):
            # named chaos site on every handoff: transient faults ride
            # the standard bounded-retry ladder ON the producer thread;
            # a kill unwinds into the error sentinel in
            # _ingest_producer and surfaces on the main loop — the
            # exactly-once resume contract the chaos matrix asserts
            _io_retry("ingest.queue", _noop, "ingest queue handoff")
            t0 = time.monotonic()
            while True:
                if aborting.is_set():
                    raise _IngestAbort()
                try:
                    ingest_q.put(item, timeout=0.05)
                    break
                except _queue.Full:
                    continue
            dt = time.monotonic() - t0
            with phase_lock:
                phase["ingest_backpressure"] += dt
            if tr is not None:
                tr.span("ingest_backpressure", t0, dt, chunk=chunk)

        def _ingest_producer():
            try:
                it = iter(chunk_iter)
                k = chunk_base
                while True:
                    t0 = time.monotonic()
                    item = next(it, None)
                    dt = time.monotonic() - t0
                    with phase_lock:
                        phase["ingest"] += dt
                    if tr is not None:
                        # the final (None-returning) read keeps its
                        # span too — chunkless, so the per-stage sums
                        # still match phase (the trace sum-check)
                        tr.span(
                            "ingest", t0, dt,
                            chunk=k if item is not None else None,
                        )
                    _drain_live(chunk=k if item is not None else None)
                    if item is None:
                        _q_put(("done", None), None)
                        return
                    prep = None
                    if k not in done_set:
                        # resume-skipped chunks splice their shard
                        # straight from disk — prepping them would also
                        # disturb the ladder/alphabet resolution order
                        # (the first non-skipped non-empty chunk
                        # decides, same as the sync path)
                        prep = _prep_chunk(k, item[1])
                    _q_put(("item", (k, item, prep)), k)
                    k += 1
            except _IngestAbort:
                pass  # run is going down; the main loop owns the error
            except BaseException as e:
                # forward EVERYTHING (InjectedKill included) to the
                # main loop: producer errors must surface there with
                # the same typed exceptions as the sync path. Bounded
                # best-effort put: either the consumer reads it, or the
                # run is already aborting for another reason.
                while not aborting.is_set():
                    try:
                        ingest_q.put(("err", e), timeout=0.05)
                        break
                    except _queue.Full:
                        continue

        ingest_thread = threading.Thread(
            target=_ingest_producer, name="dut-ingest", daemon=True
        )

        def _overlap_chunks():
            while True:
                t0 = time.monotonic()
                while True:
                    try:
                        kind, payload = ingest_q.get(timeout=0.05)
                        break
                    except _queue.Empty:
                        if not ingest_thread.is_alive() and ingest_q.empty():
                            # crashed without a sentinel (the forward
                            # loop above is total, so this should be
                            # impossible): fail loudly, never spin
                            raise RuntimeError(
                                "ingest producer died without a result"
                            )
                dt = time.monotonic() - t0
                phase["ingest_stall"] += dt
                if tr is not None:
                    tr.span(
                        "ingest_stall", t0, dt,
                        chunk=payload[0] if kind == "item" else None,
                    )
                if kind == "done":
                    return
                if kind == "err":
                    raise payload
                yield payload

        chunk_stream = _overlap_chunks()
    else:

        def _sync_chunks():
            # forced-sync path: today's exact main-loop ingest; prep
            # runs inline in the consumer body (prep=None below)
            for k, item in enumerate(
                timed_chunks(iter(chunk_iter)), start=chunk_base
            ):
                yield k, item, None

        chunk_stream = _sync_chunks()

    n_skipped = 0
    try:
        if ingest_thread is not None:
            ingest_thread.start()
        for k, (header, batch, info), prep in chunk_stream:
            if header_out is None:
                header_out = header
                # collision-free consensus @RG, resolved once from the
                # input header (deterministic, so resumed runs agree)
                from duplexumiconsensusreads_tpu.io.bam import unique_read_group_id

                read_group = unique_read_group_id(header.text, read_group)
            rep.n_chunks += 1
            if ckpt and str(k) in ckpt.done:
                # entries surviving load_or_create passed the size+CRC
                # verification — safe to splice at finalise. The commit
                # still flows through the frontier so appends stay in
                # chunk order relative to in-flight fresh chunks.
                e = ckpt.done[str(k)]
                if tr is not None:
                    tr.event("resume", chunk=k, decision="reused")
                    # reused shard: its bytes splice into the output
                    # without any transfer this run, so the ledger
                    # records them ONCE (wire only — the raw size was
                    # never re-derived) and h2d/d2h stay untouched; a
                    # resumed capture still sum-checks against the
                    # finalised output with no double-counting
                    tr.xfer(
                        "shard", None, e["size"], time.monotonic(), 0.0,
                        chunk=k, resumed=True,
                    )
                    with phase_lock:
                        led["shard_wire"] += e["size"]
                done_q[k] = (
                    e["path"], e["size"], e["crc32"],
                    e["n_records"], e["n_pairs"], e["codec"], None, True,
                )
                n_skipped += 1
                _advance_frontier()
                continue
            if tr is not None and resume:
                # the chunk was NOT served from the manifest under an
                # explicit resume: either never finished or its shard
                # failed size+CRC verification — recomputing now
                tr.event("resume", chunk=k, decision="recomputed")
            # per-read counters cover FRESH work only, so a resumed
            # run's report is internally consistent (n_records matches
            # n_valid_reads + drops); skipped chunks show up in
            # n_chunks_skipped and the final n_consensus instead
            rep.n_records += info["n_records"]
            rep.n_valid_reads += info["n_valid"]
            rep.n_dropped += (
                info["n_dropped_no_umi"]
                + info["n_dropped_umi_len"]
                + info.get("n_dropped_flag", 0)
                + info.get("n_dropped_cigar", 0)
            )
            rep.n_rescued_cigar += info.get("n_rescued_cigar", 0)
            rep.n_dropped_cigar_ab += info.get("n_dropped_cigar_ab", 0)
            rep.n_dropped_cigar_ba += info.get("n_dropped_cigar_ba", 0)
            rep.n_mixed_mate_families += info.get("n_mixed_mate_families", 0)
            if info.get("n_mixed_mate_families") and not grouping.mate_aware:
                # the iterator was created with warn_mixed=False (auto
                # resolution owns the decision); a resolved-off run
                # keeps the loud non-mate-aware contract
                from duplexumiconsensusreads_tpu.io.convert import (
                    MIXED_MATE_WARNING,
                )

                _warnings.warn(MIXED_MATE_WARNING)
            if prep is None:
                # forced-sync mode: host prep runs inline on the main
                # thread — exactly today's order (the overlap producer
                # pre-computed it for every fresh chunk it handed over)
                prep = _prep_chunk(k, batch)
            buckets, alpha, fb, n_down = prep
            rep.n_downsampled_reads += n_down
            for fk, fv in fb.items():
                setattr(rep, fk, getattr(rep, fk) + fv)
            rep.n_buckets += len(buckets)
            if not buckets:
                # empty shard: zero bytes deflate identically under
                # either codec; record the run's flavor so resume
                # verification accepts it
                spath, ssize, scrc = _write_shard(shard_dir, k, b"")
                if tr is not None:
                    # the ledger covers EVERY chunk, empty ones
                    # included — per-chunk coverage is what lets the
                    # wirestat table read as a gap-free byte account
                    tr.xfer(
                        "shard", 0, ssize, time.monotonic(), 0.0, chunk=k
                    )
                    with phase_lock:
                        led["shard_wire"] += ssize
                done_q[k] = (
                    spath, ssize, scrc, 0, 0, bgzf.deflate_flavor(),
                    b"", False,
                )
                _advance_frontier()
                continue
            # bounded H2D prefetch: take the chunk's permit BEFORE its
            # dispatches are submitted — at most prefetch_depth chunks
            # may be in the dispatched-but-not-materialised window, so
            # packing + H2D of chunk k+1 overlaps device compute of
            # chunk k without unbounded device-buffer pileup. The drain
            # worker returns the permit (finally-backstopped), so the
            # blocking acquire cannot deadlock.
            t0 = time.monotonic()
            prefetch_sem.acquire()
            dt = time.monotonic() - t0
            phase["prefetch_stall"] += dt
            if tr is not None:
                tr.span("prefetch_stall", t0, dt, chunk=k)
            entries = []
            for cbuckets, cspec in partition_buckets(
                buckets, grouping, consensus,
                packed_io=(packed != "off"),
                per_base_counts=per_base_tags,
                qual_alphabet=alpha,
            ):
                spec_cache[cspec] = True
                # transfer workers: host->device copies ride the tunnel
                # while the main loop ingests/buckets the next chunk;
                # submit never raises — failures surface in materialize
                entries.append(
                    (xfer.submit(dispatch, cbuckets, cspec, k), cbuckets, cspec)
                )
            inflight.append((k, drain.submit(drain_chunk, k, entries, batch)))
            while len(inflight) >= max_inflight:
                _wait_oldest()
        while inflight:
            _wait_oldest()
    except BaseException:
        # error/kill path: tell surviving drain workers to stop
        # retrying (the finally's shutdown waits on them), and release
        # the incremental tmp handle (the tmp itself stays on disk —
        # never visible at out_path — and the next run truncates it);
        # the frontier state is abandoned, so nothing else gets marked
        aborting.set()
        if fin["f"] is not None:
            try:
                fin["f"].close()
            except OSError:
                pass
        raise
    finally:
        if live_src is not None:
            # stop the tailer on EVERY exit path: a killed run must not
            # leave a daemon thread polling the input behind the error
            # (close is idempotent; the reader's own close also routes
            # here when the iterator winds down normally)
            try:
                live_src.close()
            except OSError:
                pass
        if ingest_thread is not None and ingest_thread.is_alive():
            # normal exit: the producer already returned after "done";
            # error exit: aborting is set (above), so a producer
            # blocked on the full queue unwinds within one put timeout.
            # The bounded join is a backstop against a producer stuck
            # deep in retry backoff — it is a daemon thread, so even
            # the pathological case cannot hang process exit.
            ingest_thread.join(timeout=30.0)
        # drop queued-but-unstarted drain tasks and transfers on the
        # error path — their results would never be committed; running
        # ones complete (their shard writes are harmless without marks)
        drain.shutdown(wait=True, cancel_futures=True)
        xfer.shutdown(wait=True, cancel_futures=True)
        if profile_dir:
            # profiler teardown rides the same finally discipline as
            # the recorder teardown: the trace directory is finalised
            # on EVERY exit path, and a teardown failure (profiler
            # died mid-run, disk full) must never mask the error that
            # brought the run down
            try:
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — telemetry teardown
                print(
                    f"[duplexumi] jax.profiler.stop_trace failed: {e!r}",
                    file=sys.stderr,
                )
            else:
                if tr is not None:
                    # the capture records that a profiler trace exists
                    # alongside it (post-mortems pair the two)
                    tr.event(
                        "profile_written",
                        profile_dir=os.path.abspath(profile_dir),
                    )

    # ---- terminal finalise: every shard is already appended into the
    # tmp in frontier order, so what remains is the EOF block + fsync +
    # the one atomic rename — the end-of-run cost no longer scales with
    # the number of chunks. ----
    t_fin = time.monotonic()
    try:
        if fin["f"] is None:
            # record-less input (or zero chunks): the real header is
            # still authoritative; emit the header-only BAM
            if header_out is None:
                _r = BamStreamReader(in_path)
                header_out = _r.header
                _r.close()
            _fin_open()
        f = fin["f"]
        end = f.tell()

        def _publish():
            rewrite_from(f, end, bgzf.BGZF_EOF)
            fsync_file(f)

        _io_retry("finalise.write", _publish, "finalise")
        if tr is not None:
            led["output_overhead_bytes"] += len(bgzf.BGZF_EOF)
        f.close()
    except BaseException:
        if fin["f"] is not None:
            try:
                fin["f"].close()
            except OSError:
                pass
        raise
    _io_retry(
        "finalise.write",
        lambda: replace_durable(tmp_path, out_path),
        "finalise rename",
    )
    if auto_ckpt:
        # implicit checkpoint: after a successful finalise the shards
        # and manifest have served their purpose
        for k in shards:
            try:
                os.remove(shards[k])
            except OSError:
                pass
        try:
            os.rmdir(shard_dir)
        except OSError:
            pass
        try:
            os.remove(checkpoint_path)
        except OSError:
            pass
    if live_mark is not None or snapshot_chunks:
        # the finished output supersedes every partial snapshot, and a
        # finished follow run must resume like any batch output (the
        # watermark pin is follow-run identity, not output state)
        from duplexumiconsensusreads_tpu.live import watermark as _wm

        for leftover in (
            snap_path, snap_path + ".bai", snap_path + ".csi",
        ):
            try:
                os.remove(leftover)
            except OSError:
                pass
        _wm.clear(out_path)
    if write_index:
        # BAI unless a header contig exceeds its 2^29 coordinate space,
        # then the CSI generalization (depth sized to the contig)
        if max(header_out.ref_lengths, default=0) > (1 << 29):
            from duplexumiconsensusreads_tpu.io.csi import build_csi

            build_csi(out_path)
        else:
            from duplexumiconsensusreads_tpu.io.bai import build_bai

            build_bai(out_path)
    dt_fin = time.monotonic() - t_fin
    phase["finalise"] += dt_fin
    if tr is not None:
        # terminal EOF/fsync/rename (+ optional index): chunkless span
        tr.span("finalise", t_fin, dt_fin)
    rep.n_chunks_skipped = n_skipped
    rep.n_pipeline_compiles = len(spec_cache)
    # follow residue: poll/wait accrued after the last chunk boundary
    # (the tailer's final EOF-detection cycles) still joins the ledger
    _drain_live()
    total = time.monotonic() - t_start
    for pk, pv in phase.items():
        rep.seconds[pk] = round(pv, 3)
    # drain-side occupancy: busy seconds across the drain stages over
    # the pool's total capacity. ~1.0 means the drain pool, not the
    # device, is the bottleneck — raise --drain-workers.
    drain_busy = sum(phase[k] for k in DRAIN_PHASES)
    rep.seconds["drain_utilization"] = round(
        min(drain_busy / max(drain_workers * total, 1e-9), 1.0), 3
    )
    rep.seconds["total"] = round(total, 3)
    if tr is not None:
        # stop the heartbeat BEFORE the summary: the summary must be
        # the capture's last record (schema contract), and a beat
        # landing after it would flake the check_trace CI gate on a
        # perfectly healthy run (the recorder also seals itself, but
        # stopping here keeps the final samples instead of dropping
        # them); the caller's finally will re-stop harmlessly
        if hb_box:
            for _hb in hb_box:
                _hb.stop()
        # clean shutdown: embed the report's busy totals so a capture
        # is self-contained for the trace_report sum-check, and the
        # byte-ledger totals + finalised output size so it is equally
        # self-contained for the wirestat byte sum-check
        try:
            out_bytes = os.path.getsize(out_path)
        except OSError:
            out_bytes = 0
        tr.write_summary(
            seconds=dict(rep.seconds),
            counters={
                "n_chunks": rep.n_chunks,
                "n_chunks_skipped": rep.n_chunks_skipped,
                "n_retries": rep.n_retries,
                "n_drain_workers": rep.n_drain_workers,
                # fresh reads this run parsed: the bytes-per-read
                # denominator (resume-skipped chunks moved no bytes,
                # so numerator and denominator agree by construction)
                "n_records": rep.n_records,
                # padding totals: fill factor = real/padded, the tuner
                # verdicts' audit trail (wirestat cross-checks these
                # against the per-record rows_real/rows_pad sums)
                "n_rows_real": rep.n_rows_real,
                "n_rows_padded": rep.n_rows_padded,
                # mesh-alignment pad buckets shipped (device-count
                # rounding): the per-record mesh_pad attrs must
                # reproduce this exactly (wirestat's mesh sum-check)
                "n_mesh_pad_buckets": rep.n_mesh_pad_buckets,
                "n_devices": rep.n_devices,
            },
            bytes={
                **led,
                "output_bytes": int(out_bytes),
                "output_path": os.path.abspath(out_path),
            },
        )
    if report_path:
        from duplexumiconsensusreads_tpu.runtime.executor import write_report

        write_report(rep, report_path)
    return rep


def _empty_records() -> BamRecords:
    return BamRecords(
        names=[],
        flags=np.zeros(0, np.uint16),
        ref_id=np.zeros(0, np.int32),
        pos=np.zeros(0, np.int32),
        mapq=np.zeros(0, np.uint8),
        next_ref_id=np.zeros(0, np.int32),
        next_pos=np.zeros(0, np.int32),
        tlen=np.zeros(0, np.int32),
        lengths=np.zeros(0, np.int32),
        seq=np.zeros((0, 0), np.uint8),
        qual=np.zeros((0, 0), np.uint8),
        cigars=[],
        umi=[],
        aux_raw=[],
    )


def _write_shard(shard_dir: str, k: int, payload: bytes) -> tuple[str, int, int]:
    """Durable shard write: tmp + fsync + atomic rename + dir fsync,
    inside the bounded transient-I/O retry. ``payload`` is the shard's
    on-disk bytes (BGZF-compressed record stream). Returns (path,
    size, crc32) — the manifest triple resume verification re-checks."""
    path = os.path.join(shard_dir, f"chunk{k:06d}.recs")
    crc = zlib.crc32(payload)

    def _once():
        # private tmp per writer: two fleet daemons recomputing the
        # same chunk (zombie overlap) publish complete — and, bytes
        # being a pure function of (input, config), identical — shards
        write_durable(path, payload, tmp=unique_tmp(path))
        return path, len(payload), crc

    return _io_retry("shard.write", _once, f"shard {k} write")


def _count_records(data: bytes) -> tuple[int, int]:
    """(record count, complete consensus R1+R2 pairs) of a raw record
    stream — pairs are identified by PAIRED|PROPER_PAIR|READ1 exactly
    as runtime.executor.count_consensus_pairs does on parsed records."""
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_PAIRED,
        FLAG_PROPER_PAIR,
        FLAG_READ1,
    )

    want = FLAG_PAIRED | FLAG_PROPER_PAIR | FLAG_READ1
    n = n_pairs = 0
    off = 0
    while off < len(data):
        (bsz,) = struct.unpack_from("<i", data, off)
        # flag = high 16 bits of the flag_nc word at body offset 12
        (flag,) = struct.unpack_from("<H", data, off + 4 + 14)
        if (flag & want) == want:
            n_pairs += 1
        off += 4 + bsz
        n += 1
    return n, n_pairs


def _finish_chunk(
    k, parts, duplex, shard_dir, serialize_bam, header, name_tag="",
    paired_out=False, read_group="A", on_stage=None, on_xfer=None,
) -> tuple[str, int, int, int, int, bytes]:
    """Merge one chunk's per-class scattered outputs and write its
    shard. parts rows are 8-tuples — (..., cons_mate, cons_pair,
    cons_end) — or 10 with per-base tags: cols[8] the depth matrix,
    cols[9] the disagreement counts; consumed positionally below, so
    extensions must append AFTER them.

    Shards are stored BGZF-COMPRESSED (native parallel deflate where
    built): the deflate cost lands on the drain worker instead of the
    finalise path, and the incremental finalise append becomes a plain
    byte copy (BGZF members concatenate). Returns (path, size, crc32,
    n_records, n_pairs, codec, shard_bytes) — the commit payload;
    codec is the deflate flavor ACTUALLY used (compress_fast can fall
    back to pure Python at runtime), persisted per shard in the
    manifest so resume can refuse to splice across codecs.

    ``on_stage(stage, t0, dt)`` is the caller's accounting hook: the
    serialize+write segments report as "shard_write" and the BGZF
    compression as "deflate" — per-stage busy phases AND trace spans
    both flow through it, so they can never disagree. ``on_xfer(
    logical, wire, t0, dt)`` is the byte-ledger hook, fired once per
    shard with the raw vs deflated byte counts (None = ledger off)."""
    t0 = time.monotonic()
    cols = sort_consensus_outputs(*(np.concatenate(x) for x in zip(*parts)))
    cb, cq, cd, fp, fu, mate, pair, end = cols[:8]
    recs = consensus_to_records(
        cb,
        cq,
        cd,
        np.ones(len(cb), bool),
        fp,
        fu,
        duplex=duplex,
        name_prefix=f"cons{name_tag}{k}",
        cons_mate=mate,
        cons_pair=pair,
        paired_out=paired_out,
        cons_pdepth=cols[8] if len(cols) > 8 else None,
        cons_perr=cols[9] if len(cols) > 9 else None,
        read_group=read_group,
        cons_end=end,
    )
    # record stream only (header stripped) so shards concatenate
    full = serialize_bam(header, recs)
    shell = serialize_bam(header, _empty_records())
    raw = full[len(shell):]
    # counted from the RAW record bytes before deflate, and persisted
    # in the manifest, so checkpoint-resumed chunks contribute to the
    # report totals without a decompress pass at finalise
    n_rec, n_pairs = _count_records(raw)
    if on_stage:
        on_stage("shard_write", t0, time.monotonic() - t0)
    t0 = time.monotonic()
    comp, codec = bgzf.compress_fast_tagged(raw, eof=False)
    dt = time.monotonic() - t0
    if on_stage:
        on_stage("deflate", t0, dt)
    if on_xfer:
        on_xfer(len(raw), len(comp), t0, dt)
    t0 = time.monotonic()
    path, size, crc = _write_shard(shard_dir, k, comp)
    if on_stage:
        on_stage("shard_write", t0, time.monotonic() - t0)
    return path, size, crc, n_rec, n_pairs, codec, comp
