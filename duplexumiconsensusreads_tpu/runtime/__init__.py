from duplexumiconsensusreads_tpu.runtime.executor import (
    RunReport,
    call_batch_cpu,
    call_batch_tpu,
    call_consensus_file,
)
from duplexumiconsensusreads_tpu.runtime.stream import (
    iter_record_chunks,
    stream_call_consensus,
)

__all__ = [
    "RunReport",
    "call_batch_cpu",
    "call_batch_tpu",
    "call_consensus_file",
    "iter_record_chunks",
    "stream_call_consensus",
]
