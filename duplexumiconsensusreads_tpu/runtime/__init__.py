from duplexumiconsensusreads_tpu.runtime.executor import (
    RunReport,
    call_batch_cpu,
    call_batch_tpu,
    call_consensus_file,
)

__all__ = [
    "RunReport",
    "call_batch_cpu",
    "call_batch_tpu",
    "call_consensus_file",
]
