"""Deterministic fault injection for the streaming executor.

The recovery machinery in ``stream_call_consensus`` — bounded
exponential-backoff retries, bucket-by-bucket poisoned-class isolation,
stale-manifest clearing, checkpoint resume — exists because device
flakes, transient I/O errors, ENOSPC, and mid-run kills are NORMAL
operating conditions for a long checkpointed run over a 200M-read BAM.
None of it is trustworthy unless it can be exercised on demand. This
module is the switchboard: named fault SITES threaded through the hot
path raise scheduled exceptions at exact, reproducible points.

Sites (see KNOWN_SITES): each names one step of the write/recover
spine. A site is hit by calling :func:`fault_point` with its name; with
no plan installed that is a single global load + None check — zero
hot-path cost.

Schedules are comma-separated ``site:nth:kind`` entries — the Nth hit
of ``site`` (1-based, counted per run) raises the exception ``kind``
maps to:

  ``oserror`` / ``io``   InjectedFault (an OSError, errno EIO): the
                         transient-failure shape every bounded-retry
                         path in the executor must absorb
  ``enospc``             InjectedFault with errno ENOSPC
  ``kill``               InjectedKill — a BaseException that models a
                         hard process kill: it must sail through every
                         ``except Exception``/``except OSError`` ladder
                         so on-disk state is exactly what a real
                         SIGKILL would leave behind

``seed:<seed>:<n>`` expands to ``n`` pseudo-random transient entries
drawn from ``random.Random(seed)`` — the same seed always produces the
same schedule, so every chaos run is replayable bit-for-bit.

Activation: ``FaultPlan.parse``/``FaultPlan.seeded`` + :func:`install`
programmatically (tests), the ``DUT_FAULTS`` env var (picked up by
``stream_call_consensus`` via :func:`install_from_env`, with fresh hit
counters per run), or the CLI's ``call --chaos SPEC`` flag.
"""

from __future__ import annotations

import errno
import os
import random
import threading

# One site per step of the streaming write/recover spine. Keep names in
# sync with the instrumentation in runtime/stream.py + runtime/executor.py
# and the "Failure model & recovery" section of ARCHITECTURE.md.
KNOWN_SITES = (
    "ingest.read",  # file read feeding the rolling BGZF buffer
    "bgzf.inflate",  # block-batch decompression (native or Python)
    "ingest.queue",  # producer->consumer handoff of a prepped chunk
    # (overlap mode's bounded queue put, on the dut-ingest thread):
    # transients ride the standard bounded-retry ladder on the producer;
    # kills forward to the main loop through the queue's error sentinel
    # and surface exactly like a main-thread InjectedKill
    "dispatch.device_put",  # stack/pack/device dispatch (xfer worker)
    "dispatch.pack",  # host-side wire packing of the stacked chunk
    "fetch.result",  # device->host materialisation of outputs
    "fetch.unpack",  # host-side unpack of packed d2h fetch (drain worker)
    "drain.scatter",  # scatter-back of device outputs (drain worker)
    "shard.write",  # per-chunk shard serialize+deflate+durable rename
    "ckpt.save",  # checkpoint manifest persist
    "finalise.write",  # incremental finalise appends + terminal EOF/rename
    # serving layer (serve/): the admission/journal/preempt spine of the
    # multi-job service — same bounded-retry ladder, same chaos coverage
    "serve.accept",  # reading + validating a spooled job submission
    "serve.journal",  # durable admission-queue journal persist
    "serve.preempt",  # journaling a chunk-boundary preemption/requeue
    # fleet spine (N daemons on one spool): the lease state machine's
    # four durable steps — claim, renewal, expiry takeover, fence check
    "serve.lease",  # durable lease claim (queued -> running + token)
    "serve.renew",  # lease renewal (heartbeat + per-chunk commit)
    "serve.expire",  # expired/dead-owner lease reclaim (takeover)
    "serve.fence",  # fencing-token check before a durable commit
    # cross-host fleet (serve/store.py sharedfs backend): the durable
    # liveness-document write and the reclaim sweep's document scan —
    # the two I/O steps pid-free takeover stands on (both sites also
    # fire on the local backend as no-op probes, so one chaos blanket
    # covers both stores)
    "serve.hb",  # durable per-daemon heartbeat document write
    "serve.store",  # lease-store liveness scan feeding reclaim verdicts
    # defensive-serving spine: the deadline sweep/expiry commit and the
    # stuck-run watchdog's stall reclaim — both durable journal moves,
    # both chaos-targetable like every other lease-state transition
    "serve.deadline",  # deadline sweep + terminal `expired` commit
    "serve.watchdog",  # no-progress stall scan + abort-requeue commit
    # scatter-gather sharding (serve/shard/): the two durable moves of
    # the parent-job state machine — registering the planned sub-jobs
    # (splitting -> fanned) and the merge path (parent advance sweep,
    # shard splice commits, merged-output publish)
    "serve.split",  # shard-plan journal txn: children registered + fanned
    "serve.merge",  # parent advance sweep + shard splice/publish commits
    # live follow-mode ingest (live/): the tailing producer's poll cycle
    # (stat + incremental read of the growing input) and the indexed
    # partial-snapshot publish at checkpoint marks — the two I/O steps a
    # follower adds on top of the batch spine
    "live.poll",  # tail poll: stat/read of the growing input
    "live.snapshot",  # partial-snapshot publish (BAM prefix + BAI)
)

_EXC_ERRNO = {
    "oserror": errno.EIO,
    "io": errno.EIO,
    "enospc": errno.ENOSPC,
}
KNOWN_KINDS = (*_EXC_ERRNO, "kill")


class InjectedFault(OSError):
    """A scheduled transient failure — shaped as the OSError the
    production retry ladders already handle, so chaos schedules
    exercise exactly the real recovery paths."""


class InjectedKill(BaseException):
    """A scheduled hard kill. BaseException on purpose: no retry or
    isolation ladder may absorb it — the run dies with whatever disk
    state it had, exactly like SIGKILL, and only checkpoint resume may
    bring the output back."""


class FaultPlan:
    """A parsed, counter-carrying fault schedule for one run."""

    def __init__(self, entries, spec: str = ""):
        self.spec = spec
        self.schedule: dict[str, dict[int, str]] = {}
        for site, nth, kind in entries:
            if site not in KNOWN_SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (known: {', '.join(KNOWN_SITES)})"
                )
            if kind not in KNOWN_KINDS:
                raise ValueError(
                    f"unknown fault kind {kind!r} (known: {', '.join(KNOWN_KINDS)})"
                )
            if nth < 1:
                raise ValueError(f"fault nth must be >= 1 (got {nth})")
            self.schedule.setdefault(site, {})[nth] = kind
        self._hits = dict.fromkeys(KNOWN_SITES, 0)
        self.n_fired = 0
        self._lock = threading.Lock()

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``site:nth:kind[,...]``; ``seed:<seed>:<n>`` entries expand
        to seeded pseudo-random transient faults."""
        entries = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            fields = part.split(":")
            if len(fields) != 3:
                raise ValueError(
                    f"bad fault entry {part!r} (want site:nth:kind or "
                    f"seed:<seed>:<n>)"
                )
            if fields[0] == "seed":
                entries.extend(cls._seed_entries(int(fields[1]), int(fields[2])))
            else:
                entries.append((fields[0], int(fields[1]), fields[2]))
        return cls(entries, spec=spec)

    @staticmethod
    def _seed_entries(seed: int, n: int, sites=KNOWN_SITES, max_nth: int = 2):
        rng = random.Random(seed)
        return [
            (rng.choice(sites), rng.randint(1, max_nth),
             rng.choice(("oserror", "enospc")))
            for _ in range(n)
        ]

    @classmethod
    def seeded(
        cls, seed: int, n_faults: int = 1, sites=KNOWN_SITES, max_nth: int = 2
    ) -> "FaultPlan":
        """Deterministic schedule from a seed — same seed, same faults."""
        return cls(
            cls._seed_entries(seed, n_faults, sites=sites, max_nth=max_nth),
            spec=f"seed:{seed}:{n_faults}",
        )

    def hit(self, site: str) -> None:
        """Count one hit of ``site``; raise if the schedule says so."""
        with self._lock:
            if site not in self._hits:
                raise ValueError(f"unknown fault site {site!r}")
            self._hits[site] += 1
            n = self._hits[site]
            # pop: each scheduled fault fires exactly once, so a retry
            # of the same step sees a clean site and can succeed
            kind = self.schedule.get(site, {}).pop(n, None)
            if kind is None:
                return
            self.n_fired += 1
        # a chaos trigger is a first-class trace event: a capture of a
        # chaos run must show the injected fault AND the retry ladder
        # it exercised as distinct records (lazy import: faults is on
        # the hot path and telemetry must stay optional)
        from duplexumiconsensusreads_tpu.telemetry.trace import emit_event

        emit_event("fault_injected", site=site, hit=n, kind=kind)
        if kind == "kill":
            raise InjectedKill(f"injected kill at {site} (hit {n})")
        raise InjectedFault(
            _EXC_ERRNO[kind], f"injected {kind} at {site} (hit {n})"
        )

    def hits(self, site: str) -> int:
        with self._lock:
            return self._hits[site]


_active: FaultPlan | None = None


def install(plan: FaultPlan | None) -> None:
    global _active
    _active = plan


def uninstall() -> None:
    install(None)


def get_active() -> FaultPlan | None:
    return _active


def install_from_env() -> FaultPlan | None:
    """Install a FRESH plan from ``DUT_FAULTS`` if set (fresh counters
    per executor run, so a schedule means the same thing every run). An
    explicitly installed plan with a DIFFERENT spec (e.g. ``call
    --chaos``) wins over a stale env export; one with the SAME spec is
    refreshed. With no env var, any programmatic plan is left alone."""
    spec = os.environ.get("DUT_FAULTS")
    if spec and (_active is None or _active.spec == spec):
        try:
            install(FaultPlan.parse(spec))
        except ValueError as e:
            # name the env var: the parse error would otherwise surface
            # as a bare traceback deep inside the executor
            raise ValueError(f"DUT_FAULTS: {e}") from None
    return _active


def fault_point(site: str) -> None:
    """Hot-path hook: no-op unless a plan is installed."""
    p = _active
    if p is not None:
        p.hit(site)
