"""The execution-knob registry: every knob declared ONCE, as data.

The repo's load-bearing contract is that output bytes are a pure
function of (input, config). Which knobs join which determinism
surface — the checkpoint fingerprint, the compile ``spec_signature``,
the ``@PG CL`` provenance line, the serve job config, the
streaming-only CLI refusals — used to live as scattered literals in
``cli/main.py`` and ``serve/job.py`` plus ARCHITECTURE prose, and two
shipped bugs (PR 13's ladder-top-rung/provenance mismatch, PR 10's
silently-dropped ``--trace``) slipped exactly that seam. This module
is the closed-world declaration; the ``knob-taint`` dutlint rule
(analysis/rules.py) model-checks the tree against it.

Policy: **adding a knob = adding a ``KNOB_TABLE`` row; the linter
enforces the rest** (an undeclared ``opt("...")`` literal, a knob
reaching a surface it does not declare, a scheduling knob tainting the
fingerprint, a declared scheduling knob with no byte-identity exercise
in the test anchors — all findings).

``KNOB_TABLE`` and ``THREAD_ROLES`` are PURE LITERALS on purpose: the
lint rules read them from the parsed corpus with ``ast.literal_eval``
(never by importing this module), so fixture corpora in tests can
declare their own miniature registries and the shipped one stays
inspectable without executing package code.

Per-knob fields:

- ``flag``: the CLI spelling (``cli/main.py`` dest = the table key).
- ``class``: ``"semantic"`` (changes output bytes — must be carried by
  every surface that replays or fingerprints the run) or
  ``"scheduling"`` (provably byte-neutral — throughput/topology only;
  MUST NOT reach the checkpoint fingerprint).
- ``surfaces``: membership in the determinism surfaces, the shipped
  behaviour stated as data:
    * ``fingerprint`` — joins the streaming checkpoint fingerprint
      (runtime/stream.py ``_fingerprint``); a resumed run must refuse
      a checkpoint written under different semantics.
    * ``spec_signature`` — joins the compile identity (serve/job.py
      ``spec_signature``): bucket geometry + pipeline spec.
    * ``provenance`` — recorded in the deterministic ``@PG CL`` line
      (serve/job.py ``serve_provenance``). Scheduling knobs the daemon
      may resolve/override per slice (mesh, ingest_overlap,
      bucket_ladder) are excluded: embedding them would make job bytes
      depend on serving topology / tuner state, breaking
      bytes == f(input, config). Client-verbatim scheduling knobs
      (drain_workers, max_inflight, packed, prefetch_depth) stay in —
      they reproduce the submitted command faithfully and are
      byte-neutral by the A/B matrix.
    * ``job_config`` — a key of the serve job config
      (serve/job.py ``CONFIG_DEFAULTS`` is derived from this table).
    * ``streaming_only`` — meaningless on the whole-file executor; the
      CLI refuses it there (refuse-don't-drop), resolved-value
      semantics: a config-file key is refused exactly like the flag.
- ``default``: the job-config default (CLI defaults match except
  ``chunk_reads``, whose CLI default 0 means "whole file").
- ``choices`` / ``min_int``: value domain, where closed/bounded.
- ``stream_kwarg``: the ``stream_call_consensus`` parameter name when
  it differs from the knob name (``read_group_id`` -> ``read_group``).
- ``via``: ``"params"`` marks knobs that reach the fingerprint through
  ``dataclasses.asdict(GroupingParams/ConsensusParams)`` rather than
  as a named ``_fingerprint`` argument.
- ``refuse_alone`` / ``refuse_note``: streaming-only refusal grouping
  (knobs without ``refuse_alone`` share one combined message).
"""

from __future__ import annotations

import dataclasses

# the determinism surfaces a knob can belong to (see module docstring)
SURFACES = (
    "fingerprint",
    "spec_signature",
    "provenance",
    "job_config",
    "streaming_only",
)

# NOTE: dict order is load-bearing — serve/job.py's CONFIG_DEFAULTS
# and the canonical @PG CL flag order are derived from it.
KNOB_TABLE = {
    "grouping": {
        "flag": "--grouping",
        "class": "semantic",
        "surfaces": ("fingerprint", "spec_signature", "provenance",
                     "job_config"),
        "default": "exact",
        "choices": ("exact", "adjacency", "cluster"),
        "via": "params",
    },
    "mode": {
        "flag": "--mode",
        "class": "semantic",
        "surfaces": ("fingerprint", "spec_signature", "provenance",
                     "job_config"),
        "default": "ss",
        "choices": ("ss", "duplex"),
        "via": "params",
    },
    "error_model": {
        "flag": "--error-model",
        "class": "semantic",
        "surfaces": ("fingerprint", "spec_signature", "provenance",
                     "job_config"),
        "default": "none",
        "choices": ("none", "cycle"),
        "via": "params",
    },
    "max_hamming": {
        "flag": "--max-hamming",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 1,
        "via": "params",
    },
    "count_ratio": {
        "flag": "--count-ratio",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 2,
        "via": "params",
    },
    "min_reads": {
        "flag": "--min-reads",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 1,
        "via": "params",
    },
    "min_duplex_reads": {
        "flag": "--min-duplex-reads",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 1,
        "via": "params",
    },
    "max_qual": {
        "flag": "--max-qual",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 90,
        "via": "params",
    },
    "max_input_qual": {
        "flag": "--max-input-qual",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 50,
        "via": "params",
    },
    "min_input_qual": {
        "flag": "--min-input-qual",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 0,
        "via": "params",
    },
    "capacity": {
        "flag": "--capacity",
        "class": "semantic",
        "surfaces": ("fingerprint", "spec_signature", "provenance",
                     "job_config"),
        "default": 2048,
        "min_int": 1,
    },
    "chunk_reads": {
        # semantic: chunk boundaries name the emitted consensus
        # records (cons<tag><chunk> ids), so different chunking is
        # different bytes. Job default 500_000 (a job MUST stream);
        # the CLI's own default is 0 = whole file, validated with a
        # dedicated streaming message — hence no min_int here.
        "flag": "--chunk-reads",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 500_000,
    },
    "max_inflight": {
        "flag": "--max-inflight",
        "class": "scheduling",
        "surfaces": ("provenance", "job_config"),
        "default": 4,
        "min_int": 1,
    },
    "drain_workers": {
        "flag": "--drain-workers",
        "class": "scheduling",
        "surfaces": ("provenance", "job_config"),
        "default": 2,
        "min_int": 1,
    },
    "packed": {
        "flag": "--packed",
        "class": "scheduling",
        "surfaces": ("provenance", "job_config", "streaming_only"),
        "default": "auto",
        "choices": ("auto", "byte", "off"),
    },
    "prefetch_depth": {
        "flag": "--prefetch-depth",
        "class": "scheduling",
        "surfaces": ("provenance", "job_config", "streaming_only"),
        "default": 2,
        "min_int": 1,
    },
    "ingest_overlap": {
        # provenance-EXCLUDED: the producer pipeline provably cannot
        # change output bytes (the producer emits in chunk order, so
        # the consumer sees the sync path's exact sequence) — a @PG CL
        # carrying it would make job bytes depend on how a daemon
        # chose to overlap its host work
        "flag": "--ingest-overlap",
        "class": "scheduling",
        "surfaces": ("job_config", "streaming_only"),
        "default": "auto",
        "choices": ("auto", "on", "off"),
    },
    "mesh": {
        # provenance-EXCLUDED: device count provably cannot change
        # output bytes (chunk order is commit order, pad buckets emit
        # nothing) and the daemon resolves "auto" against ITS pool — a
        # @PG CL carrying it would make job bytes depend on serving
        # topology. It DOES join spec_signature: GSPMD partitions the
        # same program differently per device count
        "flag": "--mesh",
        "class": "scheduling",
        "surfaces": ("spec_signature", "job_config", "streaming_only"),
        "default": "auto",
        "refuse_alone": True,
        "refuse_note": "; whole-file runs size the mesh with --devices",
    },
    "bucket_ladder": {
        # provenance-EXCLUDED: a shape knob that provably cannot
        # change output bytes (the executors' final sort makes bytes
        # a pure function of the read set), and the serve layer may
        # override it per slice from a tuner verdict — a @PG CL
        # carrying it would make job bytes depend on tuner state. It
        # DOES join spec_signature: each rung is its own
        # dispatch-class capacity, so the ladder IS geometry
        "flag": "--bucket-ladder",
        "class": "scheduling",
        "surfaces": ("spec_signature", "job_config", "streaming_only"),
        "default": "off",
        "refuse_alone": True,
    },
    "mate_aware": {
        "flag": "--mate-aware",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": "auto",
        "choices": ("auto", "on", "off"),
    },
    "max_reads": {
        "flag": "--max-reads",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": 0,
    },
    "per_base_tags": {
        "flag": "--per-base-tags",
        "class": "semantic",
        "surfaces": ("fingerprint", "spec_signature", "provenance",
                     "job_config"),
        "default": False,
    },
    "read_group_id": {
        "flag": "--read-group-id",
        "class": "semantic",
        "surfaces": ("fingerprint", "provenance", "job_config"),
        "default": "A",
        "stream_kwarg": "read_group",
    },
    "write_index": {
        # changes WHAT is produced (the .bai beside the output), not
        # the BAM bytes — carried by provenance/job_config, absent
        # from the fingerprint like every non-BAM-bytes knob
        "flag": "--write-index",
        "class": "semantic",
        "surfaces": ("provenance", "job_config"),
        "default": False,
    },
    # ---- live follow-mode knobs (live/): ALL scheduling-class and
    # fingerprint/spec_signature/provenance-EXCLUDED on purpose — they
    # steer WHEN input bytes become visible to the executor, never what
    # the executor computes from them. The chunk grid is pinned by
    # chunk_reads + the hold-back rule, so a follow run over the
    # finished file is byte-identical to the batch run (the A/B matrix
    # proves it), and a @PG CL carrying them would make job bytes
    # depend on how the input happened to arrive.
    "follow": {
        "flag": "--follow",
        "class": "scheduling",
        "surfaces": ("job_config", "streaming_only"),
        "default": False,
        "refuse_alone": True,
        "refuse_note": "; tailing a growing input requires the "
                       "streaming executor's chunk grid",
    },
    "finalize_on": {
        # structured domain (eof | idle:<seconds> | marker) hand-
        # validated like mesh/bucket_ladder — no closed choices tuple
        "flag": "--finalize-on",
        "class": "scheduling",
        "surfaces": ("job_config", "streaming_only"),
        "default": "eof",
    },
    "live_poll_s": {
        "flag": "--live-poll-s",
        "class": "scheduling",
        "surfaces": ("job_config", "streaming_only"),
        "default": 0.25,
    },
    "snapshot_chunks": {
        # 0 = no partial snapshots; N>0 publishes an indexed BAM
        # prefix every N committed chunks. Output-bytes-neutral: the
        # snapshot is a SIDE artifact (out + ".snapshot.bam"), the
        # final output bytes never depend on it
        "flag": "--snapshot-chunks",
        "class": "scheduling",
        "surfaces": ("job_config", "streaming_only"),
        "default": 0,
    },
    # ---- CLI-only execution knobs: resolvable via opt()/config file
    # but never part of a serve job (refused at --submit); empty
    # surface sets are the honest declaration, not an omission.
    "backend": {
        "flag": "--backend",
        "class": "scheduling",  # cpu/tpu outputs are byte-identical
        "surfaces": (),
        "default": "tpu",
        "choices": ("tpu", "cpu"),
    },
    "devices": {
        "flag": "--devices",
        "class": "scheduling",
        "surfaces": (),
        "default": None,
    },
    "cycle_shards": {
        "flag": "--cycle-shards",
        "class": "scheduling",
        "surfaces": (),
        "default": 1,
    },
    "ref_projected": {
        # whole-file executor only: changes bytes, but whole-file runs
        # have no checkpoint fingerprint and jobs refuse it
        "flag": "--ref-projected",
        "class": "semantic",
        "surfaces": (),
        "default": False,
    },
    "umi_whitelist": {
        "flag": "--umi-whitelist",
        "class": "semantic",
        "surfaces": (),
        "default": None,
    },
    "umi_max_mismatches": {
        "flag": "--umi-max-mismatches",
        "class": "semantic",
        "surfaces": (),
        "default": 1,
    },
    "config": {
        # the benchmark preset selector: expands to other knobs'
        # values, carries none of its own
        "flag": "--config",
        "class": "semantic",
        "surfaces": (),
        "default": None,
    },
}

# The declared thread-confinement model (the `thread-confinement`
# dutlint rule walks each entry's transitive same-file call graph
# against it — the generalisation of PR 17's ingest-only rule, whose
# contract is now the "ingest" row). Per role:
#
# - ``module``: corpus-path suffix holding the entry function.
# - ``entry``: the thread-entry function name; "" marks the main loop,
#   which is not walked — its row only declares OWNERSHIP, feeding the
#   per-module watched-name union that confines every other role.
# - ``marker``: the thread-name literal (Thread name= /
#   thread_name_prefix) pinning the role to a real thread; rename
#   protection — registry row present, entry function gone, marker
#   still in the module — is a finding, not a silent skip.
# - ``may``: permitted effect classes — "device" (jax/dispatch calls),
#   "durable" (checkpoint marks / durable writes), "journal" (flock'd
#   journal txns). Anything outside the tuple is a finding.
# - ``shared``: (structure, lock) pairs the role may touch; lock ""
#   means the structure is self-synchronizing (a Semaphore, a bounded
#   Queue). Touching a watched structure not listed, or listed but
#   outside `with <lock>:`, is a finding.
# - ``handoff``: the ONE queue the role may put to (producer roles).
THREAD_ROLES = {
    "main": {
        "module": "runtime/stream.py",
        "entry": "",
        "marker": "",
        "may": ("device", "durable", "journal"),
        "shared": (
            ("inflight", ""),
            ("done_q", ""),
            ("prefetch_sem", ""),
            ("ckpt", ""),
            ("drain", ""),
            ("xfer", ""),
            ("ingest_q", ""),
        ),
    },
    "xfer": {
        "module": "runtime/stream.py",
        "entry": "dispatch",
        "marker": "dut-xfer",
        "may": ("device",),
        "shared": (
            ("phase", "phase_lock"),
            ("rep", "phase_lock"),
            ("led", "phase_lock"),
            ("dev_pending", "phase_lock"),
            ("dev_compiled", "phase_lock"),
        ),
    },
    "drain": {
        # materialize re-dispatches on OOM retry (device) and
        # _finish_chunk -> _write_shard commits shards (durable), so
        # the drain lane legitimately holds both effect grants
        "module": "runtime/stream.py",
        "entry": "drain_chunk",
        "marker": "dut-drain",
        "may": ("device", "durable"),
        "shared": (
            ("phase", "phase_lock"),
            ("rep", "phase_lock"),
            ("led", "phase_lock"),
            ("dev_pending", "phase_lock"),
            ("dev_compiled", "phase_lock"),
            ("prefetch_sem", ""),
        ),
    },
    "ingest": {
        # PR 17's producer contract: pure host prep, no device, no
        # durable state, the bounded handoff queue is the only seam
        "module": "runtime/stream.py",
        "entry": "_ingest_producer",
        "marker": "dut-ingest",
        "may": (),
        "handoff": "ingest_q",
        "shared": (
            ("phase", "phase_lock"),
            ("ingest_q", ""),
            # the auto-ladder tuner verdict: _prep_chunk pins
            # rep.bucket_ladder ONCE on the first non-empty chunk — a
            # single GIL-atomic attribute write, before any consumer
            # reads the report, so it needs no lock
            ("rep", ""),
        ),
    },
    "heartbeat": {
        "module": "telemetry/trace.py",
        "entry": "_run",
        "marker": "dut-heartbeat",
        "may": (),
        "shared": (),
    },
    "live-tail": {
        # the follow-mode tailing producer (live/tail.py): pure host
        # I/O against the growing input — no device, no durable state
        # (the admission watermark is persisted by the main loop at
        # commit time), and the bounded admission queue is its only
        # output seam. Poll timing accrues in TailSource's own
        # lock-guarded counters; the consumer drains them into the
        # phase dict at chunk boundaries, so the tailer never touches
        # stream.py's shared state
        "module": "live/tail.py",
        "entry": "_tail_loop",
        "marker": "dut-live-tail",
        "may": (),
        "handoff": "_q",
        "shared": (),
    },
    "watchdog": {
        # reclaim/expiry sweeps move journal state through the flock'd
        # txn seam; instance-attribute structures (self.*) are rule 6
        # lock-discipline's jurisdiction, hence the empty shared list
        "module": "serve/service.py",
        "entry": "_watchdog_loop",
        "marker": "dut-watchdog",
        "may": ("journal", "durable"),
        "shared": (),
    },
    "serve-worker": {
        "module": "serve/service.py",
        "entry": "_worker_loop",
        "marker": "dut-serve",
        "may": ("device", "durable", "journal"),
        "shared": (),
    },
}


@dataclasses.dataclass(frozen=True)
class Knob:
    """One execution knob, hydrated from its KNOB_TABLE row."""

    name: str
    flag: str
    knob_class: str  # "semantic" | "scheduling"
    surfaces: tuple
    default: object
    choices: tuple | None = None
    min_int: int | None = None
    stream_kwarg: str | None = None
    via: str | None = None
    refuse_alone: bool = False
    refuse_note: str = ""

    @property
    def config_key(self) -> str:
        return self.name


def _build() -> dict:
    out = {}
    for name, row in KNOB_TABLE.items():
        cls = row["class"]
        if cls not in ("semantic", "scheduling"):
            raise ValueError(f"knob {name!r}: bad class {cls!r}")
        bad = set(row["surfaces"]) - set(SURFACES)
        if bad:
            raise ValueError(f"knob {name!r}: unknown surfaces {sorted(bad)}")
        out[name] = Knob(
            name=name,
            flag=row["flag"],
            knob_class=cls,
            surfaces=tuple(row["surfaces"]),
            default=row["default"],
            choices=tuple(row["choices"]) if "choices" in row else None,
            min_int=row.get("min_int"),
            stream_kwarg=row.get("stream_kwarg"),
            via=row.get("via"),
            refuse_alone=bool(row.get("refuse_alone", False)),
            refuse_note=row.get("refuse_note", ""),
        )
    return out


KNOBS: dict[str, Knob] = _build()


def knobs_on(surface: str) -> list[str]:
    """Knob names declaring ``surface``, in table (canonical) order."""
    if surface not in SURFACES:
        raise ValueError(f"unknown surface {surface!r}")
    return [k for k, knob in KNOBS.items() if surface in knob.surfaces]


def job_config_defaults() -> dict:
    """serve/job.py's CONFIG_DEFAULTS, derived: job-config knobs in
    table order (the canonical @PG CL flag order) with their
    defaults."""
    return {k: KNOBS[k].default for k in knobs_on("job_config")}


def job_choice_map() -> dict:
    """Closed value domains for job-config knobs (validate_spec's
    choices check; mesh/bucket_ladder have structured domains checked
    separately)."""
    return {
        k: set(KNOBS[k].choices)
        for k in knobs_on("job_config")
        if KNOBS[k].choices is not None
    }


def job_min_int_keys() -> tuple:
    """Job-config knobs requiring an int >= min_int (chunk_reads keeps
    its dedicated must-stream message in validate_spec)."""
    return tuple(
        k for k in knobs_on("job_config") if KNOBS[k].min_int is not None
    )


def streaming_only_keys() -> tuple:
    """Knobs the CLI refuses on the whole-file path, in table order."""
    return tuple(knobs_on("streaming_only"))


def config_file_keys() -> frozenset:
    """Keys accepted in a --config-file document: exactly the declared
    knobs (every execution knob is file-settable; run-control flags
    like --resume/--trace are not knobs and not file keys)."""
    return frozenset(KNOBS)
