"""File-level orchestration: BAM in → grouped/consensus-called → BAM out.

This is the host runtime around the device pipeline: parse, bucket,
dispatch buckets across the mesh, scatter device outputs back to
file order, and emit consensus records. The CPU backend routes the
same call through the NumPy oracle (the stand-in reference
implementation), which is what `--backend=cpu` means at the CLI —
the operator contract BASELINE.json's north_star requires.
"""

from __future__ import annotations

import dataclasses
import json
import time

import numpy as np

from duplexumiconsensusreads_tpu.constants import NO_FAMILY
from duplexumiconsensusreads_tpu.runtime.faults import fault_point
from duplexumiconsensusreads_tpu.types import (
    ConsensusParams,
    FamilyAssignment,
    GroupingParams,
    ReadBatch,
)
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64, umi_sort_keys


@dataclasses.dataclass
class RunReport:
    """Counters + timings for one run (CLI --report writes this as JSON)."""

    n_records: int = 0
    n_valid_reads: int = 0
    n_dropped: int = 0
    n_buckets: int = 0
    n_families: int = 0
    n_molecules: int = 0
    n_consensus: int = 0
    n_devices: int = 1
    n_chunks: int = 0  # streaming only
    n_chunks_skipped: int = 0  # streaming resume: chunks served from shards
    n_size_classes: int = 0
    n_pipeline_compiles: int = 0
    n_retries: int = 0  # streaming: chunks re-dispatched after a failure
    n_drain_workers: int = 0  # streaming: drain worker pool size
    n_mixed_mate_families: int = 0  # see io.convert.warn_mixed_mates
    n_consensus_pairs: int = 0  # mate-aware: consensus R1+R2 pairs emitted
    # result-changing bucketing fallbacks (bucketing.FALLBACK_COUNTERS):
    # nonzero means that many families/reads deviated from oracle
    # semantics (missed adjacency merges / duplicate per-split records)
    n_precluster_fallback_groups: int = 0
    n_precluster_fallback_reads: int = 0
    n_jumbo_hardcut_families: int = 0
    n_jumbo_hardcut_splits: int = 0
    n_downsampled_reads: int = 0  # --max-reads: io.convert.downsample_families
    # CIGAR input policy (io.convert): minority-CIGAR reads rescued by
    # the soft-clip trim-and-shift vs dropped outright, the latter
    # split per strand — losing one strand silently downgrades a
    # molecule from duplex to single-strand, so the split must be
    # visible, not just the aggregate
    n_rescued_cigar: int = 0
    n_dropped_cigar_ab: int = 0
    n_dropped_cigar_ba: int = 0
    # --ref-projected: reads realigned onto reference columns vs groups
    # (and their reads) that kept the cycle layout + modal-CIGAR policy
    n_projected_reads: int = 0
    n_projection_fallback_reads: int = 0
    n_projection_fallback_groups: int = 0
    # reads whose CIGAR consumes no reference (soft-clip+insertion
    # only): projected rows stay PAD, contributing no evidence
    n_projection_unanchored_reads: int = 0
    # --umi-whitelist (CorrectUmis analogue): reads whose UMI was
    # snapped to a whitelist entry / invalidated (too far or ambiguous)
    n_umi_corrected: int = 0
    n_dropped_whitelist: int = 0
    mate_aware: bool = False  # resolved mate-aware mode of this run
    ingest_overlap: bool = False  # streaming: resolved overlap mode —
    # True when ingest ran as the bounded background producer pipeline
    # (a scheduling decision like the mesh: never changes output bytes)
    backend: str = ""
    # wire accounting (streaming): bytes of device-input tensors
    # dispatched and device-output tensors materialised. Together with
    # a measured wire-bandwidth probe these turn "the tunnel was slow"
    # from an assertion into arithmetic (bytes / MB/s ~ observed wall).
    bytes_h2d: int = 0
    bytes_d2h: int = 0
    # device-ledger accounting (streaming): executed analytic FLOPs
    # of every dispatch (ops/pipeline.py's SSC_METHOD_COSTS x padded
    # bucket count; retries re-count like the byte ledger) and the
    # device wait+fetch busy seconds they ran in. flops / seconds /
    # peak (telemetry/device.py) is the run's honest MFU — the serving
    # layer derives per-job MFU from exactly these two counters, and a
    # capture's dev records must sum to them (devstat's sum-check)
    device_flops: float = 0.0
    device_seconds: float = 0.0
    # padding observability (streaming): real read rows dispatched vs
    # total padded row-slots (bucket capacities x padded bucket counts,
    # retried dispatches counted like the byte ledger counts them) —
    # fill factor = n_rows_real / n_rows_padded, the tuner's audit trail
    n_rows_real: int = 0
    n_rows_padded: int = 0
    # mesh padding (streaming): empty buckets appended per dispatch to
    # round each class's bucket count to a device-count multiple so the
    # mesh shards evenly (proven n_out == 0 on device; they ride the
    # wire and the GEMM, which is why every one is ledgered — the
    # per-record mesh_pad attrs must sum to exactly this counter)
    n_mesh_pad_buckets: int = 0
    # resolved bucket ladder of the run ([] = single-capacity): explicit
    # rungs verbatim, or the tuner verdict an auto run settled on
    bucket_ladder: list = dataclasses.field(default_factory=list)
    # follow mode (live/): number of indexed partial snapshots this run
    # has published so far (monotone across kill/resume — the admission
    # watermark carries the series); 0 when snapshots are off
    snapshot_seq: int = 0
    seconds: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> str:
        """Serialize with STABLE key order and `seconds` rounded to
        milliseconds: reports from different runs diff cleanly (keys
        never reorder, values never carry float noise past the ms the
        measurements are honest to)."""
        d = dataclasses.asdict(self)
        # sort_keys below orders every dict (seconds included); this
        # comprehension only normalises the values
        d["seconds"] = {k: round(float(v), 3) for k, v in self.seconds.items()}
        # the device-ledger accumulators carry float-sum noise past
        # what the measurements are honest to; same ms/flop rounding
        d["device_flops"] = round(float(self.device_flops), 3)
        d["device_seconds"] = round(float(self.device_seconds), 3)
        return json.dumps(d, indent=2, sort_keys=True)


def write_report(rep: "RunReport", path: str) -> None:
    """Write a RunReport JSON to ``path``; ``-`` means stdout (pipe a
    report straight into jq/diff without a temp file). Shared by both
    executors so the CLI's --report contract cannot drift."""
    text = rep.to_json() + "\n"
    if path == "-":
        import sys

        sys.stdout.write(text)
        sys.stdout.flush()
    else:
        with open(path, "w") as f:
            f.write(text)


# Transfer-pool size for the streaming executor (runtime/stream.py
# builds its ThreadPoolExecutor from this, and the busy-wall canary
# thresholds below must agree with the real pool — one constant, no
# cross-module drift). The pool's threads run under the `xfer` row of
# THREAD_ROLES (runtime/knobs.py): device grant only — the helpers in
# this module that move durable state (write_report via its allowlist
# entry aside) are called from the main/drain lanes, never from xfer.
XFER_WORKERS = 4
DRAIN_PHASES = ("device_wait_fetch", "scatter", "deflate", "shard_write")
# rep.seconds entries that are not per-stage busy seconds
# (main_loop_stall / prefetch_stall / ingest_stall are main-thread
# blocked wall — back-pressure, the bounded H2D prefetch window and the
# ingest-producer handoff respectively — and ingest_backpressure is the
# producer blocked on its full queue; shown via dedicated summary
# lines, not stage rows)
_NON_STAGE_KEYS = (
    "total", "drain_utilization", "main_loop_stall", "prefetch_stall",
    "ingest_stall", "ingest_backpressure",
)


def busy_wall_table(
    seconds: dict, drain_workers: int = 1
) -> tuple[list[str], list[str]]:
    """Render ``RunReport.seconds`` as overlapped busy-time vs wall rows.

    Since the pipelined drain, phases are per-stage BUSY seconds accrued
    on whichever thread runs the stage — they overlap each other, so
    summing them no longer gives the wall. A stage can legitimately
    exceed the wall only up to its worker-pool size; busy beyond
    wall x pool is impossible with honest clocks, so such stages are
    returned as accounting-bug canaries (second element) and flagged
    BUSY>WALL in the rendered rows.
    """
    # ONE tolerant-numeric predicate for the whole observability
    # contract: the busy>wall canary here and the trace schema
    # validator/sum-check must never diverge on what counts as a number
    from duplexumiconsensusreads_tpu.telemetry.report import _is_num

    def _num(v):
        # foreign/older report shapes can carry anything here; a
        # rendering tool must tolerate every field it touches
        return v if _is_num(v) else None

    wall = float(_num(seconds.get("total")) or 0.0)
    lines = [
        f"{'stage':<18} {'busy_s':>9} {'wall_s':>9} {'busy/wall':>9}  note"
    ]
    bugs: list[str] = []
    for k, v in seconds.items():
        if k in _NON_STAGE_KEYS:
            continue
        if _num(v) is None:
            lines.append(f"{k:<18} {'-':>9} {wall:9.3f} {'-':>9}  (non-numeric)")
            continue
        if k in ("dispatch", "mesh_h2d"):
            # dispatch normally runs on the xfer pool, but materialize's
            # retry path re-dispatches on drain workers too — the
            # canary threshold must cover both or retry-heavy runs trip
            # a false accounting bug. mesh_h2d (the per-device H2D put
            # loop inside dispatch) runs on exactly the same threads.
            pool = XFER_WORKERS + drain_workers
        else:
            pool = drain_workers if k in DRAIN_PHASES else 1
        frac = (v / wall) if wall else 0.0
        if wall and v > wall * pool + 0.05:
            note = "BUSY>WALL (accounting bug)"
            bugs.append(k)
        elif pool > 1:
            note = f"pool x{pool}"
        else:
            note = ""
        lines.append(f"{k:<18} {v:9.3f} {wall:9.3f} {frac:9.2f}  {note}")
    du = _num(seconds.get("drain_utilization"))
    if du is not None:
        lines.append(f"drain_utilization  {du:.3f}")
    stall = _num(seconds.get("main_loop_stall"))
    if stall is not None and wall:
        lines.append(
            f"main loop stalled on drain back-pressure "
            f"{stall / wall:.0%} of the wall"
        )
    pstall = _num(seconds.get("prefetch_stall"))
    if pstall is not None and wall:
        lines.append(
            f"main loop stalled on the H2D prefetch window "
            f"{pstall / wall:.0%} of the wall"
        )
    istall = _num(seconds.get("ingest_stall"))
    if istall is not None and wall:
        lines.append(
            f"main loop stalled on the ingest producer "
            f"{istall / wall:.0%} of the wall"
        )
    ibp = _num(seconds.get("ingest_backpressure"))
    if ibp is not None and wall:
        lines.append(
            f"ingest producer blocked on the full handoff queue "
            f"{ibp / wall:.0%} of the wall"
        )
    return lines, bugs


def representative_per_family(
    fam_id: np.ndarray,  # (N,) dense ids, NO_FAMILY for unassigned
    valid: np.ndarray,  # (N,)
    pos_key: np.ndarray,  # (N,) i64
    umi: np.ndarray,  # (N, U) u8
    n_fam: int,
) -> tuple[np.ndarray, np.ndarray]:
    """Per dense family id: its pos_key and consensus-reported UMI.

    pos_key is constant within a family by construction. The reported
    UMI is the family's modal UMI (most frequent member UMI, ties to
    the smallest packed code) — in adjacency mode this recovers the
    directional cluster's seed in all but adversarial tie cases, since
    the seed is defined as the highest-count UMI of the cluster.
    """
    fam_pos = np.zeros(n_fam, np.int64)
    fam_umi = np.zeros((n_fam, umi.shape[1]), np.uint8)
    sel = valid & (fam_id != NO_FAMILY)
    idx = np.nonzero(sel)[0]
    if not len(idx):
        return fam_pos, fam_umi
    f = fam_id[idx]
    words = pack_umi_words64(umi[idx])
    # count (family, umi) pairs
    key = np.column_stack([f.astype(np.int64), words])
    uniq, inv, cnt = np.unique(key, axis=0, return_inverse=True, return_counts=True)
    # first read index carrying each unique (family, umi) pair
    first_read = np.full(len(uniq), -1, np.int64)
    # reversed iteration-free: scatter min read position per pair
    order_reads = np.argsort(inv, kind="stable")
    pair_sorted = inv[order_reads]
    pair_first = np.nonzero(np.r_[True, pair_sorted[1:] != pair_sorted[:-1]])[0]
    first_read[pair_sorted[pair_first]] = order_reads[pair_first]
    # order unique pairs by (family, -count, umi words); first per family wins
    w = uniq.shape[1] - 1
    order = np.lexsort(
        (*[uniq[:, 1 + i] for i in range(w - 1, -1, -1)], -cnt, uniq[:, 0])
    )
    fam_sorted = uniq[order, 0]
    first = np.nonzero(np.r_[True, fam_sorted[1:] != fam_sorted[:-1]])[0]
    win_rows = order[first]  # one row index into uniq per family present
    fams_present = uniq[win_rows, 0].astype(np.int64)
    rep_reads = idx[first_read[win_rows]]
    fam_pos[fams_present] = pos_key[rep_reads]
    fam_umi[fams_present] = umi[rep_reads]
    # families absent from the id array keep zeros; caller masks by cons_valid
    return fam_pos, fam_umi




def scatter_bucket_outputs(
    out: dict,  # stacked device outputs, ALREADY np.asarray'd, (B, ...)
    buckets,
    batch: ReadBatch,
    duplex: bool,
    pair_base: int = 0,  # global bucket index of buckets[0] — see below
    want_depth: bool = False,  # also return per-base depth AND err rows
    # (requires cons_depth + cons_err in out, i.e. a pipeline spec with
    # per_base_counts=True — per_base_tags runs only)
):
    """Map per-bucket device outputs back to source-batch coordinates.

    Returns (cons_base, cons_qual, cons_dstats, fam_pos, fam_umi,
    cons_mate, cons_pair) concatenated over buckets, containing only
    valid consensus rows (rows past each bucket's real family/molecule
    count are dropped even if a permissive min_reads left them flagged
    valid). cons_dstats is the (n, 2) [cD, cM] table the writers need —
    the full (F, L) depth matrix never leaves the device in production.
    cons_pair is globally unique across buckets (bucket-offset int64),
    so mate re-linking at emission can never pair rows across buckets.
    Shared by the whole-file and streaming executors so their outputs
    cannot drift.
    """
    src_pos = np.asarray(batch.pos_key)
    src_umi = np.asarray(batch.umi)
    nb = len(buckets)
    f = out["cons_valid"].shape[1]
    ids = (out["molecule_id"] if duplex else out["family_id"])[:nb]
    n_out = (out["n_molecules"] if duplex else out["n_families"])[:nb]
    cv = out["cons_valid"][:nb].astype(bool)
    keep = (np.arange(f)[None, :] < np.asarray(n_out)[:, None]) & cv  # (nb, F)

    # ONE representative_per_family call over all buckets: bucket-local
    # dense ids are offset into disjoint [bi*F, bi*F+F) blocks, so the
    # (family, umi) uniq/sort machinery runs once per chunk instead of
    # once per bucket (it dominated scatter time at scale)
    ridx = np.stack([bk.read_index for bk in buckets])  # (nb, R)
    bvalid = np.stack([bk.valid for bk in buckets])
    in_src = ridx >= 0
    offset_ids = np.where(
        in_src & (ids >= 0),
        ids + (np.arange(nb, dtype=np.int64)[:, None] * f),
        NO_FAMILY,
    )
    src = np.maximum(ridx, 0)
    fam_pos, fam_umi = representative_per_family(
        offset_ids.ravel(),
        (bvalid & in_src).ravel(),
        np.where(in_src, src_pos[src], 0).ravel(),
        src_umi[src.ravel()],
        n_fam=nb * f,
    )
    fam_pos = fam_pos.reshape(nb, f)
    fam_umi = fam_umi.reshape(nb, f, -1)
    # globally-unique pair keys: bucket-local links shifted into
    # disjoint int64 blocks (a molecule's two fragment-end units always
    # land in one bucket — bucketing keeps (pos, UMI) runs whole).
    # pair_base makes the blocks unique across the CALLER'S scatter
    # calls too — dispatch classes each restart bi at 0, and a
    # collision would merge two unrelated molecules into a 4-row group
    # that then fails pair completeness at emission
    pair_local = out["cons_pair"][:nb].astype(np.int64)
    pair_glob = np.where(
        pair_local >= 0,
        pair_local + ((pair_base + np.arange(nb, dtype=np.int64))[:, None] << 33),
        -1,
    )
    res = (
        out["cons_base"][:nb][keep],
        out["cons_qual"][:nb][keep],
        np.stack(
            [out["depth_max"][:nb][keep], out["depth_min_pos"][:nb][keep]],
            axis=1,
        ),
        fam_pos[keep],
        fam_umi[keep],
        out["cons_mate"][:nb][keep],
        pair_glob[keep],
        out["cons_end"][:nb][keep],
    )
    if want_depth:
        res = res + (out["cons_depth"][:nb][keep], out["cons_err"][:nb][keep])
    return res


# Device outputs the executors actually consume. cons_depth (the padded
# (F, L) matrix) and n_overflow are deliberately absent: on a tunneled
# chip the transfer, not the compute, is the streaming bottleneck.
# (Deferring the big cons tensors and slicing them to the real row
# count at drain time was tried and is a net LOSS: the drain-time slice
# is a fresh dispatch+round-trip that breaks the async overlap worth
# more than the padding bytes it saves.)
FETCH_KEYS = (
    "family_id",
    "molecule_id",
    "n_families",
    "n_molecules",
    "cons_valid",
    "cons_base",
    "cons_qual",
    "depth_max",
    "depth_min_pos",
    "cons_mate",
    "cons_pair",
    "cons_end",
)


def start_fetch(out: dict, extra: tuple = (), keys: tuple = FETCH_KEYS) -> dict:
    """Select ``keys`` (+ extra, e.g. cons_depth for per-base tags)
    and start their device->host copies NOW, so every transfer is in
    flight before any is awaited (per-fetch tunnel latency would
    otherwise serialise)."""
    sel = {k: out[k] for k in (*keys, *extra)}
    for v in sel.values():
        try:
            v.copy_to_host_async()
        except AttributeError:  # already a NumPy array (CPU tests)
            pass
    return sel


def fetch_outputs(out: dict) -> dict:
    """Blocking conversion of an ALREADY-SELECTED start_fetch dict to
    host NumPy arrays (re-selecting here would drop extra keys)."""
    # chaos site: a scheduled fault here lands in the streaming
    # executor's materialize() retry/isolation ladder
    fault_point("fetch.result")
    return {k: np.asarray(v) for k, v in out.items()}


# ------------------------------------------------------- packed D2H rung
#
# The return path's wire diet (the gap stream.py's d2h ledger records
# used to label "nothing packs the return path yet"): a device-side
# epilogue jitted SEPARATELY from the fused pipeline (its static
# k_pad would otherwise recompile the whole pipeline per chunk) that
# (1) COMPACTS the (B, F)-padded consensus-row tensors to the valid
# prefix rows j < n_out[b] via an on-device count + prefix-gather —
# k_pad is a HOST-side bound from the same grouping invariant that
# sizes f_max (adjacency can only MERGE exact families, so output
# units per bucket <= mult * n_unique) — and (2) packs base|qual
# exploiting the kernels' output coupling (cons_base == BASE_N iff
# cons_qual == NO_CALL_QUAL, and called quals are clipped >= 2): the
# qual byte carries 0 as the N marker and bases ride 2-bit, four per
# byte, so base+qual cost 1.25 bytes/cycle at ANY max_qual instead of
# 2. Depth stats and the read->id map fit u16 (gated on capacity <
# 2**16, the same bound as the H2D pos lane), and only the id array
# the scatter actually consumes is fetched. Unpack (runtime/stream's
# drain workers, chaos site fetch.unpack) reconstructs the exact
# unpacked FETCH_KEYS arrays at every position the scatter reads, so
# output bytes are bit-identical with the rung on or off.
#
# MESH: the compaction runs PER SHARD (``n_shards`` = the mesh's data
# axis; the bucket axis is padded to a multiple of it, so each shard
# owns a contiguous (B/S)-bucket block). This is not an optimisation
# but a liveness requirement: a global cumsum/searchsorted over the
# bucket-sharded axis compiles to cross-device collectives
# (AllReduce/AllGather on XLA:CPU and TPU alike), and two sharded
# programs dispatched concurrently from different transfer threads —
# exactly what the streaming executor's async overlap does — deadlock
# the per-device collective rendezvous. The vmapped per-shard form
# keeps every lane device-local (zero collectives, the same property
# parallel/mesh.py documents for the pipeline itself), at the cost of
# padding each shard's compact rows to one shared static k_pad. The
# wire layout is therefore (S * k_pad, ...) row-blocks, one block per
# shard; host unpack re-splits on the same n_shards.

PACKED_FETCH_KEYS = (
    "n_families",
    "n_molecules",
    "ids16",
    "cons_q",
    "cons_b2",
    "cons_flags",
    "cons_dstats",
    "cons_pair",
)

class D2hCompactionOverflow(RuntimeError):
    """The packed-D2H row bound was violated: the device produced more
    output units than the grouping invariant allows. Deterministic —
    a retry re-derives the identical overflow — so the streaming
    executor's retry/isolation ladder re-raises it immediately instead
    of burning re-dispatches on it."""


_PACK_D2H = None


def _shard_pack_body(
    n_out_s, base_s, qual_s, valid_s, mate_s, end_s, dmax_s, dmin_s,
    pair_s, *, k_pad: int,
):
    """ONE shard's compaction, on (per, ...) blocks: every index below
    is shard-local, so both callers — the single-device vmap and the
    mesh's shard_map — run it with zero cross-shard traffic. One body
    on purpose: the wire layout (k_pad rows per shard, shard-major)
    must be identical whichever form produced it, because the host
    unpack cannot tell them apart."""
    import jax.numpy as jnp

    from duplexumiconsensusreads_tpu.constants import N_REAL_BASES
    from duplexumiconsensusreads_tpu.kernels.encoding import pack_2bit

    per, f = valid_s.shape
    offs = jnp.cumsum(n_out_s)
    starts = offs - n_out_s
    k = jnp.arange(k_pad, dtype=jnp.int32)
    b = jnp.minimum(
        jnp.searchsorted(offs, k, side="right"), per - 1
    ).astype(jnp.int32)
    j = jnp.clip(k - starts[b], 0, f - 1)
    live = k < offs[-1]

    def g(a):
        mask = live.reshape((-1,) + (1,) * (a.ndim - 2))
        return jnp.where(mask, a[b, j], 0)

    base = g(base_s)  # (K, L) u8
    qual = g(qual_s)  # (K, L) u8
    # the N marker: called quals are >= 2 by the kernels' clip, so 0
    # is free — and BASE_N rows always carry NO_CALL_QUAL, so dropping
    # their qual loses nothing
    qb = jnp.where(base >= N_REAL_BASES, 0, qual).astype(jnp.uint8)
    flags = (
        g(valid_s.astype(jnp.uint8)) | (g(mate_s) << 1) | (g(end_s) << 2)
    ).astype(jnp.uint8)
    return {
        "cons_q": qb,
        "cons_b2": pack_2bit(base & 3),
        "cons_flags": flags,
        "cons_dstats": jnp.stack(
            [g(dmax_s), g(dmin_s)], axis=1
        ).astype(jnp.uint16),
        "cons_pair": g(pair_s),
    }


_PACK_FIELDS = (
    "cons_base", "cons_qual", "cons_valid", "cons_mate", "cons_end",
    "depth_max", "depth_min_pos", "cons_pair",
)


def _pack_d2h_fn():
    global _PACK_D2H
    if _PACK_D2H is None:
        from functools import partial

        import jax
        import jax.numpy as jnp

        @partial(jax.jit, static_argnames=("duplex", "k_pad", "n_shards"))
        def _pack(out, duplex, k_pad, n_shards):
            n_b, f = out["cons_valid"].shape
            per = n_b // n_shards  # stack pads B to a mesh multiple

            def sh(a):  # (B, ...) -> (S, B/S, ...): contiguous blocks
                return a.reshape((n_shards, per) + a.shape[1:])

            n_out = jnp.clip(
                out["n_molecules" if duplex else "n_families"], 0, f
            )
            packed = jax.vmap(
                lambda *a: _shard_pack_body(*a, k_pad=k_pad)
            )(sh(n_out), *(sh(out[k]) for k in _PACK_FIELDS))
            # wire layout: per-shard k_pad row-blocks concatenated —
            # (S * k_pad, ...); host unpack re-splits on n_shards
            packed = {
                k: v.reshape((n_shards * k_pad,) + v.shape[2:])
                for k, v in packed.items()
            }
            ids = out["molecule_id" if duplex else "family_id"]
            return {
                "n_families": out["n_families"],
                "n_molecules": out["n_molecules"],
                # F <= capacity < 2**16, so the shared u16 lane
                # convention applies
                "ids16": ids_to_u16(ids),
                **packed,
            }

        _PACK_D2H = _pack
    return _PACK_D2H


# (mesh, duplex, k_pad) -> jitted shard_map epilogue (the multi-device
# form; Mesh hashes by device ids + axis names, so per-run mesh
# objects share compiles exactly like parallel.sharded._SHMAP_CACHE)
_PACK_D2H_SHMAP: dict = {}


def _pack_d2h_shmap(mesh, duplex: bool, k_pad: int):
    """shard_map form of the packed-D2H epilogue: each device compacts
    ITS bucket block locally — zero collectives by construction, the
    same liveness argument as parallel.sharded._shmap_pipeline (a
    GSPMD-partitioned epilogue materialises AllGather/AllReduce from
    the cross-shard cumsum, and concurrent launches deadlock the
    rendezvous). Wire layout identical to the vmap form."""
    key = (mesh, duplex, k_pad)
    fn = _PACK_D2H_SHMAP.get(key)
    if fn is None:
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec as P

        def local(nf, nm, ids, *fields):
            f = fields[2].shape[1]  # cons_valid: (per, F)
            n_out = jnp.clip(nm if duplex else nf, 0, f)
            packed = _shard_pack_body(n_out, *fields, k_pad=k_pad)
            return {
                "n_families": nf,
                "n_molecules": nm,
                "ids16": ids_to_u16(ids),
                **packed,
            }

        fn = jax.jit(
            shard_map(
                local, mesh=mesh,
                in_specs=P("data"), out_specs=P("data"),
                check_rep=False,
            )
        )
        _PACK_D2H_SHMAP[key] = fn
    return fn


def d2h_pack_ok(capacity: int, per_base_tags: bool) -> bool:
    """Gate for the packed return path: ids/depths must fit u16
    (capacity bounds both), and per-base-tag runs fetch the full
    (F, L) depth/err matrices the compact layout does not carry."""
    return capacity < (1 << 16) and not per_base_tags


# ------------------------------------------------- ids-lane u16 rung
#
# The remaining fetch-side wire-diet rung the ROADMAP named: when the
# FULL packed-D2H compaction is gated off (per-base-tag runs fetch the
# (F, L) matrices the compact layout cannot carry), the unpacked fetch
# still moved BOTH (B, R) i32 id arrays even though the scatter only
# ever consumes one (molecule_id in duplex else family_id — exactly the
# selection the full rung already makes). This partial rung fetches
# only the consumed array, biased by one into u16 like the full rung's
# ids16 lane: 8 id bytes/row -> 2. Gated per class at capacity >= 2**16
# (dense ids live in [-1, capacity)) with a ledgered packed_fallback
# event like every other rung; d2h_packed="off" keeps the honest
# fully-unpacked A/B baseline.

IDS16_FETCH_KEYS = tuple(
    k for k in FETCH_KEYS if k not in ("family_id", "molecule_id")
) + ("ids16",)

# THE u16 id-lane convention, one pack/unpack pair on purpose: dense
# ids live in [-1, capacity), so +IDS16_BIAS fits them into u16 when
# the capacity gate holds. Both d2h rungs (the full compaction's ids16
# lane and the partial ids-lane rung) and both host reconstructions go
# through these two functions — the bias, sentinel and dtypes changing
# in one site but not another would silently break the round-trip's
# byte identity.
IDS16_BIAS = 1


def ids_to_u16(ids):
    """Device-side half of the u16 id-lane convention (jit-traceable)."""
    import jax.numpy as jnp

    return (ids + IDS16_BIAS).astype(jnp.uint16)


def ids_from_u16(a) -> np.ndarray:
    """Host-side inverse: exact i32 reconstruction of the id array."""
    return np.asarray(a).astype(np.int32) - IDS16_BIAS


_IDS16_FN = None


def _ids16_fn():
    global _IDS16_FN
    if _IDS16_FN is None:
        import jax

        _IDS16_FN = jax.jit(ids_to_u16)
    return _IDS16_FN


def ids16_ok(capacity: int) -> bool:
    """Gate for the ids-lane u16 rung: biased dense ids (<= capacity)
    must fit u16 — the same bound as the full rung's ids16 lane."""
    return capacity < (1 << 16)


def d2h_rung_for_class(
    d2h_on: bool, ids16_want: bool, capacity: int, per_base_tags: bool
) -> tuple[str, str | None]:
    """THE per-class return-path rung decision, one pure function so
    the gate logic is unit-testable without a device and the dispatch
    site cannot drift from it. Returns (rung, fallback_reason):

      "packed"  full consensus-only compaction (d2h_pack_ok holds for
                this class)
      "ids16"   partial rung — full compaction gated off (per-base
                tags / capacity) but the consumed id array still packs
                u16
      "off"     fully unpacked; fallback_reason names the ledgered
                packed_fallback when a wanted rung was refused
                (capacity >= 2**16 overflows the u16 lanes — the full
                rung's established jumbo reason when it was on, the
                ids-lane reason when only the partial rung was in
                play), None when the caller asked for off
    """
    if d2h_on:
        if d2h_pack_ok(capacity, per_base_tags):
            return "packed", None
        # the class capacity defeated the full rung; the same u16
        # bound defeats the ids lane, so this is always a full falloff
        return "off", "jumbo-class-capacity-overflows-u16"
    if ids16_want:
        if ids16_ok(capacity):
            return "ids16", None
        return "off", "ids-lane-overflows-u16"
    return "off", None


def pack_ids_u16(out: dict, duplex: bool) -> dict:
    """Replace the pipeline output's two id arrays with the ONE the
    scatter consumes, biased into u16 on device (tiny jit, no static
    args — never a pipeline recompile)."""
    ids = out["molecule_id" if duplex else "family_id"]
    d = {k: v for k, v in out.items() if k not in ("family_id", "molecule_id")}
    d["ids16"] = _ids16_fn()(ids)
    return d


def d2h_unit_bound(spec) -> tuple[int, int]:
    """(mult, f) of the per-bucket output-unit bound ``min(mult *
    n_unique, f)`` — the grouping invariant both the k_pad sizing and
    the host unpack's overflow check rest on."""
    g, duplex = spec.grouping, spec.consensus.mode == "duplex"
    if duplex:
        mult = 2 if (g.mate_aware and g.paired) else 1
        f = spec.m_max or 0
    else:
        mult = (2 if g.paired else 1) * (2 if g.mate_aware else 1)
        f = spec.f_max or 0
    return mult, f


def d2h_k_pad(cbuckets, spec, n_shards: int = 1) -> int:
    """Static PER-SHARD row bound of the compacted consensus transfer:
    per bucket, output units are bounded by mult * n_unique (the
    invariant spec_for_buckets' f_max/m_max sizing already rests on),
    summed over each mesh shard's contiguous bucket block (real
    buckets sit in slots [0, len(cbuckets)); mesh-pad buckets beyond
    them are empty and bound 0) and rounded to a power of two so the
    epilogue's compile count stays bounded. The host-side unpack
    re-checks the fetched counts against this bound per shard and
    fails loudly on violation."""
    from duplexumiconsensusreads_tpu.ops.pipeline import _pow2

    mult, f = d2h_unit_bound(spec)
    f = f or cbuckets[0].capacity
    n_stacked = len(cbuckets) + (-len(cbuckets)) % max(n_shards, 1)
    per = max(n_stacked // max(n_shards, 1), 1)
    bound = 0
    for s in range(max(n_shards, 1)):
        bound = max(
            bound,
            sum(
                min(mult * bk.n_unique_umi, f)
                for bk in cbuckets[s * per : (s + 1) * per]
            ),
        )
    # the per*f cap is compile-churn-free even though it isn't a power
    # of two: the vmapped pipeline's jit is already keyed on the
    # class's (B, f) shapes, so a k_pad equal to (B/S)*f introduces no
    # compile key the dispatch didn't pay for anyway
    return min(_pow2(max(bound, 1)), per * f)


def pack_fetch_outputs(
    out: dict, spec, k_pad: int, n_shards: int = 1, mesh=None
) -> dict:
    """Apply the packed-D2H epilogue to a sharded pipeline output dict;
    returns the compact device dict (PACKED_FETCH_KEYS). ``n_shards``
    is the mesh's data-axis size: the compaction runs per shard (see
    the module comment — a cross-shard compaction deadlocks concurrent
    sharded dispatches) and the compact rows come back as S blocks of
    ``k_pad`` rows each. Pass the live ``mesh`` on multi-device runs:
    the epilogue then compiles as a shard_map (guaranteed
    collective-free); without it the vmap form is used — identical
    wire bytes, only safe when programs never run concurrently across
    devices (single device, or the whole-file executor's sequential
    dispatch)."""
    duplex = spec.consensus.mode == "duplex"
    if (
        mesh is not None
        and mesh.devices.size > 1
        and "cycle" not in mesh.axis_names
    ):
        fn = _pack_d2h_shmap(mesh, duplex, k_pad)
        return fn(
            out["n_families"], out["n_molecules"],
            out["molecule_id" if duplex else "family_id"],
            *(out[k] for k in _PACK_FIELDS),
        )
    return _pack_d2h_fn()(out, duplex, k_pad, n_shards)


def _unpack_2bit_np(packed: np.ndarray, l: int) -> np.ndarray:
    """Host mirror of kernels.encoding.pack_2bit."""
    shifts = np.arange(4, dtype=np.uint8) * 2
    codes = (packed[..., None] >> shifts) & 3
    return codes.reshape(*packed.shape[:-1], -1)[..., :l].astype(np.uint8)


def unpack_fetch_outputs(fetched: dict, cbuckets, spec, n_shards: int = 1) -> dict:
    """Host-side reconstruction of the exact unpacked FETCH_KEYS arrays
    from a packed-D2H fetch (dtypes included — byte identity of the
    final output rests on the scatter seeing indistinguishable inputs).
    Rows past each bucket's n_out reconstruct as zeros/invalid; the
    scatter's keep mask never reads them. A dict without the packed
    marker key passes through untouched. ``n_shards`` must match the
    pack side's: the wire rows arrive as S per-shard k_pad blocks."""
    from duplexumiconsensusreads_tpu.constants import BASE_N, NO_CALL_QUAL

    if "cons_q" not in fetched:
        if "ids16" in fetched:
            # ids-lane u16 rung (full compaction off): reconstruct the
            # one consumed id array at its exact i32 dtype; everything
            # else crossed unpacked
            duplex = spec.consensus.mode == "duplex"
            out = {k: v for k, v in fetched.items() if k != "ids16"}
            out["molecule_id" if duplex else "family_id"] = ids_from_u16(
                fetched["ids16"]
            )
            return out
        return fetched
    duplex = spec.consensus.mode == "duplex"
    f = (spec.m_max if duplex else spec.f_max) or cbuckets[0].capacity
    nf = np.asarray(fetched["n_families"])
    nm = np.asarray(fetched["n_molecules"])
    n_b = nf.shape[0]
    rows_wire, l = fetched["cons_q"].shape
    if n_b % max(n_shards, 1) or rows_wire % max(n_shards, 1):
        raise D2hCompactionOverflow(
            f"packed d2h shard mismatch: {n_b} buckets / {rows_wire} "
            f"wire rows not divisible by n_shards={n_shards}"
        )
    per = n_b // n_shards
    k_pad = rows_wire // n_shards
    n_out = np.clip(nm if duplex else nf, 0, f)
    shard_totals = n_out.reshape(n_shards, per).sum(axis=1)
    if (shard_totals > k_pad).any():
        # the grouping invariant the bound rests on was violated —
        # rows were dropped on device; this must never be silent
        s_bad = int(np.argmax(shard_totals > k_pad))
        raise D2hCompactionOverflow(
            f"packed d2h compaction overflow: shard {s_bad} produced "
            f"{int(shard_totals[s_bad])} output rows > bound {k_pad} "
            f"(grouping invariant violated)"
        )
    offs = np.concatenate([[0], np.cumsum(n_out)])
    total = int(offs[-1])
    b_of = np.repeat(np.arange(n_b), n_out)
    j_of = np.arange(total) - offs[b_of]
    # wire source row of each live output row: its shard's k_pad block
    # base plus the bucket-run offset WITHIN the shard
    shard_of = b_of // per
    src = shard_of * k_pad + np.arange(total) - offs[shard_of * per]

    q = np.asarray(fetched["cons_q"])[src]
    b2 = _unpack_2bit_np(np.asarray(fetched["cons_b2"])[src], l)
    none = q == 0
    base_rows = np.where(none, np.uint8(BASE_N), b2)
    qual_rows = np.where(none, np.uint8(NO_CALL_QUAL), q)
    flags = np.asarray(fetched["cons_flags"])[src]
    dstats = np.asarray(fetched["cons_dstats"])[src].astype(np.int32)
    pair_rows = np.asarray(fetched["cons_pair"])[src]
    cons_base = np.zeros((n_b, f, l), np.uint8)
    cons_qual = np.zeros((n_b, f, l), np.uint8)
    cons_valid = np.zeros((n_b, f), bool)
    depth_max = np.zeros((n_b, f), np.int32)
    depth_min_pos = np.zeros((n_b, f), np.int32)
    cons_mate = np.zeros((n_b, f), np.uint8)
    cons_end = np.zeros((n_b, f), np.uint8)
    cons_pair = np.zeros((n_b, f), np.int32)
    cons_base[b_of, j_of] = base_rows
    cons_qual[b_of, j_of] = qual_rows
    cons_valid[b_of, j_of] = (flags & 1).astype(bool)
    cons_mate[b_of, j_of] = (flags >> 1) & 1
    cons_end[b_of, j_of] = (flags >> 2) & 1
    depth_max[b_of, j_of] = dstats[:, 0]
    depth_min_pos[b_of, j_of] = dstats[:, 1]
    cons_pair[b_of, j_of] = pair_rows
    return {
        "n_families": nf,
        "n_molecules": nm,
        ("molecule_id" if duplex else "family_id"): ids_from_u16(
            fetched["ids16"]
        ),
        "cons_valid": cons_valid,
        "cons_base": cons_base,
        "cons_qual": cons_qual,
        "depth_max": depth_max,
        "depth_min_pos": depth_min_pos,
        "cons_mate": cons_mate,
        "cons_pair": cons_pair,
        "cons_end": cons_end,
    }


def d2h_logical_nbytes(fetched: dict, cbuckets, spec) -> int:
    """Bytes the UNPACKED fetch of the same chunk class would have
    moved — the packed-D2H ledger records' ``logical`` side. Exact
    integer arithmetic over the FETCH_KEYS shapes/dtypes (both (B, R)
    i32 id arrays, two (B,) i32 count vectors, and the (B, F[, L])
    consensus-row tensors)."""
    if "cons_q" not in fetched:
        if "ids16" in fetched:
            # ids-lane u16 rung: the unpacked fetch would have moved
            # BOTH (B, R) i32 id arrays where the wire carried one u16
            ids = fetched["ids16"]
            n_ids = int(np.prod(ids.shape))
            wire = sum(
                v.nbytes for v in fetched.values() if hasattr(v, "nbytes")
            )
            return wire - ids.nbytes + 2 * n_ids * 4
        return sum(v.nbytes for v in fetched.values() if hasattr(v, "nbytes"))
    duplex = spec.consensus.mode == "duplex"
    f = (spec.m_max if duplex else spec.f_max) or cbuckets[0].capacity
    n_b = np.asarray(fetched["n_families"]).shape[0]
    r = np.asarray(fetched["ids16"]).shape[1]
    _, l = fetched["cons_q"].shape
    # family_id + molecule_id (i32) + n_families + n_molecules (i32) +
    # cons_valid (bool) + cons_base/cons_qual (u8) + depth_max/
    # depth_min_pos (i32) + cons_mate/cons_end (u8) + cons_pair (i32)
    return 2 * n_b * r * 4 + 2 * n_b * 4 + n_b * f * (1 + 2 * l + 8 + 2 + 4)


# In-pipeline measurements on v5e (BENCH_r02/r03 stderr journals, full
# bench geometry, 527k reads): matmul 2.39M reads/s > blockseg 1.70M >
# runsum 1.43M (runsum also loses accuracy to prefix cancellation —
# rejected outright) > segment/pallas (r2: 1.26x/1.59x slower). On
# XLA-CPU the ranking INVERTS: blockseg 74.6k reads/s vs matmul 17.8k
# (4.2x) — dense one-hot padding FLOPs are nearly free on the MXU but
# real work on a scalar core. Hence per-backend defaults; see
# tools/tune_ssc.py for the journal.
DEFAULT_SSC_METHOD = "matmul"
DEFAULT_SSC_METHOD_CPU = "blockseg"


def default_ssc_method() -> str:
    import jax

    return (
        DEFAULT_SSC_METHOD_CPU
        if jax.default_backend() == "cpu"
        else DEFAULT_SSC_METHOD
    )


def packed_io_ok(consensus: ConsensusParams) -> bool:
    """Packed base|qual transfer is lossless iff the input-qual cap
    fits the 6-bit payload (ops.pipeline.PACKED_QUAL_MAX)."""
    from duplexumiconsensusreads_tpu.ops.pipeline import PACKED_QUAL_MAX

    return (
        consensus.max_input_qual <= PACKED_QUAL_MAX
        and consensus.min_input_qual <= PACKED_QUAL_MAX
    )


def partition_buckets(
    buckets,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    ssc_method: str | None = None,
    packed_io: bool = False,
    per_base_counts: bool = False,
    qual_alphabet: tuple | None = None,
):
    """Split buckets into dispatch classes of identical geometry+strategy.

    Returns [(class_buckets, PipelineSpec)]. Classes are keyed by
    (capacity, preclustered, pow2(unique-count)): capacity separates
    jumbo buckets (stack_buckets needs homogeneous shapes), the
    unique-count class keeps sparse buckets from paying dense buckets'
    u_max/f_max geometry, and preclustered buckets run with EXACT
    grouping — their UMIs are already relabeled to the directional
    cluster seed by the host (bucketing/buckets.py), so re-clustering
    on device could over-merge seeds whose aggregated counts now
    satisfy the directional edge condition.

    ``packed_io=True`` requests the H2D wire packing; the rung is a
    PER-CLASS decision made here (never a mid-dispatch failure):

      sub-byte  ``qual_alphabet`` provided and it fits a dictionary
                (ops.pipeline.subbyte_qbits_for) — 5 or 7 bits/cycle,
                lossless at any qual cap (the dictionary is exact)
      byte      alphabet absent/overflowing but the 6-bit payload is
                lossless (packed_io_ok)
      off       bucket-local pos ids would overflow the u16 lane
                (capacity >= 2**16), or no lossless rung exists —
                the class runs unpacked with a ledgered
                ``packed_fallback`` event instead of poisoning the
                bucket through the retry/isolation ladder
    """
    import dataclasses as _dc

    from duplexumiconsensusreads_tpu.ops.pipeline import (
        spec_for_buckets,
        subbyte_qbits_for,
    )
    from duplexumiconsensusreads_tpu.telemetry import trace as _telemetry

    if ssc_method is None:
        ssc_method = default_ssc_method()
    classes: dict[tuple, list] = {}
    for bk in buckets:
        ucls = 1 << max(bk.n_unique_umi - 1, 0).bit_length()
        classes.setdefault((bk.capacity, bk.preclustered, ucls), []).append(bk)
    byte_ok = packed_io_ok(consensus)
    out = []
    for key in sorted(classes):
        cbuckets = classes[key]
        g = _dc.replace(grouping, strategy="exact") if key[1] else grouping
        packed, qbits, lut = packed_io, None, None
        if packed_io:
            if key[0] > (1 << 16):
                # the u16 pos lane can't carry this class's dense ids
                # (ids < capacity, so capacity 2**16 still fits): run
                # it unpacked (capacity check at partition time — the
                # old pack_stacked ValueError surfaced inside the
                # retry ladder and poisoned the bucket)
                packed = False
                _telemetry.emit_event(
                    "packed_fallback", scope="h2d",
                    reason="pos-ids-overflow-u16", capacity=key[0],
                )
            elif qual_alphabet is not None and subbyte_qbits_for(
                len(qual_alphabet)
            ):
                qbits = subbyte_qbits_for(len(qual_alphabet))
                lut = tuple(qual_alphabet)
            elif not byte_ok:
                packed = False
                _telemetry.emit_event(
                    "packed_fallback", scope="h2d",
                    reason="input-qual-cap-overflows-6-bit",
                    max_input_qual=consensus.max_input_qual,
                )
        out.append(
            (
                cbuckets,
                spec_for_buckets(
                    cbuckets, g, consensus, ssc_method, packed_io=packed,
                    per_base_counts=per_base_counts,
                    packed_qbits=qbits, qual_lut=lut,
                ),
            )
        )
    return out


def sort_consensus_outputs(cb, cq, cd, fp, fu, mate, pair, *extra):
    """Order consensus rows by (pos_key, UMI) so the output BAM stays
    coordinate-sorted (class-wise dispatch visits buckets out of
    genomic order; downstream tools and our own streaming executor
    expect non-decreasing positions). Extra row-aligned arrays (e.g.
    per-base depth) ride along under the same order."""
    order = np.lexsort((*reversed(umi_sort_keys(fu)), fp))
    return (
        cb[order], cq[order], cd[order], fp[order], fu[order],
        mate[order], pair[order],
        *(x[order] for x in extra),
    )


def call_batch_tpu(
    batch: ReadBatch,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    capacity: int = 2048,
    n_devices: int | None = None,
    report: RunReport | None = None,
    cycle_shards: int = 1,
    per_base_tags: bool = False,
):
    """Run one host ReadBatch through the bucketed mesh pipeline.

    Returns (cons_base, cons_qual, cons_dstats, cons_valid, fam_pos,
    fam_umi, cons_mate, cons_pair) concatenated over buckets in global
    dense-output order; per_base_tags=True appends TWO elements — the
    (n, L) per-base depth and disagreement-count matrices (fetched
    off-device only on request — they are the transfer the FETCH_KEYS
    discipline exists to avoid).
    """
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import sharded_pipeline

    rep = report or RunReport()
    duplex = consensus.mode == "duplex"

    t0 = time.monotonic()
    fb: dict = {}
    buckets = build_buckets(batch, capacity=capacity, grouping=grouping, counters=fb)
    for k, v in fb.items():
        setattr(rep, k, getattr(rep, k) + v)
    rep.n_buckets = len(buckets)
    rep.seconds["bucketing"] = round(time.monotonic() - t0, 4)
    if not buckets:
        u = batch.umi_len
        z = np.zeros
        empty = (
            z((0, batch.read_len), np.uint8),
            z((0, batch.read_len), np.uint8),
            z((0, batch.read_len), np.int32),
            z((0,), bool),
            z((0,), np.int64),
            z((0, u), np.uint8),
            z((0,), np.uint8),
            z((0,), np.int64),
            z((0,), np.uint8),
        )
        return empty + (
            (z((0, batch.read_len), np.int32),) * 2 if per_base_tags else ()
        )

    # local devices: the executors are host-local programs (each host
    # streams its own input partition), so under an initialized
    # multi-controller runtime the mesh must never span other hosts
    n_dev = n_devices or len(jax.local_devices())
    mesh = make_mesh(n_dev, cycle_shards=cycle_shards, devices=jax.local_devices())
    rep.n_devices = n_dev
    n_data = max(n_dev // max(cycle_shards, 1), 1)

    # (genomic tile, family-size) bucketing, second axis: buckets are
    # classed by (capacity, preclustered, pow2 unique-key count) so a
    # sparse-coverage bucket doesn't pay the dense buckets' u_max/f_max
    # geometry and jumbo/preclustered buckets get their own compiles.
    # All classes are dispatched before any is drained (async overlap).
    part = partition_buckets(
        buckets, grouping, consensus, packed_io=packed_io_ok(consensus),
        per_base_counts=per_base_tags,
    )

    t0 = time.monotonic()
    pending = []
    for cbuckets, cspec in part:
        stacked = stack_buckets(cbuckets, multiple_of=n_data)
        if cspec.packed_io:
            from duplexumiconsensusreads_tpu.ops.pipeline import pack_stacked

            pack_stacked(stacked, cspec)
        pending.append(
            (
                cbuckets,
                start_fetch(
                    sharded_pipeline(stacked, cspec, mesh),
                    extra=("cons_depth", "cons_err") if per_base_tags else (),
                ),
            )
        )
    rep.seconds["device_dispatch"] = round(time.monotonic() - t0, 4)

    t0 = time.monotonic()
    parts = []
    pair_base = 0
    for cbuckets, out in pending:
        out = fetch_outputs(out)
        n_real = len(cbuckets)
        rep.n_families += int(out["n_families"][:n_real].sum())
        rep.n_molecules += int(out["n_molecules"][:n_real].sum())
        parts.append(
            scatter_bucket_outputs(
                out, cbuckets, batch, duplex, pair_base=pair_base,
                want_depth=per_base_tags,
            )
        )
        pair_base += n_real
    rep.seconds["device_pipeline_and_scatter"] = round(time.monotonic() - t0, 4)
    rep.n_size_classes = len(part)

    cols = sort_consensus_outputs(
        *(np.concatenate(x) for x in zip(*parts))
    )
    cb = cols[0]
    return (*cols[:3], np.ones(len(cb), bool), *cols[3:])


def call_batch_cpu(
    batch: ReadBatch,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    report: RunReport | None = None,
    per_base_tags: bool = False,
):
    """Oracle (reference-math) path over the whole batch."""
    from duplexumiconsensusreads_tpu.ops import ConsensusCaller, UmiGrouper

    rep = report or RunReport()
    t0 = time.monotonic()
    fams: FamilyAssignment = UmiGrouper(grouping, backend="cpu")(batch)
    cons = ConsensusCaller(consensus, backend="cpu")(batch, fams)
    rep.seconds["cpu_pipeline"] = round(time.monotonic() - t0, 4)
    rep.n_families = int(fams.n_families)
    rep.n_molecules = int(fams.n_molecules)

    duplex = consensus.mode == "duplex"
    ids = np.asarray(fams.molecule_id if duplex else fams.family_id)
    n_out = int(fams.n_molecules if duplex else fams.n_families)
    fam_pos, fam_umi = representative_per_family(
        ids,
        np.asarray(batch.valid, bool),
        np.asarray(batch.pos_key),
        np.asarray(batch.umi),
        n_fam=n_out,
    )
    cv = np.asarray(cons.valid, bool)
    from duplexumiconsensusreads_tpu.io.convert import depth_stats

    # per-output-row mate/pair metadata (host twin of the device
    # pipeline's segment-min reduction — constant within a row's reads)
    e2 = np.asarray(batch.frag_end, bool)
    s = np.asarray(batch.strand_ab, bool)
    pid = np.asarray(fams.pair_id).astype(np.int64)
    if duplex:
        mate_read = e2.astype(np.int64)
        pair_read = pid
    elif grouping.paired:
        mate_read = (e2 ^ ~s).astype(np.int64)
        pair_read = pid * 2 + (~s).astype(np.int64)
    else:
        # unpaired ss families (molecule, end) can mix strands: label
        # rows by fragment end (mirrors the device pipeline exactly)
        mate_read = e2.astype(np.int64)
        pair_read = pid
    sel = np.asarray(batch.valid, bool) & (ids >= 0)
    big = np.iinfo(np.int64).max
    mate = np.full(n_out, big, np.int64)
    pair = np.full(n_out, big, np.int64)
    np.minimum.at(mate, ids[sel], mate_read[sel])
    np.minimum.at(pair, ids[sel], pair_read[sel])
    mate = np.where(cv, np.minimum(mate, 1), 0).astype(np.uint8)
    pair = np.where(cv & (pair < big), pair, -1)
    # unit fragment end (host twin of the pipeline's cons_end)
    endv = np.full(n_out, big, np.int64)
    np.minimum.at(endv, ids[sel], e2[sel].astype(np.int64))
    endv = np.where(cv, np.minimum(endv, 1), 0).astype(np.uint8)

    res = (
        np.asarray(cons.bases)[cv],
        np.asarray(cons.quals)[cv],
        depth_stats(np.asarray(cons.depth))[cv],
        np.ones(int(cv.sum()), bool),
        fam_pos[cv],
        fam_umi[cv],
        mate[cv],
        pair[cv],
        endv[cv],
    )
    if per_base_tags:
        res = res + (np.asarray(cons.depth)[cv], np.asarray(cons.err)[cv])
    return res


def resolve_mate_aware(
    grouping: GroupingParams, info: dict, setting: str = "auto"
) -> GroupingParams:
    """Resolve the CLI's --mate-aware setting against the loaded input.

    auto = mate-aware exactly when the input's valid paired reads span
    both read numbers (``info["mixed_mates"]``) — single-end and
    split-by-read-number inputs keep the classic one-family-per-strand
    semantics, which mate-aware grouping provably reproduces anyway
    when no second-end reads exist.
    """
    if setting not in ("auto", "on", "off"):
        raise ValueError(f"mate_aware must be auto/on/off, got {setting!r}")
    on = bool(info.get("mixed_mates")) if setting == "auto" else setting == "on"
    if on == grouping.mate_aware:
        return grouping
    return dataclasses.replace(grouping, mate_aware=on)


def count_consensus_pairs(recs) -> int:
    """Complete consensus R1+R2 pairs (singleton mates carry read-number
    flags too, but with FLAG_MATE_UNMAPPED instead of PROPER_PAIR)."""
    from duplexumiconsensusreads_tpu.io.bam import (
        FLAG_PAIRED,
        FLAG_PROPER_PAIR,
        FLAG_READ1,
    )

    fl = np.asarray(recs.flags)
    want = FLAG_PAIRED | FLAG_PROPER_PAIR | FLAG_READ1
    return int(((fl & want) == want).sum())


def call_consensus_file(
    in_path: str,
    out_path: str,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    backend: str = "tpu",
    capacity: int = 2048,
    n_devices: int | None = None,
    report_path: str | None = None,
    profile_dir: str | None = None,
    cycle_shards: int = 1,
    mate_aware: str = "auto",
    max_reads: int = 0,
    per_base_tags: bool = False,
    read_group: str = "A",
    write_index: bool = False,
    ref_projected: bool = False,
    umi_whitelist=None,  # (W, U) u8 codes (io.convert.load_umi_whitelist)
    umi_max_mismatches: int = 1,
) -> RunReport:
    """End-to-end: read BAM/npz → consensus → write consensus BAM.

    Output is coordinate-sorted by construction (records emit in dense
    family-id order == ascending (pos_key, UMI)) and the header says so;
    write_index=True additionally writes the standard .bai beside it.
    """
    from duplexumiconsensusreads_tpu.io import (
        consensus_to_records,
        load_input,
        write_bam,
    )
    from duplexumiconsensusreads_tpu.io.bam import (
        derive_output_header,
        reorder_records,
        unique_read_group_id,
    )

    rep = RunReport(backend=backend)
    duplex = consensus.mode == "duplex"

    t0 = time.monotonic()
    # the mixed-mate warning only applies when mate-aware stays off
    # (auto-on and forced-on runs HANDLE those families)
    header, batch, info = load_input(
        in_path, duplex=duplex, warn_mixed=(mate_aware == "off"),
        ref_projected=ref_projected, mate_aware=mate_aware,
        umi_whitelist=umi_whitelist, umi_max_mismatches=umi_max_mismatches,
    )
    grouping = resolve_mate_aware(grouping, info, mate_aware)
    proj0 = info.get("ref_projection")
    if proj0 is not None and proj0.mate_split != grouping.mate_aware:
        # both sides derive the decision from the same mixed-mates
        # signal; a divergence would mis-key every emission lookup
        raise RuntimeError(
            "ref-projection mate split diverged from resolved grouping"
        )
    rep.mate_aware = grouping.mate_aware
    rep.n_records = info["n_records"]
    rep.n_dropped = (
        info.get("n_dropped_no_umi", 0)
        + info.get("n_dropped_umi_len", 0)
        + info.get("n_dropped_flag", 0)
        + info.get("n_dropped_cigar", 0)
    )
    rep.n_mixed_mate_families = info.get("n_mixed_mate_families", 0)
    rep.n_rescued_cigar = info.get("n_rescued_cigar", 0)
    rep.n_dropped_cigar_ab = info.get("n_dropped_cigar_ab", 0)
    rep.n_dropped_cigar_ba = info.get("n_dropped_cigar_ba", 0)
    rep.n_projected_reads = info.get("n_projected_reads", 0)
    rep.n_projection_fallback_reads = info.get("n_projection_fallback_reads", 0)
    rep.n_projection_fallback_groups = info.get(
        "n_projection_fallback_groups", 0
    )
    rep.n_projection_unanchored_reads = info.get(
        "n_projection_unanchored_reads", 0
    )
    rep.n_umi_corrected = info.get("n_umi_corrected", 0)
    rep.n_dropped_whitelist = info.get("n_dropped_whitelist", 0)
    rep.n_valid_reads = int(np.asarray(batch.valid).sum())
    if max_reads > 0:
        from duplexumiconsensusreads_tpu.io.convert import downsample_families

        rep.n_downsampled_reads = downsample_families(batch, max_reads)
    rep.seconds["read_input"] = round(time.monotonic() - t0, 4)

    prof = None
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
        prof = profile_dir
    try:
        if backend == "tpu":
            cb, cq, cd, cv, fp, fu, mate, pair, end, *rest = call_batch_tpu(
                batch, grouping, consensus, capacity, n_devices, rep,
                cycle_shards=cycle_shards, per_base_tags=per_base_tags,
            )
        elif backend == "cpu":
            cb, cq, cd, cv, fp, fu, mate, pair, end, *rest = call_batch_cpu(
                batch, grouping, consensus, rep, per_base_tags=per_base_tags
            )
        else:
            raise ValueError(f"unknown backend {backend!r}")
    finally:
        if prof:
            import jax

            jax.profiler.stop_trace()

    t0 = time.monotonic()
    # collision-free id FIRST: the RG:Z tags must match the header @RG
    read_group = unique_read_group_id(header.text, read_group)
    out_recs = consensus_to_records(
        cb, cq, cd, cv, fp, fu, duplex=duplex,
        cons_mate=mate, cons_pair=pair, paired_out=grouping.mate_aware,
        cons_pdepth=rest[0] if rest else None,
        cons_perr=rest[1] if rest else None,
        read_group=read_group,
        proj=info.get("ref_projection"),
        cons_end=end,
    )
    if info.get("ref_projection") is not None:
        # projected POS moves to the first called reference column, so
        # family-id emission order is no longer guaranteed coordinate
        # order — restore it (stable: equal positions keep UMI order)
        out_recs = reorder_records(
            out_recs,
            np.lexsort(
                (np.asarray(out_recs.pos), np.asarray(out_recs.ref_id))
            ),
        )
    header_out = derive_output_header(
        header, sort_order="coordinate", rg_id=read_group
    )
    write_bam(out_path, header_out, out_recs)
    if write_index:
        # BAI unless a header contig exceeds its 2^29 coordinate space,
        # then the CSI generalization (depth sized to the contig)
        if max(header_out.ref_lengths, default=0) > (1 << 29):
            from duplexumiconsensusreads_tpu.io.csi import build_csi

            build_csi(out_path)
        else:
            from duplexumiconsensusreads_tpu.io.bai import build_bai

            build_bai(out_path)
    rep.n_consensus = len(out_recs)
    rep.n_consensus_pairs = count_consensus_pairs(out_recs)
    rep.seconds["write_output"] = round(time.monotonic() - t0, 4)

    if report_path:
        write_report(rep, report_path)
    return rep
