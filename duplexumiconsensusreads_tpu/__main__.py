import sys

from duplexumiconsensusreads_tpu.cli import main

sys.exit(main())
