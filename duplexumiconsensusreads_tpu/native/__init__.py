"""ctypes binding to the native BAM loader (libdutbam.so).

Lazy build-on-first-use: if the shared library is missing and a C++
toolchain exists, `make` is invoked once in this directory. Everything
degrades gracefully — ``get_lib()`` returns None when the native path
is unavailable and callers fall back to the pure-Python codec.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdutbam.so")
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False

_c_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
_c_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_c_u16p = np.ctypeslib.ndpointer(np.uint16, flags="C_CONTIGUOUS")
_c_i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s"],
            cwd=_DIR,
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_SO)
    except Exception:
        return False


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.dut_bgzf_usize.restype = ctypes.c_long
    lib.dut_bgzf_usize.argtypes = [_c_u8p, ctypes.c_long]
    lib.dut_bgzf_decompress.restype = ctypes.c_long
    lib.dut_bgzf_decompress.argtypes = [
        _c_u8p, ctypes.c_long, _c_u8p, ctypes.c_long, ctypes.c_int,
    ]
    lib.dut_bgzf_compress_bound.restype = ctypes.c_long
    lib.dut_bgzf_compress_bound.argtypes = [ctypes.c_long]
    lib.dut_bgzf_compress.restype = ctypes.c_long
    lib.dut_bgzf_compress.argtypes = [
        _c_u8p, ctypes.c_long, _c_u8p, ctypes.c_long, ctypes.c_int, ctypes.c_int,
    ]
    lib.dut_bam_chain.restype = ctypes.c_long
    lib.dut_bam_chain.argtypes = [
        _c_u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long),
    ]
    lib.dut_bam_chain_offsets.restype = ctypes.c_long
    lib.dut_bam_chain_offsets.argtypes = [
        _c_u8p, ctypes.c_long, ctypes.c_long, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long), ctypes.c_void_p,
    ]
    lib.dut_bam_scan.restype = ctypes.c_long
    lib.dut_bam_scan.argtypes = [
        _c_u8p, ctypes.c_long,
        ctypes.POINTER(ctypes.c_long), ctypes.POINTER(ctypes.c_int),
        ctypes.POINTER(ctypes.c_int), ctypes.c_void_p,
    ]
    lib.dut_bam_fill.restype = ctypes.c_int
    lib.dut_bam_fill.argtypes = [
        _c_u8p, ctypes.c_long, _c_i64p, ctypes.c_long,
        ctypes.c_int, ctypes.c_int, ctypes.c_int,
        _c_u16p, _c_i32p, _c_i32p, _c_i32p, _c_i32p, _c_i32p,
        _c_u8p, _c_u8p, _c_u8p,
        np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
    ]
    return lib


def get_lib() -> ctypes.CDLL | None:
    """The bound library, building it if needed; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None:
            return _lib
        if _tried:
            return None
        _tried = True
        if not os.path.exists(_SO) and not _build():
            return None
        try:
            _lib = _bind(ctypes.CDLL(_SO))
        except AttributeError:
            # stale .so from an older source revision: rebuild once
            try:
                os.remove(_SO)
            except OSError:
                pass
            if not _build():
                return None
            try:
                _lib = _bind(ctypes.CDLL(_SO))
            except (OSError, AttributeError):
                return None
        except OSError:
            return None
        return _lib


def native_available() -> bool:
    return get_lib() is not None


def bgzf_compress_native(
    data: bytes, level: int = 6, n_threads: int = 0
) -> bytes | None:
    """Parallel BGZF-compress ``data`` (no EOF block); None if the
    native library is unavailable or compression fails."""
    lib = get_lib()
    if lib is None:
        return None
    if not data:
        return b""
    if n_threads <= 0:
        n_threads = min(os.cpu_count() or 1, 16)
    src = np.frombuffer(data, np.uint8)
    cap = lib.dut_bgzf_compress_bound(len(src))
    out = np.empty(max(cap, 1), np.uint8)
    w = lib.dut_bgzf_compress(src, len(src), out, cap, level, n_threads)
    if w < 0:
        return None
    return out[:w].tobytes()
