// Native BAM data loader: multithreaded BGZF decompression + BAM record
// field extraction into caller-preallocated (NumPy) buffers.
//
// This is the framework's native IO runtime — the role pysam/htslib
// plays for the reference's per-family Python loop (BASELINE.json
// north_star), rebuilt for the TPU pipeline's needs: it emits exactly
// the struct-of-arrays layout ReadBatch wants (padded seq/qual code
// matrices, flags, positions, RX strings) so the Python side does zero
// per-record work. The pure-Python codec (io/bgzf.py, io/bam.py) is
// the portable reference implementation it is tested against.
//
// Exposed via a C ABI for ctypes (no pybind11 in this image).

#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

#include <zlib.h>

// Parse one BGZF block header at `off`: fills compressed size and
// uncompressed size. Returns 0, or -1 on malformed input.
static int parse_bgzf_block(const uint8_t* data, long n, long off,
                            long* bsize_out, uint32_t* isize_out) {
  if (off + 18 > n || data[off] != 0x1f || data[off + 1] != 0x8b) return -1;
  if (!(data[off + 3] & 4)) return -1;  // no FEXTRA -> not BGZF
  uint16_t xlen;
  std::memcpy(&xlen, data + off + 10, 2);
  long bsize = -1;
  long p = off + 12, xend = p + xlen;
  if (xend > n) return -1;
  while (p + 4 <= xend) {
    uint8_t si1 = data[p], si2 = data[p + 1];
    uint16_t slen;
    std::memcpy(&slen, data + p + 2, 2);
    if (si1 == 66 && si2 == 67) {
      if (slen != 2 || p + 6 > xend) return -1;
      uint16_t bs;
      std::memcpy(&bs, data + p + 4, 2);
      bsize = (long)bs + 1;
      break;
    }
    p += 4 + slen;
  }
  if (bsize < 12 + 6 + 8 || off + bsize > n) return -1;
  std::memcpy(isize_out, data + off + bsize - 4, 4);
  *bsize_out = bsize;
  return 0;
}

extern "C" {

// ---------------------------------------------------------------- BGZF

// Scan BGZF blocks: returns block count, fills (optional) arrays of
// compressed offset/size and cumulative uncompressed offset.
// Returns -1 on malformed input.
long dut_bgzf_scan(const uint8_t* data, long n, long* c_off, long* c_size,
                   long* u_off) {
  long off = 0, count = 0, total_u = 0;
  while (off < n) {
    long bsize;
    uint32_t isize;
    if (parse_bgzf_block(data, n, off, &bsize, &isize) != 0) return -1;
    if (c_off) c_off[count] = off;
    if (c_size) c_size[count] = bsize;
    if (u_off) u_off[count] = total_u;
    total_u += isize;
    count++;
    off += bsize;
  }
  return count;
}

// Total uncompressed size (for buffer allocation).
long dut_bgzf_usize(const uint8_t* data, long n) {
  long off = 0, total = 0;
  while (off < n) {
    long bsize;
    uint32_t isize;
    if (parse_bgzf_block(data, n, off, &bsize, &isize) != 0) return -1;
    total += isize;
    off += bsize;
  }
  return total;
}

// Decompress all blocks (n_threads-way parallel) into out (size out_cap).
// Returns bytes written or -1.
long dut_bgzf_decompress(const uint8_t* data, long n, uint8_t* out,
                         long out_cap, int n_threads) {
  long n_blocks = dut_bgzf_scan(data, n, nullptr, nullptr, nullptr);
  if (n_blocks < 0) return -1;
  std::vector<long> c_off(n_blocks), c_size(n_blocks), u_off(n_blocks);
  dut_bgzf_scan(data, n, c_off.data(), c_size.data(), u_off.data());
  long total = 0;
  for (long i = 0; i < n_blocks; i++) {
    uint32_t isize;
    std::memcpy(&isize, data + c_off[i] + c_size[i] - 4, 4);
    total += isize;
  }
  if (total > out_cap) return -1;

  std::atomic<long> next{0};
  std::atomic<bool> failed{false};
  auto worker = [&]() {
    for (;;) {
      long i = next.fetch_add(1);
      if (i >= n_blocks || failed.load()) return;
      uint16_t xlen;
      std::memcpy(&xlen, data + c_off[i] + 10, 2);
      const uint8_t* src = data + c_off[i] + 12 + xlen;
      long src_len = c_size[i] - 12 - xlen - 8;
      uint32_t isize;
      std::memcpy(&isize, data + c_off[i] + c_size[i] - 4, 4);
      z_stream zs{};
      if (inflateInit2(&zs, -15) != Z_OK) { failed = true; return; }
      zs.next_in = const_cast<uint8_t*>(src);
      zs.avail_in = (uInt)src_len;
      zs.next_out = out + u_off[i];
      zs.avail_out = (uInt)isize;
      int rc = inflate(&zs, Z_FINISH);
      inflateEnd(&zs);
      if (!((rc == Z_STREAM_END) || (rc == Z_OK && zs.avail_out == 0)) ||
          zs.total_out != isize) {
        failed = true;
        return;
      }
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  if (failed.load()) return -1;
  return total;
}

// BGZF payload cap per block (htslib's choice: leaves headroom so even
// incompressible payloads fit the format's 65536 compressed-block cap
// as one stored-mode deflate sub-block).
static const long kBgzfPayload = 65280;
// Per-block scratch/compacted-output slot: 18-byte BGZF header + worst
// case deflate of 65280 (stored: 5 + 65280) + crc/isize trailer.
static const long kBgzfSlot = 65536;

// Required output capacity for dut_bgzf_compress over n input bytes.
long dut_bgzf_compress_bound(long n) {
  long blocks = n <= 0 ? 0 : (n + kBgzfPayload - 1) / kBgzfPayload;
  return blocks * kBgzfSlot;
}

static long deflate_block(const uint8_t* src, long len, uint8_t* dst,
                          int level) {
  // Deflate one payload into dst+18 (raw stream), returning the TOTAL
  // BGZF block size, or -1. Falls back to stored mode if the
  // compressed form would overflow the 65536 block cap.
  for (int attempt = 0; attempt < 2; attempt++) {
    z_stream zs{};
    int lvl = attempt == 0 ? level : 0;
    if (deflateInit2(&zs, lvl, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK)
      return -1;
    zs.next_in = const_cast<uint8_t*>(src);
    zs.avail_in = (uInt)len;
    zs.next_out = dst + 18;
    zs.avail_out = (uInt)(kBgzfSlot - 18 - 8);
    int rc = deflate(&zs, Z_FINISH);
    long clen = (long)zs.total_out;
    deflateEnd(&zs);
    if (rc != Z_STREAM_END) continue;  // overflow: retry stored
    long bsize = 18 + clen + 8;
    if (bsize > 65536) continue;
    // gzip header with BC FEXTRA subfield carrying (bsize - 1)
    dst[0] = 0x1f; dst[1] = 0x8b; dst[2] = 8; dst[3] = 4;
    std::memset(dst + 4, 0, 5);  // mtime + xfl
    dst[9] = 0xff;               // OS unknown
    dst[10] = 6; dst[11] = 0;    // XLEN
    dst[12] = 66; dst[13] = 67; dst[14] = 2; dst[15] = 0;
    uint16_t bs16 = (uint16_t)(bsize - 1);
    std::memcpy(dst + 16, &bs16, 2);
    uint32_t crc = crc32(0L, Z_NULL, 0);
    crc = crc32(crc, src, (uInt)len);
    uint32_t isize = (uint32_t)len;
    std::memcpy(dst + 18 + clen, &crc, 4);
    std::memcpy(dst + 18 + clen + 4, &isize, 4);
    return bsize;
  }
  return -1;
}

// Compress data into a BGZF block stream (no EOF marker), n_threads
// parallel. out must have dut_bgzf_compress_bound(n) capacity.
// Returns bytes written, or -1.
long dut_bgzf_compress(const uint8_t* data, long n, uint8_t* out,
                       long out_cap, int level, int n_threads) {
  long n_blocks = n <= 0 ? 0 : (n + kBgzfPayload - 1) / kBgzfPayload;
  if (out_cap < n_blocks * kBgzfSlot) return -1;
  std::vector<long> bsizes(n_blocks, -1);
  std::atomic<long> next{0};
  std::atomic<bool> failed{false};
  auto worker = [&]() {
    for (;;) {
      long i = next.fetch_add(1);
      if (i >= n_blocks || failed.load()) return;
      long s = i * kBgzfPayload;
      long len = (s + kBgzfPayload <= n) ? kBgzfPayload : n - s;
      long bs = deflate_block(data + s, len, out + i * kBgzfSlot, level);
      if (bs < 0) { failed = true; return; }
      bsizes[i] = bs;
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  if (failed.load()) return -1;
  // compact the fixed slots into a contiguous stream (in place, left
  // to right: the write cursor never passes the read cursor)
  long w = 0;
  for (long i = 0; i < n_blocks; i++) {
    if (w != i * kBgzfSlot)
      std::memmove(out + w, out + i * kBgzfSlot, bsizes[i]);
    w += bsizes[i];
  }
  return w;
}

// ----------------------------------------------------------------- BAM

// Walk the record chain from `off`: count complete records (up to
// max_records) using only the block_size prefixes, no field parsing.
// Sets *end_off to the byte offset just past the last complete record.
// Returns the record count, or -1 on a malformed block_size. The
// streaming reader uses this to slice whole-record byte runs off its
// rolling buffer without a per-record Python loop. rec_off, when
// non-null (capacity >= max_records), receives each record's offset —
// the linear indexer's per-record walk.
long dut_bam_chain_offsets(const uint8_t* data, long n, long off,
                           long max_records, long* end_off, long* rec_off) {
  long count = 0;
  while (count < max_records && off + 4 <= n) {
    int32_t bsz;
    std::memcpy(&bsz, data + off, 4);
    if (bsz < 33) { *end_off = off; return -1; }  // report the bad record
    if (off + 4 + (long)bsz > n) break;  // trailing partial record
    if (rec_off) rec_off[count] = off;
    off += 4 + bsz;
    count++;
  }
  *end_off = off;
  return count;
}

long dut_bam_chain(const uint8_t* data, long n, long off, long max_records,
                   long* end_off) {
  return dut_bam_chain_offsets(data, n, off, max_records, end_off, nullptr);
}

// Scan decompressed BAM: locate end of header, count records, find max
// l_seq and max RX length. Fills rec_off (record start offsets, incl.
// the 4-byte block_size field) when non-null (must have capacity from a
// prior counting call). Returns record count, or -1 on malformed data.
long dut_bam_scan(const uint8_t* data, long n, long* header_end, int* l_max,
                  int* rx_max, long* rec_off) {
  if (n < 12 || std::memcmp(data, "BAM\x01", 4) != 0) return -1;
  int32_t l_text;
  std::memcpy(&l_text, data + 4, 4);
  if (l_text < 0 || 8 + (long)l_text + 4 > n) return -1;
  long off = 8 + (long)l_text;
  int32_t n_ref;
  std::memcpy(&n_ref, data + off, 4);
  if (n_ref < 0) return -1;
  off += 4;
  for (int32_t r = 0; r < n_ref; r++) {
    if (off + 4 > n) return -1;
    int32_t l_name;
    std::memcpy(&l_name, data + off, 4);
    if (l_name < 1 || off + 4 + (long)l_name + 4 > n) return -1;
    off += 4 + l_name + 4;
  }
  if (header_end) *header_end = off;

  long count = 0;
  int lmax = 0, rxmax = 0;
  while (off < n) {
    if (off + 4 > n) return -1;
    int32_t bsz;
    std::memcpy(&bsz, data + off, 4);
    long rec_start = off;
    long rec_end = off + 4 + bsz;
    // 32 fixed bytes + >=1 NUL-terminated read-name byte: the minimum
    // true record is 37 bytes total, which io/native_reader.py relies
    // on when sizing its offsets buffer at len(data)//37.
    if (bsz < 33 || rec_end > n) return -1;
    if (rec_off) rec_off[count] = rec_start;
    const uint8_t* r = data + off + 4;
    uint8_t l_rn = r[8];
    if (l_rn < 1) return -1;
    uint16_t n_cig;
    std::memcpy(&n_cig, r + 12, 2);
    int32_t l_seq;
    std::memcpy(&l_seq, r + 16, 4);
    if (l_seq < 0) return -1;
    if (l_seq > lmax) lmax = l_seq;
    // aux region: after name, cigar, seq, qual
    long aux = off + 4 + 32 + l_rn + 4L * n_cig + (l_seq + 1) / 2 + l_seq;
    if (aux > rec_end) return -1;  // fixed fields overrun the record
    while (aux + 3 <= rec_end) {
      uint8_t t1 = data[aux], t2 = data[aux + 1], typ = data[aux + 2];
      aux += 3;
      long vlen;
      switch (typ) {
        case 'A': case 'c': case 'C': vlen = 1; break;
        case 's': case 'S': vlen = 2; break;
        case 'i': case 'I': case 'f': vlen = 4; break;
        case 'Z': case 'H': {
          long e = aux;
          while (e < rec_end && data[e] != 0) e++;
          if (e >= rec_end) return -1;  // unterminated string
          if (t1 == 'R' && t2 == 'X' && typ == 'Z') {
            int len = (int)(e - aux);
            if (len > rxmax) rxmax = len;
          }
          vlen = e - aux + 1;
          break;
        }
        case 'B': {
          if (aux + 5 > rec_end) return -1;
          uint8_t sub = data[aux];
          uint32_t cnt;
          std::memcpy(&cnt, data + aux + 1, 4);
          int esz = (sub == 'c' || sub == 'C') ? 1
                    : (sub == 's' || sub == 'S') ? 2
                    : (sub == 'i' || sub == 'I' || sub == 'f') ? 4 : -1;
          if (esz < 0) return -1;
          vlen = 5 + (long)cnt * esz;
          break;
        }
        default: return -1;
      }
      if (vlen < 0 || aux + vlen > rec_end) return -1;
      aux += vlen;
    }
    count++;
    off = rec_end;
  }
  if (l_max) *l_max = lmax;
  if (rx_max) *rx_max = rxmax;
  return count;
}

static const uint8_t kNibbleToCode[16] = {4, 0, 1, 4, 2, 4, 4, 4,
                                          3, 4, 4, 4, 4, 4, 4, 4};

// FNV-1a64 over the raw BAM cigar op words — the per-read CIGAR
// signature the modal-CIGAR input filter groups on. The Python codec
// computes the identical hash over its re-packed op words.
static uint64_t fnv1a64(const uint8_t* p, long len) {
  uint64_t h = 0xcbf29ce484222325ULL;
  for (long i = 0; i < len; i++) {
    h ^= p[i];
    h *= 0x100000001b3ULL;
  }
  return h;
}

// Fill caller-allocated arrays from record offsets. seq gets framework
// base codes padded with 5 (BASE_PAD); qual padded with 0; rx gets the
// raw RX:Z characters zero-padded to rx_cap; cig_hash gets the FNV-1a64
// CIGAR signature (0 for cigar-less records). Parallel over records.
int dut_bam_fill(const uint8_t* data, long n, const long* rec_off,
                 long n_records, int l_cap, int rx_cap, int n_threads,
                 uint16_t* flags, int32_t* ref_id, int32_t* pos,
                 int32_t* next_ref_id, int32_t* next_pos, int32_t* lseq,
                 uint8_t* seq, uint8_t* qual, uint8_t* rx,
                 uint64_t* cig_hash) {
  std::atomic<long> next{0};
  std::atomic<bool> failed{false};
  const long kChunk = 1024;
  auto worker = [&]() {
    for (;;) {
      long start = next.fetch_add(kChunk);
      if (start >= n_records || failed.load()) return;
      long end = start + kChunk < n_records ? start + kChunk : n_records;
      for (long i = start; i < end; i++) {
        long off = rec_off[i];
        int32_t bsz;
        std::memcpy(&bsz, data + off, 4);
        long rec_end = off + 4 + bsz;
        const uint8_t* r = data + off + 4;
        int32_t rid, p0, l_seq, nrid, npos;
        std::memcpy(&rid, r, 4);
        std::memcpy(&p0, r + 4, 4);
        uint8_t l_rn = r[8];
        uint16_t n_cig, flag;
        std::memcpy(&n_cig, r + 12, 2);
        std::memcpy(&flag, r + 14, 2);
        std::memcpy(&l_seq, r + 16, 4);
        std::memcpy(&nrid, r + 20, 4);
        std::memcpy(&npos, r + 24, 4);
        flags[i] = flag;
        ref_id[i] = rid;
        pos[i] = p0;
        next_ref_id[i] = nrid;
        next_pos[i] = npos;
        lseq[i] = l_seq;
        if (l_seq > l_cap) { failed = true; return; }
        cig_hash[i] = n_cig ? fnv1a64(r + 32 + l_rn, 4L * n_cig) : 0;
        const uint8_t* sp = r + 32 + l_rn + 4L * n_cig;
        uint8_t* srow = seq + (long)i * l_cap;
        std::memset(srow, 5, l_cap);  // BASE_PAD
        for (int32_t b = 0; b < l_seq; b++) {
          uint8_t nib = (b & 1) ? (sp[b >> 1] & 0xF) : (sp[b >> 1] >> 4);
          srow[b] = kNibbleToCode[nib];
        }
        const uint8_t* qp = sp + (l_seq + 1) / 2;
        uint8_t* qrow = qual + (long)i * l_cap;
        std::memset(qrow, 0, l_cap);
        if (l_seq > 0 && qp[0] == 0xFF) {
          // quality absent
        } else {
          std::memcpy(qrow, qp, l_seq);
        }
        // aux walk for RX (records were bounds-validated by dut_bam_scan,
        // but stay defensive: any overrun marks failure, never reads OOB)
        uint8_t* xrow = rx + (long)i * rx_cap;
        std::memset(xrow, 0, rx_cap);
        long aux = (qp - data) + l_seq;
        while (aux + 3 <= rec_end) {
          uint8_t t1 = data[aux], t2 = data[aux + 1], typ = data[aux + 2];
          aux += 3;
          long vlen;
          switch (typ) {
            case 'A': case 'c': case 'C': vlen = 1; break;
            case 's': case 'S': vlen = 2; break;
            case 'i': case 'I': case 'f': vlen = 4; break;
            case 'Z': case 'H': {
              long e = aux;
              while (e < rec_end && data[e] != 0) e++;
              if (e >= rec_end) { failed = true; return; }
              if (t1 == 'R' && t2 == 'X' && typ == 'Z') {
                long len = e - aux;
                if (len > rx_cap) { failed = true; return; }
                std::memcpy(xrow, data + aux, len);
              }
              vlen = e - aux + 1;
              break;
            }
            case 'B': {
              if (aux + 5 > rec_end) { failed = true; return; }
              uint8_t sub = data[aux];
              uint32_t cnt;
              std::memcpy(&cnt, data + aux + 1, 4);
              int esz = (sub == 'c' || sub == 'C') ? 1
                        : (sub == 's' || sub == 'S') ? 2
                        : (sub == 'i' || sub == 'I' || sub == 'f') ? 4 : -1;
              if (esz < 0) { failed = true; return; }
              vlen = 5 + (long)cnt * esz;
              break;
            }
            default: failed = true; return;
          }
          if (vlen < 0 || aux + vlen > rec_end) { failed = true; return; }
          aux += vlen;
        }
      }
    }
  };
  int nt = n_threads > 0 ? n_threads : 1;
  std::vector<std::thread> threads;
  for (int t = 0; t < nt; t++) threads.emplace_back(worker);
  for (auto& th : threads) th.join();
  return failed.load() ? -1 : 0;
}

}  // extern "C"
