from duplexumiconsensusreads_tpu.cli.main import CONFIG_PRESETS, build_parser, main

__all__ = ["main", "build_parser", "CONFIG_PRESETS"]
