"""Command-line interface.

`python -m duplexumiconsensusreads_tpu <subcommand>`:

  call      BAM/npz in → consensus BAM out (the reference workflow with
            --backend=tpu|cpu, per BASELINE.json's operator contract)
  simulate  write a truth-aware synthetic BAM (+ truth npz) for testing
  validate  measure consensus error rate of a consensus BAM vs truth
  bench     run the reads/sec benchmark (same as bench.py)

The --config presets map 1:1 onto the five driver benchmark configs
(BASELINE.json `configs`); explicit flags override preset fields.
"""

from __future__ import annotations

import argparse
import json
import sys

from duplexumiconsensusreads_tpu.runtime import knobs

CONFIG_PRESETS = {
    # 1. single-strand consensus, exact grouping (small amplicon)
    "config1": dict(grouping="exact", mode="ss", error_model="none"),
    # 2. directional adjacency grouping, Hamming<=1 (hybrid-capture panel)
    "config2": dict(grouping="adjacency", mode="ss", error_model="none"),
    # 3. duplex consensus, top+bottom merge (ctDNA panel)
    "config3": dict(grouping="adjacency", mode="duplex", error_model="none"),
    # 4. whole-exome duplex, family-size-bucketed shards across the mesh
    "config4": dict(grouping="adjacency", mode="duplex", error_model="none", capacity=4096),
    # 5. per-cycle error-model / quality-recalibrated duplex
    "config5": dict(grouping="adjacency", mode="duplex", error_model="cycle"),
}


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="duplexumi",
        description="TPU-native duplex UMI consensus calling",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    c = sub.add_parser("call", help="group UMIs and call consensus reads")
    c.add_argument(
        "input", nargs="?", default=None,
        help="input BAM (or ReadBatch .npz); optional only with "
        "--status/--wait",
    )
    c.add_argument(
        "-o", "--output", default=None,
        help="output consensus BAM (required except with --status/--wait)",
    )
    # ---- serving-layer client verbs (serve/client.py): one spool
    # directory is the whole protocol — no daemon handshake to lose
    c.add_argument(
        "--submit", action="store_true",
        help="do not run: durably spool this call as a job for a "
        "dut-serve daemon on --spool (prints the job id on stdout). "
        "Streaming params only — the service preempts and resumes jobs "
        "at chunk boundaries",
    )
    c.add_argument(
        "--spool", default=None, metavar="DIR",
        help="service spool directory for --submit/--status/--wait "
        "(default: $DUT_SPOOL)",
    )
    c.add_argument(
        "--priority", type=int, default=1,
        help="--submit priority class (lower = more urgent; FIFO "
        "within a class; default 1)",
    )
    c.add_argument(
        "--status", default=None, metavar="JOB_ID",
        help="print a submitted job's state as JSON and exit "
        "(exit 1 for failed/rejected/unknown)",
    )
    c.add_argument(
        "--wait", default=None, metavar="JOB_ID",
        help="poll until the job reaches a terminal state, then print "
        "its status JSON (see --wait-timeout)",
    )
    c.add_argument(
        "--wait-timeout", type=float, default=0.0, metavar="SECONDS",
        help="--wait gives up after this long (0 = wait forever): the "
        "last status is printed with timed_out=true, the job's last "
        "journaled state/reason goes to stderr, and the exit code is 3 "
        "(distinct from 1 = terminal failure) so scripts can tell "
        "'still running' from 'dead'",
    )
    c.add_argument(
        "--json", action="store_true",
        help="with --status/--wait: print a NORMALIZED machine-readable "
        "status document on stdout (state, reason, shards rollup, "
        "relative timestamps) and nothing on stderr — external monitors "
        "should parse this, not scrape the human messages. Exit codes "
        "are unchanged (0 done, 1 terminal failure, 3 wait timeout)",
    )
    c.add_argument(
        "--deadline", type=float, default=None, metavar="SECONDS",
        help="--submit wall budget from admission: past it the daemon "
        "journals the job terminal 'expired' (a running job aborts at "
        "its next checkpoint boundary; the committed prefix survives "
        "for a re-submitted resume). Default: the daemon's --deadline",
    )
    c.add_argument(
        "--shards", type=int, default=None, metavar="K",
        help="--submit scatter-gather: split the job into K genomic-"
        "range sub-jobs fanned across the fleet's daemons, then merge "
        "the shard outputs into one indexed BAM byte-identical to the "
        "unsharded run. --status/--wait on the job id aggregate the "
        "sub-jobs; the job is done when the merge publishes",
    )
    c.add_argument(
        "--shard-bytes", type=int, default=None, metavar="BYTES",
        help="--submit scatter-gather by size: like --shards, with K "
        "derived from the compressed input size (one sub-job per this "
        "many input bytes; mutually exclusive with --shards)",
    )
    c.add_argument("--config", choices=sorted(CONFIG_PRESETS), help="benchmark preset")
    c.add_argument(
        "--config-file",
        help="TOML or JSON file of call settings (same keys as the "
        "flags, underscored); precedence: explicit flag > file > "
        "--config preset > default",
    )
    c.add_argument("--backend", choices=["tpu", "cpu"], default=None)
    c.add_argument("--grouping", choices=["exact", "adjacency", "cluster"], default=None)
    c.add_argument("--mode", choices=["ss", "duplex"], default=None)
    c.add_argument("--error-model", choices=["none", "cycle"], default=None)
    c.add_argument("--max-hamming", type=int, default=None)
    c.add_argument(
        "--count-ratio", type=int, default=None,
        help="directional adjacency edge condition "
        "count(a) >= ratio*count(b)-1 (UMI-tools default 2)",
    )
    c.add_argument("--min-reads", type=int, default=None)
    c.add_argument("--min-duplex-reads", type=int, default=None)
    c.add_argument("--max-qual", type=int, default=None)
    c.add_argument("--max-input-qual", type=int, default=None)
    c.add_argument(
        "--min-input-qual",
        type=int,
        default=None,
        help="mask input bases below this quality (fgbio-style "
        "min-input-base-quality; masked bases add no evidence/depth)",
    )
    c.add_argument(
        "--mate-aware",
        choices=["auto", "on", "off"],
        default=None,
        help="paired-end mate handling: split families by fragment end "
        "and emit consensus R1+R2 pairs (fgbio-style). auto (default) "
        "turns it on exactly when the input mixes R1 and R2 mates",
    )
    c.add_argument(
        "--per-base-tags",
        action="store_true",
        default=None,
        help="emit fgbio-style per-base depth (cd:B,I) and disagreeing-"
        "read-count (ce:B,I) arrays on every consensus record (costs "
        "extra device compute, device->host transfer, and output size)",
    )
    c.add_argument(
        "--max-reads",
        type=int,
        default=None,
        help="cap each exact sub-family at this many reads, keeping the "
        "highest-quality ones (fgbio-style --max-reads; 0 = unlimited). "
        "Applied as an INPUT policy before the fused grouping, so "
        "adjacency merge decisions see capped counts — use values >= 20 "
        "(see io.convert.downsample_families). Dropped reads are "
        "counted in the report (n_downsampled_reads)",
    )
    c.add_argument("--capacity", type=int, default=None, help="bucket read capacity")
    c.add_argument("--devices", type=int, default=None, help="mesh size (default: all)")
    c.add_argument(
        "--mesh",
        default=None,
        metavar="{auto,1,2,4,8,..}",
        help="streaming mesh size: shard each chunk's bucket batch "
        "across this many devices ('auto' = all local devices). "
        "Output bytes are identical at ANY device count — chunk order "
        "is the commit order and mesh-pad buckets emit nothing — so "
        "this is a pure throughput knob, A/B-tested like "
        "--drain-workers. Carried by --submit jobs (the daemon "
        "resolves 'auto' against its own device pool); requires "
        "--chunk-reads. Simulate devices on CPU with "
        "XLA_FLAGS=--xla_force_host_platform_device_count=8",
    )
    c.add_argument(
        "--cycle-shards",
        type=int,
        default=None,
        help="shard the read-length axis this many ways (long reads); "
        "devices must be divisible by it",
    )
    c.add_argument(
        "--report",
        help="write run counters/timings JSON here ('-' writes to "
        "stdout; seconds are rounded to milliseconds with stable key "
        "order, so reports diff cleanly)",
    )
    c.add_argument("--profile", help="write a jax.profiler trace to this dir")
    c.add_argument(
        "--chunk-reads",
        type=int,
        default=None,
        help="stream the input in chunks of this many records (0 = whole "
        "file in memory); requires coordinate-sorted input",
    )
    c.add_argument("--checkpoint", help="chunk-progress manifest path (streaming)")
    c.add_argument(
        "--resume",
        action="store_true",
        help="skip chunks already recorded in --checkpoint",
    )
    c.add_argument(
        "--max-inflight",
        type=int,
        default=None,
        help="chunks dispatched to the device ahead of scatter-back "
        "(default 4); also bounds the pipelined drain's memory window",
    )
    c.add_argument(
        "--drain-workers",
        type=int,
        default=None,
        help="streaming drain worker threads: fetch, scatter, "
        "serialize and shard-write completed chunks off the main loop "
        "so ingest/dispatch never stalls behind them (default 2; "
        "output bytes are identical at any setting — checkpoint marks "
        "and the incremental finalise commit in chunk order)",
    )
    c.add_argument(
        "--packed",
        choices=["auto", "byte", "off"],
        default=None,
        help="streaming wire-packing ladder: auto picks the best "
        "lossless H2D rung per chunk (sub-byte qual-dictionary where "
        "the alphabet fits, else base|qual byte); byte caps H2D at "
        "the byte rung; both pack the consensus-only return path; "
        "off disables all wire packing. Output bytes are identical at "
        "every setting (default auto; requires --chunk-reads)",
    )
    c.add_argument(
        "--prefetch-depth",
        type=int,
        default=None,
        help="bounded H2D prefetch window: chunks dispatched (packed + "
        "device_put) ahead of the drain's materialisation, so host "
        "packing and H2D of chunk k+1 overlap device compute of chunk "
        "k (default 2; output bytes identical at any depth; requires "
        "--chunk-reads)",
    )
    c.add_argument(
        "--ingest-overlap",
        choices=["auto", "on", "off"],
        default=None,
        help="bounded background ingest producer (streaming): auto/on "
        "run BGZF read + decode + bucketing on a dedicated thread up "
        "to --prefetch-depth prepped chunks ahead of the main loop "
        "(handoff through a depth-bounded queue sharing the prefetch "
        "window's back-pressure); off forces fully synchronous "
        "main-loop ingest. Output bytes are identical either way "
        "(default auto; requires --chunk-reads)",
    )
    c.add_argument(
        "--bucket-ladder",
        default=None,
        metavar="{auto,off,R1,R2,..}",
        help="mixed-capacity bucket ladder (streaming): 'auto' profiles "
        "the first chunk's family-size histogram and picks 1-3 pow2 "
        "bucket size classes by the tuner's padded-cycles cost model "
        "(tuning/); an explicit ascending pow2 list like '256,2048' "
        "pins the rungs (the top rung replaces --capacity); 'off' "
        "(default) keeps the single --capacity. Output bytes are "
        "identical at every setting — the ladder only cuts padding "
        "(device FLOPs + wire bytes). Requires --chunk-reads",
    )
    c.add_argument(
        "--follow",
        action="store_true",
        default=None,
        help="follow-mode ingest (live/): tail a GROWING input — a "
        "regular file another process appends to, or a FIFO — admitting "
        "only complete-BGZF-block byte runs, and finalise when the "
        "input finishes (see --finalize-on). A follow run over the "
        "finished file is byte-identical to the batch run. Requires "
        "--chunk-reads",
    )
    c.add_argument(
        "--finalize-on",
        default=None,
        metavar="{eof,idle:N,marker}",
        help="follow termination rule: 'eof' waits for the 28-byte BGZF "
        "EOF block (the BAM spec's terminator; default), 'idle:N' "
        "finalises after the input stops growing for N seconds, "
        "'marker' when <input>.done appears. Requires --chunk-reads",
    )
    c.add_argument(
        "--live-poll-s",
        type=float,
        default=None,
        help="follow poll cadence: seconds the tailer sleeps when its "
        "read has caught up with the writer (default 0.25; requires "
        "--chunk-reads)",
    )
    c.add_argument(
        "--snapshot-chunks",
        type=int,
        default=None,
        help="publish an indexed partial snapshot (a valid BAM prefix + "
        "index at OUT.snapshot.bam) every N committed chunks; 0 "
        "disables (default). Output-bytes-neutral side artifact; "
        "requires --chunk-reads",
    )
    c.add_argument(
        "--read-group-id",
        default=None,
        help="output consensus read group id (fgbio-style single @RG on "
        "all consensus records; default A)",
    )
    c.add_argument(
        "--write-index",
        action="store_true",
        default=None,
        help="also write the standard .bai binning index beside the "
        "output (output is always coordinate-sorted)",
    )
    c.add_argument(
        "--ref-projected",
        action="store_true",
        default=None,
        help="project reads onto per-position reference columns instead "
        "of raw cycles: indel-bearing minority reads contribute "
        "realigned evidence instead of being dropped, and consensus "
        "records carry a structural-majority CIGAR (M/I/D). Whole-file "
        "executor only; BAM input only",
    )
    c.add_argument(
        "--umi-whitelist",
        default=None,
        help="expected-UMI list (one ACGT string per line, fgbio "
        "CorrectUmis analogue): every read's UMI (each half "
        "independently in duplex mode) snaps to its unique nearest "
        "entry within --umi-max-mismatches; too-distant or ambiguous "
        "reads are dropped and counted. Whole-file executor only",
    )
    c.add_argument(
        "--umi-max-mismatches",
        type=int,
        default=None,
        help="whitelist correction distance bound (default 1)",
    )
    c.add_argument(
        "--trace",
        default=None,
        metavar="TRACE_JSONL",
        help="record a per-chunk span + event capture (JSONL) of the "
        "streaming executor to this path: every pipeline stage with "
        "its lane (main / xfer-N / drain-N), plus fault, retry, "
        "back-pressure and resume events. Analyse with "
        "tools/trace_report.py, validate with tools/check_trace.py, "
        "or export to Perfetto (trace_report --chrome). Zero overhead "
        "when omitted; requires --chunk-reads",
    )
    c.add_argument(
        "--heartbeat",
        type=float,
        default=0.0,
        metavar="SECONDS",
        help="print a liveness line to stderr every N seconds during a "
        "streaming run (chunks done/inflight, stall fraction, retries, "
        "drain utilization); with --trace the samples also land in the "
        "capture. Requires --chunk-reads",
    )
    c.add_argument(
        "--chaos",
        default=None,
        metavar="SCHEDULE",
        help="deterministic fault injection for the streaming executor "
        "(testing): comma-separated site:nth:kind entries — the Nth hit "
        "of a named fault site raises kind (oserror/enospc/kill) — or "
        "seed:<seed>:<n> for a seeded pseudo-random schedule that "
        "replays identically. Also settable via DUT_FAULTS. See "
        "runtime/faults.py for the site list",
    )

    s = sub.add_parser("simulate", help="write a truth-aware synthetic BAM")
    s.add_argument("-o", "--output", required=True, help="output BAM path")
    s.add_argument("--truth", help="also write ground-truth npz here")
    s.add_argument("--molecules", type=int, default=1000)
    s.add_argument("--read-len", type=int, default=150)
    s.add_argument("--umi-len", type=int, default=6)
    s.add_argument("--positions", type=int, default=32)
    s.add_argument("--family-size", type=int, default=4)
    s.add_argument("--max-family-size", type=int, default=16)
    s.add_argument("--base-error", type=float, default=0.01)
    s.add_argument("--cycle-error-slope", type=float, default=0.0)
    s.add_argument("--umi-error", type=float, default=0.0)
    s.add_argument(
        "--indel-error",
        type=float,
        default=0.0,
        help="per-read 1bp indel prob (exercises the modal-CIGAR filter)",
    )
    s.add_argument("--single-strand", action="store_true", help="no duplex pairing")
    s.add_argument(
        "--sorted",
        action="store_true",
        help="emit records in coordinate order (streaming input contract)",
    )
    s.add_argument(
        "--paired-end",
        action="store_true",
        help="emit paired-end style flags (F1R2/F2R1) with mate pointers",
    )
    s.add_argument(
        "--paired-reads",
        action="store_true",
        help="simulate true R1+R2 mate pairs: each fragment end has its "
        "own ground-truth sequence (exercises mate-aware calling)",
    )
    s.add_argument("--seed", type=int, default=0)

    c.add_argument(
        "--n-hosts",
        type=int,
        default=0,
        help="multi-host partitioning: total hosts (with --host-id; "
        "requires a linear index, built on demand)",
    )
    c.add_argument("--host-id", type=int, default=None, help="this host's id")
    c.add_argument("--index", help="linear index path (default: input + .dlix)")

    f = sub.add_parser(
        "filter",
        help="post-filter a consensus BAM (the FilterConsensusReads "
        "analogue): depth/quality thresholds + low-quality base masking",
    )
    f.add_argument("input", help="consensus BAM from `call`")
    f.add_argument("-o", "--output", required=True, help="filtered BAM")
    f.add_argument(
        "--min-depth", type=int, default=0,
        help="drop consensus with max depth (cD) below this",
    )
    f.add_argument(
        "--min-min-depth", type=int, default=0,
        help="drop consensus with min positive depth (cM) below this",
    )
    f.add_argument(
        "--min-mean-qual", type=float, default=0.0,
        help="drop consensus whose mean base quality is below this",
    )
    f.add_argument(
        "--mask-qual", type=int, default=0,
        help="mask bases below this quality to N (qual 2)",
    )
    f.add_argument(
        "--min-base-depth", type=int, default=0,
        help="mask bases whose per-base depth (cd:B array, written by "
        "call --per-base-tags) is below this; records lacking the cd "
        "tag are counted + warned about",
    )
    f.add_argument(
        "--max-n-frac", type=float, default=1.0,
        help="drop consensus with more than this fraction of N bases "
        "(evaluated after masking)",
    )
    f.add_argument(
        "--max-base-error-rate", type=float, default=1.0,
        help="mask bases whose disagreeing-read fraction (ce/cd, from "
        "call --per-base-tags) exceeds this (fgbio "
        "--max-base-error-rate analogue)",
    )
    f.add_argument(
        "--max-read-error-rate", type=float, default=1.0,
        help="drop consensus whose whole-read disagreeing-read "
        "fraction (sum ce / sum cd) exceeds this (fgbio "
        "--max-read-error-rate analogue)",
    )
    f.add_argument("--chunk-records", type=int, default=200_000)

    x = sub.add_parser(
        "index", help="build the linear BGZF index for multi-host partitioning"
    )
    x.add_argument("input", help="coordinate-sorted BAM")
    x.add_argument("-o", "--output", help="index path (default: input + .dlix)")
    x.add_argument(
        "--every", type=int, default=100_000, help="sampling stride in records"
    )
    x.add_argument(
        "--bai",
        action="store_true",
        help="write the STANDARD .bai binning index (SAM spec §5.2, "
        "consumable by samtools/IGV/variant callers) instead of the "
        "tool's own linear partitioning index",
    )
    x.add_argument(
        "--csi",
        action="store_true",
        help="write the STANDARD .csi index (the BAI generalization "
        "whose binning depth is sized to the longest header contig — "
        "required past BAI's 2^29 coordinate limit)",
    )

    vw = sub.add_parser(
        "view",
        help="extract records overlapping a region via the standard "
        ".bai (samtools-view analogue; builds the index on demand)",
    )
    vw.add_argument("input", help="coordinate-sorted BAM")
    vw.add_argument(
        "region",
        help="REF[:BEG-END] (1-based inclusive, samtools convention); "
        "REF alone takes the whole reference",
    )
    vw.add_argument("-o", "--output", help="write matching records as BAM "
                    "(default: print a count summary)")
    vw.add_argument("--json", action="store_true", help="print summary as JSON")

    st = sub.add_parser(
        "stats",
        help="input metrics: family-size histogram, strand balance, "
        "position-group stats (GroupReadsByUmi-metrics analogue)",
    )
    st.add_argument("input", help="input BAM (or ReadBatch .npz)")
    st.add_argument(
        "--grouping", choices=["exact", "adjacency", "cluster"], default="adjacency"
    )
    st.add_argument("--duplex", action="store_true", help="paired UMI mode")
    st.add_argument("--json", action="store_true")

    v = sub.add_parser("validate", help="consensus error rate vs simulation truth")
    v.add_argument("consensus", help="consensus BAM from `call`")
    v.add_argument("--truth", required=True, help="truth npz from `simulate --truth`")
    v.add_argument("--json", action="store_true", help="print JSON instead of text")
    v.add_argument(
        "--pos-window",
        type=int,
        default=0,
        help="match records to same-UMI truth molecules within this "
        "many bp when the exact-POS lookup misses — needed for "
        "--ref-projected output, whose POS legitimately moves to the "
        "first called reference column. Default 0 (exact only): a "
        "consensus emitted at a WRONG position must stay a loud "
        "unmatched record, not a quiet error-rate bump",
    )

    b = sub.add_parser("bench", help="run the reads/sec benchmark")
    b.add_argument("--reads", type=int, default=None)
    b.add_argument("--capacity", type=int, default=None)

    g = sub.add_parser(
        "group",
        help="annotate reads with UMI-family tags without calling "
        "consensus (the standalone UmiGrouper operator: fgbio "
        "GroupReadsByUmi-style MI molecule ids)",
    )
    g.add_argument("input", help="input BAM")
    g.add_argument("-o", "--output", required=True, help="annotated BAM")
    g.add_argument("--grouping", choices=["exact", "adjacency", "cluster"], default="adjacency")
    g.add_argument("--max-hamming", type=int, default=1)
    g.add_argument(
        "--count-ratio", type=int, default=2,
        help="directional edge condition count(a) >= ratio*count(b)-1 "
        "(same knob as call; UMI-tools default 2)",
    )
    g.add_argument(
        "--mate-aware",
        choices=["auto", "on", "off"],
        default="auto",
        help="the SAME mate handling as call: with it on, MI carries "
        "the source molecule (a template's R1 and R2 share MI), exactly "
        "the molecule structure call --mate-aware consumes. auto turns "
        "it on when the input mixes R1/R2 mates",
    )
    g.add_argument("--backend", choices=["tpu", "cpu"], default="tpu")
    g.add_argument(
        "--duplex",
        action="store_true",
        help="duplex inputs: canonicalise A/B-strand UMI pairs; MI "
        "values carry the fgbio-style /A or /B strand suffix",
    )
    g.add_argument(
        "--capacity", type=int, default=2048,
        help="bucket read capacity for the device grouping path",
    )
    g.add_argument(
        "--umi-whitelist",
        default=None,
        help="expected-UMI list (same semantics as call --umi-whitelist)",
    )
    g.add_argument(
        "--umi-max-mismatches", type=int, default=1,
        help="whitelist correction distance bound",
    )
    g.add_argument("--json", action="store_true", help="print summary as JSON")

    return p


def _load_config_file(path: str) -> dict:
    """TOML (.toml) or JSON call settings; keys match the CLI flags
    with underscores. Unknown keys are rejected — a typo must not
    silently fall back to a default."""
    if path.endswith(".toml"):
        try:
            import tomllib
        except ModuleNotFoundError:  # stdlib tomllib is 3.11+
            try:
                import tomli as tomllib
            except ModuleNotFoundError:
                raise SystemExit(
                    f"{path}: TOML config files need Python >= 3.11 "
                    f"(stdlib tomllib) or the tomli package; use a "
                    f".json config instead"
                )

        with open(path, "rb") as f:
            conf = tomllib.load(f)
    else:
        with open(path) as f:
            conf = json.load(f)
    # exactly the declared knobs (runtime/knobs.py): every execution
    # knob is file-settable; run-control flags (--resume, --trace, …)
    # are not knobs and not file keys
    allowed = set(knobs.config_file_keys())
    unknown = set(conf) - allowed
    if unknown:
        raise SystemExit(
            f"unknown config-file keys: {sorted(unknown)} "
            f"(allowed: {sorted(allowed)})"
        )
    return conf


def _refuse_streaming_only(args, resolved: dict) -> None:
    """The whole-file path's refuse-don't-drop gate, table-driven: a
    knob declaring the ``streaming_only`` surface in runtime/knobs.py
    is refused when chunking is off — by its RESOLVED value, so a
    config-file key is refused exactly like the flag, never silently
    dropped. Grouped knobs share one message naming all their flags
    (the wire-diet trio); ``refuse_alone`` knobs each carry their own
    note (--mesh points at --devices)."""
    grouped_flags = []
    grouped_hit = False
    for name in knobs.streaming_only_keys():
        k = knobs.KNOBS[name]
        if k.refuse_alone:
            continue
        grouped_flags.append(k.flag)
        if getattr(args, name) is not None or resolved[name] != k.default:
            grouped_hit = True
    if grouped_hit:
        raise SystemExit(
            "/".join(grouped_flags)
            + " require the streaming executor (--chunk-reads N)"
        )
    for name in knobs.streaming_only_keys():
        k = knobs.KNOBS[name]
        if not k.refuse_alone:
            continue
        if getattr(args, name) is not None or resolved[name] != k.default:
            raise SystemExit(
                f"{k.flag} requires the streaming executor "
                f"(--chunk-reads N){k.refuse_note}"
            )


def _load_whitelist_or_exit(path: str):
    """Shared --umi-whitelist loader: every whitelist problem is a
    clean CLI error, never a traceback."""
    from duplexumiconsensusreads_tpu.io.convert import load_umi_whitelist

    try:
        return load_umi_whitelist(path)
    except (OSError, ValueError) as e:
        raise SystemExit(f"--umi-whitelist: {e}")


def _spool_or_exit(args) -> str:
    import os as _os

    spool = args.spool or _os.environ.get("DUT_SPOOL")
    if not spool:
        raise SystemExit(
            "--submit/--status/--wait need a service spool directory: "
            "pass --spool DIR or set DUT_SPOOL"
        )
    return spool


def _cmd_call(args) -> int:
    # ---- client verbs against a dut-serve spool: no input is read and
    # no device is touched, so these resolve before anything else
    if args.status is not None or args.wait is not None:
        if args.status is not None and args.wait is not None:
            raise SystemExit("--status and --wait are mutually exclusive")
        from duplexumiconsensusreads_tpu.serve import client

        spool = _spool_or_exit(args)
        if args.status is not None:
            st = client.status(spool, args.status)
        else:
            st = client.wait(
                spool, args.wait, timeout_s=args.wait_timeout
            )
        state = st.get("state")
        if args.json:
            # the machine contract: one normalized document on stdout,
            # NOTHING on stderr — monitors parse this and branch on the
            # exit code, instead of scraping the human messages below
            print(json.dumps(client.status_document(st), sort_keys=True))
            if st.get("timed_out"):
                return 3
            return 1 if state in (
                "failed", "rejected", "expired", "quarantined", "unknown"
            ) else 0
        print(json.dumps(st, sort_keys=True))
        if "snapshot_seq" in st:
            # follow-mode jobs: the journal carries the per-chunk live
            # counters (stamped through the fenced renewal), so watching
            # a follower is one --status away even mid-slice
            import sys as _sys

            print(
                f"[duplexumi] live: snapshot_seq={st['snapshot_seq']} "
                f"reads_emitted={st.get('reads_emitted', 0)}",
                file=_sys.stderr,
            )
        if state in ("rejected", "expired", "quarantined") and st.get("error"):
            # the reason a job never ran (or was given up on) must be
            # one --status away, not buried in the daemon's journal:
            # sheds, invalid-spec rejections, deadline expiries and
            # poison quarantines all name themselves
            import sys as _sys

            kind = (
                "shed by admission control" if st.get("shed")
                else state if state in ("expired", "quarantined")
                else "rejected"
            )
            print(
                f"[duplexumi] job {st.get('job_id')} {kind}: {st['error']}",
                file=_sys.stderr,
            )
        if st.get("timed_out"):
            # distinct exit code: the job is NOT dead, the wait budget
            # just ran out — say where the journal last saw it
            import sys as _sys

            detail = st.get("error") or (
                f"slices={st.get('slices')}" if "slices" in st else ""
            )
            print(
                f"[duplexumi] --wait timed out after {args.wait_timeout}s; "
                f"job {st.get('job_id')} last journaled state: "
                f"{state or 'unknown'}"
                + (f" ({detail})" if detail else ""),
                file=_sys.stderr,
            )
            return 3
        bad = state in (
            "failed", "rejected", "expired", "quarantined", "unknown"
        )
        return 1 if bad else 0
    if args.json:
        # the normalized status document only exists for the client
        # verbs; on --submit or a direct run the flag would be
        # silently inert (refuse-don't-drop, like --deadline)
        raise SystemExit("--json applies to --status/--wait")
    if args.input is None or args.output is None:
        raise SystemExit("call needs INPUT and -o OUTPUT (unless --status/--wait)")

    from duplexumiconsensusreads_tpu.runtime.executor import call_consensus_file
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams
    from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache

    # per_host_cpu: stale XLA:CPU AOT artifacts from another host can
    # SIGILL (see utils/compile_cache.py) - JAX_PLATFORMS=cpu runs are
    # first-class here, so the cache keys on the host CPU. A --submit
    # never touches the device (the daemon runs the job), so it skips
    # the compile-cache setup and the executor-stack import (the
    # serve client path stays off runtime/stream + ops; the jax module
    # itself still loads with the package root).
    if not args.submit:
        enable_compile_cache(per_host_cpu=True)

    fileconf = _load_config_file(args.config_file) if args.config_file else {}
    preset = dict(
        CONFIG_PRESETS.get(args.config or fileconf.get("config"), {})
    )

    def opt(name, default):
        """Precedence: explicit flag (None = unset, so falsy values
        like --min-input-qual 0 are still explicit overrides) > config
        file > preset > default. Value validity is checked separately
        (e.g. capacity must be >= 1)."""
        v = getattr(args, name)
        if v is not None:
            return v
        if name in fileconf:
            return fileconf[name]
        if name in preset:
            return preset[name]
        return default

    grouping = opt("grouping", "exact")
    mode = opt("mode", "ss")
    error_model = opt("error_model", "none")
    capacity = opt("capacity", 2048)
    backend = opt("backend", "tpu")
    chunk_reads = opt("chunk_reads", 0)
    cycle_shards = opt("cycle_shards", 1)
    devices = opt("devices", None)
    max_inflight = opt("max_inflight", 4)
    drain_workers = opt("drain_workers", 2)
    if drain_workers < 1:
        raise SystemExit(f"--drain-workers must be >= 1 (got {drain_workers})")
    packed = opt("packed", "auto")
    prefetch_depth = opt("prefetch_depth", 2)
    bucket_ladder = opt("bucket_ladder", "off")
    mesh = opt("mesh", "auto")
    if mesh != "auto":
        # config-file values arrive as ints or strings; both normalise
        try:
            mesh = int(mesh)
        except (TypeError, ValueError):
            raise SystemExit(
                f"--mesh must be 'auto' or an int >= 1 (got {mesh!r})"
            )
        if mesh < 1:
            raise SystemExit(f"--mesh must be >= 1 (got {mesh})")
        if devices is not None and devices != mesh:
            # two knobs, one mesh size: agreeing values are fine
            # (presets), disagreeing ones must not silently race
            raise SystemExit(
                f"--mesh {mesh} conflicts with --devices {devices}"
            )
    from duplexumiconsensusreads_tpu.tuning import normalize_bucket_ladder

    try:
        ladder_norm = normalize_bucket_ladder(bucket_ladder)
    except ValueError as e:
        raise SystemExit(f"--bucket-ladder: {e}")
    if packed not in ("auto", "byte", "off"):
        raise SystemExit(
            f"invalid packed value {packed!r} (allowed: ['auto', 'byte', "
            f"'off'])"
        )
    if prefetch_depth < 1:
        raise SystemExit(
            f"--prefetch-depth must be >= 1 (got {prefetch_depth})"
        )
    ingest_overlap = opt("ingest_overlap", "auto")
    if ingest_overlap not in ("auto", "on", "off"):
        raise SystemExit(
            f"invalid ingest_overlap value {ingest_overlap!r} "
            f"(allowed: ['auto', 'on', 'off'])"
        )
    follow = bool(opt("follow", False))
    finalize_on = str(opt("finalize_on", "eof"))
    # the structured domain (eof | idle:<seconds> | marker) is hand-
    # validated like --mesh/--bucket-ladder — config-file values bypass
    # argparse and must fail loudly here, before the run
    from duplexumiconsensusreads_tpu.live import parse_finalize_on

    try:
        parse_finalize_on(finalize_on)
    except ValueError as e:
        raise SystemExit(f"--finalize-on: {e}")
    live_poll_s = float(opt("live_poll_s", 0.25))
    if live_poll_s <= 0:
        raise SystemExit(f"--live-poll-s must be > 0 (got {live_poll_s})")
    snapshot_chunks = int(opt("snapshot_chunks", 0))
    if snapshot_chunks < 0:
        raise SystemExit(
            f"--snapshot-chunks must be >= 0 (got {snapshot_chunks})"
        )
    mate_aware = opt("mate_aware", "auto")
    max_reads = opt("max_reads", 0)
    if max_reads < 0:
        raise SystemExit(f"--max-reads must be >= 0 (got {max_reads})")
    per_base_tags = bool(opt("per_base_tags", False))
    read_group = str(opt("read_group_id", "A"))
    # validate BEFORE the (expensive) run: a bad id would otherwise
    # crash at record serialization or forge header fields (a tab in
    # the id splices extra @RG columns)
    if not read_group or not all(33 <= ord(ch) <= 126 for ch in read_group):
        raise SystemExit(
            f"--read-group-id must be non-empty printable ASCII without "
            f"whitespace (got {read_group!r})"
        )
    write_index = bool(opt("write_index", False))
    if write_index and not args.output.endswith(".bam"):
        raise SystemExit("--write-index requires a .bam output path")
    ref_projected = bool(opt("ref_projected", False))
    if ref_projected:
        if args.input.endswith(".npz"):
            raise SystemExit(
                "--ref-projected requires BAM input (the .npz "
                "interchange carries no CIGARs)"
            )
        if chunk_reads > 0 or args.n_hosts > 0:
            raise SystemExit(
                "--ref-projected runs on the whole-file executor "
                "(omit --chunk-reads / --n-hosts)"
            )
    umi_whitelist = None
    wl_path = opt("umi_whitelist", None)
    umi_max_mismatches = int(opt("umi_max_mismatches", 1))
    if wl_path:
        if chunk_reads > 0 or args.n_hosts > 0:
            raise SystemExit(
                "--umi-whitelist runs on the whole-file executor "
                "(omit --chunk-reads / --n-hosts)"
            )
        umi_whitelist = _load_whitelist_or_exit(wl_path)

    # config-file values bypass argparse's choices= validation; a value
    # typo must fail loudly, not silently select a default behaviour
    _check = {
        "grouping": {"exact", "adjacency", "cluster"},
        "mode": {"ss", "duplex"},
        "error_model": {"none", "cycle"},
        "backend": {"tpu", "cpu"},
        "mate_aware": {"auto", "on", "off"},
    }
    for _k, _allowed in _check.items():
        _v = {"grouping": grouping, "mode": mode, "error_model": error_model,
              "backend": backend, "mate_aware": mate_aware}[_k]
        if _v not in _allowed:
            raise SystemExit(f"invalid {_k} value {_v!r} (allowed: {sorted(_allowed)})")
    if (args.config or fileconf.get("config")) and not preset:
        raise SystemExit(
            f"unknown config preset {args.config or fileconf.get('config')!r}"
        )
    if capacity < 1:
        raise SystemExit(f"--capacity must be >= 1 (got {capacity})")
    if args.submit:
        # spool the resolved call as a service job instead of running it
        if args.n_hosts > 0:
            raise SystemExit(
                "--submit jobs are single-host (each host runs its own "
                "daemon over its own partition); drop --n-hosts"
            )
        if ref_projected or wl_path:
            raise SystemExit(
                "--submit jobs run on the streaming executor; "
                "--ref-projected/--umi-whitelist are whole-file only"
            )
        if backend != "tpu":
            raise SystemExit("--submit jobs stream on --backend=tpu")
        if args.chunk_reads is not None and args.chunk_reads <= 0:
            raise SystemExit(
                "--submit jobs stream: --chunk-reads must be >= 1"
            )
        if args.priority < 0:
            raise SystemExit(f"--priority must be >= 0 (got {args.priority})")
        if args.deadline is not None and args.deadline <= 0:
            raise SystemExit(f"--deadline must be > 0 (got {args.deadline})")
        if args.shards is not None and args.shards < 1:
            raise SystemExit(f"--shards must be >= 1 (got {args.shards})")
        if args.shard_bytes is not None and args.shard_bytes < 1:
            raise SystemExit(
                f"--shard-bytes must be >= 1 (got {args.shard_bytes})"
            )
        if args.shards is not None and args.shard_bytes is not None:
            raise SystemExit("--shards and --shard-bytes are mutually "
                             "exclusive")
        if args.checkpoint or args.resume or args.report or args.profile:
            # the daemon owns checkpointing/resume (preemption + crash
            # recovery) and the result report (spool results/): these
            # flags would be silently dropped — refuse instead
            raise SystemExit(
                "--submit: --checkpoint/--resume/--report/--profile are "
                "owned by the service (results land in the spool's "
                "results/ dir; jobs always checkpoint and resume)"
            )
        if cycle_shards != 1 or devices is not None or args.heartbeat:
            # same rule for the device/liveness knobs the job spec does
            # not carry: device topology belongs to `dut-serve
            # --devices` and liveness to `dut-serve --heartbeat` — a
            # submitted value would be silently ignored, so refuse
            raise SystemExit(
                "--submit: --cycle-shards/--devices/--heartbeat are "
                "daemon-side settings (see dut-serve --devices/"
                "--heartbeat); jobs cannot carry them"
            )
        from duplexumiconsensusreads_tpu.serve import client

        spool = _spool_or_exit(args)
        config = {
            "grouping": grouping,
            "mode": mode,
            "error_model": error_model,
            "max_hamming": opt("max_hamming", 1),
            "count_ratio": opt("count_ratio", 2),
            "min_reads": opt("min_reads", 1),
            "min_duplex_reads": opt("min_duplex_reads", 1),
            "max_qual": opt("max_qual", 90),
            "max_input_qual": opt("max_input_qual", 50),
            "min_input_qual": opt("min_input_qual", 0),
            "capacity": capacity,
            # unset/0 chunking takes the service default: a job MUST
            # stream (preemption + crash recovery are chunk-boundary
            # contracts)
            "chunk_reads": chunk_reads if chunk_reads > 0 else 500_000,
            "max_inflight": max_inflight,
            "drain_workers": drain_workers,
            "packed": packed,
            "prefetch_depth": prefetch_depth,
            "ingest_overlap": ingest_overlap,
            "mesh": mesh,
            "bucket_ladder": (
                list(ladder_norm) if isinstance(ladder_norm, tuple)
                else ladder_norm
            ),
            "mate_aware": mate_aware,
            "max_reads": max_reads,
            "per_base_tags": per_base_tags,
            "read_group_id": read_group,
            "write_index": write_index,
            "follow": follow,
            "finalize_on": finalize_on,
            "live_poll_s": live_poll_s,
            "snapshot_chunks": snapshot_chunks,
        }
        try:
            job_id = client.submit(
                spool,
                args.input,
                args.output,
                config=config,
                priority=args.priority,
                chaos=args.chaos,
                trace=args.trace,
                deadline_s=args.deadline,
                shards=args.shards,
                shard_bytes=args.shard_bytes,
            )
        except (ValueError, OSError) as e:
            raise SystemExit(f"--submit: {e}")
        print(job_id)  # stdout: the parseable handle for --status/--wait
        print(
            f"[duplexumi] job {job_id} spooled to {spool} (priority "
            f"{args.priority}); follow with `duplexumi call --wait "
            f"{job_id} --spool {spool}`",
            file=sys.stderr,
        )
        return 0
    if args.deadline is not None:
        # deadlines are a service contract (journal expiry + fenced
        # terminal state); a direct run would silently ignore the flag
        raise SystemExit(
            "--deadline applies to --submit jobs (daemon default: "
            "dut-serve --deadline)"
        )
    if args.shards is not None or args.shard_bytes is not None:
        # sharding is a fleet contract (sub-job fan-out + lease-claimed
        # merge); a direct run would silently ignore the flag
        raise SystemExit(
            "--shards/--shard-bytes apply to --submit jobs (the fleet "
            "fans the sub-jobs out and merges the shards)"
        )
    if args.trace and chunk_reads <= 0:
        # only the streaming executor is span-instrumented; on the
        # whole-file path the flag would silently record nothing
        raise SystemExit(
            "--trace requires the streaming executor (--chunk-reads N)"
        )
    if chunk_reads <= 0:
        # only the streaming executor carries the streaming_only knobs;
        # on the whole-file path they would be silently inert (a
        # --submit job always streams, so the keys rode into its config
        # above) — one registry-driven gate replaces the per-knob
        # copies, bucket_ladder refused by its NORMALISED value so a
        # cosmetic "OFF" cannot slip past
        _refuse_streaming_only(args, {
            "packed": packed,
            "prefetch_depth": prefetch_depth,
            "ingest_overlap": ingest_overlap,
            "mesh": mesh,
            "bucket_ladder": ladder_norm,
            "follow": follow,
            "finalize_on": finalize_on,
            "live_poll_s": live_poll_s,
            "snapshot_chunks": snapshot_chunks,
        })
    if args.heartbeat:
        if args.heartbeat < 0:
            raise SystemExit(
                f"--heartbeat must be > 0 seconds (got {args.heartbeat})"
            )
        if chunk_reads <= 0:
            raise SystemExit(
                "--heartbeat requires the streaming executor (--chunk-reads N)"
            )
    if args.chaos:
        if chunk_reads <= 0:
            # only the streaming executor threads the fault sites and
            # their recovery ladders; on the whole-file path the flag
            # would be silently inert (or fire where nothing recovers)
            raise SystemExit(
                "--chaos requires the streaming executor (--chunk-reads N)"
            )
        from duplexumiconsensusreads_tpu.runtime import faults

        try:
            # the explicit flag wins over a stale DUT_FAULTS export —
            # install_from_env leaves a plan with a different spec alone
            faults.install(faults.FaultPlan.parse(args.chaos))
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")

    gp = GroupingParams(
        strategy=grouping,
        max_hamming=opt("max_hamming", 1),
        count_ratio=opt("count_ratio", 2),
        paired=(mode == "duplex"),
    )
    cp = ConsensusParams(
        mode="duplex" if mode == "duplex" else "single_strand",
        min_reads=opt("min_reads", 1),
        min_duplex_reads=opt("min_duplex_reads", 1),
        max_qual=opt("max_qual", 90),
        max_input_qual=opt("max_input_qual", 50),
        min_input_qual=opt("min_input_qual", 0),
        error_model=None if error_model == "none" else error_model,
    )
    if args.n_hosts > 0:
        if args.host_id is None:
            raise SystemExit("--n-hosts requires --host-id")
        if chunk_reads <= 0:
            raise SystemExit("multi-host mode streams: pass --chunk-reads")
        import os as _os

        from duplexumiconsensusreads_tpu.parallel.distributed import (
            init_distributed,
            multihost_call,
        )

        # wire this process into the multi-controller runtime: explicit
        # env vars (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES /
        # JAX_PROCESS_ID) or managed-platform auto-detection (cloud TPU
        # pods, SLURM — auto=True runs the bare initialize() that
        # performs it) — a no-op for single-process emulation runs
        dist = init_distributed(auto=True)
        if dist["num_processes"] > 1:
            print(
                f"[duplexumi] distributed runtime: process "
                f"{dist['process_id']}/{dist['num_processes']}, "
                f"{dist['local_devices']} local / "
                f"{dist['global_devices']} global devices",
                file=sys.stderr,
            )

        # per-host output path: hosts share storage in a pod, so a
        # verbatim --output would have every host clobber the same
        # file, shard dir, and auto-checkpoint
        base, ext = _os.path.splitext(args.output)
        host_out = f"{base}.host{args.host_id}{ext or '.bam'}"
        # an explicit --checkpoint needs the same per-host suffix as the
        # output: hosts share pod storage but fingerprint different
        # input ranges, so a shared manifest path would have each host
        # overwrite the others' and defeat --resume on every host
        host_ckpt = (
            f"{args.checkpoint}.host{args.host_id}" if args.checkpoint else None
        )
        # same per-host suffix discipline as the output/checkpoint: a
        # shared --trace/--report path would have every host clobber
        # one file on shared pod storage ('-' stays stdout, per-host
        # by nature)
        host_trace = f"{args.trace}.host{args.host_id}" if args.trace else None
        host_report = (
            f"{args.report}.host{args.host_id}"
            if args.report and args.report != "-"
            else args.report
        )
        rep = multihost_call(
            args.input,
            host_out,
            gp,
            cp,
            index_path=args.index,
            process_id=args.host_id,
            num_processes=args.n_hosts,
            capacity=capacity,
            chunk_reads=chunk_reads,
            n_devices=mesh if mesh != "auto" else devices,
            max_inflight=max_inflight,
            drain_workers=drain_workers,
            packed=packed,
            prefetch_depth=prefetch_depth,
            ingest_overlap=ingest_overlap,
            bucket_ladder=ladder_norm,
            checkpoint_path=host_ckpt,
            resume=args.resume,
            report_path=host_report,
            profile_dir=args.profile,
            cycle_shards=cycle_shards,
            mate_aware=mate_aware,
            max_reads=max_reads,
            per_base_tags=per_base_tags,
            read_group=read_group,
            write_index=write_index,
            trace_path=host_trace,
            heartbeat_s=args.heartbeat,
        )
        if rep is None:
            print("[duplexumi] host has no records in range; idle", file=sys.stderr)
            return 0
        print(f"[duplexumi] host output → {host_out}", file=sys.stderr)
    elif chunk_reads > 0:
        if backend != "tpu":
            raise SystemExit("--chunk-reads streaming requires --backend=tpu")
        from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

        rep = stream_call_consensus(
            args.input,
            args.output,
            gp,
            cp,
            capacity=capacity,
            chunk_reads=chunk_reads,
            n_devices=mesh if mesh != "auto" else devices,
            max_inflight=max_inflight,
            drain_workers=drain_workers,
            packed=packed,
            prefetch_depth=prefetch_depth,
            ingest_overlap=ingest_overlap,
            bucket_ladder=ladder_norm,
            checkpoint_path=args.checkpoint,
            resume=args.resume,
            report_path=args.report,
            profile_dir=args.profile,
            cycle_shards=cycle_shards,
            mate_aware=mate_aware,
            max_reads=max_reads,
            per_base_tags=per_base_tags,
            read_group=read_group,
            write_index=write_index,
            follow=follow,
            finalize_on=finalize_on,
            live_poll_s=live_poll_s,
            snapshot_chunks=snapshot_chunks,
            trace_path=args.trace,
            heartbeat_s=args.heartbeat,
        )
    else:
        try:
            rep = call_consensus_file(
                args.input,
                args.output,
                gp,
                cp,
                backend=backend,
                capacity=capacity,
                n_devices=devices,
                report_path=args.report,
                profile_dir=args.profile,
                cycle_shards=cycle_shards,
                mate_aware=mate_aware,
                max_reads=max_reads,
                per_base_tags=per_base_tags,
                read_group=read_group,
                write_index=write_index,
                ref_projected=ref_projected,
                umi_whitelist=umi_whitelist,
                umi_max_mismatches=umi_max_mismatches,
            )
        except ValueError as e:
            # the whitelist/input length compatibility check can only
            # run once the input's UMI length is known (inside the
            # load) — surface it as the same clean CLI error as every
            # other whitelist problem
            if umi_whitelist is not None and "whitelist" in str(e):
                raise SystemExit(f"--umi-whitelist: {e}")
            raise
    pairs = f", {rep.n_consensus_pairs} R1+R2 pairs" if rep.mate_aware else ""
    print(
        f"[duplexumi] {rep.n_valid_reads}/{rep.n_records} reads → "
        f"{rep.n_consensus} consensus ({rep.n_molecules} molecules{pairs}, "
        f"{rep.n_buckets} buckets, backend={rep.backend}) "
        # "total" is the stream path's true wall; the whole-file path
        # records disjoint phases whose sum is the wall. Never sum a
        # dict that contains "total" — phase keys overlap it (and the
        # threaded "dispatch" accrues concurrent worker time > wall)
        f"in {rep.seconds.get('total', sum(rep.seconds.values())):.2f}s "
        f"{rep.seconds}",
        file=sys.stderr,
    )
    return 0


def _cmd_simulate(args) -> int:
    import numpy as np

    from duplexumiconsensusreads_tpu.io import simulated_bam
    from duplexumiconsensusreads_tpu.simulate import SimConfig

    cfg = SimConfig(
        n_molecules=args.molecules,
        read_len=args.read_len,
        umi_len=args.umi_len,
        n_positions=args.positions,
        mean_family_size=args.family_size,
        max_family_size=args.max_family_size,
        base_error=args.base_error,
        cycle_error_slope=args.cycle_error_slope,
        umi_error=args.umi_error,
        indel_error=args.indel_error,
        duplex=not args.single_strand,
        paired_reads=args.paired_reads,
        seed=args.seed,
    )
    _, recs, batch, truth = simulated_bam(
        cfg, path=args.output, sort=args.sorted, paired_end=args.paired_end
    )
    if args.truth:
        extra = {}
        if truth.mol_seq2 is not None:
            extra["mol_seq2"] = truth.mol_seq2
        np.savez_compressed(
            args.truth,
            mol_seq=truth.mol_seq,
            mol_pos_key=truth.mol_pos_key,
            mol_umi=truth.mol_umi,
            read_mol=truth.read_mol,
            read_strand=truth.read_strand,
            duplex=np.bool_(cfg.duplex),
            **extra,
        )
    print(
        f"[duplexumi] simulated {len(recs)} reads / {args.molecules} molecules "
        f"→ {args.output}",
        file=sys.stderr,
    )
    return 0


def _cmd_validate(args) -> int:
    import numpy as np

    from duplexumiconsensusreads_tpu.io import read_bam
    from duplexumiconsensusreads_tpu.io.convert import (
        pack_pos_key,
        umi_string_to_codes,
        unpack_pos_key,
    )

    from duplexumiconsensusreads_tpu.io.bam import FLAG_READ2

    _, recs = read_bam(args.consensus)
    with np.load(args.truth) as z:
        mol_seq = z["mol_seq"]
        mol_pos_key = z["mol_pos_key"]
        mol_umi = z["mol_umi"]
        # paired-reads truth: fragment end 2 has its own sequence;
        # consensus R2 records validate against it
        mol_seq2 = z["mol_seq2"] if "mol_seq2" in z.files else None

    # truth pos_key is the simulator's raw key; consensus BAM re-packs it
    # as (ref=0) << 36 | pos, so compare on the coordinate part
    _, truth_pos = unpack_pos_key(pack_pos_key(np.zeros(len(mol_pos_key)), mol_pos_key))
    index = {}
    by_pos: dict = {}
    by_umi: dict = {}
    for m in range(len(mol_seq)):
        index[(int(truth_pos[m]), mol_umi[m].tobytes())] = m
        by_pos.setdefault(int(truth_pos[m]), []).append(m)
        by_umi.setdefault(mol_umi[m].tobytes(), []).append(m)

    # pass 1: exact matches + error rate
    n_match = n_err = n_base = 0
    unmatched_idx = []
    matched_mols: set = set()
    for i in range(len(recs)):
        codes = umi_string_to_codes(recs.umi[i])
        ub = codes.tobytes() if codes is not None else b""
        m = index.get((int(recs.pos[i]), ub))
        if m is None and args.pos_window > 0:
            # --pos-window: ref-projected records move POS to the first
            # called reference column, which can differ from the
            # canonical pos_key coordinate (e.g. uniformly soft-clipped
            # starts) — fall back to the nearest same-UMI truth
            # molecule within the window so moved-POS records still
            # validate. OPT-IN: with the default exact matching, a
            # record emitted at a wrong position stays loudly
            # unmatched (pass 2 classification), never a quiet
            # error-rate bump.
            cand = [
                c for c in by_umi.get(ub, ())
                if abs(int(recs.pos[i]) - int(truth_pos[c])) <= args.pos_window
            ]
            if cand:
                m = min(cand, key=lambda c: abs(int(recs.pos[i]) - int(truth_pos[c])))
        if m is None:
            unmatched_idx.append((i, codes))
            continue
        matched_mols.add(m)
        n_match += 1
        l = int(recs.lengths[i])
        called = recs.seq[i, :l]
        is_r2 = bool(recs.flags[i] & FLAG_READ2)
        true_row = (mol_seq2 if (is_r2 and mol_seq2 is not None) else mol_seq)[m]
        # CIGAR-aware comparison: ref-projected consensus records carry
        # real M/I/D CIGARs and can start past (or span beyond) the
        # truth row — walk M runs and compare at reference offsets;
        # inserted and beyond-truth bases have no truth to compare.
        # Legacy full-M records reduce to the old direct comparison.
        p0 = int(recs.pos[i]) - int(truth_pos[m])
        q = r = 0
        for nop, op in recs.cigars[i]:
            if op in "M=X":
                roff = p0 + r + np.arange(nop)
                sel = (roff >= 0) & (roff < len(true_row))
                qs = called[q : q + nop][sel]
                tr = true_row[roff[sel]]
                real = qs != 4
                n_err += int((qs[real] != tr[real]).sum())
                n_base += int(real.sum())
                q += nop
                r += nop
            elif op in ("I", "S"):
                q += nop
            elif op in ("D", "N"):
                r += nop

    # pass 2: classify every unmatched record (VERDICT r1 item 9 —
    # "unmatched" must not be able to hide error-rate regressions):
    #   position_miss  no truth molecule at this coordinate at all
    #   seed_mismatch  a truth molecule within Hamming<=1 exists whose
    #                  exact UMI was never reported: the cluster was
    #                  called under an errored seed UMI
    #   over_split     nearest truth molecule (Hamming<=1) was ALSO
    #                  matched exactly: this record is an extra molecule
    #                  split off by UMI errors
    #   other          truth position exists but no truth UMI within
    #                  Hamming<=1 (multi-error UMI or chimera)
    cls = {"position_miss": 0, "seed_mismatch": 0, "over_split": 0, "other": 0}
    for i, codes in unmatched_idx:
        p = int(recs.pos[i])
        mols = by_pos.get(p)
        if not mols:
            cls["position_miss"] += 1
            continue
        c = codes if codes is not None else np.zeros(0, np.uint8)
        best_m, best_h = -1, 1 << 30
        for m in mols:
            t = mol_umi[m]
            h = int((t != c).sum()) if len(t) == len(c) else 1 << 30
            if h < best_h:
                best_h, best_m = h, m
        if best_h <= 1:
            if best_m in matched_mols:
                cls["over_split"] += 1
            else:
                cls["seed_mismatch"] += 1
        else:
            cls["other"] += 1

    from duplexumiconsensusreads_tpu.runtime.executor import count_consensus_pairs

    rate = n_err / max(n_base, 1)
    n_pairs = count_consensus_pairs(recs)
    out = {
        "n_consensus": len(recs),
        "n_consensus_pairs": n_pairs,
        "n_matched_to_truth": n_match,
        "n_unmatched": len(unmatched_idx),
        "unmatched": cls,
        "n_bases": n_base,
        "n_errors": n_err,
        "error_rate": rate,
    }
    if args.json:
        print(json.dumps(out))
    else:
        print(
            f"[duplexumi] {n_match}/{len(recs)} consensus matched to truth; "
            f"error rate {rate:.3e} ({n_err}/{n_base} bases); "
            f"{len(unmatched_idx)} unmatched ({cls['over_split']} over-split, "
            f"{cls['seed_mismatch']} seed-mismatch, "
            f"{cls['position_miss']} position-miss, {cls['other']} other)",
        )
    return 0


def _cmd_filter(args) -> int:
    """Streaming consensus post-filter (FilterConsensusReads analogue):
    record-level thresholds on the cD/cM aux depth stats and mean base
    quality, plus per-base low-quality masking to N. Streams in record
    chunks (no family hold-back needed — filtering is per record)."""
    import struct

    import numpy as np

    from duplexumiconsensusreads_tpu.constants import BASE_N, NO_CALL_QUAL
    from duplexumiconsensusreads_tpu.io import bgzf
    from duplexumiconsensusreads_tpu.io.bam import serialize_bam
    from duplexumiconsensusreads_tpu.runtime.stream import (
        BamStreamReader,
        _empty_records,
        _records_from_raw,
    )

    from duplexumiconsensusreads_tpu.io.bam import iter_aux_fields

    _INT_FMT = {b"c": "<b", b"C": "<B", b"s": "<h", b"S": "<H",
                b"i": "<i", b"I": "<I"}
    _B_DT = {b"c": "<i1", b"C": "<u1", b"s": "<i2",
             b"S": "<u2", b"i": "<i4", b"I": "<u4"}

    def aux_i(aux: bytes, tag: bytes) -> int | None:
        """Integer aux value for ``tag`` via the shared field walker
        (io.bam.iter_aux_fields — ONE aux-type switch for the whole
        codebase). Accepts every BAM integer type (c/C/s/S/i/I) —
        consensus BAMs from other writers store small depths as c/s
        (ADVICE r2). Returns None when the tag is absent; raises on a
        malformed aux stream or a non-integer value under the tag, so
        missing-tag and broken-record inputs are distinguishable
        instead of both silently filtering."""
        from duplexumiconsensusreads_tpu.io.bam import iter_aux_fields

        try:
            for _s, t, typ, vstart, end in iter_aux_fields(aux):
                if end > len(aux):
                    raise ValueError("malformed aux stream: value past end")
                if t == tag:
                    fmt = _INT_FMT.get(typ)
                    if fmt is None:
                        raise ValueError(
                            f"aux tag {tag.decode()} has non-integer "
                            f"type {typ.decode()!r}"
                        )
                    return struct.unpack_from(fmt, aux, vstart)[0]
        except (IndexError, struct.error) as e:
            raise ValueError(f"malformed aux stream: {e}") from e
        return None

    from duplexumiconsensusreads_tpu.io.bam import derive_output_header

    def aux_b(a: bytes, tag: bytes):
        """Integer B-array aux value for ``tag`` (any int subtype —
        other writers store small depths as B,c/B,s). None if absent."""
        try:
            for _s, t, typ, vs, _e in iter_aux_fields(a):
                sub = a[vs : vs + 1]
                if t == tag and typ == b"B" and sub in _B_DT:
                    (cnt,) = struct.unpack_from("<I", a, vs + 1)
                    return np.frombuffer(a, _B_DT[sub], cnt, vs + 5)
        except (struct.error, KeyError, IndexError) as e:
            raise ValueError(f"malformed aux stream: {e}") from e
        return None

    reader = BamStreamReader(args.input)
    # record order is preserved, so the input SO stays truthful
    # (sort_order=None); the run joins the @PG provenance chain with CL
    header = derive_output_header(reader.header, sort_order=None)
    shell = serialize_bam(header, _empty_records())
    n_in = n_kept = n_masked = n_no_tag = n_no_cd = n_no_ce = 0
    try:
        with open(args.output, "wb") as out_f:
            out_f.write(bgzf.compress_fast(shell, eof=False))
            while True:
                raw = reader.read_raw_records(args.chunk_records)
                if raw is None:
                    break
                recs = _records_from_raw(header, raw)
                n = len(recs)
                n_in += n
                err_filters = (
                    args.max_base_error_rate < 1.0
                    or args.max_read_error_rate < 1.0
                )
                need_mask = (
                    args.mask_qual > 0
                    or args.min_mean_qual > 0
                    or args.max_n_frac < 1.0
                    or err_filters
                )
                if need_mask:
                    lens = np.asarray(recs.lengths)
                    in_read = (
                        np.arange(recs.qual.shape[1])[None, :] < lens[:, None]
                    )
                if args.mask_qual > 0:
                    low = (recs.qual < args.mask_qual) & in_read
                    n_masked += int(low.sum())
                    recs.seq[low] = BASE_N
                    recs.qual[low] = NO_CALL_QUAL
                if args.min_base_depth > 0:
                    # per-base depth mask from the cd:B array (written
                    # by call --per-base-tags; any integer subtype —
                    # other writers store depths as B,S/c/s). Shallow
                    # cycles go N so the subsequent max-n-frac/
                    # mean-qual thresholds see the post-mask record.
                    for i, a in enumerate(recs.aux_raw):
                        arr = aux_b(a, b"cd")
                        li = int(recs.lengths[i])
                        if arr is None or len(arr) < li:
                            # missing tag, or a cd array shorter than
                            # the read (foreign trimming) — skip the
                            # record's mask rather than kill the run
                            n_no_cd += 1
                            continue
                        shallow = np.zeros(recs.seq.shape[1], bool)
                        shallow[:li] = arr[:li] < args.min_base_depth
                        shallow &= recs.seq[i] != BASE_N  # count NEW masks
                        n_masked += int(shallow.sum())
                        recs.seq[i][shallow] = BASE_N
                        recs.qual[i][shallow] = NO_CALL_QUAL
                keep = np.ones(n, bool)
                if err_filters:
                    # fgbio FilterConsensusReads' error-rate pair, from
                    # the ce (disagreeing reads) / cd (depth) per-base
                    # arrays: base-level masking BEFORE max-n-frac so
                    # the N-fraction threshold sees the post-mask
                    # record; read-level rate joins the drop set
                    for i, a in enumerate(recs.aux_raw):
                        cdv = aux_b(a, b"cd")
                        cev = aux_b(a, b"ce")
                        li = int(recs.lengths[i])
                        if (
                            cdv is None or cev is None
                            or len(cdv) < li or len(cev) < li
                        ):
                            n_no_ce += 1
                            continue
                        d = cdv[:li].astype(np.int64)
                        e = cev[:li].astype(np.int64)
                        if args.max_read_error_rate < 1.0:
                            tot = int(d.sum())
                            if tot and int(e.sum()) > args.max_read_error_rate * tot:
                                keep[i] = False
                                continue
                        if args.max_base_error_rate < 1.0:
                            bad = np.zeros(recs.seq.shape[1], bool)
                            # e > rate*d (no per-cycle division, so
                            # zero-depth cycles — already N — never
                            # divide by zero)
                            bad[:li] = e > args.max_base_error_rate * d
                            bad &= recs.seq[i] != BASE_N
                            n_masked += int(bad.sum())
                            recs.seq[i][bad] = BASE_N
                            recs.qual[i][bad] = NO_CALL_QUAL
                if args.min_depth > 0 or args.min_min_depth > 0:
                    # a tag is only REQUIRED when its threshold is
                    # active (a foreign BAM carrying just cD must still
                    # be filterable on --min-depth). Records missing a
                    # required tag are dropped but COUNTED and warned
                    # about, never silently conflated with low depth
                    cd = np.empty(n, np.int64)
                    cm = np.empty(n, np.int64)
                    for i, a in enumerate(recs.aux_raw):
                        vd = aux_i(a, b"cD") if args.min_depth > 0 else 0
                        vm = aux_i(a, b"cM") if args.min_min_depth > 0 else 0
                        if vd is None or vm is None:
                            n_no_tag += 1
                            cd[i] = cm[i] = -1
                        else:
                            cd[i], cm[i] = vd, vm
                    keep &= cd >= args.min_depth
                    keep &= cm >= args.min_min_depth
                if args.min_mean_qual > 0:
                    qsum = (recs.qual * in_read).sum(axis=1)
                    keep &= qsum >= args.min_mean_qual * np.maximum(lens, 1)
                if args.max_n_frac < 1.0:
                    n_count = ((recs.seq == BASE_N) & in_read).sum(axis=1)
                    keep &= n_count <= args.max_n_frac * np.maximum(lens, 1)
                kept_idx = np.nonzero(keep)[0]
                n_kept += len(kept_idx)
                if len(kept_idx):
                    sub = (
                        recs
                        if len(kept_idx) == n
                        else _take_records(recs, kept_idx)
                    )
                    payload = serialize_bam(header, sub)[len(shell):]
                    out_f.write(bgzf.compress_fast(payload, eof=False))
            out_f.write(bgzf.BGZF_EOF)
    except ValueError as e:
        # a malformed record mid-stream must not leave a truncated,
        # EOF-less output BAM behind for a later pipeline step to
        # half-read — remove it and fail with a CLI error, not a
        # traceback
        import os as _os

        try:
            _os.remove(args.output)
        except OSError:
            pass
        raise SystemExit(f"[duplexumi] filter: {e} (input record ~{n_in})")
    finally:
        reader.close()
    if n_no_tag:
        print(
            f"[duplexumi] filter: WARNING: {n_no_tag} records lack a "
            "required depth tag and were dropped by the depth filter "
            "(input not produced by `duplexumi call`?)",
            file=sys.stderr,
        )
    if n_no_cd:
        print(
            f"[duplexumi] filter: WARNING: {n_no_cd} records lack a "
            "usable per-base cd array (absent or shorter than the "
            "read) and were left unmasked by --min-base-depth (run "
            "`call --per-base-tags` to emit cd)",
            file=sys.stderr,
        )
    if n_no_ce:
        print(
            f"[duplexumi] filter: WARNING: {n_no_ce} records lack "
            "usable cd+ce per-base arrays and skipped the error-rate "
            "filters (run `call --per-base-tags` to emit both)",
            file=sys.stderr,
        )
    print(
        f"[duplexumi] filter: kept {n_kept}/{n_in} consensus reads"
        + (
            f", masked {n_masked} bases"
            if (
                args.mask_qual > 0
                or args.min_base_depth > 0
                or args.max_base_error_rate < 1.0
            )
            else ""
        ),
        file=sys.stderr,
    )
    return 0


def _take_records(recs, idx):
    import dataclasses as _dc

    from duplexumiconsensusreads_tpu.io.bam import BamRecords

    out = {}
    for fld in _dc.fields(BamRecords):
        v = getattr(recs, fld.name)
        if isinstance(v, list):
            out[fld.name] = [v[i] for i in idx]
        else:
            out[fld.name] = v[idx]
    return BamRecords(**out)


def _cmd_stats(args) -> int:
    """Input metrics from the oracle grouper (the GroupReadsByUmi
    metrics analogue): family/molecule counts, family-size histogram,
    duplex strand balance, position-group sizes."""
    import numpy as np

    from duplexumiconsensusreads_tpu.io import load_input
    from duplexumiconsensusreads_tpu.oracle import group_reads
    from duplexumiconsensusreads_tpu.types import GroupingParams

    _, batch, info = load_input(args.input, duplex=args.duplex)

    gp = GroupingParams(strategy=args.grouping, paired=args.duplex)
    fams = group_reads(batch, gp)
    valid = np.asarray(batch.valid, bool)
    fam_id = np.asarray(fams.family_id)[valid]
    mol_id = np.asarray(fams.molecule_id)[valid]
    pos = np.asarray(batch.pos_key)[valid]
    strand = np.asarray(batch.strand_ab, bool)[valid]

    sizes = np.bincount(fam_id[fam_id >= 0])
    hist_edges = [1, 2, 3, 4, 5, 10, 20, 50, 100, 1000, 1 << 30]
    hist = {}
    prev = 1
    for e in hist_edges[1:]:
        label = f"{prev}" if e == prev + 1 else f"{prev}-{e - 1}"
        hist[label] = int(((sizes >= prev) & (sizes < e)).sum())
        prev = e
    _, pg_sizes = np.unique(pos, return_counts=True)
    n_mol = int(fams.n_molecules)
    duplex_mols = 0
    duplex_size_hist: dict = {}
    duplex_yield: dict = {}
    if args.duplex and n_mol:
        ab = np.bincount(mol_id[strand], minlength=n_mol)
        ba = np.bincount(mol_id[~strand], minlength=n_mol)
        duplex_mols = int(((ab > 0) & (ba > 0)).sum())
        # CollectDuplexSeqMetrics-style strand-pair metrics: the
        # (larger, smaller) per-strand size matrix (strand label is
        # arbitrary, so the histogram is order-free) and the fraction
        # of molecules whose WEAKER strand clears a min-reads bar —
        # the duplex yield curve that decides panel sequencing depth
        hi = np.maximum(ab, ba)
        lo = np.minimum(ab, ba)
        pairs, cnts = np.unique(
            np.stack([hi, lo], axis=1), axis=0, return_counts=True
        )
        order = np.argsort(-cnts)[:20]  # top pairs; the tail is noise
        duplex_size_hist = {
            f"{int(pairs[o, 0])}+{int(pairs[o, 1])}": int(cnts[o])
            for o in order
        }
        duplex_yield = {
            f"min_reads={k}": round(float((lo >= k).mean()), 4)
            for k in (1, 2, 3, 5)
        }
    out = {
        "n_records": info["n_records"],
        "n_valid_reads": int(valid.sum()),
        "n_families": int(fams.n_families),
        "n_molecules": n_mol,
        "mean_family_size": round(float(sizes.mean()), 3) if len(sizes) else 0,
        "max_family_size": int(sizes.max()) if len(sizes) else 0,
        "family_size_hist": hist,
        "n_position_groups": int(len(pg_sizes)),
        "max_position_group": int(pg_sizes.max()) if len(pg_sizes) else 0,
        "duplex_complete_molecules": duplex_mols,
        "duplex_family_size_hist": duplex_size_hist,
        "duplex_yield": duplex_yield,
        "grouping": args.grouping,
    }
    if args.json:
        print(json.dumps(out))
    else:
        for k, v in out.items():
            print(f"{k}: {v}")
    return 0


def _cmd_index(args) -> int:
    if args.bai and args.csi:
        raise SystemExit("--bai and --csi are mutually exclusive")
    if args.csi:
        from duplexumiconsensusreads_tpu.io.csi import build_csi

        out = build_csi(args.input, args.output)
        print(f"[duplexumi] wrote standard CSI → {out}", file=sys.stderr)
        return 0
    if args.bai:
        from duplexumiconsensusreads_tpu.io.bai import build_bai

        out = build_bai(args.input, args.output)
        print(f"[duplexumi] wrote standard BAI → {out}", file=sys.stderr)
        return 0
    from duplexumiconsensusreads_tpu.io.index import INDEX_SUFFIX, build_linear_index

    out = args.output or args.input + INDEX_SUFFIX
    idx = build_linear_index(args.input, every=args.every)
    idx.save(out)
    print(
        f"[duplexumi] indexed {idx.n_records} records "
        f"({len(idx.pos_key)} entries, every {idx.every}) → {out}",
        file=sys.stderr,
    )
    return 0


def _cmd_bench(args) -> int:
    import os

    if args.reads:
        os.environ["DUT_BENCH_READS"] = str(args.reads)
    if args.capacity:
        os.environ["DUT_BENCH_CAPACITY"] = str(args.capacity)
    from duplexumiconsensusreads_tpu.benchmark import main as bench_main

    bench_main()
    return 0


def _cmd_group(args) -> int:
    """The standalone UmiGrouper operator boundary at the CLI: annotate
    every groupable read with its molecule id (MI:Z), leaving the
    records otherwise untouched — consensus-free UMI grouping, the
    fgbio GroupReadsByUmi workflow. Duplex mode appends the /A or /B
    strand suffix to MI (top/bottom strand of the source molecule).

    The TPU backend groups per position-tiled bucket exactly like the
    `call` path (adjacency is position-local, so bucket-local molecule
    ids renumber to the identical whole-file grouping PARTITION) — the
    device matrices stay u_max^2 per BUCKET, never per file. MI values
    are opaque labels: the read partition is backend-identical, but the
    numbering may differ between backends when oversized position
    groups reorder bucket emission. Two result-changing fallbacks can
    break exact partition identity (precluster on oversized position
    groups may miss cross-piece adjacency merges; jumbo hard-cuts split
    one molecule across MI values) — both are tallied via the same
    FALLBACK_COUNTERS as `call` and surfaced in the summary when
    nonzero. Host memory holds the whole record
    set (annotation needs every record); for inputs beyond that, run
    `call --chunk-reads`.
    """
    import numpy as np

    from duplexumiconsensusreads_tpu.bucketing import build_buckets
    from duplexumiconsensusreads_tpu.io.bam import (
        make_aux_z,
        read_bam,
        strip_aux_tag,
        write_bam,
    )
    from duplexumiconsensusreads_tpu.io.convert import records_to_readbatch
    from duplexumiconsensusreads_tpu.oracle import group_reads
    from duplexumiconsensusreads_tpu.types import GroupingParams
    from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache

    if args.capacity < 1:
        raise SystemExit(f"--capacity must be >= 1 (got {args.capacity})")
    # per_host_cpu: stale XLA:CPU AOT artifacts from another host can
    # SIGILL (see utils/compile_cache.py) - JAX_PLATFORMS=cpu runs are
    # first-class here, so the cache keys on the host CPU
    enable_compile_cache(per_host_cpu=True)
    header, recs = read_bam(args.input)
    wl = None
    if args.umi_whitelist:
        wl = _load_whitelist_or_exit(args.umi_whitelist)
    try:
        batch, info = records_to_readbatch(
            recs, duplex=args.duplex,
            umi_whitelist=wl, umi_max_mismatches=args.umi_max_mismatches,
        )
    except ValueError as e:
        if wl is not None and "whitelist" in str(e):
            raise SystemExit(f"--umi-whitelist: {e}")
        raise
    from duplexumiconsensusreads_tpu.runtime.executor import resolve_mate_aware

    gp = GroupingParams(
        strategy=args.grouping,
        max_hamming=args.max_hamming,
        count_ratio=args.count_ratio,
        paired=args.duplex,
    )
    # the SAME auto-detection as call: MI annotations must reproduce the
    # molecule structure call actually consensuses on the same flags
    gp = resolve_mate_aware(gp, info, args.mate_aware)
    # MI carries the SOURCE MOLECULE: under mate-aware grouping that is
    # pair_id (a template's R1 and R2 units share it); otherwise it is
    # molecule_id (the two are equal without mate awareness)
    n = len(recs)
    mol = np.full(n, -1, np.int64)
    n_mol_total = n_fam_total = 0
    counters: dict = {}
    if args.backend == "cpu":
        fams = group_reads(batch, gp)
        src = np.asarray(fams.pair_id if gp.mate_aware else fams.molecule_id)
        mol[:] = src
        n_mol_total = int(src.max()) + 1 if (src >= 0).any() else 0
        n_fam_total = int(fams.n_families)
    else:
        from duplexumiconsensusreads_tpu.bucketing.buckets import _pow2
        from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel

        for bk in build_buckets(
            batch, capacity=args.capacity, grouping=gp, counters=counters
        ):
            strategy = "exact" if bk.preclustered else gp.strategy
            _, mids, pairs, n_fam, n_mol, n_over = group_kernel(
                bk.pos, bk.umi, bk.strand_ab, bk.frag_end, bk.valid,
                strategy=strategy,
                max_hamming=gp.max_hamming,
                count_ratio=gp.count_ratio,
                paired=gp.paired,
                mate_aware=gp.mate_aware,
                u_max=min(_pow2(max(bk.n_unique_umi, 1)), bk.capacity),
                presorted=True,
            )
            mids = np.asarray(mids)
            if int(n_over) != 0:
                # production invariant (u_max >= bucket unique count),
                # not a debug check: under `python -O` an assert would
                # let overflowed reads silently drop from MI tagging
                raise RuntimeError(
                    f"group: {int(n_over)} reads overflowed u_max in a "
                    f"bucket (capacity {bk.capacity}); this is a bug in "
                    f"bucket sizing — please report"
                )
            ids = np.asarray(pairs) if gp.mate_aware else mids
            sel = (bk.read_index >= 0) & bk.valid & (ids >= 0) & (mids >= 0)
            # bucket-local dense renumber of the chosen id space (pair
            # ids are dense molecule ranks, but their count is not a
            # kernel output — derive it from the bucket's own values)
            uniq, inv = np.unique(ids[sel], return_inverse=True)
            mol[bk.read_index[sel]] = inv + n_mol_total
            n_mol_total += len(uniq)
            n_fam_total += int(n_fam)
    valid = np.asarray(batch.valid, bool)
    strand = np.asarray(batch.strand_ab, bool)
    tagged = valid & (mol >= 0)
    # strip stale MI from EVERY record (not just re-tagged ones): an
    # input annotated under a different run's numbering would otherwise
    # leave old ids on untagged reads, colliding with this run's
    # molecule-id space
    for i in range(n):
        if b"MI" in recs.aux_raw[i]:
            recs.aux_raw[i] = strip_aux_tag(recs.aux_raw[i], "MI")
    for i in np.nonzero(tagged)[0]:
        mi = str(int(mol[i]))
        if args.duplex:
            mi += "/A" if strand[i] else "/B"
        recs.aux_raw[i] = recs.aux_raw[i] + make_aux_z("MI", mi)
    from duplexumiconsensusreads_tpu.io.bam import derive_output_header

    header = derive_output_header(header, sort_order=None)
    write_bam(args.output, header, recs)
    summary = {
        "n_records": len(recs),
        "n_tagged": int(tagged.sum()),
        "n_molecules": n_mol_total,
        "n_families": n_fam_total,
        "grouping": args.grouping,
        "backend": args.backend,
        "mate_aware": gp.mate_aware,
    }
    nonzero = {k: v for k, v in counters.items() if v}
    if nonzero:
        summary["fallbacks"] = nonzero
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"[duplexumi] {summary['n_tagged']}/{summary['n_records']} reads "
            f"tagged with MI across {summary['n_molecules']} molecules "
            f"({summary['n_families']} families, {args.grouping}) → "
            f"{args.output}",
            file=sys.stderr,
        )
    if nonzero:
        print(
            f"[duplexumi] WARNING: result-changing grouping fallbacks fired: "
            f"{nonzero} — MI partition may deviate from whole-file oracle "
            f"grouping (precluster can miss cross-piece merges; jumbo "
            f"hard-cuts split molecules)",
            file=sys.stderr,
        )
    return 0


def _cmd_view(args) -> int:
    """Region query through the tool's OWN standard .bai — the
    consuming side of `index --bai` / `call --write-index` (a written
    index nobody reads is unproven; this is the samtools-view
    analogue). One seek + forward scan: the file is coordinate-sorted,
    so the spec §5.3 candidate bins + linear-index floor yield a start
    virtual offset, and the scan stops at the first record starting at
    or past the region end."""
    import os as _os
    import re as _re

    import numpy as np

    from duplexumiconsensusreads_tpu.io.bai import (
        build_bai,
        query_start_voffset,
        read_bai,
    )
    from duplexumiconsensusreads_tpu.io.bam import derive_output_header, write_bam
    from duplexumiconsensusreads_tpu.runtime.stream import (
        BamStreamReader,
        _records_from_raw,
    )

    rdr = BamStreamReader(args.input)
    header = rdr.header
    rdr.close()
    # Reference names may themselves contain ':' (GRCh38 HLA alt
    # contigs), so resolve samtools-style: the whole string as a name
    # first, then the longest header name followed by :BEG-END.
    ref_name, g_beg, g_end = None, None, None
    if args.region in header.ref_names:
        ref_name = args.region
    else:
        m = _re.fullmatch(r"(.+):(\d+)-(\d+)", args.region)
        if m and m.group(1) in header.ref_names:
            ref_name, g_beg, g_end = m.group(1), m.group(2), m.group(3)
    if ref_name is None:
        raise SystemExit(
            f"unknown reference in region {args.region!r} (want REF or "
            f"REF:BEG-END with REF from the header)"
        )
    ref_id = header.ref_names.index(ref_name)
    ref_len = header.ref_lengths[ref_id]
    # samtools convention: 1-based inclusive input -> 0-based half-open
    beg = int(g_beg) - 1 if g_beg else 0
    end = int(g_end) if g_end else ref_len
    if beg < 0 or end <= beg:
        raise SystemExit(f"bad region bounds in {args.region!r}")

    # index resolution: an existing .bai, else an existing .csi, else
    # build one — BAI by default, CSI when a contig exceeds BAI's 2^29
    # coordinate space (build_bai refuses those loudly)
    bai_path = args.input + ".bai"
    csi_path = args.input + ".csi"
    if not _os.path.exists(bai_path) and not _os.path.exists(csi_path):
        if max(header.ref_lengths, default=0) > (1 << 29):
            print(f"[duplexumi] building {csi_path}", file=sys.stderr)
            from duplexumiconsensusreads_tpu.io.csi import build_csi

            build_csi(args.input)
        else:
            print(f"[duplexumi] building {bai_path}", file=sys.stderr)
            build_bai(args.input)
    if _os.path.exists(bai_path):
        idx = read_bai(bai_path)
        start_v = query_start_voffset(idx, ref_id, beg, end)
    else:
        from duplexumiconsensusreads_tpu.io.csi import (
            query_start_voffset_csi,
            read_csi,
        )

        idx = read_csi(csi_path)
        start_v = query_start_voffset_csi(idx, ref_id, beg, end)

    kept = []
    if start_v is not None:
        rdr = BamStreamReader(
            args.input, start=(start_v >> 16, start_v & 0xFFFF)
        )
        try:
            done = False
            while not done:
                raw = rdr.read_raw_records(4096)
                if raw is None:
                    break
                recs = _records_from_raw(header, raw)
                for i in range(len(recs)):
                    rid, pos = int(recs.ref_id[i]), int(recs.pos[i])
                    if rid != ref_id or pos >= end:
                        # rid < 0 is the unmapped tail, which sorts
                        # LAST — terminal, or a whole-file decode for
                        # zero output on last-reference queries
                        if (
                            rid < 0
                            or rid > ref_id
                            or (rid == ref_id and pos >= end)
                        ):
                            done = True  # sorted: nothing further overlaps
                            break
                        continue  # earlier ref / before the chunk floor
                    span = sum(
                        n for n, op in recs.cigars[i]
                        if op in "MDN=X"
                    ) or 1
                    if pos + span > beg:
                        # copy the row OUT now: retaining (recs, i)
                        # would pin every 4096-record parsed batch with
                        # any hit until output time
                        li = int(recs.lengths[i])
                        kept.append((
                            recs.names[i], int(recs.flags[i]), rid, pos,
                            int(recs.mapq[i]), int(recs.next_ref_id[i]),
                            int(recs.next_pos[i]), int(recs.tlen[i]), li,
                            recs.seq[i, :li].copy(), recs.qual[i, :li].copy(),
                            recs.cigars[i], recs.umi[i], recs.aux_raw[i],
                        ))
        finally:
            rdr.close()

    if args.output:
        from duplexumiconsensusreads_tpu.constants import BASE_PAD
        from duplexumiconsensusreads_tpu.io.bam import BamRecords

        l_max = max((k[8] for k in kept), default=0)

        def _pad(row, fill):
            out = np.full(l_max, fill, np.uint8)
            out[: len(row)] = row
            return out

        out_recs = BamRecords(
            names=[k[0] for k in kept],
            flags=np.array([k[1] for k in kept], np.uint16),
            ref_id=np.array([k[2] for k in kept], np.int32),
            pos=np.array([k[3] for k in kept], np.int32),
            mapq=np.array([k[4] for k in kept], np.uint8),
            next_ref_id=np.array([k[5] for k in kept], np.int32),
            next_pos=np.array([k[6] for k in kept], np.int32),
            tlen=np.array([k[7] for k in kept], np.int32),
            lengths=np.array([k[8] for k in kept], np.int32),
            seq=(
                np.stack([_pad(k[9], BASE_PAD) for k in kept])
                if kept else np.zeros((0, 0), np.uint8)
            ),
            qual=(
                np.stack([_pad(k[10], 0) for k in kept])
                if kept else np.zeros((0, 0), np.uint8)
            ),
            cigars=[k[11] for k in kept],
            umi=[k[12] for k in kept],
            aux_raw=[k[13] for k in kept],
        )
        write_bam(
            args.output, derive_output_header(header, sort_order=None), out_recs
        )
    summary = {
        "region": f"{ref_name}:{beg + 1}-{end}",
        "n_records": len(kept),
        "index": bai_path,
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"[duplexumi] {summary['n_records']} records overlap "
            f"{summary['region']}"
            + (f" → {args.output}" if args.output else ""),
            file=sys.stderr,
        )
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "call":
        return _cmd_call(args)
    if args.cmd == "simulate":
        return _cmd_simulate(args)
    if args.cmd == "validate":
        return _cmd_validate(args)
    if args.cmd == "index":
        return _cmd_index(args)
    if args.cmd == "filter":
        return _cmd_filter(args)
    if args.cmd == "stats":
        return _cmd_stats(args)
    if args.cmd == "bench":
        return _cmd_bench(args)
    if args.cmd == "group":
        return _cmd_group(args)
    if args.cmd == "view":
        return _cmd_view(args)
    raise AssertionError(args.cmd)


if __name__ == "__main__":
    sys.exit(main())
