"""NumPy oracle for the per-cycle base-quality error model (config 5).

Fit: empirical per-cycle disagreement rate between raw reads and their
single-strand family consensus (Laplace-smoothed), expressed as a Phred
cap per cycle. Apply: clip every input quality at its cycle's cap, so
over-confident late-cycle qualities are recalibrated before consensus.
This two-pass (fit on first-pass consensus, re-call with recalibrated
qualities) is the framework's definition of benchmark config 5.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import N_REAL_BASES, NO_FAMILY
from duplexumiconsensusreads_tpu.types import ConsensusBatch, FamilyAssignment, ReadBatch
from duplexumiconsensusreads_tpu.utils.phred import phred_cap_from_counts


def fit_cycle_error_model(
    batch: ReadBatch,
    fams: FamilyAssignment,
    ss_consensus: ConsensusBatch,
    max_phred_cap: int = 60,
) -> np.ndarray:
    """Per-cycle Phred cap (L,) u8 from read-vs-consensus mismatch rates.

    Only cycles where both the read base and its family consensus base
    are real (A/C/G/T) contribute. Rate is (mismatch+1)/(n+2).
    """
    bases = np.asarray(batch.bases)
    fam = np.asarray(fams.family_id)
    valid = np.asarray(batch.valid, bool)
    l = batch.read_len
    mism = np.zeros(l, np.int64)
    total = np.zeros(l, np.int64)
    for i in np.nonzero(valid & (fam != NO_FAMILY))[0]:
        f = fam[i]
        if not ss_consensus.valid[f]:
            continue
        cb = ss_consensus.bases[f]
        ok = (bases[i] < N_REAL_BASES) & (cb < N_REAL_BASES)
        total += ok
        mism += ok & (bases[i] != cb)
    return phred_cap_from_counts(mism, total, max_phred_cap)


def apply_cycle_error_model(quals: np.ndarray, cycle_cap: np.ndarray) -> np.ndarray:
    """Clip qualities (N, L) at the per-cycle cap (L,)."""
    return np.minimum(quals, cycle_cap[None, :]).astype(np.uint8)
