"""NumPy oracle for the per-cycle base-quality error model (config 5).

Fit: empirical per-cycle disagreement rate between raw reads and their
single-strand family consensus (Laplace-smoothed), expressed as a Phred
cap per cycle. Apply: clip every input quality at its cycle's cap, so
over-confident late-cycle qualities are recalibrated before consensus.
This two-pass (fit on first-pass consensus, re-call with recalibrated
qualities) is the framework's definition of benchmark config 5.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import N_REAL_BASES, NO_FAMILY
from duplexumiconsensusreads_tpu.types import ConsensusBatch, FamilyAssignment, ReadBatch


def phred_cap_thresholds(max_phred_cap: int) -> np.ndarray:
    """f32 error-rate thresholds 10^(-q/10) for q = 0..max — the ONE
    table both the oracle and the device kernel compare against; any
    change here changes both sides together."""
    return (10.0 ** (-np.arange(max_phred_cap + 1) / 10.0)).astype(np.float32)


def phred_cap_from_counts(
    mism: np.ndarray, total: np.ndarray, max_phred_cap: int
) -> np.ndarray:
    """floor(-10*log10((mism+1)/(total+2))) clipped to [2, max], computed
    EXACTLY via f32 threshold comparisons.

    cap = #{q in [0..max] : rate <= 10^(-q/10)} - 1. Both sides of each
    comparison are f32 ((m+1) vs (t+2)*thr[q]); IEEE f32 multiply and
    compare give bit-identical answers on NumPy and XLA/TPU, so the
    device kernel (kernels/error_model.py) reproduces this function
    bit-for-bit — a log10 in f32-on-device vs f64-on-host would flip
    caps at floor boundaries and cascade into second-pass consensus
    differences.
    """
    thr = phred_cap_thresholds(max_phred_cap)
    m = (np.asarray(mism) + 1).astype(np.float32)
    t = (np.asarray(total) + 2).astype(np.float32)
    count = (m[:, None] <= t[:, None] * thr[None, :]).sum(axis=1)
    return np.clip(count - 1, 2, max_phred_cap).astype(np.uint8)


def fit_cycle_error_model(
    batch: ReadBatch,
    fams: FamilyAssignment,
    ss_consensus: ConsensusBatch,
    max_phred_cap: int = 60,
) -> np.ndarray:
    """Per-cycle Phred cap (L,) u8 from read-vs-consensus mismatch rates.

    Only cycles where both the read base and its family consensus base
    are real (A/C/G/T) contribute. Rate is (mismatch+1)/(n+2).
    """
    bases = np.asarray(batch.bases)
    fam = np.asarray(fams.family_id)
    valid = np.asarray(batch.valid, bool)
    l = batch.read_len
    mism = np.zeros(l, np.int64)
    total = np.zeros(l, np.int64)
    for i in np.nonzero(valid & (fam != NO_FAMILY))[0]:
        f = fam[i]
        if not ss_consensus.valid[f]:
            continue
        cb = ss_consensus.bases[f]
        ok = (bases[i] < N_REAL_BASES) & (cb < N_REAL_BASES)
        total += ok
        mism += ok & (bases[i] != cb)
    return phred_cap_from_counts(mism, total, max_phred_cap)


def apply_cycle_error_model(quals: np.ndarray, cycle_cap: np.ndarray) -> np.ndarray:
    """Clip qualities (N, L) at the per-cycle cap (L,)."""
    return np.minimum(quals, cycle_cap[None, :]).astype(np.uint8)
