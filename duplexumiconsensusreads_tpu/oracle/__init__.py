from duplexumiconsensusreads_tpu.oracle.grouping import group_reads  # noqa: F401
from duplexumiconsensusreads_tpu.oracle.consensus import (  # noqa: F401
    call_consensus,
    single_strand_consensus,
    duplex_merge,
)
from duplexumiconsensusreads_tpu.oracle.error_model import (  # noqa: F401
    fit_cycle_error_model,
    apply_cycle_error_model,
)
