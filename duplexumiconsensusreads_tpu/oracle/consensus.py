"""NumPy oracle for single-strand and duplex consensus calling.

fgbio-style per-cycle Bayesian consensus (general-knowledge math, see
SURVEY.md §7 "Domain background"; the reference mount was empty so this
oracle *defines* the framework's numerics):

  Per family, per cycle, for candidate base b in {A,C,G,T}:
      loglik[b] = sum over contributing reads i of
                    log(1 - e_i)   if read base == b
                    log(e_i / 3)   otherwise
  with e_i the error prob of the (capped) input quality. Consensus base
  is argmax_b posterior; consensus quality is the Phred of
  1 - max posterior, capped. Cycles with zero depth emit N.

Duplex merge combines the AB- and BA-strand single-strand calls:
agreement boosts quality (sum, capped), disagreement keeps the
higher-quality base at the quality difference, ties and N-inputs emit N.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import (
    BASE_N,
    N_REAL_BASES,
    NO_CALL_QUAL,
    NO_FAMILY,
)
from duplexumiconsensusreads_tpu.types import (
    ConsensusBatch,
    ConsensusParams,
    FamilyAssignment,
    ReadBatch,
)
from duplexumiconsensusreads_tpu.utils.phred import error_to_phred, phred_to_error


def single_strand_consensus(
    bases: np.ndarray,
    quals: np.ndarray,
    params: ConsensusParams,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Consensus of one family: bases/quals (K, L) ->
    (base, qual, depth, err) per cycle; err counts contributing reads
    that disagree with the called base (0 where no call)."""
    k, l = bases.shape
    out_base = np.full(l, BASE_N, np.uint8)
    out_qual = np.full(l, NO_CALL_QUAL, np.uint8)
    depth = np.zeros(l, np.int32)
    err = np.zeros(l, np.int32)
    for c in range(l):
        ll = np.zeros(N_REAL_BASES)
        cnt = np.zeros(N_REAL_BASES, np.int32)
        d = 0
        for i in range(k):
            b = bases[i, c]
            if b >= N_REAL_BASES:  # N or PAD: no evidence
                continue
            if int(quals[i, c]) < params.min_input_qual:  # masked base
                continue
            e = phred_to_error(min(int(quals[i, c]), params.max_input_qual))
            ll += np.log(e / 3.0)
            ll[b] += np.log1p(-e) - np.log(e / 3.0)
            cnt[b] += 1
            d += 1
        depth[c] = d
        if d == 0:
            continue
        ll -= ll.max()
        post = np.exp(ll)
        post /= post.sum()
        b = int(np.argmax(post))
        out_base[c] = b
        out_qual[c] = error_to_phred(1.0 - post[b], params.max_qual)
        err[c] = d - cnt[b]
    return out_base, out_qual, depth, err


def duplex_merge(
    base_ab: np.ndarray,
    qual_ab: np.ndarray,
    depth_ab: np.ndarray,
    err_ab: np.ndarray,
    base_ba: np.ndarray,
    qual_ba: np.ndarray,
    depth_ba: np.ndarray,
    err_ba: np.ndarray,
    params: ConsensusParams,
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Merge the two strand consensi of one molecule, per cycle. The
    error count is the sum of each strand's own-consensus
    disagreements (strand-level discordance shows up as the duplex
    base/qual, not in ce)."""
    l = len(base_ab)
    out_base = np.full(l, BASE_N, np.uint8)
    out_qual = np.full(l, NO_CALL_QUAL, np.uint8)
    depth = (depth_ab + depth_ba).astype(np.int32)
    err = (err_ab + err_ba).astype(np.int32)
    for c in range(l):
        ba, bb = int(base_ab[c]), int(base_ba[c])
        qa, qb = int(qual_ab[c]), int(qual_ba[c])
        if ba >= N_REAL_BASES or bb >= N_REAL_BASES:
            continue
        if ba == bb:
            out_base[c] = ba
            out_qual[c] = min(qa + qb, params.max_qual)
        elif qa != qb:
            out_base[c] = ba if qa > qb else bb
            out_qual[c] = max(abs(qa - qb), NO_CALL_QUAL)
        # qa == qb with disagreeing bases: stays N
    return out_base, out_qual, depth, err


def call_consensus(
    batch: ReadBatch,
    fams: FamilyAssignment,
    params: ConsensusParams,
    quals_override: np.ndarray | None = None,
) -> ConsensusBatch:
    """Call consensus for every family (ss mode) or molecule (duplex mode).

    Output row f corresponds to dense family id f (single_strand) or
    dense molecule id f (duplex). ``quals_override`` substitutes
    recalibrated qualities (error-model path) without touching bases.
    """
    quals = batch.quals if quals_override is None else quals_override
    bases = np.asarray(batch.bases)
    quals = np.asarray(quals)
    fam = np.asarray(fams.family_id)
    mol = np.asarray(fams.molecule_id)
    strand = np.asarray(batch.strand_ab, bool)
    valid = np.asarray(batch.valid, bool)
    l = batch.read_len

    n_fam = int(fams.n_families)
    ss = {}
    for f in range(n_fam):
        sel = np.nonzero((fam == f) & valid)[0]
        if len(sel) < params.min_reads:
            continue
        ss[f] = single_strand_consensus(bases[sel], quals[sel], params)

    if params.mode == "single_strand":
        out = ConsensusBatch(
            bases=np.full((n_fam, l), BASE_N, np.uint8),
            quals=np.full((n_fam, l), NO_CALL_QUAL, np.uint8),
            depth=np.zeros((n_fam, l), np.int32),
            valid=np.zeros(n_fam, bool),
            err=np.zeros((n_fam, l), np.int32),
        )
        for f, (b, q, d, e) in ss.items():
            out.bases[f], out.quals[f], out.depth[f], out.err[f] = b, q, d, e
            out.valid[f] = True
        return out

    if params.mode != "duplex":
        raise ValueError(f"unknown consensus mode {params.mode!r}")

    n_mol = int(fams.n_molecules)
    out = ConsensusBatch(
        bases=np.full((n_mol, l), BASE_N, np.uint8),
        quals=np.full((n_mol, l), NO_CALL_QUAL, np.uint8),
        depth=np.zeros((n_mol, l), np.int32),
        valid=np.zeros(n_mol, bool),
        err=np.zeros((n_mol, l), np.int32),
    )
    for mid in range(n_mol):
        sel_ab = np.nonzero((mol == mid) & valid & strand)[0]
        sel_ba = np.nonzero((mol == mid) & valid & ~strand)[0]
        if (
            len(sel_ab) < params.min_duplex_reads
            or len(sel_ba) < params.min_duplex_reads
        ):
            continue
        fa = fam[sel_ab[0]]
        fb = fam[sel_ba[0]]
        if fa == NO_FAMILY or fb == NO_FAMILY or fa not in ss or fb not in ss:
            continue
        if fa == fb:
            raise ValueError(
                "duplex consensus requires paired grouping "
                "(GroupingParams(paired=True)); got a shared AB/BA family id"
            )
        b, q, d, e = duplex_merge(*ss[fa], *ss[fb], params)
        out.bases[mid], out.quals[mid], out.depth[mid] = b, q, d
        out.err[mid] = e
        out.valid[mid] = True
    return out
