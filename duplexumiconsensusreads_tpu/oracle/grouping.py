"""NumPy oracle for UMI-family grouping (exact + directional adjacency).

This is the semantic reference the TPU kernels are tested against. The
directional adjacency algorithm is the UMI-tools network method
implemented literally: process unique UMIs in descending-count order,
BFS over directed edges ``u -> v`` present iff ``hamming(u, v) <=
max_hamming`` and ``count[u] >= count_ratio*count[v] - 1``, removing
visited nodes. (The TPU kernel computes the provably-equivalent
min-rank-reachability via label propagation; see
kernels/cluster.py for the equivalence argument.)

Determinism: unique UMIs are ranked by (-count, packed_umi); dense
family/molecule ids are assigned in sorted (pos_key, seed_umi[, strand])
order so oracle and kernel agree bit-for-bit.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from duplexumiconsensusreads_tpu.constants import NO_FAMILY
from duplexumiconsensusreads_tpu.types import FamilyAssignment, GroupingParams, ReadBatch
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64


def directional_seeds(
    umis: np.ndarray, counts: np.ndarray, max_hamming: int, count_ratio: int
) -> np.ndarray:
    """Cluster unique UMIs (nU, U) with counts (nU,) -> seed index per UMI.

    Returns, for each unique UMI, the index (into ``umis``) of its
    cluster seed (the highest-count UMI of its cluster). Also used by
    the bucketing layer to host-precluster oversized position groups
    (bucketing/buckets.py), so the edge computation is blocked: peak
    memory is O(nU * block * U) instead of O(nU**2 * U).
    """
    n = len(umis)
    words = pack_umi_words64(umis)  # any UMI length
    # rank 0 = highest count, ties by UMI lexicographic order
    order = np.lexsort(
        (*[words[:, i] for i in range(words.shape[1] - 1, -1, -1)], -counts)
    )
    # adjacency: ham[u, v] and counts[u] >= ratio*counts[v] - 1 (directed u->v)
    edge = np.empty((n, n), bool)
    block = max(1, (64 << 20) // max(n * umis.shape[1], 1))
    for s in range(0, n, block):
        e = min(s + block, n)
        ham = (umis[s:e, None, :] != umis[None, :, :]).sum(axis=2)
        edge[s:e] = (ham <= max_hamming) & (
            counts[s:e, None] >= count_ratio * counts[None, :] - 1
        )
    np.fill_diagonal(edge, False)

    seed_of = np.full(n, -1, np.int64)
    for u in order:
        if seed_of[u] >= 0:
            continue
        seed_of[u] = u
        q = deque([u])
        while q:
            a = q.popleft()
            for b in np.nonzero(edge[a])[0]:
                if seed_of[b] < 0:
                    seed_of[b] = u
                    q.append(b)
    return seed_of


def group_reads(batch: ReadBatch, params: GroupingParams) -> FamilyAssignment:
    """Assign family/molecule ids to every valid read in the batch.

    Molecule identity is (pos_key, clustered-UMI); in paired (duplex)
    mode a molecule has up to two single-strand families distinguished
    by strand_ab, ordered AB-before-BA in the dense family numbering.
    In unpaired mode family == molecule and strand is ignored.

    Mate-aware mode (params.mate_aware) additionally splits families by
    the fragment-end bit — a template's R1 and R2 mates cover opposite
    fragment ends, so their cycles must never share a consensus column.
    The reported molecule_id then becomes the dense (molecule,
    frag_end) unit (each unit is one duplex output: its AB family holds
    one mate's top-strand reads, its BA family the OTHER mate's
    bottom-strand reads — the fgbio cross-mate pairing), and pair_id
    keeps the true molecule for R1/R2 mate linking at emission.
    """
    n = batch.n_reads
    valid = np.asarray(batch.valid, bool)
    pos = np.asarray(batch.pos_key, np.int64)
    umi = np.asarray(batch.umi, np.uint8)
    strand = np.asarray(batch.strand_ab, bool)
    e2 = np.asarray(batch.frag_end, bool)

    # Resolved per-read cluster UMI (packed words — any UMI length)
    # after exact/adjacency grouping.
    n_words = pack_umi_words64(umi[:1]).shape[1] if n else 1
    cluster_umi = np.full((n, n_words), -1, np.int64)
    idx_valid = np.nonzero(valid)[0]
    if params.strategy == "exact":
        cluster_umi[idx_valid] = pack_umi_words64(umi[idx_valid])
    elif params.strategy in ("adjacency", "cluster"):
        # "cluster" (UMI-tools cluster method) is adjacency with the
        # count condition removed: effective_count_ratio 0 makes every
        # Hamming-<=h edge bidirectional, so the BFS labels whole
        # connected components by their highest-count member
        for p in np.unique(pos[idx_valid]):
            sel = idx_valid[pos[idx_valid] == p]
            uu, inv, cnt = np.unique(
                umi[sel], axis=0, return_inverse=True, return_counts=True
            )
            seed_of = directional_seeds(
                uu, cnt, params.max_hamming, params.effective_count_ratio
            )
            cluster_umi[sel] = pack_umi_words64(uu)[seed_of][inv]
    else:
        raise ValueError(f"unknown grouping strategy {params.strategy!r}")

    # Dense molecule ids over (pos_key, cluster_umi), sorted.
    mol_key = np.column_stack([pos, cluster_umi])
    molecule_id = np.full(n, NO_FAMILY, np.int32)
    pair_id = np.full(n, NO_FAMILY, np.int32)
    fam_id = np.full(n, NO_FAMILY, np.int32)
    if len(idx_valid):
        _, mol_inv = np.unique(mol_key[idx_valid], axis=0, return_inverse=True)
        pair_id[idx_valid] = mol_inv.astype(np.int32)
        bits = []
        if params.mate_aware:
            bits.append(e2[idx_valid].astype(np.int64))
        if params.paired:
            bits.append((~strand[idx_valid]).astype(np.int64))
        if bits:
            fam_key = np.stack([mol_inv, *bits], axis=1)
            _, fam_inv = np.unique(fam_key, axis=0, return_inverse=True)
            fam_id[idx_valid] = fam_inv.astype(np.int32)
        else:
            fam_id[idx_valid] = mol_inv.astype(np.int32)
        if params.mate_aware and params.paired:
            unit_key = np.stack([mol_inv, e2[idx_valid].astype(np.int64)], axis=1)
            _, unit_inv = np.unique(unit_key, axis=0, return_inverse=True)
            molecule_id[idx_valid] = unit_inv.astype(np.int32)
        else:
            molecule_id[idx_valid] = mol_inv.astype(np.int32)

    n_mol = int(molecule_id.max() + 1) if len(idx_valid) else 0
    n_fam = int(fam_id.max() + 1) if len(idx_valid) else 0
    return FamilyAssignment(
        family_id=fam_id,
        molecule_id=molecule_id,
        pair_id=pair_id,
        n_families=np.int32(n_fam),
        n_molecules=np.int32(n_mol),
    )
