from duplexumiconsensusreads_tpu.parallel.mesh import make_mesh  # noqa: F401
from duplexumiconsensusreads_tpu.parallel.sharded import (  # noqa: F401
    sharded_pipeline,
    shard_stacked,
)
