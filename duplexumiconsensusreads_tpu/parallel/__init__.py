from duplexumiconsensusreads_tpu.parallel.distributed import (  # noqa: F401
    host_tile_range,
    init_distributed,
)
from duplexumiconsensusreads_tpu.parallel.mesh import make_mesh  # noqa: F401
from duplexumiconsensusreads_tpu.parallel.sharded import (  # noqa: F401
    presharded_pipeline,
    shard_stacked,
    sharded_pipeline,
)
