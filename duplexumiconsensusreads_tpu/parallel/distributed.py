"""Multi-host initialisation and input partitioning.

The distributed execution model (the NCCL/MPI-backend analogue, done
the JAX way): every host runs the same program; jax.distributed wires
the hosts into one runtime whose jax.devices() spans all chips; the
('data'[, 'cycle']) mesh then shards buckets across hosts over ICI/DCN
with GSPMD. Because buckets are independent, the compiled program has
no cross-device collectives — multi-host scaling is input partitioning
plus a final per-host gather of the consensus shards each host owns.

Input partitioning for BAMs: hosts take disjoint genomic-tile ranges
(`host_tile_range`), stream their range with the chunked executor, and
write per-host outputs that concatenate like shards (BGZF members).
"""

from __future__ import annotations

import os

import jax


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    auto: bool = False,
) -> dict:
    """Initialise jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID).
    No-op on single process.

    auto=True additionally attempts a bare jax.distributed.initialize()
    when nothing is configured explicitly: on managed deployments
    (cloud TPU pods, SLURM) initialize() auto-detects the cluster from
    the platform environment, and that detection only runs INSIDE
    initialize() — the guard below would otherwise skip it and leave
    multi-host runs uncoordinated exactly where coordination matters
    most. Falls back to single-process when no cluster is detected.

    Returns {"process_id", "num_processes", "local_devices",
    "global_devices"} for logging.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    elif auto and _coordination_state() is None:
        try:
            jax.distributed.initialize()
        except (ValueError, RuntimeError):
            pass  # no cluster environment detected: single process
    # Identity comes from the COORDINATION runtime when one is up, not
    # from the backend client: backends without cross-process device
    # fabric (e.g. plain XLA-CPU) report process_count()==1 even though
    # the processes are wired into one coordination service — which is
    # all the input-partitioned executors need (each host's compute is
    # local by design; coordination covers rendezvous + shared-file
    # election + failure detection).
    st = _coordination_state()
    if st is not None and st.client is not None:
        return {
            "process_id": int(st.process_id),
            "num_processes": int(st.num_processes),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices()),
        }
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def _coordination_state():
    """The live coordination-service state, or None. Reaches into
    jax._src (no public accessor exists for the coordination client);
    every use below degrades to a no-op if the layout ever changes."""
    try:
        from jax._src import distributed as _d

        st = _d.global_state
        if getattr(st, "coordinator_address", None) is None:
            return None
        return st
    except Exception:
        return None


def coordination_barrier(name: str, timeout_ms: int = 600_000) -> bool:
    """Rendezvous all processes at ``name`` via the coordination
    service. Returns False (no-op) when not running distributed."""
    st = _coordination_state()
    if st is None or st.client is None or (st.num_processes or 1) <= 1:
        return False
    st.client.wait_at_barrier(name, timeout_ms)
    return True


def host_tile_range(
    n_tiles: int,
    process_id: int | None = None,
    num_processes: int | None = None,
) -> range:
    """This host's contiguous share of n_tiles genomic tiles.

    Tiles (position-key ranges) are the unit of input partitioning:
    each host streams only its BAM region, so the input pipeline scales
    with hosts exactly like the device pipeline does with chips.
    """
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    per = -(-n_tiles // n)
    return range(min(pid * per, n_tiles), min((pid + 1) * per, n_tiles))


def host_input_range(
    index,
    process_id: int | None = None,
    num_processes: int | None = None,
):
    """This host's share of a BAM, as a streaming input_range.

    The BamLinearIndex's sampled entries are the tiles host_tile_range
    partitions; each host's tile run maps to (start_voffset, key_lo,
    key_hi) — a BGZF seek point plus a pos_key half-open interval —
    consumable by stream_call_consensus(input_range=...). Returns None
    for an idle host (empty or degenerate share). The ranges of all
    hosts partition the key space exactly: every family lands on
    exactly one host (families never span pos_keys).
    """
    n_tiles = len(index.pos_key)
    if n_tiles == 0:
        pid = jax.process_index() if process_id is None else process_id
        # record-less file: host 0 runs the normal (no-seek) path so the
        # output still gets a header; everyone else is idle
        return (None, None, None) if pid == 0 else None
    r = host_tile_range(n_tiles, process_id, num_processes)
    if r.start >= r.stop:
        return None
    key_lo = int(index.pos_key[r.start]) if r.start > 0 else None
    key_hi = int(index.pos_key[r.stop]) if r.stop < n_tiles else None
    if key_lo is not None and key_hi is not None and key_lo >= key_hi:
        return None  # a giant same-key run swallowed this host's share
    start = index.start_voffset(key_lo)
    return (start, key_lo, key_hi)


def multihost_call(
    in_path: str,
    out_path: str,
    grouping,
    consensus,
    index_path: str | None = None,
    process_id: int | None = None,
    num_processes: int | None = None,
    index_every: int = 100_000,
    **stream_kw,
):
    """Run this host's partition of a consensus call.

    Each host writes ``out_path`` (conventionally suffixed with the
    host id by the caller); concatenating the per-host outputs in host
    order yields the whole-file result. Builds/loads the linear index
    on demand (host 0 of a pod should pre-build it; building is a
    sequential scan).
    """
    from duplexumiconsensusreads_tpu.io.index import (
        INDEX_SUFFIX,
        BamLinearIndex,
        build_linear_index,
    )
    from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

    idx_path = index_path or in_path + INDEX_SUFFIX
    pid_eff = jax.process_index() if process_id is None else process_id
    st = _coordination_state()
    coordinated = (
        st is not None and st.client is not None and (st.num_processes or 1) > 1
    )
    if coordinated:
        pid_eff = int(st.process_id)
    if coordinated:
        # Index-build election under a live coordination runtime: EVERY
        # host passes BOTH barriers unconditionally — the exists() check
        # happens only inside host 0's critical section. Hosts must
        # never branch on their own exists() view before a barrier (NFS
        # attribute caches can disagree across hosts, and a host that
        # skipped a barrier would deadlock the rest), and only host 0
        # ever writes, so concurrent builds of the same index file
        # cannot race on shared storage. The done-barrier timeout must
        # outlast a sequential scan of a pod-scale input (hours, not
        # the default 10 minutes).
        coordination_barrier("duplexumi:index:elect")
        if pid_eff == 0 and not os.path.exists(idx_path):
            build_linear_index(in_path, every=index_every).save(idx_path)
        coordination_barrier("duplexumi:index:done", timeout_ms=6 * 3600 * 1000)
        index = BamLinearIndex.load(idx_path)
    elif os.path.exists(idx_path):
        index = BamLinearIndex.load(idx_path)
    else:
        index = build_linear_index(in_path, every=index_every)
        index.save(idx_path)
    rng = host_input_range(index, process_id, num_processes)
    pid = jax.process_index() if process_id is None else process_id
    if rng is None:
        return None  # idle host: no records in range
    return stream_call_consensus(
        in_path,
        out_path,
        grouping,
        consensus,
        input_range=rng,
        name_tag=f"h{pid}_",
        **stream_kw,
    )
