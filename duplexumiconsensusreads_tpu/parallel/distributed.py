"""Multi-host initialisation and input partitioning.

The distributed execution model (the NCCL/MPI-backend analogue, done
the JAX way): every host runs the same program; jax.distributed wires
the hosts into one runtime whose jax.devices() spans all chips; the
('data'[, 'cycle']) mesh then shards buckets across hosts over ICI/DCN
with GSPMD. Because buckets are independent, the compiled program has
no cross-device collectives — multi-host scaling is input partitioning
plus a final per-host gather of the consensus shards each host owns.

Input partitioning for BAMs: hosts take disjoint genomic-tile ranges
(`host_tile_range`), stream their range with the chunked executor, and
write per-host outputs that concatenate like shards (BGZF members).
"""

from __future__ import annotations

import os

import jax


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Initialise jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID —
    cloud TPU pods auto-detect all three). No-op on single process.

    Returns {"process_id", "num_processes", "local_devices",
    "global_devices"} for logging.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_tile_range(
    n_tiles: int,
    process_id: int | None = None,
    num_processes: int | None = None,
) -> range:
    """This host's contiguous share of n_tiles genomic tiles.

    Tiles (position-key ranges) are the unit of input partitioning:
    each host streams only its BAM region, so the input pipeline scales
    with hosts exactly like the device pipeline does with chips.
    """
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    per = -(-n_tiles // n)
    return range(min(pid * per, n_tiles), min((pid + 1) * per, n_tiles))


def host_input_range(
    index,
    process_id: int | None = None,
    num_processes: int | None = None,
):
    """This host's share of a BAM, as a streaming input_range.

    The BamLinearIndex's sampled entries are the tiles host_tile_range
    partitions; each host's tile run maps to (start_voffset, key_lo,
    key_hi) — a BGZF seek point plus a pos_key half-open interval —
    consumable by stream_call_consensus(input_range=...). Returns None
    for an idle host (empty or degenerate share). The ranges of all
    hosts partition the key space exactly: every family lands on
    exactly one host (families never span pos_keys).
    """
    n_tiles = len(index.pos_key)
    if n_tiles == 0:
        pid = jax.process_index() if process_id is None else process_id
        # record-less file: host 0 runs the normal (no-seek) path so the
        # output still gets a header; everyone else is idle
        return (None, None, None) if pid == 0 else None
    r = host_tile_range(n_tiles, process_id, num_processes)
    if r.start >= r.stop:
        return None
    key_lo = int(index.pos_key[r.start]) if r.start > 0 else None
    key_hi = int(index.pos_key[r.stop]) if r.stop < n_tiles else None
    if key_lo is not None and key_hi is not None and key_lo >= key_hi:
        return None  # a giant same-key run swallowed this host's share
    start = index.start_voffset(key_lo)
    return (start, key_lo, key_hi)


def multihost_call(
    in_path: str,
    out_path: str,
    grouping,
    consensus,
    index_path: str | None = None,
    process_id: int | None = None,
    num_processes: int | None = None,
    index_every: int = 100_000,
    **stream_kw,
):
    """Run this host's partition of a consensus call.

    Each host writes ``out_path`` (conventionally suffixed with the
    host id by the caller); concatenating the per-host outputs in host
    order yields the whole-file result. Builds/loads the linear index
    on demand (host 0 of a pod should pre-build it; building is a
    sequential scan).
    """
    from duplexumiconsensusreads_tpu.io.index import (
        INDEX_SUFFIX,
        BamLinearIndex,
        build_linear_index,
    )
    from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

    idx_path = index_path or in_path + INDEX_SUFFIX
    if os.path.exists(idx_path):
        index = BamLinearIndex.load(idx_path)
    else:
        index = build_linear_index(in_path, every=index_every)
        index.save(idx_path)
    rng = host_input_range(index, process_id, num_processes)
    pid = jax.process_index() if process_id is None else process_id
    if rng is None:
        return None  # idle host: no records in range
    return stream_call_consensus(
        in_path,
        out_path,
        grouping,
        consensus,
        input_range=rng,
        name_tag=f"h{pid}_",
        **stream_kw,
    )
