"""Multi-host initialisation and input partitioning.

The distributed execution model (the NCCL/MPI-backend analogue, done
the JAX way): every host runs the same program; jax.distributed wires
the hosts into one runtime whose jax.devices() spans all chips; the
('data'[, 'cycle']) mesh then shards buckets across hosts over ICI/DCN
with GSPMD. Because buckets are independent, the compiled program has
no cross-device collectives — multi-host scaling is input partitioning
plus a final per-host gather of the consensus shards each host owns.

Input partitioning for BAMs: hosts take disjoint genomic-tile ranges
(`host_tile_range`), stream their range with the chunked executor, and
write per-host outputs that concatenate like shards (BGZF members).
"""

from __future__ import annotations

import os

import jax


def init_distributed(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Initialise jax.distributed from args or the standard env vars
    (JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID —
    cloud TPU pods auto-detect all three). No-op on single process.

    Returns {"process_id", "num_processes", "local_devices",
    "global_devices"} for logging.
    """
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes if num_processes is not None else (
        int(env_np) if env_np else None
    )
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    if coordinator_address or (num_processes or 0) > 1:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return {
        "process_id": jax.process_index(),
        "num_processes": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def host_tile_range(
    n_tiles: int,
    process_id: int | None = None,
    num_processes: int | None = None,
) -> range:
    """This host's contiguous share of n_tiles genomic tiles.

    Tiles (position-key ranges) are the unit of input partitioning:
    each host streams only its BAM region, so the input pipeline scales
    with hosts exactly like the device pipeline does with chips.
    """
    pid = jax.process_index() if process_id is None else process_id
    n = jax.process_count() if num_processes is None else num_processes
    per = -(-n_tiles // n)
    return range(min(pid * per, n_tiles), min((pid + 1) * per, n_tiles))
