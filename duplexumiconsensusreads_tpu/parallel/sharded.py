"""Mesh-sharded execution of the fused pipeline (benchmark config 4).

Design: stacked buckets (B, R, ...) are sharded over the mesh's 'data'
axis with jax.sharding.NamedSharding; the fused per-bucket pipeline is
vmapped over the bucket axis and jitted with those shardings. XLA
partitions the whole computation with zero collectives (buckets are
independent); results come back sharded and are gathered host-side
only for the final write. This is the pjit/GSPMD idiom — no NCCL-style
explicit communication, per the TPU-first design mandate.
"""

from __future__ import annotations

from functools import partial

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from duplexumiconsensusreads_tpu.ops.pipeline import PipelineSpec, fused_pipeline

_ARRAY_KEYS = ("pos", "umi", "strand_ab", "frag_end", "valid", "bases", "quals")


def stacked_nbytes(stacked: dict) -> int:
    """Bytes of the stacked arrays that actually cross the wire (the
    _ARRAY_KEYS device_put set). The stacked dict also carries host-only
    bookkeeping (read_index, n_real_buckets) that shard_stacked never
    transfers — summing the whole dict would overstate the H2D ledger
    by ~5% (8 bytes of i64 read_index per read slot)."""
    return sum(stacked[k].nbytes for k in _ARRAY_KEYS)


def shard_stacked(stacked: dict, mesh: Mesh, axis: str = "data") -> dict:
    """Device-put the stacked bucket arrays with bucket-axis sharding.

    On a ('data', 'cycle') mesh the (B, R, L) bases/quals tensors are
    additionally sharded along L — per-cycle consensus math needs no
    collectives, so this is free sequence parallelism for long reads.
    """
    sh = NamedSharding(mesh, P(axis))
    out = {}
    has_cycle = "cycle" in mesh.axis_names
    sh_cycle = NamedSharding(mesh, P(axis, None, "cycle")) if has_cycle else sh
    for k in _ARRAY_KEYS:
        out[k] = jax.device_put(
            stacked[k], sh_cycle if k in ("bases", "quals") else sh
        )
    return out


@partial(jax.jit, static_argnames=("spec",))
def _vmapped(pos, umi, strand_ab, frag_end, valid, bases, quals, spec):
    return jax.vmap(
        lambda *a: fused_pipeline(*a, spec)
    )(pos, umi, strand_ab, frag_end, valid, bases, quals)


# (mesh, spec) -> jitted shard_map pipeline. Mesh hashes by device ids
# + axis names, so a serve daemon's per-slice mesh objects and the
# streaming executor's per-run ones all hit one compiled program.
_SHMAP_CACHE: dict = {}


def _shmap_pipeline(mesh: Mesh, spec: PipelineSpec):
    """The multi-device 1-D form: shard_map over the 'data' axis, a
    LOCAL vmap of the fused pipeline inside each shard.

    This is a liveness requirement, not a style choice. Under a plain
    jit-of-vmap with GSPMD sharding, the grouping kernels' while loops
    batch their conditions with a reduce-or across the BUCKET axis —
    the sharded axis — so XLA materialises a per-iteration 1-element
    PRED AllReduce. Collectives mean every device must rendezvous per
    program, and the streaming executor launches sharded programs
    CONCURRENTLY from its transfer/drain pools: two in-flight programs
    can interleave their rendezvous order across devices and deadlock
    (reproduced on XLA:CPU; the hazard is launch-order, so it is
    timing-dependent everywhere). shard_map compiles the body as
    manual per-device SPMD — each device loops over ITS buckets only,
    zero collectives by construction, which is exactly the
    embarrassingly-parallel semantics this mesh documents."""
    key = (mesh, spec)
    fn = _SHMAP_CACHE.get(key)
    if fn is None:
        from jax.experimental.shard_map import shard_map

        def local(pos, umi, strand_ab, frag_end, valid, bases, quals):
            return jax.vmap(lambda *a: fused_pipeline(*a, spec))(
                pos, umi, strand_ab, frag_end, valid, bases, quals
            )

        fn = jax.jit(
            shard_map(
                local, mesh=mesh,
                in_specs=P("data"), out_specs=P("data"),
                check_rep=False,
            )
        )
        _SHMAP_CACHE[key] = fn
    return fn


def presharded_pipeline(args: dict, spec: PipelineSpec, mesh: Mesh) -> dict:
    """Run the pipeline on already-device-resident sharded args (from
    shard_stacked) — the pure-compute path benchmarks should time.
    Multi-device 1-D meshes take the per-shard shard_map form (see
    :func:`_shmap_pipeline`); single-device and ('data', 'cycle')
    meshes keep the GSPMD jit-of-vmap (cycle sharding is a genuine
    cross-cycle partition the manual form does not express — and with
    one data shard per program there is no sharded-axis reduction to
    turn into a collective)."""
    ordered = (
        args["pos"], args["umi"], args["strand_ab"], args["frag_end"],
        args["valid"], args["bases"], args["quals"],
    )
    if mesh.devices.size > 1 and "cycle" not in mesh.axis_names:
        return _shmap_pipeline(mesh, spec)(*ordered)
    with mesh:
        return _vmapped(*ordered, spec)


def sharded_pipeline(
    stacked: dict, spec: PipelineSpec, mesh: Mesh, axis: str = "data"
) -> dict:
    """Run all buckets across the mesh; returns stacked outputs (B, ...)."""
    return presharded_pipeline(shard_stacked(stacked, mesh, axis), spec, mesh)
