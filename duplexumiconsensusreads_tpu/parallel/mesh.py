"""Device mesh construction for bucket-parallel execution.

The workload is embarrassingly parallel over buckets (each bucket is a
closed set of position groups), so the mesh is a single 'data' axis:
buckets shard across chips over ICI, and the only cross-device traffic
is the final host gather of consensus tensors. Multi-host meshes work
unchanged — jax.sharding places bucket shards on each host's local
chips and XLA rides ICI/DCN as needed.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(n_devices: int | None = None, axis: str = "data") -> Mesh:
    devs = jax.devices()
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    return Mesh(np.array(devs[:n]), (axis,))
