"""Device mesh construction for bucket- and cycle-parallel execution.

The workload is embarrassingly parallel over buckets (each bucket is a
closed set of position groups), so the primary mesh axis is 'data':
buckets shard across chips over ICI, and the only cross-device traffic
is the final host gather of consensus tensors.

A second, optional 'cycle' axis shards the read-length dimension — the
sequence-parallelism analogue for this domain. Consensus math is
per-cycle independent (log-likelihood accumulation contracts over
reads, never cycles), so cycle shards need ZERO collectives; grouping
ignores the cycle axis entirely and is replicated by GSPMD. Use it for
long-read workloads (multi-kb cycles) where one chip's share of a
bucket's (R, L) tensor would otherwise blow past VMEM-friendly sizes.

Multi-host: call parallel.distributed.init_distributed() first; after
that jax.devices() spans every host and these meshes shard across
ICI/DCN exactly the same way (GSPMD inserts nothing extra because the
program has no cross-bucket communication).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(
    n_devices: int | None = None,
    axis: str = "data",
    cycle_shards: int = 1,
    devices=None,
) -> Mesh:
    """A ('data',) mesh, or ('data', 'cycle') when cycle_shards > 1.

    n_devices counts TOTAL devices used; it must be divisible by
    cycle_shards. ``devices`` overrides the device pool (default: all
    of jax.devices()). Under an initialized multi-controller runtime
    the INPUT-PARTITIONED executors must pass jax.local_devices():
    each host streams a different input range, so its compiled programs
    are host-local, and a global mesh would (a) be illegal
    multi-controller SPMD (different programs per host) and (b) on a
    non-zero host select another host's devices.
    """
    devs = list(jax.devices() if devices is None else devices)
    n = n_devices or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} devices, have {len(devs)}")
    if cycle_shards <= 1:
        return Mesh(np.array(devs[:n]), (axis,))
    if n % cycle_shards:
        raise ValueError(
            f"n_devices {n} not divisible by cycle_shards {cycle_shards}"
        )
    arr = np.array(devs[:n]).reshape(n // cycle_shards, cycle_shards)
    return Mesh(arr, (axis, "cycle"))
