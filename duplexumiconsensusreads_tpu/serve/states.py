"""The job state machine — the serving fleet's single source of truth.

Every journal ``state`` literal, every legal transition, and every
derived state family lives HERE and only here. ``serve/queue.py``,
``serve/service.py`` and ``serve/client.py`` import these names; no
other module may define its own state tuple. The payoff is that the
protocol is machine-checkable: dutlint's ``state-machine`` rule parses
this module's literals, rebuilds the transition graph the code actually
implements (every ``entry["state"] = ...`` write in ``serve/``, with
its from-state evidence), and fails the build on any undeclared
transition, write to a terminal state, state unreachable from
admission, or declared edge no code implements. Adding a state —
or a transition — is an edit to this file; the linter enforces the
rest (registration, reachability, coverage) at PR time, where the
chaos suite could only probe it dynamically.

Keep ``JOB_STATES``, ``INITIAL_STATES`` and ``TRANSITIONS`` literal
(string tuples / a dict of string tuples): the model-checker reads
them with ``ast``, not ``import``, so the same rule also checks the
miniature fixture corpora in ``tests/test_lint.py``.
"""

from __future__ import annotations

# every state a journal entry may ever carry
JOB_STATES = ("queued", "running", "done", "failed", "rejected",
              "expired", "quarantined", "splitting", "fanned", "merging")

# states a journal entry may be CREATED in (admission writes these;
# everything else must be reached via a declared transition)
INITIAL_STATES = ("queued", "rejected")

# the legal transition graph. One edge per durable journal move:
#   queued -> running|splitting|merging   claim (the phase field picks
#                                         the literal; all three are
#                                         leased states)
#   queued -> expired                     deadline sweep before a claim
#   queued -> failed                      sibling-cancel / orphan reap
#                                         of a shard whose parent died
#   running -> done|failed                slice outcome (fenced)
#   running -> queued                     preemption / takeover /
#                                         watchdog abort-requeue
#   running -> expired                    deadline abort at a chunk
#                                         boundary (fenced)
#   running -> quarantined                crash_count hit max_crashes
#   splitting -> fanned                   the split transaction
#   splitting -> failed|queued|quarantined  same abort family as running
#   fanned -> queued                      all children done: requeue as
#                                         the merge task (phase=merge)
#   fanned -> failed                      a child terminally failed
#   merging -> done|failed|queued|quarantined  merge outcome / aborts
# Terminal states (no successors) may never be written over: their
# results/ file is the durable record and compaction may drop them.
TRANSITIONS = {
    "queued": ("running", "splitting", "merging", "expired", "failed"),
    "running": ("done", "failed", "queued", "expired", "quarantined"),
    "splitting": ("fanned", "failed", "queued", "quarantined"),
    "fanned": ("queued", "failed"),
    "merging": ("done", "failed", "queued", "quarantined"),
    "done": (),
    "failed": (),
    "rejected": (),
    "expired": (),
    "quarantined": (),
}

# ---------------------------------------------------------- derived views
#
# The families the protocol code actually branches on, derived from the
# graph (tests/test_serve.py pins them against the pre-refactor
# literals, so a TRANSITIONS edit that silently changes a family fails
# loudly). Derivations follow JOB_STATES order, keeping the tuples
# byte-identical to the literals they replaced.

# states with nothing left to schedule: no outgoing edges — compaction
# may drop them (their durable results/ file remains the record) and
# the idle check ignores them
TERMINAL_STATES = tuple(s for s in JOB_STATES if not TRANSITIONS[s])

# states held under a lease + fencing token. A claimed state is exactly
# one an UNCLEAN abort can hit: takeover/watchdog either requeue it or
# — at max_crashes — quarantine it, so "can transition to quarantined"
# IS the lease-holding property (fanned parents park without a lease
# and can do neither).
CLAIMED_STATES = tuple(
    s for s in JOB_STATES if "quarantined" in TRANSITIONS[s]
)

# states with scheduling work left: the fleet idle check and the
# admission open-jobs bound count these (a fanned parent IS open work —
# its merge hasn't happened)
OPEN_STATES = ("queued", "fanned") + CLAIMED_STATES
