"""Client side of the service: submit / status / wait.

Submission is one durable file write into the spool inbox — no RPC, no
daemon handshake: the spool directory IS the protocol, which is what
lets a killed daemon lose nothing (the submission either is or is not
durably in the inbox/journal; there is no in-flight third state).
``call --submit/--status/--wait`` (cli/main.py) are thin wrappers over
these functions.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import time

from duplexumiconsensusreads_tpu.serve.job import validate_spec
from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue
from duplexumiconsensusreads_tpu.serve import states

# states with nothing left to wait for: the journal's terminal family
# (from the declared state machine) plus the client-side "unknown"
# pseudo-state status() reports for a job no record answers for
TERMINAL_STATES = states.TERMINAL_STATES + ("unknown",)

# --wait backoff: the delay doubles from poll_s up to this cap, with
# multiplicative jitter so a herd of waiting clients (every `--wait`
# is a journal read off the shared spool) decorrelates instead of
# hammering the filesystem in lockstep
WAIT_BACKOFF_CAP_S = 2.0
_WAIT_JITTER = (0.5, 1.0)


def make_job_id(spec_fields: dict) -> str:
    """Content hash + random suffix: collision-free without any
    coordination between clients (two submissions of the same job spec
    are two jobs, as two `call` invocations would be two runs)."""
    base = hashlib.sha256(
        json.dumps(spec_fields, sort_keys=True).encode() + os.urandom(8)
    ).hexdigest()[:12]
    return f"job-{base}"


def submit(
    spool_dir: str,
    input_path: str,
    output_path: str,
    config: dict | None = None,
    priority: int = 1,
    chaos: str | None = None,
    trace: str | None = None,
    deadline_s: float | None = None,
    shards: int | None = None,
    shard_bytes: int | None = None,
) -> str:
    """Validate + durably spool one job; returns its id. Raises
    ValueError on a bad spec and FileNotFoundError on a missing input —
    submission-time failures belong to the submitter, not the daemon.
    ``deadline_s``: wall budget from admission; past it the job is
    journaled terminal "expired" instead of run (a running slice aborts
    at its next checkpoint boundary, keeping the committed prefix).
    ``shards``/``shard_bytes`` (mutually exclusive): scatter-gather
    sharding — split the job into K range sub-jobs fanned across the
    fleet and merged into one output byte-identical to the unsharded
    run (``--status``/``--wait`` on the returned id aggregate the
    sub-jobs; the job is done only when the merge publishes)."""
    if not os.path.exists(input_path):
        raise FileNotFoundError(f"job input does not exist: {input_path}")
    fields = {
        "input": os.path.abspath(input_path),
        "output": os.path.abspath(output_path),
        "priority": priority,
        "config": dict(config or {}),
    }
    if chaos:
        fields["chaos"] = chaos
    if trace:
        fields["trace"] = os.path.abspath(trace)
    if deadline_s is not None:
        fields["deadline_s"] = deadline_s
    if shards is not None:
        fields["shards"] = shards
    if shard_bytes is not None:
        fields["shard_bytes"] = shard_bytes
    spec = validate_spec({"job_id": make_job_id(fields), **fields})
    return SpoolQueue(spool_dir).submit(spec)


def status(spool_dir: str, job_id: str) -> dict:
    return SpoolQueue(spool_dir).status(job_id)


def status_document(st: dict) -> dict:
    """Normalize a :func:`status`/:func:`wait` answer into the stable
    machine-readable document ``call --status/--wait --json`` prints:
    state + reason + shards rollup + RELATIVE timestamps. The journal's
    ``*_m`` stamps are raw stamp-clock readings that mean nothing off
    their spool — external monitors get ages/countdowns instead
    (``admitted_age_s``, ``deadline_in_s``, ``progress_age_s``,
    ``lease_expires_in_s``), computed against the SAME clock: the
    ``now_m`` the status read attached (the spool store's now — on a
    sharedfs spool the client's own monotonic clock is the wrong
    domain), falling back to local monotonic for pre-store answers."""
    now_m = st.get("now_m")
    if isinstance(now_m, (int, float)) and not isinstance(now_m, bool):
        now = float(now_m)
    else:
        now = time.monotonic()
    doc: dict = {
        "job_id": st.get("job_id"),
        "state": st.get("state"),
        "reason": st.get("error"),
    }
    for key in ("priority", "slices", "chunks_done", "token",
                "crash_count", "shed", "compacted", "timed_out",
                "phase", "parent", "shard_idx", "n_shards", "shards",
                "snapshot_seq", "reads_emitted", "result"):
        if key in st:
            doc[key] = st[key]
    ts: dict = {}
    for src, dst, sign in (
        ("admitted_m", "admitted_age_s", -1),
        ("progress_m", "progress_age_s", -1),
        ("deadline_m", "deadline_in_s", +1),
    ):
        v = st.get(src)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            ts[dst] = round(sign * (float(v) - now), 3)
    lease = st.get("lease")
    if isinstance(lease, dict):
        doc["lease_owner"] = lease.get("owner")
        exp = lease.get("expires_m")
        if isinstance(exp, (int, float)) and not isinstance(exp, bool):
            ts["lease_expires_in_s"] = round(float(exp) - now, 3)
    doc["timestamps"] = ts
    return doc


def wait(
    spool_dir: str, job_id: str, timeout_s: float = 0.0, poll_s: float = 0.5
) -> dict:
    """Poll until the job reaches a terminal state ("unknown" counts:
    waiting on a job nobody submitted must not hang). ``timeout_s`` 0 =
    wait forever; on expiry the last status is returned with
    ``timed_out: true`` rather than raising — the job is still running,
    which is an answer, not an error.

    Polling is jitter-backed-off: delays start at ``poll_s``, double up
    to ~:data:`WAIT_BACKOFF_CAP_S`, and each is scaled by a random
    factor — long jobs cost a handful of journal reads per second of
    waiting fleet-wide instead of a fixed-rate stampede, while a job
    finishing quickly is still noticed quickly."""
    q = SpoolQueue(spool_dir)
    t0 = time.monotonic()
    delay = min(poll_s, WAIT_BACKOFF_CAP_S)
    while True:
        st = q.status(job_id)
        if st.get("state") in TERMINAL_STATES:
            return st
        remaining = timeout_s - (time.monotonic() - t0) if timeout_s > 0 else None
        if remaining is not None and remaining <= 0:
            return {**st, "timed_out": True}
        step = delay * random.uniform(*_WAIT_JITTER)
        if remaining is not None:
            step = min(step, remaining)  # never oversleep the deadline
        time.sleep(step)
        delay = min(delay * 2, WAIT_BACKOFF_CAP_S)
