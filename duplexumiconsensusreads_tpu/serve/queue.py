"""Durable spool + admission queue for the consensus service.

Spool layout (one directory, shared by clients and N daemons):

  inbox/<job_id>.json   client submissions — written durably by the
                        client, removed by the daemon only AFTER the
                        job is durably journaled (so a kill anywhere in
                        admission re-admits instead of losing the job;
                        job_id is the dedupe key, so re-admission can
                        never double-enter)
  queue.json            the admission-queue journal: every accepted job
                        with its state machine (queued → running →
                        done | failed), persisted via the tmp+fsync+
                        rename protocol on EVERY transition — whatever
                        the journal says survived the crash is exactly
                        what a restarted (or surviving) daemon resumes
  journal.lock          flock target serializing journal transactions
                        (holds no data; see "Fleet transactions" below)
  results/<job_id>.json final per-job report (durable), read by
                        ``call --status/--wait``
  metrics.json          the live service heartbeat snapshot

Fleet transactions: with several ``dut-serve`` daemons on one spool the
journal is multi-writer, so every mutation is a flock'd READ-MODIFY-
WRITE — take ``journal.lock``, reload queue.json, apply the transition,
persist durably, release. In-memory ``jobs`` is only ever a cache of
the last transaction's view. flock arbitrates both across processes and
between one daemon's threads (each transaction opens its own fd), and a
SIGKILLed holder releases it automatically — the lock can never outlive
a crash the way journal state does.

Leases: a job enters ``running`` only by CLAIMING it — the claiming
transaction writes a lease entry (daemon id + owner identity, a
monotonically increasing per-job FENCING TOKEN, and a stamp-domain
expiry) into the journal. Leases are renewed from the daemon's
heartbeat and from every chunk commit; an expired lease — or one whose
owner is provably dead — lets another daemon reclaim the job (queued
again, original seq), resuming from the last durable checkpoint mark.
The token is checked at every durable commit (chunk checkpoint mark
via the executor's ``commit_guard``, result publish, every journal
update by the slice), so a zombie daemon that wakes up after its job
was reclaimed raises :class:`JobFenced` before splicing a single byte.

WHICH clock stamps ``*_m`` fields and WHAT proves an owner dead are
the spool's lease-store backend (serve/store.py, pinned per spool in
``store.json``): ``local`` stamps machine-wide CLOCK_MONOTONIC and
probes pids — NTP-proof, scoped to one host, today's exact semantics;
``sharedfs`` stamps a filesystem-calibrated shared clock and reads
durable heartbeat documents, so N hosts sharing the spool agree on
expiry without ever probing a pid. Every ``SpoolQueue`` timestamp and
liveness decision goes through ``self.store``; the fencing token —
not the liveness oracle — remains the exactly-once authority in both.

The journal lock is acquired with a bounded, jittered poll
(:class:`JournalLockTimeout` past ``lock_timeout_s``): a wedged
shared-filesystem flock must surface as a typed error plus a
``lock_stall`` ledger event, not an invisible forever-block. The
heartbeat document keeps beating while a transaction waits — beats
never take the journal lock.

Fault sites: ``serve.accept`` guards the read+parse+validate of each
submission and ``serve.journal`` every durable journal persist (both
here); the serving layer wraps the lease operations at their own sites
— ``serve.lease`` around :meth:`SpoolQueue.claim`, ``serve.renew``
around renewal, ``serve.expire`` around :meth:`SpoolQueue.reclaim_dead`,
``serve.fence`` around :meth:`SpoolQueue.verify_lease`,
``serve.deadline`` around the deadline sweep/expiry commits and
``serve.watchdog`` around :meth:`SpoolQueue.reclaim_stalled` — so chaos
schedules can target each step of the lease state machine. All ride
the streaming executor's bounded host-I/O retry ladder, so transient
faults are absorbed and an injected kill leaves exactly the on-disk
state a real SIGKILL would.
"""

from __future__ import annotations

import contextlib
import fcntl
import json
import os
import random
import time

from duplexumiconsensusreads_tpu.io.durable import (
    free_bytes,
    unique_tmp,
    write_durable,
)
from duplexumiconsensusreads_tpu.serve.job import JobSpec, validate_spec
from duplexumiconsensusreads_tpu.serve.store import LeaseStore, resolve_store

# the job state machine — states, legal transitions, and the derived
# families — lives in serve/states.py (the single declared source of
# truth dutlint's state-machine rule checks the code against); the
# names are re-exported here so queue-side callers keep one import
from duplexumiconsensusreads_tpu.serve.states import (  # noqa: F401
    CLAIMED_STATES,
    JOB_STATES,
    OPEN_STATES,
    TERMINAL_STATES,
    TRANSITIONS,
)

JOURNAL_VERSION = 1

# helpers that may touch the in-memory jobs cache OUTSIDE a lexical
# `with self._txn():` body because their caller owns the transaction
# (or, for _load, because the client-side read path is documented
# single-threaded — see status()). dutlint's txn-discipline rule reads
# this registry; everything not named here (and not *_locked/__init__)
# must mutate the cache inside a transaction.
TXN_CACHE_HELPERS = ("_load", "_compact")

# poison quarantine: a job whose run aborts THIS many times without a
# clean preemption (daemon death takeovers, watchdog stall reclaims) is
# journaled terminal `quarantined` with a diagnosis bundle instead of
# re-entering the queue — without this bound a deterministic poison job
# ping-pongs between fleet daemons forever, killing each in turn
MAX_CRASHES_DEFAULT = 3

# per-job lease claims kept for the quarantine diagnosis bundle
_LEASE_HISTORY_KEPT = 8

# disk-pressure low-water mark: admission sheds new jobs when the spool
# filesystem has less than this free (after a grace GC pass over
# terminal jobs' shard/checkpoint litter). The durable design spends
# disk on every transition — journal rewrites, shard writes, finalise
# staging — so refusing new work while it can still be refused cleanly
# beats dying on ENOSPC mid-commit.
DISK_LOW_WATER_BYTES = 64 << 20

# default lease length. Healthy daemons renew every chunk commit AND
# every heartbeat, so expiry only ever fires on a daemon that stopped
# making progress for this long — a real zombie, not a slow chunk.
LEASE_DEFAULT_S = 30.0

# journal-lock acquisition bounds: a transaction that cannot take
# journal.lock within the timeout raises JournalLockTimeout (an
# OSError — the serving layer's I/O ladders absorb it like any other
# transient and the heartbeat keeps running); past the stall threshold
# ONE lock_stall event is ledgered so a wedged shared-filesystem lock
# is visible long before the timeout fires
LOCK_TIMEOUT_DEFAULT_S = 30.0
LOCK_STALL_EVENT_S = 1.0


class JournalLockTimeout(OSError):
    """journal.lock could not be acquired within ``lock_timeout_s``.
    OSError on purpose: every caller's retry/absorb ladder already
    handles transient I/O failure, and a wedged lock (a dead NFS
    client holding flock, a hung filesystem) must degrade the same
    way — loudly typed, never an invisible forever-block."""


class JobFenced(BaseException):
    """A daemon's fencing token no longer matches the journal: its job
    was reclaimed (lease expired / owner presumed dead) and every
    durable commit it still owes is void. BaseException on purpose —
    like InjectedKill, no retry or isolation ladder may absorb it: the
    slice must abort immediately, committing nothing, and the service
    drops the result on the floor (the reclaiming daemon owns the job
    now and will produce the identical bytes)."""


def _remove_counting(path: str) -> int:
    """Remove one file, returning the bytes it held (0 when absent or
    unremovable — GC is best-effort)."""
    try:
        size = os.path.getsize(path)
        os.remove(path)
    except OSError:
        return 0
    return size


def _trace_tail(path: str, max_bytes: int = 8192, max_lines: int = 20):
    """Last ``max_lines`` lines of a (JSONL) capture file, for the
    quarantine diagnosis bundle. Read-only and size-bounded: the bundle
    must stay a small durable JSON, not re-spool the whole capture."""
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(size - max_bytes, 0))
            data = f.read(max_bytes)
    except OSError:
        return None
    lines = data.decode("utf-8", "replace").splitlines()
    return [ln[:500] for ln in lines[-max_lines:]] or None


def _capture_stitched_end(path: str) -> float | None:
    """A service capture's END on the stitched fleet timeline:
    ``meta.epoch_m`` (the recorder's stamp-domain start — the fleet
    recorder's alignment key) plus the last record's relative ``t``.
    None when the capture predates the fleet recorder (no numeric
    epoch_m in the meta line) — those fall back to mtime ordering.
    Read-only and bounded like :func:`_trace_tail`."""
    try:
        with open(path, "rb") as f:
            head = f.readline(4096)
        meta = json.loads(head.decode("utf-8", "replace"))
    except (OSError, ValueError):
        return None
    if not isinstance(meta, dict) or meta.get("type") != "meta":
        return None
    epoch = meta.get("epoch_m")
    if not isinstance(epoch, (int, float)) or isinstance(epoch, bool):
        return None
    last_t = 0.0
    for line in _trace_tail(path, max_bytes=65536, max_lines=512) or ():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        t = rec.get("t") if isinstance(rec, dict) else None
        if isinstance(t, (int, float)) and not isinstance(t, bool):
            last_t = max(last_t, float(t))
    return float(epoch) + last_t


def _last_fault_site(tail_lines) -> str | None:
    """The last injected-fault site named in a capture tail — the
    poison job's smoking gun when it carries a chaos schedule."""
    site = None
    for line in tail_lines:
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if (
            isinstance(rec, dict)
            and rec.get("name") == "fault_injected"
            and isinstance(rec.get("site"), str)
        ):
            site = rec["site"]
    return site


class SpoolQueue:
    """The admission queue over one spool directory.

    All mutating methods are flock'd journal transactions (reload →
    mutate → durable persist), safe against concurrent daemons; the
    in-memory ``jobs`` dict is only ever a cache of queue.json.
    In-process thread safety rides the same flock (each transaction
    opens a private fd); serve.service additionally serializes its own
    scheduling decisions under its lock.
    """

    def __init__(self, root: str, max_queue: int = 64,
                 max_terminal_kept: int = 256,
                 max_crashes: int = MAX_CRASHES_DEFAULT,
                 default_deadline_s: float = 0.0,
                 min_free_bytes: int = DISK_LOW_WATER_BYTES,
                 store: LeaseStore | str | None = None,
                 lock_timeout_s: float = LOCK_TIMEOUT_DEFAULT_S):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 (got {max_queue})")
        if max_terminal_kept < 0:
            raise ValueError(
                f"max_terminal_kept must be >= 0 (got {max_terminal_kept})"
            )
        if max_crashes < 1:
            raise ValueError(f"max_crashes must be >= 1 (got {max_crashes})")
        if default_deadline_s < 0:
            raise ValueError(
                f"default_deadline_s must be >= 0 (got {default_deadline_s})"
            )
        self.root = root
        self.max_queue = max_queue
        # quarantine bound: aborts-without-clean-preemption before a job
        # is declared poison (see reclaim_dead/reclaim_stalled)
        self.max_crashes = max_crashes
        # daemon-level deadline default (seconds; 0 = none): admission
        # stamps spec.deadline_s or this onto the journal entry as a
        # monotonic expiry
        self.default_deadline_s = default_deadline_s
        # disk-pressure admission bound (bytes; 0 disables the probe)
        self.min_free_bytes = min_free_bytes
        # the journal is rewritten+fsynced on every transition, so it
        # must stay bounded on a long-lived daemon: terminal entries
        # (done/failed/rejected) beyond this many are compacted away on
        # save — their durable per-job report in results/ remains the
        # record (status() falls back to it). Compaction NEVER touches
        # open (queued/running) entries, so lease/token state survives
        # every rewrite.
        self.max_terminal_kept = max_terminal_kept
        # admission policy hook (serve.service wires the scheduler's
        # shed policy here): callable(jobs, spec) -> rejection reason
        # string, or None to admit. Purely advisory load shedding —
        # invalid specs and the global bound are still enforced here.
        self.admission_policy = None
        # the spool's clock/liveness backend: an instance is adopted
        # as-is (the service injects a pinned store), a string or None
        # resolves against the spool's store.json marker WITHOUT
        # pinning it — the client poll path must never decide a
        # spool's backend, only inherit it
        if isinstance(store, LeaseStore):
            self.store = store
        else:
            self.store = resolve_store(root, store)
        # bounded journal-lock acquisition (<=0 disables the bound)
        self.lock_timeout_s = lock_timeout_s
        self.inbox_dir = os.path.join(root, "inbox")
        self.results_dir = os.path.join(root, "results")
        os.makedirs(self.inbox_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        self.journal_path = os.path.join(root, "queue.json")
        self._lock_path = os.path.join(root, "journal.lock")
        self.jobs: dict[str, dict] = {}
        self.seq = 0
        self._load()

    # ------------------------------------------------------- client side

    def submit(self, spec: JobSpec) -> str:
        """Durably spool one validated job into the inbox (client side;
        the daemon never calls this). Returns the job id."""
        path = os.path.join(self.inbox_dir, spec.job_id + ".json")
        payload = json.dumps(spec.to_dict(), sort_keys=True).encode()
        write_durable(path, payload, tmp=unique_tmp(path))
        return spec.job_id

    def status(self, job_id: str) -> dict:
        """One job's observable state, from the journal + inbox +
        results — readable while daemons run (every file involved is
        only ever atomically replaced), no lock taken. Client-side
        only: the bare reloads here assume a single-threaded instance
        (daemon threads sharing a queue must use :meth:`refresh`, which
        serializes against in-flight transactions).

        Admission-race discipline: the daemon journals BEFORE unlinking
        the inbox file, but a reader that loads the journal first and
        checks the inbox second can see neither (journal read pre-save,
        inbox checked post-unlink). After an inbox miss the journal is
        therefore RE-read — a live job must never be reported "unknown"
        (which ``client.wait`` treats as terminal)."""
        self._load()
        entry = self.jobs.get(job_id)
        if entry is None:
            if os.path.exists(os.path.join(self.inbox_dir, job_id + ".json")):
                return {"job_id": job_id, "state": "submitted"}
            self._load()  # close the accept-vs-status window
            entry = self.jobs.get(job_id)
        if entry is None:
            return self._status_from_result(job_id)
        out = {"job_id": job_id, **{k: v for k, v in entry.items()
                                    if k != "spec"}}
        # the reader's "now" in the SPOOL's stamp domain: ages and
        # expires-in arithmetic against the entry's *_m stamps is only
        # well-defined on the clock that produced them, which on a
        # sharedfs spool is not the client's own monotonic clock
        out["now_m"] = round(self.store.now(), 3)
        if entry.get("children"):
            out["shards"] = self._shard_rollup(entry)
        result_path = os.path.join(self.results_dir, job_id + ".json")
        if entry.get("state") in (
            "done", "failed", "expired", "quarantined"
        ) and os.path.exists(result_path):
            try:
                with open(result_path) as f:
                    out["result"] = json.load(f)
            except (OSError, ValueError):
                pass  # result file torn/racing: state alone still answers
        return out

    def _status_from_result(self, job_id: str) -> dict:
        """Jobs whose terminal journal entry was compacted away still
        answer from their durable result file — rejections included,
        so a shed reason survives overload-time journal churn (which is
        exactly when sheds are frequent and compaction fastest)."""
        result_path = os.path.join(self.results_dir, job_id + ".json")
        try:
            with open(result_path) as f:
                result = json.load(f)
        except (OSError, ValueError):
            return {"job_id": job_id, "state": "unknown"}
        state = (
            "rejected" if result.get("rejected")
            else "quarantined" if result.get("quarantined")
            else "expired" if result.get("expired")
            else "failed" if "error" in result
            else "done"
        )
        out = {"job_id": job_id, "state": state, "result": result,
               "compacted": True}
        if result.get("shed"):
            out["shed"] = True
        if "error" in result:
            out["error"] = result["error"]
        return out

    def _write_rejection_result(
        self, job_id: str, reason: str, shed: bool
    ) -> None:
        """Durable record of WHY a submission never ran: like
        done/failed results, it outlives the journal entry's
        compaction."""
        path = os.path.join(self.results_dir, job_id + ".json")
        payload: dict = {"error": reason[:2000], "rejected": True}
        if shed:
            payload["shed"] = True
        write_durable(
            path,
            json.dumps(payload, sort_keys=True).encode(),
            tmp=unique_tmp(path),
        )

    # ------------------------------------------------------- daemon side

    @contextlib.contextmanager
    def _txn(self):
        """One flock'd journal transaction: exclusive lock (bounded —
        see :meth:`_flock_bounded`), fresh load, caller mutates and
        persists, lock released (incl. on error/kill — the kernel
        drops flock with the fd)."""
        fd = os.open(self._lock_path, os.O_CREAT | os.O_RDWR, 0o644)
        try:
            self._flock_bounded(fd)
            self._load()
            yield
        finally:
            os.close(fd)

    def _flock_bounded(self, fd: int) -> None:
        """Take the exclusive journal flock with a bounded, jittered
        poll instead of a blocking wait. A healthy lock is free or
        held for one tmp+fsync+rename, so the fast path is a single
        non-blocking attempt; contention polls with small jittered
        backoff (jitter decorrelates N daemons hammering one shared-
        filesystem lock). Past ``LOCK_STALL_EVENT_S`` one ``lock_stall``
        event is ledgered; past ``lock_timeout_s`` the transaction
        fails typed (:class:`JournalLockTimeout`) rather than wedging
        the daemon invisibly forever."""
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return
        except OSError:
            pass
        start = time.monotonic()
        stalled = False
        while True:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return
            except OSError:
                waited = time.monotonic() - start
                if 0 < self.lock_timeout_s <= waited:
                    raise JournalLockTimeout(
                        f"journal.lock on {self.root!r} not acquired "
                        f"after {waited:.1f}s (lock_timeout_s="
                        f"{self.lock_timeout_s}): wedged holder?"
                    )
                if not stalled and waited >= LOCK_STALL_EVENT_S:
                    stalled = True
                    # lazy import: the client poll path must not drag
                    # the telemetry stack in on every status read
                    from duplexumiconsensusreads_tpu.telemetry.trace import (
                        emit_event,
                    )

                    emit_event(
                        "lock_stall",
                        waited_s=round(waited, 3),
                        spool=self.root,
                    )
                # small cap: transactions are sub-ms when healthy, and
                # the serving tests take this path with real sleeps
                time.sleep(random.uniform(0.001, 0.005))

    def refresh(self) -> None:
        """Re-read the journal so the service's idle check sees other
        daemons' transitions — UNDER the transaction lock: a bare
        reload would rebind the ``jobs`` cache while a concurrent
        transaction on this same instance (a commit-guard renewal, the
        heartbeat's renew_all) sits between its load and its save, and
        that transaction would then durably write the rebound,
        mutation-less dict — silently dropping a lease renewal or, at
        worst, a claim."""
        with self._txn():
            pass

    def _load(self) -> None:
        """Refresh the in-memory view from queue.json. A torn or
        garbage journal is discarded (never fatal): the inbox files
        still exist for every job whose admission didn't complete, and
        jobs already dispatched wrote their own durable outputs."""
        try:
            with open(self.journal_path) as f:
                on_disk = json.load(f)
            if not isinstance(on_disk, dict) or not isinstance(
                on_disk.get("jobs"), dict
            ):
                raise ValueError("journal is not a {jobs: {...}} object")
        except FileNotFoundError:
            return
        except (OSError, ValueError):
            return
        self.jobs = on_disk["jobs"]
        self.seq = int(on_disk.get("seq", len(self.jobs)))

    def _compact(self) -> None:
        """Bound the journal: drop the OLDEST terminal entries beyond
        ``max_terminal_kept`` (their results/ file stays the durable
        record). Open jobs (queued/running) are never touched — their
        lease/token state must survive every save, or a restarted
        daemon would schedule (and fence) differently than the dead
        one would have."""
        # a done sub-job of a still-open parent must survive compaction:
        # the parent's advance sweep decides fanned -> merge by reading
        # its children's journal states, and compacting one away would
        # stall (or mis-fail) the merge forever
        open_parents = {
            jid for jid, e in self.jobs.items()
            if e.get("state") not in TERMINAL_STATES
        }
        terminal = sorted(
            (
                (int(e.get("seq", 0)), jid)
                for jid, e in self.jobs.items()
                if e.get("state") in TERMINAL_STATES
                and e.get("parent") not in open_parents
            ),
        )
        for _, jid in terminal[: max(len(terminal) - self.max_terminal_kept, 0)]:
            del self.jobs[jid]

    def save(self) -> None:
        """Durable journal persist (fault site ``serve.journal``)."""
        # _io_retry imported lazily: the CLIENT side of this module
        # (submit/status for `call --submit/--status/--wait`) must not
        # drag in runtime.stream — and through it jax — on every poll
        from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

        self._compact()
        payload = json.dumps(
            {"version": JOURNAL_VERSION, "seq": self.seq, "jobs": self.jobs},
            sort_keys=True,
        ).encode()
        _io_retry(
            "serve.journal",
            lambda: write_durable(
                self.journal_path, payload, tmp=unique_tmp(self.journal_path)
            ),
            "queue journal save",
        )

    def pending_submissions(self) -> list[str]:
        """Inbox job ids in ARRIVAL order (mtime of the durable spool
        file, name as tiebreak): admission seq — and therefore FIFO
        order within a priority class — follows submission time, not
        the job-id hash the filenames happen to sort by."""
        entries = []
        try:
            for n in os.listdir(self.inbox_dir):
                if not n.endswith(".json"):
                    continue
                try:
                    mt = os.stat(os.path.join(self.inbox_dir, n)).st_mtime
                except OSError:
                    continue  # raced away mid-listing
                entries.append((mt, n))
        except OSError:
            return []
        return [n[:-5] for _, n in sorted(entries)]

    def accept_one(self, job_id: str) -> tuple[JobSpec | None, str | None]:
        """Admit one inbox submission: read + validate (fault site
        ``serve.accept``), journal it durably, THEN remove the inbox
        file — one flock'd transaction, so two daemons scanning the
        same inbox admit each job exactly once. Returns (spec, None) on
        admission, (None, reason) on rejection (shed policy, bounded
        queue, invalid spec), (None, None) when the submission was a
        duplicate of an already-journaled job.

        Kill-anywhere safety: before the journal save the inbox file is
        untouched (restart re-admits); after it, re-admission dedupes on
        job_id and merely removes the leftover inbox file."""
        from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

        path = os.path.join(self.inbox_dir, job_id + ".json")

        def _read():
            with open(path, "rb") as f:
                return f.read()

        with self._txn():
            try:
                raw = _io_retry("serve.accept", _read, f"job {job_id} accept")
            except FileNotFoundError:
                return None, None  # raced away (another daemon admitted it)
            if job_id in self.jobs:
                # already journaled (kill landed between journal +
                # unlink, or another daemon won the race): admission
                # already happened exactly once — just clean up
                self._unlink_inbox(path)
                return None, None
            try:
                spec = validate_spec(json.loads(raw.decode()))
                if spec.job_id != job_id:
                    raise ValueError(
                        f"spec job_id {spec.job_id!r} does not match the "
                        f"spool filename"
                    )
            except (ValueError, UnicodeDecodeError) as e:
                self._write_rejection_result(job_id, str(e), shed=False)
                self.jobs[job_id] = {
                    "state": "rejected", "error": str(e)[:500], "seq": self.seq,
                }
                self.seq += 1
                self.save()
                self._unlink_inbox(path)
                return None, str(e)
            # admission control: disk pressure first (accepting a job
            # the spool cannot even journal for is the worst shed),
            # then the scheduler's per-class shed policy, then the
            # global open-jobs bound as the backstop — all journaled as
            # explicit shed-with-reason rejections, so an overloaded
            # fleet degrades by policy (and tells the client why),
            # never by an inbox silently rotting
            reason = self._disk_shed_reason()
            if reason is None and self.admission_policy is not None:
                reason = self.admission_policy(self.jobs, spec)
            if reason is None:
                n_open = sum(
                    1 for j in self.jobs.values()
                    if j.get("state") in OPEN_STATES
                )
                if n_open >= self.max_queue:
                    reason = (
                        f"shed: queue full ({n_open}/{self.max_queue} "
                        f"jobs open)"
                    )
            if reason is not None:
                self._write_rejection_result(job_id, reason, shed=True)
                self.jobs[job_id] = {
                    "state": "rejected", "error": reason, "shed": True,
                    "priority": spec.priority, "seq": self.seq,
                }
                self.seq += 1
                self.save()
                self._unlink_inbox(path)
                return None, reason
            entry = {
                "state": "queued",
                "seq": self.seq,
                "priority": spec.priority,
                "spec": spec.to_dict(),
                "slices": 0,
                "chunks_done": 0,
                # admission timestamp on the spool's shared stamp
                # clock: whichever daemon eventually claims the job
                # computes its queue-wait against this
                "admitted_m": round(self.store.now(), 3),
            }
            # deadline: the job's own budget wins over the daemon-level
            # default; stamped as a stamp-domain expiry at admission
            # (the budget runs from acceptance, queue-wait included),
            # the one clock domain the whole lease machinery uses
            deadline_s = spec.deadline_s or self.default_deadline_s
            if deadline_s and deadline_s > 0:
                entry["deadline_m"] = round(
                    self.store.now() + float(deadline_s), 3
                )
            if spec.shards is not None or spec.shard_bytes is not None:
                # sharding parent: the phase field decides what a claim
                # means — "split" until the planner fans the sub-jobs
                # out, "merge" once the parent is requeued to splice
                entry["phase"] = "split"
            if spec.shard is not None:
                # a directly-spooled sub-job (normally planner-internal):
                # keep the lineage columns status/serve_report read
                entry["parent"] = str(spec.shard.get("parent"))
                entry["shard_idx"] = int(spec.shard.get("idx", 0))
            self.jobs[job_id] = entry
            self.seq += 1
            self.save()
            self._unlink_inbox(path)
            return spec, None

    @staticmethod
    def _unlink_inbox(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass  # re-admission dedupes; a leftover file is harmless

    # ------------------------------------------------------------ leases

    def _check_fence(self, job_id: str, daemon_id: str, token: int) -> dict:
        """Raise :class:`JobFenced` unless ``daemon_id`` still holds
        ``job_id``'s CURRENT lease under fencing token ``token``.
        Returns the journal entry. Caller holds the transaction."""
        entry = self.jobs.get(job_id)
        lease = (entry or {}).get("lease")
        if (
            entry is None
            or entry.get("state") not in CLAIMED_STATES
            or lease is None
            or lease.get("owner") != daemon_id
            or int(entry.get("token", 0)) != int(token)
        ):
            raise JobFenced(
                f"job {job_id}: lease lost (holder token {token}, journal "
                f"token {(entry or {}).get('token')!r}, owner "
                f"{(lease or {}).get('owner')!r})"
            )
        return entry

    def claim(
        self, job_id: str, daemon_id: str, lease_s: float = LEASE_DEFAULT_S
    ) -> int | None:
        """Claim a queued job for ``daemon_id``: bump the fencing token,
        write the lease, mark it running — one durable transaction
        (fault site ``serve.lease``). Returns the new token, or None if
        the job raced away (another daemon claimed or finished it)."""
        with self._txn():
            entry = self.jobs.get(job_id)
            if entry is None or entry.get("state") != "queued":
                return None
            token = int(entry.get("token", 0)) + 1
            entry["token"] = token
            # stage-aware claim: a sharding parent's claim means
            # planning ("split" phase) or merging ("merge" phase), and
            # the journal says which — all three literals are leased
            # states under the same fence/takeover machinery
            phase = entry.get("phase")
            entry["state"] = (
                "splitting" if phase == "split"
                else "merging" if phase == "merge"
                else "running"
            )
            entry["slices"] = int(entry.get("slices", 0)) + 1
            entry["lease"] = self.store.lease_doc(daemon_id, lease_s)
            # durable-progress stamp: a fresh claim counts as progress
            # (the watchdog must not declare a just-claimed job stalled
            # while it compiles); every chunk-commit renewal re-stamps
            entry["progress_m"] = round(self.store.now(), 3)
            # bounded claim history: who ran this job under which token
            # — the quarantine diagnosis bundle's lease trail
            hist = entry.setdefault("lease_history", [])
            hist.append(self.store.claim_rec(daemon_id, token))
            del hist[:-_LEASE_HISTORY_KEPT]
            self.save()
            return token

    def verify_lease(self, job_id: str, daemon_id: str, token: int) -> None:
        """The fence check: raise :class:`JobFenced` unless this
        (daemon, token) is still the job's current lease. Read-only;
        called (under fault site ``serve.fence``) before every durable
        commit a slice makes."""
        with self._txn():
            self._check_fence(job_id, daemon_id, token)

    def renew_lease(
        self, job_id: str, daemon_id: str, token: int,
        lease_s: float = LEASE_DEFAULT_S, progress: dict | None = None,
    ) -> None:
        """Extend the lease (fault site ``serve.renew``), fenced: a
        zombie must not be able to resurrect a reclaimed lease.

        Called from the per-chunk commit guard — i.e. exactly when a
        chunk became durable — so it also re-stamps ``progress_m``, the
        watchdog's DURABLE-progress clock. The heartbeat's
        :meth:`renew_all` deliberately does not: a wedged device step
        keeps the heartbeat (liveness) alive while committing nothing,
        and conflating the two is exactly the hang this distinction
        exists to catch.

        ``progress`` (optional) merges observable per-chunk counters
        into the journal entry inside the SAME fenced transaction —
        follow-mode jobs ride this to publish ``snapshot_seq`` /
        ``reads_emitted`` (a follow job can run for hours between slice
        boundaries, so ``--status`` must not have to wait for one). A
        fenced write on purpose: a zombie must not be able to stamp
        stale progress over the journal any more than a stale lease."""
        with self._txn():
            entry = self._check_fence(job_id, daemon_id, token)
            entry["lease"]["expires_m"] = round(
                self.store.now() + lease_s, 3
            )
            entry["progress_m"] = round(self.store.now(), 3)
            if progress:
                entry.update(progress)
            self.save()

    def renew_all(self, daemon_id: str, lease_s: float = LEASE_DEFAULT_S) -> int:
        """Heartbeat-path renewal: extend every running lease this
        daemon holds. Returns the number renewed (0 = nothing to save)."""
        with self._txn():
            renewed = 0
            deadline = round(self.store.now() + lease_s, 3)
            for entry in self.jobs.values():
                lease = entry.get("lease")
                if (
                    entry.get("state") in CLAIMED_STATES
                    and lease is not None
                    and lease.get("owner") == daemon_id
                ):
                    lease["expires_m"] = deadline
                    renewed += 1
            if renewed:
                self.save()
            return renewed

    def reclaim_dead(
        self, daemon_id: str, is_live=None, hosts=None
    ) -> list[dict]:
        """Dead-daemon takeover: requeue every running job whose lease
        no longer protects it — expired (the zombie case: the owner may
        still be alive, which is exactly what the fencing token guards
        against), provably dead by the store's liveness oracle (a dead
        local pid, a stale/rebooted heartbeat document), or missing
        entirely (a pre-lease journal). Reclaimed jobs keep their
        ORIGINAL seq (they reached the front once already) and their
        token (the NEXT claim bumps it, fencing the previous holder).

        ``is_live`` (optional callable daemon_id -> bool) identifies
        live daemons within THIS process — the in-process fleet used by
        tests and the bench, where every daemon shares one pid (local
        store only; the sharedfs backend trusts documents, not process
        state). ``hosts`` is a heartbeat snapshot from the store's
        ``observe()`` — the caller takes it under fault site
        ``serve.store``; None re-observes here. Returns [{job_id,
        reason, prev_owner, crash_count[, quarantined]}, ...]; the
        persist rides fault site ``serve.expire``.

        Every reclaim here is an abort that was NOT a clean preemption
        (the owner died or went silent holding the lease), so it
        increments the job's ``crash_count``; at ``max_crashes`` the
        job is quarantined instead of requeued (see
        :meth:`_abort_requeue_locked`)."""
        now = self.store.now()
        if hosts is None:
            hosts = self.store.observe()
        with self._txn():
            reclaimed = []
            for job_id, entry in self.jobs.items():
                if entry.get("state") not in CLAIMED_STATES:
                    continue
                reason = self.store.reclaim_reason(
                    entry.get("lease"), now, is_live=is_live, hosts=hosts
                )
                if reason is None:
                    continue
                reclaimed.append(
                    self._abort_requeue_locked(job_id, entry, reason)
                )
                entry.pop("lease", None)
            if reclaimed:
                self.save()
            return reclaimed

    def reclaim_stalled(self, stall_s: float | None) -> list[dict]:
        """Stuck-run watchdog reclaim: abort-requeue every RUNNING job
        whose last durable progress (``progress_m``: stamped at claim
        and on every chunk-commit renewal) is older than ``stall_s`` —
        regardless of lease freshness. This is the hole lease expiry
        cannot see: a wedged device step keeps the owner's heartbeat
        (and therefore its lease renewals) alive while committing
        nothing, forever. The requeue rides the normal lease/fence
        path: the token is kept and the NEXT claim bumps it, so the
        wedged slice — should it ever wake — is fenced at its first
        durable commit, exactly like a zombie after expiry takeover.

        ``stall_s`` None = disabled (returns []); the call still sits
        under fault site ``serve.watchdog`` at the caller, so chaos
        schedules target the watchdog step even when it reclaims
        nothing. Counts as a crash (not a clean preemption) toward
        quarantine, like takeover."""
        if stall_s is None or stall_s <= 0:
            return []
        now = self.store.now()
        with self._txn():
            reclaimed = []
            for job_id, entry in self.jobs.items():
                if entry.get("state") not in CLAIMED_STATES:
                    continue
                progress_m = entry.get("progress_m")
                if progress_m is None:
                    continue  # pre-watchdog journal: expiry still covers
                stalled = now - float(progress_m)
                if stalled <= stall_s:
                    continue
                rec = self._abort_requeue_locked(job_id, entry, "stalled")
                rec["stalled_s"] = round(stalled, 3)
                reclaimed.append(rec)
                entry.pop("lease", None)
            if reclaimed:
                self.save()
            return reclaimed

    def _abort_requeue_locked(
        self, job_id: str, entry: dict, reason: str
    ) -> dict:
        """One unclean abort of a running job: bump ``crash_count``,
        then either requeue at ORIGINAL seq with the token kept (the
        next claim fences the old holder) or — at ``max_crashes`` —
        move the job to terminal ``quarantined`` with a durable
        diagnosis bundle. The CALLER holds the transaction, pops the
        lease and saves ONCE after its sweep — saving per job here
        would run compaction mid-iteration (mutating the dict being
        swept) and rewrite+fsync the journal N times for one sweep.
        Returns the reclaim record for the caller's counters/events."""
        # only a leased state can abort uncleanly — and this assert is
        # also the from-state evidence the state-machine lint reads
        assert entry.get("state") in CLAIMED_STATES, entry.get("state")
        lease = entry.get("lease")
        prev = (lease or {}).get("owner")
        crashes = int(entry.get("crash_count", 0)) + 1
        entry["crash_count"] = crashes
        rec = {
            "job_id": job_id, "reason": reason, "prev_owner": prev,
            "crash_count": crashes,
        }
        if crashes >= self.max_crashes:
            diagnosis = self._diagnosis(entry, reason)
            error = (
                f"quarantined after {crashes} crashed runs "
                f"(max_crashes={self.max_crashes}; last abort: {reason})"
            )
            self._write_terminal_result(
                job_id, {"error": error, "quarantined": True,
                         "diagnosis": diagnosis},
            )
            entry["state"] = "quarantined"
            entry["error"] = error[:500]
            rec["quarantined"] = True
        else:
            entry["state"] = "queued"
        return rec

    def _diagnosis(self, entry: dict, reason: str) -> dict:
        """The quarantine post-mortem bundle, durable in the job's
        result file: why the fleet gave up, who held the job when, and
        — when the job carried its own trace capture — the capture's
        tail with the last injected/observed fault site, so the
        operator (or the poison-job test) never has to re-run the
        poison to learn what it does."""
        out = {
            "crash_count": int(entry.get("crash_count", 0)),
            "max_crashes": self.max_crashes,
            "last_abort": reason,
            "lease_history": list(entry.get("lease_history", [])),
        }
        # capture sources, most-specific first: the job's own --trace
        # capture, then the SERVICE captures — a daemon running with
        # the (default) service trace owns the process-global telemetry
        # hook, so the poison's fault_injected event lands in the
        # service capture, not the job's; and the daemon the poison
        # crashed is a PREVIOUS daemon whose capture the current one
        # rotated to .prev on startup. Each is scanned over a generous
        # suffix (the fault event lands before the in-flight drain
        # spans that follow it into the capture), but only a short tail
        # is bundled — the diagnosis must stay a small durable JSON.
        candidates = []
        trace = (entry.get("spec") or {}).get("trace")
        if trace:
            candidates.append(trace)
        # service captures are per-daemon (service.<id>.trace.jsonl +
        # rotated .prev) since the fleet recorder; the legacy shared
        # name still matters for --trace overrides and old spools.
        # Newest STITCHED END first (meta epoch_m + last relative t —
        # the clock the journal stamps live on), so the most recent
        # capture naming a fault site — the one that saw THIS job's
        # last crash — wins the setdefault/break scan below over stale
        # history. mtime is meaningless across hosts (skewed wall
        # clocks, coarse shared-fs timestamps) and only ranks the
        # pre-fleet captures that carry no epoch — those sort behind
        # every epoch-bearing capture.
        from duplexumiconsensusreads_tpu.telemetry.fleet import (
            discover_service_captures,
        )

        svc = []
        for p in discover_service_captures(self.root):
            end = _capture_stitched_end(p)
            if end is not None:
                svc.append((1, end, p))
            else:
                try:
                    svc.append((0, os.path.getmtime(p), p))
                except OSError:
                    continue
        candidates += [p for _, _, p in sorted(svc, reverse=True)]
        for path in candidates:
            lines = _trace_tail(path, max_bytes=65536, max_lines=512)
            if not lines:
                continue
            out.setdefault("trace_tail", lines[-20:])
            site = _last_fault_site(lines)
            if site is not None:
                out["last_fault_site"] = site
                break
        return out

    def _write_terminal_result(self, job_id: str, payload: dict) -> None:
        """Durable result write shared by the quarantine/expiry paths
        (same protocol as done/failed results: the file outlives the
        journal entry's compaction)."""
        path = os.path.join(self.results_dir, job_id + ".json")
        write_durable(
            path,
            json.dumps(payload, sort_keys=True).encode(),
            tmp=unique_tmp(path),
        )

    # --------------------------------------------- scatter-gather sharding

    def _shard_rollup(self, entry: dict) -> dict:
        """A parent's aggregate view of its sub-jobs: K done/claimed/
        queued plus the first failure's reason — what ``call --status
        <parent>`` and ``--wait`` progress read. Children are protected
        from compaction while the parent is open, so the journal always
        answers."""
        counts = {"n_shards": len(entry.get("children", ())),
                  "done": 0, "running": 0, "queued": 0, "failed": 0}
        compacted = 0
        first_failure = None
        for cid in entry.get("children", ()):
            c = self.jobs.get(cid)
            if c is None:
                # child entry compacted away — only possible once the
                # parent itself is terminal (open parents protect their
                # children from compaction), so this is history, not a
                # failure: the durable results/ file remains the record
                compacted += 1
                continue
            state = c.get("state")
            if state == "done":
                counts["done"] += 1
            elif state in CLAIMED_STATES:
                counts["running"] += 1
            elif state == "queued":
                counts["queued"] += 1
            else:
                counts["failed"] += 1
                if first_failure is None:
                    first_failure = {
                        "shard": cid,
                        "state": state,
                        "error": c.get("error"),
                    }
        if compacted:
            counts["compacted"] = compacted
        if first_failure is not None:
            counts["first_failure"] = first_failure
        return counts

    def register_shards(
        self, parent_id: str, daemon_id: str, token: int,
        child_dicts: list[dict],
    ) -> int:
        """The split transaction (fault site ``serve.split`` at the
        caller): register the planned sub-jobs as ordinary queued
        entries and move the fenced parent splitting -> fanned, all in
        one durable journal write. Idempotent under re-planning: child
        ids derive from (parent_id, shard_idx), so a kill between plan
        and save — or a takeover mid-split — re-registers the same ids
        and dedupes exactly like inbox re-admission. Returns the number
        of children newly registered."""
        from duplexumiconsensusreads_tpu.serve.job import validate_spec

        registered = 0
        with self._txn():
            parent = self._check_fence(parent_id, daemon_id, token)
            children = []
            for d in child_dicts:
                spec = validate_spec(d)  # the daemon never trusts a dict
                children.append(spec.job_id)
                if spec.job_id in self.jobs:
                    continue  # re-plan after a kill: already registered
                entry = {
                    "state": "queued",
                    "seq": self.seq,
                    "priority": spec.priority,
                    "spec": spec.to_dict(),
                    "slices": 0,
                    "chunks_done": 0,
                    "admitted_m": round(self.store.now(), 3),
                    "parent": parent_id,
                    "shard_idx": int((spec.shard or {}).get("idx", 0)),
                }
                deadline_m = parent.get("deadline_m")
                if deadline_m is not None:
                    # children inherit the parent's admission-stamped
                    # expiry: the deadline bounds the whole pipeline
                    entry["deadline_m"] = deadline_m
                self.jobs[spec.job_id] = entry
                self.seq += 1
                registered += 1
            parent["children"] = children
            parent["n_shards"] = len(children)
            parent["state"] = "fanned"
            parent.pop("lease", None)
            self.save()
            return registered

    def advance_parents(self) -> list[dict]:
        """One parent sweep (fault site ``serve.merge`` at the caller):
        every ``fanned`` parent whose sub-jobs all published is requeued
        as a merge task (phase "merge", ORIGINAL seq — the merge is the
        oldest work its class has); a parent with a terminally-failed
        sub-job goes terminal ``failed`` with a durable diagnosis
        naming the shard, and its still-queued siblings are failed
        alongside (running ones finish harmlessly — their outputs are
        never read). Returns [{job_id, decision, ...}] for the
        service's events/counters."""
        with self._txn():
            moved = []
            for job_id, entry in list(self.jobs.items()):
                if entry.get("state") != "fanned":
                    continue
                rollup = self._shard_rollup(entry)
                if rollup["failed"]:
                    first = rollup.get("first_failure", {})
                    error = (
                        f"shard {first.get('shard')} "
                        f"{first.get('state')}: {first.get('error')}"
                    )
                    self._write_terminal_result(
                        job_id,
                        {"error": error[:2000], "shard_failure": first},
                    )
                    entry["state"] = "failed"
                    entry["error"] = error[:500]
                    for cid in entry.get("children", ()):
                        c = self.jobs.get(cid)
                        if c is not None and c.get("state") == "queued":
                            reason = f"parent {job_id} failed: {error}"
                            self._write_terminal_result(
                                cid, {"error": reason[:2000]},
                            )
                            c["state"] = "failed"
                            c["error"] = reason[:500]
                    moved.append({
                        "job_id": job_id, "decision": "failed",
                        "shard_failure": first,
                    })
                elif rollup["done"] == rollup["n_shards"]:
                    entry["state"] = "queued"
                    entry["phase"] = "merge"
                    moved.append({
                        "job_id": job_id, "decision": "merge",
                        "n_shards": rollup["n_shards"],
                    })
            # orphan reaping: a child that was RUNNING when its parent
            # failed escapes the sibling cancellation above — it later
            # preempts (or is takeover-requeued) back to "queued", and
            # without this sweep the fleet would keep re-claiming and
            # running it forever for a result nothing will ever read
            for job_id, entry in self.jobs.items():
                if entry.get("state") != "queued":
                    continue
                parent_id = entry.get("parent")
                if parent_id is None:
                    continue
                parent = self.jobs.get(parent_id)
                if parent is None:
                    # no journal entry for the parent at all: this is a
                    # DIRECTLY-spooled sub-job (debug/re-run — submit()
                    # admits those on purpose), not an orphan; only a
                    # journaled-terminal parent proves the merge is dead
                    continue
                if parent.get("state") not in TERMINAL_STATES:
                    continue
                reason = (
                    f"parent {parent_id} is terminal "
                    f"({parent.get('state')}): "
                    f"orphaned shard will never be merged"
                )
                self._write_terminal_result(job_id, {"error": reason})
                entry["state"] = "failed"
                entry["error"] = reason[:500]
                moved.append({
                    "job_id": job_id, "decision": "orphaned",
                    "parent": parent_id,
                })
            if moved:
                self.save()
            return moved

    # ---------------------------------------------------------- deadlines

    def expire_deadlines(self) -> list[dict]:
        """Terminal-ize every QUEUED job whose admission-stamped
        monotonic deadline has passed: journal state ``expired`` with a
        durable reason (fault site ``serve.deadline`` at the caller).
        Running jobs are not touched here — their own slice aborts at
        the next checkpoint boundary via the commit-path deadline check
        — and the partial checkpoint is left intact either way, so a
        re-submitted job resumes instead of recomputing (and can never
        splice: resume re-verifies every shard)."""
        now = self.store.now()
        with self._txn():
            expired = []
            for job_id, entry in self.jobs.items():
                if entry.get("state") != "queued":
                    continue
                deadline_m = entry.get("deadline_m")
                if deadline_m is None or float(deadline_m) > now:
                    continue
                overdue = now - float(deadline_m)
                error = (
                    f"expired: deadline passed {overdue:.3f}s ago before "
                    f"the job could run (queued since admission)"
                )
                self._write_terminal_result(
                    job_id, {"error": error, "expired": True},
                )
                entry["state"] = "expired"
                entry["error"] = error[:500]
                expired.append({"job_id": job_id, "reason": error})
            if expired:
                self.save()
            return expired

    def mark_expired(
        self, job_id: str, reason: str,
        daemon_id: str | None = None, token: int | None = None,
    ) -> None:
        """A RUNNING slice hit its deadline at a chunk boundary: fenced
        terminal transition to ``expired`` with a durable reason. The
        committed checkpoint prefix is preserved byte-for-byte — the
        abort happened between commits, so the manifest is a valid
        gap-free prefix and a re-submitted job resumes from it."""
        with self._txn():
            if daemon_id is not None:
                self._check_fence(job_id, daemon_id, int(token or 0))
            self._write_terminal_result(
                job_id, {"error": reason[:2000], "expired": True},
            )
            entry = self.jobs[job_id]
            entry["state"] = "expired"
            entry["error"] = reason[:500]
            entry.pop("lease", None)
            self.save()

    # ----------------------------------------------- state transitions

    def requeue(
        self, job_id: str, chunks_done: int, back: bool,
        daemon_id: str | None = None, token: int | None = None,
    ) -> None:
        """Preempted job back to the queue, releasing its lease.
        ``back=True`` moves it behind its class's waiting jobs (the
        budget-yield fairness rule); ``back=False`` keeps its original
        seq (drain must not penalise the interrupted job). Fenced when
        the caller passes its lease identity: a zombie's requeue of a
        job someone else now owns must be void."""
        with self._txn():
            if daemon_id is not None:
                self._check_fence(job_id, daemon_id, int(token or 0))
            entry = self.jobs[job_id]
            entry["state"] = "queued"
            entry["chunks_done"] = int(chunks_done)
            entry.pop("lease", None)
            if back:
                entry["seq"] = self.seq
                self.seq += 1
            self.save()

    def mark_done(
        self, job_id: str, result: dict,
        daemon_id: str | None = None, token: int | None = None,
    ) -> None:
        """Result file first, journal second: a kill between the two
        re-runs the job's (idempotent, checkpointed) tail rather than
        journaling a result that was never durably written. The fence
        check and the publish share one transaction, so a reclaim
        cannot slip between them."""
        with self._txn():
            if daemon_id is not None:
                self._check_fence(job_id, daemon_id, int(token or 0))
            path = os.path.join(self.results_dir, job_id + ".json")
            write_durable(
                path,
                json.dumps(result, sort_keys=True).encode(),
                tmp=unique_tmp(path),
            )
            entry = self.jobs[job_id]
            entry["state"] = "done"
            entry.pop("error", None)
            entry.pop("lease", None)
            self.save()

    def mark_failed(
        self, job_id: str, error: str,
        daemon_id: str | None = None, token: int | None = None,
    ) -> None:
        with self._txn():
            if daemon_id is not None:
                self._check_fence(job_id, daemon_id, int(token or 0))
            path = os.path.join(self.results_dir, job_id + ".json")
            write_durable(
                path,
                json.dumps({"error": error[:2000]}, sort_keys=True).encode(),
                tmp=unique_tmp(path),
            )
            entry = self.jobs[job_id]
            entry["state"] = "failed"
            entry["error"] = error[:500]
            entry.pop("lease", None)
            self.save()

    def queue_depth(self) -> int:
        return sum(
            1 for j in self.jobs.values() if j.get("state") == "queued"
        )

    # ------------------------------------------------------ disk pressure

    def _disk_shed_reason(self) -> str | None:
        """Admission-control verdict for disk pressure: a ``shed:
        disk`` reason when the spool filesystem is below the low-water
        mark even after a grace GC pass over terminal jobs' litter,
        else None. An unprobeable filesystem admits (the durable writes
        themselves will say otherwise soon enough)."""
        if self.min_free_bytes <= 0:
            return None
        free = free_bytes(self.root)
        if free is None or free >= self.min_free_bytes:
            return None
        # grace pass: terminal jobs' shard/checkpoint litter is the one
        # reclaimable thing the queue owns — drop it and re-probe
        # before refusing work
        self.gc_terminal_litter()
        free = free_bytes(self.root)
        if free is None or free >= self.min_free_bytes:
            return None
        return (
            f"shed: disk free {free >> 20}MB below low-water "
            f"{self.min_free_bytes >> 20}MB on the spool filesystem"
        )

    def gc_terminal_litter(self) -> int:
        """Delete terminal jobs' recovery litter: the ``<output>.ckpt``
        manifest, ``<output>.shards/`` directory and ``<output>.tmp``
        staging file of every journaled done/failed/expired/quarantined
        job. A terminal job will never resume, so its checkpoint state
        is pure disk pressure; the published output itself (and the
        durable result) is never touched. Returns bytes freed.
        Best-effort by design — called under disk pressure and before
        failing a job on ENOSPC, where raising would only make the
        victim's story worse."""
        freed = 0
        for entry in list(self.jobs.values()):
            if entry.get("state") not in TERMINAL_STATES:
                continue
            output = (entry.get("spec") or {}).get("output")
            if not output:
                continue
            parent_id = entry.get("parent")
            if parent_id is not None:
                parent = self.jobs.get(parent_id)
                if parent is None or parent.get("state") in TERMINAL_STATES:
                    # a shard sub-job's published output is intermediate:
                    # once its parent is terminal (merged, failed or
                    # gone) the merge will never read it again — unlike
                    # user-facing outputs, it IS reclaimable litter
                    freed += _remove_counting(output)
            if entry.get("n_shards"):
                # terminal PARENT: its shard outputs are derivable even
                # after the child entries compact away (the one case
                # the per-child branch above cannot see — e.g. a daemon
                # killed between the merge publish and its cleanup)
                from duplexumiconsensusreads_tpu.serve.shard.plan import (
                    shard_output_path,
                )

                for i in range(int(entry["n_shards"])):
                    freed += _remove_counting(
                        shard_output_path(output, i)
                    )
            for path in (output + ".ckpt", output + ".tmp"):
                freed += _remove_counting(path)
            # pid/tid-suffixed staging litter next to the output (an
            # aborted merge's unique_tmp after a real SIGKILL): the
            # names are never reused, so only this sweep reclaims them.
            # Safe against a live zombie merger: its publish-by-rename
            # of a removed tmp fails loudly and the fence absorbs it.
            out_dir = os.path.dirname(output) or "."
            stem = os.path.basename(output) + ".tmp."
            try:
                names = os.listdir(out_dir)
            except OSError:
                names = []
            for n in names:
                if n.startswith(stem):
                    freed += _remove_counting(os.path.join(out_dir, n))
            shard_dir = output + ".shards"
            try:
                names = os.listdir(shard_dir)
            except OSError:
                continue
            for n in names:
                freed += _remove_counting(os.path.join(shard_dir, n))
            try:
                os.rmdir(shard_dir)
            except OSError:
                pass
        return freed

    # ------------------------------------------------------- maintenance

    def sweep_orphan_tmps(self) -> int:
        """Remove staging files orphaned by dead daemons. Fleet writers
        stage through ``<dst>.tmp.<pid>.<tid>`` names (io.durable.
        unique_tmp) so concurrent writers can't collide — but a daemon
        killed between its tmp write and the rename leaves that file
        behind forever (no later writer reuses the name). A file is an
        orphan exactly when its embedded pid is dead — no clocks, no
        guessing; live daemons' in-flight staging files are untouched.
        The pid probe is the STORE's liveness oracle: on a sharedfs
        spool pids from other hosts are unprobeable, so the store
        answers "possibly alive" for every pid and this sweep removes
        nothing (unreaped litter is inert; gc_terminal_litter still
        reclaims the bulk per terminal job). Called at daemon startup;
        returns the number removed."""
        removed = 0
        for d in (self.root, self.inbox_dir, self.results_dir):
            try:
                names = os.listdir(d)
            except OSError:
                continue
            for n in names:
                parts = n.rsplit(".", 3)
                if len(parts) != 4 or parts[1] != "tmp":
                    continue
                try:
                    pid = int(parts[2])
                    int(parts[3])
                except ValueError:
                    continue
                if self.store.pid_alive(pid):
                    continue
                try:
                    os.remove(os.path.join(d, n))
                    removed += 1
                except OSError:
                    pass  # raced away / permissions: litter, not a fault
        return removed
