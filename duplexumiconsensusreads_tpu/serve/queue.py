"""Durable spool + admission queue for the consensus service.

Spool layout (one directory, shared by clients and the daemon):

  inbox/<job_id>.json   client submissions — written durably by the
                        client, removed by the daemon only AFTER the
                        job is durably journaled (so a kill anywhere in
                        admission re-admits instead of losing the job;
                        job_id is the dedupe key, so re-admission can
                        never double-enter)
  queue.json            the daemon's admission-queue journal: every
                        accepted job with its state machine
                        (queued → running → done | failed), persisted
                        via the tmp+fsync+rename protocol on EVERY
                        transition — whatever the journal says survived
                        the crash is exactly what the restarted daemon
                        resumes
  results/<job_id>.json final per-job report (durable), read by
                        ``call --status/--wait``
  metrics.json          the live service heartbeat snapshot

Fault sites: ``serve.accept`` guards the read+parse+validate of each
submission; ``serve.journal`` guards every journal persist. Both ride
the streaming executor's bounded host-I/O retry ladder, so transient
faults are absorbed and an injected kill leaves exactly the on-disk
state a real SIGKILL would.
"""

from __future__ import annotations

import json
import os

from duplexumiconsensusreads_tpu.io.durable import write_durable
from duplexumiconsensusreads_tpu.serve.job import JobSpec, validate_spec

JOURNAL_VERSION = 1

# journal job states; the only legal transitions are
# queued -> running -> (done | failed | queued on preempt/recovery)
JOB_STATES = ("queued", "running", "done", "failed", "rejected")


class SpoolQueue:
    """The admission queue over one spool directory.

    All mutating methods persist the journal durably before returning;
    the in-memory ``jobs`` dict is only ever a cache of queue.json.
    Thread safety is the caller's job (serve.service serializes all
    journal mutations under its scheduler lock).
    """

    def __init__(self, root: str, max_queue: int = 64,
                 max_terminal_kept: int = 256):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1 (got {max_queue})")
        if max_terminal_kept < 0:
            raise ValueError(
                f"max_terminal_kept must be >= 0 (got {max_terminal_kept})"
            )
        self.root = root
        self.max_queue = max_queue
        # the journal is rewritten+fsynced on every transition, so it
        # must stay bounded on a long-lived daemon: terminal entries
        # (done/failed/rejected) beyond this many are compacted away on
        # save — their durable per-job report in results/ remains the
        # record (status() falls back to it)
        self.max_terminal_kept = max_terminal_kept
        self.inbox_dir = os.path.join(root, "inbox")
        self.results_dir = os.path.join(root, "results")
        os.makedirs(self.inbox_dir, exist_ok=True)
        os.makedirs(self.results_dir, exist_ok=True)
        self.journal_path = os.path.join(root, "queue.json")
        self.jobs: dict[str, dict] = {}
        self.seq = 0
        self._load()

    # ------------------------------------------------------- client side

    def submit(self, spec: JobSpec) -> str:
        """Durably spool one validated job into the inbox (client side;
        the daemon never calls this). Returns the job id."""
        payload = json.dumps(spec.to_dict(), sort_keys=True).encode()
        write_durable(
            os.path.join(self.inbox_dir, spec.job_id + ".json"), payload
        )
        return spec.job_id

    def status(self, job_id: str) -> dict:
        """One job's observable state, from the journal + inbox +
        results — readable while the daemon runs (every file involved
        is only ever atomically replaced).

        Admission-race discipline: the daemon journals BEFORE unlinking
        the inbox file, but a reader that loads the journal first and
        checks the inbox second can see neither (journal read pre-save,
        inbox checked post-unlink). After an inbox miss the journal is
        therefore RE-read — a live job must never be reported "unknown"
        (which ``client.wait`` treats as terminal)."""
        self._load()
        entry = self.jobs.get(job_id)
        if entry is None:
            if os.path.exists(os.path.join(self.inbox_dir, job_id + ".json")):
                return {"job_id": job_id, "state": "submitted"}
            self._load()  # close the accept-vs-status window
            entry = self.jobs.get(job_id)
        if entry is None:
            return self._status_from_result(job_id)
        out = {"job_id": job_id, **{k: v for k, v in entry.items()
                                    if k != "spec"}}
        result_path = os.path.join(self.results_dir, job_id + ".json")
        if entry.get("state") in ("done", "failed") and os.path.exists(
            result_path
        ):
            try:
                with open(result_path) as f:
                    out["result"] = json.load(f)
            except (OSError, ValueError):
                pass  # result file torn/racing: state alone still answers
        return out

    def _status_from_result(self, job_id: str) -> dict:
        """Jobs whose terminal journal entry was compacted away still
        answer from their durable result file."""
        result_path = os.path.join(self.results_dir, job_id + ".json")
        try:
            with open(result_path) as f:
                result = json.load(f)
        except (OSError, ValueError):
            return {"job_id": job_id, "state": "unknown"}
        state = "failed" if "error" in result else "done"
        return {"job_id": job_id, "state": state, "result": result,
                "compacted": True}

    # ------------------------------------------------------- daemon side

    def _load(self) -> None:
        """Refresh the in-memory view from queue.json. A torn or
        garbage journal is discarded (never fatal): the inbox files
        still exist for every job whose admission didn't complete, and
        jobs already dispatched wrote their own durable outputs."""
        try:
            with open(self.journal_path) as f:
                on_disk = json.load(f)
            if not isinstance(on_disk, dict) or not isinstance(
                on_disk.get("jobs"), dict
            ):
                raise ValueError("journal is not a {jobs: {...}} object")
        except FileNotFoundError:
            return
        except (OSError, ValueError):
            return
        self.jobs = on_disk["jobs"]
        self.seq = int(on_disk.get("seq", len(self.jobs)))

    def _compact(self) -> None:
        """Bound the journal: drop the OLDEST terminal entries beyond
        ``max_terminal_kept`` (their results/ file stays the durable
        record). Open jobs (queued/running) are never touched."""
        terminal = sorted(
            (
                (int(e.get("seq", 0)), jid)
                for jid, e in self.jobs.items()
                if e.get("state") in ("done", "failed", "rejected")
            ),
        )
        for _, jid in terminal[: max(len(terminal) - self.max_terminal_kept, 0)]:
            del self.jobs[jid]

    def save(self) -> None:
        """Durable journal persist (fault site ``serve.journal``)."""
        # _io_retry imported lazily: the CLIENT side of this module
        # (submit/status for `call --submit/--status/--wait`) must not
        # drag in runtime.stream — and through it jax — on every poll
        from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

        self._compact()
        payload = json.dumps(
            {"version": JOURNAL_VERSION, "seq": self.seq, "jobs": self.jobs},
            sort_keys=True,
        ).encode()
        _io_retry(
            "serve.journal",
            lambda: write_durable(self.journal_path, payload),
            "queue journal save",
        )

    def pending_submissions(self) -> list[str]:
        """Inbox job ids in ARRIVAL order (mtime of the durable spool
        file, name as tiebreak): admission seq — and therefore FIFO
        order within a priority class — follows submission time, not
        the job-id hash the filenames happen to sort by."""
        entries = []
        try:
            for n in os.listdir(self.inbox_dir):
                if not n.endswith(".json"):
                    continue
                try:
                    mt = os.stat(os.path.join(self.inbox_dir, n)).st_mtime
                except OSError:
                    continue  # raced away mid-listing
                entries.append((mt, n))
        except OSError:
            return []
        return [n[:-5] for _, n in sorted(entries)]

    def accept_one(self, job_id: str) -> tuple[JobSpec | None, str | None]:
        """Admit one inbox submission: read + validate (fault site
        ``serve.accept``), journal it durably, THEN remove the inbox
        file. Returns (spec, None) on admission, (None, reason) on
        rejection (bounded queue, invalid spec), (None, None) when the
        submission was a duplicate of an already-journaled job.

        Kill-anywhere safety: before the journal save the inbox file is
        untouched (restart re-admits); after it, re-admission dedupes on
        job_id and merely removes the leftover inbox file."""
        from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

        path = os.path.join(self.inbox_dir, job_id + ".json")

        def _read():
            with open(path, "rb") as f:
                return f.read()

        try:
            raw = _io_retry("serve.accept", _read, f"job {job_id} accept")
        except FileNotFoundError:
            return None, None  # raced away (duplicate listing)
        if job_id in self.jobs:
            # already journaled (kill landed between journal + unlink):
            # admission already happened exactly once — just clean up
            self._unlink_inbox(path)
            return None, None
        try:
            spec = validate_spec(json.loads(raw.decode()))
            if spec.job_id != job_id:
                raise ValueError(
                    f"spec job_id {spec.job_id!r} does not match the "
                    f"spool filename"
                )
        except (ValueError, UnicodeDecodeError) as e:
            self.jobs[job_id] = {
                "state": "rejected", "error": str(e)[:500], "seq": self.seq,
            }
            self.seq += 1
            self.save()
            self._unlink_inbox(path)
            return None, str(e)
        n_open = sum(
            1 for j in self.jobs.values() if j.get("state") in ("queued", "running")
        )
        if n_open >= self.max_queue:
            # bounded admission: REJECT (journaled, so --status answers)
            # rather than silently stalling the inbox forever
            reason = f"queue full ({n_open}/{self.max_queue} jobs open)"
            self.jobs[job_id] = {
                "state": "rejected", "error": reason, "seq": self.seq,
            }
            self.seq += 1
            self.save()
            self._unlink_inbox(path)
            return None, reason
        self.jobs[job_id] = {
            "state": "queued",
            "seq": self.seq,
            "priority": spec.priority,
            "spec": spec.to_dict(),
            "slices": 0,
            "chunks_done": 0,
        }
        self.seq += 1
        self.save()
        self._unlink_inbox(path)
        return spec, None

    @staticmethod
    def _unlink_inbox(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass  # re-admission dedupes; a leftover file is harmless

    # ----------------------------------------------- state transitions

    def mark_running(self, job_id: str) -> None:
        entry = self.jobs[job_id]
        entry["state"] = "running"
        entry["slices"] = int(entry.get("slices", 0)) + 1
        self.save()

    def requeue(self, job_id: str, chunks_done: int, back: bool) -> None:
        """Preempted (or crash-recovered) job back to the queue.
        ``back=True`` moves it behind its class's waiting jobs (the
        budget-yield fairness rule); ``back=False`` keeps its original
        seq (crash recovery must not penalise the interrupted job)."""
        entry = self.jobs[job_id]
        entry["state"] = "queued"
        entry["chunks_done"] = int(chunks_done)
        if back:
            entry["seq"] = self.seq
            self.seq += 1
        self.save()

    def mark_done(self, job_id: str, result: dict) -> None:
        """Result file first, journal second: a kill between the two
        re-runs the job's (idempotent, checkpointed) tail rather than
        journaling a result that was never durably written."""
        write_durable(
            os.path.join(self.results_dir, job_id + ".json"),
            json.dumps(result, sort_keys=True).encode(),
        )
        entry = self.jobs[job_id]
        entry["state"] = "done"
        entry.pop("error", None)
        self.save()

    def mark_failed(self, job_id: str, error: str) -> None:
        write_durable(
            os.path.join(self.results_dir, job_id + ".json"),
            json.dumps({"error": error[:2000]}, sort_keys=True).encode(),
        )
        entry = self.jobs[job_id]
        entry["state"] = "failed"
        entry["error"] = error[:500]
        self.save()

    def recover_running(self) -> list[str]:
        """Daemon start: every job the journal says was RUNNING was
        interrupted by the previous daemon's death — requeue it at its
        ORIGINAL seq (it reached the front once already) with resume
        semantics (its checkpoint, if any survived, skips done chunks)."""
        recovered = []
        for job_id, entry in self.jobs.items():
            if entry.get("state") == "running":
                entry["state"] = "queued"
                recovered.append(job_id)
        if recovered:
            self.save()
        return recovered

    def queue_depth(self) -> int:
        return sum(
            1 for j in self.jobs.values() if j.get("state") == "queued"
        )
