"""Shard merger: splice K per-shard BAMs into one indexed output.

A finalised streaming output is exactly three byte regions, each a run
of whole BGZF members:

    [ header shell ][ per-chunk record members ... ][ BGZF EOF block ]

The incremental finalise writes the header shell as its own member(s)
(``compress_fast(serialize_bam(hdr, []), eof=False)``) and appends each
chunk's deflated record stream verbatim, so the boundary between header
and records always falls on a BGZF block boundary — which is what makes
the merge a pure compressed-byte splice: take shard 0's header shell,
append every shard's record region verbatim in shard order, terminate
with the standard EOF block. No inflate, no re-deflate, no record
parse; the merged bytes are the unsharded run's bytes because each
shard's record members ARE the unsharded run's members for its chunks
(the planner's chunk-grid alignment contract, serve/shard/plan.py).

Safety: every shard's header region must be byte-identical to shard
0's — a mismatch means config/provenance drift between sub-jobs and
the merge refuses loudly rather than publish a frankenstein output.
The splice assembles in a private staging file via the idempotent
``rewrite_from`` protocol and publishes with the one atomic
fsync+rename, so a retried (or re-claimed) merge converges; commits
ride fault site ``serve.merge`` through the executor's bounded retry
ladder, and the caller's fence hook runs between shards so a zombie
merger aborts before publishing.
"""

from __future__ import annotations

import os
import time

_COPY_BLOCK = 4 << 20

# how often the splice loop re-runs the caller's fence hook while
# copying ONE shard: the hook is a flock'd journal txn + fsync, so
# per-copy-block would hammer the spool, but a multi-GB shard copy with
# no stamp at all is exactly the uninstrumented stretch the stuck-run
# watchdog would abort-requeue (and eventually quarantine) a healthy
# merge over
_FENCE_INTERVAL_S = 5.0


def member_spans(path: str) -> tuple[int, int]:
    """(header_end, eof_start): compressed byte offsets splitting a
    finalised output into its header shell / record members / EOF
    block. Raises ValueError (path-bearing) when ``path`` is not a
    well-formed finalised output — truncated, EOF-less, or with a
    header not ending on a block boundary. Reads O(header) bytes, not
    the file: the block walk stops at the first boundary at/past the
    decompressed header length, so merging never scans a shard's
    record bytes twice."""
    from duplexumiconsensusreads_tpu.io import bgzf
    from duplexumiconsensusreads_tpu.io.bgzf import BGZF_EOF
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    size = os.path.getsize(path)
    if size < len(BGZF_EOF):
        raise ValueError(f"{path}: too small to be a finalised BAM")
    with open(path, "rb") as f:
        f.seek(size - len(BGZF_EOF))
        if not bgzf.has_eof_block(f.read(len(BGZF_EOF))):
            raise ValueError(
                f"{path}: missing the BGZF EOF block — not a finalised "
                f"output (torn or still being written?)"
            )
    r = BamStreamReader(path)
    try:
        hlen = r._consumed  # decompressed header bytes, by the parser
    finally:
        r.close()
    # header-only block walk: accumulate per-block decompressed sizes
    # (the ISIZE trailer) until the running total reaches hlen — that
    # boundary's compressed offset is where the record members begin
    header_end = None
    c_pos = 0
    u_pos = 0
    with open(path, "rb") as f:
        while u_pos < hlen and c_pos + 28 <= size:
            f.seek(c_pos)
            head = f.read(18)
            if len(head) < 18:
                break
            bsize = bgzf.read_block_size(head, 0)
            if c_pos + bsize > size:
                break
            f.seek(c_pos + bsize - 4)
            isize = int.from_bytes(f.read(4), "little")
            c_pos += bsize
            u_pos += isize
    if u_pos == hlen:
        header_end = c_pos
    if header_end is None:
        raise ValueError(
            f"{path}: header does not end on a BGZF block boundary — "
            f"not a shard output of the incremental finalise"
        )
    return header_end, size - len(BGZF_EOF)


def splice_shards(
    out_path: str,
    shard_paths: list[str],
    fence=None,
    write_index: bool = False,
) -> dict:
    """Splice ``shard_paths`` (shard order) into ``out_path``.

    ``fence`` (optional callable) runs before each shard's copy AND
    before the publish — the serving layer passes its fenced lease
    renewal so a merger whose lease was reclaimed aborts mid-splice.
    ``write_index=True`` rebuilds the standard BAI (or CSI when a
    contig exceeds BAI's coordinate space) over the merged output,
    exactly as the unsharded run's finalise would.

    Returns {"output_bytes", "n_shards", "shard_bytes": [...]}. Pure
    function of the shard files: safe to re-run after any kill.
    """
    from duplexumiconsensusreads_tpu.io.bgzf import BGZF_EOF
    from duplexumiconsensusreads_tpu.io.durable import (
        fsync_file,
        replace_durable,
        rewrite_from,
        unique_tmp,
    )
    from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

    if not shard_paths:
        raise ValueError("splice_shards needs at least one shard output")
    spans = [
        _io_retry("serve.merge", lambda p=p: member_spans(p),
                  f"shard span scan {p}")
        for p in shard_paths
    ]
    with open(shard_paths[0], "rb") as f:
        header = f.read(spans[0][0])
    # header-identity invariant: sub-jobs share (input, config), so
    # their derived headers must agree byte-for-byte; drift means the
    # merged output could not equal the unsharded run's and the merge
    # must refuse rather than splice
    for p, (h_end, _) in zip(shard_paths[1:], spans[1:]):
        with open(p, "rb") as f:
            other = f.read(h_end)
        if other != header:
            raise ValueError(
                f"shard header mismatch: {p} does not reproduce "
                f"{shard_paths[0]}'s header — config/provenance drift "
                f"between sub-jobs; refusing to merge"
            )

    tmp = unique_tmp(out_path)
    shard_bytes = []
    published = False
    try:
        with open(tmp, "wb") as f:
            _io_retry(
                "serve.merge", lambda: rewrite_from(f, 0, header),
                "merge header write",
            )
            last_fence = [time.monotonic()]

            def _tick_fence():
                # rate-limited mid-copy fence: keeps the watchdog's
                # durable-progress clock running through a long single
                # shard without a journal txn per copy block
                if fence is None:
                    return
                now = time.monotonic()
                if now - last_fence[0] >= _FENCE_INTERVAL_S:
                    last_fence[0] = now
                    fence()

            for p, (h_end, eof_start) in zip(shard_paths, spans):
                if fence is not None:
                    fence()
                    last_fence[0] = time.monotonic()
                off = f.tell()

                def _copy(p=p, h_end=h_end, eof_start=eof_start, off=off):
                    # idempotent per-shard append: a transient failure
                    # mid-copy truncates back and re-copies this shard
                    # only
                    f.seek(off)
                    f.truncate(off)
                    with open(p, "rb") as src:
                        src.seek(h_end)
                        left = eof_start - h_end
                        while left > 0:
                            block = src.read(min(_COPY_BLOCK, left))
                            if not block:
                                raise ValueError(
                                    f"{p}: truncated while merging "
                                    f"(shard output changed underneath?)"
                                )
                            f.write(block)
                            left -= len(block)
                            _tick_fence()

                _io_retry("serve.merge", _copy, f"merge splice {p}")
                shard_bytes.append(eof_start - h_end)
            end = f.tell()

            def _seal():
                rewrite_from(f, end, BGZF_EOF)
                fsync_file(f)

            _io_retry("serve.merge", _seal, "merge EOF seal")
        if fence is not None:
            fence()
        _io_retry(
            "serve.merge", lambda: replace_durable(tmp, out_path),
            "merge publish",
        )
        published = True
    finally:
        if not published:
            # an aborted merge (failure, fence, modelled kill) must not
            # leak an output-sized staging file: the pid/tid-unique tmp
            # is never reused, so nothing but this cleanup (or the
            # terminal-litter GC's pattern sweep, for a real SIGKILL)
            # would ever reclaim it
            try:
                os.remove(tmp)
            except OSError:
                pass
    out_bytes = os.path.getsize(out_path)
    if write_index:
        if fence is not None:
            # the index rebuild is one long uninstrumented scan: reset
            # the watchdog's durable-progress clock going in (the build
            # itself is bounded by one watchdog interval — see
            # ARCHITECTURE "Job sharding")
            fence()
        _io_retry(
            "serve.merge", lambda: _build_merged_index(out_path),
            "merged index build",
        )
    return {
        "output_bytes": out_bytes,
        "n_shards": len(shard_paths),
        "shard_bytes": shard_bytes,
    }


def _build_merged_index(out_path: str) -> None:
    """The unsharded finalise's index choice, rebuilt over the merged
    output: BAI unless a header contig exceeds its 2^29 coordinate
    space, then CSI with depth sized to the contig."""
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    r = BamStreamReader(out_path)
    try:
        max_len = max(r.header.ref_lengths, default=0)
    finally:
        r.close()
    if max_len > (1 << 29):
        from duplexumiconsensusreads_tpu.io.csi import build_csi

        build_csi(out_path)
    else:
        from duplexumiconsensusreads_tpu.io.bai import build_bai

        build_bai(out_path)
