"""serve/shard/ — scatter-gather job sharding.

The consensus pipeline is embarrassingly parallel across genomic
ranges, but a job submitted to the service runs as one serial stream on
one daemon. This package turns N daemons into N-way parallelism on ONE
large input, with the headline contract that the merged output is
byte-identical to the same job run unsharded:

  PLANNER (plan.py)   a job submitted with ``shards=K`` (or
                      ``shard_bytes``) is claimed like any job; the
                      claiming daemon scans the input's chunk grid —
                      the exact boundaries the unsharded run would use
                      — and registers K range sub-jobs in one durable
                      journal transaction (fault site ``serve.split``,
                      fenced: a kill mid-plan re-plans idempotently,
                      sub-job ids derived from (parent_id, shard_idx)).
  FAN-OUT             sub-jobs are ordinary journal entries: they flow
                      through the unchanged queue/scheduler/lease/
                      fence/watchdog path, so every daemon claims,
                      runs, preempts, resumes, takes over and
                      quarantines them exactly like whole jobs. The
                      parent is a journaled aggregate state machine
                      (queued → splitting → fanned → merging →
                      done/failed) riding the same flock'd txn
                      protocol.
  MERGER (merge.py)   when the last sub-job publishes, the parent is
                      requeued as a merge task any daemon can claim
                      (same lease protocol, fault site ``serve.merge``)
                      and the per-shard BGZF outputs are spliced in
                      shard order — one header, the shard record
                      members verbatim, one EOF block — then the BAI/
                      CSI index is rebuilt over the merged output.

Byte identity holds because consensus record names embed the global
chunk index: the planner aligns every shard to whole-file chunk
boundaries (``chunk_base`` + ``first_read`` realign the raw-read grid,
see plan.py), so each shard output's record members are the unsharded
run's members for those chunks, verbatim.
"""

_LAZY = {
    "ShardPlan": "duplexumiconsensusreads_tpu.serve.shard.plan",
    "ShardRange": "duplexumiconsensusreads_tpu.serve.shard.plan",
    "plan_shards": "duplexumiconsensusreads_tpu.serve.shard.plan",
    "child_job_id": "duplexumiconsensusreads_tpu.serve.shard.plan",
    "shard_output_path": "duplexumiconsensusreads_tpu.serve.shard.plan",
    "splice_shards": "duplexumiconsensusreads_tpu.serve.shard.merge",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
