"""Shard planner: split one streaming job into K range sub-jobs whose
outputs splice back byte-identical to the unsharded run.

The unsharded executor's chunk boundaries are a pure function of two
things: the sequence of raw-read END positions (record counts on the
stream — ``chunk_reads`` per read, fewer at EOF) and the pos_keys of
the records (the hold-back rule in ``_resolve_chunk_boundary``). The
consensus record NAMES embed the chunk index, so byte identity requires
a shard to reproduce the whole-file chunk grid exactly, not just cover
the right records. The planner therefore:

  1. replays the chunker's boundary rule over one sequential scan of
     the input, recording for every chunk its first record's global
     index, pos_key, decompressed offset, and the stream position its
     first raw-read buffer ends at;
  2. picks K-1 shard boundaries at eligible chunk starts (mapped keys
     only — a boundary inside the unmapped sentinel tail would make
     the key range degenerate), balanced by DECOMPRESSED input offset
     (compressed offsets quantize to ~64KB BGZF blocks, which
     degenerates the balance on small inputs);
  3. emits per shard: ``input_range`` (BGZF seek voffset + half-open
     pos_key range), ``chunk_base`` (the shard's first global chunk
     index — record names and checkpoint keys stay on the parent
     grid), and ``first_read`` (records in the shard's first raw read,
     realigning the read grid so every later boundary lands where the
     whole-file stream's would).

Because shard ranges are half-open pos_key intervals at chunk starts
and families never span pos_keys, every record — mate/overlap edge
reads included — lands in exactly one shard; the tiling is exact by
the same family-integrity argument the multihost partition uses.

``mate_aware="auto"`` resolves against the FIRST chunk of a run, which
for a shard would be the shard's own first chunk — so the planner
resolves it once against the parent's first chunk and PINS the
resolution into every sub-job, keeping grouping (and bytes) identical
to the unsharded run whatever each shard's local pairedness looks like.

Planning costs one sequential decode pass (pos_keys only — no device,
no consensus) plus one header-only BGZF block walk (``_scan_blocks``
re-reads the compressed bytes without inflating, to map the K-1
boundary offsets to seekable voffsets — BGZF has no block index, so
the walk cannot be skipped; threading the block table out of the
decode pass itself is a known follow-up). The scan reuses the
streaming reader, the block table and the chunk-boundary rule
verbatim, so planner and executor cannot drift.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from duplexumiconsensusreads_tpu.io.convert import UNMAPPED_POS_KEY


# fan-out ceiling when the caller supplies no bound of its own: a
# --shard-bytes request over a jumbo input must not register thousands
# of sub-jobs in one journal txn (every journal save rewrites every
# entry, and the fleet's admission bound is phrased over open jobs)
MAX_SHARDS_DEFAULT = 256


def child_job_id(parent_id: str, idx: int) -> str:
    """Deterministic sub-job id: re-planning after a kill derives the
    same ids, so journal dedupe makes registration idempotent."""
    return f"{parent_id}.s{idx:03d}"


def shard_output_path(parent_output: str, idx: int) -> str:
    """Per-shard output path, derived (not journaled) so the planner
    and the merger agree without coordination."""
    return f"{parent_output}.shard{idx:03d}.bam"


@dataclasses.dataclass(frozen=True)
class ShardRange:
    """One sub-job's share of the parent's chunk grid."""

    idx: int
    chunk_base: int  # global index of the shard's first chunk
    n_chunks: int
    start: tuple[int, int] | None  # BGZF (coffset, uoffset) seek, or None
    key_lo: int | None  # half-open pos_key range [key_lo, key_hi)
    key_hi: int | None
    first_read: int | None  # records in the first raw read (grid realign)
    n_records: int
    approx_cbytes: int  # compressed input bytes this shard spans


@dataclasses.dataclass(frozen=True)
class ShardPlan:
    input: str
    chunk_reads: int
    n_chunks: int
    n_records: int
    mate_aware: str  # pinned resolution: "on" | "off"
    ranges: tuple


def _chunk_grid(path: str, chunk_reads: int,
                progress=None) -> tuple[list[dict], int]:
    """Replay the streaming chunk-boundary rule: one sequential scan
    yielding, per chunk, {start (global record idx), uoff (global
    decompressed offset of its first record), key (first record's
    pos_key), first_read (records the chunk's first raw-read buffer
    holds from its start — the shard realignment count), n (records)}.
    Returns (chunks, total_records).

    ``progress`` (optional callable) fires once per raw read: the
    serving layer wires a rate-limited fenced lease renewal here so a
    long planner scan keeps stamping durable progress — without it the
    stuck-run watchdog would see a silent ``splitting`` parent and
    abort-requeue (eventually quarantine) a perfectly healthy job.
    """
    from duplexumiconsensusreads_tpu.io.index import _record_offsets
    from duplexumiconsensusreads_tpu.io.native_reader import region_pos_keys
    from duplexumiconsensusreads_tpu.runtime.stream import (
        BamStreamReader,
        _resolve_chunk_boundary,
    )

    reader = BamStreamReader(path)
    chunks: list[dict] = []
    buf_keys = np.zeros(0, np.int64)
    buf_uoffs = np.zeros(0, np.int64)
    buf_start = 0  # global record index of buffer[0]
    recs_read = 0  # stream records consumed so far
    first_buf_end = None  # recs_read after the current buffer's 1st read
    prev_last = None
    try:
        while True:
            raw = reader.read_raw_records(chunk_reads)
            if progress is not None:
                progress()
            if raw is None:
                if len(buf_keys):
                    # EOF flush: the held-back tail (one pos_key group
                    # by the cut rule) becomes the final chunk
                    chunks.append({
                        "start": buf_start,
                        "uoff": int(buf_uoffs[0]),
                        "key": int(buf_keys[0]),
                        "first_read": (
                            (first_buf_end if first_buf_end is not None
                             else recs_read + chunk_reads) - buf_start
                        ),
                        "n": len(buf_keys),
                    })
                break
            offs = _record_offsets(raw)
            base = reader._consumed - len(raw)
            keys = region_pos_keys(np.frombuffer(raw, np.uint8), offs)
            recs_read += len(offs)
            if first_buf_end is None:
                first_buf_end = recs_read
            buf_keys = np.concatenate([buf_keys, keys])
            buf_uoffs = np.concatenate([buf_uoffs, base + offs])
            cut, prev_last = _resolve_chunk_boundary(buf_keys, prev_last)
            if cut == 0:
                continue  # whole buffer one group: keep growing
            chunks.append({
                "start": buf_start,
                "uoff": int(buf_uoffs[0]),
                "key": int(buf_keys[0]),
                "first_read": first_buf_end - buf_start,
                "n": int(cut),
            })
            buf_start += int(cut)
            buf_keys = buf_keys[cut:]
            buf_uoffs = buf_uoffs[cut:]
            first_buf_end = None
    finally:
        reader.close()
    return chunks, recs_read


def _pin_mate_aware(path: str, chunk_reads: int, duplex: bool,
                    setting: str) -> str:
    """Resolve the parent's mate_aware setting the way the unsharded
    run would — against the whole file's FIRST chunk — and pin it.
    The resolution goes through the executor's own resolver, not a
    local copy of its rule: this pin exists so shard grouping matches
    the unsharded run byte-for-byte, and a drifted duplicate of the
    auto policy would be exactly the silent divergence it prevents."""
    if setting in ("on", "off"):
        return setting
    from duplexumiconsensusreads_tpu.runtime.executor import (
        resolve_mate_aware,
    )
    from duplexumiconsensusreads_tpu.runtime.stream import iter_batch_chunks
    from duplexumiconsensusreads_tpu.types import GroupingParams

    it = iter_batch_chunks(path, chunk_reads, duplex, warn_mixed=False)
    first = next(it, None)
    it.close()
    info = first[2] if first is not None else {}
    resolved = resolve_mate_aware(GroupingParams(), info, setting)
    return "on" if resolved.mate_aware else "off"


def plan_shards(
    path: str,
    chunk_reads: int,
    duplex: bool,
    n_shards: int | None = None,
    shard_bytes: int | None = None,
    mate_aware: str = "auto",
    progress=None,
    max_shards: int | None = None,
) -> ShardPlan:
    """Plan K range sub-jobs over ``path``'s whole-file chunk grid.

    ``n_shards`` asks for K directly; ``shard_bytes`` derives K from
    the compressed input size. Either way K is clamped to what the
    grid can legally support (eligible boundaries are chunk starts
    with mapped keys — never inside the unmapped sentinel tail — and
    there are only n_chunks of those) AND to ``max_shards`` (default
    :data:`MAX_SHARDS_DEFAULT`; the serving layer passes its own
    open-jobs bound so one parent cannot swamp the fleet's admission
    control). K=1 degenerates to one sub-job with no range at all:
    literally the unsharded invocation.
    """
    import os

    from duplexumiconsensusreads_tpu.io.index import _scan_blocks

    if (n_shards is None) == (shard_bytes is None):
        raise ValueError("plan_shards needs exactly one of n_shards / "
                         "shard_bytes")
    chunks, n_records = _chunk_grid(path, chunk_reads, progress=progress)
    total_cbytes = os.path.getsize(path)
    if not chunks:
        # record-less input: one degenerate sub-job runs the plain
        # path and emits the header-only BAM; merge of 1 reassembles it
        return ShardPlan(
            input=path, chunk_reads=chunk_reads, n_chunks=0, n_records=0,
            mate_aware=_pin_mate_aware(path, chunk_reads, duplex, mate_aware),
            ranges=(ShardRange(
                idx=0, chunk_base=0, n_chunks=0, start=None, key_lo=None,
                key_hi=None, first_read=None, n_records=0,
                approx_cbytes=total_cbytes,
            ),),
        )
    if shard_bytes is not None:
        n_shards = max(-(-total_cbytes // max(shard_bytes, 1)), 1)
    # eligible interior boundaries: chunk c (c >= 1) whose start key is
    # mapped — a sentinel-key boundary would give key_lo == key_hi ==
    # UNMAPPED_POS_KEY (the whole tail shares the sentinel), an empty
    # range that loses the tail
    eligible = [
        c for c in range(1, len(chunks))
        if chunks[c]["key"] != int(UNMAPPED_POS_KEY)
    ]
    cap = max_shards if max_shards is not None else MAX_SHARDS_DEFAULT
    k = max(min(int(n_shards), len(eligible) + 1, max(cap, 1)), 1)

    # voffset mapping for the boundary chunks' first records; the walk
    # re-reads every compressed block, so it stamps progress like the
    # decode pass (the watchdog must never see a silent full-file scan)
    c_off, cum_u = _scan_blocks(path, progress=progress)

    def _voffset(uoff: int) -> tuple[int, int]:
        bi = min(
            int(np.searchsorted(cum_u, uoff, side="right")) - 1,
            len(c_off) - 1,
        )
        return int(c_off[bi]), int(uoff - cum_u[bi])

    # boundary choice balanced by DECOMPRESSED input offset: pick, for
    # each target i*total/k, the eligible boundary nearest it (strictly
    # after the previous pick). Decompressed — not compressed — offsets,
    # because BGZF blocks quantize compressed offsets to ~64KB, which
    # collapses every boundary of a small input onto one block and
    # degenerates the balance
    total_u = int(cum_u[-1])
    bounds: list[int] = []
    if k > 1:
        per = total_u / k
        prev = 0
        for i in range(1, k):
            target = i * per
            cands = [c for c in eligible if c > prev]
            if not cands:
                break
            best = min(cands, key=lambda c: abs(chunks[c]["uoff"] - target))
            bounds.append(best)
            prev = best
    starts = [0, *bounds, len(chunks)]

    ranges = []
    for i in range(len(starts) - 1):
        b, e = starts[i], starts[i + 1]
        first = chunks[b]
        co = _voffset(first["uoff"])[0] if b > 0 else 0
        co_end = (
            _voffset(chunks[e]["uoff"])[0] if e < len(chunks)
            else total_cbytes
        )
        ranges.append(ShardRange(
            idx=i,
            chunk_base=b,
            n_chunks=e - b,
            # shard 0 runs the plain no-seek path: its grid is already
            # the whole-file grid, so no realignment either
            start=_voffset(first["uoff"]) if b > 0 else None,
            key_lo=first["key"] if b > 0 else None,
            key_hi=chunks[e]["key"] if e < len(chunks) else None,
            first_read=first["first_read"] if b > 0 else None,
            n_records=sum(c["n"] for c in chunks[b:e]),
            approx_cbytes=co_end - co,
        ))
    pinned = _pin_mate_aware(path, chunk_reads, duplex, mate_aware)
    return ShardPlan(
        input=path,
        chunk_reads=chunk_reads,
        n_chunks=len(chunks),
        n_records=n_records,
        mate_aware=pinned,
        ranges=tuple(ranges),
    )


def child_spec_dicts(parent_spec, plan: ShardPlan) -> list[dict]:
    """The K sub-job spec dicts for one parent: same config (the @PG
    provenance line — and therefore the header bytes — must match the
    unsharded run's), mate_aware pinned, range/grid fields under
    ``shard``. Deterministic: a re-plan after a kill emits the same
    dicts, and journal dedupe on the derived ids does the rest."""
    out = []
    for r in plan.ranges:
        d = {
            "job_id": child_job_id(parent_spec.job_id, r.idx),
            "input": parent_spec.input,
            "output": shard_output_path(parent_spec.output, r.idx),
            "priority": parent_spec.priority,
            # the config is the PARENT's verbatim: the @PG provenance
            # line derives from it, and the shard headers must be the
            # unsharded run's header byte-for-byte. Run-time overrides
            # (pinned mate_aware, range, grid, no per-shard index) ride
            # the shard metadata, which provenance never sees.
            "config": dict(parent_spec.config),
            "shard": {
                "parent": parent_spec.job_id,
                "idx": r.idx,
                "k": len(plan.ranges),
                "chunk_base": r.chunk_base,
                "n_chunks": r.n_chunks,
                "start": list(r.start) if r.start is not None else None,
                "key_lo": r.key_lo,
                "key_hi": r.key_hi,
                "first_read": r.first_read,
                # the planner's resolution of the parent's mate_aware
                # setting against the WHOLE FILE's first chunk — pinned
                # so a shard's own first chunk can never drift grouping
                "mate_aware": plan.mate_aware,
            },
        }
        if parent_spec.deadline_s is not None:
            d["deadline_s"] = parent_spec.deadline_s
        if parent_spec.chaos is not None:
            # each sub-job is a job: the schedule installs per child
            # with its own hit counters (a poison schedule poisons
            # every shard — and the quarantine/diagnosis machinery
            # names the shard that kept dying)
            d["chaos"] = parent_spec.chaos
        if parent_spec.trace is not None:
            # per-shard capture paths: K recorders on one file would
            # interleave into garbage
            d["trace"] = f"{parent_spec.trace}.s{r.idx:03d}"
        out.append(d)
    return out
