"""serve/ — the multi-job consensus service.

One warm process multiplexes many consensus jobs onto one device:

  client (``call --submit`` / serve.client) durably spools a job file
  into ``<spool>/inbox/`` → the daemon (``dut-serve`` / serve.service)
  ADMITS it into a bounded, durably-journaled queue (serve.queue;
  io.durable tmp+fsync+rename, so a killed daemon loses no accepted
  job) → a FAIR SCHEDULER (serve.scheduler: FIFO within priority
  class, per-job chunk budget) hands it to a WARM WORKER (serve.worker)
  that runs it as a ``stream_call_consensus`` slice, reusing the
  process's already-compiled kernels — the ~once-per-bucket-spec XLA
  compile is paid once for the daemon's lifetime instead of once per
  job.

Preemption is free by construction: a job yields the device only at a
chunk boundary, where the streaming executor's checkpoint/resume
contract (PR 1) already guarantees a later slice converges to the
byte-identical output. SIGTERM triggers graceful drain: finish the
in-flight chunk, checkpoint, journal the queue, exit 0; a restarted
daemon resumes both the queue and the interrupted job.

A FLEET is N daemons on one spool: every journal mutation is a flock'd
transaction, each job runs under exactly one daemon's durable LEASE
(fencing token + monotonic expiry, renewed per chunk commit and per
heartbeat), dead daemons' jobs are taken over and resumed from their
checkpoints, zombies are fenced off before they can write a byte, and
overload sheds by per-class policy with queue-wait / time-to-first-
chunk percentiles in ``metrics.json`` (see ARCHITECTURE.md "Fleet &
leases").

Attribute access is lazy (PEP 562): the CLIENT side
(``serve.client``/``serve.queue``, behind ``call --submit/--status/
--wait``) must stay importable without dragging in the executor stack
— and through it jax — on every submit or status poll; only the
daemon-side classes (``ConsensusService``) pay that import.
"""

_LAZY = {
    "ConsensusService": "duplexumiconsensusreads_tpu.serve.service",
    "FairScheduler": "duplexumiconsensusreads_tpu.serve.scheduler",
    "JobSpec": "duplexumiconsensusreads_tpu.serve.job",
    "SpoolQueue": "duplexumiconsensusreads_tpu.serve.queue",
    "job_params": "duplexumiconsensusreads_tpu.serve.job",
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod), name)
