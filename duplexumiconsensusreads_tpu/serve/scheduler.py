"""Fair scheduling over the journaled queue.

The policy is deliberately small enough to state in full:

  * strict priority CLASSES: a queued job of a lower priority value
    always runs before any higher value (0 is the most urgent);
  * FIFO WITHIN a class, keyed on the admission sequence number the
    journal assigned;
  * per-job CHUNK BUDGET: a running job yields the device after
    ``chunk_budget`` fresh chunks — but only when another job is
    actually waiting (yielding to an empty queue is pure overhead) —
    and re-enters its class at the BACK, so a jumbo job interleaves
    with small ones instead of starving them. Preemption happens at a
    chunk boundary, where the streaming executor's checkpoint/resume
    contract makes the yield free (the next slice recomputes nothing);
  * ADMISSION SHEDDING per class: each priority class can carry a
    queue-depth bound, and a submission that would exceed its class's
    bound is rejected at admission with an explicit journaled reason —
    overload degrades by policy (urgent classes keep their budgeted
    room), never by an unbounded queue quietly absorbing everything.

Pure functions over the journal's ``jobs`` dict: no state of its own,
so every daemon of a fleet — or a restarted daemon — schedules exactly
as any other would from the same journal.
"""

from __future__ import annotations


def parse_class_depths(spec: str) -> dict[int, int]:
    """``"0=8,1=4"`` → {0: 8, 1: 4}: per-priority-class queued-depth
    bounds for ``dut-serve --class-depth``. Raises ValueError naming
    the offending entry."""
    out: dict[int, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        cls, eq, depth = part.partition("=")
        try:
            if not eq:
                raise ValueError
            c, d = int(cls), int(depth)
            if c < 0 or d < 1:
                raise ValueError
        except ValueError:
            raise ValueError(
                f"bad class-depth entry {part!r} (want CLASS=DEPTH with "
                f"CLASS >= 0 and DEPTH >= 1, e.g. '0=8,1=4')"
            ) from None
        out[c] = d
    return out


class FairScheduler:
    def __init__(
        self, chunk_budget: int = 0,
        class_depths: dict[int, int] | None = None,
    ):
        """``chunk_budget`` = fresh chunks a slice may commit before
        yielding (0 = run to completion; no preemption).
        ``class_depths`` maps priority class -> max QUEUED jobs of that
        class (absent classes are unbounded up to the queue's global
        open-jobs cap)."""
        if chunk_budget < 0:
            raise ValueError(f"chunk_budget must be >= 0 (got {chunk_budget})")
        self.chunk_budget = chunk_budget
        self.class_depths = dict(class_depths or {})

    def shed_reason(self, jobs: dict, priority: int) -> str | None:
        """Admission-control verdict for one incoming submission: a
        reason string when its priority class is at its queued-depth
        bound (the queue journals it as an explicit shed), else None.
        Pure over the journal, so every daemon sheds identically."""
        bound = self.class_depths.get(int(priority))
        if bound is None:
            return None
        depth = sum(
            1 for e in jobs.values()
            if e.get("state") == "queued"
            and int(e.get("priority", 1)) == int(priority)
        )
        if depth >= bound:
            return (
                f"shed: priority class {priority} queue depth "
                f"{depth}/{bound} (admission control)"
            )
        return None

    @staticmethod
    def pick(jobs: dict, now: float | None = None) -> str | None:
        """The next job to run: min (priority, seq) over queued jobs.

        ``now`` (a ``time.monotonic()`` reading — deadlines live only
        in the monotonic domain) makes the pick deadline-aware: a
        queued job whose admission-stamped ``deadline_m`` has passed is
        never claimed. The service's deadline sweep journals such jobs
        terminal ``expired`` in the same pass; refusing here too closes
        the fleet race where another daemon picks between this
        daemon's sweep and its claim."""
        best = None
        best_key = None
        for job_id, entry in jobs.items():
            if entry.get("state") != "queued":
                continue
            if now is not None:
                deadline_m = entry.get("deadline_m")
                if deadline_m is not None and float(deadline_m) <= now:
                    continue  # expired: the sweep owns its terminal move
            key = (int(entry.get("priority", 1)), int(entry.get("seq", 0)))
            if best_key is None or key < best_key:
                best, best_key = job_id, key
        return best

    @staticmethod
    def others_waiting(jobs: dict, job_id: str) -> bool:
        """Would any queued job actually run if ``job_id`` yielded now?
        Only a waiter of EQUAL-OR-MORE-URGENT class counts: yielding to
        a strictly less urgent job would just re-pick the yielder
        (strict priority), burning a preempt/resume cycle for nothing —
        and with an empty queue the running job keeps the device."""
        mine = int(jobs.get(job_id, {}).get("priority", 1))
        return any(
            jid != job_id
            and entry.get("state") == "queued"
            and int(entry.get("priority", 1)) <= mine
            for jid, entry in jobs.items()
        )
