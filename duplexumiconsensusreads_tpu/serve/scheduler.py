"""Fair scheduling over the journaled queue.

The policy is deliberately small enough to state in full:

  * strict priority CLASSES: a queued job of a lower priority value
    always runs before any higher value (0 is the most urgent);
  * FIFO WITHIN a class, keyed on the admission sequence number the
    journal assigned;
  * per-job CHUNK BUDGET: a running job yields the device after
    ``chunk_budget`` fresh chunks — but only when another job is
    actually waiting (yielding to an empty queue is pure overhead) —
    and re-enters its class at the BACK, so a jumbo job interleaves
    with small ones instead of starving them. Preemption happens at a
    chunk boundary, where the streaming executor's checkpoint/resume
    contract makes the yield free (the next slice recomputes nothing).

Pure functions over the journal's ``jobs`` dict: no state of its own,
so a restarted daemon schedules exactly as the dead one would have.
"""

from __future__ import annotations


class FairScheduler:
    def __init__(self, chunk_budget: int = 0):
        """``chunk_budget`` = fresh chunks a slice may commit before
        yielding (0 = run to completion; no preemption)."""
        if chunk_budget < 0:
            raise ValueError(f"chunk_budget must be >= 0 (got {chunk_budget})")
        self.chunk_budget = chunk_budget

    @staticmethod
    def pick(jobs: dict) -> str | None:
        """The next job to run: min (priority, seq) over queued jobs."""
        best = None
        best_key = None
        for job_id, entry in jobs.items():
            if entry.get("state") != "queued":
                continue
            key = (int(entry.get("priority", 1)), int(entry.get("seq", 0)))
            if best_key is None or key < best_key:
                best, best_key = job_id, key
        return best

    @staticmethod
    def others_waiting(jobs: dict, job_id: str) -> bool:
        """Would any queued job actually run if ``job_id`` yielded now?
        Only a waiter of EQUAL-OR-MORE-URGENT class counts: yielding to
        a strictly less urgent job would just re-pick the yielder
        (strict priority), burning a preempt/resume cycle for nothing —
        and with an empty queue the running job keeps the device."""
        mine = int(jobs.get(job_id, {}).get("priority", 1))
        return any(
            jid != job_id
            and entry.get("state") == "queued"
            and int(entry.get("priority", 1)) <= mine
            for jid, entry in jobs.items()
        )
