"""Lease-store backends: WHERE lease authority comes from.

The journal's lease protocol (claim / renew / fence / reclaim, see
serve/queue.py) is backend-agnostic — what varies across deployments is
the pair of primitives the protocol leans on:

  * the CLOCK the ``*_m`` journal stamps (``admitted_m``,
    ``deadline_m``, ``expires_m``, ``progress_m``, ``claimed_m``) are
    taken on, and
  * the LIVENESS oracle that lets one daemon declare another dead.

``local`` (:class:`LocalLeaseStore`) is the historical single-host
contract, byte-for-byte: stamps are the machine-wide CLOCK_MONOTONIC
(``time.monotonic()``), and a lease owner is provably dead when its
recorded pid no longer exists on this host (``os.kill(pid, 0)``).
Cheap and exact — and meaningless the moment two hosts share a spool:
pids collide across hosts and each host's monotonic clock starts at an
arbitrary boot-relative zero.

``sharedfs`` (:class:`SharedFsLeaseStore`) is the cross-host contract
for a spool on a shared filesystem. Two substitutions:

  CLOCK — every store instance calibrates its host-local monotonic
  clock against the SPOOL FILESYSTEM's timestamp domain once at
  startup (write a probe file, stat it, remember
  ``fs_delta = st_mtime - monotonic()``), and :meth:`now` returns
  ``monotonic() + fs_delta`` ever after. Stamps from different hosts
  then live in one shared domain — the PR-14 ``epoch_m`` alignment
  trick, applied to the journal itself — so a cross-host
  ``expires_m <= now`` comparison is well-defined no matter which
  host's arbitrary monotonic epoch produced either side. The delta is
  frozen at init: a wall-clock step on the filesystem server after
  calibration skews hosts calibrated before/after against each other,
  which widens (never corrupts) takeover latency — the fencing token
  keeps every verdict safe regardless (see below).

  LIVENESS — pid probes are replaced by durable per-daemon heartbeat
  documents (``hosts/<daemon_id>.json``: host id, a per-process
  ``boot`` nonce, a ``stamp_m`` in the shared clock domain). Takeover
  triggers on translated lease EXPIRY (the primary path — a dead
  daemon stops renewing), on a ``boot`` nonce mismatch (the restarted-
  daemon case: same host id, new process — reclaim instantly instead
  of waiting out the lease), or on heartbeat staleness past the
  owner-declared ``stale_s`` (the backstop for a lease carrying a
  garbage far-future expiry). ``os.kill`` never crosses a host
  boundary; dutlint rule "host-locality" pins pid-liveness idioms to
  this module's local backend.

Neither backend is the AUTHORITY for exactly-once — that is always the
per-job fencing token, bumped in the same durable transaction as every
claim and checked at every durable commit. A wrong liveness verdict
(either direction) costs at most duplicated compute or takeover
latency; it can never corrupt an output. That token-over-pid argument
is what makes the liveness substitution safe to ship.

The backend choice is pinned per spool in a ``store.json`` marker so a
mixed fleet cannot happen: the first daemon writes the marker
(``resolve_store(..., pin=True)``) and every later daemon or client
either inherits it or fails loudly on an explicit mismatch.

This module must stay importable without jax (the client's poll path
constructs a store per SpoolQueue).
"""

from __future__ import annotations

import json
import os
import socket
import time
import uuid

from duplexumiconsensusreads_tpu.io.durable import unique_tmp, write_durable

# the per-spool backend pin (see resolve_store)
STORE_MARKER = "store.json"
STORE_KINDS = ("local", "sharedfs")

# durable heartbeat documents live here, one per daemon
HB_DIRNAME = "hosts"

# a sharedfs daemon's heartbeat is declared stale after this many lease
# lengths without a fresh stamp_m — the reclaim ladder's BACKSTOP, not
# its trigger: a dead daemon's leases expire after one lease_s, well
# before its heartbeat goes stale, so staleness only decides for leases
# whose expiry stamp cannot be trusted
HB_STALE_FACTOR = 2.0

# synthetic-host knobs for multi-host tests/benches on one machine:
# distinct host identities and skewed monotonic epochs without needing
# two kernels (the calibration must cancel the skew exactly)
HOST_ID_ENV = "DUT_HOST_ID"
EPOCH_SKEW_ENV = "DUT_HOST_EPOCH_SKEW"

_HOST = socket.gethostname()


def _pid_alive(pid: int) -> bool:
    """Local-host pid probe (the ``local`` backend's liveness oracle).
    Only meaningful for pids of THIS host — which is exactly why it
    lives here and why dutlint's host-locality rule keeps it here."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except (OSError, OverflowError):
        return True  # exists but not ours (EPERM), or unprobeable: assume alive
    return True


class LeaseStore:
    """One spool's clock + liveness contract. Subclasses implement the
    primitives; serve/queue.py and serve/service.py call only this
    surface, never ``time.monotonic()``/``os.kill`` directly (the
    host-locality lint pins that)."""

    kind = "abstract"

    # ------------------------------------------------------------ clock

    def now(self) -> float:
        """Current time in the spool's shared stamp domain — the domain
        of every ``*_m`` journal stamp and service-capture epoch."""
        raise NotImplementedError

    # ------------------------------------------------------- lease docs

    def lease_doc(self, owner: str, lease_s: float) -> dict:
        """The journal lease entry a fresh claim writes."""
        raise NotImplementedError

    def claim_rec(self, owner: str, token: int) -> dict:
        """One bounded lease_history record (quarantine diagnosis)."""
        raise NotImplementedError

    def reclaim_reason(
        self, lease, now: float, is_live=None, hosts=None
    ) -> str | None:
        """Why this lease no longer protects its job — ``"no-lease"`` /
        ``"expired"`` / ``"dead-owner"`` / ``"restarted"`` — or None
        while it still holds. ``is_live`` is the in-process daemon
        registry (local backend only); ``hosts`` a heartbeat snapshot
        from :meth:`observe` (sharedfs only)."""
        raise NotImplementedError

    # -------------------------------------------------------- liveness

    def pid_alive(self, pid: int) -> bool:
        """Is a pid embedded in spool litter (``*.tmp.<pid>.<tid>``
        staging names) possibly alive? Cross-host backends must answer
        True (pids from other hosts are unprobeable — never reap)."""
        return True

    def attach(self, daemon_id: str, lease_s: float) -> None:
        """Bind a daemon identity to this store (daemon side only;
        clients never attach). Backends with heartbeat documents write
        the first one here."""

    def beat(self) -> None:
        """Refresh this daemon's liveness evidence (fault site
        ``serve.hb`` at the caller). No-op for backends whose liveness
        is kernel-derived."""

    def observe(self) -> dict:
        """Snapshot of the fleet's heartbeat documents
        ``{daemon_id: doc}`` (fault site ``serve.store`` at the
        caller). Empty for backends without documents."""
        return {}

    def capture_epoch(self) -> float | None:
        """``epoch_m`` override for this daemon's service capture: the
        capture's clock domain must match the journal stamps so the
        fleet stitcher can align N daemons' captures. None = keep the
        recorder's own monotonic t0 (single-host domain)."""
        return None


class LocalLeaseStore(LeaseStore):
    """Single-host semantics, unchanged: CLOCK_MONOTONIC stamps,
    pid-liveness, flock + kernel as the only fleet substrate."""

    kind = "local"

    def now(self) -> float:
        return time.monotonic()

    def lease_doc(self, owner: str, lease_s: float) -> dict:
        return {
            "owner": owner,
            "pid": os.getpid(),
            "host": _HOST,
            "expires_m": round(self.now() + lease_s, 3),
        }

    def claim_rec(self, owner: str, token: int) -> dict:
        return {
            "owner": owner, "pid": os.getpid(), "token": token,
            "claimed_m": round(self.now(), 3),
        }

    def reclaim_reason(
        self, lease, now: float, is_live=None, hosts=None
    ) -> str | None:
        if lease is None:
            return "no-lease"
        if float(lease.get("expires_m", 0)) <= now:
            return "expired"
        if lease.get("host") == _HOST:
            pid = int(lease.get("pid", -1))
            if not _pid_alive(pid):
                return "dead-owner"
            if (
                pid == os.getpid()
                and is_live is not None
                and not is_live(lease.get("owner"))
            ):
                return "dead-owner"
        return None

    def pid_alive(self, pid: int) -> bool:
        return _pid_alive(pid)


class SharedFsLeaseStore(LeaseStore):
    """Cross-host semantics for a spool on a shared filesystem: stamps
    in the filesystem's timestamp domain, liveness from durable
    heartbeat documents, takeover by translated expiry — never by pid.

    ``host_id``/``epoch_skew`` come from the constructor, the
    ``DUT_HOST_ID``/``DUT_HOST_EPOCH_SKEW`` environment (subprocess
    multi-host tests), or default to the real hostname / zero skew.
    ``epoch_skew`` shifts this instance's view of its own monotonic
    clock — a synthetic stand-in for "a different host booted at a
    different time"; the probe calibration cancels it exactly
    (``now() = probe_mtime + monotonic_elapsed_since_probe``), which
    the clock-matrix tests pin as a regression guard."""

    kind = "sharedfs"

    def __init__(
        self, root: str, host_id: str | None = None,
        epoch_skew: float | None = None,
    ):
        self.root = root
        self.host_id = (
            host_id if host_id is not None
            else os.environ.get(HOST_ID_ENV) or _HOST
        )
        if epoch_skew is None:
            epoch_skew = float(os.environ.get(EPOCH_SKEW_ENV) or 0.0)
        self._skew = float(epoch_skew)
        # per-process nonce: a restarted daemon (same host id, same
        # daemon id on the command line) is a DIFFERENT boot, and its
        # heartbeat document proves it — the instant-takeover case
        self.boot = uuid.uuid4().hex[:12]
        self.hb_dir = os.path.join(root, HB_DIRNAME)
        os.makedirs(self.hb_dir, exist_ok=True)
        self._daemon_id: str | None = None
        self._lease_s = 0.0
        self._stale_s = 0.0
        self._beats = 0
        self._fs_delta = self._calibrate()

    # ---------------------------------------------------- fs-clock sync

    def _host_clock(self) -> float:
        return time.monotonic() + self._skew

    def _calibrate(self) -> float:
        """One probe write against the spool filesystem: the frozen
        offset from this host's (skewed) monotonic clock to the
        filesystem timestamp domain. Error is one write-to-stat
        latency; precision is the filesystem's timestamp granularity —
        both far under any sane lease_s."""
        probe = os.path.join(self.hb_dir, f".probe.{self.boot}")
        fd = os.open(probe, os.O_CREAT | os.O_WRONLY | os.O_TRUNC, 0o644)
        try:
            os.write(fd, b"probe")
            sampled = self._host_clock()
        finally:
            os.close(fd)
        try:
            mtime = os.stat(probe).st_mtime
        finally:
            try:
                os.remove(probe)
            except OSError:
                pass  # best-effort; a stray probe is inert litter
        return mtime - sampled

    def now(self) -> float:
        return self._host_clock() + self._fs_delta

    # ------------------------------------------------------- lease docs

    def lease_doc(self, owner: str, lease_s: float) -> dict:
        # no pid, no kernel hostname: the lease carries exactly the
        # identity the reclaim ladder can verify from across a host
        # boundary — owner + boot nonce + translated expiry
        return {
            "owner": owner,
            "host": self.host_id,
            "boot": self.boot,
            "expires_m": round(self.now() + lease_s, 3),
        }

    def claim_rec(self, owner: str, token: int) -> dict:
        return {
            "owner": owner, "boot": self.boot, "token": token,
            "claimed_m": round(self.now(), 3),
        }

    def reclaim_reason(
        self, lease, now: float, is_live=None, hosts=None
    ) -> str | None:
        # ``is_live`` (the in-process registry) is deliberately ignored:
        # across hosts the only evidence is the journal + heartbeat
        # documents, and the token makes any verdict safe
        if lease is None:
            return "no-lease"
        if float(lease.get("expires_m", 0)) <= now:
            return "expired"
        hb = (hosts or {}).get(lease.get("owner"))
        if isinstance(hb, dict):
            boot = lease.get("boot")
            if boot is not None and hb.get("boot") != boot:
                return "restarted"
            try:
                stamp = float(hb.get("stamp_m", now))
                stale_s = float(hb.get("stale_s", 0.0))
            except (TypeError, ValueError):
                return None  # garbage heartbeat: expiry still covers
            if stale_s > 0 and now - stamp > stale_s:
                return "dead-owner"
        return None

    # -------------------------------------------------------- heartbeat

    def attach(self, daemon_id: str, lease_s: float) -> None:
        self._daemon_id = daemon_id
        self._lease_s = float(lease_s)
        self._stale_s = HB_STALE_FACTOR * float(lease_s)
        self.beat()

    def beat(self) -> None:
        if self._daemon_id is None:
            return  # client-side store: no identity, no document
        self._beats += 1
        doc = {
            "daemon_id": self._daemon_id,
            "host_id": self.host_id,
            "boot": self.boot,
            "stamp_m": round(self.now(), 3),
            "beats": self._beats,
            "lease_s": self._lease_s,
            "stale_s": self._stale_s,
            "fs_delta": round(self._fs_delta, 6),
        }
        path = os.path.join(self.hb_dir, self._daemon_id + ".json")
        write_durable(
            path,
            json.dumps(doc, sort_keys=True).encode(),
            tmp=unique_tmp(path),
        )

    def observe(self) -> dict:
        out: dict[str, dict] = {}
        try:
            names = os.listdir(self.hb_dir)
        except OSError:
            return out
        for n in sorted(names):
            if not n.endswith(".json"):
                continue
            try:
                with open(os.path.join(self.hb_dir, n)) as f:
                    doc = json.load(f)
            except (OSError, ValueError):
                continue  # torn/racing document: skip, expiry covers
            if isinstance(doc, dict) and isinstance(
                doc.get("daemon_id"), str
            ):
                out[doc["daemon_id"]] = doc
        return out

    def capture_epoch(self) -> float | None:
        return self.now()


def resolve_store(
    root: str, kind: str | None = None, pin: bool = False,
    host_id: str | None = None, epoch_skew: float | None = None,
) -> LeaseStore:
    """Resolve one spool's lease-store backend against its
    ``store.json`` marker. ``kind`` None inherits the marker (default
    ``local`` on an unmarked spool); an explicit ``kind`` that
    contradicts an existing marker is a hard error — a mixed-backend
    fleet would compare stamps across clock domains. ``pin=True``
    (the daemon path — clients never pin) durably writes the marker on
    an unmarked spool, implicit-default ``local`` included, so the
    SECOND daemon cannot accidentally diverge from the first."""
    os.makedirs(root, exist_ok=True)
    marker = os.path.join(root, STORE_MARKER)
    on_disk = None
    try:
        with open(marker) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("store"), str):
            on_disk = doc["store"]
    except (OSError, ValueError):
        pass  # absent/torn marker: the next pinning daemon rewrites it
    if kind is None:
        kind = on_disk or "local"
    elif on_disk is not None and kind != on_disk:
        raise ValueError(
            f"spool {root!r} is pinned to store {on_disk!r} but "
            f"--store {kind} was requested: one spool, one clock/"
            f"liveness domain (remove the spool or drop the flag)"
        )
    if kind not in STORE_KINDS:
        raise ValueError(
            f"unknown lease store {kind!r} (expected one of {STORE_KINDS})"
        )
    if pin and on_disk is None:
        write_durable(
            marker,
            json.dumps(
                {"version": 1, "store": kind}, sort_keys=True
            ).encode(),
            tmp=unique_tmp(marker),
        )
    if kind == "sharedfs":
        return SharedFsLeaseStore(root, host_id=host_id,
                                  epoch_skew=epoch_skew)
    return LocalLeaseStore()
