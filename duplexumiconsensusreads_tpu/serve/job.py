"""Job specs: what a client submits and what a worker slice runs.

A job is (input BAM → output path) plus a CONFIG dict holding the same
keys as the streaming ``call`` flags (underscored). The spec is
validated twice — at submission (a typo fails in the client, not hours
later in the daemon) and again at admission (the daemon never trusts
spooled bytes) — with the same function, so the two ends cannot drift.

Only STREAMING params are accepted: the service's whole preemption and
crash-recovery story is phrased over chunk boundaries, so a job must
run on the streaming executor (``chunk_reads > 0``). Whole-file-only
features (--ref-projected, --umi-whitelist) are rejected at submission.
"""

from __future__ import annotations

import dataclasses

from duplexumiconsensusreads_tpu.runtime import knobs
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

# config keys a job may carry, with the SAME defaults as cli/main.py's
# opt() resolution — a job submitted with an empty config must run the
# identical workload as a bare `call --chunk-reads` would. Derived from
# the knob registry (runtime/knobs.py): the job_config surface IS the
# declaration, so job.py and main.py cannot drift. Table order is the
# canonical @PG CL flag order serve_provenance emits.
CONFIG_DEFAULTS = knobs.job_config_defaults()

_CHOICES = knobs.job_choice_map()


@dataclasses.dataclass(frozen=True)
class JobSpec:
    """One validated consensus job."""

    job_id: str
    input: str
    output: str
    priority: int = 1  # lower = more urgent; FIFO within a class
    config: dict = dataclasses.field(default_factory=dict)
    chaos: str | None = None  # per-job fault schedule (faults.FaultPlan)
    trace: str | None = None  # per-job run-capture path
    # optional wall budget (seconds from ADMISSION, not from first
    # chunk): admission stamps a monotonic expiry on the journal entry,
    # the scheduler refuses to claim past it and a running slice aborts
    # at its next checkpoint boundary — terminal state "expired", with
    # the partial checkpoint preserved so a re-submitted job resumes
    deadline_s: float | None = None
    # scatter-gather sharding (serve/shard/): a PARENT job asks to be
    # split into K range sub-jobs (`shards`), or into sub-jobs of
    # roughly this many compressed input bytes each (`shard_bytes`);
    # the planner fans the sub-jobs across the fleet and a merge stage
    # splices their outputs into one BAM byte-identical to the same job
    # run unsharded. Mutually exclusive with each other and with
    # `shard` below.
    shards: int | None = None
    shard_bytes: int | None = None
    # planner-written SUB-JOB metadata (never client-set): the child's
    # half-open range on the parent's whole-file chunk grid — see
    # serve/shard/plan.py for the field contract
    shard: dict | None = None

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        return {k: v for k, v in d.items() if v not in (None, {})}


def validate_spec(d: dict) -> JobSpec:
    """Dict (from a client call or a spooled JSON file) → JobSpec.
    Raises ValueError naming the offending field; never half-accepts."""
    if not isinstance(d, dict):
        raise ValueError("job spec must be a JSON object")
    allowed_top = {"job_id", "input", "output", "priority", "config",
                   "chaos", "trace", "deadline_s", "shards",
                   "shard_bytes", "shard"}
    unknown = set(d) - allowed_top
    if unknown:
        raise ValueError(f"unknown job fields: {sorted(unknown)}")
    for field in ("job_id", "input", "output"):
        v = d.get(field)
        if not isinstance(v, str) or not v:
            raise ValueError(f"job {field!r} must be a non-empty string")
    priority = d.get("priority", 1)
    if not isinstance(priority, int) or isinstance(priority, bool) or priority < 0:
        raise ValueError(f"job priority must be an int >= 0 (got {priority!r})")
    config = d.get("config", {})
    if not isinstance(config, dict):
        raise ValueError("job config must be an object")
    unknown = set(config) - set(CONFIG_DEFAULTS)
    if unknown:
        raise ValueError(
            f"unknown job config keys: {sorted(unknown)} "
            f"(allowed: {sorted(CONFIG_DEFAULTS)})"
        )
    merged = {**CONFIG_DEFAULTS, **config}
    for key, allowed in _CHOICES.items():
        if merged[key] not in allowed:
            raise ValueError(
                f"invalid config {key} value {merged[key]!r} "
                f"(allowed: {sorted(allowed)})"
            )
    if not isinstance(merged["chunk_reads"], int) or merged["chunk_reads"] < 1:
        raise ValueError(
            "jobs run on the streaming executor: config chunk_reads "
            f"must be an int >= 1 (got {merged['chunk_reads']!r})"
        )
    for key in knobs.job_min_int_keys():
        if not isinstance(merged[key], int) or merged[key] < 1:
            raise ValueError(f"config {key} must be an int >= 1")
    mesh = merged["mesh"]
    if mesh != "auto" and (
        not isinstance(mesh, int) or isinstance(mesh, bool) or mesh < 1
    ):
        # the job's mesh size (devices its slices shard over): "auto" =
        # the daemon's device pool; an int is validated against the
        # pool only at slice time (submission hosts may not see the
        # daemon's devices)
        raise ValueError(
            f"config mesh must be 'auto' or an int >= 1 (got {mesh!r})"
        )
    ladder = _normalized_ladder(merged)  # raises ValueError on a bad value
    if isinstance(ladder, tuple) and ladder[-1] != merged["capacity"]:
        # an explicit ladder's top rung REPLACES the capacity in the
        # executor — but serve_provenance excludes bucket_ladder from
        # the @PG CL (tuner overrides must not change job bytes), so a
        # mismatched top rung would make the recorded '--capacity' a
        # lie and break the reproduce-from-provenance contract. Refuse
        # at submission like every other config error.
        raise ValueError(
            f"config bucket_ladder top rung {ladder[-1]} must equal "
            f"config capacity {merged['capacity']} (the top rung IS the "
            f"job's capacity; set them consistently)"
        )
    from duplexumiconsensusreads_tpu.live.tail import parse_finalize_on

    try:
        # structured domain (eof | idle:<seconds> | marker), hand-
        # validated like mesh/bucket_ladder; the parser is shared with
        # the CLI so both surfaces reject exactly the same strings
        parse_finalize_on(merged["finalize_on"])
    except ValueError as e:
        raise ValueError(f"config finalize_on: {e}")
    lp = merged["live_poll_s"]
    if not isinstance(lp, (int, float)) or isinstance(lp, bool) or lp <= 0:
        raise ValueError(
            f"config live_poll_s must be a number > 0 (got {lp!r})"
        )
    sc = merged["snapshot_chunks"]
    if not isinstance(sc, int) or isinstance(sc, bool) or sc < 0:
        raise ValueError(
            f"config snapshot_chunks must be an int >= 0 (got {sc!r})"
        )
    chaos = d.get("chaos")
    if chaos is not None:
        if not isinstance(chaos, str) or not chaos:
            raise ValueError("job chaos must be a non-empty schedule string")
        from duplexumiconsensusreads_tpu.runtime.faults import FaultPlan

        FaultPlan.parse(chaos)  # reject a bad schedule at submission
    trace = d.get("trace")
    if trace is not None and (not isinstance(trace, str) or not trace):
        raise ValueError("job trace must be a non-empty path")
    deadline_s = d.get("deadline_s")
    if deadline_s is not None:
        if (
            not isinstance(deadline_s, (int, float))
            or isinstance(deadline_s, bool)
            or deadline_s <= 0
        ):
            raise ValueError(
                f"job deadline_s must be a number > 0 (got {deadline_s!r})"
            )
        deadline_s = float(deadline_s)
    shards = d.get("shards")
    if shards is not None and (
        not isinstance(shards, int) or isinstance(shards, bool) or shards < 1
    ):
        raise ValueError(f"job shards must be an int >= 1 (got {shards!r})")
    shard_bytes = d.get("shard_bytes")
    if shard_bytes is not None and (
        not isinstance(shard_bytes, int)
        or isinstance(shard_bytes, bool)
        or shard_bytes < 1
    ):
        raise ValueError(
            f"job shard_bytes must be an int >= 1 (got {shard_bytes!r})"
        )
    if shards is not None and shard_bytes is not None:
        raise ValueError("job shards and shard_bytes are mutually exclusive")
    shard = d.get("shard")
    if shard is not None:
        if shards is not None or shard_bytes is not None:
            raise ValueError(
                "a shard sub-job cannot itself request sharding"
            )
        if not isinstance(shard, dict):
            raise ValueError("job shard metadata must be an object")
        missing = {"parent", "idx", "k", "chunk_base"} - set(shard)
        if missing:
            raise ValueError(
                f"job shard metadata lacks required keys: {sorted(missing)}"
            )
    if merged["follow"] and (
        shards is not None or shard_bytes is not None or shard is not None
    ):
        # shard planning walks the finished file to place byte-range
        # cut points; a growing input has no finished length to plan
        # over and no random access for sub-jobs to seek into
        raise ValueError(
            "a follow job cannot be sharded: byte-range planning "
            "requires the finished input file"
        )
    return JobSpec(
        job_id=d["job_id"],
        input=d["input"],
        output=d["output"],
        priority=priority,
        config=config,
        chaos=chaos,
        trace=trace,
        deadline_s=deadline_s,
        shards=shards,
        shard_bytes=shard_bytes,
        shard=shard,
    )


def _normalized_ladder(c: dict):
    """The config's bucket_ladder, NORMALISED ("auto" | "off" | rung
    tuple) — one helper shared by validation, job_params and
    spec_signature so a cosmetic variant ("AUTO", " 256 , 1024 ") can
    never bypass the verdict store or split the compile signature.
    Raises ValueError naming the field on an invalid value."""
    ladder = c["bucket_ladder"]
    if not isinstance(ladder, (str, list, tuple)):
        raise ValueError(
            f"config bucket_ladder must be 'auto', 'off' or a rung list "
            f"(got {ladder!r})"
        )
    from duplexumiconsensusreads_tpu.tuning import normalize_bucket_ladder

    try:
        return normalize_bucket_ladder(ladder)
    except ValueError as e:
        raise ValueError(f"config bucket_ladder: {e}")


def job_params(spec: JobSpec):
    """(GroupingParams, ConsensusParams, stream kwargs) for one job —
    the serve-side mirror of cli/main.py's flag resolution."""
    c = {**CONFIG_DEFAULTS, **spec.config}
    gp = GroupingParams(
        strategy=c["grouping"],
        max_hamming=c["max_hamming"],
        count_ratio=c["count_ratio"],
        paired=(c["mode"] == "duplex"),
    )
    cp = ConsensusParams(
        mode="duplex" if c["mode"] == "duplex" else "single_strand",
        min_reads=c["min_reads"],
        min_duplex_reads=c["min_duplex_reads"],
        max_qual=c["max_qual"],
        max_input_qual=c["max_input_qual"],
        min_input_qual=c["min_input_qual"],
        error_model=None if c["error_model"] == "none" else c["error_model"],
    )
    kwargs = dict(
        capacity=c["capacity"],
        chunk_reads=c["chunk_reads"],
        max_inflight=c["max_inflight"],
        drain_workers=c["drain_workers"],
        packed=c["packed"],
        prefetch_depth=c["prefetch_depth"],
        ingest_overlap=c["ingest_overlap"],
        bucket_ladder=_normalized_ladder(c),
        # "auto" -> None: the worker resolves the mesh within its own
        # device pool (run_slice pops this key; it is not a
        # stream_call_consensus kwarg)
        mesh=None if c["mesh"] == "auto" else int(c["mesh"]),
        mate_aware=c["mate_aware"],
        max_reads=c["max_reads"],
        per_base_tags=bool(c["per_base_tags"]),
        read_group=str(c["read_group_id"]),
        write_index=bool(c["write_index"]),
        follow=bool(c["follow"]),
        finalize_on=str(c["finalize_on"]),
        live_poll_s=float(c["live_poll_s"]),
        snapshot_chunks=int(c["snapshot_chunks"]),
    )
    return gp, cp, kwargs


def serve_provenance(config: dict) -> str:
    """The deterministic @PG CL line for a service-run output: the
    equivalent ``duplexumi call`` CONFIG flags in canonical order, with
    no paths and no daemon argv. A one-shot output's CL records the
    invoking command line — but a service job's bytes must be a pure
    function of (input bytes, config): the same job must produce
    identical bytes whichever daemon (or daemon restart) finishes it,
    and two equal jobs writing different paths must still compare
    byte-identical. That is exactly the property the soak and
    crash-convergence tests are phrased over, so paths and argv are
    deliberately excluded."""
    parts = ["duplexumi", "call"]
    merged = {**CONFIG_DEFAULTS, **config}
    for key, default in CONFIG_DEFAULTS.items():  # canonical flag order
        val = merged[key]
        if val == default:
            continue
        if "provenance" not in knobs.KNOBS[key].surfaces:
            # surface membership is DECLARED, not hand-rolled here:
            # mesh / ingest_overlap / bucket_ladder carry their
            # why-excluded rationale on their KNOB_TABLE rows in
            # runtime/knobs.py, and the knob-taint rule holds this
            # loop to the declaration. (bucket_ladder is the only
            # list-capable config key, so every value below is a
            # scalar.)
            continue
        flag = "--" + key.replace("_", "-")
        if isinstance(val, bool):
            parts.append(flag)
        else:
            parts.extend([flag, str(val)])
    parts.append("[dut-serve]")
    return " ".join(parts)


def spec_signature(spec: JobSpec) -> str:
    """The job's COMPILE identity: the config subset that determines
    bucket geometry + pipeline spec (capacity, grouping strategy, mode,
    error model, per-base tags, and the bucket-ladder spec — each rung
    is its own dispatch-class capacity, so the ladder IS geometry). Two
    jobs sharing a signature share XLA programs, so the second is a
    compile-cache hit in the warm daemon — the amortisation the service
    exists to provide. "auto" jobs share the auto token: their resolved
    ladders come from the spool's verdict store, which maps one input
    profile to one ladder, so equal-profile jobs still share programs
    in practice."""
    c = {**CONFIG_DEFAULTS, **spec.config}
    try:
        ladder = _normalized_ladder(c)
    except ValueError:
        # a never-validated spec (direct construction): fall back to
        # the raw token — the signature must never raise
        ladder = c["bucket_ladder"]
    if isinstance(ladder, (list, tuple)):
        ladder = ",".join(str(x) for x in ladder)
    # mesh joins the compile identity: GSPMD partitions the same
    # program differently per device count, so jobs only share XLA
    # executables when their mesh agrees ("auto" jobs share the
    # daemon's resolved pool, hence the auto token)
    return "|".join(
        str(c[k])
        for k in ("capacity", "grouping", "mode", "error_model",
                  "per_base_tags")
    ) + f"|ladder={ladder}|mesh={c['mesh']}"
