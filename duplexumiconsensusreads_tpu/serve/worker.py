"""Warm-device worker: runs job slices on the long-lived process.

The worker is why the service exists: every ``stream_call_consensus``
invocation in a fresh process pays XLA compile + device warm-up +
executor setup (~11.6s on the r05 bench) before the first chunk moves.
Inside the daemon the jit cache is process-global and the persistent
compile cache (utils/compile_cache.py) is enabled once, so every job
after the first with the same bucket-spec signature starts hot — the
worker tracks exactly that as the compile-cache hit rate.

A SLICE is one bounded run of a job: ``stream_call_consensus`` with
``resume=True`` under the job's own checkpoint (the executor's default
``out + ".ckpt"``), preempted at a chunk boundary by raising
:class:`JobPreempted` from the executor's ``progress`` callback — which
fires on the main commit path right AFTER the chunk's checkpoint mark
is durable, so a preempted slice leaves exactly the state a resumed
slice needs and nothing else. Fault-site scoping: a job carrying a
``chaos`` schedule gets its own FaultPlan installed for its slices only
(counters live across the job's slices, not across jobs).

Thread model: this code runs on the daemon's ``dut-serve`` worker
thread — the ``serve-worker`` row of THREAD_ROLES in
``runtime/knobs.py``, which grants it all three effects (device,
durable, journal) because a slice IS a full streaming run plus its
lease bookkeeping. Job-config resolution is registry-driven too: the
defaults/choices the slices run under come from the same KNOB_TABLE
(via serve/job.py), so a knob edit lands here without touching this
file.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time

from duplexumiconsensusreads_tpu.runtime import faults
from duplexumiconsensusreads_tpu.serve.job import (
    JobSpec,
    job_params,
    serve_provenance,
    spec_signature,
)
from duplexumiconsensusreads_tpu.serve.queue import LEASE_DEFAULT_S, SpoolQueue


class JobPreempted(Exception):
    """A slice yielded the device at a chunk boundary (budget or
    drain). Not an error: the job goes back to the queue and a later
    slice resumes from the checkpoint."""

    def __init__(self, chunks_done: int, reason: str):
        super().__init__(f"preempted after {chunks_done} chunks ({reason})")
        self.chunks_done = chunks_done
        self.reason = reason


class JobDeadlineExceeded(Exception):
    """A slice crossed its job's monotonic deadline and aborted at the
    next checkpoint boundary — the same yield point preemption uses, so
    every committed chunk stays durable and byte-identical: a
    re-submitted job RESUMES the checkpoint, it never splices. The
    service journals the job terminal ``expired`` with this message."""

    def __init__(self, chunks_done: int, overdue_s: float):
        super().__init__(
            f"expired: deadline passed {overdue_s:.3f}s ago; slice "
            f"aborted at the chunk boundary after {chunks_done} committed "
            f"chunks (checkpoint preserved for resume)"
        )
        self.chunks_done = chunks_done
        self.overdue_s = overdue_s


@dataclasses.dataclass
class LeaseContext:
    """The slice's fleet identity: which lease it runs under and how to
    keep it alive. The worker turns this into the executor's
    ``commit_guard``: before EVERY durable chunk commit the fencing
    token is verified against the journal (site ``serve.fence``) and
    the lease deadline is pushed out (site ``serve.renew``) — so a
    healthy slice can never expire mid-run, and a zombie slice aborts
    via :class:`~..serve.queue.JobFenced` before splicing a byte.
    ``on_first_chunk`` (optional) fires once, right after the job's
    first fresh chunk of its first slice is durable — the service's
    time-to-first-chunk sample. ``on_chunk`` (optional) fires on EVERY
    chunk commit — the service's chunk-cadence sample, which derives
    the watchdog's default stall threshold. ``deadline_m`` is the
    job's admission-stamped expiry (None = no deadline): the commit
    path checks it right after each chunk's mark is durable and aborts
    the slice with :class:`JobDeadlineExceeded` when passed.
    ``now_fn`` supplies "now" in the SAME clock domain ``deadline_m``
    was stamped in — the spool's lease-store clock (the service wires
    ``store.now``); None falls back to the local monotonic clock, the
    single-host domain."""

    queue: SpoolQueue
    daemon_id: str
    token: int
    lease_s: float = LEASE_DEFAULT_S
    on_first_chunk: object = None
    on_chunk: object = None
    deadline_m: float | None = None
    now_fn: object = None


def fenced_renew(queue: SpoolQueue, job_id: str, daemon_id: str,
                 token: int, lease_s: float,
                 progress: dict | None = None) -> None:
    """THE fenced-renewal guard, shared by every stage that commits
    under a lease (the per-chunk commit guard here, the service's
    split/merge stages): one flock'd transaction — renew_lease verifies
    the token first (raising JobFenced through both ladders on a
    mismatch) and pushes the lease deadline out in the same journal
    write. The two nested retry ladders keep the fence check and the
    renewal persist individually targetable by chaos schedules
    (serve.fence / serve.renew) while transient faults at either site
    are absorbed. One definition on purpose: two copies of a
    fencing-critical idiom is how the chaos coverage and the behavior
    drift apart."""
    from duplexumiconsensusreads_tpu.runtime.stream import _io_retry

    _io_retry(
        "serve.fence",
        lambda: _io_retry(
            "serve.renew",
            lambda: queue.renew_lease(
                job_id, daemon_id, token, lease_s, progress=progress
            ),
            f"job {job_id} lease renewal",
        ),
        f"job {job_id} fence check",
    )


def verdict_key(spec) -> str:
    """Verdict-store key for a job spec: input identity x compile
    signature. A shard sub-job folds its range into the key — it
    profiles ITS range's group-size mix, which can legitimately differ
    per region, so sibling shards (and the whole-file job) must not
    collide on one verdict; a collision would pin a ladder tuned for a
    different region and break the store's same-key-same-value
    contract."""
    from duplexumiconsensusreads_tpu import tuning

    sig = spec_signature(spec)
    if spec.shard is not None:
        sig += (
            f"|shard={spec.shard.get('chunk_base')}"
            f":{spec.shard.get('key_lo')}"
            f":{spec.shard.get('key_hi')}"
        )
    return tuning.profile_key(spec.input, sig)


def _ckpt_done_count(out_path: str) -> int:
    """Chunks already durably committed for this output (the auto
    checkpoint's ``done`` map — a gap-free prefix by the frontier
    contract). 0 when there is no usable manifest; the count only
    separates resumed commits from fresh ones for budget accounting, so
    a discarded-at-run-time manifest costing a slightly early yield is
    harmless."""
    try:
        with open(out_path + ".ckpt") as f:
            manifest = json.load(f)
        done = manifest.get("done")
        return len(done) if isinstance(done, dict) else 0
    except (OSError, ValueError):
        return 0


class WarmWorker:
    """Executes slices; owns the warm-compile bookkeeping."""

    def __init__(self, n_devices: int | None = None, devices=None):
        self.n_devices = n_devices
        # local-device index subset this worker's slices run on
        # (dut-serve --devices pinning); None = all local devices
        self.devices = list(devices) if devices else None
        self._lock = threading.Lock()
        self._warm_specs: set[str] = set()
        self._job_plans: dict[str, faults.FaultPlan] = {}
        self.n_spec_hits = 0
        self.n_spec_misses = 0
        self.n_slices = 0
        # tuner verdict traffic (tuning/store.py): auto-ladder slices
        # that reused a stored verdict vs fresh resolutions persisted.
        # The store rides a worker ATTRIBUTE (set by the service), not a
        # run_slice kwarg: tests and the bench wrap run_slice with
        # old-signature shims, and a new keyword would break every shim
        self.verdict_store = None
        # service-set ledger hook, callable(job_id, attrs): emits a
        # tuner_verdict event into the service capture whenever a slice
        # reuses or persists a verdict — the registry promises the
        # fleet's shape decisions are auditable from the capture. An
        # attribute for the same reason verdict_store is one.
        self.on_verdict = None
        self.n_verdict_hits = 0
        self.n_verdict_puts = 0

    def compile_hit_rate(self) -> float:
        total = self.n_spec_hits + self.n_spec_misses
        return self.n_spec_hits / total if total else 0.0

    def note_job_start(self, spec: JobSpec, first_slice: bool) -> bool:
        """Record the job's compile identity; True = warm (its bucket
        spec was already compiled by an earlier job this daemon ran)."""
        sig = spec_signature(spec)
        with self._lock:
            hit = sig in self._warm_specs
            if first_slice:
                if hit:
                    self.n_spec_hits += 1
                else:
                    self.n_spec_misses += 1
        return hit

    def _emit_verdict(self, job_id: str, attrs: dict) -> None:
        """Ledger a verdict decision through the service's hook (no-op
        for direct-worker callers like tests and the bench shims)."""
        hook = self.on_verdict
        if hook is not None:
            hook(job_id, attrs)

    def _note_verdict(
        self, verdicts, vkey, reused, ladder, rows_real, rows_pad,
        job_id: str = "",
    ) -> None:
        """Persist a fresh auto run's resolved ladder into the spool
        store (no-op on reuse — the stored verdict already matches by
        construction). Best-effort: a store write failure must never
        fail the job whose bytes are already durable."""
        if verdicts is None or vkey is None or reused or not ladder:
            return
        try:
            from duplexumiconsensusreads_tpu import tuning

            rungs = tuning.validate_ladder(ladder)
        except ValueError:
            # a resolved single-rung "ladder" can be an off-ladder
            # capacity (non-pow2 / below MIN_RUNG) that validate_ladder
            # would refuse on reuse — persisting it would make every
            # later slice hit, fail validation, re-profile and re-put
            # the store forever; skip instead (re-profiling is cheap)
            return
        entry = {
            "ladder": [int(r) for r in rungs],
            "source": "run",
        }
        if rows_pad:
            entry["fill_factor"] = round(rows_real / rows_pad, 4)
        try:
            verdicts.put(vkey, entry)
        except OSError:
            return
        with self._lock:
            self.n_verdict_puts += 1
        self._emit_verdict(job_id, dict(entry))

    def _job_plan(self, spec: JobSpec) -> faults.FaultPlan | None:
        if not spec.chaos:
            return None
        with self._lock:
            plan = self._job_plans.get(spec.job_id)
            if plan is None:
                plan = faults.FaultPlan.parse(spec.chaos)
                self._job_plans[spec.job_id] = plan
        return plan

    def run_slice(
        self,
        spec: JobSpec,
        budget: int,
        should_yield,
        drain_event: threading.Event,
        lease: LeaseContext | None = None,
    ):
        """One slice of ``spec``. Returns ("done", report_dict) or
        ("preempted", chunks_done, reason, slice_bytes) where
        ``slice_bytes`` is {"h2d_bytes", "d2h_bytes", "reads"} as of
        the slice's last committed chunk — the byte ledger's
        serving-side view, TRAFFIC-attributed (chunks in flight at a
        preemption are re-transferred and re-counted by the resuming
        slice; see the comment at the snapshot below). The service
        accumulates it per job so metrics.json can answer
        bytes-per-read per job even across preemptions. Job errors
        propagate, and a lost lease surfaces as
        :class:`~..serve.queue.JobFenced` (a BaseException — nothing
        here may absorb it).

        ``budget`` bounds FRESH chunks this slice commits (0 = no
        bound); ``should_yield()`` is consulted before yielding so the
        budget only preempts when another job is actually waiting.
        ``lease`` (fleet mode) wires the fencing/renewal commit guard —
        see :class:`LeaseContext`."""
        from duplexumiconsensusreads_tpu.runtime.stream import (
            stream_call_consensus,
        )

        gp, cp, kwargs = job_params(spec)
        # job-level mesh (config "mesh": device count, "auto" = None):
        # an explicit job mesh wins over the daemon's default count;
        # both resolve within the daemon's pinned device subset, and an
        # over-subscription (mesh 8 on a 2-device daemon) fails the job
        # with the executor's clear requested-vs-have error. Mesh size
        # never changes job bytes (the mesh byte-identity contract), so
        # serve_provenance deliberately excludes it from the @PG CL.
        job_mesh = kwargs.pop("mesh", None)
        if spec.shard is not None:
            # shard sub-job (serve/shard/): run the planner's range on
            # the parent's whole-file chunk grid. The overrides ride
            # kwargs only — config (and so the @PG provenance header)
            # stays the parent's verbatim, which the merge's
            # header-identity invariant depends on.
            sh = spec.shard
            start = sh.get("start")
            kwargs["input_range"] = (
                tuple(start) if start is not None else None,
                sh.get("key_lo"), sh.get("key_hi"),
            )
            kwargs["chunk_base"] = int(sh.get("chunk_base", 0))
            kwargs["first_read"] = sh.get("first_read")
            # the planner resolved mate_aware against the parent's
            # first chunk; per-shard auto-resolution must not drift it
            kwargs["mate_aware"] = sh.get("mate_aware", kwargs["mate_aware"])
            # the merged output gets the one index; per-shard BAIs
            # would be thrown away
            kwargs["write_index"] = False
        # tuner verdict consult (self.verdict_store — tuning/store.py,
        # wired by the service): an "auto" bucket-ladder job takes the
        # spool's stored verdict for its input profile when one exists
        # (skipping the profile pass and pinning the fleet-wide shape);
        # a fresh auto resolution is persisted after the slice below.
        # Shape-only: output bytes are identical with or without a
        # verdict, which is why the override rides kwargs and never
        # touches spec.config (the @PG provenance header derives from
        # config and must not depend on tuner state).
        verdicts = self.verdict_store
        vkey = None
        verdict_reused = False
        if verdicts is not None and kwargs.get("bucket_ladder") == "auto":
            from duplexumiconsensusreads_tpu import tuning

            vkey = verdict_key(spec)
            hit = verdicts.get(vkey)
            if hit and hit.get("ladder"):
                try:
                    rungs = tuning.validate_ladder(hit["ladder"])
                    if rungs[-1] != kwargs["capacity"]:
                        # a well-formed but wrong-capacity entry (hand
                        # edit, torn write that parses) would silently
                        # change the run's effective capacity — and the
                        # escape thresholds with it — while the @PG CL
                        # still claims the configured one
                        raise ValueError("verdict top rung != capacity")
                    kwargs["bucket_ladder"] = rungs
                    verdict_reused = True
                    with self._lock:
                        self.n_verdict_hits += 1
                    self._emit_verdict(spec.job_id, {
                        "ladder": [int(r) for r in kwargs["bucket_ladder"]],
                        "source": "store",
                    })
                except ValueError:
                    pass  # corrupt stored verdict: re-profile honestly
        # resolved-ladder snapshot for verdict persistence: a preempted
        # slice raises out of the executor, so the progress callback
        # mirrors the live report fields (same idiom as slice_bytes)
        ladder_seen: dict = {"ladder": None, "rows_real": 0, "rows_pad": 0}
        n_resumed = _ckpt_done_count(spec.output)
        commits = [0]
        # wire bytes this slice moved, as of its last committed chunk:
        # a preempted slice raises out of the executor, so the report
        # object is unreachable afterwards — the progress callback
        # snapshots its live counters instead. TRAFFIC-attributed, not
        # commit-attributed: at the snapshot the pipeline already
        # dispatched/fetched up to max-inflight later chunks whose
        # commits the preemption abandons, and the resuming slice
        # recomputes (re-transfers, re-counts) them — so job totals
        # measure bytes daemons actually moved for the job, counting a
        # re-transfer each time it crosses the wire, exactly like
        # retried dispatches in the run capture's byte ledger.
        # device_flops / device_s ride the same snapshot: per-job MFU
        # must survive preemption for the same traffic-attributed reason
        slice_bytes = {"h2d_bytes": 0, "d2h_bytes": 0, "reads": 0,
                       "device_flops": 0.0, "device_s": 0.0}
        # follow-mode observability: a follow job can run for hours
        # between slice boundaries, so its snapshot/emission counters
        # piggyback on the per-chunk fenced renewal instead of waiting
        # for a preemption requeue. The progress callback (post-commit,
        # chunk k) fills this; the commit guard (pre-commit, chunk k+1)
        # ships it — one chunk of lag, zero extra journal transactions
        live_progress: dict = {}
        live_run = bool(kwargs.get("follow") or kwargs.get("snapshot_chunks"))

        commit_guard = None
        if lease is not None:

            def commit_guard(_k):
                # pre-commit, on the executor main thread: the shared
                # fenced-renewal guard — one transaction per chunk
                fenced_renew(
                    lease.queue, spec.job_id, lease.daemon_id,
                    lease.token, lease.lease_s,
                    progress=dict(live_progress) if live_progress else None,
                )

        def progress(_k, _rep):
            # called on the executor's main thread inside _commit, after
            # chunk _k's checkpoint mark is durable — the one point where
            # yielding is free by the resume contract
            commits[0] += 1
            slice_bytes["h2d_bytes"] = _rep.bytes_h2d
            slice_bytes["d2h_bytes"] = _rep.bytes_d2h
            slice_bytes["reads"] = _rep.n_records
            slice_bytes["device_flops"] = _rep.device_flops
            slice_bytes["device_s"] = _rep.device_seconds
            ladder_seen["ladder"] = list(_rep.bucket_ladder)
            ladder_seen["rows_real"] = _rep.n_rows_real
            ladder_seen["rows_pad"] = _rep.n_rows_padded
            if live_run:
                live_progress["snapshot_seq"] = int(_rep.snapshot_seq)
                live_progress["reads_emitted"] = int(_rep.n_consensus)
            fresh = commits[0] - n_resumed
            if lease is not None and lease.on_chunk is not None:
                lease.on_chunk()
            if (
                fresh == 1
                and lease is not None
                and lease.on_first_chunk is not None
            ):
                lease.on_first_chunk()
            if lease is not None and lease.deadline_m is not None:
                # deadline abort rides the preemption contract: this
                # chunk's mark is already durable, nothing later is —
                # the strongest point to stop without wasting the
                # prefix or splicing a byte. "now" comes from the
                # lease's clock (the spool's stamp domain); bare
                # monotonic is only the single-host fallback
                now_fn = lease.now_fn or time.monotonic
                overdue = now_fn() - lease.deadline_m
                if overdue >= 0:
                    raise JobDeadlineExceeded(commits[0], overdue)
            if drain_event.is_set():
                raise JobPreempted(commits[0], "drain")
            if budget > 0 and fresh >= budget and should_yield():
                raise JobPreempted(commits[0], "budget")

        plan = self._job_plan(spec)
        prev_plan = faults.get_active()
        if plan is not None:
            # per-job fault-site scoping: the job's schedule is active
            # only while its slice runs; the service-level plan (chaos
            # tests, DUT_FAULTS) is restored afterwards
            faults.install(plan)
        try:
            with self._lock:
                self.n_slices += 1
            rep = stream_call_consensus(
                spec.input,
                spec.output,
                gp,
                cp,
                n_devices=job_mesh or self.n_devices,
                devices=self.devices,
                resume=True,
                progress=progress,
                commit_guard=commit_guard,
                trace_path=spec.trace,
                # canonical config-derived @PG CL: the job's bytes must
                # not depend on the daemon's argv or restart history
                provenance_cl=serve_provenance(spec.config),
                **kwargs,
            )
        except JobPreempted as p:
            # a preempted slice dispatched real work: its programs are
            # compiled, so later jobs of this signature start warm
            with self._lock:
                self._warm_specs.add(spec_signature(spec))
            self._note_verdict(
                verdicts, vkey, verdict_reused, ladder_seen["ladder"],
                ladder_seen["rows_real"], ladder_seen["rows_pad"],
                job_id=spec.job_id,
            )
            return ("preempted", p.chunks_done, p.reason, dict(slice_bytes))
        except JobDeadlineExceeded:
            # same warm logic: the slice ran real chunks before the
            # deadline abort; the service owns the terminal transition
            with self._lock:
                self._warm_specs.add(spec_signature(spec))
            raise
        finally:
            if plan is not None:
                faults.install(prev_plan)
        # success only: a slice that failed before dispatch (bad input
        # path, not-a-BAM) compiled nothing, and marking its signature
        # warm would inflate the compile-hit metric the bench reports
        with self._lock:
            self._warm_specs.add(spec_signature(spec))
        self._note_verdict(
            verdicts, vkey, verdict_reused, list(rep.bucket_ladder),
            rep.n_rows_real, rep.n_rows_padded,
            job_id=spec.job_id,
        )
        result = json.loads(rep.to_json())
        result["output"] = os.path.abspath(spec.output)
        return ("done", result)
