"""``dut-serve`` — the long-running consensus daemon.

    dut-serve SPOOL_DIR [--chunk-budget N] [--max-queue N] [--workers N]
                        [--lease S] [--class-depth SPEC] [--heartbeat S]
                        [--deadline S] [--watchdog S] [--max-crashes N]
                        [--min-free-mb MB] [--no-trace] [--once] ...

Runs a :class:`~duplexumiconsensusreads_tpu.serve.service.ConsensusService`
over SPOOL_DIR until SIGTERM/SIGINT, which trigger graceful drain:
every running job yields at its next chunk boundary and is re-journaled
as queued, the admission queue is already durable, and the process
exits 0. Restarting the daemon on the same spool resumes the queue and
every interrupted job (checkpoint resume skips their committed chunks).

FLEET MODE is just more daemons: start ``dut-serve SPOOL_DIR`` N times
(same host under the default ``local`` lease store; N *hosts* sharing
the spool over a shared filesystem with ``--store sharedfs``) and they
coordinate through the journal's lease/claim protocol — each job runs
under exactly one daemon's lease, a SIGKILLed daemon's jobs are taken
over (``local``: immediately when its pid is provably dead, within
``--lease`` seconds otherwise; ``sharedfs``: by translated lease
expiry or a restarted/stale heartbeat document, never by pid) and
resumed from their last durable checkpoint mark, and a zombie daemon
is fenced off by its stale token before it can splice a byte.

Submit work with ``duplexumi call IN -o OUT --submit --spool SPOOL_DIR``
and follow it with ``call --status/--wait`` (or read
``SPOOL_DIR/metrics.json`` for the live service snapshot, including
per-priority-class queue-wait / time-to-first-chunk percentiles).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dut-serve",
        description="multi-job consensus service over a spool directory",
    )
    p.add_argument("spool", help="spool directory (created if missing)")
    p.add_argument(
        "--chunk-budget", type=int, default=8,
        help="fresh chunks a job may commit before yielding the device "
        "to a waiting job (0 = run each job to completion; default 8). "
        "Preemption happens at chunk boundaries, where checkpoint/resume "
        "makes the yield free",
    )
    p.add_argument(
        "--max-queue", type=int, default=64,
        help="bounded admission: open (queued+running) jobs beyond this "
        "are rejected with a journaled reason (default 64)",
    )
    p.add_argument(
        "--workers", type=int, default=1,
        help="warm worker threads draining the queue (default 1: one "
        "device, one job at a time — the scheduler owns arbitration)",
    )
    p.add_argument(
        "--devices", default=None,
        help="device topology per job slice: a COUNT ('4' — the first "
        "N local devices; the legacy meaning of a bare integer) or a "
        "comma-separated local-device INDEX subset ('0,1' — pin this "
        "daemon to those chips, so a fleet on one host can partition "
        "the devices and the scatter-gather fan-out drives daemons "
        "that each own real silicon; a SINGLE index needs the "
        "trailing comma: '2,' pins chip 2, where '2' means a count "
        "of two). Default: all local devices",
    )
    p.add_argument(
        "--lease", type=float, default=None, metavar="SECONDS",
        help="job lease length for fleet coordination (default 30). "
        "Healthy daemons renew every chunk commit and every heartbeat; "
        "a daemon silent this long forfeits its running jobs to the "
        "other daemons on the spool",
    )
    p.add_argument(
        "--class-depth", default=None, metavar="SPEC",
        help="per-priority-class admission bounds as CLASS=DEPTH pairs "
        "(e.g. '0=8,1=4'): submissions over their class's queued depth "
        "are shed with a journaled reason instead of queued (classes "
        "not listed are bounded only by --max-queue)",
    )
    p.add_argument(
        "--daemon-id", default=None,
        help="fleet identity for lease ownership (default: a unique "
        "pid-derived id; override only for debugging)",
    )
    p.add_argument(
        "--store", default=None, choices=("local", "sharedfs"),
        help="lease-store backend for the spool: 'local' (flock + "
        "pid-liveness + machine monotonic clock — one host per spool) "
        "or 'sharedfs' (filesystem-calibrated shared clock + durable "
        "heartbeat documents — N hosts may share the spool; takeover "
        "by translated lease expiry, never by pid). Default: inherit "
        "the spool's store.json pin, 'local' on a fresh spool. The "
        "first daemon durably pins the choice; a later conflicting "
        "--store fails loudly",
    )
    p.add_argument(
        "--deadline", type=float, default=0.0, metavar="SECONDS",
        help="default job deadline from admission (0 = none; a job's "
        "own deadline_s wins). Overdue queued jobs journal terminal "
        "'expired'; a running job aborts at its next checkpoint "
        "boundary with the committed prefix preserved for resume",
    )
    p.add_argument(
        "--watchdog", type=float, default=None, metavar="SECONDS",
        help="stuck-run watchdog: abort-requeue a running job whose "
        "current chunk made no durable progress for this long (0 "
        "disables; default: derived from the observed chunk-commit "
        "p95 once enough chunks have been seen)",
    )
    p.add_argument(
        "--max-crashes", type=int, default=3, metavar="N",
        help="quarantine bound: a job whose runs abort uncleanly "
        "(daemon death takeover, watchdog) this many times is "
        "journaled terminal 'quarantined' with a diagnosis bundle "
        "instead of re-entering the queue (default 3)",
    )
    p.add_argument(
        "--min-free-mb", type=int, default=64, metavar="MB",
        help="disk low-water mark: shed new submissions when the spool "
        "filesystem has less than this free, after a grace GC of "
        "terminal jobs' shard/checkpoint litter (0 disables; "
        "default 64)",
    )
    p.add_argument(
        "--poll", type=float, default=0.25, metavar="SECONDS",
        help="inbox poll interval when idle (default 0.25)",
    )
    p.add_argument(
        "--heartbeat", type=float, default=10.0, metavar="SECONDS",
        help="service heartbeat period: stderr line + capture event + "
        "metrics.json snapshot (0 disables; default 10)",
    )
    p.add_argument(
        "--trace", default=None, metavar="TRACE_JSONL",
        help="service capture path (default: "
        "SPOOL/service.<daemon_id>.trace.jsonl — per-daemon on purpose: "
        "fleet members must not rotate each other's live captures, and "
        "tools/fleet_report.py stitches all of a spool's captures)",
    )
    p.add_argument(
        "--no-trace", action="store_true",
        help="disable the service capture entirely",
    )
    p.add_argument(
        "--once", action="store_true",
        help="drain until the queue, inbox and workers are idle, then "
        "exit (batch mode; the default is to serve until SIGTERM)",
    )
    return p


def parse_devices(value: str | None) -> tuple[int | None, list[int] | None]:
    """``--devices`` → (n_devices, device_indices): a bare integer
    keeps the legacy COUNT meaning; anything with a comma is an INDEX
    subset (duplicates/negatives refused), so a single-chip pin is the
    one-element list ``'2,'`` — a bare '2' cannot be both, and the
    count reading wins for compatibility (the --help text and the
    count error below both name the trailing-comma form so a mis-typed
    single index is discoverable). One helper so the CLI and tests
    cannot drift on the syntax."""
    if value is None:
        return None, None
    parts = [p.strip() for p in str(value).split(",")]
    try:
        nums = [int(p) for p in parts if p != ""]
    except ValueError:
        raise ValueError(
            f"--devices must be a count or a comma-separated index "
            f"list (got {value!r})"
        )
    if not nums:
        raise ValueError("--devices got an empty list")
    if len(parts) == 1:
        if nums[0] < 1:
            raise ValueError(
                f"--devices count must be >= 1 (got {nums[0]}; to PIN "
                f"a single device by index, use the one-element list "
                f"form '{nums[0]},')"
            )
        return nums[0], None
    if any(n < 0 for n in nums) or len(set(nums)) != len(nums):
        raise ValueError(
            f"--devices index list must be unique non-negative indices "
            f"(got {value!r})"
        )
    return None, nums


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.chunk_budget < 0:
        raise SystemExit(f"--chunk-budget must be >= 0 (got {args.chunk_budget})")
    if args.workers < 1:
        raise SystemExit(f"--workers must be >= 1 (got {args.workers})")
    if args.lease is not None and args.lease <= 0:
        raise SystemExit(f"--lease must be > 0 (got {args.lease})")
    if args.deadline < 0:
        raise SystemExit(f"--deadline must be >= 0 (got {args.deadline})")
    if args.watchdog is not None and args.watchdog < 0:
        raise SystemExit(f"--watchdog must be >= 0 (got {args.watchdog})")
    if args.max_crashes < 1:
        raise SystemExit(f"--max-crashes must be >= 1 (got {args.max_crashes})")
    if args.min_free_mb < 0:
        raise SystemExit(f"--min-free-mb must be >= 0 (got {args.min_free_mb})")
    class_depths = None
    if args.class_depth:
        from duplexumiconsensusreads_tpu.serve.scheduler import (
            parse_class_depths,
        )

        try:
            class_depths = parse_class_depths(args.class_depth)
        except ValueError as e:
            raise SystemExit(f"--class-depth: {e}")
    from duplexumiconsensusreads_tpu.serve.queue import LEASE_DEFAULT_S
    from duplexumiconsensusreads_tpu.serve.service import ConsensusService

    try:
        n_devices, device_indices = parse_devices(args.devices)
    except ValueError as e:
        raise SystemExit(str(e))
    os.makedirs(args.spool, exist_ok=True)
    try:
        service = ConsensusService(
            args.spool,
            chunk_budget=args.chunk_budget,
            max_queue=args.max_queue,
            workers=args.workers,
            poll_s=args.poll,
            heartbeat_s=args.heartbeat,
            trace_path=None if args.no_trace else args.trace,
            n_devices=n_devices,
            device_indices=device_indices,
            lease_s=(
                args.lease if args.lease is not None else LEASE_DEFAULT_S
            ),
            class_depths=class_depths,
            daemon_id=args.daemon_id,
            default_deadline_s=args.deadline,
            watchdog_s=args.watchdog,
            max_crashes=args.max_crashes,
            min_free_bytes=args.min_free_mb << 20,
            store=args.store,
        )
    except ValueError as e:
        # e.g. --store conflicting with the spool's store.json pin
        raise SystemExit(str(e))
    if service.trace_path is None and not args.no_trace:
        # the default capture path is PER-DAEMON (it needs the resolved
        # daemon id, which the service generates): a shared default
        # would have every new fleet member rotate the previous one's
        # LIVE capture to .prev — with three daemons, the rotation
        # destroys a capture. The fleet stitcher discovers every
        # service*.trace.jsonl on the spool.
        service.trace_path = os.path.join(
            args.spool, f"service.{service.daemon_id}.trace.jsonl"
        )

    def _drain(signum, _frame):
        print(
            f"[dut-serve] signal {signum}: graceful drain — finishing "
            f"in-flight chunks, journaling the queue",
            file=sys.stderr,
            flush=True,
        )
        service.request_drain()

    signal.signal(signal.SIGTERM, _drain)
    signal.signal(signal.SIGINT, _drain)
    print(
        f"[dut-serve] serving {os.path.abspath(args.spool)} "
        f"(workers={args.workers}, chunk_budget={args.chunk_budget}, "
        f"max_queue={args.max_queue}, lease_s={service.lease_s}, "
        f"store={service.store.kind}, "
        f"daemon_id={service.daemon_id}, pid={os.getpid()})",
        file=sys.stderr,
        flush=True,
    )
    snap = service.run(once=args.once)
    print(f"[dut-serve] drained: {snap}", file=sys.stderr, flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
