"""The consensus service: admission → fair scheduling → warm slices.

One ``ConsensusService`` owns a spool directory and drains it through a
small pool of warm workers. All queue/journal mutations are serialized
under one lock; the slices themselves (the expensive part) run outside
it. The service is equally usable in-process (tests, the bench's
``serve_n_jobs`` leg) and as the ``dut-serve`` daemon (serve.daemon).

Graceful drain: :meth:`request_drain` (the daemon's SIGTERM handler)
makes every running slice yield at its next chunk boundary — the
executor checkpoints the committed prefix, the job is re-journaled as
queued, and :meth:`run` returns cleanly. A restarted service resumes
both the queue and every interrupted job from exactly that state; the
chaos-kill path (InjectedKill anywhere in admission or a slice) leaves
the same journal a real SIGKILL would, which the recovery test pins.

Telemetry: with ``trace_path`` set the service records a
kind="service" capture (telemetry/trace.py): job lifecycle events on
``job-<id>`` lanes, service heartbeats carrying the queue snapshot, and
— because the recorder is installed as the process-global hook — every
fault/retry/durable event the switchboard emits while jobs run.
``tools/serve_report.py`` summarises it; ``tools/check_trace.py``
validates it.
"""

from __future__ import annotations

import os
import threading
import time

from duplexumiconsensusreads_tpu.io.durable import write_durable
from duplexumiconsensusreads_tpu.runtime.stream import _io_retry
from duplexumiconsensusreads_tpu.serve.job import validate_spec
from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue
from duplexumiconsensusreads_tpu.serve.scheduler import FairScheduler
from duplexumiconsensusreads_tpu.serve.worker import WarmWorker
from duplexumiconsensusreads_tpu.telemetry import trace as telemetry
from duplexumiconsensusreads_tpu.telemetry.trace import Heartbeat, TraceRecorder


class ConsensusService:
    def __init__(
        self,
        spool_dir: str,
        chunk_budget: int = 8,
        max_queue: int = 64,
        workers: int = 1,
        poll_s: float = 0.25,
        heartbeat_s: float = 0.0,
        trace_path: str | None = None,
        n_devices: int | None = None,
    ):
        if workers < 1:
            raise ValueError(f"workers must be >= 1 (got {workers})")
        if poll_s <= 0:
            raise ValueError(f"poll_s must be > 0 (got {poll_s})")
        self.queue = SpoolQueue(spool_dir, max_queue=max_queue)
        self.sched = FairScheduler(chunk_budget=chunk_budget)
        self.worker = WarmWorker(n_devices=n_devices)
        self.workers = workers
        self.poll_s = poll_s
        self.heartbeat_s = heartbeat_s
        self.trace_path = trace_path
        self._lock = threading.Lock()
        self._drain = threading.Event()
        self._fatal: BaseException | None = None
        self._n_running = 0
        self._t0 = time.monotonic()
        self._job_seconds: dict[str, dict] = {}
        self.counters = {
            "jobs_accepted": 0, "jobs_rejected": 0, "jobs_done": 0,
            "jobs_failed": 0, "preemptions": 0, "jobs_recovered": 0,
        }
        self._tr: TraceRecorder | None = None

    # ------------------------------------------------------------ control

    def request_drain(self) -> None:
        """Graceful shutdown: running slices yield at the next chunk
        boundary and are re-journaled as queued; :meth:`run` returns.
        Safe from signal handlers and any thread."""
        self._drain.set()

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            snap = {
                "elapsed_s": round(time.monotonic() - self._t0, 1),
                "queue_depth": self.queue.queue_depth(),
                "jobs_inflight": self._n_running,
                **self.counters,
                "slices": self.worker.n_slices,
                "compile_hit_rate": round(self.worker.compile_hit_rate(), 3),
            }
        return snap

    def _write_metrics(self, snap: dict) -> None:
        """The live snapshot file beside the journal: queue depth, jobs
        in flight, per-job phase seconds, compile-cache hit rate —
        readable by ops/`call --status` while the daemon runs."""
        import json

        with self._lock:
            payload = json.dumps(
                {**snap, "job_seconds": self._job_seconds}, sort_keys=True
            ).encode()
        try:
            write_durable(os.path.join(self.queue.root, "metrics.json"), payload)
        except OSError:
            pass  # the snapshot is observability, never worth a crash

    def _beat_stats(self) -> dict:
        snap = self.stats()
        self._write_metrics(snap)
        return snap

    # ----------------------------------------------------------- running

    def run(self, once: bool = False) -> dict:
        """Drain the spool. ``once=True`` returns when the queue, inbox
        and workers are all idle (tests, the bench leg); ``once=False``
        runs until :meth:`request_drain`. Returns the final stats
        snapshot; re-raises a fatal error (injected kill, journal I/O
        beyond retries) after the surviving workers stop."""
        from duplexumiconsensusreads_tpu.utils.compile_cache import (
            enable_compile_cache,
        )

        enable_compile_cache(per_host_cpu=True)
        tr = None
        hooked = False
        if self.trace_path:
            tr = TraceRecorder(self.trace_path, kind="service")
            self._tr = tr
            if telemetry.get_active() is None:
                # the service capture doubles as the switchboard sink:
                # fault/retry/durable events from admissions AND from
                # untraced job slices land here
                telemetry.install(tr)
                hooked = True
        hb = None
        if self.heartbeat_s and self.heartbeat_s > 0:
            hb = Heartbeat(self.heartbeat_s, self._beat_stats, recorder=tr)
            hb.start()
        recovered = self.queue.recover_running()
        with self._lock:
            self.counters["jobs_recovered"] += len(recovered)
        for job_id in recovered:
            if tr is not None:
                tr.event(
                    "resume", job=job_id, lane=f"job-{job_id}",
                    decision="requeued_running",
                )
        threads = [
            threading.Thread(
                target=self._worker_loop, args=(once,),
                name=f"dut-serve_{i}", daemon=True,
            )
            for i in range(self.workers)
        ]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            if hb is not None:
                hb.stop()
            snap = self._beat_stats()
            if tr is not None:
                if self._fatal is None:
                    # clean shutdown only: a fatal exit must leave a
                    # summary-less capture, the post-mortem marker
                    tr.write_summary(counters=snap)
                if hooked:
                    telemetry.uninstall()
                tr.close()
                self._tr = None
        if self._fatal is not None:
            raise self._fatal
        return snap

    def run_until_idle(self) -> dict:
        return self.run(once=True)

    # ------------------------------------------------------- worker loop

    def _accept_pending_locked(self) -> None:
        """Admit every spooled submission (caller holds the lock)."""
        tr = self._tr
        for job_id in self.queue.pending_submissions():
            spec, reason = self.queue.accept_one(job_id)
            if spec is not None:
                self.counters["jobs_accepted"] += 1
                if tr is not None:
                    tr.event(
                        "job_accepted", job=spec.job_id,
                        lane=f"job-{spec.job_id}", priority=spec.priority,
                        seq=self.queue.jobs[spec.job_id]["seq"],
                        queue_depth=self.queue.queue_depth(),
                    )
            elif reason is not None:
                self.counters["jobs_rejected"] += 1
                if tr is not None:
                    tr.event(
                        "job_rejected", job=job_id, lane=f"job-{job_id}",
                        reason=reason[:200],
                    )

    def _idle_done(self, once: bool) -> bool:
        if not once:
            return False
        with self._lock:
            return (
                self.queue.queue_depth() == 0
                and self._n_running == 0
                and not self.queue.pending_submissions()
            )

    def _worker_loop(self, once: bool) -> None:
        try:
            while not self._drain.is_set():
                with self._lock:
                    self._accept_pending_locked()
                    job_id = self.sched.pick(self.queue.jobs)
                    if job_id is not None:
                        entry = self.queue.jobs[job_id]
                        # journaled spec, not a cached object: a daemon
                        # restarted onto an old journal must run exactly
                        # what admission durably recorded
                        spec = validate_spec(entry["spec"])
                        self.queue.mark_running(job_id)
                        first_slice = entry["slices"] == 1
                        self._n_running += 1
                if job_id is None:
                    if self._idle_done(once):
                        return
                    self._drain.wait(self.poll_s)
                    continue
                try:
                    self._run_one(spec, first_slice)
                finally:
                    with self._lock:
                        self._n_running -= 1
        except BaseException as e:  # noqa: BLE001 — modelled process death
            # an injected kill or a journal failure beyond the retry
            # ladder is the daemon dying: stop every worker, surface the
            # exception from run() with the journal exactly as durable
            # state left it (the recovery tests restart from there)
            with self._lock:
                if self._fatal is None:
                    self._fatal = e
            self._drain.set()

    def _run_one(self, spec, first_slice: bool) -> None:
        tr = self._tr
        job_id = spec.job_id
        lane = f"job-{job_id}"
        warm = self.worker.note_job_start(spec, first_slice)
        if tr is not None:
            with self._lock:
                n_slice = self.queue.jobs[job_id]["slices"]
            tr.event(
                "job_started", job=job_id, lane=lane, slice=n_slice,
                warm=warm, resumed=not first_slice,
            )

        def should_yield() -> bool:
            with self._lock:
                return self.sched.others_waiting(self.queue.jobs, job_id)

        t0 = time.monotonic()
        try:
            out = self.worker.run_slice(
                spec, self.sched.chunk_budget, should_yield, self._drain
            )
        except Exception as e:  # noqa: BLE001 — job-scoped failure
            with self._lock:
                self.queue.mark_failed(job_id, repr(e))
                self.counters["jobs_failed"] += 1
            if tr is not None:
                tr.event("job_failed", job=job_id, lane=lane,
                         error=repr(e)[:200])
            return
        wall = round(time.monotonic() - t0, 3)
        if out[0] == "done":
            _, result = out
            with self._lock:
                self.queue.mark_done(job_id, result)
                self.counters["jobs_done"] += 1
                self._job_seconds[job_id] = result.get("seconds", {})
            if tr is not None:
                tr.event(
                    "job_completed", job=job_id, lane=lane, wall_s=wall,
                    n_chunks=result.get("n_chunks", 0),
                    n_consensus=result.get("n_consensus", 0),
                    warm=warm, seconds=result.get("seconds", {}),
                )
        else:
            _, chunks_done, reason = out

            def _requeue():
                with self._lock:
                    self.queue.requeue(
                        job_id, chunks_done, back=(reason == "budget")
                    )

            # serve.preempt guards the preemption commit: a transient
            # fault re-runs the idempotent requeue; an injected kill
            # leaves the job journaled "running", which restart recovery
            # requeues — the same convergence a real crash gets
            _io_retry("serve.preempt", _requeue, f"job {job_id} requeue")
            with self._lock:
                self.counters["preemptions"] += 1
            if tr is not None:
                tr.event(
                    "job_preempted", job=job_id, lane=lane,
                    chunks_done=chunks_done, reason=reason, wall_s=wall,
                )
