"""The consensus service: admission → fair scheduling → warm slices.

One ``ConsensusService`` owns a spool directory and drains it through a
small pool of warm workers — and N services (processes or instances)
can share ONE spool as a fleet: every queue/journal mutation is a
flock'd transaction (serve.queue), a job runs only under a durable
LEASE claimed in that journal, and every durable commit a slice makes
is fenced by the lease's token. Two daemons can therefore never run one
job at the same time, a daemon that dies mid-job is taken over (lease
expiry, or immediately when its pid is provably dead) with the next
slice resuming from the last durable checkpoint mark, and a zombie
daemon that wakes up after takeover aborts before splicing a byte.
In-process scheduling decisions stay serialized under one lock; the
slices themselves (the expensive part) run outside it. The service is
equally usable in-process (tests, the bench's ``serve_n_jobs`` /
``serve_fleet`` legs) and as the ``dut-serve`` daemon (serve.daemon).

Admission control: beyond the global open-jobs bound, each priority
class can carry a queued-depth bound (``class_depths``); submissions
over a bound are journaled as explicit shed-with-reason rejections
(``job_shed`` trace events, ``shed: ...`` reasons in ``--status``), and
per-class queue-wait / time-to-first-chunk percentiles land in
``metrics.json`` — overload degrades by policy, observably.

Defensive layer (deadlines / watchdog / quarantine / disk pressure):
jobs may carry a ``deadline_s`` (or inherit the daemon's default) —
admission stamps a monotonic expiry, the scheduler refuses expired
picks, a per-pass sweep journals overdue queued jobs terminal
``expired``, and a running slice aborts at its next checkpoint
boundary with the committed prefix preserved for a re-submitted
resume. A per-daemon WATCHDOG thread compares each running job's
durable-progress stamp (re-written on every chunk-commit lease
renewal, NOT by the heartbeat) against a stall threshold (explicit, or
derived from the observed chunk-commit p95) and abort-requeues wedged
runs through the lease/fence path. Every such unclean abort — watchdog
or dead-daemon takeover — bumps the job's ``crash_count``; at
``max_crashes`` the job is QUARANTINED terminally with a durable
diagnosis bundle instead of re-poisoning the fleet. Admission sheds
new jobs when the spool filesystem is below a low-water mark (after a
grace GC of terminal jobs' litter), and an ENOSPC inside a job fails
that job cleanly — durable reason, daemon alive.

Graceful drain: :meth:`request_drain` (the daemon's SIGTERM handler)
makes every running slice yield at its next chunk boundary — the
executor checkpoints the committed prefix, the job is re-journaled as
queued (lease released), and :meth:`run` returns cleanly. A restarted
service resumes both the queue and every interrupted job from exactly
that state; the chaos-kill path (InjectedKill anywhere in admission or
a slice) leaves the same journal a real SIGKILL would, which the
recovery tests pin.

Telemetry: with ``trace_path`` set the service records a
kind="service" capture (telemetry/trace.py): job lifecycle events on
``job-<id>`` lanes (now including ``job_shed``, ``job_fenced`` and
``lease_takeover``), service heartbeats carrying the queue snapshot,
and — because the recorder is installed as the process-global hook —
every fault/retry/durable event the switchboard emits while jobs run.
``tools/serve_report.py`` summarises it; ``tools/check_trace.py``
validates it.
"""

from __future__ import annotations

import errno
import os
import threading
import time
import uuid

from duplexumiconsensusreads_tpu.io.durable import unique_tmp, write_durable
from duplexumiconsensusreads_tpu.runtime.stream import _io_retry
from duplexumiconsensusreads_tpu.serve.job import validate_spec
from duplexumiconsensusreads_tpu.serve.queue import (
    DISK_LOW_WATER_BYTES,
    LEASE_DEFAULT_S,
    MAX_CRASHES_DEFAULT,
    JobFenced,
    SpoolQueue,
)
from duplexumiconsensusreads_tpu.serve.states import OPEN_STATES
from duplexumiconsensusreads_tpu.serve.store import LeaseStore, resolve_store
from duplexumiconsensusreads_tpu.serve.scheduler import FairScheduler
from duplexumiconsensusreads_tpu.serve.worker import (
    JobDeadlineExceeded,
    LeaseContext,
    WarmWorker,
)
from duplexumiconsensusreads_tpu.telemetry import trace as telemetry
from duplexumiconsensusreads_tpu.telemetry.device import (
    device_peak_flops,
    round_mfu,
)
from duplexumiconsensusreads_tpu.telemetry.report import _pctl
from duplexumiconsensusreads_tpu.telemetry.trace import Heartbeat, TraceRecorder

# Live daemons in THIS process, by daemon id. The lease liveness probe
# can ask the kernel whether another process's pid is alive, but an
# in-process fleet (tests, the bench's serve_fleet leg, embedded use)
# shares one pid — this registry is the equivalent probe for those:
# a lease whose owner registered here and then unwound (crash or clean
# exit both pass through run()'s finally) is reclaimable immediately.
_LIVE_LOCK = threading.Lock()
_LIVE_DAEMONS: set = set()


def _daemon_is_live(daemon_id: str) -> bool:
    with _LIVE_LOCK:
        return daemon_id in _LIVE_DAEMONS


# per-class latency sample caps: enough for honest p95s on a long-lived
# daemon without unbounded growth (oldest samples age out)
_LAT_SAMPLES_KEPT = 512

# stuck-run watchdog: with no explicit --watchdog the stall threshold
# derives from this daemon's OBSERVED chunk cadence — a run is declared
# stalled only when its current chunk has made no durable progress for
# WATCHDOG_P95_MULT x the p95 inter-commit interval (floored at
# WATCHDOG_MIN_S), and only once enough samples exist to know what
# "normal" looks like. Conservative by design: a watchdog that fires on
# a slow-but-alive chunk converts honest work into a fenced abort and a
# crash_count tick.
WATCHDOG_MIN_S = 10.0
WATCHDOG_P95_MULT = 20.0
WATCHDOG_MIN_SAMPLES = 8
_CHUNK_SAMPLES_KEPT = 256


class ConsensusService:
    def __init__(
        self,
        spool_dir: str,
        chunk_budget: int = 8,
        max_queue: int = 64,
        workers: int = 1,
        poll_s: float = 0.25,
        heartbeat_s: float = 0.0,
        trace_path: str | None = None,
        n_devices: int | None = None,
        device_indices: list[int] | None = None,
        lease_s: float = LEASE_DEFAULT_S,
        class_depths: dict | None = None,
        daemon_id: str | None = None,
        default_deadline_s: float = 0.0,
        watchdog_s: float | None = None,
        max_crashes: int = MAX_CRASHES_DEFAULT,
        min_free_bytes: int = DISK_LOW_WATER_BYTES,
        store: str | LeaseStore | None = None,
    ):
        """Defensive knobs: ``default_deadline_s`` (daemon-level job
        deadline, 0 = none; a job's own ``deadline_s`` wins),
        ``watchdog_s`` (stall threshold for the stuck-run watchdog —
        None = derive from observed chunk p95, 0 = disabled),
        ``max_crashes`` (unclean aborts before a job is quarantined),
        ``min_free_bytes`` (disk low-water mark below which admission
        sheds, 0 = no probe), ``store`` (the spool's lease-store
        backend — "local"/"sharedfs"/a LeaseStore instance; None
        inherits the spool's store.json pin, defaulting to local)."""
        if workers < 1:
            raise ValueError(f"workers must be >= 1 (got {workers})")
        if poll_s <= 0:
            raise ValueError(f"poll_s must be > 0 (got {poll_s})")
        if lease_s <= 0:
            raise ValueError(f"lease_s must be > 0 (got {lease_s})")
        if watchdog_s is not None and watchdog_s < 0:
            raise ValueError(f"watchdog_s must be >= 0 (got {watchdog_s})")
        # daemons PIN the spool's backend (clients only inherit): the
        # first daemon's choice — the implicit local default included —
        # is durably recorded so a later daemon cannot diverge
        if not isinstance(store, LeaseStore):
            store = resolve_store(spool_dir, store, pin=True)
        self.store = store
        self.queue = SpoolQueue(
            spool_dir, max_queue=max_queue, max_crashes=max_crashes,
            default_deadline_s=default_deadline_s,
            min_free_bytes=min_free_bytes, store=store,
        )
        self.sched = FairScheduler(
            chunk_budget=chunk_budget, class_depths=class_depths
        )
        # the scheduler's shed policy gates admission (pure over the
        # journal, so every fleet member sheds identically)
        self.queue.admission_policy = (
            lambda jobs, spec: self.sched.shed_reason(jobs, spec.priority)
        )
        # device_indices pins this daemon's slices to a local-device
        # subset (dut-serve --devices 0,1): a fleet on one host can
        # partition the chips so each daemon's jobs own real devices —
        # mesh size then resolves within the subset
        self.worker = WarmWorker(
            n_devices=n_devices, devices=device_indices
        )
        # fleet-shared tuner verdicts (tuning/store.py): auto-ladder
        # jobs consult/persist per-input-profile bucket-shape verdicts
        # through the spool, so every daemon serving this traffic mix
        # converges on the same fast shapes (and the same compiles)
        from duplexumiconsensusreads_tpu.tuning.store import spool_store

        self.verdicts = spool_store(spool_dir)
        self.worker.verdict_store = self.verdicts
        self.worker.on_verdict = self._tuner_verdict_event
        self.workers = workers
        self.poll_s = poll_s
        self.heartbeat_s = heartbeat_s
        self.trace_path = trace_path
        self.lease_s = lease_s
        # fleet identity: unique per service INSTANCE (not per pid), so
        # an in-process restart is a new daemon whose predecessor's
        # leases are provably dead via the live registry
        self.daemon_id = daemon_id or (
            f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        )
        # bind the fleet identity to the lease store: backends with
        # durable heartbeat documents write the first one here, and
        # every later beat (fault site serve.hb) refreshes it — the
        # cross-host liveness evidence other daemons' reclaim sweeps
        # read under fault site serve.store
        self.store.attach(self.daemon_id, lease_s)
        # rate limiter for in-loop/on-chunk beats: first call always
        # due, then at most one per half lease
        self._hb_due_m = 0.0
        self._lock = threading.Lock()
        self._drain = threading.Event()
        self._fatal: BaseException | None = None
        self._n_running = 0
        self._t0 = time.monotonic()
        self._job_seconds: dict[str, dict] = {}
        # per-job wire-byte totals accumulated across slices (the
        # serving-side byte ledger: h2d/d2h/reads per job, snapshotted
        # into metrics.json as job_bytes with bytes_per_read derived).
        # TRAFFIC-attributed: chunks in flight at a preemption are
        # re-transferred and re-counted by the resuming slice (see
        # WarmWorker.run_slice) — these measure bytes moved, not
        # bytes committed
        self._job_bytes: dict[str, dict] = {}
        # per-priority-class latency samples: queue-wait (admission ->
        # first claim) and time-to-first-chunk (admission -> first
        # fresh chunk durable), bounded FIFO
        self._lat: dict[int, dict[str, list]] = {}
        self.watchdog_s = watchdog_s
        # observed inter-chunk-commit intervals (bounded FIFO): the
        # auto-mode watchdog threshold derives from their p95
        self._chunk_durs: list[float] = []
        self.counters = {
            "jobs_accepted": 0, "jobs_rejected": 0, "jobs_shed": 0,
            "jobs_done": 0, "jobs_failed": 0, "jobs_fenced": 0,
            "preemptions": 0, "jobs_recovered": 0,
            "jobs_expired": 0, "jobs_quarantined": 0, "watchdog_fired": 0,
            # scatter-gather sharding: parents fanned out (split) and
            # merged back by THIS daemon — any fleet member may do
            # either half of a given parent
            "jobs_split": 0, "jobs_merged": 0,
            # cumulative wire bytes across every slice this daemon
            # committed — rides the heartbeat line and metrics.json, so
            # a long-lived daemon's transfer pressure is live-readable
            "h2d_bytes": 0, "d2h_bytes": 0,
            # cumulative executed device FLOPs and device-busy seconds
            # (the device-ledger twin of the byte counters): stats()
            # derives the daemon's live MFU from these
            "device_flops": 0.0, "device_s": 0.0,
        }
        # a restarted daemon's counters must not lie about the spool it
        # serves: seed the job-outcome counters from the journal the
        # restart inherited, so metrics.json stays truthful across
        # restarts (bounded by journal compaction — results/ remains
        # the per-job record beyond it)
        self._rebuild_counters_from_journal()
        self._tr: TraceRecorder | None = None

    def _rebuild_counters_from_journal(self) -> None:
        """Seed the outcome counters from the durable journal at
        startup. Only JOURNAL-derivable counters are rebuilt (terminal
        states and admissions); event counters a restart cannot know
        (preemptions, fenced slices, takeovers, byte totals) start at
        zero, honestly."""
        by_state = {
            "done": "jobs_done", "failed": "jobs_failed",
            "expired": "jobs_expired", "quarantined": "jobs_quarantined",
        }
        for entry in self.queue.jobs.values():
            state = entry.get("state")
            if state == "rejected":
                if entry.get("shed"):
                    self.counters["jobs_shed"] += 1
                else:
                    self.counters["jobs_rejected"] += 1
                continue
            # every non-rejected journal entry passed admission
            self.counters["jobs_accepted"] += 1
            key = by_state.get(state)
            if key is not None:
                self.counters[key] += 1

    # ------------------------------------------------------------ control

    def request_drain(self) -> None:
        """Graceful shutdown: running slices yield at the next chunk
        boundary and are re-journaled as queued; :meth:`run` returns.
        Safe from signal handlers and any thread."""
        self._drain.set()

    # ------------------------------------------------------------- stats

    def stats(self) -> dict:
        with self._lock:
            # tuner verdict traffic: slices that reused a stored
            # bucket-shape verdict over all verdict resolutions (reuses
            # + fresh persists) — the counters have existed since the
            # tuner landed but never rode the live line
            v_hits = self.worker.n_verdict_hits
            v_total = v_hits + self.worker.n_verdict_puts
            snap = {
                "elapsed_s": round(time.monotonic() - self._t0, 1),
                # short-form fleet identity on every heartbeat line and
                # metrics snapshot: N daemons interleave on one stderr
                # and one spool, and an anonymous beat is unattributable
                "daemon": self.daemon_id[:12],
                "queue_depth": self.queue.queue_depth(),
                "jobs_inflight": self._n_running,
                **self.counters,
                "slices": self.worker.n_slices,
                "compile_hit_rate": round(self.worker.compile_hit_rate(), 3),
                "verdict_hit_rate": (
                    round(v_hits / v_total, 3) if v_total else 0.0
                ),
            }
            # daemon-level honest MFU: executed FLOPs over device-busy
            # seconds over the shared peak table — the serve analogue of
            # the capture's device ledger (fleet_report folds it)
            dev_s = self.counters["device_s"]
            snap["mfu"] = (
                round_mfu(
                    self.counters["device_flops"] / dev_s
                    / self._peak_flops()
                )
                if dev_s > 0 else 0.0
            )
        return snap

    @staticmethod
    def _peak_flops() -> float:
        """Peak FLOP/s for MFU denominators, resolved per call: the
        env override may change under test, and resolving lazily keeps
        jax backend init off the service constructor."""
        return device_peak_flops()[0]

    def _note_chunk_locked(self, interval_s: float) -> None:
        """One observed inter-chunk-commit interval (caller holds the
        lock): the auto-watchdog's notion of a normal chunk."""
        self._chunk_durs.append(round(interval_s, 4))
        del self._chunk_durs[:-_CHUNK_SAMPLES_KEPT]

    def _watchdog_threshold(self) -> float | None:
        """The effective stall threshold: the explicit setting, or —
        in auto mode — WATCHDOG_P95_MULT x the observed chunk-commit
        p95 (floored at WATCHDOG_MIN_S) once enough samples exist.
        None = the watchdog must not fire (disabled, or auto mode still
        calibrating)."""
        if self.watchdog_s is not None:
            return self.watchdog_s if self.watchdog_s > 0 else None
        with self._lock:
            if len(self._chunk_durs) < WATCHDOG_MIN_SAMPLES:
                return None
            vals = sorted(self._chunk_durs)
        return max(WATCHDOG_MIN_S, WATCHDOG_P95_MULT * _pctl(vals, 0.95))

    def _note_latency_locked(self, priority: int, kind: str, value_s: float) -> None:
        samples = self._lat.setdefault(
            int(priority), {"queue_wait": [], "ttfc": []}
        )[kind]
        samples.append(round(value_s, 4))
        del samples[:-_LAT_SAMPLES_KEPT]

    def _note_bytes_locked(self, job_id: str, sb: dict) -> None:
        """Fold one slice's byte snapshot into the per-job and daemon
        cumulative totals (caller holds the lock)."""
        jb = self._job_bytes.setdefault(
            job_id, {"h2d_bytes": 0, "d2h_bytes": 0, "reads": 0,
                     "device_flops": 0.0, "device_s": 0.0}
        )
        for key in ("h2d_bytes", "d2h_bytes", "reads"):
            jb[key] += int(sb.get(key, 0) or 0)
        # device-ledger twin: FLOPs/seconds accumulate per job and per
        # daemon the same traffic-attributed way the bytes do
        for key in ("device_flops", "device_s"):
            v = float(sb.get(key, 0.0) or 0.0)
            jb[key] = round(jb.get(key, 0.0) + v, 6)
            self.counters[key] = round(self.counters[key] + v, 6)
        self.counters["h2d_bytes"] += int(sb.get("h2d_bytes", 0) or 0)
        self.counters["d2h_bytes"] += int(sb.get("d2h_bytes", 0) or 0)

    def _job_bytes_snapshot_locked(self) -> dict:
        """metrics.json's job_bytes: per-job totals plus the derived
        bytes_per_read (total wire traffic over fresh reads)."""
        out = {}
        for job_id, jb in self._job_bytes.items():
            wire = jb["h2d_bytes"] + jb["d2h_bytes"]
            dev_s = jb.get("device_s", 0.0)
            out[job_id] = {
                **jb,
                "bytes_per_read": (
                    round(wire / jb["reads"], 1) if jb["reads"] else 0.0
                ),
                # per-job honest MFU off the slices' snapshots (0.0 for
                # jobs whose slices predate the device ledger)
                "mfu": (
                    round_mfu(
                        jb.get("device_flops", 0.0) / dev_s
                        / self._peak_flops()
                    )
                    if dev_s > 0 else 0.0
                ),
            }
        return out

    def _class_latency_locked(self) -> dict:
        """Per-priority-class p50/p95 of queue-wait and time-to-first-
        chunk — the service's SLO surface, snapshotted into
        metrics.json beside the queue depth."""
        out = {}
        for pri in sorted(self._lat):
            row = {}
            for kind, key in (("queue_wait", "queue_wait"), ("ttfc", "ttfc")):
                vals = sorted(self._lat[pri][kind])
                row[f"n_{key}"] = len(vals)
                row[f"{key}_p50_s"] = round(_pctl(vals, 0.50), 4)
                row[f"{key}_p95_s"] = round(_pctl(vals, 0.95), 4)
            out[str(pri)] = row
        return out

    def _write_metrics(self, snap: dict) -> None:
        """The live snapshot file beside the journal: queue depth, jobs
        in flight, per-job phase seconds, compile-cache hit rate, and
        the per-class latency percentiles — readable by ops/`call
        --status` while the daemon runs. Fleet note: every daemon
        snapshots the same legacy path (private tmp, atomic replace —
        never torn); last writer wins and names itself in ``daemon_id``.
        Each daemon ALSO owns ``metrics/<daemon_id>.json`` — the
        per-daemon snapshot the fleet aggregator (telemetry/fleet.py,
        tools/fleet_report.py) merges, which additionally carries the
        RAW bounded latency sample FIFOs (``class_latency_samples``):
        fleet-level percentiles need the samples, because percentiles
        of percentiles are not percentiles."""
        import json

        with self._lock:
            doc = {
                **snap,
                "daemon_id": self.daemon_id,
                "lease_s": self.lease_s,
                "job_seconds": self._job_seconds,
                "job_bytes": self._job_bytes_snapshot_locked(),
                "class_latency": self._class_latency_locked(),
                "class_latency_samples": {
                    str(pri): {k: list(v) for k, v in kinds.items()}
                    for pri, kinds in self._lat.items()
                },
            }
            payload = json.dumps(doc, sort_keys=True).encode()
        path = os.path.join(self.queue.root, "metrics.json")
        mine = os.path.join(
            self.queue.root, "metrics", f"{self.daemon_id}.json"
        )
        try:
            os.makedirs(os.path.dirname(mine), exist_ok=True)
            write_durable(path, payload, tmp=unique_tmp(path))
            write_durable(mine, payload, tmp=unique_tmp(mine))
        except OSError:
            pass  # the snapshot is observability, never worth a crash

    def _beat_if_due(self) -> None:
        """Rate-limited liveness-document beat for the worker-loop and
        chunk-commit paths (the heartbeat thread, when enabled, beats
        on its own cadence through :meth:`_beat_stats`). At most one
        durable write per half lease; the first call is always due, so
        every daemon leaves at least one document. Same fault site and
        absorb policy as the heartbeat path: serve.hb, transient
        faults retried, OSError beyond the ladder tolerated (expiry
        still covers), a modelled kill re-raised to die properly."""
        now = time.monotonic()
        with self._lock:
            if now < self._hb_due_m:
                return
            self._hb_due_m = now + self.lease_s / 2.0
        try:
            _io_retry(
                "serve.hb", self.store.beat, "liveness heartbeat document"
            )
        except OSError:
            pass  # staleness backstop only; expiry still covers

    def _beat_stats(self) -> dict:
        # the heartbeat is the lease keep-alive path: every beat
        # refreshes the store's liveness document (serve.hb — the
        # cross-host evidence; journal-lock-free, so it keeps beating
        # even while a transaction waits out a wedged flock) and then
        # extends this daemon's running leases, so a paused daemon
        # (whose beats stop) expires within lease_s while a healthy
        # one can never expire between chunk commits. A dying daemon
        # (fatal set) must NOT renew — its leases should lapse so the
        # fleet takes its jobs over as fast as possible.
        if self._fatal is None:
            try:
                _io_retry(
                    "serve.hb",
                    self.store.beat,
                    "liveness heartbeat document",
                )
            except OSError:
                pass  # staleness backstop only; expiry still covers
            except BaseException as e:  # noqa: BLE001 — modelled kill
                with self._lock:
                    if self._fatal is None:
                        self._fatal = e
                self._drain.set()
                raise
        if self._fatal is None:
            try:
                _io_retry(
                    "serve.renew",
                    lambda: self.queue.renew_all(self.daemon_id, self.lease_s),
                    "heartbeat lease renewal",
                )
            except OSError:
                pass  # beyond retries: per-chunk renewal still covers
            except BaseException as e:  # noqa: BLE001 — modelled kill
                # an InjectedKill landing on the heartbeat thread must
                # take the DAEMON down, not just this thread — a
                # half-alive daemon that keeps committing after its
                # modelled death would break the kill-equals-SIGKILL
                # contract the chaos suite is phrased over
                with self._lock:
                    if self._fatal is None:
                        self._fatal = e
                self._drain.set()
                raise
        snap = self.stats()
        self._write_metrics(snap)
        return snap

    # ----------------------------------------------------------- running

    def run(self, once: bool = False) -> dict:
        """Drain the spool. ``once=True`` returns when the queue, inbox
        and all fleet work are idle (tests, the bench legs);
        ``once=False`` runs until :meth:`request_drain`. Returns the
        final stats snapshot; re-raises a fatal error (injected kill,
        journal I/O beyond retries) after the surviving workers stop."""
        from duplexumiconsensusreads_tpu.utils.compile_cache import (
            enable_compile_cache,
        )

        enable_compile_cache(per_host_cpu=True)
        with _LIVE_LOCK:
            _LIVE_DAEMONS.add(self.daemon_id)
        tr = None
        hooked = False
        hb = None
        wd_stop = threading.Event()
        wd = None
        try:
            if self.trace_path:
                # the meta header names this daemon: every record in
                # the capture is this daemon's testimony, and the fleet
                # stitcher (telemetry/fleet.py) attributes run slices
                # to daemons by exactly this attr. On a cross-host
                # store the meta also OVERRIDES epoch_m into the
                # spool's stamp domain (the recorder's own t0 is this
                # host's arbitrary monotonic epoch): relative ts then
                # stitch against other hosts' captures and the
                # journal's *_m stamps without any per-host offset
                meta = {"daemon_id": self.daemon_id}
                epoch = self.store.capture_epoch()
                if epoch is not None:
                    meta["epoch_m"] = round(epoch, 6)
                tr = TraceRecorder(self.trace_path, kind="service",
                                   meta=meta)
                self._tr = tr
                if telemetry.get_active() is None:
                    # the service capture doubles as the switchboard
                    # sink: fault/retry/durable events from admissions
                    # AND from untraced job slices land here
                    telemetry.install(tr)
                    hooked = True
            if self.heartbeat_s and self.heartbeat_s > 0:
                hb = Heartbeat(self.heartbeat_s, self._beat_stats, recorder=tr)
                hb.start()
            # the stuck-run watchdog: independent of the workers (a
            # wedged slice freezes them) and of the heartbeat (which
            # keeps renewing the very lease a wedged run hides behind)
            wd = threading.Thread(
                target=self._watchdog_loop, args=(wd_stop,),
                name="dut-watchdog", daemon=True,
            )
            wd.start()
            # startup sweeps: staging files orphaned by dead daemons
            # (crash litter — their pid-suffixed tmps are never reused)
            # and jobs the journal says are running under a dead
            # daemon's (or no) lease, requeued before the workers start
            # so recovery counters/events land once
            self.queue.sweep_orphan_tmps()
            with self._lock:
                self._reclaim_locked()
            threads = [
                threading.Thread(
                    target=self._worker_loop, args=(once,),
                    name=f"dut-serve_{i}", daemon=True,
                )
                for i in range(self.workers)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        except BaseException as e:  # noqa: BLE001 — incl. startup kills
            # a kill during setup/startup reclaim is the same modelled
            # death as one inside a worker: record it, clean up below,
            # re-raise with durable state exactly as the kill left it
            with self._lock:
                if self._fatal is None:
                    self._fatal = e
        finally:
            wd_stop.set()
            if wd is not None and wd.is_alive():
                wd.join(timeout=2.0)
            if hb is not None:
                hb.stop()
            snap = self._beat_stats()
            if tr is not None:
                if self._fatal is None:
                    # clean shutdown only: a fatal exit must leave a
                    # summary-less capture, the post-mortem marker
                    tr.write_summary(counters=snap)
                if hooked:
                    telemetry.uninstall()
                tr.close()
                self._tr = None
            # crash or clean exit, this daemon is dead to the fleet:
            # deregistering lets a successor reclaim its leases
            # immediately instead of waiting out the expiry
            with _LIVE_LOCK:
                _LIVE_DAEMONS.discard(self.daemon_id)
        if self._fatal is not None:
            raise self._fatal
        return snap

    def run_until_idle(self) -> dict:
        return self.run(once=True)

    # ------------------------------------------------------- worker loop

    def _accept_pending_locked(self) -> None:
        """Admit every spooled submission (caller holds the lock)."""
        tr = self._tr
        for job_id in self.queue.pending_submissions():
            spec, reason = self.queue.accept_one(job_id)
            if spec is not None:
                self.counters["jobs_accepted"] += 1
                if tr is not None:
                    tr.event(
                        "job_accepted", job=spec.job_id,
                        lane=f"job-{spec.job_id}", priority=spec.priority,
                        seq=self.queue.jobs[spec.job_id]["seq"],
                        queue_depth=self.queue.queue_depth(),
                    )
            elif reason is not None:
                entry = self.queue.jobs.get(job_id, {})
                if entry.get("shed"):
                    # admission-control rejection: valid job, no room
                    # in its class (or the global bound) — a distinct
                    # event so overload is legible in the capture
                    self.counters["jobs_shed"] += 1
                    if tr is not None:
                        tr.event(
                            "job_shed", job=job_id, lane=f"job-{job_id}",
                            reason=reason[:200],
                            priority=entry.get("priority", 1),
                        )
                else:
                    self.counters["jobs_rejected"] += 1
                    if tr is not None:
                        tr.event(
                            "job_rejected", job=job_id, lane=f"job-{job_id}",
                            reason=reason[:200],
                        )

    def _reclaim_locked(self) -> list[dict]:
        """One takeover sweep (caller holds the lock): requeue every
        running job whose lease is expired or whose owner is provably
        dead. The heartbeat-document scan rides fault site
        ``serve.store`` and the scan itself fault site ``serve.expire``
        (the persist inside reclaim_dead does too), so chaos schedules
        can target each step even on passes that reclaim nothing."""
        tr = self._tr
        hosts = _io_retry(
            "serve.store",
            self.store.observe,
            "lease-store liveness scan",
        )
        reclaimed = _io_retry(
            "serve.expire",
            lambda: self.queue.reclaim_dead(
                self.daemon_id, is_live=_daemon_is_live, hosts=hosts
            ),
            "lease reclaim sweep",
        )
        requeued = [r for r in reclaimed if not r.get("quarantined")]
        if requeued:
            self.counters["jobs_recovered"] += len(requeued)
        for r in reclaimed:
            if tr is not None:
                lane = f"job-{r['job_id']}"
                tr.event(
                    "lease_takeover", job=r["job_id"], lane=lane,
                    reason=r["reason"],
                    prev_owner=str(r["prev_owner"])[:80],
                    by=self.daemon_id,
                )
                if not r.get("quarantined"):
                    tr.event(
                        "resume", job=r["job_id"], lane=lane,
                        decision="requeued_running",
                    )
        # a reclaim that crossed max_crashes went to quarantine, not
        # back to the queue: count + record it
        self._note_reclaim_quarantines_locked(reclaimed)
        return reclaimed

    def _expire_deadlines_locked(self) -> list[dict]:
        """One deadline sweep (caller holds the lock): journal every
        queued job whose monotonic deadline has passed as terminal
        ``expired`` with a durable reason. Rides fault site
        ``serve.deadline`` every pass (like the takeover sweep), so
        chaos schedules can target the deadline step even when nothing
        expires."""
        tr = self._tr
        expired = _io_retry(
            "serve.deadline",
            self.queue.expire_deadlines,
            "deadline sweep",
        )
        if expired:
            self.counters["jobs_expired"] += len(expired)
        for r in expired:
            if tr is not None:
                tr.event(
                    "job_expired", job=r["job_id"],
                    lane=f"job-{r['job_id']}", reason=r["reason"][:200],
                )
        return expired

    def _note_reclaim_quarantines_locked(self, reclaimed: list[dict]) -> int:
        """Shared bookkeeping for takeover/watchdog reclaims whose
        crash count crossed the quarantine bound: counter + event per
        quarantined job. CALLER HOLDS the service lock (the recorder
        has its own lock and never takes this one, so recording under
        it cannot invert an ordering). Returns how many of
        ``reclaimed`` were quarantined (the rest were requeued)."""
        tr = self._tr
        n = 0
        for r in reclaimed:
            if not r.get("quarantined"):
                continue
            n += 1
            self.counters["jobs_quarantined"] += 1
            if tr is not None:
                tr.event(
                    "job_quarantined", job=r["job_id"],
                    lane=f"job-{r['job_id']}", reason=r["reason"],
                    crash_count=r.get("crash_count", 0),
                    prev_owner=str(r.get("prev_owner"))[:80],
                )
        return n

    def _advance_parents_locked(self) -> list[dict]:
        """One sharding-parent sweep (caller holds the lock): requeue
        every fanned parent whose sub-jobs all published as a merge
        task, and fail parents with a terminally-failed shard. Rides
        fault site ``serve.merge`` on every pass (like the takeover and
        deadline sweeps), so chaos schedules can target the merge
        step's scheduling edge even on passes that move nothing."""
        tr = self._tr
        moved = _io_retry(
            "serve.merge", self.queue.advance_parents, "parent sweep",
        )
        for r in moved:
            if r["decision"] == "failed":
                self.counters["jobs_failed"] += 1
                if tr is not None:
                    first = r.get("shard_failure", {})
                    tr.event(
                        "job_failed", job=r["job_id"],
                        lane=f"job-{r['job_id']}",
                        error=f"shard {first.get('shard')} "
                              f"{first.get('state')}: "
                              f"{str(first.get('error'))[:120]}",
                        shard=first.get("shard"),
                    )
            elif r["decision"] == "orphaned":
                # a requeued child of an already-terminal parent was
                # reaped instead of re-run
                self.counters["jobs_failed"] += 1
                if tr is not None:
                    tr.event(
                        "job_failed", job=r["job_id"],
                        lane=f"job-{r['job_id']}",
                        error=f"orphaned shard of terminal parent "
                              f"{r.get('parent')}",
                    )
            elif tr is not None:
                tr.event(
                    "resume", job=r["job_id"], lane=f"job-{r['job_id']}",
                    decision="requeued_merge",
                )
        return moved

    def _watchdog_sweep(self) -> list[dict]:
        """One stuck-run scan: abort-requeue every running job with no
        durable progress for the stall threshold (the lease/fence path
        does the fencing — a wedged slice that wakes later is fenced at
        its first commit). Rides fault site ``serve.watchdog`` on every
        tick, reclaim or not, so chaos can target the watchdog step."""
        tr = self._tr
        threshold = self._watchdog_threshold()
        reclaimed = _io_retry(
            "serve.watchdog",
            lambda: self.queue.reclaim_stalled(threshold),
            "watchdog stall scan",
        )
        for r in reclaimed:
            if tr is not None:
                tr.event(
                    "watchdog_fired", job=r["job_id"],
                    lane=f"job-{r['job_id']}",
                    stalled_s=r.get("stalled_s"),
                    threshold_s=round(threshold, 3),
                    prev_owner=str(r.get("prev_owner"))[:80],
                )
        if reclaimed:
            with self._lock:
                self.counters["watchdog_fired"] += len(reclaimed)
                self._note_reclaim_quarantines_locked(reclaimed)
        return reclaimed

    def _watchdog_loop(self, stop: threading.Event) -> None:
        """The per-daemon watchdog thread. A separate thread on
        purpose: with every worker wedged inside a stuck slice the
        scheduler loop never runs again, so only an independent thread
        can notice that durable progress stopped while the heartbeat
        kept the lease alive."""
        while not stop.wait(0.25):
            try:
                self._watchdog_sweep()
            except OSError:
                continue  # beyond retries: observe again next tick
            except BaseException as e:  # noqa: BLE001 — modelled kill
                # same contract as the heartbeat thread: an injected
                # kill on the watchdog takes the daemon down whole
                with self._lock:
                    if self._fatal is None:
                        self._fatal = e
                self._drain.set()
                raise

    def _idle_done(self, once: bool) -> bool:
        if not once:
            return False
        with self._lock:
            # fleet-aware idleness: a job running under ANOTHER
            # daemon's live lease is still open work — a --once drain
            # must not declare victory (or strand a waiting takeover)
            # while the journal holds any open job
            self.queue.refresh()
            open_jobs = any(
                e.get("state") in OPEN_STATES
                for e in self.queue.jobs.values()
            )
            return (
                not open_jobs
                and self._n_running == 0
                and not self.queue.pending_submissions()
            )

    def _worker_loop(self, once: bool) -> None:
        try:
            while not self._drain.is_set():
                claimed = None
                # liveness document refresh, rate-limited (first pass
                # always due): a daemon running with the heartbeat
                # thread disabled must still leave cross-host evidence
                # it is alive, or a sharedfs peer's staleness backstop
                # would read silence as death
                self._beat_if_due()
                with self._lock:
                    self._accept_pending_locked()
                    self._reclaim_locked()
                    self._expire_deadlines_locked()
                    self._advance_parents_locked()
                    # deadline-aware pick: never claim a job the sweep
                    # (or another daemon's sweep) is about to expire —
                    # "now" on the spool's stamp clock, the domain of
                    # the entries' deadline_m
                    job_id = self.sched.pick(
                        self.queue.jobs, now=self.store.now()
                    )
                    if job_id is not None:
                        # the pick is advisory until the CLAIM commits:
                        # the flock'd transaction re-checks the state,
                        # so two daemons picking the same job resolve
                        # to exactly one lease holder. The claim rides
                        # fault site serve.lease — a transient fault is
                        # retried, a kill dies with the job still queued
                        token = _io_retry(
                            "serve.lease",
                            lambda: self.queue.claim(
                                job_id, self.daemon_id, self.lease_s
                            ),
                            f"job {job_id} lease claim",
                        )
                        if token is not None:
                            entry = self.queue.jobs[job_id]
                            # journaled spec, not a cached object: a
                            # daemon restarted onto an old journal must
                            # run exactly what admission durably recorded
                            spec = validate_spec(entry["spec"])
                            first_slice = entry["slices"] == 1
                            if first_slice and "admitted_m" in entry:
                                self._note_latency_locked(
                                    entry.get("priority", 1), "queue_wait",
                                    self.store.now() - entry["admitted_m"],
                                )
                            self._n_running += 1
                            # what the claim MEANT is in the journal:
                            # a sharding parent claims as splitting or
                            # merging, everything else as running
                            claimed = (
                                spec, first_slice, token, entry["state"]
                            )
                if claimed is None:
                    if self._idle_done(once):
                        return
                    self._drain.wait(self.poll_s)
                    continue
                try:
                    spec, first_slice, token, stage = claimed
                    if stage == "splitting":
                        self._run_split(spec, token)
                    elif stage == "merging":
                        self._run_merge(spec, token)
                    else:
                        self._run_one(spec, first_slice, token)
                finally:
                    with self._lock:
                        self._n_running -= 1
        except BaseException as e:  # noqa: BLE001 — modelled process death
            # an injected kill or a journal failure beyond the retry
            # ladder is the daemon dying: stop every worker, surface the
            # exception from run() with the journal exactly as durable
            # state left it (the recovery tests restart from there)
            with self._lock:
                if self._fatal is None:
                    self._fatal = e
            self._drain.set()

    def _tuner_verdict_event(self, job_id: str, attrs: dict) -> None:
        """The worker's on_verdict hook: ledger a bucket-ladder verdict
        decision (persisted fresh, source="run", or reused from the
        spool store, source="store") into the service capture — the
        KNOWN_EVENTS registry promises the fleet's shape decisions are
        auditable from any capture."""
        tr = self._tr
        if tr is not None:
            tr.event("tuner_verdict", job=job_id, lane=f"job-{job_id}",
                     **attrs)

    def _fenced(self, job_id: str, lane: str, detail: str,
                token: int | None = None) -> None:
        """A slice lost its lease: count it, record it, commit nothing.
        Not a failure — the reclaiming daemon owns the job and will
        produce the identical bytes. ``token`` names the STALE lease
        the zombie slice held, so the stitcher can tie the fence back
        to the slice it voids."""
        tr = self._tr
        with self._lock:
            self.counters["jobs_fenced"] += 1
        attrs = {} if token is None else {"token": token}
        if tr is not None:
            tr.event("job_fenced", job=job_id, lane=lane,
                     detail=detail[:200], **attrs)

    def _fenced_renew(self, job_id: str, token: int) -> None:
        """Fence check + lease renewal in one flock'd txn — the planner
        and merger's commit guard, THE SAME helper a consensus slice's
        per-chunk guard runs (serve.worker.fenced_renew), so the two
        stages cannot drift."""
        from duplexumiconsensusreads_tpu.serve.worker import fenced_renew

        fenced_renew(
            self.queue, job_id, self.daemon_id, token, self.lease_s
        )

    def _fail_job(self, job_id: str, lane: str, e: Exception,
                  token: int) -> None:
        """Journal a job-scoped failure (fenced) — shared by the slice,
        split and merge stages. ENOSPC gets the disk-pressure grace
        pass: before journaling the failure (itself a durable write
        that needs space), drop terminal jobs' shard/checkpoint litter
        so the victim fails cleanly and the daemon lives on."""
        tr = self._tr
        enospc = isinstance(e, OSError) and e.errno == errno.ENOSPC
        if enospc:
            self.queue.gc_terminal_litter()
        try:
            with self._lock:
                self.queue.mark_failed(job_id, repr(e), self.daemon_id, token)
                self.counters["jobs_failed"] += 1
        except JobFenced as f:
            # the job died HERE but was already reclaimed: the new
            # owner decides its fate; this daemon records nothing
            self._fenced(job_id, lane, str(f), token=token)
            return
        if tr is not None:
            # token: the slice's lease identity — the stitcher pairs
            # this terminal with its job_started on the same token
            tr.event("job_failed", job=job_id, lane=lane,
                     error=repr(e)[:200], enospc=enospc, token=token)

    def _run_split(self, spec, token: int) -> None:
        """The parent's split stage: scan the input's chunk grid, plan
        K range sub-jobs, register them + move the parent to ``fanned``
        in one fenced journal transaction (fault site ``serve.split``).
        The scan runs outside any lock or transaction — only the
        registration is a journal move — and a kill anywhere re-plans
        idempotently (derived child ids dedupe)."""
        from duplexumiconsensusreads_tpu.serve.job import job_params
        from duplexumiconsensusreads_tpu.serve.shard.plan import (
            child_spec_dicts,
            plan_shards,
        )

        tr = self._tr
        job_id = spec.job_id
        lane = f"job-{job_id}"
        if tr is not None:
            with self._lock:
                n_slice = self.queue.jobs[job_id]["slices"]
            tr.event("job_started", job=job_id, lane=lane, slice=n_slice,
                     stage="split", token=token)
        t0 = time.monotonic()
        # the scan is pure host I/O with no chunk commits, so the
        # watchdog's durable-progress clock would run dry on a large
        # input: stamp progress (one fenced renewal) at most every
        # half lease interval while scanning — a wedged scan still
        # stops stamping and stays watchdog-visible
        last_renew = [time.monotonic()]

        def scan_progress():
            now = time.monotonic()
            if now - last_renew[0] >= self.lease_s / 2:
                last_renew[0] = now
                self._fenced_renew(job_id, token)

        try:
            _, cp, kwargs = job_params(spec)
            plan = plan_shards(
                spec.input, kwargs["chunk_reads"],
                duplex=(cp.mode == "duplex"),
                n_shards=spec.shards, shard_bytes=spec.shard_bytes,
                mate_aware=kwargs["mate_aware"],
                progress=scan_progress,
                # one parent must not swamp the fleet's open-jobs
                # bound: the fan-out is capped at the admission bound
                # the parent itself was admitted under
                max_shards=self.queue.max_queue,
            )
            dicts = child_spec_dicts(spec, plan)
            # the scan can outlive a lease renewal interval: re-arm
            # (and fence) before committing the plan
            self._fenced_renew(job_id, token)
            _io_retry(
                "serve.split",
                lambda: self.queue.register_shards(
                    job_id, self.daemon_id, token, dicts
                ),
                f"job {job_id} shard registration",
            )
        except JobFenced as e:
            self._fenced(job_id, lane, str(e), token=token)
            return
        except Exception as e:  # noqa: BLE001 — job-scoped failure
            self._fail_job(job_id, lane, e, token)
            return
        with self._lock:
            self.counters["jobs_split"] += 1
        if tr is not None:
            tr.event(
                "job_split", job=job_id, lane=lane, token=token,
                n_shards=len(dicts), n_chunks=plan.n_chunks,
                n_records=plan.n_records,
                wall_s=round(time.monotonic() - t0, 3),
            )

    def _run_merge(self, spec, token: int) -> None:
        """The parent's merge stage: splice the per-shard outputs (in
        shard order) into the final BAM, rebuild its index, publish the
        aggregate result and journal the parent done — every commit
        fenced, every durable move on fault site ``serve.merge``. Pure
        function of the shard files: a kill mid-merge re-runs it
        whole on whichever daemon claims the parent next."""
        from duplexumiconsensusreads_tpu.serve.job import job_params
        from duplexumiconsensusreads_tpu.serve.shard.merge import (
            splice_shards,
        )
        from duplexumiconsensusreads_tpu.serve.shard.plan import (
            shard_output_path,
        )

        tr = self._tr
        job_id = spec.job_id
        lane = f"job-{job_id}"
        with self._lock:
            entry = self.queue.jobs.get(job_id, {})
            children = list(entry.get("children", ()))
            n_slice = entry.get("slices", 0)
        if tr is not None:
            tr.event("job_started", job=job_id, lane=lane, slice=n_slice,
                     stage="merge", token=token)
        t0 = time.monotonic()
        shard_paths = [
            shard_output_path(spec.output, i) for i in range(len(children))
        ]
        try:
            _, _, kwargs = job_params(spec)
            merged = splice_shards(
                spec.output, shard_paths,
                fence=lambda: self._fenced_renew(job_id, token),
                write_index=bool(kwargs["write_index"]),
            )
            result = self._aggregate_shard_results(children)
            result["output"] = os.path.abspath(spec.output)
            result["sharded"] = {
                **merged, "merge_s": round(time.monotonic() - t0, 3),
            }
            with self._lock:
                self.queue.mark_done(job_id, result, self.daemon_id, token)
                self.counters["jobs_done"] += 1
                self.counters["jobs_merged"] += 1
        except JobFenced as e:
            self._fenced(job_id, lane, str(e), token=token)
            return
        except Exception as e:  # noqa: BLE001 — job-scoped failure
            self._fail_job(job_id, lane, e, token)
            return
        wall = round(time.monotonic() - t0, 3)
        if tr is not None:
            tr.event(
                "job_merged", job=job_id, lane=lane, token=token,
                n_shards=len(shard_paths), merge_s=wall,
                output_bytes=result["sharded"]["output_bytes"],
            )
            tr.event(
                "job_completed", job=job_id, lane=lane, wall_s=wall,
                token=token,
                n_chunks=result.get("n_chunks", 0),
                n_consensus=result.get("n_consensus", 0),
                warm=False, seconds=result.get("seconds", {}),
            )
        # the published merge supersedes the intermediate shard
        # outputs: reclaim their disk now, not at the next GC pass
        for p in shard_paths:
            try:
                os.remove(p)
            except OSError:
                pass

    def _aggregate_shard_results(self, children: list[str]) -> dict:
        """The parent's result: sums of the sub-jobs' durable results —
        read from results/ (the files outlive journal compaction), so
        the rollup answers whichever daemon merges."""
        import json

        totals = {"n_chunks": 0, "n_consensus": 0, "n_records": 0,
                  "n_consensus_pairs": 0}
        for cid in children:
            path = os.path.join(self.queue.results_dir, cid + ".json")
            try:
                with open(path) as f:
                    r = json.load(f)
            except (OSError, ValueError):
                continue  # best-effort rollup; the merge bytes are the contract
            for key in totals:
                v = r.get(key)
                if isinstance(v, (int, float)):
                    totals[key] += int(v)
        return totals

    def _run_one(self, spec, first_slice: bool, token: int) -> None:
        tr = self._tr
        job_id = spec.job_id
        lane = f"job-{job_id}"
        warm = self.worker.note_job_start(spec, first_slice)
        if tr is not None:
            with self._lock:
                n_slice = self.queue.jobs[job_id]["slices"]
            lineage = {}
            if spec.shard is not None:
                # shard lineage on the wire: serve_report's parent
                # rollup and per-job lineage column read these
                lineage = {
                    "parent": spec.shard.get("parent"),
                    "shard_idx": spec.shard.get("idx"),
                }
            tr.event(
                "job_started", job=job_id, lane=lane, slice=n_slice,
                warm=warm, resumed=not first_slice, token=token,
                **lineage,
            )

        def should_yield() -> bool:
            with self._lock:
                return self.sched.others_waiting(self.queue.jobs, job_id)

        on_first_chunk = None
        with self._lock:
            entry = self.queue.jobs.get(job_id, {})
            admitted_m = entry.get("admitted_m")
            priority = entry.get("priority", 1)
            deadline_m = entry.get("deadline_m")
        if first_slice and admitted_m is not None:

            def on_first_chunk():
                with self._lock:
                    self._note_latency_locked(
                        priority, "ttfc",
                        self.store.now() - admitted_m,
                    )

        # chunk-cadence sampling: inter-commit intervals feed the
        # auto-watchdog threshold (what a "normal" chunk costs here).
        # Each commit also refreshes the liveness document (rate-
        # limited): a long slice must keep its cross-host heartbeat
        # honest even when the heartbeat thread is off.
        last_commit = [time.monotonic()]

        def on_chunk():
            now = time.monotonic()
            with self._lock:
                self._note_chunk_locked(now - last_commit[0])
            last_commit[0] = now
            self._beat_if_due()

        lease = LeaseContext(
            queue=self.queue, daemon_id=self.daemon_id, token=token,
            lease_s=self.lease_s, on_first_chunk=on_first_chunk,
            on_chunk=on_chunk, deadline_m=deadline_m,
            now_fn=self.store.now,
        )
        t0 = time.monotonic()
        try:
            out = self.worker.run_slice(
                spec, self.sched.chunk_budget, should_yield, self._drain,
                lease=lease,
            )
        except JobFenced as e:
            self._fenced(job_id, lane, str(e), token=token)
            return
        except JobDeadlineExceeded as e:
            # deadline abort at a chunk boundary: terminal `expired`
            # with a durable reason; the committed checkpoint prefix is
            # preserved byte-for-byte for a future re-submission. The
            # fenced transition rides fault site serve.deadline, like
            # the queued-side sweep.
            try:
                _io_retry(
                    "serve.deadline",
                    lambda: self.queue.mark_expired(
                        job_id, str(e), self.daemon_id, token
                    ),
                    f"job {job_id} deadline expiry",
                )
            except JobFenced as f:
                self._fenced(job_id, lane, str(f), token=token)
                return
            with self._lock:
                self.counters["jobs_expired"] += 1
            if tr is not None:
                tr.event("job_expired", job=job_id, lane=lane,
                         reason=str(e)[:200],
                         chunks_done=e.chunks_done, token=token)
            return
        except Exception as e:  # noqa: BLE001 — job-scoped failure
            self._fail_job(job_id, lane, e, token)
            return
        wall = round(time.monotonic() - t0, 3)
        if out[0] == "done":
            _, result = out
            try:
                with self._lock:
                    self.queue.mark_done(
                        job_id, result, self.daemon_id, token
                    )
                    self.counters["jobs_done"] += 1
                    self._job_seconds[job_id] = result.get("seconds", {})
                    self._note_bytes_locked(job_id, {
                        "h2d_bytes": result.get("bytes_h2d", 0),
                        "d2h_bytes": result.get("bytes_d2h", 0),
                        "reads": result.get("n_records", 0),
                        "device_flops": result.get("device_flops", 0.0),
                        "device_s": result.get("device_seconds", 0.0),
                    })
                    jb = dict(self._job_bytes.get(job_id, {}))
            except JobFenced as f:
                self._fenced(job_id, lane, str(f), token=token)
                return
            if tr is not None:
                wire = jb.get("h2d_bytes", 0) + jb.get("d2h_bytes", 0)
                tr.event(
                    "job_completed", job=job_id, lane=lane, wall_s=wall,
                    token=token,
                    n_chunks=result.get("n_chunks", 0),
                    n_consensus=result.get("n_consensus", 0),
                    warm=warm, seconds=result.get("seconds", {}),
                    # the job's whole-life byte totals (every slice,
                    # preempted ones included) — serve_report's per-job
                    # byte column reads straight off this event
                    h2d_bytes=jb.get("h2d_bytes", 0),
                    d2h_bytes=jb.get("d2h_bytes", 0),
                    bytes_per_read=(
                        round(wire / jb["reads"], 1)
                        if jb.get("reads") else 0.0
                    ),
                    # whole-life device ledger: executed FLOPs and the
                    # job's honest MFU (serve_report's mfu column)
                    device_flops=round(jb.get("device_flops", 0.0), 3),
                    mfu=(
                        round_mfu(
                            jb.get("device_flops", 0.0)
                            / jb["device_s"] / self._peak_flops()
                        )
                        if jb.get("device_s") else 0.0
                    ),
                )
        else:
            _, chunks_done, reason, slice_bytes = out
            with self._lock:
                self._note_bytes_locked(job_id, slice_bytes)

            def _requeue():
                with self._lock:
                    self.queue.requeue(
                        job_id, chunks_done, back=(reason == "budget"),
                        daemon_id=self.daemon_id, token=token,
                    )

            # serve.preempt guards the preemption commit: a transient
            # fault re-runs the idempotent requeue; an injected kill
            # leaves the job journaled "running" under this lease,
            # which takeover (expiry/dead-owner) requeues — the same
            # convergence a real crash gets
            try:
                _io_retry("serve.preempt", _requeue, f"job {job_id} requeue")
            except JobFenced as f:
                self._fenced(job_id, lane, str(f), token=token)
                return
            with self._lock:
                self.counters["preemptions"] += 1
            if tr is not None:
                tr.event(
                    "job_preempted", job=job_id, lane=lane,
                    chunks_done=chunks_done, reason=reason, wall_s=wall,
                    token=token,
                )
