"""Follow-mode ingest: consensus as the sequencer runs.

The streaming executor (runtime/stream.py) normally consumes a
finished, coordinate-sorted BAM. This package turns it into a
*follower* of a growing one — a regular file another process is
appending to, or a FIFO/pipe — so consensus calling overlaps the
instrument run instead of starting after it:

``tail.TailSource``
    A file-like object the stream reader can open instead of the real
    file. A dedicated tailing thread (``dut-live-tail``, a declared
    ``THREAD_ROLES`` row) polls the growing input and admits only
    byte runs that end on a complete-BGZF-block boundary (the stream
    reader's ``_complete_prefix`` rule), so the consumer never sees a
    torn block no matter when the writer is interrupted. Termination
    is the 28-byte BGZF EOF block by default, with ``idle:<seconds>``
    and ``<path>.done`` marker modes for writers that cannot promise
    one (``parse_finalize_on``).

``watermark``
    The durable follow-run identity (``<out>.livemark``): a pinned
    ``stat_sig`` replaces the input's (size, mtime) pair in the
    checkpoint fingerprint — a growing file changes both every poll,
    and without the pin a kill/resume mid-tail would refuse its own
    checkpoint. Snapshot sequencing lives here too, so a resumed
    follower continues the published-snapshot series.

Everything else — chunk grid, hold-back boundary rule, device
pipeline, incremental finalise, checkpoint resume — is the batch
spine, unchanged: a follow run over the finished file must produce
byte-identical output (BAI included) to the batch run, which is why
every knob this package adds is scheduling-class.
"""

from duplexumiconsensusreads_tpu.live import watermark
from duplexumiconsensusreads_tpu.live.tail import (
    TailSource,
    parse_finalize_on,
)

__all__ = ["TailSource", "parse_finalize_on", "watermark"]
