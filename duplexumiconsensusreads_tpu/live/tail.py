"""The tailing producer: a file-like follower of a growing BGZF stream.

``TailSource`` sits between the growing input and ``BamStreamReader``.
The reader calls plain ``read``/``tell``/``seek`` and cannot tell it is
not holding a finished file; the tailing thread behind those calls
polls the input, admits only complete-BGZF-block byte runs, and decides
when the stream is finished. Admission is the whole trick: the stream
reader's contract is "``read()`` returns b'' only at true EOF, and the
bytes before it form whole BGZF blocks" — a growing file violates both
(it has a perpetually torn tail and a perpetually moving end), so the
tailer buffers the torn tail privately and releases bytes only up to
the last complete block boundary (``_complete_prefix``, the same rule
the batch reader applies to its rolling buffer).

Thread model (declared as the ``live-tail`` row in
``runtime/knobs.py`` THREAD_ROLES): the tailer performs pure host I/O
against the input — no device calls, no durable state moves (the
admission watermark is persisted by the main loop at commit time) —
and its only output seam is the bounded admission queue ``_q``.
Failures, including injected kills at fault site ``live.poll``, are
forwarded through the queue as an error sentinel and re-raised on the
consumer side, mirroring the overlap-mode ingest producer.

Timing is split across the seam: the tailer accumulates its idle-poll
seconds, the consumer accumulates its blocked-on-tailer seconds, both
under the source's own lock; the executor drains them into the phase
ledger (``live_poll`` / ``live_wait``) at chunk boundaries so the
tailer never touches stream.py's shared state.
"""

from __future__ import annotations

import os
import queue
import stat
import threading
import time

from duplexumiconsensusreads_tpu.io import bgzf

# bounded admission queue depth, in admitted slabs (not bytes): deep
# enough to decouple poll cadence from chunk cadence, shallow enough
# that a stalled consumer stops the tailer from buffering the whole
# growing file in memory
_QUEUE_SLABS = 8

# granularity of interruptible blocking on the queue: close() must be
# able to unstick either side without poisoning the queue
_BLOCK_TICK_S = 0.1


def parse_finalize_on(spec: str):
    """``(mode, idle_s)`` from ``eof`` | ``idle:<seconds>`` | ``marker``.

    ``eof``      finish when the admitted stream ends with the 28-byte
                 BGZF EOF block (the BAM spec's own terminator — the
                 default, and what any htslib-family writer emits);
    ``idle:N``   finish when the input has not grown for N seconds
                 (writers that die without an EOF block);
    ``marker``   finish when ``<input>.done`` exists (pipelines that
                 signal completion out-of-band).
    """
    if spec == "eof":
        return "eof", None
    if spec == "marker":
        return "marker", None
    if isinstance(spec, str) and spec.startswith("idle:"):
        try:
            idle = float(spec[len("idle:"):])
        except ValueError:
            idle = -1.0
        if idle > 0:
            return "idle", idle
    raise ValueError(
        f"finalize_on must be 'eof', 'idle:<seconds>' or 'marker' "
        f"(got {spec!r})"
    )


class TailSource:
    """File-like follower of a growing BGZF file or FIFO.

    Forward-only: ``seek`` accepts only the current position (which is
    all the stream reader's retry ladder ever asks for). ``read``
    blocks until the tailer admits bytes or declares the stream
    finished; it returns b"" only at the true end, with every byte
    before it part of a complete BGZF block.
    """

    def __init__(
        self,
        path: str,
        poll_s: float = 0.25,
        finalize_on: str = "eof",
        read_size: int = 1 << 20,
    ):
        self.path = path
        self.mode, self.idle_s = parse_finalize_on(finalize_on)
        self.poll_s = max(float(poll_s), 0.001)
        self.read_size = int(read_size)
        st = os.stat(path)
        self.is_fifo = stat.S_ISFIFO(st.st_mode)
        self.finish_reason = None
        self._q = queue.Queue(maxsize=_QUEUE_SLABS)
        self._closed = threading.Event()
        self._buf = bytearray()
        self._pos = 0  # logical consumed offset (reader-visible)
        self._finished = False
        self._err = None
        self._lock = threading.Lock()
        self._admitted = 0  # bytes admitted by the tailer
        self._poll_seconds = 0.0  # tailer side: idle-poll sleep time
        self._wait_seconds = 0.0  # consumer side: blocked-on-tailer time
        self._thread = threading.Thread(
            target=self._tail_loop, name="dut-live-tail", daemon=True
        )
        self._thread.start()

    # ---------------------------------------------- tailer thread side

    def _read_poll(self, f):
        # one poll cycle: a single incremental read of the growing
        # input. Fault site live.poll wraps this call — transients ride
        # the standard bounded-retry ladder on the tailer itself; kills
        # forward through the queue's error sentinel
        return f.read(self.read_size)

    def _put(self, item) -> None:
        # bounded handoff in interruptible steps: close() (run abort)
        # must unstick a tailer blocked on a full queue
        while not self._closed.is_set():
            try:
                self._q.put(item, timeout=_BLOCK_TICK_S)
                return
            except queue.Full:
                continue

    def _finish_drained(self, pending: bytes, why: str) -> None:
        # idle/marker/writer-close finalisation reached with a torn
        # trailing block: the input ended mid-write. Refuse loudly —
        # silently dropping the partial block would publish an output
        # missing reads with no warning
        if pending:
            self._put(("err", ValueError(
                f"{self.path}: follow input finalised ({why}) with a "
                f"truncated trailing BGZF block ({len(pending)} bytes)"
            )))
        else:
            self._put(("done", why))

    def _tail_loop(self) -> None:
        # function-level import: runtime.stream imports this package
        # lazily for follow runs, and the tailer reuses its retry
        # ladder and block-boundary rule rather than reimplementing
        # either
        from duplexumiconsensusreads_tpu.runtime.stream import (
            _complete_prefix,
            _io_retry,
        )

        try:
            with open(self.path, "rb") as f:
                pending = b""
                # rolling last-28-admitted-bytes window: the EOF block
                # is itself a complete BGZF block, so it is admitted
                # like any other and detected here, after the boundary
                # cut (has_eof_block is the single definition of
                # "finished" shared with the batch reader and merger)
                tail = b""
                last_growth = time.monotonic()
                while not self._closed.is_set():
                    data = _io_retry(
                        "live.poll", self._read_poll, "live tail poll", f
                    )
                    if data:
                        pending += data
                        last_growth = time.monotonic()
                        off = _complete_prefix(pending)
                        if off:
                            admit = bytes(pending[:off])
                            pending = pending[off:]
                            tail = (tail + admit)[-len(bgzf.BGZF_EOF):]
                            with self._lock:
                                self._admitted += len(admit)
                            self._put(admit)
                        if (
                            self.mode == "eof"
                            and not pending
                            and bgzf.has_eof_block(tail)
                        ):
                            self._put(("done", "eof"))
                            return
                        continue
                    # the read caught up with the writer
                    if (
                        self.mode == "eof"
                        and not pending
                        and bgzf.has_eof_block(tail)
                    ):
                        self._put(("done", "eof"))
                        return
                    if self.is_fifo:
                        # EOF on a pipe is definitive: the writer closed
                        # its end and the stream can never grow again
                        self._finish_drained(pending, "writer closed pipe")
                        return
                    if self.mode == "marker" and os.path.exists(
                        self.path + ".done"
                    ):
                        self._finish_drained(pending, "marker present")
                        return
                    if (
                        self.mode == "idle"
                        and time.monotonic() - last_growth >= self.idle_s
                    ):
                        self._finish_drained(
                            pending, f"idle {self.idle_s:g}s"
                        )
                        return
                    t0 = time.monotonic()
                    self._closed.wait(self.poll_s)
                    with self._lock:
                        self._poll_seconds += time.monotonic() - t0
        except BaseException as e:  # noqa: BLE001 — forwards InjectedKill
            self._put(("err", e))

    # ------------------------------------------------- consumer side

    def read(self, n: int = -1) -> bytes:
        """Blocking read of up to ``n`` admitted bytes; b"" only at the
        true end of the followed stream."""
        while not self._buf and not self._finished:
            if self._err is not None:
                raise self._err
            t0 = time.monotonic()
            try:
                item = self._q.get(timeout=_BLOCK_TICK_S)
            except queue.Empty:
                item = None
            with self._lock:
                self._wait_seconds += time.monotonic() - t0
            if item is None:
                continue
            if isinstance(item, tuple):
                kind, payload = item
                if kind == "err":
                    # sticky: the reader's own retry ladder re-reads,
                    # and every attempt must see the same failure
                    self._err = payload
                    raise payload
                self._finished = True
                self.finish_reason = payload
            else:
                self._buf += item
        if not self._buf:
            return b""
        if n is None or n < 0:
            n = len(self._buf)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        self._pos += len(out)
        return out

    def tell(self) -> int:
        return self._pos

    def seek(self, pos: int, whence: int = 0) -> int:
        # the stream reader's retry ladder re-seeks to the position it
        # captured before the read — always the current one. Anything
        # else is a logic error: a growing input has no random access
        if whence != 0 or pos != self._pos:
            raise ValueError(
                f"TailSource is forward-only: cannot seek to {pos} "
                f"(at {self._pos})"
            )
        return self._pos

    def admitted_bytes(self) -> int:
        """Bytes released past the complete-block boundary so far."""
        with self._lock:
            return self._admitted

    def take_phase_seconds(self):
        """Drain ``(poll_s, wait_s)`` accumulated since the last call.

        The executor folds these into its phase ledger (``live_poll``,
        ``live_wait``) at chunk boundaries — pull-based on purpose, so
        the tailer thread never touches stream.py's shared state.
        """
        with self._lock:
            p, w = self._poll_seconds, self._wait_seconds
            self._poll_seconds = 0.0
            self._wait_seconds = 0.0
        return p, w

    def close(self) -> None:
        self._closed.set()
        self._thread.join(timeout=5.0)
