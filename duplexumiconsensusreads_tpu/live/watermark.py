"""The durable admission watermark: a follow run's pinned identity.

The checkpoint fingerprint keys the input's ``(size, mtime)`` so a
resume refuses to splice shards computed over a different file. A
growing input changes both every poll — under the batch rule a
follower killed mid-tail could never accept its own checkpoint. The
watermark (``<out>.livemark``, the tmp-write protocol like every other
durable artifact) pins a ``stat_sig`` token at follow-run start; the
fingerprint substitutes it for the size/mtime pair, so kill/resume
mid-tail converges exactly once while two *different* follow runs
still get distinct fingerprints (the token is random per creation).

Same-input evidence on resume is the head CRC: the first 64 KiB of a
coordinate-sorted BAM (header + first reads) is already on disk when
the watermark is created and never changes as the file grows. A
mismatch means the path was reused for a different run — the mark is
discarded, the fingerprint changes, and the stale checkpoint is
rejected exactly as the batch rule would have done. FIFOs have no
re-readable head (and no re-readable anything): resuming a follow run
over a pipe is refused outright.

The mark also carries ``snapshot_seq`` so a resumed follower continues
the published-snapshot series instead of restarting it, and
``admitted_bytes`` as a progress breadcrumb for operators.

Persistence discipline: only the executor's main loop writes the mark
(watermark saves are durable moves, and the ``live-tail`` role's grant
set is empty — see THREAD_ROLES).
"""

from __future__ import annotations

import json
import os
import stat
import zlib

# head-signature window: comfortably covers the BAM header plus the
# first records for any realistic reference set, tiny to hash
_HEAD_BYTES = 64 << 10


def mark_path(out_path: str) -> str:
    return out_path + ".livemark"


def _head_crc(in_path: str) -> int:
    with open(in_path, "rb") as f:
        return zlib.crc32(f.read(_HEAD_BYTES)) & 0xFFFFFFFF


def load(out_path: str):
    """The persisted mark, or None when absent/unreadable (an
    unreadable mark is treated as no mark: the run re-pins and the
    fingerprint change invalidates any stale checkpoint)."""
    try:
        with open(mark_path(out_path), encoding="utf-8") as f:
            mark = json.load(f)
    except (OSError, ValueError):
        return None
    return mark if isinstance(mark, dict) else None


def load_or_create(out_path: str, in_path: str, resume: bool = True) -> dict:
    """The follow-run identity for this (output, input) pair.

    ``resume=True`` reuses an existing mark when it names the same
    input with the same head signature; anything else — no mark, a
    different input, a rewritten head, ``resume=False`` — pins a fresh
    ``stat_sig`` and persists it before any chunk is read.
    """
    st = os.stat(in_path)
    fifo = stat.S_ISFIFO(st.st_mode)
    head = None if fifo else _head_crc(in_path)
    abspath = os.path.abspath(in_path)
    if resume:
        mark = load(out_path)
        if mark is not None and mark.get("input") == abspath:
            if fifo:
                raise ValueError(
                    f"{in_path}: cannot resume a follow run over a FIFO "
                    f"— the consumed bytes are gone; restart with a "
                    f"fresh output path"
                )
            if mark.get("head_crc") == head:
                return mark
    mark = {
        "input": abspath,
        "head_crc": head,
        "stat_sig": os.urandom(8).hex(),
        "snapshot_seq": 0,
        "admitted_bytes": 0,
    }
    save(out_path, mark)
    return mark


def save(out_path: str, mark: dict) -> None:
    from duplexumiconsensusreads_tpu.io.durable import write_durable

    write_durable(
        mark_path(out_path),
        (json.dumps(mark, sort_keys=True) + "\n").encode(),
    )


def clear(out_path: str) -> None:
    """Remove the mark (terminal finalise: the follow run is now just
    a finished output and must resume like one)."""
    try:
        os.remove(mark_path(out_path))
    except OSError:
        pass
