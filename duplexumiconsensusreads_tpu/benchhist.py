"""Bench-trajectory analysis: the driver's BENCH_r0N.json files as a
time series, and the regression gate over it.

The driver records each round as ``{"n", "cmd", "rc", "tail",
"parsed"}`` where ``tail`` is a BOUNDED suffix (~2000 bytes) of the
bench's merged stdout+stderr and ``parsed`` is the JSON line it could
recover from that window. Two failure modes have already happened to
this trajectory:

  * r5: the bench's single result line grew past the tail window, so
    its head fell off and ``parsed`` is null — the round's canonical
    metrics survive only as a truncated JSON FRAGMENT in the tail.
    :func:`salvage_metrics` recovers every scalar ``"key": value``
    pair from such fragments, so r5 still contributes its floor/AB/CPU
    numbers to the trajectory instead of reading as a gap.
  * the fix going forward (benchmark.main): the LAST stdout line is
    now a compact canonical summary guaranteed to fit the window, with
    the full result printed on the line above and mirrored to
    ``<cache>/bench_full.json``.

The gate (:func:`check_regression`): compare each canonical metric's
latest reading against the previous round that measured it; a drop
beyond the threshold exits 1 through ``tools/bench_history.py
--check`` — the bench stops being a diary. The default threshold is
deliberately loose (50%): the tunnel's wire varies ~3x intra-day (r4),
and a gate that cries weather trains everyone to ignore it; it exists
to catch the r5 class of regression (a metric silently halving or
vanishing), not 10% noise.
"""

from __future__ import annotations

import json
import os
import re

# canonical trajectory metrics, in display order: (key, higher_is_better,
# gate) — `gate` marks the metrics --check defends by default. Keys
# match the bench JSON (compact line and full result alike).
CANONICAL_METRICS = (
    ("value", True, True),  # device-compute reads/s (the headline)
    ("mfu", True, False),
    ("e2e_reads_per_sec", True, True),
    ("e2e_wall_s", False, False),
    # device ledger (telemetry/devledger.py): e2e MFU measured from the
    # capture's own dev records, and the fraction of the measured
    # roofline the run attained — informational, never gated (both
    # follow tunnel weather and sim-device sharing on CPU legs)
    ("e2e_mfu", True, False),
    ("e2e_roofline_frac", True, False),
    ("e2e_wire_floor_frac", False, False),
    ("e2e_wire_floor_frac_measured", False, False),
    ("e2e_bytes_per_read", False, False),
    ("e2e_packed_speedup", True, False),
    # wire diet v2 (PR 11): what the packed consensus-only return path
    # buys on its own, the H2D rung the canonical leg actually ran
    # (16/8/7/5 bits per cycle), and the bounded prefetch window —
    # informational, never gated (rung choice follows the input's qual
    # alphabet; depth is a config echo)
    ("e2e_d2h_packed_speedup", True, False),
    ("e2e_h2d_bits_per_cycle", False, False),
    ("e2e_prefetch_depth", False, False),
    # bucket auto-tuner (PR 13): measured fill of the long-tail fixture
    # under the auto verdict and the verdict's cost-model ratio —
    # informational, never gated (shape decisions follow the input mix)
    ("e2e_fill_factor", True, False),
    ("tuner_predicted_speedup", True, False),
    ("e2e_vs_cpu_e2e", True, False),
    ("serve_amortised_speedup", True, False),
    # defensive serving (PR 9): quarantine depth should sit AT the
    # max_crashes bound (lower = gave up early, higher = re-ran poison)
    # and watchdog latency is a detection cost — informational only,
    # never gated (they characterise defense policy, not throughput)
    ("serve_quarantine_after_crashes", False, False),
    ("serve_watchdog_detect_latency_s", False, False),
    # fleet flight recorder (tools/fleet_report.py): e2e p95 and the
    # takeover recovery gap measured from the serve_fleet leg's OWN
    # stitched captures — informational, never gated (single-host
    # in-process fleets measure scheduling, not production latency)
    ("fleet_e2e_p95_s", False, False),
    ("fleet_takeover_gap_s", False, False),
    # scatter-gather sharding (serve/shard/): single-host fleets share
    # one device, so the K=4/K=1 ratio characterises scheduling +
    # pipeline-overlap headroom, not device scaling — informational,
    # never gated
    ("serve_shard_speedup", True, False),
    ("serve_shard_merge_s", False, False),
    # cross-host fleet (sharedfs lease store): takeover latency is
    # lease-expiry-dominated by design (pid-free detection waits out
    # the translated lease, never probes a pid) and the recovery count
    # is a scenario invariant — informational, never gated
    ("serve_xhost_takeover_latency_s", False, False),
    ("serve_xhost_recovered", True, False),
    # mesh-sharded execution (real multi-device consensus): the e2e
    # leg's resolved device count and the K-vs-1 wall ratio of the
    # mesh-scaling A/B — informational, never gated (simulated CPU
    # devices share the host's cores; judge scaling on real silicon)
    ("e2e_mesh_devices", False, False),
    ("e2e_mesh_scaling", True, False),
    # live follow-mode (live/): first-snapshot latency and steady lag
    # behind the paced synthetic writer — informational, never gated
    # (both numbers follow the writer's slab cadence, not the pipeline)
    ("live_first_snapshot_latency_s", False, False),
    ("live_steady_lag_chunks", False, False),
)

_NUM = r"-?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?"
# `"key": 1.5`, `"key": [1.5, 2.5]` — the scalar shapes the canonical
# metrics use; strings/objects are context, not trajectory data
_PAIR_RE = re.compile(
    rf'"([A-Za-z0-9_]+)":\s*({_NUM}|\[\s*{_NUM}(?:\s*,\s*{_NUM})*\s*\])'
)


def salvage_metrics(tail: str) -> dict:
    """Recover numeric ``"key": value`` pairs from a bounded tail whose
    JSON line may be truncated at the HEAD (the r5 failure). Whole
    parseable JSON lines win over fragment scans; within fragments the
    last occurrence of a key wins (later lines are later output)."""
    out: dict = {}
    for line in tail.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            d = json.loads(line)
        except ValueError:
            d = None
        if isinstance(d, dict):
            out.update(d)
            continue
        if '"' not in line:
            continue
        for key, val in _PAIR_RE.findall(line):
            try:
                out[key] = json.loads(val)
            except ValueError:
                continue
    return out


def _metric_value(d: dict, key: str):
    """One representative float for a metric, or None. List values
    (the probe-bracketed floor fracs like [0.63, 0.72]) read as their
    midpoint — a single trajectory needs a single number."""
    v = d.get(key)
    if isinstance(v, bool):
        return None
    if isinstance(v, (int, float)):
        return float(v)
    if (
        isinstance(v, list)
        and v
        and all(isinstance(x, (int, float)) and not isinstance(x, bool)
                for x in v)
    ):
        return round(sum(float(x) for x in v) / len(v), 6)
    return None


def load_round(path: str) -> dict:
    """One BENCH_r0N.json -> {"name", "path", "metrics", "salvaged",
    "rc"}. ``metrics`` comes from ``parsed`` when the driver recovered
    it, else from the tail salvage; a bench RESULT json (no tail — the
    --candidate form) is used as-is."""
    with open(path) as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a JSON object")
    name = os.path.basename(path)
    name = re.sub(r"^BENCH_|\.json$", "", name)
    if "tail" in doc or "parsed" in doc:
        parsed = doc.get("parsed")
        salvaged = not isinstance(parsed, dict)
        metrics = (
            dict(parsed) if isinstance(parsed, dict)
            else salvage_metrics(str(doc.get("tail") or ""))
        )
        rc = doc.get("rc")
    else:
        metrics, salvaged, rc = dict(doc), False, None
    return {
        "name": name, "path": path, "metrics": metrics,
        "salvaged": salvaged, "rc": rc,
    }


def default_paths(root: str = ".") -> list[str]:
    """The driver's trajectory files next to the repo root, in round
    order (their zero-padded names sort correctly)."""
    import glob

    return sorted(glob.glob(os.path.join(root, "BENCH_r[0-9]*.json")))


def trajectory(rounds: list[dict]) -> dict:
    """The canonical-metric table: per metric, the per-round readings
    and the delta (%) between the last two rounds that measured it."""
    out: dict = {"rounds": [r["name"] for r in rounds], "metrics": {}}
    for key, higher, gate in CANONICAL_METRICS:
        vals = [_metric_value(r["metrics"], key) for r in rounds]
        present = [(i, v) for i, v in enumerate(vals) if v is not None]
        row = {"values": vals, "higher_is_better": higher, "gate": gate}
        if len(present) >= 2:
            (_, prev), (_, last) = present[-2], present[-1]
            row["delta_pct"] = (
                round((last - prev) / abs(prev) * 100, 1) if prev else None
            )
        out["metrics"][key] = row
    return out


def check_regression(
    rounds: list[dict],
    threshold: float = 0.5,
    metrics: list[str] | None = None,
) -> tuple[bool, list[str]]:
    """The gate: for each gate metric, the NEWEST round's reading must
    not regress beyond ``threshold`` (fractional, on the
    better-direction axis) against the previous round that measured
    it. A metric the newest round did not measure is SKIPPED entirely
    — a tiny smoke bench must not fail the gate for not running the
    e2e leg, and the gate must never re-litigate a regression between
    two HISTORICAL rounds the current run had no part in (the r3→r4
    e2e weather dip is recorded fact, not this run's fault). The r5
    parse hole itself is caught by the driver's parsed being null
    (salvage keeps the trajectory, the new last-line contract keeps
    r6+ parseable)."""
    if not (0 < threshold):
        raise ValueError(f"threshold must be > 0 (got {threshold})")
    gate_keys = metrics or [k for k, _, g in CANONICAL_METRICS if g]
    directions = {k: h for k, h, _ in CANONICAL_METRICS}
    problems: list[str] = []
    for key in gate_keys:
        higher = directions.get(key, True)
        readings = [
            (r["name"], _metric_value(r["metrics"], key)) for r in rounds
        ]
        if not readings or readings[-1][1] is None:
            continue  # the round under judgment didn't measure this
        present = [(n, v) for n, v in readings if v is not None]
        if len(present) < 2:
            continue
        (prev_name, prev), (last_name, last) = present[-2], present[-1]
        if prev == 0:
            continue
        drop = (prev - last) / abs(prev) if higher else (last - prev) / abs(prev)
        if drop > threshold:
            problems.append(
                f"{key}: {last_name} = {last:g} regressed "
                f"{drop * 100:.0f}% vs {prev_name} = {prev:g} "
                f"(threshold {threshold * 100:.0f}%)"
            )
    return not problems, problems


def render_table(rounds: list[dict]) -> list[str]:
    traj = trajectory(rounds)
    names = traj["rounds"]
    lines = []
    lines.append(
        f"{'metric':<30} " + " ".join(f"{n:>10}" for n in names)
        + f" {'Δ last':>8}"
    )
    for key, row in traj["metrics"].items():
        if all(v is None for v in row["values"]):
            continue
        cells = " ".join(
            f"{v:>10g}" if v is not None else f"{'-':>10}"
            for v in row["values"]
        )
        delta = row.get("delta_pct")
        dtxt = f"{delta:+.1f}%" if delta is not None else "-"
        lines.append(f"{key:<30} {cells} {dtxt:>8}")
    salvaged = [r["name"] for r in rounds if r["salvaged"]]
    if salvaged:
        lines.append(
            f"(salvaged from truncated tails: {', '.join(salvaged)} — "
            f"metrics recovered per-key, absent keys read as '-')"
        )
    return lines
