"""ConsensusCaller — the preserved operator boundary, consensus stage.

backend="cpu": NumPy oracle with the two-pass error-model flow.
backend="tpu": JAX kernels (ssc one-hot-matmul GEMM, duplex merge,
per-cycle error model), composed but NOT fused across the operator
boundary — use ops.pipeline for the fully-fused single-jit path the
north-star prescribes; this class exists for operator-level parity
with the reference API.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.kernels.consensus import duplex_kernel, ssc_kernel
from duplexumiconsensusreads_tpu.kernels.error_model import (
    apply_cycle_cap,
    fit_cycle_cap_kernel,
)
from duplexumiconsensusreads_tpu.oracle.consensus import call_consensus as _oracle_call
from duplexumiconsensusreads_tpu.oracle.error_model import (
    apply_cycle_error_model,
    fit_cycle_error_model,
)
from duplexumiconsensusreads_tpu.types import (
    ConsensusBatch,
    ConsensusParams,
    FamilyAssignment,
    ReadBatch,
)


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


class ConsensusCaller:
    def __init__(
        self,
        params: ConsensusParams | None = None,
        backend: str = "tpu",
        method: str = "matmul",
    ):
        self.params = params or ConsensusParams()
        if backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.method = method

    def __call__(self, batch: ReadBatch, fams: FamilyAssignment) -> ConsensusBatch:
        if self.backend == "cpu":
            return self._call_cpu(batch, fams)
        return self._call_tpu(batch, fams)

    def _call_cpu(self, batch, fams):
        p = self.params
        if p.error_model == "cycle":
            import dataclasses

            ss = _oracle_call(
                batch,
                fams,
                dataclasses.replace(p, mode="single_strand", error_model=None),
            )
            cap = fit_cycle_error_model(batch, fams, ss)
            q2 = apply_cycle_error_model(np.asarray(batch.quals), cap)
            return _oracle_call(batch, fams, p, quals_override=q2)
        return _oracle_call(batch, fams, p)

    def _call_tpu(self, batch, fams):
        p = self.params
        bases = np.asarray(batch.bases)
        quals = np.asarray(batch.quals)
        valid = np.asarray(batch.valid)
        fam = np.asarray(fams.family_id)
        # Family axis sized from the actual family count (known host-side
        # at this operator boundary), rounded to a power of two so jit
        # recompiles O(log N) times, not per batch. Padding to n_reads
        # would make the one-hot GEMM quadratic in batch size.
        f_max = _pow2(int(fams.n_families))

        def ssc(q):
            return ssc_kernel(
                bases,
                q,
                fam,
                valid,
                f_max=f_max,
                min_reads=p.min_reads,
                max_qual=p.max_qual,
                max_input_qual=p.max_input_qual,
                min_input_qual=p.min_input_qual,
                method=self.method,
            )

        quals_eff = quals
        if p.error_model == "cycle":
            cb0, _, _, _, fv0 = ssc(quals)
            cap = fit_cycle_cap_kernel(bases, fam, valid, cb0, fv0)
            quals_eff = apply_cycle_cap(quals, cap)
        cb, cq, dep, size, fv = ssc(quals_eff)

        if p.mode == "single_strand":
            n_fam = int(fams.n_families)
            return ConsensusBatch(
                bases=np.asarray(cb)[:n_fam].astype(np.uint8),
                quals=np.asarray(cq)[:n_fam].astype(np.uint8),
                depth=np.asarray(dep)[:n_fam],
                valid=np.asarray(fv)[:n_fam],
            )
        if p.mode != "duplex":
            raise ValueError(f"unknown consensus mode {p.mode!r}")

        db, dq, dd, dv = duplex_kernel(
            cb,
            cq,
            dep,
            fv,
            fam,
            np.asarray(fams.molecule_id),
            np.asarray(batch.strand_ab),
            valid,
            m_max=_pow2(int(fams.n_molecules)),
            min_duplex_reads=p.min_duplex_reads,
            max_qual=p.max_qual,
        )
        n_mol = int(fams.n_molecules)
        return ConsensusBatch(
            bases=np.asarray(db)[:n_mol].astype(np.uint8),
            quals=np.asarray(dq)[:n_mol].astype(np.uint8),
            depth=np.asarray(dd)[:n_mol],
            valid=np.asarray(dv)[:n_mol],
        )
