"""UmiGrouper — the preserved operator boundary, grouping stage.

Matches the reference's operator contract (BASELINE.json north_star:
"the existing UmiGrouper / ConsensusCaller operator boundary stays
intact; only the backend swaps"): same inputs/outputs on both backends.

backend="cpu": NumPy oracle (also the correctness reference).
backend="tpu": fused JAX kernel (kernels/grouping.py) — device sort,
MXU Hamming adjacency, transitive-closure clustering.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.oracle.grouping import group_reads as _oracle_group
from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel
from duplexumiconsensusreads_tpu.types import FamilyAssignment, GroupingParams, ReadBatch


def dense_pos_ids(pos_key: np.ndarray) -> np.ndarray:
    """Host int64 genomic keys -> bucket-local dense i32 ids (sorted order
    preserving, so device grouping emits ids in the same order as the
    oracle's int64 sort)."""
    _, inv = np.unique(np.asarray(pos_key), return_inverse=True)
    return inv.astype(np.int32)


class UmiGrouper:
    def __init__(
        self,
        params: GroupingParams | None = None,
        backend: str = "tpu",
        u_max: int | None = None,
    ):
        self.params = params or GroupingParams()
        if backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.u_max = u_max

    def __call__(self, batch: ReadBatch) -> FamilyAssignment:
        if self.backend == "cpu":
            return _oracle_group(batch, self.params)
        p = self.params
        u_max = self.u_max
        if u_max is None and p.strategy == "adjacency":
            # Size the unique-UMI table from the data (cheap host count,
            # rounded to a power of two to bound recompiles) instead of
            # defaulting to n_reads, which would make the all-pairs
            # Hamming/reachability matrices quadratic in batch size.
            from duplexumiconsensusreads_tpu.utils.phred import pack_umi

            valid = np.asarray(batch.valid, bool)
            key = np.stack(
                [
                    np.asarray(batch.pos_key)[valid],
                    pack_umi(np.asarray(batch.umi)[valid]),
                ],
                axis=1,
            )
            n_unique = max(len(np.unique(key, axis=0)), 1)
            u_max = 1 << (n_unique - 1).bit_length()
        fam, mol, n_fam, n_mol, n_over = group_kernel(
            dense_pos_ids(batch.pos_key),
            np.asarray(batch.umi),
            np.asarray(batch.strand_ab),
            np.asarray(batch.valid),
            strategy=p.strategy,
            max_hamming=p.max_hamming,
            count_ratio=p.count_ratio,
            paired=p.paired,
            u_max=u_max,
        )
        if int(n_over):
            import warnings

            warnings.warn(
                f"UmiGrouper: {int(n_over)} reads overflowed the unique-UMI "
                f"table (u_max={self.u_max}); size buckets larger or raise u_max"
            )
        return FamilyAssignment(
            family_id=fam, molecule_id=mol, n_families=n_fam, n_molecules=n_mol
        )
