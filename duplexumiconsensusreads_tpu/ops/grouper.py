"""UmiGrouper — the preserved operator boundary, grouping stage.

Matches the reference's operator contract (BASELINE.json north_star:
"the existing UmiGrouper / ConsensusCaller operator boundary stays
intact; only the backend swaps"): same inputs/outputs on both backends.

backend="cpu": NumPy oracle (also the correctness reference).
backend="tpu": fused JAX kernel (kernels/grouping.py) — device sort,
MXU Hamming adjacency, transitive-closure clustering.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.oracle.grouping import group_reads as _oracle_group
from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel
from duplexumiconsensusreads_tpu.types import FamilyAssignment, GroupingParams, ReadBatch


def dense_pos_ids(pos_key: np.ndarray) -> np.ndarray:
    """Host int64 genomic keys -> bucket-local dense i32 ids (sorted order
    preserving, so device grouping emits ids in the same order as the
    oracle's int64 sort)."""
    _, inv = np.unique(np.asarray(pos_key), return_inverse=True)
    return inv.astype(np.int32)


class UmiGrouper:
    def __init__(
        self,
        params: GroupingParams | None = None,
        backend: str = "tpu",
        u_max: int | None = None,
    ):
        self.params = params or GroupingParams()
        if backend not in ("cpu", "tpu"):
            raise ValueError(f"unknown backend {backend!r}")
        self.backend = backend
        self.u_max = u_max

    def __call__(self, batch: ReadBatch) -> FamilyAssignment:
        if self.backend == "cpu":
            return _oracle_group(batch, self.params)
        from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64

        p = self.params
        valid_arr = np.asarray(batch.valid, bool)
        # multi-word packing handles any UMI length (int64 pack caps at
        # 31 codes — real duplex pairs can exceed that); computed once
        # and shared by the u_max sizing and the presort below
        words = pack_umi_words64(np.asarray(batch.umi))
        words[~valid_arr] = 0
        u_max = self.u_max
        if u_max is None and p.strategy in ("adjacency", "cluster"):
            # Size the unique-UMI table from the data (cheap host count,
            # rounded to a power of two to bound recompiles) instead of
            # defaulting to n_reads, which would make the all-pairs
            # Hamming/reachability matrices quadratic in batch size.
            key = np.column_stack(
                [np.asarray(batch.pos_key)[valid_arr], words[valid_arr]]
            )
            n_unique = max(len(np.unique(key, axis=0)), 1)
            u_max = 1 << (n_unique - 1).bit_length()
        # host presort (cheap NumPy lexsort, invalid reads to the tail)
        # so the device kernel runs its sort-free presorted path — the
        # same contract bucketing provides the fused pipeline
        w = words.shape[1]
        order = np.lexsort(
            (
                *[words[:, i] for i in range(w - 1, -1, -1)],
                np.asarray(batch.pos_key),
                ~valid_arr,
            )
        )
        inv = np.empty_like(order)
        inv[order] = np.arange(len(order))
        fam_s, mol_s, pair_s, n_fam, n_mol, n_over = group_kernel(
            dense_pos_ids(batch.pos_key)[order],
            np.asarray(batch.umi)[order],
            np.asarray(batch.strand_ab)[order],
            np.asarray(batch.frag_end)[order],
            valid_arr[order],
            strategy=p.strategy,
            max_hamming=p.max_hamming,
            count_ratio=p.effective_count_ratio,
            paired=p.paired,
            mate_aware=p.mate_aware,
            u_max=u_max,
            presorted=True,
        )
        fam = np.asarray(fam_s)[inv]
        mol = np.asarray(mol_s)[inv]
        pair = np.asarray(pair_s)[inv]
        if int(n_over):
            import warnings

            warnings.warn(
                f"UmiGrouper: {int(n_over)} reads overflowed the unique-UMI "
                f"table (u_max={self.u_max}); size buckets larger or raise u_max"
            )
        return FamilyAssignment(
            family_id=fam, molecule_id=mol, pair_id=pair,
            n_families=n_fam, n_molecules=n_mol,
        )
