from duplexumiconsensusreads_tpu.ops.grouper import UmiGrouper  # noqa: F401
from duplexumiconsensusreads_tpu.ops.caller import ConsensusCaller  # noqa: F401
from duplexumiconsensusreads_tpu.ops.pipeline import (  # noqa: F401
    PipelineSpec,
    fused_pipeline,
    run_bucket,
    spec_for_buckets,
)
