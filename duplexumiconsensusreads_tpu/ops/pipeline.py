"""Fully-fused device pipeline: grouping → ssc → error model → duplex
in ONE jitted call, as the north-star prescribes (BASELINE.json:
"grouping + consensus + duplex reconciliation + error model fused into
one vmap'd call").

The fused function is shape-static over a bucket spec (R reads, L
cycles, B umi bases, u_max unique-UMI slots) so XLA compiles it once
per bucket geometry; host bucketing (bucketing/) guarantees every
bucket fits the spec. The same function is the unit that
parallel/sharded.py maps over the device mesh (config 4).

Bucket LADDERS (bucketing/ ``ladder=``, tuning/ auto-tuner) need no
special casing here: each rung is just another bucket capacity, so
``partition_buckets`` keys a dispatch class per (rung, preclustered,
unique-count) and ``spec_for_buckets`` sizes that class's u_max/f_max/
m_max from its OWN buckets — the grouping invariant that bounds f_max
and the packed-D2H k_pad therefore holds per rung by construction, and
the jit cache absorbs each rung's spec exactly like a jumbo class's.
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp

from duplexumiconsensusreads_tpu.kernels.consensus import (
    duplex_kernel,
    duplex_merge_strided,
    ssc_kernel,
)
from duplexumiconsensusreads_tpu.kernels.error_model import (
    apply_cycle_cap,
    fit_cycle_cap_from_counts,
    fit_cycle_cap_kernel,
)
from duplexumiconsensusreads_tpu.kernels.grouping import group_kernel
from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams


@dataclasses.dataclass(frozen=True)
class PipelineSpec:
    """Static geometry + algorithm config of one fused pipeline compile.

    Hashable → usable as a jit static argument. u_max/f_max/m_max
    default to the read capacity R (worst case: every read its own
    family) — use spec_for_buckets() to size them from the data, which
    is where most of the device FLOPs are saved.
    """

    grouping: GroupingParams = GroupingParams()
    consensus: ConsensusParams = ConsensusParams()
    u_max: int | None = None  # unique-UMI table slots (adjacency mode)
    f_max: int | None = None  # family-axis rows for the ssc reduction
    m_max: int | None = None  # molecule-axis rows for the duplex merge
    ssc_method: str = "matmul"
    # blockseg tile height (rows per block-local GEMM) — only used when
    # ssc_method == "blockseg"; spec-level so tools/tune_ssc.py can
    # sweep it without monkey-patching a module constant
    blockseg_t: int = 128
    # True asserts reads are sorted by (pos, UMI) with padding at the
    # tail — the bucketing layer's output contract — letting the device
    # kernel skip its (expensive) sorts. spec_for_buckets() sets it;
    # the conservative default matches fused_pipeline's original
    # any-order contract.
    presorted: bool = False
    # True: the wire-optimized input convention (pack_stacked below) —
    # ``bases`` carries base|qual packed one byte per cycle, ``umi``
    # 2-bit codes four-per-byte, ``pos`` u16, and ``strand_ab`` a
    # strand|frag_end|valid flag byte (frag_end/quals/valid become
    # zero-width dummies). Decoding is fused into the first consumers
    # on device. Exact whenever max_input_qual <= PACKED_QUAL_MAX (the
    # executors check before enabling); host->device transfer is the
    # dominant streaming phase on tunneled chips, and the non-base
    # fields were the remaining ~17% of wire bytes after base|qual
    # packing (r4: the SURVEY packing ladder, completed).
    packed_io: bool = False
    # true UMI code count, required to un-pack the 2-bit umi bytes
    # (static — the packed width ceil(U/4)*4 over-covers)
    umi_len: int | None = None
    # Sub-byte H2D rung (the next SURVEY-ladder rung past one byte per
    # cycle): qual-DICTIONARY packing. The host scans the chunk's real
    # input-qual alphabet; when it fits 2**packed_qbits - 1 entries the
    # per-cycle code is base (2 bits) | dictionary index (packed_qbits),
    # bit-plane packed to 2 + packed_qbits bits/cycle (5 at qbits=3 for
    # RTA-binned instruments, 7 at qbits=5), with the all-ones index
    # reserved as the non-evidence marker. Lossless for ANY qual values
    # (the dictionary carries them verbatim — no 6-bit clip), so this
    # rung needs no max_input_qual gate; alphabet overflow falls back
    # to the byte rung per chunk (the packed_io_ok gate generalised to
    # a per-chunk decision, recorded in the byte ledger).
    # None = byte rung (packed_io semantics unchanged).
    packed_qbits: int | None = None
    # the dictionary itself: sorted tuple of the distinct real-cycle
    # input quals (static — alphabets are stable per instrument, so the
    # jit cache absorbs it like any other spec field)
    qual_lut: tuple | None = None
    # true cycle count L, required to slice the bit-plane decode (the
    # packed width nbits*ceil(L/8)*8 over-covers)
    cycles_len: int | None = None
    # True: also compute per-base disagreement counts (the ce tag) —
    # widens the ssc reduction by 4L count columns, so opt-in
    # (--per-base-tags runs only).
    per_base_counts: bool = False
    # error-model pass-1 fit formulation: "gather" re-visits read space
    # with the (R, L) consensus row-gather; "counts" tallies mismatches
    # family-side from 4L extra GEMM columns (zero gathers). Both exact.
    # Measured in-pipeline on v5e (2x each, interleaved): gather 164.4 ms
    # vs counts 170.0 ms full step — the gather fuses into the fused
    # pipeline (which CSEs the one-hot family matrix across passes)
    # better than the GEMM widening pays; standalone the order flips
    # (84 vs 87 ms), which is why only in-pipeline numbers decide.
    # Journal: tools/tune_ssc.py.
    fit_impl: str = "gather"

    def __post_init__(self):
        if self.consensus.mode == "duplex" and not self.grouping.paired:
            raise ValueError(
                "duplex consensus requires paired grouping "
                "(GroupingParams(paired=True))"
            )


def _pow2(n: int) -> int:
    return 1 << max(n - 1, 0).bit_length()


# packed byte layout: base code (2 bits) | qual << 2 (6 bits); 0xFF
# marks a non-evidence cycle (N base or padding). Quals clip at 62 —
# lossless whenever the consensus input cap max_input_qual <= 62,
# since the kernel clips quals there anyway.
PACKED_QUAL_MAX = 62
PACKED_NONE = 255

# sub-byte rung dictionary widths, smallest first: 3 index bits cover
# the <= 7-value alphabets RTA-binned instruments emit (5 bits/cycle);
# 5 bits cover <= 31 values (7 bits/cycle) for wider real-world
# alphabets. One index pattern (all ones) is reserved per width as the
# non-evidence marker, hence capacity 2**qbits - 1.
SUBBYTE_QBITS = (3, 5)


def subbyte_qbits_for(alphabet_size: int) -> int | None:
    """Smallest dictionary width whose capacity covers the alphabet, or
    None (overflow -> byte-rung fallback)."""
    for qbits in SUBBYTE_QBITS:
        if alphabet_size <= (1 << qbits) - 1:
            return qbits
    return None


def qual_alphabet(buckets) -> tuple:
    """Sorted distinct input quals at REAL base cycles of valid reads
    across ``buckets`` — the chunk's qual alphabet, scanned once per
    chunk and shared by every dispatch class. Non-evidence cycles
    (N/PAD bases, invalid rows) are excluded: they pack as the NONE
    marker and must not burn dictionary slots."""
    import numpy as np

    seen = np.zeros(256, bool)
    for bk in buckets:
        sel = (np.asarray(bk.bases) < 4) & np.asarray(bk.valid, bool)[:, None]
        seen[np.asarray(bk.quals)[sel]] = True
    return tuple(int(q) for q in np.nonzero(seen)[0])


def pack_base_qual(bases: "np.ndarray", quals: "np.ndarray"):
    """Host-side pack of (.., L) u8 base codes + quals into one byte per
    cycle (numpy in, numpy out)."""
    import numpy as np

    real = bases < 4
    return np.where(
        real,
        bases | (np.minimum(quals, PACKED_QUAL_MAX).astype(np.uint8) << 2),
        np.uint8(PACKED_NONE),
    ).astype(np.uint8)


def pack_stacked(stacked: dict, spec: "PipelineSpec | None" = None) -> dict:
    """Apply the packed-io convention to a stacked bucket dict IN PLACE
    (the host side of spec.packed_io — fused_pipeline decodes):

      bases      byte rung: base|qual, one byte per cycle
                 (pack_base_qual); sub-byte rung (spec.packed_qbits):
                 base (2 bits) | qual-dictionary index (qbits),
                 bit-plane packed to 2+qbits bits per cycle
      umi        2-bit codes, four per byte
      pos        u16 (bucket-local dense ids < capacity — the
                 executors gate oversized classes at partition time,
                 so the check here is a defensive backstop)
      strand_ab  strand | frag_end<<1 | valid<<2 flag byte
      quals/frag_end/valid  zero-width dummies

    Shared by the whole-file and streaming executors so the convention
    can never desync. Everything is lossless: the byte rung clips quals
    at PACKED_QUAL_MAX (gated by the executors' packed_io_ok check);
    the sub-byte rung carries the exact quals in spec.qual_lut.
    ``spec=None`` keeps the original byte-rung-only behaviour."""
    import numpy as np

    if spec is not None and spec.packed_qbits:
        qbits = spec.packed_qbits
        lut = np.asarray(spec.qual_lut, np.uint8)
        nbits = 2 + qbits
        none_code = np.uint8((((1 << qbits) - 1) << 2) | 3)
        bases = np.asarray(stacked["bases"])
        # invalid rows' cycles pack as NONE too: their (possibly
        # off-dictionary) quals never reach the kernels, which mask on
        # red/valid everywhere — same dead-distinction argument as the
        # byte rung's N-vs-PAD collapse
        real = (bases < 4) & np.asarray(stacked["valid"], bool)[:, :, None]
        qidx = np.minimum(
            np.searchsorted(lut, np.asarray(stacked["quals"])), len(lut) - 1
        ).astype(np.uint8)
        code = np.where(real, (qidx << 2) | bases, none_code)
        stacked["bases"] = np.concatenate(
            [
                np.packbits((code >> b) & 1, axis=-1, bitorder="little")
                for b in range(nbits)
            ],
            axis=-1,
        )
    else:
        stacked["bases"] = pack_base_qual(stacked["bases"], stacked["quals"])
    stacked["quals"] = np.zeros(stacked["quals"].shape[:2] + (0,), np.uint8)
    u = np.asarray(stacked["umi"])
    b_, r_, w_ = u.shape
    pad = (-w_) % 4
    if pad:
        u = np.concatenate([u, np.zeros((b_, r_, pad), np.uint8)], axis=2)
    u4 = u.reshape(b_, r_, -1, 4)
    stacked["umi"] = (
        u4[..., 0] | (u4[..., 1] << 2) | (u4[..., 2] << 4) | (u4[..., 3] << 6)
    ).astype(np.uint8)
    pos = np.asarray(stacked["pos"])
    if pos.max(initial=0) >= 1 << 16 or pos.min(initial=0) < 0:
        raise ValueError("packed io: bucket-local pos ids must fit u16")
    stacked["pos"] = pos.astype(np.uint16)
    flags = (
        np.asarray(stacked["strand_ab"], bool).astype(np.uint8)
        | (np.asarray(stacked["frag_end"], bool).astype(np.uint8) << 1)
        | (np.asarray(stacked["valid"], bool).astype(np.uint8) << 2)
    )
    stacked["strand_ab"] = flags
    stacked["frag_end"] = np.zeros((b_, 0), np.uint8)
    stacked["valid"] = np.zeros((b_, 0), np.uint8)
    return stacked


def spec_for_buckets(
    buckets,
    grouping: GroupingParams,
    consensus: ConsensusParams,
    ssc_method: str = "matmul",
    packed_io: bool = False,
    per_base_counts: bool = False,
    packed_qbits: int | None = None,
    qual_lut: tuple | None = None,
) -> PipelineSpec:
    """Size the static axes from bucket statistics.

    Directional adjacency can only MERGE exact families, so the unique
    (pos, UMI) count per bucket upper-bounds cluster count, hence:
      u_max >= max unique          (table never overflows)
      f_max >= 2*unique (paired: a unique pair can split into AB + BA
               families) or unique (unpaired)
      m_max >= unique
    All rounded to powers of two (bounded recompiles), capped at the
    read capacity R which is always sufficient.
    """
    import os as _os

    # measured choice (see PipelineSpec.fit_impl); env knob so
    # tools/profile_components.py can A/B the formulations in-pipeline
    fit_impl = _os.environ.get("DUT_FIT_IMPL", "gather")
    if not buckets:
        return PipelineSpec(
            grouping, consensus, ssc_method=ssc_method, packed_io=packed_io,
            per_base_counts=per_base_counts, fit_impl=fit_impl,
        )
    umi_len = int(buckets[0].umi.shape[1]) if packed_io else None
    cycles_len = int(buckets[0].bases.shape[1]) if packed_qbits else None
    r = buckets[0].capacity
    max_u = max(b.n_unique_umi for b in buckets)
    u_max = min(_pow2(max_u), r)
    # family/unit multiplicity per unique (pos, UMI): strand doubles it,
    # the mate-aware fragment-end bit doubles it again
    f_mult = (2 if grouping.paired else 1) * (2 if grouping.mate_aware else 1)
    m_mult = 2 if (grouping.mate_aware and grouping.paired) else 1
    return PipelineSpec(
        grouping=grouping,
        consensus=consensus,
        u_max=u_max,
        f_max=min(_pow2(f_mult * max_u), r),
        m_max=min(_pow2(m_mult * max_u), r),
        ssc_method=ssc_method,
        presorted=True,  # bucketing's output contract
        packed_io=packed_io,
        umi_len=umi_len,
        packed_qbits=packed_qbits,
        qual_lut=qual_lut,
        cycles_len=cycles_len,
        per_base_counts=per_base_counts,
        fit_impl=fit_impl,
    )


def _ssc_cost_matmul(spec: "PipelineSpec", r: int, cols: int) -> float:
    f = (spec.f_max or r) + 1
    return 2.0 * f * r * cols  # dense one-hot GEMM


def _ssc_cost_blockseg(spec: "PipelineSpec", r: int, cols: int) -> float:
    t = min(spec.blockseg_t, r)
    return 2.0 * r * t * cols  # block-local rank one-hot GEMMs


def _ssc_cost_reduction(spec: "PipelineSpec", r: int, cols: int) -> float:
    # segment/runsum/pallas perform ~the useful reduction FLOPs only
    return 2.0 * r * cols


# Per-method ssc reduction cost functions — the kernel-cost registry.
# EVERY method literal kernels/consensus.py dispatches on must have an
# entry here (dutlint's dev-ledger rule pins the two sets against each
# other), so a new kernel cannot ship without its cost model and the
# device ledger's per-class FLOPs stay honest for every capture.
SSC_METHOD_COSTS = {
    "matmul": _ssc_cost_matmul,
    "blockseg": _ssc_cost_blockseg,
    "segment": _ssc_cost_reduction,
    "runsum": _ssc_cost_reduction,
    "pallas": _ssc_cost_reduction,
    "pallas_interpret": _ssc_cost_reduction,
}


def analytic_flops(spec: PipelineSpec, r: int, l: int, b: int) -> float:
    """Executed FLOPs of ONE fused_pipeline call on an (r, l) bucket
    with b UMI code columns — the denominator-side input of the
    benchmark's MFU accounting and of every ``dev`` record in the
    device ledger (telemetry/devledger.py). Counts the two MXU-heavy
    GEMMs (Hamming one-hot, ssc segment reduction via the
    ``SSC_METHOD_COSTS`` registry) plus a floor on the seed
    propagation's per-sweep VPU select/min (the r5 replacement for the
    closure squarings this function used to count — negligible next to
    the GEMMs, kept so the term list matches the kernel). Other
    elementwise/VPU work is excluded, so the number is a lower bound
    on executed work and MFU is conservative. Raises on a method with
    no registered cost function — a silent 0 would fake MFU.
    """
    g, c = spec.grouping, spec.consensus
    u = spec.u_max or r
    fl = 0.0
    if g.strategy in ("adjacency", "cluster"):
        fl += 2.0 * u * u * 4 * b  # matches = onehot @ onehot.T
        # seed search: min-key propagation sweeps over the (U, U) edge
        # grid — O(u^2) VPU work per sweep, floored at the 2 sweeps a
        # fixpoint check needs (the r1-r4 closure-squaring term,
        # log2(u) * 2u^3, stopped being executed work when r5 replaced
        # the closure; keeping it inflated analytic TFLOPs/MFU ~25% at
        # bench shapes, so the r5 builder-side captures' mfu fields
        # overcount — see bench_logs/README.md)
        fl += 2 * 2.0 * float(u) ** 2
    # error model adds a fit-only pass: 4l+1 evidence columns (no depth
    # block) vs the final pass's 5l+1
    cols = (5 * l + 1) + ((4 * l + 1) if c.error_model == "cycle" else 0)
    cost = SSC_METHOD_COSTS.get(spec.ssc_method)
    if cost is None:
        raise ValueError(
            f"ssc_method {spec.ssc_method!r} has no registered cost "
            f"function (SSC_METHOD_COSTS: {sorted(SSC_METHOD_COSTS)})"
        )
    fl += cost(spec, r, cols)
    return fl


@partial(jax.jit, static_argnames=("spec",))
def fused_pipeline(
    pos: jnp.ndarray,  # (R,) i32 bucket-local dense position ids
    umi: jnp.ndarray,  # (R, B) u8
    strand_ab: jnp.ndarray,  # (R,) bool
    frag_end: jnp.ndarray,  # (R,) bool
    valid: jnp.ndarray,  # (R,) bool
    bases: jnp.ndarray,  # (R, L) u8
    quals: jnp.ndarray,  # (R, L) u8
    spec: PipelineSpec,
):
    """Returns a dict of device arrays:

      family_id, molecule_id (R,) i32; n_families, n_molecules,
      n_overflow scalars; cons_base/cons_qual/cons_depth (F, L);
      cons_valid (F,) — F = R rows, dense id order, padding rows invalid.
      Duplex mode: the cons_* tensors are per-molecule (mate-aware: per
      (molecule, frag_end) unit); ss mode: per-family.
      cons_mate (F,) i32 marks second-mate output rows (R2 consensus);
      cons_pair (F,) i32 links the R1/R2 rows of one template (-1 on
      invalid rows) — both only meaningful under mate-aware grouping.
    """
    g, c = spec.grouping, spec.consensus
    r = pos.shape[0]

    if spec.packed_io:
        # decode the wire convention on device (VPU, fused into the
        # first consumers). base|qual: N and PAD both decode to BASE_N —
        # the kernels only ever test bases < N_REAL_BASES, so the
        # distinction is dead
        from duplexumiconsensusreads_tpu.constants import BASE_N as _BN

        if spec.packed_qbits:
            # sub-byte rung: bit-plane codes -> (base, dictionary qual)
            from duplexumiconsensusreads_tpu.kernels.encoding import (
                unpack_bitplanes,
            )

            qbits = spec.packed_qbits
            none_idx = (1 << qbits) - 1
            code = unpack_bitplanes(bases, spec.cycles_len, 2 + qbits)
            qidx = (code >> 2) & none_idx
            none = qidx == none_idx
            # lut padded to the full index range so the take never
            # reads out of bounds (the NONE index lands on the pad)
            lut = jnp.asarray(
                tuple(spec.qual_lut)
                + (0,) * (none_idx + 1 - len(spec.qual_lut)),
                dtype=jnp.uint8,
            )
            quals = jnp.where(none, 0, lut[qidx]).astype(jnp.uint8)
            bases = jnp.where(none, _BN, code & 3).astype(jnp.uint8)
        else:
            real_b = bases != PACKED_NONE
            quals = jnp.where(real_b, bases >> 2, 0).astype(jnp.uint8)
            bases = jnp.where(real_b, bases & 3, _BN).astype(jnp.uint8)
        # flag byte -> the three bool vectors (frag_end/valid arrive as
        # zero-width dummies)
        flags8 = strand_ab.astype(jnp.uint8)
        strand_ab = (flags8 & 1) != 0
        frag_end = (flags8 & 2) != 0
        valid = (flags8 & 4) != 0
        pos = pos.astype(jnp.int32)
        # 2-bit umi bytes -> codes; the packed width over-covers, slice
        # to the true (static) code count
        if spec.umi_len is None:
            raise ValueError("packed_io requires spec.umi_len")
        shifts = jnp.arange(4, dtype=jnp.uint8) * 2
        codes = (umi[:, :, None] >> shifts[None, None, :]) & 3
        umi = codes.reshape(r, -1)[:, : spec.umi_len].astype(jnp.uint8)

    fam, mol, pair, n_fam, n_mol, n_over = group_kernel(
        pos,
        umi,
        strand_ab,
        frag_end,
        valid,
        strategy=g.strategy,
        max_hamming=g.max_hamming,
        count_ratio=g.effective_count_ratio,
        paired=g.paired,
        mate_aware=g.mate_aware,
        u_max=spec.u_max,
        presorted=spec.presorted,
    )

    f_max = spec.f_max or r
    m_max = spec.m_max or r

    # Duplex mode reduces the ssc into rows keyed by the STRIDED id
    # (molecule*2 + strand_ba) instead of the dense family rank: same
    # GEMM cost whenever 2*m_max == f_max (spec_for_buckets guarantees
    # it — f_mult is always 2*m_mult), and the duplex merge collapses
    # from six row-gathers + four segment reductions to reshape-slices
    # (duplex_merge_strided; 18.6% of the r3 fused step). The dense
    # family_id output is untouched — it stays the oracle-parity id.
    strided = c.mode == "duplex" and 2 * m_max == f_max
    if strided:
        red = jnp.where(
            (mol >= 0) & valid,
            mol * 2 + jnp.where(strand_ab, 0, 1),
            jnp.int32(-1),
        )
    else:
        red = fam

    def ssc(q, want_err=False, columns="full"):
        return ssc_kernel(
            bases,
            q,
            red,
            valid,
            f_max=f_max,
            min_reads=c.min_reads,
            max_qual=c.max_qual,
            max_input_qual=c.max_input_qual,
            min_input_qual=c.min_input_qual,
            method=spec.ssc_method,
            want_err=want_err,
            columns=columns,
            blockseg_t=spec.blockseg_t,
        )

    quals_eff = quals
    if c.error_model == "cycle":
        # pass 1 runs fit-only columns: no depth block in the GEMM, no
        # consensus-qual math — the cap fit needs only argmax bases,
        # family sizes, and the mismatch tally. fit_impl picks how the
        # tally is computed (both exact, measured ~equal; see
        # PipelineSpec.fit_impl and the tune_ssc journal).
        if spec.fit_impl == "counts":
            cb0, _sz0, fv0, counts0 = ssc(quals, columns="fit_counts")
            cap = fit_cycle_cap_from_counts(cb0, counts0, fv0)
        else:
            cb0, _sz0, fv0 = ssc(quals, columns="fit")
            cap = fit_cycle_cap_kernel(bases, red, valid, cb0, fv0)
        quals_eff = apply_cycle_cap(quals, cap)

    # per-base disagreement counts only on the FINAL pass (the error
    # model's fit pass needs bases, not counts)
    cb, cq, dep, size, fv, *err_rest = ssc(quals_eff, spec.per_base_counts)
    ss_err = err_rest[0] if err_rest else None

    out_e = None
    if c.mode == "single_strand":
        out_b, out_q, out_d, out_v = cb, cq, dep, fv
        out_e = ss_err
    elif strided:
        out_b, out_q, out_d, out_v, *dx_rest = duplex_merge_strided(
            cb,
            cq,
            dep,
            size,
            fv,
            ss_err,
            m_max=m_max,
            min_duplex_reads=c.min_duplex_reads,
            max_qual=c.max_qual,
            want_err=spec.per_base_counts,
        )
        out_e = dx_rest[0] if dx_rest else None
    elif c.mode == "duplex":
        out_b, out_q, out_d, out_v, *dx_rest = duplex_kernel(
            cb,
            cq,
            dep,
            fv,
            fam,
            mol,
            strand_ab,
            valid,
            ss_err,
            m_max=m_max,
            min_duplex_reads=c.min_duplex_reads,
            max_qual=c.max_qual,
            want_err=spec.per_base_counts,
        )
        out_e = dx_rest[0] if dx_rest else None
    else:
        raise ValueError(f"unknown consensus mode {c.mode!r}")

    # Per-output-row mate/pair metadata (mate-aware emission): the
    # second-mate bit and the template link, reduced from the read
    # level with two tiny segment-mins (constant within a row's reads
    # by construction, so min == the value).
    duplex_out = c.mode == "duplex"
    out_ids = mol if duplex_out else fam
    n_rows = (m_max if duplex_out else f_max)
    ok_r = valid & (out_ids >= 0)
    seg = jnp.where(ok_r, jnp.minimum(out_ids, n_rows), n_rows)
    e2_i = frag_end.astype(jnp.int32)
    if duplex_out:
        mate_read = e2_i  # unit rows: R2 output iff second fragment end
        pair_read = pair
    elif g.paired:
        # ss family rows (molecule, end, strand): the member reads'
        # read-number (frag_end XOR bottom-strand — constant, strand is
        # in the key); pairs are (molecule, strand)
        mate_read = e2_i ^ jnp.where(strand_ab, 0, 1)
        pair_read = pair * 2 + jnp.where(strand_ab, 0, 1)
    else:
        # unpaired ss family rows (molecule, end) can mix strands, so
        # the read-number is NOT constant within a row — label by the
        # fragment end itself (end1 row emits as R1), paired by molecule
        mate_read = e2_i
        pair_read = pair
    cons_mate = jax.ops.segment_min(
        mate_read, seg, num_segments=n_rows + 1
    )[:n_rows]
    cons_pair = jax.ops.segment_min(
        pair_read, seg, num_segments=n_rows + 1
    )[:n_rows]
    # the unit's FRAGMENT end (distinct from cons_mate's read number in
    # ss-paired modes, where mate = end XOR strand): mate-split
    # ref-projection keys its column tables by (pos_key, frag_end), so
    # emission needs the end itself. Constant within a row's reads under
    # mate-aware grouping (end is in the family key); under non-split
    # grouping it is only consumed when proj.mate_split is False anyway.
    cons_end = jax.ops.segment_min(
        e2_i, seg, num_segments=n_rows + 1
    )[:n_rows]
    cons_mate = jnp.where(out_v, cons_mate, 0)
    cons_pair = jnp.where(out_v, cons_pair, -1)
    cons_end = jnp.where(out_v, cons_end, 0)

    # Per-family depth stats computed ON DEVICE: the writers only need
    # cD (max depth) and cM (min positive depth) per consensus, so the
    # executors fetch two (F,) vectors instead of the padded (F, L)
    # depth matrix — on a tunneled chip the transfer is the bottleneck.
    d_max = out_d.max(axis=1)
    pos_d = out_d > 0
    d_min_pos = jnp.where(
        pos_d.any(axis=1),
        jnp.where(pos_d, out_d, jnp.iinfo(jnp.int32).max).min(axis=1),
        0,
    )
    return {
        "family_id": fam,
        "molecule_id": mol,
        "n_families": n_fam,
        "n_molecules": n_mol,
        "n_overflow": n_over,
        # u8/u16 on device: base codes fit u8 (0..5), quals fit u8
        # (<= max_qual), depth stats fit u16-range values but stay i32
        # vectors (tiny) — 8x fewer bytes over the wire than the i32
        # (F, L) tensors they replace
        "cons_base": out_b.astype(jnp.uint8),
        "cons_qual": out_q.astype(jnp.uint8),
        "cons_depth": out_d,
        "depth_max": d_max,
        "depth_min_pos": d_min_pos,
        "cons_valid": out_v,
        "cons_mate": cons_mate.astype(jnp.uint8),
        "cons_pair": cons_pair,
        "cons_end": cons_end.astype(jnp.uint8),
        **({"cons_err": out_e} if out_e is not None else {}),
    }


def run_bucket(bucket, spec: PipelineSpec):
    """Convenience host entry: run one host-side bucket (from bucketing/)
    through the fused pipeline. bucket carries i32 dense pos ids."""
    return fused_pipeline(
        bucket.pos,
        bucket.umi,
        bucket.strand_ab,
        bucket.frag_end,
        bucket.valid,
        bucket.bases,
        bucket.quals,
        spec,
    )
