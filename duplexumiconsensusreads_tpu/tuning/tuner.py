"""Profile-guided bucket auto-tuner (the ROADMAP's "5M+ reads/s" lever).

The r4 per-config spread (2.3M-4.9M reads/s, capacity-4096 slowest)
showed bucket SHAPE alone is worth ~2x of device compute, and every
padded row/cycle the bucketer emits also rides the PCIe link the r5
capture measured at 63-72% of the e2e wall — so fill-factor waste is
paid twice, once in GEMM rows and once in wire bytes. This module
turns the shape choice from a hand-picked ``--capacity`` into a
measured decision:

  profile pass   one cheap host-side scan of a chunk's position-group
                 size sequence (``group_sizes``) — the exact input the
                 bucketer packs, no device involved;
  cost model     candidate ladders are scored by SIMULATING the
                 bucketer's own packing on that sequence
                 (``ladder_cost`` runs the same DP
                 ``bucketing.buckets._ladder_partition`` uses, so the
                 prediction and the run can never disagree about how
                 reads would pack), plus per-bucket dispatch overhead,
                 per-rung compile/class overhead, and the mesh
                 stack-padding multiple;
  verdict        a durable, ledgered :class:`TunerVerdict` — the
                 chosen ladder, the stack-padding multiple it modelled,
                 the ssc method (filled in by the offline race), and
                 the predicted fill factors/speedup, persisted by the
                 serve layer (tuning/store.py) so a fleet converges on
                 the fast shapes for its live traffic mix;
  micro race     ``race_ssc_methods`` times the FUSED pipeline per ssc
                 method through the existing per-bucket-spec compile
                 cache — tools/tune_ssc.py is the offline driver (the
                 method table was stale since the r5 min-rank
                 propagation rewrite changed the FLOP mix).

Verdicts are shape decisions ONLY: output bytes are identical at every
ladder (the executors' final (pos_key, UMI) sort makes bytes a pure
function of the read set — pinned by the test matrix), which is what
lets the serve layer fold verdicts in without touching the jobs'
bytes-are-a-pure-function-of-(input, config) contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time

import numpy as np

# NOTE: bucketing (and through it the jax-importing kernel stack) is
# imported lazily inside ladder_cost — this module must stay loadable
# on the jax-free client path (serve/job.py validates bucket_ladder
# values at submission, which deliberately never touches the device
# stack).

# Cost-model constants, in padded-row-equivalents. Each bucket costs a
# fixed host+dispatch overhead on top of its rows (stack/pack slices,
# per-bucket scatter bookkeeping); each distinct rung adds a dispatch
# class — an extra sharded_pipeline call per chunk plus a compile the
# first time the daemon sees the geometry. Both are deliberately coarse:
# the model's job is to rank ladders on the dominant padded-rows term
# and stop rung proliferation from winning on noise, not to predict
# wall-clock.
BUCKET_OVERHEAD_ROWS = 64
CLASS_OVERHEAD_ROWS = 512

# auto mode proposes at most this many rungs (the ISSUE's 2-4 band:
# every rung past the first buys less and costs a compile class)
MAX_RUNGS = 3

# rungs below this are never proposed: a 16-row GEMM under-utilises
# even one MXU tile and the per-bucket overhead dominates
MIN_RUNG = 32


def validate_ladder(ladder) -> tuple:
    """Normalise + validate an explicit ladder: 1-4 strictly-ascending
    power-of-two rungs, each >= MIN_RUNG. Returns the tuple; raises
    ValueError naming the offence (shared by the CLI, the job-spec
    validator and the executors, so the three ends cannot drift)."""
    try:
        rungs = tuple(int(r) for r in ladder)
    except (TypeError, ValueError):
        raise ValueError(f"bucket ladder must be a list of ints, got {ladder!r}")
    if not 1 <= len(rungs) <= 4:
        raise ValueError(
            f"bucket ladder needs 1-4 rungs, got {len(rungs)} ({rungs})"
        )
    if list(rungs) != sorted(set(rungs)):
        raise ValueError(
            f"bucket ladder rungs must be strictly ascending, got {rungs}"
        )
    for r in rungs:
        if r < MIN_RUNG or r & (r - 1):
            raise ValueError(
                f"bucket ladder rungs must be powers of two >= {MIN_RUNG}, "
                f"got {r}"
            )
    return rungs


def normalize_bucket_ladder(value):
    """The ``--bucket-ladder`` setting in any of its carriers (CLI
    string, config-file/job-config string or int list, executor tuple)
    -> "auto" | "off" | validated rung tuple."""
    if value is None:
        return "off"
    if isinstance(value, str):
        v = value.strip().lower()
        if v in ("auto", "off"):
            return v
        parts = [p for p in v.replace(" ", "").split(",") if p]
        if not parts:
            raise ValueError(f"invalid bucket ladder {value!r}")
        try:
            return validate_ladder(int(p) for p in parts)
        except ValueError as e:
            raise ValueError(f"invalid bucket ladder {value!r}: {e}")
    if isinstance(value, (list, tuple)):
        return validate_ladder(value)
    raise ValueError(
        f"bucket ladder must be 'auto', 'off' or a rung list, got {value!r}"
    )


# ------------------------------------------------------------ profile pass

def group_sizes(batch) -> np.ndarray:
    """Valid-read position-group sizes of a chunk, in the ascending
    pos_key order the bucketer packs them — the profile pass. One
    np.unique over the valid pos_keys; no device, no second sort (the
    bucketer re-derives its own boundaries)."""
    valid = np.asarray(batch.valid, bool)
    pos = np.asarray(batch.pos_key)[valid]
    if len(pos) == 0:
        return np.zeros(0, np.int64)
    _, counts = np.unique(pos, return_counts=True)
    return counts.astype(np.int64)


def single_capacity_cost(
    sizes: np.ndarray, capacity: int, pack_mult: int = 1
) -> dict:
    """Padded-rows cost of the single-capacity packer on a group-size
    sequence (the tuner's "off" baseline): exactly the 1-rung ladder,
    which the DP pads like the legacy greedy (pinned by
    test_single_rung_matches_greedy_cost). ONE simulation of the
    packer's semantics on purpose — a second hand-rolled greedy here
    would have to mirror every flush/oversized rule (groups past the
    capacity take the escapes identically under every ladder and drop
    out of both sides of the comparison), and the two drifting apart
    would silently bias every auto verdict."""
    return ladder_cost(sizes, (int(capacity),), pack_mult)


def ladder_cost(
    sizes: np.ndarray, ladder: tuple, pack_mult: int = 1
) -> dict:
    """Padded-rows cost of a candidate ladder on a group-size sequence,
    via the SAME DP the bucketer runs (oversized groups flush the
    contiguous run exactly as the special paths do). Mesh stack-padding
    and compile-class overhead are modelled per RUNG as an
    approximation: each distinct rung's bucket count pads to a multiple
    of ``pack_mult`` with full-capacity empties and is charged one
    CLASS_OVERHEAD_ROWS. Real dispatch classes additionally key on
    (preclustered, pow2 unique-count), so one rung can split into
    several independently mesh-padded classes the model undercharges —
    a bias toward multi-rung ladders that grows with ``pack_mult``.
    Acceptable for a heuristic whose verdict is informational and whose
    byte-level effect is nil (bytes are ladder-invariant); revisit if
    fleet meshes (pack_mult > 1) start picking ladders the measured
    fill factors contradict."""
    from duplexumiconsensusreads_tpu.bucketing.buckets import _ladder_partition

    capacity = ladder[-1]
    per_rung: dict[int, int] = {}
    real = 0
    seg = [0]

    def _flush():
        if len(seg) > 1:
            for a, b, cap in _ladder_partition(
                np.asarray(seg, np.int64), ladder
            ):
                per_rung[cap] = per_rung.get(cap, 0) + 1
        del seg[1:]

    for s in sizes:
        s = int(s)
        if s > capacity:
            _flush()
            continue
        real += s
        seg.append(seg[-1] + s)
    _flush()
    rows = 0
    n_b = 0
    mult = max(pack_mult, 1)
    for rung, cnt in per_rung.items():
        padded_cnt = cnt + ((-cnt) % mult)
        rows += padded_cnt * rung
        n_b += padded_cnt
    n_classes = max(len(per_rung), 1)
    return {
        "rows_padded": rows,
        "n_buckets": n_b,
        "rows_real": real,
        "cost": rows
        + BUCKET_OVERHEAD_ROWS * sum(per_rung.values())
        + CLASS_OVERHEAD_ROWS * n_classes,
    }


def candidate_ladders(capacity: int, max_rungs: int = MAX_RUNGS) -> list[tuple]:
    """Candidate ladders for a top capacity: every <=``max_rungs``
    subset of the pow2 sub-rungs capacity/2 .. max(MIN_RUNG,
    capacity/32), each ending at the capacity itself (the top rung must
    keep the oversized/jumbo escapes' boundary). The single-rung
    ``(capacity,)`` candidate IS the off baseline, so auto can
    legitimately conclude "one capacity was right"."""
    import itertools

    subs = []
    r = capacity // 2
    while r >= max(MIN_RUNG, capacity // 32):
        subs.append(r)
        r //= 2
    out = [(capacity,)]
    for k in range(1, max_rungs):
        for combo in itertools.combinations(subs, k):
            out.append(tuple(sorted(combo)) + (capacity,))
    return out


@dataclasses.dataclass(frozen=True)
class TunerVerdict:
    """One durable tuning decision for one input profile.

    ``ladder`` is the chosen rung tuple (length 1 = single-capacity —
    the tuner concluded the ladder buys nothing); ``pack_mult`` the
    mesh stack-padding multiple the cost model assumed; ``ssc_method``
    the raced reduction method (None until an offline
    ``tools/tune_ssc.py`` race fills it in — the executors then keep
    their per-backend default). Fill factors are real rows over padded
    row-slots as the cost model predicts them; ``source`` says whether
    the verdict came from the model alone or a timed race."""

    ladder: tuple
    capacity: int
    pack_mult: int = 1
    ssc_method: str | None = None
    fill_factor: float = 0.0
    fill_factor_off: float = 0.0
    predicted_speedup: float = 1.0
    n_reads: int = 0
    n_groups: int = 0
    source: str = "model"

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["ladder"] = list(self.ladder)
        return d

    @staticmethod
    def from_dict(d: dict) -> "TunerVerdict":
        known = {f.name for f in dataclasses.fields(TunerVerdict)}
        kw = {k: v for k, v in d.items() if k in known}
        kw["ladder"] = tuple(int(r) for r in kw.get("ladder", ()))
        return TunerVerdict(**kw)


def choose_ladder(
    sizes: np.ndarray,
    capacity: int,
    pack_mult: int = 1,
    max_rungs: int = MAX_RUNGS,
) -> TunerVerdict:
    """The auto verdict: score every candidate ladder on the profiled
    group-size sequence and keep the cheapest (the per-rung class
    overhead in the model is the anti-proliferation term — an extra
    rung must pay for its compile class in padded rows saved)."""
    base = single_capacity_cost(sizes, capacity, pack_mult)
    best_l: tuple = (capacity,)
    best = dict(base)
    for cand in candidate_ladders(capacity, max_rungs=max_rungs):
        if len(cand) == 1:
            continue  # == the base case by the DP's single-rung parity
        c = ladder_cost(sizes, cand, pack_mult)
        if c["cost"] < best["cost"]:
            best, best_l = c, cand
    def _fill(c):
        return round(c["rows_real"] / c["rows_padded"], 4) if c["rows_padded"] else 1.0
    return TunerVerdict(
        ladder=best_l,
        capacity=capacity,
        pack_mult=max(pack_mult, 1),
        fill_factor=_fill(best),
        fill_factor_off=_fill(base),
        predicted_speedup=round(base["cost"] / max(best["cost"], 1), 3),
        n_reads=int(np.asarray(sizes).sum()) if len(sizes) else 0,
        n_groups=int(len(sizes)),
        source="model",
    )


def profile_key(input_path: str, signature: str) -> str:
    """Stable key of one (input, compile-signature) profile for the
    serve layer's verdict store: the same input bytes under the same
    geometry-determining config always map to one verdict, so a fleet
    converges instead of re-profiling per daemon."""
    try:
        st = os.stat(input_path)
        ident = [os.path.abspath(input_path), st.st_size, int(st.st_mtime)]
    except OSError:
        ident = [os.path.abspath(input_path), -1, -1]
    key = json.dumps([ident, signature], sort_keys=True)
    return hashlib.sha256(key.encode()).hexdigest()[:16]


# ------------------------------------------------------------- micro race

def race_ssc_methods(
    methods: tuple = ("matmul", "blockseg", "runsum", "segment"),
    blockseg_ts: tuple = (128,),
    reps: int = 6,
    n_molecules: int = 22_000,
    read_len: int = 150,
    n_positions: int = 460,
    capacity: int = 2048,
    seed: int = 7,
) -> dict:
    """Timed fused-pipeline race over the ssc reduction methods — the
    ONLY honest scope (isolated-kernel rankings invert in-pipeline; see
    the tools/tune_ssc.py journal). Runs against whatever kernels are
    live, so re-running after a kernel rewrite (the r5 min-rank
    propagation) re-measures the real FLOP mix instead of the stale
    table. Each method's programs go through the same per-bucket-spec
    jit/compile cache the serve daemon shares. Returns
    ``{"backend", "n_reads", "methods": {label: {...}}, "winner",
    "winner_method"}``."""
    import dataclasses as _dc

    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import partition_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)
    cfg = SimConfig(
        n_molecules=n_molecules,
        read_len=read_len,
        n_positions=n_positions,
        mean_family_size=4,
        umi_error=0.01,
        duplex=True,
        seed=seed,
    )
    batch, _ = simulate_batch(cfg)
    n_reads = int(np.asarray(batch.valid).sum())
    buckets = build_buckets(batch, capacity=capacity, grouping=gp)
    mesh = make_mesh(len(jax.devices()))

    plans = []
    for m in methods:
        if m == "blockseg":
            plans.extend(("blockseg", t) for t in blockseg_ts)
        else:
            plans.append((m, None))

    rows: dict[str, dict] = {}
    for method, t in plans:
        part = partition_buckets(buckets, gp, cp, method)
        classes = [
            (
                cspec if t is None else _dc.replace(cspec, blockseg_t=t),
                # pad each class's bucket count to the mesh size, the
                # same discipline as the executors' dispatch — an
                # uneven count is a sharding error on a real mesh
                shard_stacked(
                    stack_buckets(cb, multiple_of=mesh.devices.size),
                    mesh,
                ),
            )
            for cb, cspec in part
        ]
        jax.block_until_ready([c[1] for c in classes])

        def run_all():
            return [
                presharded_pipeline(args, cspec, mesh)
                for cspec, args in classes
            ]

        for o in run_all():
            np.asarray(o["n_families"])  # compile + sync
        # best of two rounds: first-burst timings absorb one-off compile
        # thread tails / allocator warmup (the r5 config4 lesson)
        dt = None
        for _ in range(2):
            t0 = time.monotonic()
            outs = [run_all() for _ in range(max(reps, 1))]
            for o in outs[-1]:
                np.asarray(o["n_families"])
            d = (time.monotonic() - t0) / max(reps, 1)
            dt = d if dt is None else min(dt, d)
        label = method if t is None else f"{method}(T={t})"
        rows[label] = {
            "method": method,
            "blockseg_t": t,
            "step_s": round(dt, 4),
            "reads_per_sec": round(n_reads / dt, 1),
        }
    winner = max(rows, key=lambda k: rows[k]["reads_per_sec"])
    return {
        "backend": jax.default_backend(),
        "n_reads": n_reads,
        "capacity": capacity,
        "reps": reps,
        "methods": rows,
        "winner": winner,
        "winner_method": rows[winner]["method"],
    }
