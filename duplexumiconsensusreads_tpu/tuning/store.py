"""Durable per-input-profile verdict store for the serving fleet.

One JSON file in the spool (``tuner_verdicts.json``): profile key
(tuning.profile_key — input identity x compile signature) ->
TunerVerdict dict. Daemons CONSULT it before an auto-ladder job slice
(a hit skips the profile pass and pins the fleet-wide shape) and
PERSIST the verdict a fresh auto run resolved, so a fleet converges on
the fast shapes for its live traffic mix instead of each daemon
re-deciding per slice.

Concurrency contract: same-KEY races are harmless (verdicts are a pure
function of (input bytes, signature), so two daemons racing one key
write the same value), but different-key races are not — a lock-free
read-merge-write would let the last rename discard the other daemon's
freshly profiled key. Every put therefore runs its read-merge-write
under an flock on ``<store>.lock`` (the journal's own discipline,
kernel-released on any death), staged through the durable
tmp+fsync+rename protocol (unique_tmp keeps concurrent stagings from
interleaving). A torn or garbage store is never fatal: reads degrade
to "no verdict" and the next put rewrites it whole.
"""

from __future__ import annotations

import fcntl
import json
import os
import threading

from duplexumiconsensusreads_tpu.io.durable import unique_tmp, write_durable

# bounded store: verdicts are tiny, but a long-lived spool serving an
# ever-changing input mix must not grow one unbounded file (insertion
# order approximates recency — json dict order is preserved)
MAX_VERDICTS_KEPT = 512


class VerdictStore:
    """Load-on-demand, durable-on-put verdict map."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            return doc if isinstance(doc, dict) else {}
        except (OSError, ValueError):
            return {}

    def get(self, key: str) -> dict | None:
        v = self._load().get(key)
        return v if isinstance(v, dict) else None

    def put(self, key: str, verdict: dict) -> None:
        with self._lock:  # intra-process: one read-merge-write at a time
            # cross-process: flock the whole read-merge-write — two
            # daemons putting DIFFERENT keys must both survive (the
            # fleet-convergence contract), which a lock-free
            # last-rename-wins would break
            with open(self.path + ".lock", "a+") as lf:
                fcntl.flock(lf, fcntl.LOCK_EX)
                doc = self._load()
                doc[key] = verdict
                if len(doc) > MAX_VERDICTS_KEPT:
                    # drop the oldest entries (insertion order)
                    for stale in list(doc)[: len(doc) - MAX_VERDICTS_KEPT]:
                        del doc[stale]
                payload = json.dumps(doc, sort_keys=False).encode()
                write_durable(self.path, payload, tmp=unique_tmp(self.path))

    def __len__(self) -> int:
        return len(self._load())


def spool_store(spool_dir: str) -> VerdictStore:
    """The spool's canonical verdict store path (one per fleet)."""
    return VerdictStore(os.path.join(spool_dir, "tuner_verdicts.json"))
