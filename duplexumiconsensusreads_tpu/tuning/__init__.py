from duplexumiconsensusreads_tpu.tuning.tuner import (  # noqa: F401
    MAX_RUNGS,
    MIN_RUNG,
    TunerVerdict,
    candidate_ladders,
    choose_ladder,
    group_sizes,
    ladder_cost,
    normalize_bucket_ladder,
    profile_key,
    race_ssc_methods,
    single_capacity_cost,
    validate_ladder,
)
from duplexumiconsensusreads_tpu.tuning.store import VerdictStore  # noqa: F401
