"""Crash-safe write primitives shared by every persistent writer.

``os.replace`` makes a rename ATOMIC but not DURABLE: after a power cut
the directory entry may still be the old one unless the file's bytes
AND the containing directory were fsynced. Every writer whose output a
later run trusts by existence (checkpoint manifests, chunk shards, the
finalised BAM, index files) must therefore write

    tmp -> fsync(tmp) -> os.replace(tmp, dst) -> fsync(dirname(dst))

or a crash can leave a file that LOOKS complete but holds truncated or
stale bytes — exactly the failure mode the chaos suite's kill tests
pin down.

Incremental assembly (the streaming executor's pipelined finalise)
stays inside this protocol: appends go to the ``.tmp`` staging file
only, each append is made idempotent with :func:`rewrite_from` (seek +
truncate + write, so a bounded retry after a torn append cannot
duplicate bytes), and the ``os.replace`` publish still happens exactly
once, at the very end.
"""

from __future__ import annotations

import os
import threading
import time

# observability hook only (stdlib-only module, no import cycle): every
# completed durable publish is a trace event when a recorder is
# installed — fsync stalls on shared pod storage are a classic hidden
# wall cost, and the capture should name them
from duplexumiconsensusreads_tpu.telemetry.trace import get_active as _trace_active


def fsync_file(f) -> None:
    """Flush + fsync an open file object (raises on real I/O failure —
    callers wrap in their bounded-retry ladders)."""
    f.flush()
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync: makes a preceding rename durable.

    Some filesystems refuse O_RDONLY directory fsync (and on those the
    rename durability is the mount's problem) — never fail the run
    over it.
    """
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def unique_tmp(dst: str) -> str:
    """A staging name no other writer of ``dst`` can collide with.

    The default ``dst + ".tmp"`` staging name assumes ONE writer per
    destination. Fleet-shared files (checkpoint manifests, chunk
    shards, the queue journal, spool results/metrics) can be written by
    several daemons — and, inside one daemon, by several worker
    threads — at once: two writers interleaving bytes into one shared
    tmp would publish a torn file under a clean atomic rename. A
    (pid, thread) suffix keeps every in-flight staging write private;
    the rename still serializes publication (last writer wins with a
    complete file, never a spliced one)."""
    return f"{dst}.tmp.{os.getpid()}.{threading.get_ident()}"


def free_bytes(path: str) -> int | None:
    """Free disk space (bytes available to this process) on the
    filesystem holding ``path``, or None when it cannot be probed.

    The durable spool/checkpoint design leans on the disk everywhere —
    journal rewrites, shard writes, incremental finalise — so the
    serving layer's disk-pressure degradation (admission shedding below
    a low-water mark, terminal-litter GC) needs one honest probe rather
    than waiting for the first ENOSPC to land mid-commit. ``f_bavail``
    (not ``f_bfree``): what an unprivileged writer can actually use."""
    try:
        st = os.statvfs(path)
    except OSError:
        return None
    return int(st.f_bavail) * int(st.f_frsize)


def rewrite_from(f, offset: int, payload: bytes) -> None:
    """Idempotent append to a staging file: truncate back to ``offset``
    and write ``payload`` there. A transient failure mid-write can be
    retried with the same arguments without duplicating or interleaving
    bytes — the append-side twin of the tmp-write protocol."""
    f.seek(offset)
    f.truncate(offset)
    f.write(payload)


def replace_durable(tmp: str, dst: str) -> None:
    """Atomic rename + directory fsync — the publish step of the
    tmp-write protocol."""
    os.replace(tmp, dst)
    fsync_dir(os.path.dirname(os.path.abspath(dst)))


def write_durable(dst: str, payload: bytes, tmp: str | None = None) -> str:
    """The whole tmp-write protocol in one call, so no writer can
    half-apply it. ``tmp`` overrides the staging name (e.g. a
    pid-suffixed tmp when uncoordinated hosts may write the same
    path)."""
    tr = _trace_active()
    t0 = time.monotonic() if tr is not None else 0.0
    tmp = tmp or dst + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        fsync_file(f)
    replace_durable(tmp, dst)
    if tr is not None:
        tr.event(
            "durable_write", path=dst, bytes=len(payload),
            dur=round(time.monotonic() - t0, 6),
        )
    return dst
