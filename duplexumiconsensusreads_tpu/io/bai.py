"""Standard BAI (BAM binning index) writer — SAM spec §5.2.

Every downstream consumer of consensus BAMs (variant callers, IGV,
samtools-compatible tooling) random-accesses through a ``.bai``; a
coordinate-sorted BAM without one is not drop-in output (VERDICT r3
missing #1). This builder produces the spec layout directly from the
published format — R-tree bins via reg2bin, chunk lists as virtual
offset pairs, the 16 kb linear index, the htslib metadata pseudo-bin
(37450) and the unplaced-read trailer — with no htslib dependency.

One sequential pass shared with the tool's own linear index
(io/index.py): the BGZF block table maps global decompressed offsets to
virtual offsets ((coffset << 16) | uoffset), and the native record
chain walk yields record boundaries.

Reference parity note: the reference mount is empty (SURVEY.md §0);
the layout authority is the published SAM/BAM specification.
"""

from __future__ import annotations

import struct

import numpy as np

BAI_MAGIC = b"BAI\x01"
LINEAR_SHIFT = 14
METADATA_BIN = 37450  # htslib pseudo-bin: file-range + mapped/unmapped counts

# CIGAR ops that consume reference: M(0) D(2) N(3) =(7) X(8)
_REF_CONSUME_MASK = (1 << 0) | (1 << 2) | (1 << 3) | (1 << 7) | (1 << 8)


class _RefIndex:
    """Accumulating per-reference state: bins -> chunk lists, linear
    index, and the metadata counts. All accumulation is batched — a
    per-record Python loop costs minutes of host time on the critical
    path of a 200M-read output (r4 review finding)."""

    __slots__ = ("bins", "linear", "off_beg", "off_end", "n_mapped", "n_unmapped")

    def __init__(self):
        self.bins: dict[int, list[list[int]]] = {}
        self.linear = np.zeros(0, np.int64)
        self.off_beg = -1
        self.off_end = 0
        self.n_mapped = 0
        self.n_unmapped = 0

    def add_batch(self, begs, ends, bins_, v_begs, v_ends, unm):
        """Accumulate one file-order batch of placed records.

        Chunk-merge semantics are identical to the per-record form: per
        bin, a record whose v_beg equals the previous record's v_end
        extends that chunk (a stable sort by bin preserves file order
        within each bin, and the dict tail carries contiguity across
        batches)."""
        n = len(begs)
        if n == 0:
            return
        if self.off_beg < 0:
            self.off_beg = int(v_begs[0])
        self.off_end = int(v_ends[-1])
        nu = int(unm.sum())
        self.n_unmapped += nu
        self.n_mapped += n - nu
        order = np.argsort(bins_, kind="stable")
        sb, svb, sve = bins_[order], v_begs[order], v_ends[order]
        new = np.r_[True, (sb[1:] != sb[:-1]) | (svb[1:] != sve[:-1])]
        starts = np.nonzero(new)[0]
        last = np.r_[starts[1:], n] - 1
        for bi, s, e in zip(
            sb[starts].tolist(), svb[starts].tolist(), sve[last].tolist()
        ):
            chunks = self.bins.setdefault(bi, [])
            if chunks and chunks[-1][1] == s:
                chunks[-1][1] = e  # contiguous across the batch seam
            else:
                chunks.append([s, e])
        # linear index: first voffset touching each 16 kb window the
        # alignment overlaps. Records arrive in coordinate (= voffset)
        # order, so first-wins == min within the batch; values from
        # earlier batches are smaller still, so set-if-unset keeps them.
        lo = begs >> LINEAR_SHIFT
        hi = np.maximum(ends - 1, begs) >> LINEAR_SHIFT
        cnt = hi - lo + 1
        tot = int(cnt.sum())
        wins = np.repeat(lo, cnt) + (
            np.arange(tot, dtype=np.int64)
            - np.repeat(np.cumsum(cnt) - cnt, cnt)
        )
        m = int(hi.max()) + 1
        if m > len(self.linear):
            grow = np.zeros(m, np.int64)
            grow[: len(self.linear)] = self.linear
            self.linear = grow
        # operate on the batch's touched window only: full-index-length
        # temporaries per batch would cost O(n_batches * contig_windows)
        # host work on a 200M-read file — a slice of the per-record-walk
        # overhead this method exists to remove (review r5 finding)
        w0 = int(lo.min())
        sentinel = np.iinfo(np.int64).max
        cur = np.full(m - w0, sentinel, np.int64)
        np.minimum.at(cur, wins - w0, np.repeat(v_begs, cnt))
        head = self.linear[w0:m]
        self.linear[w0:m] = np.where(
            (head == 0) & (cur != sentinel), cur, head
        )


def _build_refs(path: str, binner, max_coord: int, fmt: str):
    """Shared index-builder core: one sequential scan accumulating
    per-reference bins/linear/metadata, parameterized over the bin
    function so BAI (fixed 5-level reg2bin) and CSI (io/csi.py,
    min_shift/depth-generalized) share every other line.

    Returns (refs, n_ref, n_no_coor). Raises ValueError if records are
    not coordinate-sorted (an index over unsorted data would silently
    serve wrong regions) or a contig exceeds max_coord.
    """
    from duplexumiconsensusreads_tpu.io.bam import FLAG_UNMAPPED
    from duplexumiconsensusreads_tpu.io.index import _record_offsets, _scan_blocks
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    # voffset mapping happens batched below: global decompressed offset
    # u -> ((c_off[block(u)] << 16) | (u - cum_u[block(u)])), clamped so
    # u == total size maps to the trailing block at offset 0 (the
    # conventional end-of-data virtual offset)
    c_off, cum_u = _scan_blocks(path)

    reader = BamStreamReader(path)
    refs: list[_RefIndex] = []
    n_no_coor = 0
    last_key = -1
    n_ref = 0
    try:
        header = reader.header  # parsed by the reader's constructor
        n_ref = len(header.ref_names)
        # a contig longer than the binning scheme's address space would
        # silently index wrong regions. Refuse loudly; for BAI (2^29,
        # 512 Mbp — some plant/amphibian genomes exceed it) the CSI
        # format is the spec's answer and io/csi.py sizes its depth to
        # fit any contig.
        for nm, ln in zip(header.ref_names, header.ref_lengths):
            if ln > max_coord:
                raise ValueError(
                    f"{path}: contig {nm!r} length {ln} exceeds the "
                    f"{fmt} format's {max_coord} coordinate limit"
                    + (
                        "; this file needs a CSI index "
                        "(duplexumi index --csi)"
                        if fmt == "BAI"
                        else ""
                    )
                )
        refs = [_RefIndex() for _ in range(n_ref)]
        while True:
            raw = reader.read_raw_records(8192)
            if raw is None:
                break
            offs = _record_offsets(raw)
            base = reader._consumed - len(raw)
            # fully vectorised per batch: field extraction, voffset
            # mapping, sortedness check, CIGAR reference-length
            # reduction, bin assignment, and bins/linear accumulation
            # (per-record Python here cost minutes on 1M+ records;
            # VERDICT r4 item 7)
            b8 = np.frombuffer(raw, np.uint8)

            def _i32(field_off):
                o = offs + field_off
                return (
                    b8[o].astype(np.int64)
                    | (b8[o + 1].astype(np.int64) << 8)
                    | (b8[o + 2].astype(np.int64) << 16)
                    | (b8[o + 3].astype(np.int64) << 24)
                ).astype(np.int32)

            bszs = _i32(0).astype(np.int64)
            ref_ids = _i32(4)
            poss = _i32(8)
            l_names = b8[offs + 12].astype(np.int64)
            n_cigs = b8[offs + 16].astype(np.int64) | (
                b8[offs + 17].astype(np.int64) << 8
            )
            unm = (b8[offs + 18].astype(np.int64) & FLAG_UNMAPPED) != 0
            g_beg = base + offs
            g_end = g_beg + 4 + bszs
            bi_beg = np.minimum(
                np.searchsorted(cum_u, g_beg, side="right") - 1, len(c_off) - 1
            )
            bi_end = np.minimum(
                np.searchsorted(cum_u, g_end, side="right") - 1, len(c_off) - 1
            )
            v_begs = (c_off[bi_beg] << 16) | (g_beg - cum_u[bi_beg])
            v_ends = (c_off[bi_end] << 16) | (g_end - cum_u[bi_end])
            keys = (ref_ids.astype(np.int64) << 34) | (poss.astype(np.int64) + 1)

            if np.any(ref_ids >= n_ref):
                bad = int(ref_ids[ref_ids >= n_ref][0])
                raise ValueError(f"{path}: record ref_id {bad} out of range")
            placed = ref_ids >= 0
            n_no_coor += int((~placed).sum())
            pidx = np.nonzero(placed)[0]
            if not len(pidx):
                continue
            pk = keys[pidx]
            mono = np.r_[pk[0] >= last_key, np.diff(pk) >= 0]
            if not mono.all():
                k = pidx[int(np.nonzero(~mono)[0][0])]
                raise ValueError(
                    f"{path}: not coordinate-sorted (ref {int(ref_ids[k])} "
                    f"pos {int(poss[k])} after a later record) — BAI "
                    f"requires SO:coordinate"
                )
            last_key = int(pk[-1])

            # reference-consumed length per record: one flat gather of
            # every CIGAR op in the batch, reduced back per record
            pn_cig = n_cigs[pidx]
            ref_len = np.zeros(len(pidx), np.int64)
            tot = int(pn_cig.sum())
            if tot:
                rec_of = np.repeat(np.arange(len(pidx)), pn_cig)
                within = np.arange(tot, dtype=np.int64) - np.repeat(
                    np.cumsum(pn_cig) - pn_cig, pn_cig
                )
                op_off = (offs + 36 + l_names)[pidx][rec_of] + 4 * within
                ops = (
                    b8[op_off].astype(np.uint32)
                    | (b8[op_off + 1].astype(np.uint32) << 8)
                    | (b8[op_off + 2].astype(np.uint32) << 16)
                    | (b8[op_off + 3].astype(np.uint32) << 24)
                )
                consume = (_REF_CONSUME_MASK >> (ops & 0xF).astype(np.int64)) & 1
                ref_len = np.bincount(
                    rec_of, weights=((ops >> 4).astype(np.int64) * consume),
                    minlength=len(pidx),
                ).astype(np.int64)

            # spec-legal placed-but-positionless records (ref_id set,
            # pos -1) clamp to 0, matching the serializers' own bin
            # computation (io/bam.py max(pos, 0))
            begs = np.maximum(poss[pidx].astype(np.int64), 0)
            ends = begs + np.maximum(ref_len, 1)
            bins_ = binner(begs, ends).astype(np.int64)
            pv_begs, pv_ends = v_begs[pidx], v_ends[pidx]
            punm = unm[pidx]
            pref = ref_ids[pidx]
            # coordinate order => refs appear as runs within the batch
            run = np.r_[0, np.nonzero(pref[1:] != pref[:-1])[0] + 1, len(pref)]
            for s, e in zip(run[:-1], run[1:]):
                refs[int(pref[s])].add_batch(
                    begs[s:e], ends[s:e], bins_[s:e],
                    pv_begs[s:e], pv_ends[s:e], punm[s:e],
                )
    finally:
        reader.close()
    return refs, n_ref, n_no_coor


def build_bai(path: str, bai_path: str | None = None) -> str:
    """Index a coordinate-sorted BAM; returns the .bai path written."""
    from duplexumiconsensusreads_tpu.io.bam import _reg2bin_vec

    refs, n_ref, n_no_coor = _build_refs(
        path, _reg2bin_vec, 1 << 29, "BAI"
    )

    out = bytearray()
    out += BAI_MAGIC
    out += struct.pack("<i", n_ref)
    for r in refs:
        meta = r.off_beg >= 0
        out += struct.pack("<i", len(r.bins) + (1 if meta else 0))
        for bin_ in sorted(r.bins):
            chunks = r.bins[bin_]
            out += struct.pack("<Ii", bin_, len(chunks))
            for beg_v, end_v in chunks:
                out += struct.pack("<QQ", beg_v, end_v)
        if meta:
            out += struct.pack("<Ii", METADATA_BIN, 2)
            out += struct.pack("<QQ", r.off_beg, r.off_end)
            out += struct.pack("<QQ", r.n_mapped, r.n_unmapped)
        # backfill linear-index holes with the previous window's offset
        # (htslib convention; readers expect monotone non-zero runs):
        # forward-fill via a running max of last-nonzero indices
        lin = r.linear
        if len(lin):
            idxs = np.where(lin != 0, np.arange(len(lin)), 0)
            np.maximum.accumulate(idxs, out=idxs)
            lin = lin[idxs]
        out += struct.pack("<i", len(lin))
        out += lin.astype("<u8").tobytes()
    out += struct.pack("<Q", n_no_coor)

    import os

    from duplexumiconsensusreads_tpu.io.durable import write_durable

    bai_path = bai_path or path + ".bai"
    # per-writer tmp: no shared-tmp races
    return write_durable(bai_path, bytes(out), tmp=f"{bai_path}.tmp.{os.getpid()}")


def reg2bins(beg: int, end: int) -> list[int]:
    """All bins that MAY hold alignments overlapping [beg, end) — the
    SAM spec §5.3 candidate-bin enumeration (the query-side dual of
    reg2bin)."""
    end -= 1
    bins = [0]
    for shift, off in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        bins.extend(range(off + (beg >> shift), off + (end >> shift) + 1))
    return bins


def query_start_voffset(idx: dict, ref_id: int, beg: int, end: int) -> int | None:
    """The virtual offset to start scanning for alignments overlapping
    [beg, end) on ref_id, from a read_bai() index: the minimum chunk
    begin across candidate bins, floored by the linear-index window
    (htslib's query strategy). None when the reference holds nothing
    relevant. The file is coordinate-sorted, so ONE seek + a forward
    scan that stops at the first record starting >= end is a complete
    query."""
    if ref_id < 0 or ref_id >= idx["n_ref"]:
        return None
    ref = idx["refs"][ref_id]
    if ref["meta"] is None and not ref["bins"]:
        return None
    lin = ref["linear"]
    w = beg >> LINEAR_SHIFT
    min_lin = lin[min(w, len(lin) - 1)] if lin else 0
    # every overlapping alignment lives in a candidate bin (reg2bins is
    # the dual of reg2bin), so no candidate chunks => nothing to find.
    # The linear floor CLAMPS the start (a candidate chunk may begin
    # before it, holding earlier irrelevant records) — skipping such
    # chunks instead of clamping would jump past relevant records.
    best = None
    for b in reg2bins(beg, end):
        for beg_v, _end_v in ref["bins"].get(b, ()):
            if best is None or beg_v < best:
                best = beg_v
    if best is None:
        return None
    return max(best, min_lin)


def read_bai(path: str) -> dict:
    """Parse a .bai into {n_ref, refs: [{bins: {bin: [(beg, end), ...]},
    linear: [...], meta: (off_beg, off_end, n_mapped, n_unmapped) | None}],
    n_no_coor} — the test-side inverse of build_bai, also usable to
    sanity-check third-party indexes."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != BAI_MAGIC:
        raise ValueError(f"{path}: not a BAI file")
    try:
        return _parse_bai(path, data)
    except (struct.error, IndexError) as e:
        # truncated/corrupt index must fail loudly with the path, never
        # leak a bare struct.error (or an IndexError from a malformed
        # chunk list) — the repo-wide truncation discipline
        raise ValueError(f"{path}: truncated or corrupt BAI: {e}") from e


def _parse_bai(path: str, data: bytes) -> dict:
    off = 4
    (n_ref,) = struct.unpack_from("<i", data, off)
    off += 4
    refs = []
    for _ in range(n_ref):
        (n_bin,) = struct.unpack_from("<i", data, off)
        off += 4
        bins: dict[int, list[tuple[int, int]]] = {}
        meta = None
        for _ in range(n_bin):
            bin_, n_chunk = struct.unpack_from("<Ii", data, off)
            off += 8
            chunks = []
            for _ in range(n_chunk):
                beg_v, end_v = struct.unpack_from("<QQ", data, off)
                off += 16
                chunks.append((beg_v, end_v))
            if bin_ == METADATA_BIN:
                # exactly 2 chunks by construction (file range +
                # mapped/unmapped counts); see the CSI twin
                if n_chunk != 2:
                    raise ValueError(
                        f"{path}: truncated or corrupt BAI: metadata "
                        f"pseudo-bin has {n_chunk} chunks (expected 2)"
                    )
                meta = (*chunks[0], *chunks[1])
            else:
                bins[bin_] = chunks
        (n_intv,) = struct.unpack_from("<i", data, off)
        off += 4
        linear = list(struct.unpack_from(f"<{n_intv}Q", data, off))
        off += 8 * n_intv
        refs.append({"bins": bins, "linear": linear, "meta": meta})
    n_no_coor = struct.unpack_from("<Q", data, off)[0] if off + 8 <= len(data) else 0
    return {"n_ref": n_ref, "refs": refs, "n_no_coor": n_no_coor}
