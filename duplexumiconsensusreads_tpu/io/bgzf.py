"""BGZF block codec (pure Python + zlib).

BGZF is the blocked-gzip container BAM files live in: a series of
standard gzip members, each carrying an extra "BC" subfield with the
compressed block size, terminated by a fixed 28-byte empty EOF block.
Because each member is independently decompressible, the format
supports random access and parallel decompression — the property the
native C++ loader (io/native) exploits; this module is the portable
reference implementation.

No pysam/htslib exists in this environment (SURVEY.md §7 "Hard parts"
item 4), so the codec is built from the BGZF spec directly.
"""

from __future__ import annotations

import gzip
import io as _io
import struct
import zlib

# Fixed empty gzip member marking end-of-file (BGZF spec appendix).
BGZF_EOF = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000"
)


def has_eof_block(buf: bytes) -> bool:
    """True iff ``buf`` ends with the 28-byte BGZF EOF marker.

    The single definition of "this BGZF stream is finished" — the
    stream reader, the shard merger, and the live tailer all route
    their EOF comparisons through here so the answer cannot drift
    between consumers.
    """
    return len(buf) >= len(BGZF_EOF) and buf[-len(BGZF_EOF):] == BGZF_EOF

# Max uncompressed payload per block. The format caps the *compressed*
# block at 65536; 65280 uncompressed leaves headroom like htslib does.
MAX_BLOCK_UNCOMPRESSED = 65280

_HEADER = struct.Struct("<BBBBIBBH")  # magic1 magic2 CM FLG MTIME XFL OS XLEN
# Precompiled scalar codecs for the hot header scan: read_block_size
# runs once per 18-byte BGZF header on the streaming ingest path, and
# struct.unpack_from("<H", ...) re-parses the format string each call.
_U16 = struct.Struct("<H")
_U32X2 = struct.Struct("<II")


def read_block_size(data: bytes, offset: int) -> int:
    """Total compressed size of the block starting at ``offset``.

    Parses the gzip FEXTRA subfields looking for BC (SI1=66, SI2=67).
    """
    if data[offset : offset + 2] != b"\x1f\x8b":
        raise ValueError(f"not a gzip member at offset {offset}")
    flg = data[offset + 3]
    if not flg & 4:  # FEXTRA
        raise ValueError("gzip member without FEXTRA: not BGZF")
    xlen = _U16.unpack_from(data, offset + 10)[0]
    pos = offset + 12
    end = pos + xlen
    while pos + 4 <= end:
        si1, si2, slen = data[pos], data[pos + 1], _U16.unpack_from(data, pos + 2)[0]
        if si1 == 66 and si2 == 67:
            if slen != 2:
                raise ValueError("BC subfield with SLEN != 2")
            return _U16.unpack_from(data, pos + 4)[0] + 1
        pos += 4 + slen
    raise ValueError("no BC subfield: not BGZF")


def iter_block_offsets(data: bytes):
    """Yield (offset, size) for every BGZF block in ``data``."""
    off = 0
    n = len(data)
    while off < n:
        size = read_block_size(data, off)
        yield off, size
        off += size
    if off != n:
        raise ValueError("trailing garbage after last BGZF block")


def decompress_block(data: bytes, offset: int, size: int) -> bytes:
    """Decompress one block given its offset and compressed size."""
    xlen = _U16.unpack_from(data, offset + 10)[0]
    start = offset + 12 + xlen
    # last 8 bytes are CRC32 + ISIZE
    payload = data[start : offset + size - 8]
    out = zlib.decompress(payload, wbits=-15)
    crc, isize = _U32X2.unpack_from(data, offset + size - 8)
    if len(out) != isize or zlib.crc32(out) != crc:
        raise ValueError(f"BGZF block at {offset}: CRC/size mismatch")
    return out


def decompress(data: bytes) -> bytes:
    """Decompress a whole BGZF byte string (fast path: C gzip handles
    concatenated members natively; falls back to per-block on error)."""
    try:
        return gzip.decompress(data)
    except Exception:
        return b"".join(
            decompress_block(data, off, size) for off, size in iter_block_offsets(data)
        )


def compress_block(payload: bytes, level: int = 6) -> bytes:
    """Compress one ≤MAX_BLOCK_UNCOMPRESSED payload into a BGZF block."""
    if len(payload) > MAX_BLOCK_UNCOMPRESSED:
        raise ValueError("payload too large for one BGZF block")
    c = zlib.compressobj(level, zlib.DEFLATED, -15)
    body = c.compress(payload) + c.flush()
    bsize = len(body) + 12 + 6 + 8  # header(12) + xtra(6) + body + tail(8)
    header = _HEADER.pack(0x1F, 0x8B, 8, 4, 0, 0, 0xFF, 6)
    xtra = struct.pack("<BBHH", 66, 67, 2, bsize - 1)
    tail = struct.pack("<II", zlib.crc32(payload), len(payload))
    return header + xtra + body + tail


def compress(data: bytes, level: int = 6, eof: bool = True) -> bytes:
    """Compress bytes into a BGZF stream (with EOF block by default)."""
    out = _io.BytesIO()
    for i in range(0, len(data), MAX_BLOCK_UNCOMPRESSED):
        out.write(compress_block(data[i : i + MAX_BLOCK_UNCOMPRESSED], level))
    if eof:
        out.write(BGZF_EOF)
    return out.getvalue()


def compress_fast(data: bytes, level: int = 6, eof: bool = True) -> bytes:
    """BGZF-compress via the native multithreaded library when present
    (io/native), falling back to the pure-Python codec. DUT_NO_NATIVE=1
    forces the fallback (same knob as the native reader)."""
    return compress_fast_tagged(data, level=level, eof=eof)[0]


def compress_fast_tagged(
    data: bytes, level: int = 6, eof: bool = True
) -> tuple[bytes, str]:
    """``compress_fast`` plus the codec ACTUALLY used: (bytes,
    "native"|"python"). Native and pure-Python deflate produce
    different — both valid — bytes for the same records, and the
    native call can fail at RUNTIME after a successful capability
    probe; callers persisting compressed artifacts that a later run
    may splice verbatim (the streaming executor's checkpoint shards)
    must record this tag, not an up-front probe."""
    import os

    out = None
    if not os.environ.get("DUT_NO_NATIVE"):
        try:
            from duplexumiconsensusreads_tpu.native import bgzf_compress_native

            out = bgzf_compress_native(data, level=level)
        except Exception:
            out = None
    if out is None:
        return compress(data, level=level, eof=eof), "python"
    return out + (BGZF_EOF if eof else b""), "native"


# capability probe cache: native availability is stable within a
# process (get_lib binds once), so one tiny real compression settles it
_compress_capable: bool | None = None


def native_compress_capable() -> bool:
    """True iff the native BGZF deflate path actually WORKS, probed by
    compressing a tiny payload — not by ``get_lib()`` presence. A
    library that loads but whose compress entry point fails must read
    as incapable, or fingerprints tag shards with a codec the runtime
    then silently falls back from (mixed-codec splices on resume)."""
    global _compress_capable
    if _compress_capable is None:
        try:
            from duplexumiconsensusreads_tpu.native import bgzf_compress_native

            _compress_capable = bgzf_compress_native(b"dut-probe") is not None
        except Exception:
            _compress_capable = False
    return _compress_capable


def deflate_flavor() -> str:
    """The deflate codec a compress_fast call is EXPECTED to use right
    now: "native" or "python". Joins the streaming checkpoint
    fingerprint; per-shard truth is compress_fast_tagged's return."""
    import os

    if os.environ.get("DUT_NO_NATIVE"):
        return "python"
    return "native" if native_compress_capable() else "python"


def is_bgzf(data: bytes) -> bool:
    if len(data) < 18 or data[:2] != b"\x1f\x8b":
        return False
    try:
        read_block_size(data, 0)
        return True
    except ValueError:
        return False
