"""Minimal BAM reader/writer over the BGZF codec.

Implements the BAM binary layout (SAM spec §4) directly — magic,
header text, reference dictionary, and alignment records — producing a
struct-of-arrays ``BamRecords`` that converts losslessly into the
framework's padded ``ReadBatch`` tensors (io/convert.py).

Scope notes (deliberate, documented):
- CIGAR ops are parsed and preserved round-trip but consensus math
  operates on raw cycles for same-length family members, the fgbio-style
  default chosen in SURVEY.md §7 ("Hard parts" item 4 — the reference
  mount is empty, so cycle-space consensus is the contract default).
- Aux tags: RX (UMI) is interpreted; all other tags are preserved as
  raw bytes per record so nothing is lost on passthrough.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from duplexumiconsensusreads_tpu.io import bgzf

BAM_MAGIC = b"BAM\x01"

# BAM 4-bit base codes "=ACMGRSVTWYHKDBN" → framework codes (A=0 C=1
# G=2 T=3, everything ambiguous → N=4).
_NIBBLE_TO_CODE = np.full(16, 4, np.uint8)
_NIBBLE_TO_CODE[1] = 0  # A
_NIBBLE_TO_CODE[2] = 1  # C
_NIBBLE_TO_CODE[4] = 2  # G
_NIBBLE_TO_CODE[8] = 3  # T
_CODE_TO_NIBBLE = np.array([1, 2, 4, 8, 15, 15], np.uint8)  # A C G T N PAD→N

FLAG_PAIRED = 0x1
FLAG_PROPER_PAIR = 0x2
FLAG_UNMAPPED = 0x4
FLAG_MATE_UNMAPPED = 0x8
FLAG_REVERSE = 0x10
FLAG_MATE_REVERSE = 0x20
FLAG_READ1 = 0x40
FLAG_READ2 = 0x80
FLAG_SECONDARY = 0x100
FLAG_QCFAIL = 0x200
FLAG_DUP = 0x400
FLAG_SUPPLEMENTARY = 0x800

# Records carrying any of these flags never enter UMI families:
# unmapped reads have no coordinate; secondary/supplementary alignments
# re-observe a primary record (counting them inflates family depth and
# shifts consensus); QC-fail reads are untrusted. This mirrors the
# conventional fgbio-style input filter. PCR/optical duplicates (0x400)
# are deliberately NOT excluded — duplicate collapse is this tool's job.
FLAG_CONSENSUS_EXCLUDE = FLAG_UNMAPPED | FLAG_SECONDARY | FLAG_QCFAIL | FLAG_SUPPLEMENTARY


def consensus_excluded(flags, ref_id):
    """Exclusion mask shared by BOTH codecs (io/convert.py and
    io/native_reader.py must stay bit-identical — the streaming
    chunker's sentinel flush assumes no excluded record can ever form a
    family). ref_id < 0 is excluded unconditionally, not just via
    FLAG_UNMAPPED: such records map to the UNMAPPED_POS_KEY sentinel."""
    return ((np.asarray(flags).astype(np.int64) & FLAG_CONSENSUS_EXCLUDE) != 0) | (
        np.asarray(ref_id) < 0
    )


@dataclasses.dataclass
class BamHeader:
    text: str
    ref_names: list[str]
    ref_lengths: list[int]

    @staticmethod
    def synthetic(
        ref_names=("chr1",),
        ref_lengths=(10_000_000,),
        extra: str = "",
        sort_order: str = "unsorted",
    ):
        lines = [f"@HD\tVN:1.6\tSO:{sort_order}"]
        for n, l in zip(ref_names, ref_lengths):
            lines.append(f"@SQ\tSN:{n}\tLN:{l}")
        lines.append("@PG\tID:duplexumi\tPN:duplexumiconsensusreads_tpu")
        if extra:
            lines.append(extra)
        return BamHeader(
            text="\n".join(lines) + "\n",
            ref_names=list(ref_names),
            ref_lengths=list(ref_lengths),
        )


def set_sort_order(text: str, so: str) -> str:
    """Rewrite (or insert) the @HD line's SO: field."""
    lines = text.rstrip("\n").split("\n") if text.strip() else []
    for i, line in enumerate(lines):
        if line.startswith("@HD"):
            fields = [f for f in line.split("\t") if not f.startswith("SO:")]
            lines[i] = "\t".join(fields + [f"SO:{so}"])
            break
    else:
        lines.insert(0, f"@HD\tVN:1.6\tSO:{so}")
    return "\n".join(lines) + "\n"



def _header_ids(text: str, tag: str) -> tuple[set, str | None]:
    """(all ID: values of @<tag> lines, the LAST one seen) — shared by
    the @PG and @RG uniquification so the parse/suffix logic cannot
    diverge between them."""
    ids: set = set()
    last = None
    for line in (text.rstrip("\n").split("\n") if text.strip() else []):
        if line.startswith(tag):
            for f in line.split("\t")[1:]:
                if f.startswith("ID:"):
                    ids.add(f[3:])
                    last = f[3:]
    return ids, last


def _uniquify(base: str, ids: set) -> str:
    out, k = base, 0
    while out in ids:
        k += 1
        out = f"{base}.{k}"
    return out


def chain_pg(text: str, pn: str = "duplexumiconsensusreads_tpu", cl: str | None = None) -> str:
    """Append a new @PG entry chained (PP:) to the last program in the
    existing chain, with a collision-free ID — real pipelines key
    provenance on the @PG chain, so reruns must never clobber it."""
    lines = text.rstrip("\n").split("\n") if text.strip() else []
    ids, last_id = _header_ids(text, "@PG")
    new_id = _uniquify("duplexumi", ids)
    entry = f"@PG\tID:{new_id}\tPN:{pn}"
    if last_id is not None:
        entry += f"\tPP:{last_id}"
    if cl:
        entry += "\tCL:" + cl.replace("\t", " ").replace("\n", " ")
    lines.append(entry)
    return "\n".join(lines) + "\n"


def unique_read_group_id(text: str, rg_id: str) -> str:
    """Collision-free consensus read-group id: if the input header
    already carries @RG ID:<rg_id> (e.g. an fgbio-produced input whose
    consensus group is also 'A'), attributing our consensus records to
    that EXISTING group would silently inherit its SM/LB/PL — so
    uniquify with the same helper chain_pg uses for @PG IDs. Must be
    resolved BEFORE records are built (the RG:Z tags must match the
    final id)."""
    ids, _last = _header_ids(text, "@RG")
    return _uniquify(rg_id, ids)


def add_read_group(text: str, rg_id: str, sample: str | None = None) -> str:
    """Append a consensus @RG line (fgbio-style: one NEW output read
    group; input @RG lines are preserved above it for provenance). The
    sample defaults to the union of input SM values, else the rg id."""
    lines = text.rstrip("\n").split("\n") if text.strip() else []
    sms = []
    for line in lines:
        if line.startswith("@RG"):
            for f in line.split("\t")[1:]:
                if f.startswith("ID:") and f[3:] == rg_id:
                    return "\n".join(lines) + "\n"  # already present
                if f.startswith("SM:") and f[3:] not in sms:
                    sms.append(f[3:])
    sm = sample or (",".join(sms) if sms else rg_id)
    lines.append(f"@RG\tID:{rg_id}\tSM:{sm}")
    return "\n".join(lines) + "\n"


def derive_output_header(
    header: "BamHeader",
    sort_order: str | None = "coordinate",
    rg_id: str | None = None,
    cl: str | None = None,
) -> "BamHeader":
    """The consensus-output header: input text preserved verbatim
    (@SQ/@RG/@CO and the existing @PG chain survive), @HD SO: set to
    the true output order, a new @PG chained, and optionally the
    consensus @RG appended. cl defaults to this process's command line
    (what the @PG CL: field records by convention)."""
    import sys as _sys

    text = header.text
    if sort_order:
        text = set_sort_order(text, sort_order)
    text = chain_pg(text, cl=cl if cl is not None else " ".join(_sys.argv))
    if rg_id:
        text = add_read_group(text, rg_id)
    return BamHeader(
        text=text, ref_names=header.ref_names, ref_lengths=header.ref_lengths
    )


@dataclasses.dataclass
class BamRecords:
    """Struct-of-arrays of N alignment records (host NumPy).

    seq/qual are padded to the max read length; lengths[i] gives the
    real length. umi holds the RX tag string per record ("" if absent).
    aux_raw preserves every record's full aux-tag byte blob.
    """

    names: list[str]
    flags: np.ndarray      # u16 (N,)
    ref_id: np.ndarray     # i32 (N,)
    pos: np.ndarray        # i32 (N,) 0-based
    mapq: np.ndarray       # u8  (N,)
    next_ref_id: np.ndarray  # i32 (N,)
    next_pos: np.ndarray   # i32 (N,)
    tlen: np.ndarray       # i32 (N,)
    lengths: np.ndarray    # i32 (N,)
    seq: np.ndarray        # u8 (N, L) framework base codes, PAD beyond length
    qual: np.ndarray       # u8 (N, L)
    cigars: list[list[tuple[int, str]]]
    umi: list[str]
    aux_raw: list[bytes]

    def __len__(self) -> int:
        return len(self.names)


def reorder_records(recs: "BamRecords", order) -> "BamRecords":
    """Row-permute a BamRecords (e.g. restore coordinate order after
    ref-projected emission moves POS values)."""
    o = np.asarray(order)
    ol = o.tolist()
    return BamRecords(
        names=[recs.names[i] for i in ol],
        flags=np.asarray(recs.flags)[o],
        ref_id=np.asarray(recs.ref_id)[o],
        pos=np.asarray(recs.pos)[o],
        mapq=np.asarray(recs.mapq)[o],
        next_ref_id=np.asarray(recs.next_ref_id)[o],
        next_pos=np.asarray(recs.next_pos)[o],
        tlen=np.asarray(recs.tlen)[o],
        lengths=np.asarray(recs.lengths)[o],
        seq=np.asarray(recs.seq)[o],
        qual=np.asarray(recs.qual)[o],
        cigars=[recs.cigars[i] for i in ol],
        umi=[recs.umi[i] for i in ol],
        aux_raw=[recs.aux_raw[i] for i in ol],
    )


_CIGAR_OPS = "MIDNSHP=X"


def iter_aux_fields(aux: bytes):
    """Yield (field_start, tag, typ, value_start, field_end) for each
    aux field — the ONE walker parse/strip/filter code shares, so a
    type-handling fix can never apply to one consumer and miss another.

    Raises ValueError on any malformation it VISITS (unknown type/
    subtype, any truncation including 1-2 stray trailing bytes).
    Consumers that early-exit once they find their tag (RX extraction,
    the filter's tag reads) deliberately do not visit — hence do not
    validate — fields after it; only full walks (strip_aux_tag, a
    search for an absent tag) check the whole blob."""
    pos, n = 0, len(aux)
    while pos + 3 <= n:
        start = pos
        tag = aux[pos : pos + 2]
        typ = aux[pos + 2 : pos + 3]
        pos += 3
        vstart = pos
        if typ in b"AcC":
            size = 1
        elif typ in b"sS":
            size = 2
        elif typ in b"iIf":
            size = 4
        elif typ in b"ZH":
            try:
                size = aux.index(b"\x00", pos) - pos + 1
            except ValueError:
                raise ValueError(
                    f"unterminated Z/H aux field {tag!r} (no NUL before "
                    f"end of aux block)"
                ) from None
        elif typ == b"B":
            if pos + 5 > n:
                raise ValueError(f"truncated B-array header for tag {tag!r}")
            sub = aux[pos : pos + 1]
            cnt = struct.unpack_from("<I", aux, pos + 1)[0]
            sub_size = {b"c": 1, b"C": 1, b"s": 2, b"S": 2, b"i": 4, b"I": 4, b"f": 4}.get(sub)
            if sub_size is None:
                raise ValueError(f"unknown B-array subtype {sub!r} for tag {tag!r}")
            size = 5 + cnt * sub_size
        else:
            raise ValueError(f"unknown aux tag type {typ!r}")
        pos += size
        if pos > n:
            raise ValueError(
                f"truncated aux field {tag!r}:{typ!r} (needs {pos - n} more bytes)"
            )
        yield start, tag, typ, vstart, pos
    if pos != n:
        # 1-2 stray trailing bytes: a truncated next-field tag, not a
        # valid stream tail — reject like every other truncation point
        raise ValueError(f"trailing {n - pos} stray aux bytes (truncated field)")


def _parse_aux_rx(aux: bytes) -> str:
    """Extract the RX:Z tag from an aux blob (empty string if absent)."""
    for _, tag, typ, vstart, end in iter_aux_fields(aux):
        if tag == b"RX" and typ == b"Z":
            return aux[vstart : end - 1].decode("ascii")
    return ""


def parse_bam(data: bytes) -> tuple[BamHeader, BamRecords]:
    """Parse a BAM byte string (BGZF-compressed or raw) fully."""
    if bgzf.is_bgzf(data):
        data = bgzf.decompress(data)
    if data[:4] != BAM_MAGIC:
        raise ValueError("not a BAM file (bad magic)")
    off = 4
    (l_text,) = struct.unpack_from("<i", data, off)
    off += 4
    text = data[off : off + l_text].split(b"\x00", 1)[0].decode("utf-8")
    off += l_text
    (n_ref,) = struct.unpack_from("<i", data, off)
    off += 4
    ref_names, ref_lengths = [], []
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", data, off)
        off += 4
        ref_names.append(data[off : off + l_name - 1].decode("ascii"))
        off += l_name
        (l_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        ref_lengths.append(l_ref)
    header = BamHeader(text=text, ref_names=ref_names, ref_lengths=ref_lengths)

    names: list[str] = []
    flags, ref_id, pos_, mapq = [], [], [], []
    next_ref, next_pos, tlen, lengths = [], [], [], []
    seqs: list[np.ndarray] = []
    quals: list[np.ndarray] = []
    cigars: list[list[tuple[int, str]]] = []
    umis: list[str] = []
    aux_raws: list[bytes] = []

    n_total = len(data)
    while off < n_total:
        if off + 4 > n_total:
            raise ValueError("truncated BAM: partial record length field")
        (block_size,) = struct.unpack_from("<i", data, off)
        off += 4
        rec_end = off + block_size
        if block_size < 32 or rec_end > n_total:
            raise ValueError(
                f"truncated/corrupt BAM record at byte {off - 4} "
                f"(block_size={block_size}, {n_total - off} bytes left)"
            )
        (rid, p, l_rn, mq, _bin, n_cig, flag, l_seq, nrid, npos, tl) = struct.unpack_from(
            "<iiBBHHHiiii", data, off
        )
        # l_rn >= 1: the spec's NUL terminator — l_read_name=0 would
        # shift every later field onto garbage instead of failing here
        if l_rn < 1 or l_seq < 0 or 32 + l_rn + 4 * n_cig + (l_seq + 1) // 2 + l_seq > block_size:
            raise ValueError(
                f"corrupt BAM record at byte {off - 4}: fixed fields "
                f"(name {l_rn} + cigar {n_cig} ops + seq {l_seq}) overrun "
                f"block_size {block_size}"
            )
        off += 32
        names.append(data[off : off + l_rn - 1].decode("ascii"))
        off += l_rn
        cig = []
        for _ in range(n_cig):
            (v,) = struct.unpack_from("<I", data, off)
            off += 4
            cig.append((v >> 4, _CIGAR_OPS[v & 0xF]))
        packed = np.frombuffer(data, np.uint8, (l_seq + 1) // 2, off)
        off += (l_seq + 1) // 2
        nib = np.empty(2 * len(packed), np.uint8)
        nib[0::2] = packed >> 4
        nib[1::2] = packed & 0xF
        seqs.append(_NIBBLE_TO_CODE[nib[:l_seq]])
        q = np.frombuffer(data, np.uint8, l_seq, off).copy()
        off += l_seq
        if l_seq and q[0] == 0xFF:
            q[:] = 0
        quals.append(q)
        aux = data[off:rec_end]
        off = rec_end
        flags.append(flag)
        ref_id.append(rid)
        pos_.append(p)
        mapq.append(mq)
        next_ref.append(nrid)
        next_pos.append(npos)
        tlen.append(tl)
        lengths.append(l_seq)
        cigars.append(cig)
        umis.append(_parse_aux_rx(aux))
        aux_raws.append(bytes(aux))

    n = len(names)
    lmax = int(max(lengths, default=0))
    from duplexumiconsensusreads_tpu.constants import BASE_PAD

    seq_arr = np.full((n, lmax), BASE_PAD, np.uint8)
    qual_arr = np.zeros((n, lmax), np.uint8)
    for i, (s, q) in enumerate(zip(seqs, quals)):
        seq_arr[i, : len(s)] = s
        qual_arr[i, : len(q)] = q

    recs = BamRecords(
        names=names,
        flags=np.asarray(flags, np.uint16),
        ref_id=np.asarray(ref_id, np.int32),
        pos=np.asarray(pos_, np.int32),
        mapq=np.asarray(mapq, np.uint8),
        next_ref_id=np.asarray(next_ref, np.int32),
        next_pos=np.asarray(next_pos, np.int32),
        tlen=np.asarray(tlen, np.int32),
        lengths=np.asarray(lengths, np.int32),
        seq=seq_arr,
        qual=qual_arr,
        cigars=cigars,
        umi=umis,
        aux_raw=aux_raws,
    )
    return header, recs


def read_bam(path: str) -> tuple[BamHeader, BamRecords]:
    with open(path, "rb") as f:
        return parse_bam(f.read())


def _reg2bin(beg: int, end: int) -> int:
    """SAM spec §5.3 bin computation."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def _reg2bin_vec(beg: np.ndarray, end: np.ndarray) -> np.ndarray:
    """Vectorised _reg2bin (SAM spec §5.3)."""
    end = end - 1
    out = np.zeros(len(beg), np.int64)
    done = np.zeros(len(beg), bool)
    for shift, base in ((14, 4681), (17, 585), (20, 73), (23, 9), (26, 1)):
        hit = ~done & ((beg >> shift) == (end >> shift))
        out[hit] = base + (beg[hit] >> shift)
        done |= hit
    return out


def _scatter_runs(buf, dst_starts, lengths, payload_flat):
    """buf[dst_starts[i] : dst_starts[i]+lengths[i]] = consecutive runs
    of payload_flat — the variable-length scatter at the heart of the
    vectorised serializer."""
    total = int(lengths.sum())
    if total == 0:
        return
    cum = np.concatenate(([0], np.cumsum(lengths)[:-1]))
    idx = np.repeat(dst_starts - cum, lengths) + np.arange(total)
    buf[idx] = payload_flat[:total]


def _slice_recs(recs: BamRecords, a: int, b: int) -> BamRecords:
    return BamRecords(
        **{
            f.name: getattr(recs, f.name)[a:b]
            for f in dataclasses.fields(BamRecords)
        }
    )


def _serialize_records_fast(recs: BamRecords) -> bytes | None:
    """Vectorised record serialization for the dominant shape — every
    record has exactly one CIGAR op 'M' covering its whole sequence
    (all simulator and consensus output records). Returns None when the
    records don't fit that shape (caller falls back to the general
    per-record path). A 30x+ speedup at 10M-read scale."""
    n = len(recs)
    if n == 0:
        return b""
    lengths = np.asarray(recs.lengths, np.int64)
    for c, l in zip(recs.cigars, recs.lengths):
        if len(c) != 1 or c[0][1] != "M" or c[0][0] != l:
            return None
    name_bytes = [s.encode("ascii") + b"\x00" for s in recs.names]
    name_len = np.fromiter((len(b) for b in name_bytes), np.int64, n)
    aux_len = np.fromiter((len(a) for a in recs.aux_raw), np.int64, n)
    seq_b = (lengths + 1) // 2
    if (
        (lengths == lengths[0]).all()
        and (name_len == name_len[0]).all()
        and (aux_len == aux_len[0]).all()
    ):
        return _serialize_uniform(recs, name_bytes, int(name_len[0]), int(aux_len[0]))
    body_len = 32 + name_len + 4 + seq_b + lengths + aux_len
    starts = np.concatenate(([0], np.cumsum(4 + body_len)[:-1]))
    buf = np.zeros(int(starts[-1] + 4 + body_len[-1]), np.uint8)

    def put_i32(off_arr, values):
        idx = off_arr[:, None] + np.arange(4)[None, :]
        buf[idx] = values.astype("<i4").view(np.uint8).reshape(n, 4)

    pos = np.asarray(recs.pos, np.int64)
    put_i32(starts, body_len)
    b = starts + 4
    put_i32(b, np.asarray(recs.ref_id, np.int64))
    put_i32(b + 4, pos)
    b0 = np.maximum(pos, 0)
    e0 = b0 + np.maximum(lengths, 1)
    # BAI reg2bin is only DEFINED below 2^29: past it the leaf formula
    # yields invalid-but-u16-fitting bins (e.g. 41305 at 600 Mbp) that
    # strict validators flag. Write bin=0 for any record touching the
    # out-of-scheme range (htslib convention for CSI-indexed files —
    # no reader trusts the field there).
    bin_ = np.where(e0 > (1 << 29), 0, _reg2bin_vec(b0, e0))
    # l_read_name(u8) mapq(u8) bin(u16) packed little-endian as one i32
    put_i32(b + 8, name_len | (np.asarray(recs.mapq, np.int64) << 8) | (bin_ << 16))
    # n_cigar_op(u16)=1 | flag(u16)
    put_i32(b + 12, 1 | (np.asarray(recs.flags, np.int64) << 16))
    put_i32(b + 16, lengths)
    put_i32(b + 20, np.asarray(recs.next_ref_id, np.int64))
    put_i32(b + 24, np.asarray(recs.next_pos, np.int64))
    put_i32(b + 28, np.asarray(recs.tlen, np.int64))
    name_dst = b + 32
    _scatter_runs(buf, name_dst, name_len, np.frombuffer(b"".join(name_bytes), np.uint8))
    put_i32(name_dst + name_len, (lengths << 4) | 0)  # one M op
    # packed 4-bit seq: framework codes -> BAM nibbles, padded rows
    l_max = recs.seq.shape[1]
    nib = _CODE_TO_NIBBLE[np.minimum(recs.seq, len(_CODE_TO_NIBBLE) - 1)]
    # zero nibbles past each row's length so odd-length padding is 0
    col = np.arange(l_max)[None, :]
    nib = np.where(col < lengths[:, None], nib, 0)
    if l_max % 2:
        nib = np.concatenate([nib, np.zeros((n, 1), np.uint8)], axis=1)
    packed = (nib[:, 0::2] << 4) | nib[:, 1::2]
    w = packed.shape[1]
    pk_idx = (np.repeat(np.arange(n), seq_b) * w) + (
        np.arange(int(seq_b.sum())) - np.repeat(np.concatenate(([0], np.cumsum(seq_b)[:-1])), seq_b)
    )
    _scatter_runs(buf, name_dst + name_len + 4, seq_b, packed.reshape(-1)[pk_idx])
    q_idx = (np.repeat(np.arange(n), lengths) * l_max) + (
        np.arange(int(lengths.sum())) - np.repeat(np.concatenate(([0], np.cumsum(lengths)[:-1])), lengths)
    )
    _scatter_runs(
        buf, name_dst + name_len + 4 + seq_b, lengths,
        np.asarray(recs.qual, np.uint8).reshape(-1)[q_idx],
    )
    _scatter_runs(
        buf, name_dst + name_len + 4 + seq_b + lengths, aux_len,
        np.frombuffer(b"".join(recs.aux_raw), np.uint8),
    )
    return buf.tobytes()


def _serialize_uniform(
    recs: BamRecords, name_bytes: list[bytes], nl: int, al: int
) -> bytes:
    """Fully-uniform record layout (same read length, name width, aux
    width, one M CIGAR op): the whole batch serializes as one (n,
    rec_len) matrix of pure column writes — no per-byte index arrays.
    This is the shape every simulator/consensus writer emits."""
    n = len(recs)
    l = int(recs.lengths[0])
    sb = (l + 1) // 2
    body = 32 + nl + 4 + sb + l + al
    rec_len = 4 + body
    buf = np.empty((n, rec_len), np.uint8)

    def col_i32(off, values):
        buf[:, off : off + 4] = (
            np.ascontiguousarray(values.astype("<i4")).view(np.uint8).reshape(n, 4)
        )

    pos = np.asarray(recs.pos, np.int64)
    col_i32(0, np.full(n, body, np.int64))
    col_i32(4, np.asarray(recs.ref_id, np.int64))
    col_i32(8, pos)
    b0 = np.maximum(pos, 0)
    # past-BAI coords (end > 2^29): bin=0 — see _serialize_records_fast
    bin_ = np.where(
        b0 + max(l, 1) > (1 << 29), 0, _reg2bin_vec(b0, b0 + max(l, 1))
    )
    col_i32(12, nl | (np.asarray(recs.mapq, np.int64) << 8) | (bin_ << 16))
    col_i32(16, 1 | (np.asarray(recs.flags, np.int64) << 16))
    col_i32(20, np.full(n, l, np.int64))
    col_i32(24, np.asarray(recs.next_ref_id, np.int64))
    col_i32(28, np.asarray(recs.next_pos, np.int64))
    col_i32(32, np.asarray(recs.tlen, np.int64))
    buf[:, 36 : 36 + nl] = np.frombuffer(b"".join(name_bytes), np.uint8).reshape(n, nl)
    col_i32(36 + nl, np.full(n, (l << 4) | 0, np.int64))
    o = 40 + nl
    nib = _CODE_TO_NIBBLE[np.minimum(recs.seq[:, :l], len(_CODE_TO_NIBBLE) - 1)]
    if l % 2:
        nib = np.concatenate([nib, np.zeros((n, 1), np.uint8)], axis=1)
    buf[:, o : o + sb] = (nib[:, 0::2] << 4) | nib[:, 1::2]
    buf[:, o + sb : o + sb + l] = np.asarray(recs.qual, np.uint8)[:, :l]
    if al:
        buf[:, o + sb + l :] = np.frombuffer(b"".join(recs.aux_raw), np.uint8).reshape(n, al)
    return buf.tobytes()


def serialize_bam(header: BamHeader, recs: BamRecords) -> bytes:
    """Serialize header + records to uncompressed BAM bytes."""
    out = bytearray()
    out += BAM_MAGIC
    text = header.text.encode("utf-8")
    out += struct.pack("<i", len(text))
    out += text
    out += struct.pack("<i", len(header.ref_names))
    for name, length in zip(header.ref_names, header.ref_lengths):
        nb = name.encode("ascii") + b"\x00"
        out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)

    # vectorised path, in row blocks so the scatter index arrays stay
    # bounded (~8 bytes of index per output byte)
    block = 65536
    fast_parts = []
    for s in range(0, max(len(recs), 1), block):
        part = _serialize_records_fast(_slice_recs(recs, s, min(s + block, len(recs))))
        if part is None:
            fast_parts = None
            break
        fast_parts.append(part)
    if fast_parts is not None:
        return bytes(out) + b"".join(fast_parts)

    op_idx = {c: i for i, c in enumerate(_CIGAR_OPS)}
    for i in range(len(recs)):
        name_b = recs.names[i].encode("ascii") + b"\x00"
        l_seq = int(recs.lengths[i])
        cig = recs.cigars[i]
        seq_codes = recs.seq[i, :l_seq]
        nib = _CODE_TO_NIBBLE[seq_codes]
        if l_seq % 2:
            nib = np.append(nib, 0)
        packed = ((nib[0::2] << 4) | nib[1::2]).astype(np.uint8).tobytes()
        qual = recs.qual[i, :l_seq].tobytes()
        aux = recs.aux_raw[i]
        p = int(recs.pos[i])
        # bin covers the record's REFERENCE span (CIGAR M/D/N/=/X
        # total), not l_seq: a ref-projected consensus with D ops spans
        # more reference than it has bases, and strict validators check
        # bin == reg2bin(pos, pos + ref_span). CIGAR-less records keep
        # the l_seq-based placeholder span (matches the fast path).
        # past-BAI coords (end > 2^29): bin=0 — see _serialize_records_fast
        span = sum(n_op for n_op, op in cig if op in "MDN=X") if cig else l_seq
        end = max(p, 0) + max(span, 1)
        rbin = 0 if end > (1 << 29) else _reg2bin(max(p, 0), end)
        body = struct.pack(
            "<iiBBHHHiiii",
            int(recs.ref_id[i]),
            p,
            len(name_b),
            int(recs.mapq[i]),
            rbin,
            len(cig),
            int(recs.flags[i]),
            l_seq,
            int(recs.next_ref_id[i]),
            int(recs.next_pos[i]),
            int(recs.tlen[i]),
        )
        body += name_b
        for n_op, op in cig:
            body += struct.pack("<I", (n_op << 4) | op_idx[op])
        body += packed + qual + aux
        out += struct.pack("<i", len(body)) + body
    return bytes(out)


def write_bam(path: str, header: BamHeader, recs: BamRecords, level: int = 6) -> None:
    with open(path, "wb") as f:
        f.write(bgzf.compress_fast(serialize_bam(header, recs), level=level))


def strip_aux_tag(aux: bytes, tag: str) -> bytes:
    """Return ``aux`` with every field named ``tag`` removed (any value
    type) — re-annotators must replace, not duplicate, their tags."""
    t = tag.encode("ascii")
    out = bytearray()
    for start, name, _typ, _vstart, end in iter_aux_fields(aux):
        if name != t:
            out += aux[start:end]
    return bytes(out)


def make_aux_z(tag: str, value: str) -> bytes:
    return tag.encode("ascii") + b"Z" + value.encode("ascii") + b"\x00"


def make_aux_i(tag: str, value: int) -> bytes:
    return tag.encode("ascii") + b"i" + struct.pack("<i", value)
