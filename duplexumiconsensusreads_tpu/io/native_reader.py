"""Fast BAM → ReadBatch path using the native loader (native/).

The C++ library decompresses BGZF blocks in parallel and extracts
record fields straight into preallocated NumPy buffers; this module
does only vectorised post-processing (UMI char→code mapping, duplex
strand derivation + canonical pair swap, pos_key packing — the same
contract io/convert.py documents). Falls back to None when the native
library can't be built; callers then use the pure-Python codec.

The native path intentionally skips read names / cigars / full aux
blobs — it feeds the compute pipeline, which needs none of them. Use
io.read_bam for full-fidelity parsing.
"""

from __future__ import annotations

import ctypes
import os
import struct

import numpy as np

from duplexumiconsensusreads_tpu.io.bam import (
    FLAG_PAIRED,
    FLAG_READ1,
    FLAG_READ2,
    FLAG_REVERSE,
    BamHeader,
    consensus_excluded,
)
from duplexumiconsensusreads_tpu.io.convert import pack_pos_key
from duplexumiconsensusreads_tpu.types import ReadBatch

_CHAR_CODE = np.full(256, 255, np.uint8)
for _i, _c in enumerate(b"ACGT"):
    _CHAR_CODE[_c] = _i
for _i, _c in enumerate(b"acgt"):  # Python codec upper()s, so must we
    _CHAR_CODE[_c] = _i
_SEP = ord("-")


def _parse_header_region(data: bytes, header_end: int) -> BamHeader:
    (l_text,) = struct.unpack_from("<i", data, 4)
    text = data[8 : 8 + l_text].split(b"\x00", 1)[0].decode("utf-8")
    off = 8 + l_text
    (n_ref,) = struct.unpack_from("<i", data, off)
    off += 4
    names, lengths = [], []
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", data, off)
        off += 4
        names.append(data[off : off + l_name - 1].decode("ascii"))
        off += l_name
        (l_ref,) = struct.unpack_from("<i", data, off)
        off += 4
        lengths.append(l_ref)
    return BamHeader(text=text, ref_names=names, ref_lengths=lengths)


def scan_region(lib, data: np.ndarray, what: str = "BAM"):
    """One native scan pass over an uncompressed BAM byte region.

    Returns (header_end, l_max, rx_max, rec_off). The offsets buffer is
    sized at the minimum-record-size upper bound (block_size field 4B +
    fixed fields 32B + 1 name byte) so counting and offset collection
    don't walk the region twice.
    """
    header_end = ctypes.c_long()
    l_max = ctypes.c_int()
    rx_max = ctypes.c_int()
    rec_off = np.empty(max(len(data) // 37, 1), np.int64)
    n_rec = lib.dut_bam_scan(
        data, len(data), ctypes.byref(header_end),
        ctypes.byref(l_max), ctypes.byref(rx_max),
        rec_off.ctypes.data_as(ctypes.c_void_p),
    )
    if n_rec < 0:
        raise ValueError(f"{what}: malformed BAM")
    return (
        int(header_end.value),
        int(l_max.value),
        int(rx_max.value),
        rec_off[:n_rec],
    )


def _gather_i32(data: np.ndarray, starts: np.ndarray, field_off: int) -> np.ndarray:
    """Vectorised little-endian i32 reads at starts+field_off (unaligned)."""
    idx = starts[:, None] + (field_off + np.arange(4))[None, :]
    return np.ascontiguousarray(data[idx]).view("<i4")[:, 0]


def region_pos_keys(data: np.ndarray, rec_off: np.ndarray) -> np.ndarray:
    """Canonical fragment pos_key per record, straight from raw record
    bytes — byte-identical to io.convert.records_pos_keys (the grouping
    key the streaming chunker's family-integrity guarantee rides on)."""
    if len(rec_off) == 0:
        return np.zeros(0, np.int64)
    body = rec_off + 4  # skip the block_size field
    ref_id = _gather_i32(data, body, 0)
    pos = _gather_i32(data, body, 4)
    flag_word = _gather_i32(data, body, 12)  # n_cigar_op(16) | flag(16)
    flags = (flag_word >> 16) & 0xFFFF
    next_ref = _gather_i32(data, body, 20)
    next_pos = _gather_i32(data, body, 24)
    from duplexumiconsensusreads_tpu.io.bam import FLAG_PAIRED as _FP

    paired_ok = ((flags & _FP) != 0) & (next_ref == ref_id) & (next_pos >= 0)
    coord = np.where(paired_ok, np.minimum(pos, next_pos), pos)
    return pack_pos_key(ref_id, coord)


def read_bam_native(
    path: str,
    duplex: bool = True,
    n_threads: int | None = None,
    warn_mixed: bool = True,
) -> tuple[BamHeader, ReadBatch, dict] | None:
    """Parse a BAM file via the native loader. None if lib unavailable."""
    from duplexumiconsensusreads_tpu.native import get_lib

    lib = get_lib()
    if lib is None:
        return None
    nt = n_threads or min(os.cpu_count() or 1, 16)

    with open(path, "rb") as f:
        raw = np.frombuffer(f.read(), np.uint8)

    if len(raw) >= 2 and raw[0] == 0x1F and raw[1] == 0x8B:
        usize = lib.dut_bgzf_usize(raw, len(raw))
        if usize < 0:
            raise ValueError(f"{path}: malformed BGZF")
        data = np.empty(usize, np.uint8)
        if lib.dut_bgzf_decompress(raw, len(raw), data, usize, nt) != usize:
            raise ValueError(f"{path}: BGZF decompression failed")
    else:
        data = raw.copy()

    header_end, l_max, rx_max, rec_off = scan_region(lib, data, path)
    header = _parse_header_region(data[:header_end].tobytes(), header_end)
    batch, info = batch_from_offsets(
        lib, data, rec_off, l_max, rx_max, duplex=duplex, n_threads=nt,
        warn_mixed=warn_mixed,
    )
    return header, batch, info


def _cigar_at(data: np.ndarray, off: int):
    """Parse ONE record's CIGAR ops from the raw uncompressed bytes —
    used only for the few modal-vote minority reads the soft-clip
    rescue inspects, so a per-record Python parse is fine (the bulk
    path never touches cigars, by design)."""
    import struct as _struct

    from duplexumiconsensusreads_tpu.io.bam import _CIGAR_OPS

    # operate on the ndarray through the buffer protocol — no copy of
    # the (large) decompressed chunk
    l_rn = int(data[off + 12])
    (n_cig,) = _struct.unpack_from("<H", data, off + 16)
    if not n_cig:
        return []
    ops = np.frombuffer(data, "<u4", n_cig, off + 36 + l_rn)
    return [(int(v) >> 4, _CIGAR_OPS[int(v) & 0xF]) for v in ops]


def batch_from_offsets(
    lib,
    data: np.ndarray,
    rec_off: np.ndarray,
    l_max: int,
    rx_max: int,
    duplex: bool,
    n_threads: int,
    warn_mixed: bool = True,
) -> tuple[ReadBatch, dict]:
    """Native fill + vectorised ReadBatch assembly for the records at
    ``rec_off`` within ``data`` (uncompressed BAM bytes). l_max/rx_max
    are capacity hints from scan_region (may cover a superset of the
    records; widths are sliced back to the actual maxima below)."""
    nt = n_threads
    # Allocation width stays >=1 so the ctypes buffers have real
    # storage; seq/qual are sliced back to the true l_max below so a
    # record-less / sequence-less file matches the Python codec's
    # zero-width batch exactly.
    n, l, rx_cap = len(rec_off), max(int(l_max), 1), max(int(rx_max), 1)
    flags = np.empty(n, np.uint16)
    ref_id = np.empty(n, np.int32)
    pos = np.empty(n, np.int32)
    next_ref = np.empty(n, np.int32)
    next_pos = np.empty(n, np.int32)
    lseq = np.empty(n, np.int32)
    seq = np.empty((n, l), np.uint8)
    qual = np.empty((n, l), np.uint8)
    rx = np.empty((n, rx_cap), np.uint8)
    cig_hash = np.empty(n, np.uint64)
    rec_off = np.ascontiguousarray(rec_off)
    rc = lib.dut_bam_fill(
        data, len(data), rec_off, n, l, rx_cap, nt,
        flags, ref_id, pos, next_ref, next_pos, lseq, seq, qual, rx,
        cig_hash,
    )
    if rc != 0:
        raise ValueError("BAM record fill failed")

    # width = the actual max over THESE records (a superset capacity
    # hint from scan_region must not widen the batch)
    actual_l = int(lseq.max()) if n else 0
    if actual_l < l:
        seq = seq[:, :actual_l]
        qual = qual[:, :actual_l]

    # --- vectorised ReadBatch assembly (contract: io/convert.py) ---
    # Mirror the Python codec's semantics exactly: flag-excluded reads
    # (unmapped/secondary/supplementary/qcfail) are invalid and touch
    # nothing else; a read is "parseable" iff it has a non-empty RX
    # whose non-separator chars are all ACGT (case-insensitive);
    # umi_len is the max over PARSEABLE NON-EXCLUDED reads only (an
    # unparseable long RX must not inflate it); parseable reads of a
    # different length are dropped as length-inconsistent. An RX of
    # only separators gives n_umi_chars == 0 — such reads are valid
    # exactly when umi_len == 0, as in the Python codec.
    excluded = consensus_excluded(flags, ref_id)
    codes_all = _CHAR_CODE[rx]
    has_char = rx != 0
    is_umi_char = (rx != _SEP) & has_char
    n_umi_chars = is_umi_char.sum(axis=1)
    has_rx = has_char.any(axis=1)
    bad_char = ((codes_all == 255) & is_umi_char).any(axis=1)
    parseable = has_rx & ~bad_char
    counted = parseable & ~excluded
    umi_len = int(n_umi_chars[counted].max()) if counted.any() else 0
    valid = counted & (n_umi_chars == umi_len)

    umi_codes = np.zeros((n, umi_len), np.uint8)
    if umi_len:
        vidx = np.nonzero(valid)[0]
        layout = is_umi_char[vidx]
        if len(layout) and (layout == layout[0]).all():
            # fast path: identical RX layout on every valid read
            cols = np.nonzero(layout[0])[0]
            umi_codes[vidx] = codes_all[np.ix_(vidx, cols)]
        else:
            for i in vidx:
                umi_codes[i] = codes_all[i][is_umi_char[i]]

    f = flags.astype(np.int64)
    paired = (f & FLAG_PAIRED) != 0
    rev = (f & FLAG_REVERSE) != 0
    r1 = (f & FLAG_READ1) != 0
    r2 = (f & FLAG_READ2) != 0
    top = np.where(paired, r1 != rev, ~rev)
    # fragment-end bit — must mirror records_to_readbatch exactly
    frag_end = paired & (r2 == top)

    if duplex and umi_len:
        h = umi_len // 2
        ba = ~top & valid
        umi_codes[ba] = np.concatenate(
            [umi_codes[ba][:, h:], umi_codes[ba][:, :h]], axis=1
        )

    paired_ok = paired & (next_ref == ref_id) & (next_pos >= 0)
    coord = np.where(paired_ok, np.minimum(pos, next_pos), pos)
    pos_key = pack_pos_key(ref_id, coord)

    # CIGAR/indel policy — must mirror records_to_readbatch exactly
    from duplexumiconsensusreads_tpu.io.convert import modal_cigar_keep

    # mixed-mate detection BEFORE the CIGAR filter (mates often differ
    # in soft-clips; the modal filter would hide exactly these)
    from duplexumiconsensusreads_tpu.io.convert import warn_mixed_mates

    n_mixed, mixed_present = warn_mixed_mates(
        flags, pos_key, umi_codes, top & valid, valid, warn=warn_mixed
    )

    valid_pre = valid  # pre-CIGAR mask: keeps the drop counters disjoint
    keep = modal_cigar_keep(pos_key, umi_codes, valid, cig_hash, top)
    from duplexumiconsensusreads_tpu.io.convert import softclip_rescue

    rescue_info = softclip_rescue(
        seq, qual, keep, valid, pos_key, umi_codes, top, pos,
        lambda i: _cigar_at(data, int(rec_off[i])),
    )
    valid = valid & keep
    n_cigar = int(valid_pre.sum()) - int(valid.sum())

    batch = ReadBatch(
        bases=seq,
        quals=qual,
        umi=umi_codes,
        pos_key=pos_key,
        strand_ab=top & valid,  # invalid rows keep the codec's False default
        frag_end=frag_end & valid,
        valid=valid,
    )
    info = {
        "n_records": n,
        "n_valid": int(valid.sum()),
        "n_dropped_no_umi": int((~parseable & ~excluded).sum()),
        "n_dropped_umi_len": int((counted & ~valid_pre).sum()),
        "n_dropped_flag": int(excluded.sum()),
        "n_dropped_cigar": n_cigar,
        **rescue_info,
        "n_mixed_mate_families": n_mixed,
        "mixed_mates": mixed_present,
        "umi_len": umi_len,
        "native": True,
    }
    return batch, info
