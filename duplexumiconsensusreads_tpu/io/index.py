"""Linear BGZF/BAM index: the multi-host input-partitioning consumer.

A coordinate-sorted BAM is divided by sampled record boundaries: every
``every`` records the index stores (pos_key, compressed block offset,
offset within that block's decompressed payload). Because BGZF blocks
are independently decompressible, a host can open the file AT an index
entry (seek + skip) and stream only its genomic key range — this is
what makes `parallel.distributed.host_tile_range` executable: each
host's share of the key space maps to a byte region it can read
without touching the rest of the file.

Range semantics: a host owns pos_keys in [key_lo, key_hi) (None = open
end). Since families never span pos_keys, any such partition preserves
family integrity; reading starts at the last entry strictly BEFORE
key_lo so a position group that straddles a sampled boundary is always
seen from its first record (leading records below key_lo are skipped).
"""

from __future__ import annotations

import dataclasses
import os
import struct

import numpy as np

from duplexumiconsensusreads_tpu.io import bgzf

INDEX_SUFFIX = ".dlix"
_MAGIC = "duplexumi-linear-index-v1"


@dataclasses.dataclass
class BamLinearIndex:
    """Sampled record boundaries of a coordinate-sorted BAM.

    pos_key[i]  pos_key of the i-th sampled record
    coffset[i]  compressed file offset of the BGZF block holding it
    uoffset[i]  offset of the record within that block's decompressed
                payload
    every       sampling stride in records (entry i = record i*every)
    n_records   total records in the file
    """

    pos_key: np.ndarray
    coffset: np.ndarray
    uoffset: np.ndarray
    every: int
    n_records: int

    def save(self, path: str) -> None:
        # file handle, not path: savez would append ".npz" to the
        # conventional ".dlix" suffix and break exists()/load() lookups.
        # tmp + atomic replace: concurrent hosts on shared storage must
        # never observe (or interleave into) a torn index — a reader
        # whose exists() check lands mid-write would load a corrupt npz
        import os as _os

        from duplexumiconsensusreads_tpu.io.durable import (
            fsync_file,
            replace_durable,
        )

        # per-writer tmp name: two uncoordinated hosts saving the same
        # index must never interleave into one tmp file
        tmp = f"{path}.tmp.{_os.getpid()}"
        with open(tmp, "wb") as f:
            np.savez_compressed(
                f,
                magic=_MAGIC,
                pos_key=self.pos_key,
                coffset=self.coffset,
                uoffset=self.uoffset,
                every=self.every,
                n_records=self.n_records,
            )
            fsync_file(f)
        replace_durable(tmp, path)

    @staticmethod
    def load(path: str) -> "BamLinearIndex":
        with np.load(path, allow_pickle=False) as z:
            if str(z["magic"]) != _MAGIC:
                raise ValueError(f"{path}: not a duplexumi linear index")
            return BamLinearIndex(
                pos_key=z["pos_key"],
                coffset=z["coffset"],
                uoffset=z["uoffset"],
                every=int(z["every"]),
                n_records=int(z["n_records"]),
            )

    def start_voffset(self, key_lo) -> tuple[int, int] | None:
        """(coffset, uoffset) to start reading so that every record with
        pos_key >= key_lo is seen; None = no seek (record-less file).
        An open start (key_lo None) seeks to entry 0 — the first
        record — never to byte 0, which would replay the header bytes
        as records."""
        if len(self.pos_key) == 0:
            return None
        if key_lo is None:
            return (int(self.coffset[0]), int(self.uoffset[0]))
        # last entry strictly below key_lo (entries are non-decreasing);
        # an entry AT key_lo may sit mid-position-group, so it is not a
        # safe entry point for that group's first records
        j = int(np.searchsorted(self.pos_key, key_lo, side="left")) - 1
        if j < 0:
            return (int(self.coffset[0]), int(self.uoffset[0]))
        return (int(self.coffset[j]), int(self.uoffset[j]))


def build_linear_index(path: str, every: int = 100_000) -> BamLinearIndex:
    """One sequential pass: block table from the compressed stream,
    record boundaries from the decompressed stream (native chain walk
    when available), sampled every ``every`` records."""
    from duplexumiconsensusreads_tpu.io.native_reader import region_pos_keys
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    c_off, cum_u = _scan_blocks(path)

    reader = BamStreamReader(path)
    entries_key, entries_c, entries_u = [], [], []
    n_records = 0
    try:
        while True:
            raw = reader.read_raw_records(8192)
            if raw is None:
                break
            offs = _record_offsets(raw)
            base = reader._consumed - len(raw)
            first = (-n_records) % every
            sel = np.arange(first, len(offs), every)
            if len(sel):
                keys = region_pos_keys(np.frombuffer(raw, np.uint8), offs[sel])
                for key, o in zip(keys.tolist(), offs[sel].tolist()):
                    g = base + o  # global decompressed offset
                    bi = int(np.searchsorted(cum_u, g, side="right")) - 1
                    entries_key.append(key)
                    entries_c.append(int(c_off[bi]))
                    entries_u.append(g - int(cum_u[bi]))
            n_records += len(offs)
    finally:
        reader.close()
    return BamLinearIndex(
        pos_key=np.array(entries_key, np.int64),
        coffset=np.array(entries_c, np.int64),
        uoffset=np.array(entries_u, np.int64),
        every=every,
        n_records=n_records,
    )


def _scan_blocks(path: str, read_size: int = 8 << 20, progress=None):
    """Streaming BGZF block table: (compressed offsets, cumulative
    decompressed offsets). Header-only scan in bounded memory — the
    index targets files far larger than RAM. ``progress`` (optional
    callable) fires once per ``read_size`` batch: long walks under a
    lease (the shard planner) stamp liveness through it."""
    c_off, u_sizes = [], []
    base = 0
    buf = b""
    with open(path, "rb") as f:
        head = f.read(2)
        if head[:2] != b"\x1f\x8b":
            raise ValueError(f"{path}: linear index requires BGZF input")
        f.seek(0)
        while True:
            data = f.read(read_size)
            if progress is not None:
                progress()
            if data:
                buf += data
            off = 0
            while off + 18 <= len(buf):
                size = bgzf.read_block_size(buf, off)
                if off + size > len(buf):
                    break
                c_off.append(base + off)
                u_sizes.append(struct.unpack_from("<I", buf, off + size - 4)[0])
                off += size
            base += off
            buf = buf[off:]
            if not data:
                if buf:
                    raise ValueError(f"{path}: trailing truncated BGZF block")
                break
    return (
        np.array(c_off, np.int64),
        np.concatenate(([0], np.cumsum(np.array(u_sizes, np.int64)))),
    )


def _record_offsets(raw: bytes) -> np.ndarray:
    """Offsets of each record within a whole-records byte run (native
    chain walk when available; Python fallback otherwise)."""
    import ctypes

    from duplexumiconsensusreads_tpu.native import get_lib

    lib = get_lib()
    if lib is not None:
        arr = np.frombuffer(raw, np.uint8)
        # whole-record runs: record count <= len/37 (min record size)
        offs = np.empty(max(len(raw) // 37, 1), np.int64)
        end = ctypes.c_long()
        n = lib.dut_bam_chain_offsets(
            arr, len(arr), 0, len(offs), ctypes.byref(end),
            offs.ctypes.data_as(ctypes.c_void_p),
        )
        if n >= 0:
            return offs[:n]
    offs_l = []
    off = 0
    n = len(raw)
    while off + 4 <= n:
        (bsz,) = struct.unpack_from("<i", raw, off)
        offs_l.append(off)
        off += 4 + bsz
    return np.array(offs_l, np.int64)
