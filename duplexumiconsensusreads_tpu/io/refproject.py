"""Per-reference-position (CIGAR-projected) consensus support — opt-in.

The default input policy votes each family's modal CIGAR, rescues
soft-clip-only minorities, and DROPS indel-bearing minority reads: their
cycles are misaligned relative to the family and would corrupt the
cycle-space consensus. That loses real evidence — a read with a 1 bp
sequencing-artifact deletion still observes ~149 perfectly aligned bases,
all shifted by one cycle.

``--ref-projected`` replaces the drop with a PROJECTION: every read's
query bases are placed into a reference-coordinate column grid shared by
its position group — one column per reference position, plus insertion
columns keyed by ``(ref_pos, ins_offset)`` for every insertion boundary
any group member carries. The device pipeline is unchanged: it consumes
the projected ``(N, C)`` grid exactly as it consumed the ``(N, L)``
cycle grid (the tpu-native trick — alignment is a data transform at
ingest, not a kernel change), and the NumPy oracle consumes the same
grid, so oracle/device parity is structural.

Consensus CIGARs are decided by per-family structural majorities
computed here, on the host, from pure integer counts:

  - a reference column is DELETED (``D``) when more family reads span it
    without contributing a base (their CIGAR deleted it) than contribute;
  - an insertion column is EMITTED (``I``) when a strict majority of the
    reads spanning it carry the insertion; otherwise it is suppressed
    and the minority's inserted bases are simply excluded (the only
    evidence lost — everything else realigns).

Family keys are ``(pos_key, canonical UMI)`` — the same granularity as
the modal-CIGAR vote it replaces; strand is deliberately excluded so the
two strands of a duplex molecule share one structural decision, matching
the single consensus record they merge into. Adjacency-merged minority
UMIs fall back to the position group's aggregate decision (the seed's
exact family has its own entry, so only minority members consult the
aggregate), mirroring the modal vote's exact-key approximation.

Groups whose projected width would exceed ``cap_factor * L`` columns
(e.g. distant-mate families sharing a pos_key) FALL BACK to the classic
cycle-space layout, modal vote and all; the counters report how many.

Whole-file executor only, by design: the projected column width is
data-dependent (max group span + insertion columns), and per-chunk
streaming would make every chunk a fresh (R, C) pipeline geometry —
an XLA recompile per chunk (20-40 s each on the tunneled chip) for a
host-side transform whose value is per-family, not per-byte-stream.
Chunk boundaries themselves would be safe (the streaming contract
never splits a pos_key group); width-quantization could bound the
compile count if streaming projection is ever needed.

Reference parity note: the reference mount is empty (SURVEY.md §0); the
semantics here follow the SAM spec's CIGAR/coordinate model and the
per-column consensus convention of alignment-space duplex callers.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from duplexumiconsensusreads_tpu.constants import BASE_PAD

# emit codes per column, decided per family (or per group as fallback)
EMIT = 0        # column appears in the consensus (M, or I on ins columns)
EMIT_DEL = 1    # reference column deleted by family majority -> D
EMIT_SKIP = 2   # insertion column without majority support -> suppressed


@dataclasses.dataclass
class RefProjection:
    """Column metadata produced at conversion, consumed at emission."""

    width: int  # C: column width of the projected bases/quals arrays
    read_len: int  # original cycle width L (fallback rows live in [0, L))
    # pos_key -> (col_pos (C_g,) i64 absolute ref position per column,
    #             col_ins (C_g,) i32 insertion offset, 0 = reference col)
    groups: dict
    # (pos_key, canonical-UMI bytes) -> (C_g,) u8 emit codes
    fam_emit: dict
    # pos_key -> (C_g,) u8 aggregate emit codes (merged-minority fallback)
    group_emit: dict
    n_projected_reads: int = 0
    n_fallback_reads: int = 0
    n_fallback_groups: int = 0
    # reads whose CIGAR consumes no reference (soft-clips + insertions
    # only): they have no reference-anchored bases to place, so their
    # projected rows stay PAD — the analogue of the modal-CIGAR drop,
    # counted separately. The caller INVALIDATES them (ref_project's
    # returned ``unanchored`` mask): an all-PAD row would inflate
    # family size (min-reads gates, depth denominators) without
    # contributing evidence
    n_unanchored_reads: int = 0
    # True: column tables were keyed by pos_key*2 + frag_end (mate-aware
    # runs — each mate side projects around its own alignment span);
    # False: keyed by pos_key*2. Emission must use the same composite.
    mate_split: bool = False


def _cigar_spans(cig):
    """(query_segments, ref_len) for one CIGAR: segments are
    (kind, q_start, length, ref_off) with kind 'M' (aligned run at
    reference offset ref_off from the alignment start) or 'I'
    (insertion before reference offset ref_off)."""
    segs = []
    q = r = 0
    for n, op in cig:
        if op in "M=X":
            segs.append(("M", q, n, r))
            q += n
            r += n
        elif op == "I":
            segs.append(("I", q, n, r))
            q += n
        elif op in "DN":
            r += n
        elif op == "S":
            q += n
        # H/P consume nothing
    return segs, r


def ref_project(
    bases: np.ndarray,  # (N, L) u8 — source query bases
    quals: np.ndarray,  # (N, L) u8
    valid: np.ndarray,  # (N,) bool
    pos_key: np.ndarray,  # (N,) i64 canonical family position key
    umi: np.ndarray,  # (N, U) u8 canonical codes
    read_pos: np.ndarray,  # (N,) i32 each record's OWN alignment start
    get_cigar,  # callable i -> [(n, op), ...]
    cap_factor: int = 2,
) -> tuple[np.ndarray, np.ndarray, RefProjection, np.ndarray]:
    """Project valid reads onto per-position-group reference columns.

    Returns (proj_bases (N, C), proj_quals (N, C), RefProjection,
    fallback (N,) bool, unanchored (N,) bool). Fallback rows are copied
    unchanged into columns [0, L) — the caller applies the classic
    modal-CIGAR policy to them. Unanchored rows (CIGAR consumes no
    reference) stay PAD; the caller must invalidate them so they don't
    inflate family size without contributing evidence.
    """
    n, l = bases.shape
    pk = np.asarray(pos_key)
    rp = np.asarray(read_pos)
    v = np.asarray(valid, bool)
    fallback = np.zeros(n, bool)
    unanchored = np.zeros(n, bool)

    # ---- pass 1: per-group column tables ----
    order = np.argsort(pk[v], kind="stable")
    vidx = np.nonzero(v)[0][order]
    runs = np.r_[0, np.nonzero(np.diff(pk[vidx]) != 0)[0] + 1, len(vidx)]

    cigs = {int(i): get_cigar(int(i)) for i in vidx}
    plans = []  # (group_reads, span_lo, ins dict, total_cols) | fallback
    width = l
    for s, e in zip(runs[:-1], runs[1:]):
        g = vidx[s:e]
        spans = {}
        ins_len: dict[int, int] = {}
        lo, hi = None, None
        for i in g.tolist():
            segs, ref_len = _cigar_spans(cigs[i])
            start = int(rp[i])
            if ref_len == 0:
                # no reference-anchored bases: nothing to place, and
                # its insertion boundaries may lie outside the group
                # span (they would KeyError at placement and inflate
                # the cap total for columns no anchored read shares)
                spans[i] = []
                continue
            spans[i] = segs
            lo = start if lo is None else min(lo, start)
            hi = start + ref_len if hi is None else max(hi, start + ref_len)
            # I boundaries of anchored reads always fall inside
            # [start, start + ref_len] and hence inside [lo, hi]
            for kind, _q, ln, roff in segs:
                if kind == "I":
                    p = start + roff
                    ins_len[p] = max(ins_len.get(p, 0), ln)
        total = (0 if lo is None else hi - lo) + sum(ins_len.values())
        if lo is None or total > cap_factor * l:
            fallback[g] = True
            plans.append((g, None, None, None, None))
            continue
        plans.append((g, lo, hi, ins_len, spans))
        width = max(width, total)

    proj_b = np.full((n, width), BASE_PAD, np.uint8)
    proj_q = np.zeros((n, width), np.uint8)
    proj = RefProjection(
        width=width, read_len=l, groups={}, fam_emit={}, group_emit={}
    )

    # ---- pass 2: place bases, count structure, decide emission ----
    u = umi.shape[1]
    for g, lo, hi, ins_len, spans in plans:
        if lo is None:
            proj_b[g, :l] = bases[g]
            proj_q[g, :l] = quals[g]
            proj.n_fallback_reads += len(g)
            proj.n_fallback_groups += 1
            continue
        # column table: insertion slots for boundary p sit BEFORE the
        # reference column of p (trailing insertions land after the
        # last reference column, at p == hi)
        col_pos, col_ins = [], []
        ins_start = {}
        for p in range(lo, hi + 1):
            k = ins_len.get(p, 0)
            if k:
                ins_start[p] = len(col_pos)
                col_pos.extend([p] * k)
                col_ins.extend(range(1, k + 1))
            if p < hi:
                col_pos.append(p)
                col_ins.append(0)
        col_pos = np.asarray(col_pos, np.int64)
        col_ins = np.asarray(col_ins, np.int32)
        cg = len(col_pos)
        # reference-column index lookup: ref position p -> its column
        ref_col = np.nonzero(col_ins == 0)[0]  # (hi - lo,) in p order
        gpk = int(pk[g[0]])
        proj.groups[gpk] = (col_pos, col_ins)

        # per-read placement + span tracking (unanchored reads have
        # empty span lists: their rows stay PAD, counted below)
        first_col = np.full(len(g), cg, np.int64)
        last_col = np.full(len(g), -1, np.int64)
        placed_cols: list[np.ndarray] = []
        placed_rows: list[np.ndarray] = []
        n_anchored = 0
        for j, i in enumerate(g.tolist()):
            if not spans[i]:
                proj.n_unanchored_reads += 1
                unanchored[i] = True
                continue
            n_anchored += 1
            start = int(rp[i])
            for kind, q0, ln, roff in spans[i]:
                if kind == "M":
                    cols = ref_col[start - lo + roff : start - lo + roff + ln]
                else:  # insertion before ref offset roff
                    c0 = ins_start[start + roff]
                    cols = np.arange(c0, c0 + ln)
                proj_b[i, cols] = bases[i, q0 : q0 + ln]
                proj_q[i, cols] = quals[i, q0 : q0 + ln]
                first_col[j] = min(first_col[j], int(cols[0]))
                last_col[j] = max(last_col[j], int(cols[-1]))
                placed_cols.append(cols)
                placed_rows.append(np.full(len(cols), j, np.int64))
        proj.n_projected_reads += n_anchored

        pc = np.concatenate(placed_cols) if placed_cols else np.zeros(0, np.int64)
        pr = np.concatenate(placed_rows) if placed_rows else np.zeros(0, np.int64)
        covered = last_col >= 0

        # structural decisions per family (pos_key, canonical UMI) and
        # per group (aggregate, for adjacency-merged minority UMIs)
        ub = umi[g].reshape(len(g), u)
        fam_keys = [r.tobytes() for r in ub]
        by_fam: dict[bytes, list[int]] = {}
        for j, kb in enumerate(fam_keys):
            by_fam.setdefault(kb, []).append(j)

        # placed-base counts for EVERY family in one pass over the
        # placed entries (a per-family np.isin would re-scan them
        # n_families times — quadratic on deep position groups)
        fam_list = list(by_fam.items())
        nf = len(fam_list)
        fidx = np.empty(len(g), np.int64)
        for fi, (_kb, members) in enumerate(fam_list):
            fidx[np.asarray(members)] = fi
        nb_f = np.bincount(
            fidx[pr] * cg + pc, minlength=nf * cg
        ).reshape(nf, cg)

        def decide(members: np.ndarray, n_base: np.ndarray) -> np.ndarray:
            m = members[covered[members]]
            n_span = np.zeros(cg + 1, np.int64)
            np.add.at(n_span, first_col[m], 1)
            np.add.at(n_span, last_col[m] + 1, -1)
            n_span = np.cumsum(n_span)[:cg]
            n_del = n_span - n_base
            emit = np.zeros(cg, np.uint8)
            is_ref = col_ins == 0
            emit[is_ref & (n_del > n_base)] = EMIT_DEL
            emit[~is_ref & ~(2 * n_base > n_span)] = EMIT_SKIP
            return emit

        all_j = np.arange(len(g))
        proj.group_emit[gpk] = decide(all_j, nb_f.sum(axis=0))
        for fi, (kb, members) in enumerate(fam_list):
            proj.fam_emit[(gpk, kb)] = decide(np.asarray(members), nb_f[fi])

    return proj_b, proj_q, proj, fallback, unanchored


def emit_columns(
    proj: RefProjection,
    pos_key: int,
    umi_bytes: bytes,
    cons_base_row: np.ndarray,  # (C,) u8/int codes, BASE_N where uncalled
) -> tuple[np.ndarray, list, int] | None:
    """Emission plan for one consensus row: (kept column indices,
    cigar [(n, op), ...], start ref position). None when the row's
    position group was never projected (fallback: legacy full-M)."""
    entry = proj.groups.get(pos_key)
    if entry is None:
        return None
    col_pos, col_ins = entry
    cg = len(col_pos)
    emit = proj.fam_emit.get((pos_key, umi_bytes))
    if emit is None:
        emit = proj.group_emit[pos_key]
    base = np.asarray(cons_base_row[:cg])
    keep = emit == EMIT
    called = keep & (base < 4)
    if not called.any():
        return None  # nothing real to place — caller falls back
    first = int(np.argmax(called))
    last = cg - 1 - int(np.argmax(called[::-1]))
    kept_idx = np.nonzero(keep[first : last + 1])[0] + first
    # CIGAR runs over [first, last]: M for ref columns, I for kept
    # insertion columns, D for majority-deleted ref columns; suppressed
    # insertion columns contribute nothing
    ops = np.full(cg, -1, np.int8)  # -1 skip, 0 M, 1 I, 2 D
    span = slice(first, last + 1)
    is_ref = col_ins == 0
    ops[span] = np.where(
        (emit == EMIT_DEL)[span], 2,
        np.where((emit == EMIT) & is_ref, 0,
                 np.where(emit == EMIT, 1, -1))[span],
    )
    cigar = []
    oseq = ops[span]
    oseq = oseq[oseq >= 0]
    if len(oseq):
        chg = np.r_[0, np.nonzero(np.diff(oseq) != 0)[0] + 1, len(oseq)]
        letters = "MID"
        for s, e in zip(chg[:-1], chg[1:]):
            cigar.append((int(e - s), letters[int(oseq[s])]))
    # leading/trailing D runs are illegal — the [first, last] trim above
    # guarantees the ends are called (EMIT) columns, so none can occur
    return kept_idx, cigar, int(col_pos[first])
