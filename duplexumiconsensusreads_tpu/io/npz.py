"""Packed .npz interchange for ReadBatch tensors.

The testing/benchmark format SURVEY.md §7 calls for ("a simple packed
.npz/Arrow interchange so tests don't need real BAMs"): a ReadBatch is
six named arrays in one compressed npz, loadable straight onto device.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.types import ReadBatch

_FIELDS = ("bases", "quals", "umi", "pos_key", "strand_ab", "frag_end", "valid")


def save_readbatch(path: str, batch: ReadBatch) -> None:
    np.savez_compressed(
        path, **{name: np.asarray(getattr(batch, name)) for name in _FIELDS}
    )


def load_readbatch(path: str) -> ReadBatch:
    with np.load(path) as z:
        fields = {}
        for name in _FIELDS:
            if name in z.files:
                fields[name] = z[name]
            elif name == "frag_end":  # pre-mate-aware npz files
                fields[name] = np.zeros(z["valid"].shape, bool)
            else:
                raise KeyError(f"ReadBatch npz missing field {name!r}")
        return ReadBatch(**fields)
