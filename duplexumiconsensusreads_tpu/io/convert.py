"""BamRecords ↔ ReadBatch conversion: where alignment records become
the HBM-resident padded tensors the kernels run on.

Conventions (the contract between io and grouping — SURVEY.md §7):

- **UMI**: the RX:Z aux tag, segments joined in read order ("ACG-TTG"
  → 6 codes). Reads with a missing RX or an N inside the UMI are marked
  invalid (the conventional fgbio/UMI-tools behaviour of dropping
  un-groupable reads) and counted in the returned info dict.
- **Duplex strand** (paired mode): a read observes the *top* (AB)
  strand iff it is read1-forward or read2-reverse (F1R2); the
  complementary F2R1 orientation is the bottom (BA) strand. For
  unpaired records the reverse flag alone decides. BA reads have their
  two UMI segments swapped so both strands of one source molecule carry
  the identical canonical UMI pair — molecule identity is then exactly
  (pos_key, clustered UMI) as oracle/grouping.py defines it.
- **pos_key**: i64 packing (ref_id << 36) | canonical fragment start,
  where the canonical start is min(pos, next_pos) for properly-paired
  records (both mates and both strands of a molecule share it) and pos
  otherwise.
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import BASE_PAD, N_REAL_BASES
from duplexumiconsensusreads_tpu.io.bam import (
    _CIGAR_OPS,
    FLAG_PAIRED,
    FLAG_READ1,
    FLAG_READ2,
    FLAG_REVERSE,
    BamHeader,
    BamRecords,
    consensus_excluded,
    make_aux_i,
    make_aux_z,
)
from duplexumiconsensusreads_tpu.types import ReadBatch
from duplexumiconsensusreads_tpu.utils.phred import pack_umi_words64

UMI_SEP = "-"
_POS_BITS = 36
_POS_MASK = (1 << _POS_BITS) - 1

_CHAR_TO_CODE = {c: i for i, c in enumerate("ACGT")}
_CODE_TO_CHAR = "ACGTN."
# u8 code -> ASCII byte, vectorised twin of _CODE_TO_CHAR (codes past
# the alphabet render as '.', same as the scalar path would index-error
# rather than emit — consensus UMIs only carry 0..3 in practice)
_CODE_CHARS = np.full(256, ord("."), np.uint8)
_CODE_CHARS[: len(_CODE_TO_CHAR)] = np.frombuffer(
    _CODE_TO_CHAR.encode("ascii"), np.uint8
)


# Sentinel key for unmapped records (ref_id < 0). samtools places
# unmapped reads at EOF of a coordinate-sorted BAM, so their key must
# sort AFTER every mapped key; sign-extending -1 through the shift/OR
# would instead give pos_key=-1 (sorts first) and trip the streaming
# sort-contract check on perfectly standard input.
UNMAPPED_POS_KEY = np.int64(1) << 62
_REF_ID_MAX = 1 << (62 - _POS_BITS)  # mapped keys must stay below the sentinel


def pack_pos_key(ref_id: np.ndarray, coord: np.ndarray) -> np.ndarray:
    ref_id = np.asarray(ref_id, np.int64)
    if (ref_id >= _REF_ID_MAX).any():
        # a mapped key must never alias UNMAPPED_POS_KEY (the streaming
        # chunker flushes sentinel keys without family hold-back) or
        # overflow i64; refuse rather than silently corrupt grouping
        raise ValueError(
            f"ref_id >= {_REF_ID_MAX} cannot be packed into a pos_key "
            f"({_POS_BITS} position bits); re-shard the reference"
        )
    coord = np.maximum(np.asarray(coord, np.int64), 0)
    key = (np.maximum(ref_id, 0) << _POS_BITS) | (coord & _POS_MASK)
    return np.where(ref_id < 0, UNMAPPED_POS_KEY, key)


def unpack_pos_key(key: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    key = np.asarray(key, np.int64)
    return (key >> _POS_BITS).astype(np.int32), (key & _POS_MASK).astype(np.int32)


def umi_string_to_codes(rx: str) -> np.ndarray | None:
    """RX string → u8 codes; None if any base is not ACGT."""
    s = rx.replace(UMI_SEP, "")
    codes = np.empty(len(s), np.uint8)
    for i, c in enumerate(s.upper()):
        v = _CHAR_TO_CODE.get(c)
        if v is None:
            return None
        codes[i] = v
    return codes


def load_umi_whitelist(path: str) -> np.ndarray:
    """Read an expected-UMI list (one ACGT string per line, '#'
    comments and blanks skipped) into an (W, U) u8 code matrix.
    All entries must share one length (the fgbio CorrectUmis input
    contract); raises ValueError otherwise."""
    entries = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            s = line.strip()
            if not s or s.startswith("#"):
                continue
            codes = umi_string_to_codes(s)
            if codes is None:
                raise ValueError(
                    f"{path}:{ln}: non-ACGT UMI {s!r} in whitelist"
                )
            entries.append(codes)
    if not entries:
        raise ValueError(f"{path}: empty UMI whitelist")
    lens = {len(e) for e in entries}
    if len(lens) != 1:
        raise ValueError(
            f"{path}: whitelist mixes UMI lengths {sorted(lens)}"
        )
    return np.stack(entries)


def correct_umis_whitelist(
    batch, whitelist: np.ndarray, max_mismatches: int = 1
) -> dict:
    """fgbio CorrectUmis analogue, as an input policy: snap every valid
    read's UMI (each half independently in duplex mode) to its UNIQUE
    nearest whitelist entry within ``max_mismatches``; reads whose half
    has no whitelist entry close enough, or ties between two entries,
    are invalidated (counted, never silently kept — a wrong-molecule
    merge is the error class UMIs exist to prevent).

    Mutates batch.umi/batch.valid in place. Returns counters:
    n_umi_corrected (reads with >=1 half changed),
    n_dropped_whitelist (reads invalidated). Runs BEFORE grouping,
    mixed-mate detection, and projection, so every family-identity
    consumer sees corrected UMIs.
    """
    v = np.asarray(batch.valid, bool)
    idx = np.nonzero(v)[0]
    if not len(idx):
        return {"n_umi_corrected": 0, "n_dropped_whitelist": 0}
    u = np.asarray(batch.umi)[idx]  # (n, U)
    w_len = whitelist.shape[1]
    total = u.shape[1]
    if total % w_len != 0 or total // w_len not in (1, 2):
        raise ValueError(
            f"whitelist UMI length {w_len} does not divide the input "
            f"UMI length {total} into 1 or 2 halves"
        )
    halves = total // w_len
    changed = np.zeros(len(idx), bool)
    bad = np.zeros(len(idx), bool)
    for h in range(halves):
        part = u[:, h * w_len : (h + 1) * w_len]
        # (n, W) mismatch counts, blocked to bound peak memory
        best = np.full(len(idx), 255, np.uint8)
        second = np.full(len(idx), 255, np.uint8)
        best_w = np.zeros(len(idx), np.int64)
        block = max(1, (32 << 20) // max(len(whitelist) * w_len, 1))
        for s in range(0, len(idx), block):
            e = min(s + block, len(idx))
            d = (part[s:e, None, :] != whitelist[None, :, :]).sum(
                axis=2
            ).astype(np.uint8)
            o = np.argsort(d, axis=1)[:, :2]
            best[s:e] = d[np.arange(e - s), o[:, 0]]
            best_w[s:e] = o[:, 0]
            second[s:e] = (
                d[np.arange(e - s), o[:, 1]]
                if d.shape[1] > 1
                else np.uint8(255)
            )
        ok = (best <= max_mismatches) & (second > best)
        bad |= ~ok
        hit = ok & (best > 0)
        changed |= hit
        part[ok] = whitelist[best_w[ok]]
        u[:, h * w_len : (h + 1) * w_len] = part
    batch.umi[idx] = u
    batch.valid[idx[bad]] = False
    changed &= ~bad
    return {
        "n_umi_corrected": int(changed.sum()),
        "n_dropped_whitelist": int(bad.sum()),
    }


def umi_codes_to_string(codes: np.ndarray, paired: bool) -> str:
    s = "".join(_CODE_TO_CHAR[int(c)] for c in codes)
    if paired:
        h = len(s) // 2
        return s[:h] + UMI_SEP + s[h:]
    return s


def read_is_top_strand(flag: int) -> bool:
    if flag & FLAG_PAIRED:
        r1 = bool(flag & FLAG_READ1)
        rev = bool(flag & FLAG_REVERSE)
        return r1 != rev  # F1R2 → top
    return not flag & FLAG_REVERSE


def records_pos_keys(recs: BamRecords) -> np.ndarray:
    """Canonical fragment pos_key per record — THE grouping key.

    Single source of truth shared by batch conversion and the
    streaming chunker (whose family-integrity guarantee requires the
    chunk-boundary key to be byte-identical to the grouping key).
    """
    flags = np.asarray(recs.flags)
    paired_ok = (
        (flags & FLAG_PAIRED).astype(bool)
        & (recs.next_ref_id == recs.ref_id)
        & (recs.next_pos >= 0)
    )
    coord = np.where(paired_ok, np.minimum(recs.pos, recs.next_pos), recs.pos)
    return pack_pos_key(recs.ref_id, coord)


_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
# derived from io/bam.py's single spec constant — the FNV hash parity
# between both codecs depends on this mapping staying identical
_CIGAR_OP_IDX = {c: i for i, c in enumerate(_CIGAR_OPS)}


def cigar_hashes(cigars) -> np.ndarray:
    """FNV-1a64 over each record's BAM-encoded cigar op words — MUST
    stay bit-identical to the native loader's fnv1a64 over the raw
    cigar bytes (bamloader.cpp). 0 for cigar-less records."""
    out = np.empty(len(cigars), np.uint64)
    for i, cig in enumerate(cigars):
        if not cig:
            out[i] = 0
            continue
        h = _FNV_OFFSET
        for n_op, op in cig:
            v = (int(n_op) << 4) | _CIGAR_OP_IDX[op]
            for b in v.to_bytes(4, "little"):
                h = ((h ^ b) * _FNV_PRIME) & 0xFFFFFFFFFFFFFFFF
        out[i] = h
    return out


def modal_cigar_keep(
    pos_key: np.ndarray,  # (N,) i64
    umi: np.ndarray,  # (N, U) u8 canonical codes
    valid: np.ndarray,  # (N,) bool
    cig_hash: np.ndarray,  # (N,) u64
    strand_ab: np.ndarray | None = None,  # (N,) bool
) -> np.ndarray:
    """CIGAR/indel policy (VERDICT r1 item 6): within each EXACT family
    (pos_key, canonical UMI, strand), keep only reads carrying the
    family's modal CIGAR (ties to the smaller hash). Consensus math
    operates on raw cycles, so a read whose alignment differs from its
    family's (indel, clipping) would misalign every downstream column;
    a true indel-bearing molecule keeps its own family intact because
    ALL its reads share the indel CIGAR. The A/B strand sub-families
    are independent alignments that can legitimately differ in
    soft-clipping, so the modal vote runs PER STRAND (ADVICE r2) —
    keying on (pos, UMI) alone would silently drop a whole minority
    strand and downgrade the molecule from duplex to single-strand.
    Exact-family granularity is chosen over adjacency-cluster
    granularity so the filter can run at input conversion, identically
    for the oracle and the device pipeline.
    Returns the reduced validity mask."""
    idx = np.nonzero(np.asarray(valid, bool))[0]
    if not len(idx):
        return np.asarray(valid, bool).copy()
    # fast path: one CIGAR shape across the whole batch (the normal
    # uniform-length case) — every read is trivially modal
    ch_all = cig_hash[idx]
    if (ch_all == ch_all[0]).all():
        return np.asarray(valid, bool).copy()
    fam = _family_cols(pos_key, umi, idx)
    if strand_ab is not None:
        fam = np.column_stack(
            [fam, np.asarray(strand_ab, bool)[idx][:, None].astype(np.int64)]
        )
    # flip the sign bit so int64 comparison reproduces UNSIGNED hash
    # order ("ties to the smaller u64 hash" stays literally true)
    ch = (cig_hash[idx] ^ np.uint64(1 << 63)).view(np.int64)
    key = np.column_stack([fam, ch[:, None]])
    uniq, inv, cnt = np.unique(key, axis=0, return_inverse=True, return_counts=True)
    w = uniq.shape[1] - 1
    order = np.lexsort((uniq[:, w], -cnt, *[uniq[:, j] for j in range(w - 1, -1, -1)]))
    fam_sorted = uniq[order, :w]
    first = np.nonzero(
        np.r_[True, (fam_sorted[1:] != fam_sorted[:-1]).any(axis=1)]
    )[0]
    winner = np.zeros(len(uniq), bool)
    winner[order[first]] = True
    keep = np.asarray(valid, bool).copy()
    keep[idx] = winner[inv]
    return keep


def _cigar_edges(cig):
    """(lead_soft, core_ops, trail_soft, core_query_len) — the CIGAR
    split the soft-clip rescue compares on: edge S ops stripped, the
    aligned core kept verbatim."""
    if not cig:
        return 0, (), 0, 0
    i0, i1 = 0, len(cig)
    lead = trail = 0
    if cig[0][1] == "S":
        lead, i0 = cig[0][0], 1
    if i1 > i0 and cig[-1][1] == "S":
        trail, i1 = cig[-1][0], i1 - 1
    core = tuple(cig[i0:i1])
    qlen = sum(n for n, op in core if op in "MIS=X")
    return lead, core, trail, qlen


def softclip_rescue(
    bases: np.ndarray,  # (N, L) u8, MUTATED for rescued rows
    quals: np.ndarray,  # (N, L) u8, MUTATED for rescued rows
    keep: np.ndarray,  # (N,) bool modal-vote result, updated in place
    valid: np.ndarray,  # (N,) bool pre-CIGAR validity
    pos_key: np.ndarray,
    umi: np.ndarray,
    strand_ab: np.ndarray,
    read_pos: np.ndarray,  # (N,) i32 each record's OWN alignment start
    get_cigar,  # callable i -> [(n, op), ...]
    l_cap: int | None = None,  # true cycle width; defaults to the
    # matrix width, which is ONLY correct for unprojected batches — a
    # ref-projected caller must pass read_len, since its fallback rows
    # live in cycle space [0, read_len) inside a wider projected matrix
    # and a rescue spilling past read_len would be silently truncated
    # at emission
) -> dict:
    """Rescue minority-CIGAR reads whose difference from their family's
    modal CIGAR is SOFT-CLIPPING ONLY (identical aligned core): instead
    of dropping their evidence, trim to the aligned span and shift into
    the modal reads' cycle space (query q of the rescued read covers
    the same reference offset as modal query q - lead_r + lead_m,
    because the rescue REQUIRES the read's own alignment start to equal
    the donor's — family membership alone does not imply it: paired
    mates share (pos_key, UMI, strand) while their own POS differ, and
    repeat-region minority alignments can start a few bases off; a
    shift computed from clip leads alone would inject misaligned
    evidence, the exact corruption the modal vote exists to prevent).
    The read's own clipped bases are masked PAD — they were clipped
    for a reason. Runs at input
    conversion in BOTH codecs, so the oracle and device pipelines see
    the identical transformed batch (VERDICT r3 item 7).

    Returns counters: n_rescued_cigar, and the per-strand evidence-loss
    split n_dropped_cigar_ab / n_dropped_cigar_ba of the reads that
    stayed dropped (per-strand because losing one strand downgrades a
    molecule from duplex to single-strand — an invisible cost when only
    the aggregate was reported).
    """
    from duplexumiconsensusreads_tpu.constants import BASE_PAD

    v = np.asarray(valid, bool)
    sab = np.asarray(strand_ab, bool)
    dropped = np.nonzero(v & ~keep)[0]
    n_rescued = 0
    rp = np.asarray(read_pos)
    if len(dropped):
        kept_idx = np.nonzero(v & keep)[0]
        # the donor key includes the read's OWN alignment start, so each
        # mate side (and each distinct minority start) gets its own
        # donor — keying by family alone let the first kept mate shadow
        # rescues whose span matched a later same-POS kept read
        # (advisor r4 finding)
        famk = _family_cols(pos_key, umi, kept_idx)
        famk = np.column_stack(
            [famk, sab[kept_idx].astype(np.int64), rp[kept_idx].astype(np.int64)]
        )
        dfam = _family_cols(pos_key, umi, dropped)
        dfam = np.column_stack(
            [dfam, sab[dropped].astype(np.int64), rp[dropped].astype(np.int64)]
        )
        # vectorised pre-filter BEFORE any per-record Python: the vote
        # drops a handful of reads but the kept set is the whole chunk —
        # restrict it to rows of families that actually lost a read
        # (realistic indel inputs hit this path on nearly every chunk)
        allrows = np.concatenate([dfam, famk])
        _u, inv = np.unique(allrows, axis=0, return_inverse=True)
        d_ids = np.unique(inv[: len(dfam)])
        hit = np.isin(inv[len(dfam):], d_ids)
        kept_idx, famk = kept_idx[hit], famk[hit]
        modal_of: dict = {}
        for row, i in zip(map(tuple, famk.tolist()), kept_idx.tolist()):
            modal_of.setdefault(row, i)
        if l_cap is None:
            l_cap = bases.shape[1]
        for row, i in zip(map(tuple, dfam.tolist()), dropped.tolist()):
            m = modal_of.get(row)
            if m is None:
                # no kept read shares this (family, strand, own-POS):
                # other mate / shifted alignment, or the whole family
                # was dropped elsewhere (not by the vote)
                continue
            lead_r, core_r, _tr, qlen = _cigar_edges(get_cigar(i))
            lead_m, core_m, _tm, _q = _cigar_edges(get_cigar(m))
            if not core_r or core_r != core_m or lead_m + qlen > l_cap:
                continue
            span_b = bases[i, lead_r : lead_r + qlen].copy()
            span_q = quals[i, lead_r : lead_r + qlen].copy()
            bases[i, :] = BASE_PAD
            quals[i, :] = 0
            bases[i, lead_m : lead_m + qlen] = span_b
            quals[i, lead_m : lead_m + qlen] = span_q
            keep[i] = True
            n_rescued += 1
    still = v & ~keep
    return {
        "n_rescued_cigar": n_rescued,
        "n_dropped_cigar_ab": int((still & sab).sum()),
        "n_dropped_cigar_ba": int((still & ~sab).sum()),
    }


def _family_cols(pos_key, umi, idx) -> np.ndarray:
    """THE exact-family key columns — (pos_key, packed UMI words) per
    selected read. Single source of truth for every conversion-time
    family grouping (modal-CIGAR filter, mixed-mate detection)."""
    return np.column_stack(
        [np.asarray(pos_key)[idx][:, None], pack_umi_words64(np.asarray(umi)[idx])]
    )


MIXED_MATE_WARNING = (
    "input families contain both R1 and R2 mates: cycle-space "
    "consensus would mix opposite fragment ends. Use mate-aware "
    "calling (--mate-aware on, the default auto resolution) or "
    "split the input by read number (samtools view -f 64 / "
    "-f 128). See n_mixed_mate_families in the report."
)


def warn_mixed_mates(
    flags: np.ndarray, pos_key, umi, strand_ab, valid, warn: bool = True
) -> tuple[int, bool]:
    """Detect families containing BOTH R1 and R2 mates.

    Cycle-space consensus assumes every family member covers the same
    cycles; a template's two mates cover opposite fragment ends, so
    merging them corrupts columns. Mate-aware grouping
    (GroupingParams.mate_aware, resolved automatically by the CLI)
    handles this properly by splitting families on the fragment-end
    bit and emitting consensus R1+R2 pairs; callers that run WITHOUT
    mate-aware grouping leave ``warn`` on so the hazard stays loud.
    Must run on the PRE-CIGAR-filter mask: mates often differ in
    soft-clips, so the modal-CIGAR filter would hide exactly the
    families this check exists to surface. Returns (n_mixed,
    mixed_present): the number of affected exact families — a LOWER
    bound under adjacency grouping (a mate with an errored UMI joins
    its cluster but forms a distinct exact key here) — and whether any
    family actually mixes the two mates (the CLI's mate-aware
    auto-detection signal). Mere R1+R2 flag PRESENCE is deliberately
    not the signal: classic one-read-per-strand F1R2/F2R1 inputs carry
    both flags yet every strand-keyed family is single-mate, and
    mate-aware grouping must stay off there (it provably changes
    nothing for such inputs, but the emitted records would gain paired
    flags).
    """
    import warnings as _warnings

    v = np.asarray(valid, bool)
    idx = np.nonzero(v)[0]
    if not len(idx):
        return 0, False
    fl = np.asarray(flags)[idx]
    paired = (fl & FLAG_PAIRED) != 0
    if not paired.any():
        return 0, False
    r1 = ((fl & FLAG_READ1) != 0) & paired
    r2 = ((fl & FLAG_READ2) != 0) & paired
    # inputs split by read number (the recommended workflow) skip the
    # family grouping entirely
    if not (r1.any() and r2.any()):
        return 0, False
    key = np.column_stack(
        [
            _family_cols(pos_key, umi, idx),
            np.asarray(strand_ab, bool)[idx][:, None].astype(np.int64),
        ]
    )
    uniq, inv = np.unique(key, axis=0, return_inverse=True)
    has_r1 = np.zeros(len(uniq), bool)
    has_r2 = np.zeros(len(uniq), bool)
    np.logical_or.at(has_r1, inv, r1)
    np.logical_or.at(has_r2, inv, r2)
    n_mixed = int((has_r1 & has_r2).sum())
    if n_mixed and warn:
        # stable text (no counts) so the warnings module dedups it on
        # chunked runs; the count travels in info/run reports instead
        _warnings.warn(MIXED_MATE_WARNING)
    return n_mixed, n_mixed > 0


def mixed_ends_present(batch) -> bool:
    """True iff some exact (pos_key, UMI, strand) family holds reads of
    BOTH fragment ends — the batch-level twin of warn_mixed_mates'
    mixed-mate detection, for inputs that carry no BAM flags (npz).
    Mere presence of second-end reads is NOT the signal: a
    split-by-read-number file has end-2 reads (bottom-strand R1) in
    every family, yet each family is single-end and mate-aware grouping
    must stay off for it."""
    v = np.asarray(batch.valid, bool)
    idx = np.nonzero(v)[0]
    if not len(idx):
        return False
    e2 = np.asarray(batch.frag_end, bool)[idx]
    if not e2.any() or e2.all():
        return False
    key = np.column_stack(
        [
            _family_cols(batch.pos_key, batch.umi, idx),
            np.asarray(batch.strand_ab, bool)[idx][:, None].astype(np.int64),
        ]
    )
    uniq, inv = np.unique(key, axis=0, return_inverse=True)
    has1 = np.zeros(len(uniq), bool)
    has2 = np.zeros(len(uniq), bool)
    np.logical_or.at(has1, inv, ~e2)
    np.logical_or.at(has2, inv, e2)
    return bool((has1 & has2).any())


def downsample_families(batch, max_reads: int) -> int:
    """Cap every exact sub-family (pos_key, UMI, strand, fragment end)
    at ``max_reads`` reads, keeping the highest-summed-quality reads
    (ties break to the earliest record — deterministic). Extra reads
    are marked invalid in place; returns how many were dropped.

    This is the input-policy analogue of the reference domain's
    --max-reads: beyond ~20 reads the consensus posterior is saturated,
    so pathological families (primer stacks, optical duplicates of
    duplicates) only cost compute and pad jumbo buckets. Applied on the
    host BEFORE grouping — the same stage as every other input policy
    here (SAM-flag exclusion, min-input-qual, the modal-CIGAR filter),
    so both backends and both executors see the identical capped input.
    Two documented consequences of the pre-grouping semantics:
    - under adjacency grouping, the directional count-ratio rule sees
      CAPPED counts, so an error-UMI sub-family at >= max_reads reads
      may stay unmerged where uncapped counts would have absorbed it
      (tools that downsample after a separate grouping step — fgbio's
      CallMolecularConsensusReads after GroupReadsByUmi — do not have
      this edge; here grouping is fused). Choose max_reads comfortably
      above the error-family size (>= 20) to keep the edge negligible.
    - a directional cluster may still merge several capped
      sub-families, so a cluster's total depth can exceed max_reads.
    """
    v = np.asarray(batch.valid, bool)
    idx = np.nonzero(v)[0]
    if max_reads <= 0 or not len(idx):
        return 0
    key = np.column_stack(
        [
            _family_cols(batch.pos_key, batch.umi, idx),
            np.asarray(batch.strand_ab, bool)[idx][:, None].astype(np.int64),
            np.asarray(batch.frag_end, bool)[idx][:, None].astype(np.int64),
        ]
    )
    _, inv = np.unique(key, axis=0, return_inverse=True)
    bases = np.asarray(batch.bases)[idx]
    quals = np.asarray(batch.quals)[idx]
    score = (quals.astype(np.int64) * (bases < N_REAL_BASES)).sum(axis=1)
    order = np.lexsort((idx, -score, inv))  # family, then best-first
    sf = inv[order]
    rank = np.arange(len(sf)) - np.searchsorted(sf, sf, side="left")
    drop = rank >= max_reads
    batch.valid[idx[order[drop]]] = False
    return int(drop.sum())


def records_to_readbatch(
    recs: BamRecords, duplex: bool = True, warn_mixed: bool = True,
    ref_projected: bool = False, mate_aware: str = "off",
    umi_whitelist: np.ndarray | None = None, umi_max_mismatches: int = 1,
) -> tuple[ReadBatch, dict]:
    """Convert parsed BAM records into a padded ReadBatch.

    Returns (batch, info); info counts reads dropped for missing/N UMIs,
    inconsistent UMI length, excluded FLAGs, or a CIGAR differing from
    the exact family's modal CIGAR. Dropped reads occupy invalid slots
    so read indices stay aligned with ``recs``. ``warn_mixed=False``
    suppresses the mixed-mate warning (mate-aware callers handle those
    families; the counter still fills).

    ref_projected=True places reads on per-position-group REFERENCE
    columns instead of cycles (io/refproject.py): indel-bearing reads
    contribute realigned evidence instead of being dropped, and
    info["ref_projection"] carries the column metadata the emission
    side needs. Groups that cannot project (span too wide) keep the
    classic cycle layout + modal-CIGAR policy. ``mate_aware`` (the CLI
    setting: auto/on/off) decides the projection grouping: when it
    resolves on (auto = mixed mates present — the same rule the
    executor applies), column tables split by fragment end so each
    mate side projects around its own alignment span instead of one
    fragment-length-wide table that would blow the span cap.
    """
    n = len(recs)
    l = recs.seq.shape[1] if n else 0
    flags = np.asarray(recs.flags)
    excluded = consensus_excluded(flags, recs.ref_id)
    n_flag_excluded = int(excluded.sum())

    umi_len = 0
    umi_codes: list[np.ndarray | None] = []
    for i, rx in enumerate(recs.umi):
        # excluded reads skip UMI parsing entirely — their codes are
        # never consumed, and a large unmapped/secondary tail would
        # otherwise burn per-char Python time for nothing
        codes = umi_string_to_codes(rx) if (rx and not excluded[i]) else None
        umi_codes.append(codes)
        if codes is not None and len(codes) > umi_len:
            umi_len = len(codes)

    batch = ReadBatch.empty(n, l, umi_len)
    n_no_umi = n_bad_len = 0
    pos_key = records_pos_keys(recs)

    for i in range(n):
        if excluded[i]:
            continue
        codes = umi_codes[i]
        if codes is None:
            n_no_umi += 1
            continue
        if len(codes) != umi_len:
            n_bad_len += 1
            continue
        fl = int(flags[i])
        top = read_is_top_strand(fl)
        if duplex and not top:
            h = umi_len // 2
            codes = np.concatenate([codes[h:], codes[:h]])
        batch.umi[i] = codes
        batch.strand_ab[i] = top
        # fragment-end bit: top-R1 and bottom-R2 observe end 1 (the
        # cross-mate duplex partners); single-end records are end 1
        batch.frag_end[i] = bool(fl & FLAG_PAIRED) and (
            bool(fl & FLAG_READ2) == top
        )
        batch.valid[i] = True
    batch.bases[:] = recs.seq
    batch.quals[:] = recs.qual
    batch.pos_key[:] = pos_key

    # whitelist UMI correction FIRST (CorrectUmis analogue): every
    # family-identity consumer below — mixed-mate detection, the
    # projection grouping, the modal-CIGAR vote — must see corrected
    # UMIs, or a heals-to-the-same-molecule read would split a family
    wl_info = {}
    if umi_whitelist is not None:
        wl_info = correct_umis_whitelist(
            batch, umi_whitelist, umi_max_mismatches
        )

    # mixed-mate detection BEFORE the CIGAR filter: mates often differ
    # in soft-clips, so the modal filter would hide exactly these
    n_mixed, mixed_present = warn_mixed_mates(
        flags, batch.pos_key, batch.umi, batch.strand_ab, batch.valid,
        warn=warn_mixed,
    )
    n_before = int(batch.valid.sum())
    proj = None
    if ref_projected:
        from duplexumiconsensusreads_tpu.io.refproject import ref_project

        mate_split = mate_aware == "on" or (
            mate_aware == "auto" and mixed_present
        )
        gk = np.asarray(batch.pos_key) * 2 + (
            np.asarray(batch.frag_end).astype(np.int64) if mate_split else 0
        )
        pb, pq, proj, fb, unanch = ref_project(
            batch.bases, batch.quals, batch.valid, gk,
            batch.umi, np.asarray(recs.pos), lambda i: recs.cigars[i],
        )
        proj.mate_split = mate_split
        widened = ReadBatch.empty(n, proj.width, umi_len)
        widened.bases[:] = pb
        widened.quals[:] = pq
        for f in ("umi", "pos_key", "strand_ab", "frag_end", "valid"):
            getattr(widened, f)[:] = getattr(batch, f)
        batch = widened
        # unanchored reads (CIGAR consumes no reference) placed nothing:
        # an all-PAD row would inflate family size (min-reads gates,
        # depth denominators) without contributing evidence — invalidate
        # them after counting (proj.n_unanchored_reads above)
        batch.valid &= ~unanch
        batch.strand_ab &= ~unanch
        batch.frag_end &= ~unanch
        # the classic policy applies only to the fallback groups, whose
        # rows kept the cycle layout in columns [0, L)
        policy_valid = batch.valid & fb
    else:
        policy_valid = batch.valid
    keep = modal_cigar_keep(
        batch.pos_key, batch.umi, policy_valid, cigar_hashes(recs.cigars),
        batch.strand_ab,
    )
    keep |= batch.valid & ~policy_valid  # projected reads are all kept
    rescue_info = softclip_rescue(
        batch.bases, batch.quals, keep, policy_valid, batch.pos_key,
        batch.umi, batch.strand_ab, np.asarray(recs.pos),
        lambda i: recs.cigars[i],
        l_cap=(proj.read_len if proj is not None else None),
    )
    batch.valid &= keep
    batch.strand_ab &= keep
    batch.frag_end &= keep
    n_cigar = n_before - int(batch.valid.sum())
    if proj is not None:
        # unanchored invalidations have their own counter
        # (n_projection_unanchored_reads); keep the drop counters disjoint
        n_cigar -= proj.n_unanchored_reads

    info = {
        "n_records": n,
        "n_valid": int(batch.valid.sum()),
        "n_dropped_no_umi": n_no_umi,
        "n_dropped_umi_len": n_bad_len,
        "n_dropped_flag": n_flag_excluded,
        "n_dropped_cigar": n_cigar,
        **rescue_info,
        "n_mixed_mate_families": n_mixed,
        "mixed_mates": mixed_present,
        "umi_len": umi_len,
        **wl_info,
    }
    if proj is not None:
        info["ref_projection"] = proj
        info["n_projected_reads"] = proj.n_projected_reads
        info["n_projection_fallback_reads"] = proj.n_fallback_reads
        info["n_projection_fallback_groups"] = proj.n_fallback_groups
        info["n_projection_unanchored_reads"] = proj.n_unanchored_reads
    return batch, info


def readbatch_to_records(
    batch: ReadBatch,
    duplex: bool = True,
    names: list[str] | None = None,
    paired_end: bool = False,
) -> BamRecords:
    """Inverse of records_to_readbatch for synthetic data: emit records
    whose flags encode the strand and whose RX segments are
    de-canonicalised (swapped back for BA reads).

    paired_end=False emits single-end records (reverse flag = strand).
    paired_end=True emits paired-style flags instead, derived from the
    strand AND fragment-end bits: read number = frag_end XOR
    bottom-strand, reverse iff the read number equals the top-strand
    bit (so a frag_end-free batch reproduces the classic F1R2/F2R1
    one-read-per-strand convention) — with a mate pointer at the same
    position, exercising the full paired strand/mate derivation and
    min(pos, next_pos) pos_key path end-to-end.
    """
    from duplexumiconsensusreads_tpu.io.bam import FLAG_MATE_REVERSE

    valid = np.asarray(batch.valid, bool)
    idx = np.nonzero(valid)[0]
    n = len(idx)
    l = batch.read_len
    lengths = np.full(n, l, np.int32)
    ref_id, pos = unpack_pos_key(np.asarray(batch.pos_key)[idx])
    strand = np.asarray(batch.strand_ab, bool)[idx]
    if paired_end:
        e2 = np.asarray(batch.frag_end, bool)[idx]
        r2 = e2 ^ ~strand
        rev = r2 == strand
        flags = (
            FLAG_PAIRED
            | np.where(r2, FLAG_READ2, FLAG_READ1)
            | np.where(rev, FLAG_REVERSE, 0)
            | np.where(rev, 0, FLAG_MATE_REVERSE)
        ).astype(np.uint16)
    else:
        flags = np.where(strand, 0, FLAG_REVERSE).astype(np.uint16)

    umis = []
    for j, i in enumerate(idx):
        codes = np.asarray(batch.umi)[i]
        if duplex and not strand[j]:
            h = len(codes) // 2
            codes = np.concatenate([codes[h:], codes[:h]])
        umis.append(umi_codes_to_string(codes, paired=duplex))

    seq = np.asarray(batch.bases)[idx]
    # PAD cycles inside a record are not representable; render as N
    seq = np.where(seq == BASE_PAD, 4, seq).astype(np.uint8)

    if paired_end:
        # mate points at the same fragment start so pos_key (min of the
        # two coordinates) round-trips exactly
        next_ref_id = ref_id.copy()
        next_pos = pos.copy()
        tlen = np.full(n, l, np.int32)
    else:
        next_ref_id = np.full(n, -1, np.int32)
        next_pos = np.full(n, -1, np.int32)
        tlen = np.zeros(n, np.int32)
    return BamRecords(
        # fixed-width names give every record an identical byte layout,
        # unlocking the uniform vectorised serializer (io/bam.py)
        names=(names or [f"read{i:010d}" for i in idx]),
        flags=flags,
        ref_id=ref_id,
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=next_ref_id,
        next_pos=next_pos,
        tlen=tlen,
        lengths=lengths,
        seq=seq,
        qual=np.asarray(batch.quals)[idx],
        cigars=[[(l, "M")] for _ in range(n)],
        umi=umis,
        aux_raw=[make_aux_z("RX", u) for u in umis],
    )


def depth_stats(depth: np.ndarray) -> np.ndarray:
    """(F, L) per-cycle depth -> (F, 2) [cD = max depth, cM = min
    positive depth]. int64 up front: masking with the int64-max
    sentinel in the source's int32 dtype would wrap to -1 under NEP 50
    promotion. The device pipeline computes the same two stats on
    device (ops/pipeline.py) so the padded matrix never crosses the
    host link."""
    d = np.asarray(depth, np.int64)
    n = d.shape[0]
    if not d.size:
        return np.zeros((n, 2), np.int64)
    c_max = d.max(axis=1)
    masked = np.where(d > 0, d, np.iinfo(np.int64).max)
    c_min = np.where((d > 0).any(axis=1), masked.min(axis=1), 0)
    return np.stack([c_max, c_min], axis=1)


def consensus_to_records(
    cons_base: np.ndarray,  # (F, L) u8
    cons_qual: np.ndarray,  # (F, L) u8
    cons_dstats: np.ndarray,  # (F, 2) i64 [cD, cM] — see depth_stats()
    cons_valid: np.ndarray,  # (F,) bool
    fam_pos_key: np.ndarray,  # (F,) i64 representative pos_key per family
    fam_umi: np.ndarray,  # (F, U) u8 representative canonical UMI per family
    duplex: bool,
    name_prefix: str = "cons",
    cons_mate: np.ndarray | None = None,  # (F,) second-mate bit
    cons_pair: np.ndarray | None = None,  # (F,) i64 template link
    paired_out: bool = False,
    cons_pdepth: np.ndarray | None = None,  # (F, L) per-base depth -> cd:B,I
    cons_perr: np.ndarray | None = None,  # (F, L) per-base errors -> ce:B,I
    read_group: str | None = None,  # RG:Z on every record (fgbio-style
    # single consensus read group; the header gains the matching @RG)
    proj=None,  # RefProjection: reference-column emission (io/refproject)
    cons_end: np.ndarray | None = None,  # (F,) unit fragment-end bit —
    # required for proj.mate_split lookups (key = pos_key*2 + end)
) -> BamRecords:
    """Build consensus BAM records from (scattered-back) pipeline output.

    Emitted per valid family/molecule: a mapped record at the family's
    canonical position with RX (canonical UMI), cD (max depth) and cM
    (min positive depth) aux tags — the fgbio-style consensus metadata.

    paired_out=True (mate-aware runs) re-links output rows into
    consensus R1/R2 mates: two rows sharing a cons_pair value with
    opposite cons_mate bits become a proper read pair — shared qname,
    FLAG_PAIRED|PROPER|READ1/READ2, mate pointer at the shared
    canonical position. Rows whose partner emitted no consensus (e.g.
    one fragment end failed min_duplex_reads) stay single-end records.
    """
    idx = np.nonzero(np.asarray(cons_valid, bool))[0]
    n = len(idx)
    l = cons_base.shape[1]
    ref_id, pos = unpack_pos_key(fam_pos_key[idx])

    # -------- reference-column emission (--ref-projected) --------
    # Per row: keep the family's emitted columns, derive the consensus
    # CIGAR from the structural majorities decided at projection, and
    # move POS to the first called reference column. Rows whose group
    # fell back (or called nothing) keep the legacy full-M emission.
    plan = [None] * n
    if proj is not None:
        if proj.mate_split and cons_end is None:
            raise ValueError(
                "mate-split ref-projection needs cons_end (the unit "
                "fragment-end bits) to address its column tables"
            )
        from duplexumiconsensusreads_tpu.io.refproject import emit_columns

        for k in range(n):
            i = int(idx[k])
            gk = int(fam_pos_key[i]) * 2 + (
                int(cons_end[i]) if proj.mate_split else 0
            )
            plan[k] = emit_columns(
                proj, gk, fam_umi[i].tobytes(), cons_base[i]
            )
            if plan[k] is not None:
                pos[k] = plan[k][2]

    # per-record emitted lengths + reference spans. In a projected run
    # the matrices are proj.width wide, but fallback rows only ever
    # held cycles [0, read_len) — emitting the full width would pad
    # their SEQ/CIGAR/cd/ce out to the widest projected group. The
    # reference span (M+D) feeds the mate-pair PNEXT/TLEN below, where
    # projection can move the two mates' POS apart.
    base_len = l if proj is None else proj.read_len
    lens = np.full(n, base_len, np.int32)
    ref_len_v = np.full(n, base_len, np.int64)
    for k, p in enumerate(plan):
        if p is not None:
            lens[k] = len(p[0])
            ref_len_v[k] = sum(nn for nn, op in p[1] if op in "MD")

    # -------- mate-pair linking (mate-aware emission) --------
    flags_v = np.zeros(n, np.uint16)
    next_ref = np.full(n, -1, np.int32)
    next_pos_v = np.full(n, -1, np.int32)
    tlen_v = np.zeros(n, np.int32)
    pair_gid = np.full(n, -1, np.int64)  # rows in a complete pair share it
    if paired_out and cons_pair is not None and n:
        mate = np.asarray(cons_mate)[idx].astype(np.int64)
        pairk = np.asarray(cons_pair)[idx].astype(np.int64)
        order = np.lexsort((mate, pairk))
        pk_s = pairk[order]
        mate_s = mate[order]
        new_grp = np.r_[True, pk_s[1:] != pk_s[:-1]]
        gid_s = np.cumsum(new_grp) - 1
        grp_start = np.nonzero(new_grp)[0]
        grp_size = np.diff(np.r_[grp_start, len(pk_s)])
        # complete = exactly two rows whose (mate-sorted) mates are 0, 1
        comp_grp = grp_size == 2
        two = grp_start[comp_grp]
        comp_grp[comp_grp] = (
            (mate_s[two] == 0) & (mate_s[two + 1] == 1) & (pk_s[two] >= 0)
        )
        row_complete = comp_grp[gid_s]
        inv = np.empty(n, np.int64)
        inv[order] = np.arange(n)
        row_complete_n = row_complete[inv]
        mate_n = mate
        pair_gid = np.where(row_complete_n, gid_s[inv], -1)
        from duplexumiconsensusreads_tpu.io.bam import (
            FLAG_MATE_UNMAPPED,
            FLAG_PROPER_PAIR,
        )

        # every mate-aware row keeps its read-number flag — a row whose
        # partner emitted no consensus is still the R1 (or R2) side of
        # its template, and validators/downstream tools need that bit;
        # the missing partner is declared via FLAG_MATE_UNMAPPED
        rnum = np.where(mate_n == 1, FLAG_READ2, FLAG_READ1)
        flags_v = (
            FLAG_PAIRED
            | rnum
            | np.where(row_complete_n, FLAG_PROPER_PAIR, FLAG_MATE_UNMAPPED)
        ).astype(np.uint16)
        next_ref = np.where(row_complete_n, ref_id, -1).astype(np.int32)
        # PNEXT/TLEN from the PARTNER row: projection moves each mate's
        # POS to its own first called reference column, so the mates of
        # one template no longer share a position (unprojected runs
        # still do, where this reduces to the old shared-POS ±L form).
        # Complete pairs sort adjacently (mate 0 then 1), so the
        # partner is the sorted neighbour.
        t = np.arange(len(order))
        partner = np.clip(np.where(mate_s == 0, t + 1, t - 1), 0, max(len(t) - 1, 0))
        pos_s = pos[order].astype(np.int64)
        end_s = pos_s + ref_len_v[order]
        ppos_s = pos_s[partner]
        pend_s = end_s[partner]
        span = np.maximum(end_s, pend_s) - np.minimum(pos_s, ppos_s)
        left = (pos_s < ppos_s) | ((pos_s == ppos_s) & (mate_s == 0))
        tlen_s = np.where(left, span, -span)
        next_pos_v = np.where(
            row_complete_n, ppos_s[inv], -1
        ).astype(np.int32)
        tlen_v = np.where(row_complete_n, tlen_s[inv], 0).astype(np.int32)
    # vectorised RX strings: code matrix -> ASCII bytes (+ separator
    # column for duplex pairs), one decode per batch instead of a
    # Python join per record
    u = fam_umi.shape[1]
    chars = _CODE_CHARS[fam_umi[idx]] if n else np.zeros((0, u), np.uint8)
    if duplex:
        h = u // 2
        sep = np.full((n, 1), ord(UMI_SEP), np.uint8)
        chars = np.concatenate([chars[:, :h], sep, chars[:, h:]], axis=1)
    w = chars.shape[1]
    flat = chars.tobytes()
    umis = [flat[k * w:(k + 1) * w].decode("ascii") for k in range(n)]
    ds = np.asarray(cons_dstats, np.int64)[idx]
    cd_bytes = ds[:, 0].astype("<i4").tobytes()
    cm_bytes = ds[:, 1].astype("<i4").tobytes()

    def _row_cols(arr, k):
        """One record's emitted per-base values from a padded (F, C)
        matrix: the projection's kept columns, or the full row."""
        p = plan[k]
        row = np.asarray(arr)[idx[k]]
        return row[p[0]] if p is not None else row[:base_len]

    def _pb_rows(tag: bytes, arr):
        # fgbio-style per-base B array. fgbio emits B,S; we match that
        # whenever every value fits u16, widening to B,I only for jumbo
        # depths (the hard cap is 64x bucket capacity, which can exceed
        # u16) — strict fgbio-downstream parsers accept the common case
        import struct as _struct

        if proj is None:
            # vectorised fast path — the streaming executor calls this
            # per chunk on the 200M-read path, where per-record Python
            # costs minutes of host wall (the repo's standing contract)
            rows = np.asarray(arr)[idx]
            if rows.size == 0 or int(rows.max()) < 65536:
                sub, width, dt = b"S", 2, "<u2"
            else:
                sub, width, dt = b"I", 4, "<u4"
            hdr = tag + b"B" + sub + _struct.pack("<I", l)
            flat = rows.astype(dt).tobytes()
            return [
                hdr + flat[width * l * k : width * l * (k + 1)]
                for k in range(n)
            ]
        rows = [_row_cols(arr, k) for k in range(n)]
        vmax = max((int(r.max()) for r in rows if r.size), default=0)
        if vmax < 65536:
            sub, dt = b"S", "<u2"
        else:
            sub, dt = b"I", "<u4"
        return [
            tag + b"B" + sub + _struct.pack("<I", int(lens[k]))
            + rows[k].astype(dt).tobytes()
            for k in range(n)
        ]

    pd_rows = None if cons_pdepth is None else _pb_rows(b"cd", cons_pdepth)
    pe_rows = None if cons_perr is None else _pb_rows(b"ce", cons_perr)
    names, aux = [], []
    rg_bytes = (
        b"RGZ" + read_group.encode("ascii") + b"\x00" if read_group else b""
    )
    rid_l, pos_l, idx_l = ref_id.tolist(), pos.tolist(), idx.tolist()
    # mates must share ONE qname, but projection can move the two
    # mates' POS apart — embed the pair's LEFTMOST pos in both rows'
    # names (unprojected pairs share pos anyway, so this is identical
    # there)
    pair_pos_l = pos_l
    if n and int(pair_gid.max()) >= 0:
        g_min = np.full(int(pair_gid.max()) + 1, np.iinfo(np.int64).max)
        has = pair_gid >= 0
        np.minimum.at(g_min, pair_gid[has], pos[has])
        pair_pos_l = np.where(has, g_min[np.maximum(pair_gid, 0)], pos).tolist()
    gid_l = pair_gid.tolist()
    for k in range(n):
        # fixed-width fields -> identical record layout -> uniform
        # vectorised serializer (io/bam.py). Mate pairs share a qname
        # (their pair-group id); the s/p suffix keeps the single-record
        # and pair id spaces from colliding at equal width.
        if gid_l[k] >= 0:
            names.append(
                f"{name_prefix}:{rid_l[k]}:{pair_pos_l[k]:010d}:{gid_l[k]:07d}p"
            )
        else:
            names.append(
                f"{name_prefix}:{rid_l[k]}:{pos_l[k]:010d}:{idx_l[k]:07d}s"
            )
        aux.append(
            b"RXZ"
            + umis[k].encode("ascii")
            + b"\x00cDi"
            + cd_bytes[4 * k : 4 * k + 4]
            + b"cMi"
            + cm_bytes[4 * k : 4 * k + 4]
            + rg_bytes
            + (pd_rows[k] if pd_rows is not None else b"")
            + (pe_rows[k] if pe_rows is not None else b"")
        )
    if proj is None:
        # vectorised fast path (streaming hot path — see _pb_rows)
        rows_b = np.asarray(cons_base)[idx]
        seq_m = np.where(rows_b == BASE_PAD, 4, rows_b).astype(np.uint8)
        qual_m = np.asarray(cons_qual)[idx].astype(np.uint8)
        cigars: list = [[(base_len, "M")] for _ in range(n)]
    else:
        w_out = int(lens.max()) if n else l
        seq_m = np.full((n, w_out), 4, np.uint8)
        qual_m = np.zeros((n, w_out), np.uint8)
        cigars = []
        for k in range(n):
            m = int(lens[k])
            row = _row_cols(cons_base, k)
            seq_m[k, :m] = np.where(row == BASE_PAD, 4, row)
            qual_m[k, :m] = _row_cols(cons_qual, k)
            p = plan[k]
            cigars.append([(base_len, "M")] if p is None else p[1])
    return BamRecords(
        names=names,
        flags=flags_v,
        ref_id=ref_id,
        pos=pos,
        mapq=np.full(n, 60, np.uint8),
        next_ref_id=next_ref,
        next_pos=next_pos_v,
        tlen=tlen_v,
        lengths=lens,
        seq=seq_m,
        qual=qual_m,
        cigars=cigars,
        umi=umis,
        aux_raw=aux,
    )


def simulated_bam(
    cfg=None, path: str | None = None, sort: bool = False, paired_end: bool = False
):
    """Simulate a truth-aware batch and render it as a BAM (bytes or file).

    Convenience used by the CLI's `simulate` subcommand and tests.
    sort=True emits records in coordinate order (the streaming
    executor's input contract). Returns (header, records, batch, truth).
    """
    import dataclasses as _dc

    from duplexumiconsensusreads_tpu.io.bam import write_bam
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ReadBatch

    cfg = cfg or SimConfig()
    batch, truth = simulate_batch(cfg)
    if sort:
        order = np.argsort(np.asarray(batch.pos_key), kind="stable")
        batch = batch.take(order)
        truth = _dc.replace(
            truth,
            read_mol=truth.read_mol[order],
            read_strand=truth.read_strand[order],
            read_end2=(
                None if truth.read_end2 is None else truth.read_end2[order]
            ),
        )
    header = BamHeader.synthetic(
        sort_order="coordinate" if sort else "unsorted"
    )
    # true mate pairs only exist in BAM form as paired-end records
    recs = readbatch_to_records(
        batch, duplex=cfg.duplex, paired_end=paired_end or cfg.paired_reads
    )
    if cfg.indel_error > 0:
        inject_indels(recs, cfg.indel_error, seed=cfg.seed + 9999)
    if path is not None:
        write_bam(path, header, recs)
    return header, recs, batch, truth


def inject_indels(recs: BamRecords, rate: float, seed: int = 0) -> np.ndarray:
    """Give a random subset of records a 1bp indel: shifted sequence
    content plus the matching CIGAR (pM 1I (l-p-1)M or pM 1D (l-p)M).
    These reads are cycle-misaligned relative to their family — exactly
    what the modal-CIGAR input filter must drop. Returns the mutated
    record indices."""
    rng = np.random.default_rng(seed)
    sel = np.nonzero(rng.random(len(recs)) < rate)[0]
    sel = sel[np.asarray(recs.lengths)[sel] >= 3]  # too short to cut
    for i in sel:
        l = int(recs.lengths[i])
        p = int(rng.integers(1, l - 1))
        if rng.random() < 0.5:  # insertion at cycle p
            recs.cigars[i] = [(p, "M"), (1, "I"), (l - p - 1, "M")]
            recs.seq[i, p + 1 : l] = recs.seq[i, p : l - 1].copy()
            recs.seq[i, p] = rng.integers(0, 4)
        else:  # 1bp deletion after cycle p
            recs.cigars[i] = [(p, "M"), (1, "D"), (l - p, "M")]
            recs.seq[i, p : l - 1] = recs.seq[i, p + 1 : l].copy()
            recs.seq[i, l - 1] = rng.integers(0, 4)
    return sel
