"""Standard CSI (coordinate-sorted index) writer/reader — SAM spec §5.

The BAI format's R-tree addresses coordinates below 2^29 (512 Mbp);
longer contigs (some plant/amphibian genomes) need the CSI
generalization: the same binning scheme parameterized by ``min_shift``
(smallest bin width, 2^min_shift) and ``depth`` (tree levels), with the
linear index folded into a per-bin ``loffset``. This module writes and
queries CSI with depth sized automatically so any contig in the header
fits, sharing the batched scan core (``io/bai.py:_build_refs``) with
the BAI writer — one vectorised pass, no per-record Python.

Layout (little-endian), per the published spec / htslib:

    magic "CSI\\1"
    int32 min_shift, int32 depth, int32 l_aux, uint8 aux[l_aux]
    int32 n_ref
    per ref:  int32 n_bin
      per bin: uint32 bin, uint64 loffset, int32 n_chunk,
               { uint64 chunk_beg, uint64 chunk_end } * n_chunk
    uint64 n_no_coor

The metadata pseudo-bin is ``n_bins + 1`` where
``n_bins = ((1 << 3*(depth+1)) - 1) // 7`` (37450 at depth 5 —
consistent with BAI's fixed constant).

Reference parity note: the reference mount is empty (SURVEY.md §0);
the layout authority is the published SAM/BAM specification.
"""

from __future__ import annotations

import struct

import numpy as np

CSI_MAGIC = b"CSI\x01"
DEFAULT_MIN_SHIFT = 14


def _n_bins(depth: int) -> int:
    return ((1 << (3 * (depth + 1))) - 1) // 7


def _level_offset(level: int) -> int:
    """First bin number of a tree level (level 0 = root)."""
    return ((1 << (3 * level)) - 1) // 7


def depth_for(max_len: int, min_shift: int = DEFAULT_MIN_SHIFT) -> int:
    """Smallest depth whose address space 2^(min_shift + 3*depth) covers
    max_len, floored at the BAI-equivalent 5."""
    depth = 5
    while max_len > (1 << (min_shift + 3 * depth)):
        depth += 1
    return depth


def reg2bin_vec(
    begs: np.ndarray, ends: np.ndarray, min_shift: int, depth: int
) -> np.ndarray:
    """Vectorised generalized reg2bin: the smallest bin fully containing
    each [beg, end). Mirrors htslib's hts_reg2bin level walk."""
    b = np.asarray(begs, np.int64)
    e = np.maximum(np.asarray(ends, np.int64) - 1, b)
    out = np.zeros(len(b), np.int64)  # root bin when no level contains
    done = np.zeros(len(b), bool)
    s = min_shift
    t = _level_offset(depth)
    for level in range(depth, 0, -1):
        hit = ~done & ((b >> s) == (e >> s))
        out[hit] = t + (b[hit] >> s)
        done |= hit
        s += 3
        t -= 1 << (3 * (level - 1))
    return out


def reg2bins(beg: int, end: int, min_shift: int, depth: int) -> list[int]:
    """All bins that MAY hold alignments overlapping [beg, end) — the
    query-side dual of reg2bin, generalized."""
    end -= 1
    bins = []
    for level in range(depth + 1):
        t = _level_offset(level)
        s = min_shift + 3 * (depth - level)
        bins.extend(range(t + (beg >> s), t + (end >> s) + 1))
    return bins


def build_csi(
    path: str,
    csi_path: str | None = None,
    min_shift: int = DEFAULT_MIN_SHIFT,
    depth: int | None = None,
) -> str:
    """Index a coordinate-sorted BAM as CSI; returns the path written.

    depth=None sizes the tree from the longest header contig (>= 5, the
    BAI-equivalent). The builder shares io/bai.py's scan core, so the
    sortedness and ref_id validations are identical.
    """
    from duplexumiconsensusreads_tpu.io.bai import LINEAR_SHIFT, _build_refs
    from duplexumiconsensusreads_tpu.runtime.stream import BamStreamReader

    if depth is None:
        rdr = BamStreamReader(path)
        try:
            max_len = max(
                [int(x) for x in rdr.header.ref_lengths], default=0
            )
        finally:
            rdr.close()
        depth = depth_for(max_len, min_shift)
    max_coord = 1 << (min_shift + 3 * depth)

    refs, n_ref, n_no_coor = _build_refs(
        path,
        lambda b, e: reg2bin_vec(b, e, min_shift, depth),
        max_coord,
        "CSI",
    )
    meta_bin = _n_bins(depth) + 1

    out = bytearray()
    out += CSI_MAGIC
    out += struct.pack("<iii", min_shift, depth, 0)  # no aux payload
    out += struct.pack("<i", n_ref)
    for r in refs:
        meta = r.off_beg >= 0
        out += struct.pack("<i", len(r.bins) + (1 if meta else 0))
        # loffset per bin from the shared linear accumulation: the bin's
        # first min_shift window, forward-filled the BAI way. The scan
        # core accumulates linear at LINEAR_SHIFT windows; CSI folds
        # that into bins instead of a separate array.
        lin = r.linear
        if len(lin):
            idxs = np.where(lin != 0, np.arange(len(lin)), 0)
            np.maximum.accumulate(idxs, out=idxs)
            lin = lin[idxs]
        for bin_ in sorted(r.bins):
            # bin -> its level (largest with level_offset <= bin), then
            # its first coordinate window
            level = depth
            while _level_offset(level) > bin_:
                level -= 1
            k = bin_ - _level_offset(level)
            first_coord = k << (min_shift + 3 * (depth - level))
            w = first_coord >> LINEAR_SHIFT
            loffset = int(lin[min(w, len(lin) - 1)]) if len(lin) else 0
            chunks = r.bins[bin_]
            out += struct.pack("<IQi", bin_, loffset, len(chunks))
            for beg_v, end_v in chunks:
                out += struct.pack("<QQ", beg_v, end_v)
        if meta:
            out += struct.pack("<IQi", meta_bin, 0, 2)
            out += struct.pack("<QQ", r.off_beg, r.off_end)
            out += struct.pack("<QQ", r.n_mapped, r.n_unmapped)
    out += struct.pack("<Q", n_no_coor)

    import os

    from duplexumiconsensusreads_tpu.io.durable import write_durable

    csi_path = csi_path or path + ".csi"
    # per-writer tmp: no shared-tmp races
    return write_durable(csi_path, bytes(out), tmp=f"{csi_path}.tmp.{os.getpid()}")


def read_csi(path: str) -> dict:
    """Parse a .csi into {min_shift, depth, n_ref, refs: [{bins:
    {bin: [(beg, end), ...]}, loffsets: {bin: loffset}, meta}],
    n_no_coor} — the query/test-side inverse of build_csi."""
    with open(path, "rb") as f:
        data = f.read()
    if data[:4] != CSI_MAGIC:
        raise ValueError(f"{path}: not a CSI file")
    try:
        return _parse_csi(path, data)
    except (struct.error, IndexError) as e:
        # truncated/corrupt index must fail loudly with the path, never
        # leak a bare struct.error (or an IndexError from a malformed
        # chunk list) — the repo-wide truncation discipline
        raise ValueError(f"{path}: truncated or corrupt CSI: {e}") from e


def _parse_csi(path: str, data: bytes) -> dict:
    min_shift, depth, l_aux = struct.unpack_from("<iii", data, 4)
    off = 16 + l_aux
    (n_ref,) = struct.unpack_from("<i", data, off)
    off += 4
    meta_bin = _n_bins(depth) + 1
    refs = []
    for _ in range(n_ref):
        (n_bin,) = struct.unpack_from("<i", data, off)
        off += 4
        bins: dict[int, list[tuple[int, int]]] = {}
        loffsets: dict[int, int] = {}
        meta = None
        for _ in range(n_bin):
            bin_, loffset, n_chunk = struct.unpack_from("<IQi", data, off)
            off += 16
            chunks = []
            for _ in range(n_chunk):
                beg_v, end_v = struct.unpack_from("<QQ", data, off)
                off += 16
                chunks.append((beg_v, end_v))
            if bin_ == meta_bin:
                # the htslib metadata pseudo-bin carries exactly 2
                # chunks (file range + mapped/unmapped counts); any
                # other count is corruption, and chunks[1] below would
                # otherwise escape as a bare IndexError
                if n_chunk != 2:
                    raise ValueError(
                        f"{path}: truncated or corrupt CSI: metadata "
                        f"pseudo-bin has {n_chunk} chunks (expected 2)"
                    )
                meta = (*chunks[0], *chunks[1])
            else:
                bins[bin_] = chunks
                loffsets[bin_] = loffset
        refs.append({"bins": bins, "loffsets": loffsets, "meta": meta})
    n_no_coor = (
        struct.unpack_from("<Q", data, off)[0] if off + 8 <= len(data) else 0
    )
    return {
        "min_shift": min_shift,
        "depth": depth,
        "n_ref": n_ref,
        "refs": refs,
        "n_no_coor": n_no_coor,
    }


def query_start_voffset_csi(
    idx: dict, ref_id: int, beg: int, end: int
) -> int | None:
    """Virtual offset to start scanning for alignments overlapping
    [beg, end) from a read_csi() index — the CSI analogue of
    io/bai.py:query_start_voffset: minimum candidate-chunk begin,
    floored by the deepest existing containing bin's loffset (which is
    the linear value of beg's window, or an ancestor's — always <= the
    first overlapping record's offset, so the one-seek forward scan
    stays complete)."""
    if ref_id < 0 or ref_id >= idx["n_ref"]:
        return None
    ref = idx["refs"][ref_id]
    if ref["meta"] is None and not ref["bins"]:
        return None
    min_shift, depth = idx["min_shift"], idx["depth"]
    best = None
    for b in reg2bins(beg, end, min_shift, depth):
        for beg_v, _end_v in ref["bins"].get(b, ()):
            if best is None or beg_v < best:
                best = beg_v
    if best is None:
        return None
    floor = 0
    for level in range(depth, -1, -1):
        b = _level_offset(level) + (
            beg >> (min_shift + 3 * (depth - level))
        )
        if b in ref["loffsets"]:
            floor = ref["loffsets"][b]
            break
    return max(best, floor)
