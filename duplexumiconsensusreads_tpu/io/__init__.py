"""IO layer: BGZF/BAM codec and ReadBatch interchange.

Produces the padded device tensors everything downstream runs on. The
pure-Python codec here is the portable reference; io/native (C++)
accelerates the hot decompress/parse path when built.
"""

from duplexumiconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecords,
    read_bam,
    write_bam,
)
from duplexumiconsensusreads_tpu.io.convert import (
    consensus_to_records,
    readbatch_to_records,
    records_to_readbatch,
    simulated_bam,
)
from duplexumiconsensusreads_tpu.io.npz import load_readbatch, save_readbatch


def load_input(
    path: str, duplex: bool, warn_mixed: bool = True,
    ref_projected: bool = False, mate_aware: str = "off",
    umi_whitelist=None, umi_max_mismatches: int = 1,
):
    """ONE input loader for every consumer (call, stats, ...): .npz
    ReadBatch interchange, else native BAM parse when available
    (DUT_NO_NATIVE=1 forces the portable codec), else pure Python.
    Returns (header, batch, info). warn_mixed=False defers the
    mixed-mate warning to the caller (mate-aware auto-resolution
    decides whether it applies). ref_projected=True projects reads onto
    reference columns (io/refproject.py) — BAM inputs only (the .npz
    interchange carries no CIGARs), via the portable codec (the native
    fast path hands back a finished batch; projection needs the parsed
    records)."""
    import os

    if path.endswith(".npz"):
        if ref_projected:
            raise ValueError(
                "ref-projected consensus requires BAM input (CIGARs); "
                ".npz interchange carries none"
            )
        from duplexumiconsensusreads_tpu.io.convert import (
            correct_umis_whitelist,
            mixed_ends_present,
        )

        batch = load_readbatch(path)
        info = {
            "n_records": batch.n_reads,
            # same auto-detection semantics as the BAM codecs: on only
            # when some family actually mixes fragment ends
            "mixed_mates": mixed_ends_present(batch),
        }
        if umi_whitelist is not None:
            info.update(
                correct_umis_whitelist(batch, umi_whitelist, umi_max_mismatches)
            )
            info["mixed_mates"] = mixed_ends_present(batch)
        return BamHeader.synthetic(), batch, info
    # the native fast path applies its family policies (modal-CIGAR
    # vote) during the fill, which must see CORRECTED UMIs — whitelist
    # runs force the portable codec, like ref_projected does
    if (
        not ref_projected
        and umi_whitelist is None
        and not os.environ.get("DUT_NO_NATIVE")
    ):
        from duplexumiconsensusreads_tpu.io.native_reader import read_bam_native

        res = read_bam_native(path, duplex=duplex, warn_mixed=warn_mixed)
        if res is not None:
            return res
    header, recs = read_bam(path)
    batch, info = records_to_readbatch(
        recs, duplex=duplex, warn_mixed=warn_mixed,
        ref_projected=ref_projected, mate_aware=mate_aware,
        umi_whitelist=umi_whitelist, umi_max_mismatches=umi_max_mismatches,
    )
    return header, batch, info


__all__ = [
    "load_input",
    "BamHeader",
    "BamRecords",
    "read_bam",
    "write_bam",
    "records_to_readbatch",
    "readbatch_to_records",
    "consensus_to_records",
    "simulated_bam",
    "save_readbatch",
    "load_readbatch",
]
