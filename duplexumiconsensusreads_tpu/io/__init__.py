"""IO layer: BGZF/BAM codec and ReadBatch interchange.

Produces the padded device tensors everything downstream runs on. The
pure-Python codec here is the portable reference; io/native (C++)
accelerates the hot decompress/parse path when built.
"""

from duplexumiconsensusreads_tpu.io.bam import (
    BamHeader,
    BamRecords,
    read_bam,
    write_bam,
)
from duplexumiconsensusreads_tpu.io.convert import (
    consensus_to_records,
    readbatch_to_records,
    records_to_readbatch,
    simulated_bam,
)
from duplexumiconsensusreads_tpu.io.npz import load_readbatch, save_readbatch

__all__ = [
    "BamHeader",
    "BamRecords",
    "read_bam",
    "write_bam",
    "records_to_readbatch",
    "readbatch_to_records",
    "consensus_to_records",
    "simulated_bam",
    "save_readbatch",
    "load_readbatch",
]
