"""Truth-aware synthetic read simulator.

Generates ground-truth source molecules (known sequence, position, UMI
pair), then amplifies each into top-/bottom-strand reads with
Phred-consistent sequencing errors and optional UMI base errors (to
exercise directional adjacency clustering). Because the true molecule
sequence is known, tests can measure the *consensus error rate* of any
pipeline output directly — this is the stand-in for "matched consensus
error rate" given the empty reference mount (SURVEY.md §7).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from duplexumiconsensusreads_tpu.constants import BASE_N, N_REAL_BASES
from duplexumiconsensusreads_tpu.types import ReadBatch


@dataclasses.dataclass(frozen=True)
class SimConfig:
    n_molecules: int = 64
    read_len: int = 48
    umi_len: int = 6           # per-strand UMI; duplex uses a pair => 2*umi_len codes
    n_positions: int = 4       # distinct genomic positions (tiles collapse later)
    mean_family_size: int = 4  # reads per (molecule, strand), geometric-ish
    max_family_size: int = 16
    base_error: float = 0.01   # per-base sequencing error prob (flat component)
    cycle_error_slope: float = 0.0  # extra error prob per cycle (config 5 exercises >0)
    umi_error: float = 0.0     # per-UMI-base error prob (exercises adjacency grouping)
    indel_error: float = 0.0   # per-read prob of a 1bp indel (CIGAR I/D; exercises
    #                            the modal-CIGAR input filter — simulated_bam only,
    #                            since indels live in BAM CIGARs, not ReadBatch)
    qual_lo: int = 20
    qual_hi: int = 40
    duplex: bool = True
    paired_reads: bool = False  # each (molecule, strand) family's reads
    #                             come as R1+R2 mate PAIRS covering two
    #                             distinct fragment ends (mol_seq /
    #                             mol_seq2); exercises mate-aware calling
    n_frac: float = 0.0        # fraction of read bases replaced by N
    seed: int = 0


@dataclasses.dataclass
class SimTruth:
    """Ground truth: per-molecule sequence + per-read provenance."""

    mol_seq: np.ndarray       # u8 (M, L) true molecule sequences (fragment end 1)
    mol_pos_key: np.ndarray   # i64 (M,)
    mol_umi: np.ndarray       # u8 (M, U) canonical UMI(-pair) codes
    read_mol: np.ndarray      # i32 (N,) true molecule id per read
    read_strand: np.ndarray   # bool (N,) true strand per read
    mol_seq2: np.ndarray | None = None  # u8 (M, L) fragment-end-2 truth
    #                                     (paired_reads only)
    read_end2: np.ndarray | None = None  # bool (N,) fragment end per read


def _geometric_sizes(rng, n, mean, max_size):
    sizes = rng.geometric(1.0 / mean, size=n)
    return np.clip(sizes, 1, max_size)


def simulate_batch(cfg: SimConfig) -> tuple[ReadBatch, SimTruth]:
    """Simulate one batch of reads with full ground truth.

    Per-cycle error prob for cycle c is ``base_error + c*cycle_error_slope``.
    Reported quality is drawn uniformly in [qual_lo, qual_hi] and the
    realised error event is sampled from the *true* per-cycle error, so a
    fitted per-cycle error model has a real signal to recover.
    """
    rng = np.random.default_rng(cfg.seed)
    m, l, u = cfg.n_molecules, cfg.read_len, cfg.umi_len

    mol_seq = rng.integers(0, N_REAL_BASES, size=(m, l), dtype=np.uint8)
    pos_choices = (np.arange(cfg.n_positions, dtype=np.int64) + 1) * 1000
    mol_pos = rng.choice(pos_choices, size=m)
    upair = 2 * u if cfg.duplex else u
    # Distinct (pos, UMI) per molecule so ground truth really is 1:1 with
    # exact families (resample collisions; UMI read errors are separate).
    mol_umi = rng.integers(0, N_REAL_BASES, size=(m, upair), dtype=np.uint8)
    for _ in range(100):
        keys = [(mol_pos[i], mol_umi[i].tobytes()) for i in range(m)]
        seen: dict = {}
        dup = [i for i, k in enumerate(keys) if seen.setdefault(k, i) != i]
        if not dup:
            break
        mol_umi[dup] = rng.integers(0, N_REAL_BASES, size=(len(dup), upair), dtype=np.uint8)
    else:
        raise RuntimeError("could not draw distinct (pos, UMI) molecule keys")

    # fragment end 2 has its own true sequence (paired_reads mode):
    # a template's R1 and R2 mates genuinely observe different bases,
    # so mixing them in one consensus family is measurably wrong
    mol_seq2 = (
        rng.integers(0, N_REAL_BASES, size=(m, l), dtype=np.uint8)
        if cfg.paired_reads
        else None
    )

    strands = [True, False] if cfg.duplex else [True]
    per_strand_sizes = {
        s: _geometric_sizes(rng, m, cfg.mean_family_size, cfg.max_family_size)
        for s in strands
    }
    ends = [False, True] if cfg.paired_reads else [False]
    n_reads = int(sum(sz.sum() for sz in per_strand_sizes.values())) * len(ends)

    bases = np.empty((n_reads, l), np.uint8)
    quals = np.empty((n_reads, l), np.uint8)
    umi = np.empty((n_reads, upair), np.uint8)
    pos_key = np.empty((n_reads,), np.int64)
    strand_ab = np.empty((n_reads,), bool)
    frag_end = np.empty((n_reads,), bool)
    read_mol = np.empty((n_reads,), np.int32)

    cycle_err = cfg.base_error + cfg.cycle_error_slope * np.arange(l)
    cycle_err = np.clip(cycle_err, 1e-6, 0.5)

    i = 0
    for s in strands:
        for mol in range(m):
            # paired_reads: the family's k read PAIRS contribute k reads
            # to EACH fragment end (every R1 has its R2 mate)
            k = int(per_strand_sizes[s][mol])
            for e2 in ends:
                sl = slice(i, i + k)
                i += k
                true_seq = mol_seq2[mol] if e2 else mol_seq[mol]
                b = np.broadcast_to(true_seq, (k, l)).copy()
                err = rng.random((k, l)) < cycle_err[None, :]
                # substitution: true base + offset in {1,2,3} mod 4
                offset = rng.integers(1, N_REAL_BASES, size=(k, l), dtype=np.uint8)
                b[err] = (b[err] + offset[err]) % N_REAL_BASES
                if cfg.n_frac > 0:
                    b[rng.random((k, l)) < cfg.n_frac] = BASE_N
                bases[sl] = b
                quals[sl] = rng.integers(cfg.qual_lo, cfg.qual_hi + 1, size=(k, l))
                uread = np.broadcast_to(mol_umi[mol], (k, upair)).copy()
                if cfg.umi_error > 0:
                    uerr = rng.random((k, upair)) < cfg.umi_error
                    uoff = rng.integers(
                        1, N_REAL_BASES, size=(k, upair), dtype=np.uint8
                    )
                    uread[uerr] = (uread[uerr] + uoff[uerr]) % N_REAL_BASES
                umi[sl] = uread
                pos_key[sl] = mol_pos[mol]
                strand_ab[sl] = s
                frag_end[sl] = e2
                read_mol[sl] = mol

    perm = rng.permutation(n_reads)
    batch = ReadBatch(
        bases=bases[perm],
        quals=quals[perm],
        umi=umi[perm],
        pos_key=pos_key[perm],
        strand_ab=strand_ab[perm],
        frag_end=frag_end[perm],
        valid=np.ones((n_reads,), bool),
    )
    truth = SimTruth(
        mol_seq=mol_seq,
        mol_pos_key=mol_pos,
        mol_umi=mol_umi,
        read_mol=read_mol[perm],
        read_strand=strand_ab[perm],
        mol_seq2=mol_seq2,
        read_end2=frag_end[perm],
    )
    return batch, truth


def pad_batch(batch: ReadBatch, n_to: int) -> ReadBatch:
    """Pad a ReadBatch with invalid slots up to n_to reads (static shapes)."""
    n = batch.n_reads
    if n_to < n:
        raise ValueError(f"pad target {n_to} < batch size {n}")
    out = ReadBatch.empty(n_to, batch.read_len, batch.umi_len)
    for name in (
        "bases", "quals", "umi", "pos_key", "strand_ab", "frag_end", "valid"
    ):
        arr = getattr(out, name)
        arr[:n] = getattr(batch, name)
    return out
