"""At-scale BAM simulation: write a coordinate-sorted, truth-free BAM
of arbitrary size in bounded memory.

The in-memory simulator (simulator.py) materialises every read at
once — fine for tests, hopeless for the 10M+-read end-to-end benchmark
input (BASELINE.json's north-star is wall-clock on a 200M-read BAM).
This writer simulates independent position-range chunks and appends
each as its own BGZF member run, so peak memory is one chunk and the
output is globally coordinate-sorted (chunk i's positions all precede
chunk i+1's).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from duplexumiconsensusreads_tpu.io import bgzf
from duplexumiconsensusreads_tpu.io.bam import BamHeader, serialize_bam
from duplexumiconsensusreads_tpu.io.convert import readbatch_to_records
from duplexumiconsensusreads_tpu.simulate.simulator import SimConfig, simulate_batch


def simulate_bam_file(
    path: str,
    n_molecules: int,
    cfg: SimConfig | None = None,
    chunk_molecules: int = 25_000,
    seed: int = 0,
    paired_end: bool = False,
    progress=None,
) -> dict:
    """Write ``n_molecules`` worth of simulated reads to ``path``.

    cfg supplies per-chunk parameters (read_len, family size, error
    rates, n_positions PER CHUNK); n_molecules/seed are overridden per
    chunk. Returns {"n_reads", "n_molecules", "seconds"}.
    """
    cfg = cfg or SimConfig()
    # monotonic, like every duration in the codebase: the "seconds"
    # field is a delta, and an NTP step mid-simulation must not skew it
    t0 = time.monotonic()
    stride = (cfg.n_positions + 1) * 1000  # chunk i owns one position range
    n_chunks = (n_molecules + chunk_molecules - 1) // chunk_molecules
    if stride * n_chunks >= 1 << 31:
        raise ValueError(
            "position space overflow: lower n_positions or chunk count "
            f"({n_chunks} chunks x stride {stride} exceeds int32 coordinates)"
        )
    header = BamHeader.synthetic(
        ref_lengths=(min(stride * n_chunks + 1000, (1 << 31) - 1),),
        sort_order="coordinate",  # chunks emit in ascending position
    )
    shell = serialize_bam(header, _empty())
    n_reads = 0
    done = 0
    with open(path, "wb") as f:
        f.write(bgzf.compress_fast(shell, eof=False))
        for ci in range(n_chunks):
            m = min(chunk_molecules, n_molecules - done)
            done += m
            ccfg = dataclasses.replace(cfg, n_molecules=m, seed=seed + ci)
            batch, _ = simulate_batch(ccfg)
            batch.pos_key = np.asarray(batch.pos_key) + ci * stride
            order = np.argsort(batch.pos_key, kind="stable")
            batch = batch.take(order)
            recs = readbatch_to_records(
                batch, duplex=cfg.duplex, paired_end=paired_end
            )
            payload = serialize_bam(header, recs)[len(shell):]
            f.write(bgzf.compress_fast(payload, eof=False))
            n_reads += len(recs)
            if progress:
                progress(ci, n_chunks, n_reads)
        f.write(bgzf.BGZF_EOF)
    return {
        "n_reads": n_reads,
        "n_molecules": n_molecules,
        "seconds": round(time.monotonic() - t0, 2),
        "bytes": os.path.getsize(path),
    }


def _empty():
    from duplexumiconsensusreads_tpu.io.bam import BamRecords

    return BamRecords(
        names=[],
        flags=np.zeros(0, np.uint16),
        ref_id=np.zeros(0, np.int32),
        pos=np.zeros(0, np.int32),
        mapq=np.zeros(0, np.uint8),
        next_ref_id=np.zeros(0, np.int32),
        next_pos=np.zeros(0, np.int32),
        tlen=np.zeros(0, np.int32),
        lengths=np.zeros(0, np.int32),
        seq=np.zeros((0, 0), np.uint8),
        qual=np.zeros((0, 0), np.uint8),
        cigars=[],
        umi=[],
        aux_raw=[],
    )
