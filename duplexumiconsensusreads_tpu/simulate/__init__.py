from duplexumiconsensusreads_tpu.simulate.simulator import (  # noqa: F401
    SimConfig,
    SimTruth,
    pad_batch,
    simulate_batch,
)
