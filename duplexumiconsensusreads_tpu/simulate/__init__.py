from duplexumiconsensusreads_tpu.simulate.simulator import (  # noqa: F401
    SimConfig,
    SimTruth,
    simulate_batch,
)
