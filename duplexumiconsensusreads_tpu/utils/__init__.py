from duplexumiconsensusreads_tpu.utils.phred import (  # noqa: F401
    phred_to_error,
    error_to_phred,
    seq_to_codes,
    codes_to_seq,
    pack_umi,
)
