"""Phred-scale quality math and base-code helpers (NumPy, host-side).

These are the single source of truth for quality<->probability
conversions; the oracle and the JAX kernels both follow the same
conventions (see kernels/consensus.py for the on-device mirror).
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import (
    BASE_CHARS,
    CHAR_TO_CODE,
    MAX_PHRED,
    MIN_ERROR_PROB,
)


def phred_to_error(q: np.ndarray) -> np.ndarray:
    """Error probability for integer Phred quality q: e = 10**(-q/10)."""
    return np.maximum(10.0 ** (-np.asarray(q, dtype=np.float64) / 10.0), MIN_ERROR_PROB)


def error_to_phred(e: np.ndarray, max_phred: int = MAX_PHRED) -> np.ndarray:
    """Integer Phred quality for error probability e, clipped to [2, max_phred]."""
    e = np.maximum(np.asarray(e, dtype=np.float64), MIN_ERROR_PROB)
    q = np.floor(-10.0 * np.log10(e) + 1e-9)
    return np.clip(q, 2, max_phred).astype(np.uint8)


def seq_to_codes(seq: str) -> np.ndarray:
    """ACGTN string -> u8 codes (A=0..T=3, N=4)."""
    return np.array([CHAR_TO_CODE.get(c, 4) for c in seq.upper()], dtype=np.uint8)


def codes_to_seq(codes: np.ndarray) -> str:
    """u8 codes -> ACGTN. string (PAD renders as '.')."""
    return "".join(BASE_CHARS[min(int(c), 5)] for c in codes)


def pack_umi(codes: np.ndarray) -> np.ndarray:
    """Pack 2-bit UMI codes (..., U) into a single int64 per UMI.

    Only valid for U <= 31 and codes in {0..3}; N in a UMI should be
    handled upstream (reads with N UMIs are conventionally dropped).
    """
    codes = np.asarray(codes, dtype=np.int64)
    u = codes.shape[-1]
    if u > 31:
        raise ValueError(f"UMI length {u} > 31 cannot pack into int64")
    if codes.size and (codes.min() < 0 or codes.max() >= 4):
        raise ValueError(
            "pack_umi requires 2-bit codes in {0..3}; reads with N in the "
            "UMI must be dropped upstream (io layer)"
        )
    shifts = np.arange(u, dtype=np.int64)[::-1] * 2
    return (codes << shifts).sum(axis=-1)
